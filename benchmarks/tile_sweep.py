"""Tile-size sweep for the brute-force closest-point kernel.

The production tiles (tile_q=256, tile_f=2048) were chosen analytically
(VMEM budget: 19 face planes x tile_f + query columns).  This sweeps the
neighborhood on the live backend at the north-star shape (BASELINE
config 3: 13776 faces, batch-sized query sets) and prints one JSON line
per combination, so a recovered tunnel window can answer "are we leaving
tile-shape performance on the table?" in ~a minute.

    python benchmarks/tile_sweep.py [--queries 262144] [--faces 13776]
"""

import itertools
import json
import sys
from functools import partial

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from mesh_tpu.utils.profiling import time_fn  # noqa: E402


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--queries", type=int, default=262144)
    parser.add_argument("--faces", type=int, default=13776)
    parser.add_argument("--reps", type=int, default=5)
    parser.add_argument("--mxu", action="store_true",
                        help="sweep the experimental MXU-fed tile instead")
    args = parser.parse_args(argv)

    from bench import backend_responsive

    ok, reason = backend_responsive()
    if not ok:
        print(json.dumps({"error": "backend probe failed: %s" % reason}))
        sys.exit(1)

    from mesh_tpu.query.autotune import _sphere_mesh
    from mesh_tpu.query.pallas_closest import (
        closest_point_pallas,
        closest_point_pallas_mxu,
        mesh_is_nondegenerate,
    )
    from mesh_tpu.utils.compilation_cache import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    v, f = _sphere_mesh(args.faces)
    if args.mxu:
        kernel = closest_point_pallas_mxu
    else:
        # sweep the tile the production facade would compile for this mesh
        kernel = partial(
            closest_point_pallas,
            assume_nondegenerate=mesh_is_nondegenerate(v, f))
    rng = np.random.RandomState(0)
    pts = rng.randn(args.queries, 3).astype(np.float32)

    best = None
    n_errors = 0
    for tile_q, tile_f in itertools.product(
        (128, 256, 512, 1024), (512, 1024, 2048, 4096)
    ):
        try:
            t = time_fn(
                lambda: kernel(v, f, pts, tile_q=tile_q, tile_f=tile_f),
                reps=args.reps,
            )
            rate = args.queries / t
            row = {"tile_q": tile_q, "tile_f": tile_f,
                   "queries_per_sec": round(rate, 1)}
            if best is None or rate > best["queries_per_sec"]:
                best = row
        except Exception as e:  # VMEM overflow etc. — record, keep sweeping
            n_errors += 1
            row = {"tile_q": tile_q, "tile_f": tile_f,
                   "error": str(e)[:120]}
        print(json.dumps(row), flush=True)
    summary = {"best": best, "n_errors": n_errors}
    if best is None:
        # automation must not mistake an all-failed sweep for a healthy one
        summary["error"] = "every tile combination failed"
    elif not args.mxu:
        # quantify the degenerate-tail cost on this backend: same kernel,
        # best tile shape, safe tile (assume_nondegenerate=False) — the
        # on-chip evidence for the facade's pay-per-use override
        try:
            t_safe = time_fn(
                lambda: closest_point_pallas(
                    v, f, pts, tile_q=best["tile_q"], tile_f=best["tile_f"],
                    assume_nondegenerate=False),
                reps=args.reps,
            )
            safe_rate = args.queries / t_safe
            summary["safe_tile_queries_per_sec"] = round(safe_rate, 1)
            summary["degenerate_tail_cost_pct"] = round(
                100.0 * (best["queries_per_sec"] - safe_rate)
                / best["queries_per_sec"], 1)
        except Exception as e:
            summary["safe_tile_error"] = str(e)[:120]
    print(json.dumps(summary))
    if best is None:
        sys.exit(1)


if __name__ == "__main__":
    main()
