"""Tile-size sweep for the hot Pallas pair-grid kernels.

The production tiles (closest-point: tile_q=256, tile_f=2048; tri-tri:
256x512) were chosen analytically (VMEM budget: plane count x tile_f +
query columns).  This sweeps the neighborhood on the live backend and
prints one JSON line per combination, so a recovered tunnel window can
answer "are we leaving tile-shape performance on the table?" in ~a
minute per kernel.

    python benchmarks/tile_sweep.py [--queries 262144] [--faces 13776]
    python benchmarks/tile_sweep.py --mxu        # MXU dot-product tile;
                                                 # best shape feeds the
                                                 # mxu_crossover calib.
    python benchmarks/tile_sweep.py --tri-tri    # Möller + segment tiles
                                                 # at the config-4 shape

The closest-point sweep also re-times the best tile with the safe
(degenerate-tail) variant; the tri-tri sweep times segment and Möller at
every shape, so the on-chip moller_speedup lands per tile shape.
"""

import itertools
import json
import sys
from functools import partial

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from mesh_tpu.utils.profiling import time_fn  # noqa: E402


def _sweep(make_call, shapes, reps, n_items):
    """Generic (tile_q, tile_f) sweep: prints one row per shape, returns
    (best_row, n_errors)."""
    best = None
    n_errors = 0
    for tile_q, tile_f in shapes:
        try:
            t = time_fn(partial(make_call, tile_q, tile_f), reps=reps)
            rate = n_items / t
            row = {"tile_q": tile_q, "tile_f": tile_f,
                   "queries_per_sec": round(rate, 1)}
            if best is None or rate > best["queries_per_sec"]:
                best = row
        except Exception as e:  # VMEM overflow etc. — record, keep sweeping
            n_errors += 1
            row = {"tile_q": tile_q, "tile_f": tile_f,
                   "error": str(e)[:120]}
        print(json.dumps(row), flush=True)
    return best, n_errors


def _closest_point_sweep(args):
    from mesh_tpu.query.autotune import _sphere_mesh
    from mesh_tpu.query.pallas_closest import (
        closest_point_pallas,
        closest_point_pallas_mxu,
        mesh_is_nondegenerate,
    )

    v, f = _sphere_mesh(args.faces)
    # sweep the tile variant the production facade would compile for this
    # mesh (best-vs-best between the MXU and VPU families)
    nondegen = mesh_is_nondegenerate(v, f)
    kernel = partial(
        closest_point_pallas_mxu if args.mxu else closest_point_pallas,
        assume_nondegenerate=nondegen)
    rng = np.random.RandomState(0)
    pts = rng.randn(args.queries, 3).astype(np.float32)

    best, n_errors = _sweep(
        lambda tq, tf: kernel(v, f, pts, tile_q=tq, tile_f=tf),
        itertools.product((128, 256, 512, 1024), (512, 1024, 2048, 4096)),
        args.reps, args.queries,
    )
    summary = {"best": best, "n_errors": n_errors}
    if best is not None and args.mxu:
        # feed the winning MXU tile shape into the persisted crossover
        # calibration (query/autotune.py): the routed facades then pick
        # MXU-vs-VPU from a measurement at the sweep's best shape, with
        # the same env-override / corrupt-cache contract as the other
        # calibrations
        from mesh_tpu.query import autotune

        try:
            summary["mxu_crossover"] = autotune.calibrate_mxu_crossover(
                tile_q=best["tile_q"], tile_f=best["tile_f"], save=True)
        except Exception as e:
            summary["mxu_crossover_error"] = str(e)[:120]
    if best is not None and not args.mxu:
        # quantify the round-4/5 variant family at the best tile shape —
        # each row is the on-chip evidence for (or against) one variant:
        #   degenerate_tail   — the pay-per-use override's cost
        #                       (gate 4's degenerate_tail_cost_pct)
        #   sliver_safe       — the direct-corner tile's cost (VERDICT r4
        #                       #7: price of reference-grade conditioning)
        #   fused_reduction   — the packed single-pass min+argmin
        #                       (VERDICT r4 #4: the post-55% lever)
        def _try(label, **kw):
            try:
                t_var = time_fn(
                    lambda: closest_point_pallas(
                        v, f, pts, tile_q=best["tile_q"],
                        tile_f=best["tile_f"], **kw),
                    reps=args.reps,
                )
                rate = args.queries / t_var
                summary["%s_queries_per_sec" % label] = round(rate, 1)
                summary["%s_cost_pct" % label] = round(
                    100.0 * (best["queries_per_sec"] - rate)
                    / best["queries_per_sec"], 1)
            except Exception as e:
                summary["%s_error" % label] = str(e)[:120]

        _try("safe_tile", assume_nondegenerate=False)
        if "safe_tile_cost_pct" in summary:
            # gate-4's historical name for this row (harvest_gates reads it)
            summary["degenerate_tail_cost_pct"] = summary.pop(
                "safe_tile_cost_pct")
        _try("sliver_safe", assume_nondegenerate=nondegen,
             tile_variant="safe")
        _try("fused_reduction", assume_nondegenerate=nondegen,
             reduction="fused")
    return summary


def _tri_tri_sweep(args):
    """Both tri-tri tiles at the config-4 shape (MANO-sized query mesh vs
    SMPL-sized body mesh), per tile shape — the per-shape moller_speedup."""
    from mesh_tpu.models import smpl_sized_sphere
    from mesh_tpu.query.pallas_ray import tri_tri_any_hit_pallas
    from mesh_tpu.sphere import _icosphere

    body_v, body_f = smpl_sized_sphere()
    hand_v, hand_f = _icosphere(3)
    hand_v = hand_v * 0.2 + np.array([0.9, 0, 0])
    q_tri = hand_v.astype(np.float32)[hand_f]
    m_tri = body_v.astype(np.float32)[body_f.astype(np.int64)]
    n_items = len(q_tri)

    shapes = list(itertools.product((128, 256, 512), (256, 512, 1024)))
    results = {}
    for algo in ("segment", "moller"):
        print(json.dumps({"sweep_algorithm": algo}), flush=True)
        best, n_errors = _sweep(
            lambda tq, tf: tri_tri_any_hit_pallas(
                q_tri, m_tri, tile_q=tq, tile_f=tf, algorithm=algo),
            shapes, args.reps, n_items,
        )
        results[algo] = {"best": best, "n_errors": n_errors}
    # overall health keys on EITHER tile family succeeding ("best" is what
    # main() checks); a family that failed at every shape is flagged
    # explicitly rather than conflated with total failure
    summary = {
        "best": results["moller"]["best"] or results["segment"]["best"],
        "n_errors": sum(r["n_errors"] for r in results.values()),
        "moller_best": results["moller"]["best"],
        "segment_best": results["segment"]["best"],
    }
    for algo in ("segment", "moller"):
        if results[algo]["best"] is None:
            summary["%s_error" % algo] = (
                "every %s tile combination failed" % algo)
    if results["moller"]["best"] and results["segment"]["best"]:
        summary["moller_speedup_best_tiles"] = round(
            results["moller"]["best"]["queries_per_sec"]
            / results["segment"]["best"]["queries_per_sec"], 2)
    return summary


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--queries", type=int, default=262144)
    parser.add_argument("--faces", type=int, default=13776)
    parser.add_argument("--reps", type=int, default=5)
    parser.add_argument("--mxu", action="store_true",
                        help="sweep the MXU dot-product tile instead and "
                             "persist the mxu_crossover calibration at "
                             "the best shape")
    parser.add_argument("--tri-tri", action="store_true", dest="tri_tri",
                        help="sweep the triangle-triangle tiles instead")
    args = parser.parse_args(argv)
    if args.mxu and args.tri_tri:
        parser.error("--mxu and --tri-tri are mutually exclusive")

    from bench import backend_responsive

    ok, reason = backend_responsive()
    if not ok:
        print(json.dumps({"error": "backend probe failed: %s" % reason}))
        sys.exit(1)

    from mesh_tpu.utils.compilation_cache import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    summary = (_tri_tri_sweep(args) if args.tri_tri
               else _closest_point_sweep(args))
    if summary["best"] is None:
        # automation must not mistake an all-failed sweep for a healthy one
        summary["error"] = "every tile combination failed"
    print(json.dumps(summary))
    if summary["best"] is None:
        sys.exit(1)


if __name__ == "__main__":
    main()
