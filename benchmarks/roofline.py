"""Device-absolute kernel accounting: pair rates, FLOP/s, HBM traffic and
% of chip peak (VERDICT r2 #2).

The CPU ratios in BASELINE.md ride on a shared VM whose clock drifts ~2x
between reruns; these absolute figures make kernel quality comparable
across rounds without trusting that clock.  Per measured kernel we
report:

- pair_tests/sec (the natural unit of every query kernel),
- achieved FLOP/s from an ANALYTIC per-pair flop count (hand-counted
  from the tile math, +-20% — good enough to place a kernel on the
  roofline; they are NOT hardware counters),
- modeled HBM bytes/s (face planes re-streamed per query tile + query
  I/O; VMEM-resident accumulators add nothing),
- % of v5e peak for whichever unit bounds the kernel, and the bound
  itself from the roofline ridge: intensity = flops/bytes vs
  peak_flops/peak_bw.

Peaks (per v5e chip, public figures; the VPU number is an estimate from
the "How to Scale Your Model" architecture description — 8x128 lanes x 4
ALUs x ~0.94 GHz):
"""

V5E_PEAK_FLOPS_VPU_F32 = 3.9e12     # elementwise f32 (no MXU)
V5E_PEAK_FLOPS_MXU_BF16 = 1.97e14
V5E_PEAK_HBM_BYTES = 8.19e11        # 819 GB/s

# analytic flops per pair test, hand-counted from each kernel's tile math
FLOPS_PER_PAIR = {
    # pallas_closest corner-a Ericson tile (pallas_closest.py:_cost_tile):
    # ap + 4 dots + derived corner terms + va/vb/vc + region selects
    "closest_point": 70,
    # division-free Moller-Trumbore any-hit (pallas_ray.py:_mt_hit):
    # 2 crosses + 4 dots + sign/tolerance compares
    "ray_any_hit": 50,
    # + |t| ordering division (pallas_ray.py:_alongnormal_cost_tile)
    "alongnormal": 55,
    # 6 edge-vs-face segment tests per triangle pair
    # (pallas_ray.py:_tri_tri_kernel)
    "tri_tri": 330,
    # Möller no-div interval test (pallas_ray.py:_moller_hit): plane
    # distances + D axis/projection + two interval computations + overlap
    "tri_tri_moller": 180,
    # nearest-vertex argmin: diff + sqnorm + running min
    "nearest_vertex": 10,
}


def accounting(kind, t_seconds, n_pairs, n_queries, n_faces,
               face_planes=9, query_io_bytes=0, platform="tpu"):
    """Roofline figures for one measured kernel invocation.

    :param kind: key into FLOPS_PER_PAIR
    :param t_seconds: measured seconds per invocation
    :param n_pairs: pair tests per invocation (usually Q*F or Q*F*B)
    :param n_queries: queries per invocation (I/O modeling)
    :param n_faces: faces streamed per query tile (HBM modeling)
    :param face_planes: f32 planes fetched per face per query tile
    :param query_io_bytes: extra per-invocation query-side I/O bytes
    :param platform: % of peak only reported for "tpu"
    """
    flops = FLOPS_PER_PAIR[kind] * n_pairs
    # each query tile streams every face plane once; 256 = the kernels'
    # default query tile
    n_qtiles = max(1, -(-n_queries // 256))
    hbm = n_qtiles * n_faces * face_planes * 4 + query_io_bytes
    out = {
        "kind": kind,
        "pair_tests_per_sec": round(n_pairs / t_seconds, 1),
        "achieved_flops_per_sec": round(flops / t_seconds, 1),
        "modeled_hbm_bytes_per_sec": round(hbm / t_seconds, 1),
    }
    if platform == "tpu":
        intensity = flops / max(hbm, 1)
        ridge = V5E_PEAK_FLOPS_VPU_F32 / V5E_PEAK_HBM_BYTES
        bound = "vpu" if intensity >= ridge else "hbm"
        out["arithmetic_intensity_flops_per_byte"] = round(intensity, 2)
        out["bound"] = bound
        out["pct_vpu_f32_peak"] = round(
            100.0 * flops / t_seconds / V5E_PEAK_FLOPS_VPU_F32, 1
        )
        out["pct_hbm_peak"] = round(
            100.0 * hbm / t_seconds / V5E_PEAK_HBM_BYTES, 1
        )
    return out
