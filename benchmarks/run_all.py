"""Full benchmark suite: the five BASELINE.md configs.

Run on the default (TPU) platform: `python benchmarks/run_all.py`.
Prints one JSON line per config plus a summary table; results fill the
BASELINE.md measurement columns.  CPU baseline timings use single-core
numpy/scipy equivalents of each workload (the reference's CGAL/OpenMP stack
is not installable here; algorithmic class is matched — tree-seeded exact
closest point, vectorized numpy normals).
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _time(fn, reps=3, warmup=1):
    import jax

    for _ in range(warmup):
        out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def config1():
    """Single SMPL template: estimate_vertex_normals + query-structure build
    (the reference builds a CGAL AABB tree, spatialsearchmodule.cpp:74-127;
    here 'build' is staging the triangle corner planes = negligible)."""
    import jax.numpy as jnp

    from mesh_tpu.geometry import vert_normals
    from mesh_tpu.models import smpl_sized_sphere

    v, f = smpl_sized_sphere()
    vj = jnp.asarray(v, jnp.float32)
    fj = jnp.asarray(f, jnp.int32)
    t = _time(lambda: vert_normals(vj, fj), reps=10)

    t0 = time.perf_counter()
    fn_np = np.cross(v[f[:, 1]] - v[f[:, 0]], v[f[:, 2]] - v[f[:, 0]])
    vn = np.zeros_like(v)
    for k in range(3):
        np.add.at(vn, f[:, k], fn_np)
    vn /= np.maximum(np.linalg.norm(vn, axis=1, keepdims=True), 1e-30)
    t_cpu = time.perf_counter() - t0
    return {"metric": "config1_single_smpl_normals", "value": round(1.0 / t, 1),
            "unit": "meshes/sec", "vs_baseline": round(t_cpu / t, 2)}


def config2():
    """FLAME-sized mesh (5023 v): tri_normals + connectivity + visibility."""
    import jax.numpy as jnp

    from mesh_tpu.geometry import tri_normals, vert_normals
    from mesh_tpu.query import visibility_compute
    from mesh_tpu.topology.connectivity import edge_topology_arrays

    # FLAME-scale vertex count: 71x71 parametric sphere + poles = 5043 verts
    n_seg, n_ring = 71, 71
    theta = np.pi * np.arange(1, n_ring + 1) / (n_ring + 1)
    phi = 2 * np.pi * np.arange(n_seg) / n_seg
    rings = np.stack([
        np.outer(np.sin(theta), np.cos(phi)),
        np.outer(np.sin(theta), np.sin(phi)),
        np.outer(np.cos(theta), np.ones(n_seg)),
    ], axis=-1).reshape(-1, 3)
    v = np.vstack([[[0, 0, 1.0]], rings, [[0, 0, -1.0]]])
    faces = []
    for r in range(n_ring - 1):
        b0, b1 = 1 + r * n_seg, 1 + (r + 1) * n_seg
        for s in range(n_seg):
            s1 = (s + 1) % n_seg
            faces.append([b0 + s, b1 + s, b1 + s1])
            faces.append([b0 + s, b1 + s1, b0 + s1])
    f = np.array(faces, dtype=np.int32)

    vj = jnp.asarray(v, jnp.float32)
    fj = jnp.asarray(f, jnp.int32)
    n = np.asarray(vert_normals(vj, fj))
    cams = np.array([[0, 0, 3.0], [3.0, 0, 0]])

    def work():
        tn = tri_normals(vj, fj)
        vis, ndc = visibility_compute(np.asarray(v), f, cams, n=n)
        return tn

    t = _time(work, reps=2)
    # connectivity is host-side, cached; time the cold build
    t0 = time.perf_counter()
    edge_topology_arrays(f, len(v))
    t_conn = time.perf_counter() - t0

    # cpu visibility baseline: per-camera x vertex x face Moller-Trumbore in
    # numpy (vectorized per camera-vertex chunk) — single core
    t0 = time.perf_counter()
    tri = v[f]
    for cam in cams[:1]:
        dirs = cam[None] - v
        dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
        # sample 500 vertices to keep the baseline tractable, then scale
        sub = slice(0, 500)
        o = v[sub] + 1e-3 * dirs[sub]
        e1 = tri[:, 1] - tri[:, 0]
        e2 = tri[:, 2] - tri[:, 0]
        pvec = np.cross(dirs[sub][:, None], e2[None])
        det = np.einsum("fk,qfk->qf", e1, pvec)
        inv = 1.0 / np.where(np.abs(det) < 1e-9, 1.0, det)
        tvec = o[:, None] - tri[None, :, 0]
        u = np.einsum("qfk,qfk->qf", tvec, pvec) * inv
        qvec = np.cross(tvec, e1[None])
        w = np.einsum("qk,qfk->qf", dirs[sub], qvec) * inv
        tt = np.einsum("fk,qfk->qf", e2, qvec) * inv
        hit = (np.abs(det) > 1e-9) & (u >= 0) & (w >= 0) & (u + w <= 1) & (tt >= 0)
        hit.any(axis=1)
    t_cpu = (time.perf_counter() - t0) * (len(v) / 500) * len(cams)
    return {"metric": "config2_flame_trinormals_visibility",
            "value": round(1.0 / t, 2), "unit": "passes/sec",
            "vs_baseline": round(t_cpu / t, 2), "conn_build_s": round(t_conn, 3)}


def config3():
    """Batch-256 posed bodies (the bench.py north star) — shares its code."""
    import bench

    elapsed, total_queries, out, model, betas, pose, queries = bench.tpu_workload()
    cpu_total = bench.cpu_baseline(model, betas, pose, queries)
    return {"metric": "config3_batch256_normals_closest_point",
            "value": round(total_queries / elapsed, 1), "unit": "queries/sec",
            "vs_baseline": round(cpu_total / elapsed, 2)}


def config4():
    """MANO-hand-sized vs SMPL-body-sized mesh intersection test."""
    import jax.numpy as jnp

    from mesh_tpu.query import intersections_mask
    from mesh_tpu.models import smpl_sized_sphere
    from mesh_tpu.sphere import _icosphere

    body_v, body_f = smpl_sized_sphere()
    hand_v, hand_f = _icosphere(3)  # 642 verts / 1280 faces ~ MANO scale
    hand_v = hand_v * 0.2 + np.array([0.9, 0, 0])  # grazing the body surface

    bv = body_v.astype(np.float32)
    bf = body_f.astype(np.int32)
    hv = hand_v.astype(np.float32)
    hf = hand_f.astype(np.int32)

    def work():
        return intersections_mask(bv, bf, hv, hf, chunk=128)

    t = _time(work, reps=2)
    n_hit = int(np.asarray(work()).sum())

    # cpu baseline: numpy segment-vs-triangle over the same pair grid,
    # chunked single-core; sample 64 query faces and scale
    from mesh_tpu.query.ray import tri_tri_intersects
    t0 = time.perf_counter()
    tri_b = body_v[body_f.astype(np.int64)]
    tri_h = hand_v[hand_f.astype(np.int64)][:64]
    for qt in tri_h:
        e = qt[[1, 2, 0]] - qt
        # 3 segment-vs-all-body-faces tests, numpy
        for i in range(3):
            s0, d = qt[i], e[i]
            a, b, c = tri_b[:, 0], tri_b[:, 1], tri_b[:, 2]
            e1, e2 = b - a, c - a
            pvec = np.cross(d, e2)
            det = np.einsum("fk,fk->f", e1, pvec)
            inv = 1.0 / np.where(np.abs(det) < 1e-9, 1.0, det)
            tvec = s0 - a
            u = np.einsum("fk,fk->f", tvec, pvec) * inv
            qvec = np.cross(tvec, e1)
            w = qvec @ d * inv
            tt = np.einsum("fk,fk->f", e2, qvec) * inv
            ((np.abs(det) > 1e-9) & (u >= 0) & (w >= 0) & (u + w <= 1)
             & (tt >= 0) & (tt <= 1)).any()
    t_cpu = (time.perf_counter() - t0) * (len(hand_f) / 64) * 2  # both dirs
    return {"metric": "config4_hand_body_intersection",
            "value": round(1.0 / t, 2), "unit": "tests/sec",
            "vs_baseline": round(t_cpu / t, 2), "intersecting_faces": n_hit}


def config5():
    """Scan registration scale: 100k-point scan -> SMPL closest faces.
    Single-chip here; sharded over all visible devices when >1 (the v5e-8
    path exercised by tests/test_parallel.py + dryrun_multichip)."""
    import jax

    from mesh_tpu.models import smpl_sized_sphere
    from mesh_tpu.query.pallas_closest import closest_point_pallas
    from mesh_tpu.query import closest_faces_and_points

    v, f = smpl_sized_sphere()
    rng = np.random.RandomState(0)
    scan = (rng.randn(100_000, 3) * 0.5).astype(np.float32)
    vf = v.astype(np.float32)
    fi = f.astype(np.int32)

    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel:
        def work():
            return closest_point_pallas(vf, fi, scan)
    else:
        def work():
            return closest_faces_and_points(vf, fi, scan)

    t = _time(work, reps=2)
    # cpu baseline lower bound: KD-tree seed query cost, scaled to 100k
    from scipy.spatial import cKDTree

    t0 = time.perf_counter()
    tree = cKDTree(v)
    tree.query(scan[:10000])
    t_seed = (time.perf_counter() - t0) * 10  # KD seed alone, scaled to 100k
    # exact refinement costs ~5x the seed in bench.py measurements; use seed
    # only as a LOWER bound for the CPU -> conservative vs_baseline
    return {"metric": "config5_scan100k_closest_faces",
            "value": round(100_000 / t, 1), "unit": "queries/sec",
            "vs_baseline": round(t_seed / t, 2)}


def main():
    results = []
    for cfg in (config1, config2, config3, config4, config5):
        try:
            res = cfg()
        except Exception as e:  # keep the suite running
            res = {"metric": cfg.__name__, "error": str(e)[:200]}
        results.append(res)
        print(json.dumps(res), flush=True)
    print(json.dumps({"suite": "baseline_configs", "results": results}))


if __name__ == "__main__":
    main()
