"""Full benchmark suite: the five BASELINE.md configs.

Run on the default (TPU) platform: `python benchmarks/run_all.py`.
Prints one JSON line per config plus a summary table; results fill the
BASELINE.md measurement columns.  CPU baseline timings use single-core
numpy/scipy equivalents of each workload (the reference's CGAL/OpenMP stack
is not installable here; algorithmic class is matched — tree-seeded exact
closest point, vectorized numpy normals).
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


# time_fn handles the axon-backend caveat: jax.block_until_ready returns
# before execution completes there, so timings synchronize by reading
# values back (see mesh_tpu/utils/profiling.py)
from mesh_tpu.utils.profiling import time_fn as _time  # noqa: E402
from roofline import accounting as _roofline  # noqa: E402


def _platform():
    import jax

    return jax.devices()[0].platform



def _chunked_moller_trumbore(origins, dirs, tri, t_max=None, chunk=500):
    """Single-core numpy Moller-Trumbore of many rays/segments against all
    triangles, chunked over the query axis.  ``t_max=None`` tests rays
    (t >= 0); ``t_max=1`` tests segments.  Shared CPU-baseline kernel for
    configs 2 and 4 so their timings stay comparable."""
    a = tri[:, 0]
    e1 = tri[:, 1] - a
    e2 = tri[:, 2] - a
    for lo in range(0, len(origins), chunk):
        o = origins[lo:lo + chunk]
        d = dirs[lo:lo + chunk]
        pvec = np.cross(d[:, None], e2[None])
        det = np.einsum("fk,qfk->qf", e1, pvec)
        inv = 1.0 / np.where(np.abs(det) < 1e-9, 1.0, det)
        tvec = o[:, None] - a[None]
        u = np.einsum("qfk,qfk->qf", tvec, pvec) * inv
        qvec = np.cross(tvec, e1[None])
        w = np.einsum("qk,qfk->qf", d, qvec) * inv
        tt = np.einsum("fk,qfk->qf", e2, qvec) * inv
        hit = (np.abs(det) > 1e-9) & (u >= 0) & (w >= 0) & (u + w <= 1) & (tt >= 0)
        if t_max is not None:
            hit &= tt <= t_max
        hit.any(axis=1)


def _cpu_exact_on_candidates(points, tri_cand):
    """Min squared point-triangle distance over per-query candidate sets,
    single-core vectorized numpy (7-candidate Ericson form: the three
    corners, the three clamped edge projections, and the clamped interior
    projection).  Shared CPU-baseline kernel of configs 5 and 6 so their
    tree-seeded baselines stay identical.

    :param points: [n, 3] f64 queries
    :param tri_cand: [n, K, 3, 3] f64 candidate triangles per query
    :returns: [n] min squared distances
    """
    a_, b_, c_ = tri_cand[:, :, 0], tri_cand[:, :, 1], tri_cand[:, :, 2]
    p = points[:, None, :]
    ab, ac, ap = b_ - a_, c_ - a_, p - a_
    d1 = np.einsum("nkj,nkj->nk", ab, ap)
    d2 = np.einsum("nkj,nkj->nk", ac, ap)
    bp = p - b_
    d3 = np.einsum("nkj,nkj->nk", ab, bp)
    d4 = np.einsum("nkj,nkj->nk", ac, bp)
    cp = p - c_
    d5 = np.einsum("nkj,nkj->nk", ab, cp)
    d6 = np.einsum("nkj,nkj->nk", ac, cp)
    va = d3 * d6 - d5 * d4
    vb = d5 * d2 - d1 * d6
    vc = d1 * d4 - d3 * d2
    denom = np.where(va + vb + vc == 0, 1.0, va + vb + vc)
    w1 = vb / denom
    w2 = vc / denom
    # the interior projection is only a valid candidate when it falls
    # inside the triangle — clamping the barycentrics independently can
    # produce a point OUTSIDE the face whose distance underestimates the
    # true one; substitute corner a (already a candidate) when invalid
    inside = (w1 >= 0) & (w2 >= 0) & (w1 + w2 <= 1)
    w1 = np.where(inside, w1, 0.0)
    w2 = np.where(inside, w2, 0.0)
    # region clamps (vectorized Ericson)
    t_ab = np.clip(d1 / np.where(d1 - d3 == 0, 1.0, d1 - d3), 0, 1)
    t_ac = np.clip(d2 / np.where(d2 - d6 == 0, 1.0, d2 - d6), 0, 1)
    t_bc = np.clip(
        (d4 - d3) / np.where((d4 - d3) + (d5 - d6) == 0, 1.0,
                             (d4 - d3) + (d5 - d6)), 0, 1)
    cands = np.stack([
        a_, b_, c_,
        a_ + t_ab[..., None] * ab,
        a_ + t_ac[..., None] * ac,
        b_ + t_bc[..., None] * (c_ - b_),
        a_ + w1[..., None] * ab + w2[..., None] * ac,
    ], axis=2)                                          # [n, K, 7, 3]
    diff = p[:, :, None, :] - cands
    dall = np.einsum("nkrj,nkrj->nkr", diff, diff)
    return dall.min(axis=(1, 2))


def config1():
    """Single SMPL template: estimate_vertex_normals + query-structure build
    (the reference builds a CGAL AABB tree, spatialsearchmodule.cpp:74-127;
    here 'build' is staging the triangle corner planes = negligible)."""
    import jax.numpy as jnp

    from mesh_tpu.geometry import vert_normals
    from mesh_tpu.models import smpl_sized_sphere

    import jax

    v, f = smpl_sized_sphere()
    vj = jnp.asarray(v, jnp.float32)
    fj = jnp.asarray(f, jnp.int32)
    # one dispatch per mesh: dominated by the host->device dispatch latency
    # on this machine's tunneled TPU (~25 ms/call) — reported for honesty
    t_dispatch = _time(lambda: vert_normals(vj, fj), reps=20)

    # sustained device-resident rate: 200 dependent iterations inside one
    # jit (the framework's model is mesh pipelines living on device; the
    # +1e-30*n data dependence stops XLA from eliding iterations)
    loop_n = 200

    @jax.jit
    def sustained(vv):
        def body(vv, _):
            n = vert_normals(vv, fj)
            return vv + 1e-30 * n, ()
        vv, _ = jax.lax.scan(body, vv, None, length=loop_n)
        return vv

    t = _time(lambda: sustained(vj), reps=3) / loop_n

    t0 = time.perf_counter()
    fn_np = np.cross(v[f[:, 1]] - v[f[:, 0]], v[f[:, 2]] - v[f[:, 0]])
    vn = np.zeros_like(v)
    for k in range(3):
        np.add.at(vn, f[:, k], fn_np)
    vn /= np.maximum(np.linalg.norm(vn, axis=1, keepdims=True), 1e-30)
    t_cpu = time.perf_counter() - t0
    # batched facade: B same-topology meshes through the reference-shaped
    # numpy-in/numpy-out API in ONE dispatch (mesh_tpu.batch) — the entry
    # point that lets facade callers amortize the tunnel round trip
    # (VERDICT r2 #4: target within ~4x of the sustained device rate)
    from mesh_tpu.batch import (
        batched_vertex_normals,
        fused_normals_and_closest_points,
    )

    batch_b = 64
    rng = np.random.RandomState(0)
    v_stack = (
        v[None] + 0.01 * rng.randn(batch_b, *v.shape)
    ).astype(np.float32)
    f_np = np.asarray(f, np.int32)
    t_batched = _time(
        lambda: batched_vertex_normals((v_stack, f_np)), reps=5
    ) / batch_b
    # the fused facade entry (normals AND closest-point queries, one
    # dispatch for the whole batch): the reference-shaped caller's escape
    # from per-call tunnel latency (VERDICT r3 #4)
    q_fused = rng.randn(256, 3).astype(np.float32)
    t_fused = _time(
        lambda: fused_normals_and_closest_points((v_stack, f_np), q_fused),
        reps=5,
    ) / batch_b

    # metric renamed from config1_single_smpl_normals (which measured
    # per-call dispatch until r01): the headline is the sustained
    # device-resident rate, the dispatch-bound rate rides alongside
    return {"metric": "config1_sustained_normals", "value": round(1.0 / t, 1),
            "unit": "meshes/sec", "vs_baseline": round(t_cpu / t, 2),
            "single_dispatch_meshes_per_sec": round(1.0 / t_dispatch, 1),
            "facade_batched_meshes_per_sec": round(1.0 / t_batched, 1),
            "facade_fused_normals_plus_query_meshes_per_sec":
                round(1.0 / t_fused, 1)}


def config2():
    """FLAME-sized mesh (5023 v): tri_normals + connectivity + visibility."""
    import jax.numpy as jnp

    from mesh_tpu.geometry import tri_normals, vert_normals
    from mesh_tpu.query import visibility_compute
    from mesh_tpu.topology.connectivity import edge_topology_arrays

    # FLAME-scale vertex count: 71x71 parametric sphere + poles = 5043 verts
    n_seg, n_ring = 71, 71
    theta = np.pi * np.arange(1, n_ring + 1) / (n_ring + 1)
    phi = 2 * np.pi * np.arange(n_seg) / n_seg
    rings = np.stack([
        np.outer(np.sin(theta), np.cos(phi)),
        np.outer(np.sin(theta), np.sin(phi)),
        np.outer(np.cos(theta), np.ones(n_seg)),
    ], axis=-1).reshape(-1, 3)
    v = np.vstack([[[0, 0, 1.0]], rings, [[0, 0, -1.0]]])
    faces = []
    for r in range(n_ring - 1):
        b0, b1 = 1 + r * n_seg, 1 + (r + 1) * n_seg
        for s in range(n_seg):
            s1 = (s + 1) % n_seg
            faces.append([b0 + s, b1 + s, b1 + s1])
            faces.append([b0 + s, b1 + s1, b0 + s1])
    f = np.array(faces, dtype=np.int32)

    import jax

    from mesh_tpu.query.visibility import _visibility_local

    vj = jnp.asarray(v, jnp.float32)
    fj = jnp.asarray(f, jnp.int32)
    nj = vert_normals(vj, fj)
    n = np.asarray(nj)
    cams = np.array([[0, 0, 3.0], [3.0, 0, 0]])

    # facade path (host numpy in/out — the reference's API shape); on this
    # machine's tunneled TPU each call pays two host round-trips
    t_facade = _time(
        lambda: visibility_compute(np.asarray(v), f, cams, n=n), reps=5
    )

    # device-resident path the way a TPU pipeline calls it:
    # _visibility_local is visibility_compute's own backend dispatch
    # (Pallas any-hit kernel on TPU, XLA tiling elsewhere)
    occ = jax.device_put(vj[fj])
    cams_j = jax.device_put(cams.astype(np.float32))

    @jax.jit
    def work():
        tn = tri_normals(vj, fj)
        vis, ndc = _visibility_local(
            vj, occ, cams_j, nj, None, np.float32(1e-3)
        )
        return tn, vis, ndc

    t = _time(work, reps=10)
    # connectivity is host-side, cached; time the cold build
    t0 = time.perf_counter()
    edge_topology_arrays(f, len(v))
    t_conn = time.perf_counter() - t0

    # cpu visibility baseline: per-camera x vertex x face Moller-Trumbore in
    # numpy, single core, FULL SIZE (every vertex, every camera — no
    # sample-and-scale)
    t0 = time.perf_counter()
    tri = v[f]
    for cam in cams:
        dirs = cam[None] - v
        dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
        _chunked_moller_trumbore(v + 1e-3 * dirs, dirs, tri)
    t_cpu = time.perf_counter() - t0
    return {"metric": "config2_flame_trinormals_visibility",
            "value": round(1.0 / t, 2), "unit": "passes/sec",
            "vs_baseline": round(t_cpu / t, 2), "conn_build_s": round(t_conn, 3),
            "facade_passes_per_sec": round(1.0 / t_facade, 2),
            "device_absolute": _roofline(
                "ray_any_hit", t, n_pairs=len(cams) * len(v) * len(f),
                n_queries=len(cams) * len(v), n_faces=len(f),
                face_planes=9, platform=_platform())}


def config3():
    """Batch-256 posed bodies (the bench.py north star) — shares its code."""
    import bench

    elapsed, total_queries, out, model, betas, pose, queries = bench.tpu_workload()
    cpu_total = bench.cpu_baseline(model, betas, pose, queries)
    n_faces = int(np.asarray(model.faces).shape[0])
    return {"metric": "config3_batch256_normals_closest_point",
            "value": round(total_queries / elapsed, 1), "unit": "queries/sec",
            "vs_baseline": round(cpu_total / elapsed, 2),
            "device_absolute": _roofline(
                "closest_point", elapsed, n_pairs=total_queries * n_faces,
                n_queries=total_queries, n_faces=n_faces,
                face_planes=19, platform=_platform())}


def config4():
    """MANO-hand-sized vs SMPL-body-sized mesh intersection test."""
    import jax.numpy as jnp

    from mesh_tpu.query import intersections_mask
    from mesh_tpu.models import smpl_sized_sphere
    from mesh_tpu.sphere import _icosphere

    body_v, body_f = smpl_sized_sphere()
    hand_v, hand_f = _icosphere(3)  # 642 verts / 1280 faces ~ MANO scale
    hand_v = hand_v * 0.2 + np.array([0.9, 0, 0])  # grazing the body surface

    bv = body_v.astype(np.float32)
    bf = body_f.astype(np.int32)
    hv = hand_v.astype(np.float32)
    hf = hand_f.astype(np.int32)

    def work():
        return intersections_mask(bv, bf, hv, hf, chunk=128)

    t = _time(work, reps=5)
    n_hit = int(np.asarray(work()).sum())

    # both tile algorithms, timed explicitly on the Pallas path (the
    # facade auto-picks moller for this clean geometry; the pair shows
    # the measured win and keeps the segment tile's number comparable
    # across rounds)
    from mesh_tpu.query.ray import _tri_tri_algorithm
    from mesh_tpu.utils.dispatch import pallas_default as _pd

    algo = _tri_tri_algorithm(bv, bf, hv, hf) if _pd() else "segment(xla)"
    t_by_algo = {}
    if _pd():
        from mesh_tpu.query.ray import _intersections_mask_pallas

        for name in ("segment", "moller"):
            t_by_algo[name] = _time(
                lambda: _intersections_mask_pallas(
                    bv, bf, hv, hf, algorithm=name),
                reps=5,
            )

    # cpu baseline: numpy segment-vs-triangle over the full pair grid,
    # single core, FULL SIZE — all edges of each mesh against all faces of
    # the other (tri-tri intersection needs both directions), no
    # sample-and-scale
    t0 = time.perf_counter()
    tri_b = body_v[body_f.astype(np.int64)]
    tri_h = hand_v[hand_f.astype(np.int64)]
    for tri_src, tri_dst in ((tri_h, tri_b), (tri_b, tri_h)):
        seg0 = tri_src.reshape(-1, 3)
        segd = (tri_src[:, [1, 2, 0]] - tri_src).reshape(-1, 3)
        _chunked_moller_trumbore(seg0, segd, tri_dst, t_max=1.0, chunk=64)
    t_cpu = time.perf_counter() - t0
    rec = {"metric": "config4_hand_body_intersection",
           "value": round(1.0 / t, 2), "unit": "tests/sec",
           "vs_baseline": round(t_cpu / t, 2), "intersecting_faces": n_hit,
           "tri_tri_algorithm": algo,
           "device_absolute": _roofline(
               "tri_tri_moller" if algo == "moller" else "tri_tri", t,
               n_pairs=len(hf) * len(bf), n_queries=len(hf),
               n_faces=len(bf),
               face_planes=13 if algo == "moller" else 9,
               platform=_platform())}
    if t_by_algo:
        rec["segment_tests_per_sec"] = round(1.0 / t_by_algo["segment"], 2)
        rec["moller_tests_per_sec"] = round(1.0 / t_by_algo["moller"], 2)
        rec["moller_speedup"] = round(
            t_by_algo["segment"] / t_by_algo["moller"], 2)
    return rec


def config5():
    """Scan registration scale: 100k-point scan -> SMPL closest faces.
    Single-chip here; sharded over all visible devices when >1 (the v5e-8
    path exercised by tests/test_parallel.py + dryrun_multichip)."""
    import jax

    from mesh_tpu.models import smpl_sized_sphere
    from mesh_tpu.query.pallas_closest import closest_point_pallas
    from mesh_tpu.query import closest_faces_and_points

    v, f = smpl_sized_sphere()
    rng = np.random.RandomState(0)
    # a scan IS noisy surface samples of the scanned subject: sample the
    # mesh surface and perturb (1 cm noise at body scale), rather than an
    # unrelated gaussian blob
    sample = rng.randint(0, len(f), 100_000)
    bary = rng.dirichlet([1.0, 1.0, 1.0], 100_000)
    scan = (
        (v[f[sample]] * bary[:, :, None]).sum(1)
        + rng.randn(100_000, 3) * 0.01
    ).astype(np.float32)
    vf = v.astype(np.float32)
    fi = f.astype(np.int32)

    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel:
        from mesh_tpu.query.pallas_closest import mesh_is_nondegenerate

        nondegen = mesh_is_nondegenerate(vf, fi)

        def work():
            return closest_point_pallas(
                vf, fi, scan, assume_nondegenerate=nondegen)
    else:
        def work():
            return closest_faces_and_points(vf, fi, scan)

    t = _time(work, reps=10)

    # CPU baseline: single-core, fully vectorized numpy — KD-tree vertex
    # seed + exact Ericson test on the seed vertex's nearby faces (padded
    # 2-ring table; table build excluded from timing, like the reference's
    # cached AABB tree build).  This is the same algorithmic class as the
    # reference's CGAL stack, vectorized as well as numpy allows.
    from scipy.spatial import cKDTree

    ring_k = 32
    incident = [[] for _ in range(len(v))]
    for fi_, (a, b, c) in enumerate(f):
        incident[a].append(fi_)
        incident[b].append(fi_)
        incident[c].append(fi_)
    ring = np.zeros((len(v), ring_k), np.int64)
    for vi_ in range(len(v)):
        faces = {
            g for u in {x for fj_ in incident[vi_] for x in f[fj_]}
            for g in incident[u]
        }
        lst = sorted(faces)[:ring_k]
        ring[vi_, : len(lst)] = lst
        ring[vi_, len(lst):] = lst[0] if lst else 0
    tree = cKDTree(v)
    n_sub = 100_000          # FULL SIZE: every scan point, no scale-up
    t0 = time.perf_counter()
    _, seed = tree.query(scan[:n_sub])
    cand = ring[seed]                                   # [n, K]
    best = _cpu_exact_on_candidates(
        scan[:n_sub].astype(np.float64), v[f[cand]]
    )
    t_cpu = (time.perf_counter() - t0) * (100_000 / n_sub)
    del best
    return {"metric": "config5_scan100k_closest_faces",
            "value": round(100_000 / t, 1), "unit": "queries/sec",
            "vs_baseline": round(t_cpu / t, 2),
            "device_absolute": _roofline(
                "closest_point", t, n_pairs=100_000 * len(f),
                n_queries=100_000, n_faces=len(f),
                face_planes=19, platform=_platform())}


def config6():
    """Large-F regime (VERDICT r2 #3): a ~1M-face mesh queried by sparse
    (1024) and scan-dense (100k) point sets.  Brute force is O(Q*F) exact
    work; the tile-sphere-culled kernel does an O(Q*F) cheap-bound pass +
    O(Q*k) exact work and must win here — the regime where the reference's
    CGAL tree descends in O(log F) (spatialsearchmodule.cpp:105-127).
    Also runs `calibrate_crossover()` so closest_faces_and_points_auto
    switches at the crossover MEASURED on this backend.
    """
    from mesh_tpu.query import (
        calibrate_crossover,
        closest_faces_and_points,
        closest_faces_and_points_auto,
    )
    from mesh_tpu.query.autotune import _sphere_mesh
    from mesh_tpu.query.culled import closest_faces_and_points_culled
    from mesh_tpu.utils.dispatch import pallas_default

    on_accel = pallas_default()
    # full size on the accelerator; tractable smoke size if someone runs
    # the suite on CPU (labelled honestly in the output)
    n_faces = 1_000_000 if on_accel else 32_768
    n_dense = 100_000 if on_accel else 2_048
    reps = 3 if on_accel else 1
    v, f = _sphere_mesh(n_faces)
    rng = np.random.RandomState(0)
    sparse = rng.randn(1024, 3).astype(np.float32)
    dense = rng.randn(n_dense, 3).astype(np.float32)

    if on_accel:
        from functools import partial as _partial

        from mesh_tpu.query.pallas_closest import (
            closest_point_pallas,
            mesh_is_nondegenerate,
        )
        from mesh_tpu.query.pallas_culled import closest_point_pallas_culled

        # mirror the facade dispatch: both kernels run with the
        # data-derived nondegeneracy flag (culled.py does the same check)
        _nd = mesh_is_nondegenerate(v, f)
        brute = _partial(closest_point_pallas, assume_nondegenerate=_nd)
        culled = _partial(closest_point_pallas_culled,
                          assume_nondegenerate=_nd)
    else:
        brute = closest_faces_and_points
        culled = closest_faces_and_points_culled

    t_brute_sparse = _time(lambda: brute(v, f, sparse), reps=reps)
    t_culled_sparse = _time(lambda: culled(v, f, sparse), reps=reps)
    t_brute_dense = _time(lambda: brute(v, f, dense), reps=reps)
    t_culled_dense = _time(lambda: culled(v, f, dense), reps=reps)

    # the auto strategy must pick the measured winner at this F
    if on_accel:
        crossover = calibrate_crossover()
    else:
        # CPU smoke: low-rep truncated ladder — never persist it over the
        # production default on a shared cache dir
        crossover = calibrate_crossover(
            ladder=(4096, 8192, 16384), n_queries=256, reps=1, save=False
        )
    t_auto_dense = _time(
        lambda: closest_faces_and_points_auto(v, f, dense), reps=reps
    )
    # label the timing with the strategy auto ACTUALLY used — its threshold
    # resolves through crossover_faces(), where an env override outranks
    # the calibration just performed
    from mesh_tpu.query import crossover_faces

    auto_picked = "culled" if f.shape[0] > crossover_faces() else "brute"

    # exactness: all strategies agree on the sparse set (auto is exact by
    # construction; brute is the oracle)
    ref = brute(v, f, sparse)
    got = closest_faces_and_points_auto(v, f, sparse)
    d_err = float(np.abs(
        np.sqrt(np.asarray(got["sqdist"]))
        - np.sqrt(np.asarray(ref["sqdist"]))
    ).max())
    assert d_err < 1e-4, "auto disagrees with brute at %d faces: %g" % (
        f.shape[0], d_err)

    # CPU baseline, same algorithmic class as the reference's CGAL stack:
    # KD-tree over triangle centroids seeds k candidates, exact vectorized
    # Ericson test on the candidates (tree build excluded, like the
    # reference's cached aabbtree_compute)
    from scipy.spatial import cKDTree

    tri = v[f].astype(np.float64)
    tree = cKDTree(tri.mean(axis=1))
    n_sub = min(20_000, n_dense)
    t0 = time.perf_counter()
    _, cand = tree.query(dense[:n_sub].astype(np.float64), k=32)
    _cpu_exact_on_candidates(dense[:n_sub].astype(np.float64), tri[cand])
    t_cpu = (time.perf_counter() - t0) * (n_dense / n_sub)

    return {"metric": "config6_largef_closest_point",
            "value": round(n_dense / t_auto_dense, 1), "unit": "queries/sec",
            "vs_baseline": round(t_cpu / t_auto_dense, 2),
            "n_faces": int(f.shape[0]), "n_dense": n_dense,
            "crossover_measured": int(crossover),
            "auto_picked": auto_picked,
            "sparse_brute_s": round(t_brute_sparse, 4),
            "sparse_culled_s": round(t_culled_sparse, 4),
            "dense_brute_s": round(t_brute_dense, 4),
            "dense_culled_s": round(t_culled_dense, 4),
            "culled_speedup_dense": round(t_brute_dense / t_culled_dense, 2),
            "device_absolute_brute": _roofline(
                "closest_point", t_brute_dense,
                n_pairs=n_dense * int(f.shape[0]), n_queries=n_dense,
                n_faces=int(f.shape[0]), face_planes=19,
                platform=_platform())}


ALL_CONFIGS = (config1, config2, config3, config4, config5, config6)


def main(argv=None):
    """`python benchmarks/run_all.py [--configs 1,4,6] [--trace DIR]`

    --configs reruns a subset (the on-chip gates shouldn't pay for five
    healthy configs to re-measure one fix); --trace captures a
    jax.profiler trace per config under DIR (view with tensorboard or
    xprof) for kernel-level analysis on the chip.
    """
    import argparse

    from bench import backend_responsive

    parser = argparse.ArgumentParser(description=main.__doc__)
    parser.add_argument("--configs", default=None,
                        help="comma-separated config numbers, e.g. 1,4,6")
    parser.add_argument("--trace", default=None, metavar="DIR",
                        help="write a jax.profiler trace per config")
    args = parser.parse_args(argv)

    configs = ALL_CONFIGS
    if args.configs:
        try:
            wanted = {int(x) for x in args.configs.split(",")}
        except ValueError:
            parser.error("--configs wants comma-separated integers, got %r"
                         % args.configs)
        unknown = wanted - set(range(1, len(ALL_CONFIGS) + 1))
        if unknown:
            parser.error("unknown config numbers: %s" % sorted(unknown))
        configs = [c for i, c in enumerate(ALL_CONFIGS, 1) if i in wanted]

    ok, reason = backend_responsive()
    if not ok:
        # the wedged-tunnel guard (bench.py): fail fast with a record
        # instead of hanging inside the first config's backend init
        print(json.dumps({"suite": "baseline_configs", "results": [],
                          "error": "jax backend probe failed: %s" % reason}))
        sys.exit(1)
    # rerun compiles (a fresh process per gate, tools/run_tpu_gates.sh)
    # load from disk instead of recompiling every config's programs
    from mesh_tpu.utils.compilation_cache import (
        enable_persistent_compilation_cache,
    )

    enable_persistent_compilation_cache()
    import contextlib

    if args.trace:
        from mesh_tpu.utils.profiling import trace

    results = []
    for cfg in configs:
        ctx = (trace("%s/%s" % (args.trace, cfg.__name__))
               if args.trace else contextlib.nullcontext())
        try:
            with ctx:
                res = cfg()
        except Exception as e:  # keep the suite running
            res = {"metric": cfg.__name__, "error": str(e)[:200]}
        results.append(res)
        print(json.dumps(res), flush=True)
    print(json.dumps({"suite": "baseline_configs", "results": results}))


if __name__ == "__main__":
    main()
