// Fast OBJ parser for mesh_tpu — native I/O core.
//
// TPU-native analog of the reference's C++ loader (mesh/src/py_loadobj.cpp):
// the device side of the framework is JAX/Pallas, but file ingest is still
// host CPU work, and Python-level line parsing is the bottleneck the
// reference grew a C++ loader for (serialization.py:414: "XXX experimental
// cpp obj loader" is the default).  This library exposes a plain C ABI
// consumed via ctypes (no pybind11 in the image): parse once into growable
// buffers, hand Python flat arrays + a compact event log for segments,
// landmarks and mtllib lines.
//
// Supported surface (parity with py_loadobj.cpp:105-189):
//   v x y z [r g b]      vt u v [w]        vn x y z
//   f a b c d...         (fan triangulation; a, a/t, a/t/n, a//n forms)
//   g <name>             #landmark <name>  mtllib <path>

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct ObjData {
  std::vector<double> v, vt, vn, vc;
  std::vector<int64_t> f, ft, fn;
  int vt_width = 2;
  // event log: lines of "g <name> <next_face_idx>", "l <name> <next_vert>",
  // "m <mtl_path>" — decoded by the Python binding
  std::string events;
  std::string error;
};

inline const char* skip_ws(const char* p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
  return p;
}

inline const char* next_token(const char* p, std::string* out) {
  p = skip_ws(p);
  const char* start = p;
  while (*p && *p != ' ' && *p != '\t' && *p != '\r' && *p != '\n') ++p;
  out->assign(start, p - start);
  return p;
}

// parse up to `max_vals` doubles; returns count parsed
inline int parse_doubles(const char* p, double* out, int max_vals) {
  int n = 0;
  char* end = nullptr;
  while (n < max_vals) {
    p = skip_ws(p);
    if (*p == '\0' || *p == '\n') break;
    double val = strtod(p, &end);
    if (end == p) break;
    out[n++] = val;
    p = end;
  }
  return n;
}

}  // namespace

extern "C" {

ObjData* obj_load(const char* path) {
  FILE* fp = fopen(path, "rb");
  auto* data = new ObjData();
  if (!fp) {
    data->error = std::string("could not open ") + path;
    return data;
  }
  // slurp the file; OBJ files are line-oriented ascii
  fseek(fp, 0, SEEK_END);
  long size = ftell(fp);
  fseek(fp, 0, SEEK_SET);
  std::string buf(size, '\0');
  size_t got = fread(&buf[0], 1, size, fp);
  fclose(fp);
  buf.resize(got);

  std::string pending_landmark;
  std::string tok;
  std::vector<int64_t> corner_v, corner_t, corner_n;

  const char* p = buf.c_str();
  const char* bufend = p + buf.size();
  while (p < bufend) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', bufend - p));
    if (!line_end) line_end = bufend;
    const char* q = skip_ws(p);
    if (q[0] == 'v' && (q[1] == ' ' || q[1] == '\t')) {
      double vals[6];
      int n = parse_doubles(q + 1, vals, 6);
      if (n >= 3) {
        data->v.insert(data->v.end(), vals, vals + 3);
        if (n == 6) data->vc.insert(data->vc.end(), vals + 3, vals + 6);
        if (!pending_landmark.empty()) {
          data->events += "l " + pending_landmark + " " +
                          std::to_string(data->v.size() / 3 - 1) + "\n";
          pending_landmark.clear();
        }
      }
    } else if (q[0] == 'v' && q[1] == 't') {
      // always store 3 slots per vt so a mid-file 2->3 component switch
      // cannot misalign the buffer; obj_copy strides by the final width
      double vals[3] = {0.0, 0.0, 0.0};
      int n = parse_doubles(q + 2, vals, 3);
      if (n >= 2) {
        if (n == 3) data->vt_width = 3;
        data->vt.insert(data->vt.end(), vals, vals + 3);
      }
    } else if (q[0] == 'v' && q[1] == 'n') {
      double vals[3];
      if (parse_doubles(q + 2, vals, 3) == 3)
        data->vn.insert(data->vn.end(), vals, vals + 3);
    } else if (q[0] == 'f' && (q[1] == ' ' || q[1] == '\t')) {
      corner_v.clear();
      corner_t.clear();
      corner_n.clear();
      const char* c = q + 1;
      while (c < line_end) {
        c = skip_ws(c);
        if (c >= line_end || *c == '\n') break;
        char* end = nullptr;
        long a = strtol(c, &end, 10);
        if (end == c) break;
        c = end;
        long t = 0, nn = 0;
        bool has_t = false, has_n = false;
        if (*c == '/') {
          ++c;
          if (*c != '/') {
            t = strtol(c, &end, 10);
            has_t = end != c;
            c = end;
          }
          if (*c == '/') {
            ++c;
            nn = strtol(c, &end, 10);
            has_n = end != c;
            c = end;
          }
        }
        corner_v.push_back(a);
        corner_t.push_back(has_t ? t : 0);
        corner_n.push_back(has_n ? nn : 0);
      }
      for (size_t i = 1; i + 1 < corner_v.size(); ++i) {
        data->f.push_back(corner_v[0] - 1);
        data->f.push_back(corner_v[i] - 1);
        data->f.push_back(corner_v[i + 1] - 1);
        if (corner_t[0] > 0) {
          data->ft.push_back(corner_t[0] - 1);
          data->ft.push_back(corner_t[i] - 1);
          data->ft.push_back(corner_t[i + 1] - 1);
        }
        if (corner_n[0] > 0) {
          data->fn.push_back(corner_n[0] - 1);
          data->fn.push_back(corner_n[i] - 1);
          data->fn.push_back(corner_n[i + 1] - 1);
        }
      }
    } else if (q[0] == 'g' && (q[1] == ' ' || q[1] == '\t')) {
      next_token(q + 1, &tok);
      data->events +=
          "g " + tok + " " + std::to_string(data->f.size() / 3) + "\n";
    } else if (strncmp(q, "#landmark", 9) == 0) {
      next_token(q + 9, &pending_landmark);
    } else if (strncmp(q, "mtllib", 6) == 0) {
      next_token(q + 6, &tok);
      data->events += "m " + tok + "\n";
    }
    p = line_end + 1;
  }
  return data;
}

void obj_free(ObjData* data) { delete data; }

const char* obj_error(ObjData* data) { return data->error.c_str(); }

const char* obj_events(ObjData* data) { return data->events.c_str(); }

void obj_counts(ObjData* data, int64_t* out) {
  out[0] = data->v.size() / 3;
  out[1] = data->vt.size() / 3;  // stored 3 slots per entry regardless of width
  out[2] = data->vn.size() / 3;
  out[3] = data->f.size() / 3;
  out[4] = data->ft.size() / 3;
  out[5] = data->fn.size() / 3;
  out[6] = data->vc.size() / 3;
  out[7] = data->vt_width;
}

void obj_copy(ObjData* data, double* v, double* vt, double* vn, double* vc,
              int64_t* f, int64_t* ft, int64_t* fn) {
  if (v) memcpy(v, data->v.data(), data->v.size() * sizeof(double));
  if (vt) {
    // emit rows of vt_width components from the 3-slot storage
    size_t rows = data->vt.size() / 3;
    for (size_t r = 0; r < rows; ++r)
      memcpy(vt + r * data->vt_width, data->vt.data() + r * 3,
             data->vt_width * sizeof(double));
  }
  if (vn) memcpy(vn, data->vn.data(), data->vn.size() * sizeof(double));
  if (vc) memcpy(vc, data->vc.data(), data->vc.size() * sizeof(double));
  if (f) memcpy(f, data->f.data(), data->f.size() * sizeof(int64_t));
  if (ft) memcpy(ft, data->ft.data(), data->ft.size() * sizeof(int64_t));
  if (fn) memcpy(fn, data->fn.data(), data->fn.size() * sizeof(int64_t));
}

}  // extern "C"
