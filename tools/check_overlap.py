"""Self-audit for incidental source overlap with the reference package.

    python tools/check_overlap.py [threshold]

For every Python file in this repo, finds the reference file (same name, or
any reference file) with the highest stripped-line overlap and prints files
above the threshold.  "Stripped" = whitespace-normalized, comment-free,
non-empty lines.  Delegation one-liners and file-format constants overlap
unavoidably (the facade sits at ~34% from one-line delegates alone), so
the default gate is 0.50 — between that baseline and the 0.60 copy
detector; pass a lower threshold for an informational listing (nonzero
exit when any file matches).
"""

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference"


def stripped_lines(path):
    out = []
    for line in open(path, encoding="utf-8", errors="ignore"):
        s = re.sub(r"\s+", " ", line.strip())
        if s and not s.startswith("#"):
            out.append(s)
    return out


def collect(root, skip_dirs=()):
    files = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in skip_dirs and d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                files[os.path.relpath(p, root)] = stripped_lines(p)
    return files


def main():
    threshold = float(sys.argv[1]) if len(sys.argv) > 1 else 0.50
    if not os.path.isdir(REFERENCE):
        # an absent reference must not read as a clean bill of health
        print("error: reference checkout not found at %s" % REFERENCE,
              file=sys.stderr)
        return 2
    ours = collect(REPO, skip_dirs=(".git", "tests"))
    refs = collect(REFERENCE, skip_dirs=(".git",))
    ref_sets = {rel: set(lines) for rel, lines in refs.items()}

    rows = []
    for rel, lines in sorted(ours.items()):
        if len(lines) < 20:
            continue
        best_frac, best_ref = 0.0, ""
        for ref_rel, ref_set in ref_sets.items():
            ov = sum(1 for l in lines if l in ref_set)
            frac = ov / len(lines)
            if frac > best_frac:
                best_frac, best_ref = frac, ref_rel
        if best_frac >= threshold:
            rows.append((best_frac, rel, best_ref))

    for frac, rel, ref_rel in sorted(rows, reverse=True):
        print("%5.0f%%  %-50s  vs %s" % (frac * 100, rel, ref_rel))
    if not rows:
        print("no files at or above %.0f%% overlap" % (threshold * 100))
        return 0
    return 1        # nonzero so CI can gate on a caller-chosen threshold


if __name__ == "__main__":
    sys.exit(main())
