#!/bin/bash
# Standing tunnel watchdog (VERDICT r3 #2): probe the axon TPU tunnel on an
# interval, and in the FIRST healthy window run the full on-chip gate suite,
# harvest the rows into BASELINE.md, and commit — so the freshest on-chip
# record is always at most one healthy window old and a wedge can never
# cost a round its driver-visible numbers again.
#
#   nohup bash tools/tpu_watchdog.sh >> /tmp/tpu_watchdog.out 2>&1 &
#
# Safety rules it encodes (learned the hard way, 2026-07-30/31):
#  - ONE TPU process at a time: the whole probe->gates cycle holds
#    /tmp/tpu.lock via flock; coordinate manual chip use through the same
#    lock (`flock /tmp/tpu.lock python bench.py`).
#  - NEVER timeout-kill a running TPU computation (that wedged the tunnel
#    on 2026-07-31 ~04:55 UTC).  Probing uses bench.backend_responsive,
#    which only ever kills its own throwaway child stuck in *backend
#    init* — a process that never reached the chip; gates run with no
#    timeout at all.
#  - Wedged probes are cheap and aggregated; gate runs are expensive and
#    logged + committed even when the tunnel dies mid-suite (every
#    completed config keeps its row).
#
# Env knobs: PROBE_INTERVAL (s between probes while wedged, default 480),
# SUCCESS_COOLDOWN (s before re-running gates after a full pass, default
# 14400), FAIL_COOLDOWN (s before retrying after a cycle that RAN but
# failed, default 3600 — a deterministically red gate on a healthy tunnel
# must not re-run the whole suite and commit every probe interval),
# LOGDIR (gate logs, default /tmp/tpu_gates), WATCHDOG_ONESHOT=1 (exit
# after the first completed gate cycle instead of re-arming),
# WATCHDOG_LOG_MAX_KB / WATCHDOG_LOG_KEEP (cycle-log rotation cap and
# generations, default 256 KB x 3 — tools/rotate_log.sh).

set -u
cd "$(dirname "$0")/.."
REPO=$(pwd)
PROBE_INTERVAL=${PROBE_INTERVAL:-480}
SUCCESS_COOLDOWN=${SUCCESS_COOLDOWN:-14400}
FAIL_COOLDOWN=${FAIL_COOLDOWN:-3600}
LOGDIR=${LOGDIR:-/tmp/tpu_gates}
LOCK=/tmp/tpu.lock
CYCLE_LOG=tools/WATCHDOG_LOG.md

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
note() { echo "[$(stamp)] $*"; }

probe() {
    # rc 0 = responsive.  backend_responsive spawns a throwaway child and
    # gives it 150 s to init the backend + run an 8x8 sum; a hang means
    # the tunnel is wedged (the child never reached the chip, killing it
    # is safe — distinct from killing live compute, which is forbidden).
    flock "$LOCK" python -c "
import sys
sys.path.insert(0, '$REPO')
from bench import backend_responsive
ok, reason = backend_responsive(attempts=1)
print(reason if reason else 'responsive')
sys.exit(0 if ok else 1)" 2>/dev/null
}

run_cycle() {
    # tunnel is healthy: run gates (NO timeout — each step gets all the
    # time it needs), harvest, stamp BASELINE.md, commit.
    local started rc
    started=$(stamp)
    note "tunnel healthy — running gate suite (logs: $LOGDIR)"
    if LOGDIR="$LOGDIR" flock "$LOCK" bash tools/run_tpu_gates.sh; then
        rc=0
    else
        rc=$?
    fi
    note "gate suite finished rc=$rc — harvesting"
    local harvest_rc=0
    python tools/harvest_gates.py --write "$LOGDIR" || harvest_rc=$?

    # size-capped keep-N rotation (mirrors the MESH_TPU_OBS_JSONL sink's
    # semantics) so an unattended loop can't grow the cycle log forever
    bash tools/rotate_log.sh "$CYCLE_LOG"

    {
        echo ""
        echo "## Watchdog cycle $started"
        echo ""
        echo "- probes while wedged since last cycle: $WEDGED_PROBES"
        echo "- gates started: $started, finished: $(stamp), rc=$rc"
        echo "- logs: $LOGDIR (gate1/gate2/config1..6/sweep/sweep_mxu)"
        echo "- harvest --write rc=$harvest_rc$([ $harvest_rc = 0 ] \
            && echo ' (BASELINE.md auto-harvest section restamped)' \
            || echo ' (BASELINE.md NOT restamped)')"
    } >> "$CYCLE_LOG"

    # commit ONLY the watchdog's own artifacts: add them (add handles a
    # not-yet-tracked cycle log), then commit by pathspec so whatever a
    # developer may have staged while this nohup'd loop was mid-cycle is
    # never swept into the automated commit
    git add -- BASELINE.md bench_last_good.json "$CYCLE_LOG"
    if ! git diff --cached --quiet -- BASELINE.md bench_last_good.json "$CYCLE_LOG"; then
        git commit -q \
            -m "onchip: automated watchdog gate cycle ($([ $rc = 0 ] && echo 'all gates passed' || echo "partial, rc=$rc"))" \
            -- BASELINE.md bench_last_good.json "$CYCLE_LOG" \
            && note "committed harvest" || note "commit failed"
    else
        note "nothing new to commit"
    fi
    return $rc
}

WEDGED_PROBES=0
note "watchdog armed (probe every ${PROBE_INTERVAL}s, cooldown ${SUCCESS_COOLDOWN}s after a pass)"
while :; do
    if out=$(probe); then
        note "probe ok after $WEDGED_PROBES wedged probes"
        if run_cycle; then
            WEDGED_PROBES=0
            [ "${WATCHDOG_ONESHOT:-0}" = 1 ] && { note "oneshot done"; exit 0; }
            note "full pass — cooling down ${SUCCESS_COOLDOWN}s"
            sleep "$SUCCESS_COOLDOWN"
        else
            WEDGED_PROBES=0
            # the cycle RAN and failed: could be a mid-suite re-wedge (next
            # probe will say) or a deterministically red gate on a healthy
            # tunnel — cool down long enough that the latter can't spin the
            # suite + a commit every probe interval
            note "partial cycle — cooling down ${FAIL_COOLDOWN}s before re-probing"
            sleep "$FAIL_COOLDOWN"
        fi
    else
        WEDGED_PROBES=$((WEDGED_PROBES + 1))
        # aggregate: one log line every 5 wedged probes
        if [ $((WEDGED_PROBES % 5)) = 1 ]; then
            note "tunnel wedged (probe $WEDGED_PROBES: ${out:-hang})"
        fi
        sleep "$PROBE_INTERVAL"
    fi
done
