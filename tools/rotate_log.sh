#!/bin/bash
# Size-capped keep-N rotation for append-forever logs.
#
#   bash tools/rotate_log.sh <path> [max_kb] [keep]
#
# Mirrors the MESH_TPU_OBS_JSONL rotation semantics (jsonl_sink in
# mesh_tpu/obs/trace.py): shift path.i -> path.(i+1) for i = keep-1..1,
# then move the live file to path.1, oldest generation dropped.  A file
# at or under the cap is left untouched, so calling this before every
# append is cheap and idempotent.
#
# Defaults come from WATCHDOG_LOG_MAX_KB (256) and WATCHDOG_LOG_KEEP (3)
# because the first caller is tools/tpu_watchdog.sh, whose cycle log
# otherwise grows forever; the path/size/keep arguments keep it generic.
# A rotated markdown log is reseeded with a short header so the live
# file stays self-describing.

set -u
path=${1:?usage: rotate_log.sh <path> [max_kb] [keep]}
max_kb=${2:-${WATCHDOG_LOG_MAX_KB:-256}}
keep=${3:-${WATCHDOG_LOG_KEEP:-3}}

[ -f "$path" ] || exit 0
size_kb=$(( ($(wc -c < "$path") + 1023) / 1024 ))
[ "$size_kb" -le "$max_kb" ] && exit 0

i=$((keep - 1))
while [ "$i" -ge 1 ]; do
    [ -f "$path.$i" ] && mv -f "$path.$i" "$path.$((i + 1))"
    i=$((i - 1))
done
mv -f "$path" "$path.1"

case "$path" in
    *.md)
        {
            echo "# $(basename "$path") (rotated $(date -u +%Y-%m-%dT%H:%M:%SZ))"
            echo ""
            echo "Older entries live in $(basename "$path").1 .. .$keep"
            echo "(size-capped at ${max_kb} KB per generation by"
            echo "tools/rotate_log.sh; oldest generation dropped)."
        } > "$path"
        ;;
    *)
        : > "$path"
        ;;
esac
