"""Summarize on-chip gate logs into BASELINE-ready rows.

    python tools/harvest_gates.py [logdir]     # default /tmp/tpu_gates

Reads gate1.log / gate2.log / config*.log as written by
tools/run_tpu_gates.sh (or /tmp's probe-and-gates variant), extracts the
one-line JSON records, and prints a compact table plus the raw
device_absolute blocks — the inputs for BASELINE.md's measurement
columns after a tunnel-recovery run.
"""

import glob
import json
import os
import sys


def _json_lines(path):
    out = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line.startswith("{"):
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return out


def main():
    logdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_gates"
    if not os.path.isdir(logdir):
        print("no log dir at %s" % logdir)
        return 1

    g1 = os.path.join(logdir, "gate1.log")
    if os.path.exists(g1):
        tail = open(g1).read().strip().splitlines()
        print("gate1 (compiled kernels): %s" % (tail[-2:] or "?"))

    rows = _json_lines(os.path.join(logdir, "gate2.log"))
    for rec in rows:
        if rec.get("value") is not None:
            print("bench: %(value)s %(unit)s  vs_baseline=%(vs_baseline)s"
                  % rec)

    for path in sorted(glob.glob(os.path.join(logdir, "config*.log"))):
        for rec in _json_lines(path):
            if "suite" in rec or rec.get("metric") is None:
                continue
            extras = {
                k: v for k, v in rec.items()
                if k not in ("metric", "value", "unit", "vs_baseline")
                and not k.startswith("device_absolute")
            }
            print("%-40s %12s %-12s vs=%s" % (
                rec["metric"], rec.get("value"), rec.get("unit", ""),
                rec.get("vs_baseline")))
            if extras:
                print("    %s" % json.dumps(extras))
            for key in ("device_absolute", "device_absolute_brute"):
                if key in rec:
                    print("    %s: %s" % (key, json.dumps(rec[key])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
