"""Summarize on-chip gate logs into BASELINE-ready rows.

    python tools/harvest_gates.py [logdir]            # print table
    python tools/harvest_gates.py --write [logdir]    # + stamp BASELINE.md

Reads gate1.log / gate2.log / config*.log / sweep*.log as written by
tools/run_tpu_gates.sh, extracts the one-line JSON records, and prints a
compact table plus the raw device_absolute blocks — the inputs for
BASELINE.md's measurement columns after a tunnel-recovery run.

``--write`` additionally replaces the delimited auto-harvest section of
BASELINE.md with the fresh rows (markers below), so the watchdog
(tools/tpu_watchdog.sh) can stamp the repo's headline doc and commit it
without a human in the loop.  The hand-written analysis rows above the
section stay untouched.
"""

import glob
import json
import os
import sys
import time

_BEGIN = "<!-- BEGIN AUTO-HARVESTED ONCHIP (tools/harvest_gates.py) -->"
_END = "<!-- END AUTO-HARVESTED ONCHIP -->"
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _json_lines(path):
    out = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line.startswith("{"):
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return out


def _mtime_utc(path):
    try:
        return time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(path))
        )
    except OSError:
        return "?"


def harvest(logdir):
    """Collect every gate's result into a structured dict."""
    out = {"logdir": logdir, "lint": None, "gate1": None, "bench": None,
           "configs": [], "sweeps": []}

    g0 = os.path.join(logdir, "gate0.log")
    if os.path.exists(g0):
        try:
            with open(g0) as fh:
                rec = json.load(fh)   # `mesh-tpu lint --json` is one doc
        except (OSError, ValueError):
            rec = None
        out["lint"] = {"rec": rec, "mtime_utc": _mtime_utc(g0)}

    g1 = os.path.join(logdir, "gate1.log")
    if os.path.exists(g1):
        lines = open(g1).read().strip().splitlines()
        summary = next(
            (ln for ln in reversed(lines)
             if "passed" in ln or "failed" in ln or "error" in ln), "?")
        out["gate1"] = {"summary": summary.strip(), "mtime_utc": _mtime_utc(g1)}

    g2 = os.path.join(logdir, "gate2.log")
    for rec in _json_lines(g2):
        if rec.get("metric"):
            out["bench"] = dict(rec, mtime_utc=_mtime_utc(g2))

    out["bench_variants"] = []
    for path in sorted(glob.glob(os.path.join(logdir, "gate2b*.log"))):
        for rec in _json_lines(path):
            if rec.get("metric"):
                out["bench_variants"].append(
                    dict(rec, mtime_utc=_mtime_utc(path)))

    for path in sorted(glob.glob(os.path.join(logdir, "config*.log"))):
        for rec in _json_lines(path):
            if "suite" in rec or rec.get("metric") is None:
                continue
            out["configs"].append(dict(rec, mtime_utc=_mtime_utc(path)))

    for path in sorted(glob.glob(os.path.join(logdir, "sweep*.log"))):
        rows = _json_lines(path)
        summary = next((r for r in rows if "best" in r), None)
        if summary is not None:
            name = os.path.splitext(os.path.basename(path))[0]
            out["sweeps"].append(
                dict(summary, sweep=name, mtime_utc=_mtime_utc(path)))
    return out


def _lint_family_suffix(rec):
    """Per-family breakdown for the whole-program rule packs (LOK =
    lock order, PAL = Pallas DMA) and the flow-sensitive layer (RES =
    resource pairing, LED = ledger lifecycle, FLW = tracer/host-sync
    upgrades) — the families whose findings mean a deadlock, a chip
    hang, or a leaked record rather than hygiene, so the gate row
    names them explicitly."""
    parts = []
    for fam in ("LOK", "PAL", "RES", "LED", "FLW"):
        new = sum(1 for f in (rec.get("findings") or [])
                  if str(f.get("rule", "")).startswith(fam))
        kept = sum(1 for f in (rec.get("suppressed") or [])
                   if str(f.get("rule", "")).startswith(fam))
        parts.append("%s %d new/%d baselined" % (fam, new, kept))
    return "; " + ", ".join(parts)


def render_table(h):
    """The human-readable summary (also what lands in BASELINE.md)."""
    lines = []
    if h.get("lint"):
        rec = h["lint"]["rec"]
        counts = (rec or {}).get("counts", {})
        if rec is None:
            # hard gate: an unreadable lint record reads as a failure,
            # never as a silent pass
            lines.append(
                "gate 0 (meshlint, %s): NOT AN IMPROVEMENT — lint "
                "record unreadable (rerun `mesh-tpu lint --json`)"
                % h["lint"]["mtime_utc"])
        elif rec.get("rc") or counts.get("new"):
            lines.append(
                "gate 0 (meshlint, %s): NOT AN IMPROVEMENT — %s new "
                "static-analysis finding(s)%s; fix or baseline them "
                "(tools/meshlint_baseline.json) before quoting numbers"
                % (h["lint"]["mtime_utc"], counts.get("new", "?"),
                   _lint_family_suffix(rec)))
        else:
            lines.append(
                "gate 0 (meshlint, %s): OK — 0 new findings over %s "
                "file(s) (%s baselined, %s stale%s)" % (
                    h["lint"]["mtime_utc"],
                    rec.get("files_scanned", "?"),
                    counts.get("suppressed", 0),
                    counts.get("stale_baseline", 0),
                    _lint_family_suffix(rec)))
    if h["gate1"]:
        lines.append("gate 1 (compiled kernels, %s): %s" % (
            h["gate1"]["mtime_utc"], h["gate1"]["summary"]))
    if h["bench"]:
        b = h["bench"]
        if b.get("value") is None:
            # a failed capture must read as a failure, not a null row
            lines.append("gate 2 (bench.py, %s): CAPTURE FAILED — %s" % (
                b["mtime_utc"], b.get("error", "no value, no error recorded")))
        elif b.get("stale"):
            # a stale record is a republished last-good value, not a fresh
            # measurement: render it as NOT an improvement so a wedged-run
            # harvest can never stamp BASELINE.md with a fake new row
            age = b.get("stale_age_hours")
            lines.append(
                "gate 2 (bench.py, %s): STALE last-good record — tunnel "
                "was wedged; %s %s republished%s, vs_baseline=null — NOT "
                "an improvement, not comparable with fresh rows" % (
                    b["mtime_utc"], b.get("value"), b.get("unit", ""),
                    " (age %sh)" % age if age is not None else ""))
        else:
            lines.append(
                "gate 2 (bench.py, %s): %s %s  vs_baseline=%s" % (
                    b["mtime_utc"], b.get("value"), b.get("unit", ""),
                    b.get("vs_baseline")))
        # accel sub-linearity gate: the spatial index only counts as an
        # improvement when its exact pair tests per query stay strictly
        # below brute-force F at the largest bench mesh
        acc = b.get("accel")
        if isinstance(acc, dict):
            ppq = acc.get("pair_tests_per_query")
            faces = acc.get("faces")
            if ppq is None or faces is None:
                lines.append(
                    "gate 2 accel: NOT AN IMPROVEMENT — accel record "
                    "carries no pair_tests_per_query/faces to prove "
                    "sub-linearity")
            elif ppq < faces:
                lines.append(
                    "gate 2 accel: sub-linear OK — %.1f pair tests/query "
                    "vs brute F=%d (skip ratio %s)" % (
                        ppq, faces, acc.get("value")))
            else:
                lines.append(
                    "gate 2 accel: NOT AN IMPROVEMENT — %.1f pair "
                    "tests/query >= brute F=%d (index does not prune)" % (
                        ppq, faces))
        # MXU matmul-form gate: the reformulation only counts as an
        # improvement when the repair pipeline returned the dense
        # kernel's exact answers (checksum/match flags) AND the bf16
        # screen still prunes — a drifted checksum or a repair rate at
        # 1.0 is a correctness/regression signal, never a perf win
        mx = b.get("mxu")
        if isinstance(mx, dict):
            matches = [mx.get(k) for k in (
                "dense_match", "degenerate_match", "leaf_visit_match")]
            rate = mx.get("repair_rate")
            if mx.get("value") is None or mx.get("checksum") is None:
                lines.append(
                    "gate 2 mxu: NOT AN IMPROVEMENT — mxu record carries "
                    "no speedup/checksum to prove the repair contract")
            elif not all(m is True for m in matches):
                lines.append(
                    "gate 2 mxu: NOT AN IMPROVEMENT — bit-identity flags "
                    "%s (repair must equal the dense kernel exactly)"
                    % json.dumps(dict(zip(
                        ("dense", "degenerate", "leaf_visit"), matches))))
            elif rate is None or rate >= 1.0:
                lines.append(
                    "gate 2 mxu: NOT AN IMPROVEMENT — repair rate %s "
                    "(bf16 screen prunes nothing; perfcheck grades drift "
                    "against benchmarks/mxu_golden.json)" % (rate,))
            else:
                lines.append(
                    "gate 2 mxu: %.3fx vpu/repair OK — checksum %.6f, "
                    "repair rate %.4f (%d/%d tiles)" % (
                        mx["value"], mx["checksum"], rate,
                        mx.get("repaired", -1), mx.get("screened", -1)))
        # record/replay gate: replay only counts as an improvement when
        # the double-run admission-sequence checksum is present — a
        # missing checksum means determinism is unproven, and perfcheck
        # fails hard on drift against benchmarks/replay_golden.json
        rp = b.get("replay")
        if isinstance(rp, dict):
            if rp.get("value") is None or rp.get("checksum") is None:
                lines.append(
                    "gate 2 replay: NOT AN IMPROVEMENT — replay record "
                    "carries no admissions/checksum to prove the "
                    "same-trace-same-sequence contract")
            elif rp.get("double_run") != "checksum_equal":
                lines.append(
                    "gate 2 replay: NOT AN IMPROVEMENT — double-run "
                    "verdict %r (the same trace must replay to an "
                    "identical admission sequence)" % (
                        rp.get("double_run"),))
            else:
                lines.append(
                    "gate 2 replay: %d admissions OK — checksum %.6f "
                    "double-run equal (perfcheck grades drift against "
                    "benchmarks/replay_golden.json)" % (
                        rp["value"], rp["checksum"]))
        # dynamic-mesh gate: refit only counts as an improvement when it
        # actually beats rebuilding (>= 1.0x) AND the record carries the
        # traversal checksum proving exactness — perfcheck grades drift
        # against benchmarks/anim_golden.json
        an = b.get("anim")
        if isinstance(an, dict):
            if an.get("value") is None or an.get("checksum") is None:
                lines.append(
                    "gate 2 anim: NOT AN IMPROVEMENT — anim record "
                    "carries no speedup/checksum to prove the refit "
                    "exactness contract")
            elif an["value"] < 1.0:
                lines.append(
                    "gate 2 anim: NOT AN IMPROVEMENT — refit speedup "
                    "%.3fx < 1.0x (frozen-order refit loses to a full "
                    "rebuild)" % an["value"])
            else:
                lines.append(
                    "gate 2 anim: %.3fx rebuild/refit OK — checksum "
                    "%.6f over %s frames (max inflation %s; perfcheck "
                    "grades drift against benchmarks/anim_golden.json)"
                    % (an["value"], an["checksum"], an.get("frames"),
                       an.get("inflation_max")))
        # request-identity gate: the trace join only counts as an
        # improvement when the double-run join checksum is present and
        # every forced deadline-miss/error kept its span tree —
        # perfcheck fails hard on drift against
        # benchmarks/trace_golden.json
        tr = b.get("trace")
        if isinstance(tr, dict):
            if tr.get("value") is None or tr.get("checksum") is None:
                lines.append(
                    "gate 2 trace: NOT AN IMPROVEMENT — trace record "
                    "carries no joined-request count/checksum to prove "
                    "the request-identity join contract")
            elif tr.get("double_run") != "checksum_equal":
                lines.append(
                    "gate 2 trace: NOT AN IMPROVEMENT — double-run "
                    "verdict %r (the same mix must join to identical "
                    "ledger/span/router evidence)" % (
                        tr.get("double_run"),))
            else:
                lines.append(
                    "gate 2 trace: %d requests joined OK — checksum "
                    "%.6f, %s miss/error span trees retained, "
                    "double-run equal (perfcheck grades drift against "
                    "benchmarks/trace_golden.json)" % (
                        tr["value"], tr["checksum"],
                        tr.get("tail_retained")))
    for b in h.get("bench_variants", ()):
        if b.get("value") is None:
            lines.append(
                "gate 2b (bench.py A/B, %s): CAPTURE FAILED — %s" % (
                    b["mtime_utc"],
                    b.get("error", "no value, no error recorded")))
        elif "kernel_knobs" not in b:
            if "kernel_knobs_requested" in b or b.get("stale"):
                # a wedged A/B attempt carries the DEFAULT-kernel stale
                # headline plus kernel_knobs_requested — never render
                # that value as a variant measurement
                lines.append(
                    "gate 2b (bench.py A/B requested=%s, %s): NOT "
                    "MEASURED — tunnel wedged; stale value shown is the "
                    "DEFAULT-kernel headline, not an A/B result" % (
                        json.dumps(b.get("kernel_knobs_requested", {})),
                        b["mtime_utc"]))
            else:
                # live run, but the record never echoed its knobs: the
                # CPU-fallback path ignores kernel knobs entirely, so
                # this is a healthy DEFAULT-path measurement that must
                # not be read as a variant A/B either
                lines.append(
                    "gate 2b (bench.py A/B, %s): NOT AN A/B — kernel "
                    "knobs ignored on the CPU fallback path; %s %s is a "
                    "default-path measurement" % (
                        b["mtime_utc"], b.get("value"),
                        b.get("unit", "")))
        else:
            lines.append(
                "gate 2b (bench.py A/B %s, %s): %s %s  vs_baseline=%s" % (
                    json.dumps(b["kernel_knobs"]), b["mtime_utc"],
                    b.get("value"), b.get("unit", ""),
                    b.get("vs_baseline")))
    if h["configs"]:
        lines.append("")
        lines.append("| config metric | value | unit | vs CPU | measured (log mtime, UTC) |")
        lines.append("|---|---|---|---|---|")
        for rec in h["configs"]:
            if rec.get("value") is None:
                lines.append("| %s | FAILED: %s | | | %s |" % (
                    rec["metric"], rec.get("error", "no value recorded"),
                    rec["mtime_utc"]))
            else:
                lines.append("| %s | %s | %s | %s | %s |" % (
                    rec["metric"], rec.get("value"), rec.get("unit", ""),
                    rec.get("vs_baseline"), rec["mtime_utc"]))
        for rec in h["configs"]:
            extras = {
                k: v for k, v in rec.items()
                if k not in ("metric", "value", "unit", "vs_baseline",
                             "mtime_utc")
                and not k.startswith("device_absolute")
            }
            keyed = [("extras", extras)] if extras else []
            keyed += [(k, rec[k]) for k in
                      ("device_absolute", "device_absolute_brute") if k in rec]
            if keyed:
                lines.append("")
                lines.append("`%s`:" % rec["metric"])
                for k, vval in keyed:
                    lines.append("- %s: `%s`" % (k, json.dumps(vval)))
    for sw in h["sweeps"]:
        lines.append("")
        lines.append("tile %s (%s): best=`%s` n_errors=%s" % (
            sw["sweep"], sw["mtime_utc"], json.dumps(sw.get("best")),
            sw.get("n_errors")))
        extras = {
            k: v for k, v in sw.items()
            if k not in ("sweep", "mtime_utc", "best", "n_errors")
        }
        if extras:
            # the variant rows (degenerate_tail / sliver_safe /
            # fused_reduction / moller splits) ride in the summary line
            lines.append("- variants: `%s`" % json.dumps(extras))
    return "\n".join(lines)


def write_baseline(h, baseline_path=None):
    """Replace (or append) the delimited auto-harvest section in BASELINE.md."""
    baseline_path = baseline_path or os.path.join(_REPO, "BASELINE.md")
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    section = "\n".join([
        _BEGIN,
        "",
        "## Latest on-chip gate run (auto-harvested)",
        "",
        "Stamped %s by `tools/harvest_gates.py --write` from `%s`" % (
            stamp, h["logdir"]),
        "(the watchdog loop in `tools/tpu_watchdog.sh` runs gates and",
        "re-stamps this section in the first healthy tunnel window; rows",
        "above are hand-written analysis of the same measurements).",
        "",
        render_table(h),
        "",
        _END,
    ])
    try:
        text = open(baseline_path).read()
    except OSError:
        text = ""
    if _BEGIN in text and _END in text:
        head, rest = text.split(_BEGIN, 1)
        _, tail = rest.split(_END, 1)
        text = head + section + tail
    else:
        text = text.rstrip("\n") + "\n\n" + section + "\n"
    with open(baseline_path, "w") as fh:
        fh.write(text)
    return baseline_path


def main():
    argv = [a for a in sys.argv[1:]]
    write = "--write" in argv
    argv = [a for a in argv if a != "--write"]
    logdir = argv[0] if argv else "/tmp/tpu_gates"
    if not os.path.isdir(logdir):
        print("no log dir at %s" % logdir)
        return 1

    h = harvest(logdir)
    print(render_table(h))
    if not (h["gate1"] or h["bench"] or h["configs"] or h["sweeps"]
            or h["bench_variants"] or h["lint"]):
        print("nothing harvested from %s" % logdir)
        return 1
    if write:
        path = write_baseline(h)
        print("\nstamped %s" % path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
