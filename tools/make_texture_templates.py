"""Generate the packaged texture templates under mesh_tpu/ressources/textures.

The reference ships SCAPE-derived `textured_template_{low,high}_v*.obj`
bodies it cannot redistribute here (texture.py:39-55 loads them by version
number).  This repo ships procedurally generated equivalents instead: unit
icospheres with per-wedge spherical uv (seam-safe because every face corner
gets its own vt row) plus a deterministic checker/gradient texture, enough
for `Mesh.load_texture(0)` to work on any icosphere-topology mesh and for
texture-pipeline tests.

Run from the repo root:  python tools/make_texture_templates.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mesh_tpu import Mesh, texture_path  # noqa: E402
from mesh_tpu.sphere import _icosphere  # noqa: E402


def spherical_uv_per_wedge(v, f):
    """(vt, ft): one uv row per face corner from lat/lon of the direction."""
    corners = v[f.reshape(-1)]
    d = corners / np.linalg.norm(corners, axis=1, keepdims=True)
    u = 0.5 + np.arctan2(d[:, 1], d[:, 0]) / (2 * np.pi)
    w = 0.5 + np.arcsin(np.clip(d[:, 2], -1, 1)) / np.pi
    # unwrap the +-pi seam inside each face: shift corners that are more
    # than half the texture away from the face's first corner
    u = u.reshape(-1, 3)
    anchor = u[:, :1]
    u = u + np.round(anchor - u)
    vt = np.column_stack([u.reshape(-1), w])
    ft = np.arange(len(vt), dtype=np.uint32).reshape(-1, 3)
    return vt, ft


def make_texture(path, size=256, version=0):
    """Deterministic checker + gradient, BGR, written with cv2; each
    version gets a visually distinct pattern so load_texture(version)
    choices are distinguishable in renders."""
    import cv2

    yy, xx = np.mgrid[0:size, 0:size]
    cell = 16 * (version + 1)
    checker = (((xx // cell) + (yy // cell)) % 2).astype(np.float64)
    img = np.stack([
        64 + 128 * checker,                 # blue channel
        yy * 255.0 / size,                  # green gradient
        xx * 255.0 / size,                  # red gradient
    ], axis=2).astype(np.uint8)
    if version % 2 == 1:
        img = img[:, :, ::-1].copy()        # swap gradients for odd versions
    cv2.imwrite(path, img)


def make_template(version, subdiv, name, texture_file):
    v, f = _icosphere(subdiv)
    v = v + 0.0          # normalize -0.0 so regeneration is byte-stable
    m = Mesh(v=v, f=f.astype(np.uint32))
    m.vt, m.ft = spherical_uv_per_wedge(m.v, m.f.astype(np.int64))
    m.texture_filepath = texture_file
    out = os.path.join(texture_path, "%s_v%d.obj" % (name, version))
    m.write_obj(out)      # also writes the .mtl and copies the texture
    print("wrote", out)


def main():
    import tempfile

    os.makedirs(texture_path, exist_ok=True)
    for version in (0, 1):
        # write_obj copies the texture next to each template, so the source
        # image only needs a temporary home
        with tempfile.TemporaryDirectory() as tmp:
            src = os.path.join(tmp, "texture.png")
            make_texture(src, version=version)
            make_template(version, 1, "textured_template_low", src)
            make_template(version, 3, "textured_template_high", src)


if __name__ == "__main__":
    main()
