#!/bin/bash
# On-chip revalidation gates, run STRICTLY one process at a time (overlapping
# TPU processes are what wedged the axon tunnel on 2026-07-30; killing a TPU
# process mid-call appears to wedge it too — give each step all the time it
# needs rather than wrapping it in `timeout`).  Run this as soon as
# `python -c "from bench import backend_responsive; ..."` reports the tunnel
# responsive:
#
#   bash tools/run_tpu_gates.sh
#
# Order matters: the compiled-kernel tests validate every Pallas kernel
# BEFORE the benchmarks quote numbers from them.  Each step gets its own
# process.  Benchmark configs run one process each so a mid-suite tunnel
# failure keeps every completed config's row (logs under /tmp/tpu_gates/);
# the persistent compilation cache (mesh_tpu/utils/compilation_cache.py)
# makes the per-process restarts cheap after the first pass.
set -e
cd "$(dirname "$0")/.."
LOGDIR=${LOGDIR:-/tmp/tpu_gates}
mkdir -p "$LOGDIR"

echo "=== gate 1: compiled-kernel tests on the real chip ==="
MESH_TPU_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -m tpu -q

echo "=== gate 2: north-star bench ==="
python bench.py

echo "=== gate 3: benchmark configs, one process each ==="
fail=0
for n in 1 2 3 4 5 6; do
    echo "--- config $n (log: $LOGDIR/config$n.log) ---"
    if python -u benchmarks/run_all.py --configs "$n" 2>&1 | tee "$LOGDIR/config$n.log"; then
        :
    else
        echo "config $n FAILED (rc=$?) — continuing; fix and rerun just it:"
        echo "    python benchmarks/run_all.py --configs $n"
        fail=1
    fi
done
[ "$fail" = 0 ] || exit 1

echo "=== all gates passed; update BASELINE.md with the new rows ==="
