#!/bin/bash
# On-chip revalidation gates, run STRICTLY one process at a time (overlapping
# TPU processes are what wedged the axon tunnel on 2026-07-30; killing a TPU
# process mid-call appears to wedge it too — give each step all the time it
# needs rather than wrapping it in `timeout`).  Run this as soon as
# `python -c "from bench import backend_responsive; ..."` reports the tunnel
# responsive (tools/tpu_watchdog.sh does exactly that, automatically):
#
#   bash tools/run_tpu_gates.sh
#
# Order matters: the compiled-kernel tests validate every Pallas kernel
# BEFORE the benchmarks quote numbers from them.  Each step gets its own
# process.  Benchmark configs run one process each so a mid-suite tunnel
# failure keeps every completed config's row; every gate logs under
# $LOGDIR (default /tmp/tpu_gates) in the layout tools/harvest_gates.py
# reads.  The persistent compilation cache
# (mesh_tpu/utils/compilation_cache.py) makes the per-process restarts
# cheap after the first pass.
set -e
set -o pipefail
cd "$(dirname "$0")/.."
LOGDIR=${LOGDIR:-/tmp/tpu_gates}
mkdir -p "$LOGDIR"
# clear prior-cycle logs so a run that stops early can't pass yesterday's
# rows off as this cycle's harvest; gate 5's profiler traces live under
# $LOGDIR/trace and accumulate the same way (advisor round-4)
rm -f "$LOGDIR"/*.log
rm -rf "$LOGDIR/trace"
fail=0

echo "=== gate 0: meshlint static analysis (chip-free) ==="
# the analyzer is stdlib-only and must never touch the chip: force the
# CPU backend exactly like the other chip-free tools
if PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m mesh_tpu.cli lint \
        --json > "$LOGDIR/gate0.log" 2>"$LOGDIR/gate0.err"; then
    echo "gate 0 OK ($(python -c 'import json,sys; d=json.load(open(sys.argv[1])); print("%d files, %d baselined" % (d["files_scanned"], d["counts"]["suppressed"]))' "$LOGDIR/gate0.log"))"
else
    cat "$LOGDIR/gate0.err" >&2 || true
    python - "$LOGDIR/gate0.log" <<'PYEOF' || true
import json, sys
try:
    doc = json.load(open(sys.argv[1]))
except Exception:
    sys.exit(0)
for f in doc.get("findings", []):
    print("  %s:%s: %s %s %s" % (f["path"], f["line"], f["severity"],
                                 f["rule"], f["message"]))
PYEOF
    echo "gate 0 FAILED — stopping: new static-analysis findings must be"
    echo "fixed (or baselined with a reason in tools/meshlint_baseline.json)"
    echo "before any chip time is spent."
    exit 1
fi

echo "=== gate 1: compiled-kernel tests on the real chip ==="
if MESH_TPU_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -m tpu -q \
        2>&1 | tee "$LOGDIR/gate1.log"; then
    :
else
    echo "gate 1 FAILED — stopping: benchmarks must not quote numbers from"
    echo "kernels whose compiled validation is red."
    exit 1
fi

echo "=== gate 2: north-star bench ==="
if python bench.py 2>&1 | tee "$LOGDIR/gate2.log"; then
    # bench.py exits 0 with a stale last-good record when the tunnel
    # wedges between the outer probe and its own — an honest driver
    # artifact, but NOT a fresh measurement, so the gate cycle must not
    # claim a full pass (the watchdog would cool down on yesterday's
    # number otherwise)
    if grep -q '"stale": true' "$LOGDIR/gate2.log"; then
        echo "gate 2 returned a STALE record (tunnel wedged mid-cycle) — not a fresh pass"
        fail=1
    fi
else
    echo "gate 2 FAILED (rc=$?) — continuing to per-config runs"
    fail=1
fi

echo "=== gate 2b: north-star bench with the fused-reduction knob (A/B) ==="
# experimental round-5 variant on the FULL workload (the sweep times it at
# the sweep shape only); never overwrites the headline last-good record
# (bench.py guards on non-default knobs) and never fails the cycle
if MESH_TPU_BENCH_REDUCTION=fused python bench.py 2>&1 \
        | tee "$LOGDIR/gate2b_fused.log"; then
    :
else
    echo "gate 2b (fused knob) FAILED (rc=$?) — non-fatal, continuing"
fi

echo "=== gate 3: benchmark configs, one process each ==="
for n in 1 2 3 4 5 6; do
    echo "--- config $n (log: $LOGDIR/config$n.log) ---"
    if python -u benchmarks/run_all.py --configs "$n" 2>&1 \
            | tee "$LOGDIR/config$n.log"; then
        :
    else
        echo "config $n FAILED (rc=$?) — continuing; fix and rerun just it:"
        echo "    python benchmarks/run_all.py --configs $n"
        fail=1
    fi
done

echo "=== gate 4: tile sweeps (VPU grid, MXU hypothesis, tri-tri tiles) ==="
for sweep in "" "--mxu" "--tri-tri"; do
    case "$sweep" in
        --mxu) name=sweep_mxu ;;
        --tri-tri) name=sweep_tritri ;;
        *) name=sweep ;;
    esac
    echo "--- tile_sweep $sweep (log: $LOGDIR/$name.log) ---"
    if python -u benchmarks/tile_sweep.py $sweep 2>&1 \
            | tee "$LOGDIR/$name.log"; then
        :
    else
        echo "tile_sweep $sweep FAILED (rc=$?) — continuing"
        fail=1
    fi
done

echo "=== gate 5: kernel trace for the north-star config (limiter analysis) ==="
# a jax.profiler trace of config 3 (view with tensorboard/xprof); failure
# here is non-fatal — the trace is analysis material, not a measurement
if python -u benchmarks/run_all.py --configs 3 --trace "$LOGDIR/trace" 2>&1 \
        | tee "$LOGDIR/gate5_trace.log"; then
    echo "trace written under $LOGDIR/trace"
else
    echo "gate 5 trace capture failed (rc=$?) — continuing (non-fatal)"
fi

if [ "$fail" != 0 ]; then
    echo "=== gates FINISHED WITH FAILURES (see above; logs in $LOGDIR) ==="
    exit 1
fi
echo "=== all gates passed; harvest rows: python tools/harvest_gates.py ==="
