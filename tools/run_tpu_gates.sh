#!/bin/bash
# On-chip revalidation gates, run STRICTLY one at a time (overlapping TPU
# processes are what wedged the axon tunnel on 2026-07-30).  Run this as
# soon as `python -c "from bench import backend_responsive; ..."` reports
# the tunnel responsive:
#
#   bash tools/run_tpu_gates.sh
#
# Order matters: the compiled-kernel tests validate every Pallas kernel
# added since the last good window BEFORE the benchmarks quote numbers
# from them.  Each step gets its own process; a failure stops the chain
# (fix, then rerun from the top — the suite is cheap compared to a wedge).
set -e
cd "$(dirname "$0")/.."

echo "=== gate 1/3: compiled-kernel tests on the real chip ==="
MESH_TPU_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -m tpu -q

echo "=== gate 2/3: north-star bench ==="
python bench.py

echo "=== gate 3/3: full benchmark suite (writes BASELINE rows) ==="
# retry a single fixed config with `--configs N`; add `--trace DIR` for a
# per-config jax.profiler capture
python benchmarks/run_all.py

echo "=== all gates passed; update BASELINE.md with the new rows ==="
