"""`psbody` namespace shim.

The reference package installs as `psbody.mesh` (psbody-mesh-namespace/
__init__.py declares the namespace).  This shim lets code written against
the reference run unchanged on top of mesh_tpu:

    from psbody.mesh import Mesh, MeshViewer      # works as before

Every submodule re-exports the mesh_tpu implementation of the same name.
"""
