"""reference mesh/mesh.py surface."""
from mesh_tpu.mesh import Mesh  # noqa: F401
