"""reference mesh/texture.py surface."""
from mesh_tpu.texture import (  # noqa: F401
    load_texture,
    reload_texture_image,
    set_texture_image,
    texture_coordinates_by_vertex,
    texture_rgb,
    texture_rgb_vec,
    transfer_texture,
)
