"""reference mesh/meshviewer.py surface."""
from mesh_tpu.viewer.meshviewer import (  # noqa: F401
    Dummy,
    MeshSubwindow,
    MeshViewer,
    MeshViewerLocal,
    MeshViewers,
    test_for_opengl,
)
from mesh_tpu.viewer.server import (  # noqa: F401
    MeshViewerRemote,
    MeshViewerSingle,
)
