"""reference mesh/topology/linear_mesh_transform.py surface."""
from mesh_tpu.topology.linear_mesh_transform import (  # noqa: F401
    LinearMeshTransform,
)
