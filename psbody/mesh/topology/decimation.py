"""reference mesh/topology/decimation.py surface."""
from mesh_tpu.topology.decimation import (  # noqa: F401
    qslim_decimator,
    qslim_decimator_fast,
    qslim_decimator_transformer,
    remove_redundant_verts,
    vertex_quadrics,
)
