"""reference mesh/topology/connectivity.py surface."""
from mesh_tpu.topology.connectivity import (  # noqa: F401
    get_faces_per_edge,
    get_faces_per_edge_old,
    get_vert_connectivity,
    get_vert_opposites_per_edge,
    get_vertices_per_edge,
    vertices_in_common,
    vertices_to_edges_matrix,
)
