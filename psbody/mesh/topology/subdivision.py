"""reference mesh/topology/subdivision.py surface."""
from mesh_tpu.topology.subdivision import loop_subdivider  # noqa: F401
