"""reference mesh/topology package surface."""
