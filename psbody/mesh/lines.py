"""reference mesh/lines.py surface."""
from mesh_tpu.lines import Lines  # noqa: F401
