"""reference mesh/search.py surface."""
from mesh_tpu.search import (  # noqa: F401
    AabbNormalsTree,
    AabbTree,
    CGALClosestPointTree,
    ClosestPointTree,
)
