"""reference mesh/utils.py surface."""
from mesh_tpu.utils import col, row, sparse  # noqa: F401
