"""reference mesh/geometry/triangle_area.py surface."""
from mesh_tpu.geometry import triangle_area  # noqa: F401
