"""reference mesh/geometry/cross_product.py surface."""
from mesh_tpu.geometry.compat import CrossProduct  # noqa: F401
