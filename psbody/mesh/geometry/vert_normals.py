"""reference mesh/geometry/vert_normals.py surface."""
from mesh_tpu.geometry.compat import (  # noqa: F401
    MatVecMult,
    VertNormals,
    VertNormalsScaled,
)
