"""reference mesh/geometry/rodrigues.py surface."""
from mesh_tpu.geometry import rodrigues, rodrigues2rotmat  # noqa: F401
