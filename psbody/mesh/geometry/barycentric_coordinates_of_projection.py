"""reference mesh/geometry/barycentric_coordinates_of_projection.py surface."""
from mesh_tpu.geometry import (  # noqa: F401
    barycentric_coordinates_of_projection,
)
