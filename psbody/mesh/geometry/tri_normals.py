"""reference mesh/geometry/tri_normals.py surface (chumpy-era flat API)."""
from mesh_tpu.geometry.compat import (  # noqa: F401
    NormalizedNx3,
    NormalizeRows,
    TriEdges,
    TriNormals,
    TriNormalsScaled,
    TriToScaledNormal,
)
