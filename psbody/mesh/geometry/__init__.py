"""reference mesh/geometry package surface."""
