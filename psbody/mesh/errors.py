"""reference mesh/errors.py surface."""
from mesh_tpu.errors import MeshError, SerializationError  # noqa: F401
