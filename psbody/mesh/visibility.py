"""reference compiled `visibility` extension surface
(py_visibility.cpp:24-30): visibility_compute(cams=..., v=..., f=..., ...)."""
from mesh_tpu.query import visibility_compute  # noqa: F401
