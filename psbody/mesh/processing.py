"""reference mesh/processing.py surface."""
from mesh_tpu.processing import (  # noqa: F401
    concatenate_mesh,
    flip_faces,
    keep_vertices,
    point_cloud,
    remove_faces,
    reorder_vertices,
    reset_face_normals,
    reset_normals,
    rotate_vertices,
    scale_vertices,
    subdivide_triangles,
    translate_vertices,
    uniquified_mesh,
)
