"""reference mesh/colors.py surface."""
from mesh_tpu.colors import main, name_to_rgb  # noqa: F401
