"""reference mesh/serialization package surface."""
from . import serialization  # noqa: F401
