"""reference mesh/serialization/serialization.py surface."""
from mesh_tpu.serialization.serialization import (  # noqa: F401
    load_from_file,
    load_from_json,
    load_from_obj,
    load_from_obj_cpp,
    load_from_ply,
    set_landmark_indices_from_any,
    set_landmark_indices_from_lmrkfile,
    set_landmark_indices_from_ppfile,
    write_json,
    write_mtl,
    write_obj,
    write_ply,
    write_three_json,
)
