"""reference mesh/fonts.py surface."""
from mesh_tpu.viewer.fonts import (  # noqa: F401
    get_image_with_text,
    get_textureid_with_text,
)
