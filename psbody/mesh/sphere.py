"""reference mesh/sphere.py surface."""
from mesh_tpu.sphere import Sphere  # noqa: F401
