"""reference mesh/landmarks.py surface."""
from mesh_tpu.landmarks import (  # noqa: F401
    is_index,
    is_vertex,
    landm_xyz,
    landm_xyz_linear_transform,
    recompute_landmark_indices,
    set_landmarks_from_raw,
    set_landmarks_from_xyz,
)
