"""reference mesh/arcball.py surface."""
from mesh_tpu.viewer.arcball import (  # noqa: F401
    ArcBallT,
    Matrix3fMulMatrix3f,
    Matrix3fSetRotationFromQuat4f,
    Matrix3fT,
    Matrix4fSetRotationFromMatrix3f,
    Matrix4fT,
    Point2fT,
)
