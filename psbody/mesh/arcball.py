"""reference mesh/arcball.py surface."""
from mesh_tpu.viewer.arcball import (  # noqa: F401
    ArcBallT,
    Matrix3fMulMatrix3f,
    Matrix3fSetIdentity,
    Matrix3fSetRotationFromQuat4f,
    Matrix3fT,
    Matrix4fSVD,
    Matrix4fSetRotationFromMatrix3f,
    Matrix4fSetRotationScaleFromMatrix3f,
    Matrix4fT,
    Point2fT,
    Quat4fT,
    Vector3fCross,
    Vector3fDot,
    Vector3fLength,
    Vector3fT,
)
