"""Drop-in `psbody.mesh` facade over mesh_tpu (reference mesh/__init__.py).

Exports the reference package surface — `Mesh`, `MeshViewer`, `MeshViewers`,
`texture_path`, `mesh_package_cache_folder` — plus submodules mirroring the
reference layout (psbody.mesh.meshviewer, .geometry.tri_normals, ...), each
a thin re-export of the corresponding mesh_tpu module.
"""

from mesh_tpu import (  # noqa: F401
    Mesh,
    MeshArrays,
    mesh_package_cache_folder,
    texture_path,
)
from mesh_tpu.viewer import MeshViewer, MeshViewers  # noqa: F401
