# Makefile for mesh_tpu — same targets as the reference package's Makefile
# (all / import_tests / unit_tests / tests / sdist / wheel / documentation /
# clean, reference Makefile:4-45), adapted to the pyproject build: there is
# no CGAL/Boost machinery to configure, and the native I/O core compiles
# itself on first use.
package_name := mesh_tpu

all:
	@echo "----- [ ${package_name} ] Installing with `which python`"
	@pip install --upgrade .

import_tests:
	@echo "----- [ ${package_name} ] Performing import tests"
	@MESH_TPU_CACHE=`mktemp -d -t mesh_tpu.XXXXXXXXXX` python -c "from mesh_tpu import Mesh"
	@python -c "from psbody.mesh.mesh import Mesh"
	@python -c "from mesh_tpu.viewer import MeshViewers"
	@echo "----- [ ${package_name} ] OK import tests"

unit_tests:
	@echo "----- [ ${package_name} ] Running pytest (virtual 8-device CPU platform)"
	@MESH_TPU_CACHE=`mktemp -d -t mesh_tpu.XXXXXXXXXX` python -m pytest tests/ -q -n 4

tpu_tests:
	@echo "----- [ ${package_name} ] Compiled-kernel tests on the real chip"
	@MESH_TPU_TEST_TPU=1 python -m pytest tests/test_tpu_compiled.py -m tpu -q

tests: import_tests unit_tests

lint:
	@echo "----- [ ${package_name} ] meshlint static analysis (no jax init)"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m mesh_tpu.cli lint

lint-fast:
	@echo "----- [ ${package_name} ] meshlint, changed files only"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m mesh_tpu.cli lint --changed

bench:
	@python bench.py

perfcheck:
	@echo "----- [ ${package_name} ] Chip-free perf gate (staged probe + CPU proxies)"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		MESH_TPU_BENCH_PARTIAL=/tmp/mesh_tpu_perfcheck_partial.json \
		python bench.py --stages probe,pallas_proxy,accel_proxy,accel_stream_proxy,mxu_proxy,store_cold_start,tuner_convergence,replay_proxy,fleet_proxy,anim_proxy,trace_proxy > /tmp/mesh_tpu_perfcheck_bench.json || true
	@python -m mesh_tpu.cli perfcheck /tmp/mesh_tpu_perfcheck_bench.json

proxy-golden:
	@echo "----- [ ${package_name} ] Recording the CPU-interpreter proxy golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python bench.py --stage pallas_proxy > benchmarks/proxy_golden.json
	@cat benchmarks/proxy_golden.json

accel-golden:
	@echo "----- [ ${package_name} ] Recording the spatial-index CPU golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python bench.py --stage accel_proxy > benchmarks/accel_golden.json
	@cat benchmarks/accel_golden.json

accel-stream-golden:
	@echo "----- [ ${package_name} ] Recording the streamed-kernel CPU golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python bench.py --stage accel_stream_proxy > benchmarks/accel_stream_golden.json
	@cat benchmarks/accel_stream_golden.json

mxu-golden:
	@echo "----- [ ${package_name} ] Recording the MXU matmul-form CPU golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python bench.py --stage mxu_proxy > benchmarks/mxu_golden.json
	@cat benchmarks/mxu_golden.json

store-golden:
	@echo "----- [ ${package_name} ] Recording the store cold-start CPU golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
		python bench.py --stage store_cold_start > benchmarks/store_golden.json
	@cat benchmarks/store_golden.json

tuner-golden:
	@echo "----- [ ${package_name} ] Recording the tuner convergence golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu MESH_TPU_TUNER=1 \
		MESH_TPU_COALESCE_WINDOW_MS= MESH_TPU_ACCEL_MIN_FACES= \
		MESH_TPU_MXU_CROSSOVER_FACES= \
		MESH_TPU_BVH_STREAM_BUFFERS= MESH_TPU_SERVE_LADDER= \
		python bench.py --stage tuner_convergence > benchmarks/tuner_golden.json
	@cat benchmarks/tuner_golden.json

replay-golden:
	@echo "----- [ ${package_name} ] Recording the replay determinism golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu MESH_TPU_REPLAY_TRACE= \
		python bench.py --stage replay_proxy > benchmarks/replay_golden.json
	@cat benchmarks/replay_golden.json

fleet-golden:
	@echo "----- [ ${package_name} ] Recording the fleet fabric golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu MESH_TPU_FLEET=1 \
		MESH_TPU_FLEET_SPILL=1 MESH_TPU_FLEET_VNODES= \
		MESH_TPU_FLEET_AOT=1 MESH_TPU_NO_XLA_CACHE= \
		MESH_TPU_REPLAY_TRACE= \
		python bench.py --stage fleet_proxy > benchmarks/fleet_golden.json
	@cat benchmarks/fleet_golden.json

anim-golden:
	@echo "----- [ ${package_name} ] Recording the anim refit-vs-rebuild golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu MESH_TPU_ANIM=1 \
		python bench.py --stage anim_proxy > benchmarks/anim_golden.json
	@cat benchmarks/anim_golden.json

trace-golden:
	@echo "----- [ ${package_name} ] Recording the request-identity join golden"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu MESH_TPU_OBS=1 \
		MESH_TPU_TRACE_CONTEXT=1 MESH_TPU_TRACE_TAIL=256 \
		MESH_TPU_TRACE_RESERVOIR= MESH_TPU_FLEET=1 \
		MESH_TPU_FLEET_SPILL=1 MESH_TPU_FLEET_VNODES= \
		MESH_TPU_LEDGER=1 MESH_TPU_LEDGER_CAPACITY= \
		MESH_TPU_REPLAY_TRACE= \
		python bench.py --stage trace_proxy > benchmarks/trace_golden.json
	@cat benchmarks/trace_golden.json

gates:
	@bash tools/run_tpu_gates.sh

sweep:
	@python benchmarks/tile_sweep.py

sdist:
	@echo "----- [ ${package_name} ] Creating the source distribution"
	@python -m build --sdist

wheel:
	@echo "----- [ ${package_name} ] Creating the wheel distribution"
	@pip wheel --no-deps -w dist .

documentation:
	@echo "----- [ ${package_name} ] API map is generated, not Sphinx-built"
	@python tools/gen_parity_map.py > PARITY.md
	@echo "wrote PARITY.md"

docs:
	@echo "----- [ ${package_name} ] Building HTML documentation"
	@PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/build_docs.py

clean:
	@rm -rf build dist *.egg-info doc/_build

.PHONY: all import_tests unit_tests tpu_tests tests lint lint-fast bench perfcheck proxy-golden accel-golden accel-stream-golden mxu-golden store-golden tuner-golden replay-golden fleet-golden anim-golden trace-golden gates sweep sdist wheel documentation docs clean
