"""MeshArrays: the functional, jit/vmap-compatible mesh container.

This is the TPU-native data model (SURVEY.md section 7.1): a registered
pytree dataclass whose leaves are `jax.Array`s.  Vertices may carry leading
batch axes ``[..., V, 3]`` over a shared static topology ``f [F, 3]`` — the
multi-mesh batching the reference lacks entirely (SURVEY.md P5).  All
operations on it are free functions (mesh_tpu.geometry / mesh_tpu.query)
usable under jit, vmap, grad, and shard_map.

The mutable `mesh_tpu.Mesh` facade (mesh.py) wraps host numpy arrays for
reference-API parity and converts at the kernel boundary; heavy pipelines
should hold a MeshArrays and stay on device.
"""

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MeshArrays:
    """Device-resident triangle mesh.

    v: [..., V, 3] float32 vertices (leading batch axes allowed)
    f: [F, 3] int32 faces, shared across the batch
    vn/vc: optional per-vertex arrays batched like v
    vt/ft: optional texture coords / texture faces (unbatched topology)
    """

    v: jax.Array
    f: jax.Array
    vn: Optional[jax.Array] = None
    vc: Optional[jax.Array] = None
    vt: Optional[jax.Array] = None
    ft: Optional[jax.Array] = None

    @classmethod
    def create(cls, v, f, vn=None, vc=None, vt=None, ft=None, dtype=jnp.float32):
        as_f = lambda x: None if x is None else jnp.asarray(np.asarray(x), dtype)
        as_i = lambda x: None if x is None else jnp.asarray(np.asarray(x), jnp.int32)
        return cls(v=as_f(v), f=as_i(f), vn=as_f(vn), vc=as_f(vc),
                   vt=as_f(vt), ft=as_i(ft))

    @property
    def num_vertices(self):
        return self.v.shape[-2]

    @property
    def num_faces(self):
        return self.f.shape[0]

    @property
    def batch_shape(self):
        return self.v.shape[:-2]

    def with_vertices(self, v):
        return dataclasses.replace(self, v=v)

    def tri(self):
        """Triangle corner coordinates [..., F, 3, 3]."""
        return jnp.take(self.v, self.f, axis=-2)
