"""QSlim-style quadric edge-collapse decimation
(reference mesh/topology/decimation.py).

Inherently sequential greedy-heap algorithm — kept on host per SURVEY.md
section 7.3 ("resist the urge to TPU-ify"), but the setup is vectorized:
vertex quadrics come from closed-form plane equations accumulated with
np.add.at instead of the reference's per-face SVD loop (decimation.py:43-68),
which is ~100x faster at SMPL scale.  The output is a sparse downsample
transform applied on-device as a gather-matmul via LinearMeshTransform.
"""

import heapq
import math

import numpy as np
import scipy.sparse as sp
import scipy.spatial

from .linear_mesh_transform import LinearMeshTransform


def remove_redundant_verts(v, f, eps=1e-10):
    """Collapse vertices closer than `eps` onto one representative and
    renumber faces compactly (reference decimation.py:15-40 behavior,
    re-derived: KD-tree near-pair graph + connected components instead of
    the reference's dense O(V^2) pdist loop).

    Vertices not referenced by any face after merging are dropped, matching
    the reference.
    """
    import scipy.sparse.csgraph as csgraph

    v = np.asarray(v)
    f = np.asarray(f, dtype=np.int64)
    n = len(v)
    near = scipy.spatial.cKDTree(v).query_pairs(eps, output_type="ndarray")
    graph = sp.coo_matrix(
        (np.ones(len(near)), (near[:, 0], near[:, 1])), shape=(n, n)
    )
    _, component = csgraph.connected_components(graph, directed=False)
    # each duplicate group collapses onto its smallest member index
    representative = np.full(component.max() + 1, n, dtype=np.int64)
    np.minimum.at(representative, component, np.arange(n))
    merged_faces = representative[component[f]]

    kept = np.unique(merged_faces)
    renumber = np.zeros(n, dtype=np.int64)
    renumber[kept] = np.arange(kept.size)
    return v[kept], renumber[merged_faces]


def vertex_quadrics(mesh):
    """(V, 4, 4) accumulated plane quadrics per vertex.

    The plane equation of each face is the unit normal plus offset
    [n, -n.v0]; its outer product accumulates onto the face's three corners
    (closed form replacing the reference's SVD per face,
    decimation.py:43-68; the SVD null-space vector equals +-[n, d]/|n| and
    the outer product is sign-invariant).
    """
    v = np.asarray(mesh.v, dtype=np.float64)
    f = np.asarray(mesh.f, dtype=np.int64)
    a, b, c = v[f[:, 0]], v[f[:, 1]], v[f[:, 2]]
    n = np.cross(b - a, c - a)
    norms = np.linalg.norm(n, axis=1, keepdims=True)
    norms[norms == 0] = 1.0
    n = n / norms
    d = -np.sum(n * a, axis=1, keepdims=True)
    eq = np.concatenate([n, d], axis=1)  # (F, 4)
    quad = eq[:, :, None] * eq[:, None, :]  # (F, 4, 4)
    v_quadrics = np.zeros((len(v), 4, 4))
    for k in range(3):
        np.add.at(v_quadrics, f[:, k], quad)
    return v_quadrics


def qslim_decimator_transformer(mesh, factor=None, n_verts_desired=None):
    """Greedy quadric edge collapse until n_verts_desired vertices remain.

    :returns: (new_faces Fx3, mtx): sparse (3V' x 3V) downsample transform
        (reference decimation.py:78-190).
    """
    if factor is None and n_verts_desired is None:
        raise ValueError("Need either factor or n_verts_desired.")
    if n_verts_desired is None:
        n_verts_desired = math.ceil(len(mesh.v) * factor)

    Qv = vertex_quadrics(mesh)
    from .connectivity import get_vertices_per_edge

    vert_adj = np.asarray(get_vertices_per_edge(mesh), dtype=np.int64)
    v = np.asarray(mesh.v, dtype=np.float64)

    def collapse_cost(r, c):
        Qsum = Qv[r] + Qv[c]
        p1 = np.append(v[r], 1.0)
        p2 = np.append(v[c], 1.0)
        destroy_c_cost = float(p1 @ Qsum @ p1)
        destroy_r_cost = float(p2 @ Qsum @ p2)
        return destroy_c_cost, destroy_r_cost, Qsum

    queue = []
    for r, c in vert_adj:
        r, c = (int(r), int(c)) if r < c else (int(c), int(r))
        dc, dr, _ = collapse_cost(r, c)
        heapq.heappush(queue, (min(dc, dr), (r, c)))

    faces = np.asarray(mesh.f, dtype=np.int64)
    # merged-vertex forest: heap entries keep their original endpoint ids
    # and are canonicalized through find() on pop, so a collapse is
    # O(log E) instead of rewriting + re-heapifying the whole queue
    parent = np.arange(len(mesh.v))

    def find(x):
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:        # path compression
            parent[x], x = root, parent[x]
        return root

    def resolve_all():
        """Vectorized find() for every vertex via pointer doubling
        (O(log depth) rounds), writing the fully-compressed forest back so
        later resyncs and scalar find() calls start from depth 1."""
        remap = parent[parent]
        while True:
            nxt = remap[remap]
            if np.array_equal(nxt, remap):
                parent[:] = remap
                return remap
            remap = nxt

    def live_vertex_count():
        """Exact count of vertices still referenced by a non-degenerate
        face under the current merges (what the pre-union-find code
        recomputed every iteration)."""
        remapped = resolve_all()[faces]
        alive = ~(
            (remapped[:, 0] == remapped[:, 1])
            | (remapped[:, 1] == remapped[:, 2])
            | (remapped[:, 2] == remapped[:, 0])
        )
        return len(np.unique(remapped[alive]))

    nverts_total = len(np.unique(faces))
    since_resync = 0
    while nverts_total > n_verts_desired and queue:
        cost0, (r0, c0) = heapq.heappop(queue)
        r, c = find(r0), find(c0)
        if r == c:
            continue
        if r > c:
            r, c = c, r
        dc, dr, Qsum = collapse_cost(r, c)
        if min(dc, dr) > cost0:
            # stale entry: re-push with the fresh cost (lazy-deletion heap)
            heapq.heappush(queue, (min(dc, dr), (r, c)))
            continue
        to_keep, to_destroy = (r, c) if dc < dr else (c, r)
        parent[to_destroy] = to_keep
        Qv[r] = Qsum
        Qv[c] = Qsum
        # a collapse merges two live face-vertices, but can also orphan
        # others by degenerating all their faces — decrement optimistically
        # and resync the exact count periodically and near the target
        nverts_total -= 1
        since_resync += 1
        if since_resync >= 64 or nverts_total <= n_verts_desired:
            nverts_total = live_vertex_count()
            since_resync = 0

    # apply all merges to the faces at once, then drop collapsed faces
    faces = resolve_all()[faces]
    degenerate = (
        (faces[:, 0] == faces[:, 1])
        | (faces[:, 1] == faces[:, 2])
        | (faces[:, 2] == faces[:, 0])
    )
    faces = faces[~degenerate]

    return _get_sparse_transform(faces, len(mesh.v))


def qslim_decimator(mesh, factor=None, n_verts_desired=None):
    """Simplified mesh as a LinearMeshTransform (reference
    decimation.py:192-202)."""
    new_faces, mtx = qslim_decimator_transformer(mesh, factor, n_verts_desired)
    return LinearMeshTransform(mtx, new_faces)


def qslim_decimator_fast(mesh, factor=None, n_verts_desired=None):
    """Decimate and return the simplified mesh directly (reference
    decimation.py:71-75).  The reference version shells out to an external
    `experiments.qslim` package that it does not ship; here the vectorized
    quadric pipeline above is already the fast path, so this simply applies
    the transform and hands back the coarse mesh."""
    xform = qslim_decimator(mesh, factor=factor, n_verts_desired=n_verts_desired)
    return xform(mesh)


def _get_sparse_transform(faces, num_original_verts):
    """Renumber `faces` onto their surviving vertices and build the sparse
    (3V' x 3V) selection matrix that picks those vertices' flattened xyz
    coordinates out of the original array (reference decimation.py:204-223).
    """
    survivors = np.unique(faces)            # sorted original vertex ids
    lookup = np.full(num_original_verts, -1, dtype=np.int64)
    lookup[survivors] = np.arange(survivors.size)
    new_faces = lookup[np.asarray(faces, dtype=np.int64)]
    # flat coordinate 3i+k of new vertex i reads 3*survivors[i]+k
    out_coords = np.arange(3 * survivors.size)
    in_coords = (3 * survivors[:, None] + np.arange(3)).ravel()
    mtx = sp.csc_matrix(
        (np.ones(out_coords.size), (out_coords, in_coords)),
        shape=(3 * survivors.size, 3 * num_original_verts),
    )
    return new_faces, mtx
