from .connectivity import (  # noqa: F401
    get_vert_opposites_per_edge,
    get_vert_connectivity,
    get_vertices_per_edge,
    get_faces_per_edge,
    vertices_to_edges_matrix,
    vertices_in_common,
)
from .decimation import (  # noqa: F401
    qslim_decimator,
    qslim_decimator_transformer,
    vertex_quadrics,
    remove_redundant_verts,
)
from .subdivision import loop_subdivider  # noqa: F401
from .linear_mesh_transform import LinearMeshTransform  # noqa: F401
