"""Callable sparse vertex->vertex resolution transform
(reference mesh/topology/linear_mesh_transform.py).

Wraps the sparse up/downsample matrix produced by loop_subdivider /
qslim_decimator.  Applied to a Mesh it returns the remeshed Mesh; applied to
a raw array it returns the mapped flat coordinates; with want_edges=True it
returns per-edge difference vectors.  `as_dense_gather()` exports the
transform as device arrays for on-TPU application inside jitted pipelines.
"""

import numpy as np

from ..utils import col, row
from .connectivity import vertices_to_edges_matrix


class LinearMeshTransform(object):
    def __init__(self, mtx, faces, vt=None, ft=None):
        from ..mesh import Mesh

        self.mtx = mtx
        self.faces = faces
        self.remeshed_vtx_to_remeshed_edge_mtx = vertices_to_edges_matrix(
            Mesh(f=faces, v=np.zeros((mtx.shape[0], 3))), want_xyz=True
        )
        self.vtx_to_edge_mtx = self.remeshed_vtx_to_remeshed_edge_mtx.dot(self.mtx)
        if vt is not None:
            self.vt = vt
        if ft is not None:
            self.ft = ft

    def as_coo_arrays(self):
        """(rows, cols, vals) int32/int32/float32 device-ready COO triplets,
        for applying the transform with jax segment_sum inside jit."""
        coo = self.mtx.tocoo()
        return (
            np.asarray(coo.row, np.int32),
            np.asarray(coo.col, np.int32),
            np.asarray(coo.data, np.float32),
        )

    def __call__(self, a, want_edges=False):
        from ..mesh import Mesh

        if not isinstance(a, Mesh):
            return self.chained_obj_for(a, want_edges)

        a_is_subdivided = a.v.size == self.mtx.shape[0]
        if want_edges:
            if a_is_subdivided:
                return self.remeshed_vtx_to_remeshed_edge_mtx.dot(
                    col(a.v)
                ).reshape((-1, 3))
            return self.vtx_to_edge_mtx.dot(col(a.v)).reshape((-1, 3))

        if a_is_subdivided:
            return a
        result = Mesh(
            v=self.mtx.dot(col(a.v)).reshape((-1, 3)), f=self.faces.copy()
        )
        if hasattr(a, "segm"):
            result.transfer_segm(a)
        if hasattr(a, "landm"):
            result.landm = dict(
                (k, np.argmin(np.sum((result.v - row(a.v[v])) ** 2, axis=1)))
                for k, v in a.landm.items()
            )
        if hasattr(self, "ft"):
            result.ft = self.ft
        if hasattr(self, "vt"):
            result.vt = self.vt
        return result

    def chained_obj_for(self, a, want_edges):
        a_len = len(a.r) if hasattr(a, "r") else a.size
        a_is_subdivided = a_len == self.mtx.shape[0]
        if a_is_subdivided and not want_edges:
            return a
        if not want_edges:
            mtx = self.mtx
        elif a_is_subdivided:
            mtx = self.remeshed_vtx_to_remeshed_edge_mtx
        else:
            mtx = self.vtx_to_edge_mtx
        return mtx.dot(col(np.asarray(a))).flatten()
