"""Callable sparse vertex->vertex resolution transform
(reference mesh/topology/linear_mesh_transform.py).

Wraps the sparse up/downsample matrix produced by loop_subdivider /
qslim_decimator.  Applied to a Mesh it returns the remeshed Mesh; applied to
a raw array it returns the mapped flat coordinates; with want_edges=True it
returns per-edge difference vectors.  `as_dense_gather()` exports the
transform as device arrays for on-TPU application inside jitted pipelines.
"""

import numpy as np

from ..utils import col
from .connectivity import vertices_to_edges_matrix


class LinearMeshTransform(object):
    def __init__(self, mtx, faces, vt=None, ft=None):
        from ..mesh import Mesh

        self.mtx = mtx
        self.faces = faces
        self.remeshed_vtx_to_remeshed_edge_mtx = vertices_to_edges_matrix(
            Mesh(f=faces, v=np.zeros((mtx.shape[0], 3))), want_xyz=True
        )
        self.vtx_to_edge_mtx = self.remeshed_vtx_to_remeshed_edge_mtx.dot(self.mtx)
        if vt is not None:
            self.vt = vt
        if ft is not None:
            self.ft = ft

    def as_coo_arrays(self):
        """(rows, cols, vals) int32/int32/float32 device-ready COO triplets,
        for applying the transform with jax segment_sum inside jit."""
        coo = self.mtx.tocoo()
        return (
            np.asarray(coo.row, np.int32),
            np.asarray(coo.col, np.int32),
            np.asarray(coo.data, np.float32),
        )

    def _matrix_for(self, n_coords, want_edges):
        """Pick the sparse matrix mapping an input with `n_coords` flat
        coordinates to the requested output, or None for identity (input is
        already at the target resolution and vertices were asked for)."""
        at_target = n_coords == self.mtx.shape[0]
        if want_edges:
            return (
                self.remeshed_vtx_to_remeshed_edge_mtx
                if at_target
                else self.vtx_to_edge_mtx
            )
        return None if at_target else self.mtx

    def __call__(self, a, want_edges=False):
        from ..mesh import Mesh

        if not isinstance(a, Mesh):
            return self.chained_obj_for(a, want_edges)
        mtx = self._matrix_for(a.v.size, want_edges)
        if want_edges:
            return (mtx @ col(a.v)).reshape(-1, 3)
        if mtx is None:
            return a
        return self._remeshed(a, mtx)

    def _remeshed(self, source, mtx):
        """Mesh at the target resolution, carrying over segmentation,
        landmarks (snapped to nearest new vertex), and texture coords."""
        from ..mesh import Mesh

        out = Mesh(v=(mtx @ col(source.v)).reshape(-1, 3), f=self.faces.copy())
        if hasattr(source, "segm"):
            out.transfer_segm(source)
        if hasattr(source, "landm"):
            out.landm = {
                name: int(
                    np.argmin(((out.v - source.v[idx]) ** 2).sum(axis=1))
                )
                for name, idx in source.landm.items()
            }
        for attr in ("vt", "ft"):
            if hasattr(self, attr):
                setattr(out, attr, getattr(self, attr))
        return out

    def chained_obj_for(self, a, want_edges):
        """Apply to a raw array or an autodiff-style chained object with a
        `.r` value attribute; returns flat coordinates."""
        n_coords = len(a.r) if hasattr(a, "r") else a.size
        mtx = self._matrix_for(n_coords, want_edges)
        if mtx is None:
            return a
        return np.asarray(mtx @ col(np.asarray(a))).ravel()
