"""Edge / adjacency topology structures (reference mesh/topology/connectivity.py).

Host-side numpy, vectorized (the reference builds dicts in Python loops,
connectivity.py:17-34), with the same crc32-keyed disk cache so repeated runs
on a fixed topology (e.g. the SMPL template) are free
(connectivity.py:115-130; cache folder semantics from mesh/__init__.py:14-20).

These structures are *setup-time*: their outputs (edge lists, incidence
matrices) become static device constants consumed by jitted kernels.
"""

import os
import pickle
import zlib

import numpy as np
import scipy.sparse as sp

from .. import mesh_package_cache_folder
from ..utils import col, row


def _cached(tag, faces, builder, extra=""):
    key = str(zlib.crc32(np.ascontiguousarray(faces).flatten()))
    fname = os.path.join(
        mesh_package_cache_folder, "%s_%s%s.pkl" % (tag, key, extra)
    )
    try:
        with open(fname, "rb") as fp:
            return pickle.load(fp)
    except Exception:
        result = builder()
        try:
            with open(fname, "wb") as fp:
                pickle.dump(result, fp, -1)
        except OSError:
            pass
        return result


def get_vert_opposites_per_edge(mesh):
    """Dict from sorted vertex-index edge pairs to the list of opposite
    vertices (reference connectivity.py:17-34)."""
    f = np.asarray(mesh.f, dtype=np.int64)
    result = {}
    # vectorized edge/opposite extraction, dict assembly at the end
    edges = np.concatenate([f[:, [0, 1]], f[:, [1, 2]], f[:, [2, 0]]], axis=0)
    opps = np.concatenate([f[:, 2], f[:, 0], f[:, 1]], axis=0)
    edges = np.sort(edges, axis=1)
    for (e0, e1), opp in zip(edges, opps):
        result.setdefault((e0, e1), []).append(opp)
    return result


def get_vert_connectivity(mesh):
    """Sparse V x V vertex adjacency (reference connectivity.py:37-54)."""
    f = np.asarray(mesh.f, dtype=np.int64)
    n_v = len(mesh.v)
    vpv = sp.csc_matrix((n_v, n_v))
    for i in range(3):
        IS = f[:, i]
        JS = f[:, (i + 1) % 3]
        data = np.ones(len(IS))
        mtx = sp.csc_matrix((data, (IS, JS)), shape=(n_v, n_v))
        vpv = vpv + mtx + mtx.T
    return vpv


def vertices_in_common(face_1, face_2):
    """The vertices shared by two faces, sorted (reference
    connectivity.py:84-107)."""
    return sorted(set(np.asarray(face_1).tolist()) & set(np.asarray(face_2).tolist()))


def get_vertices_per_edge(mesh, faces_per_edge=None):
    """Ex2 unique vertex-index pairs, one row per edge
    (reference connectivity.py:108-130, cached)."""
    faces = np.asarray(mesh.f)
    extra = (
        "_" + str(zlib.crc32(np.ascontiguousarray(faces_per_edge).flatten()))
        if faces_per_edge is not None
        else ""
    )

    def build():
        if faces_per_edge is not None:
            return np.asarray(
                np.vstack(
                    [
                        row(np.intersect1d(faces[k[0]], faces[k[1]]))
                        for k in faces_per_edge
                    ]
                ),
                np.uint32,
            )
        vc = sp.coo_matrix(get_vert_connectivity(mesh))
        result = np.hstack((col(vc.row), col(vc.col)))
        return result[result[:, 0] < result[:, 1]]

    return _cached("verts_per_edge_cache", faces, build, extra)


def get_faces_per_edge(mesh):
    """Ex2 adjacent-face index pairs, one row per interior edge
    (reference connectivity.py:139-161, cached)."""
    faces = np.asarray(mesh.f)

    def build():
        f = np.asarray(faces, dtype=np.int64)
        IS = np.repeat(np.arange(len(f)), 3)
        JS = f.ravel()
        data = np.ones(IS.size)
        f2v = sp.csc_matrix((data, (IS, JS)), shape=(len(f), np.max(JS) + 1))
        f2f = (f2v @ f2v.T).tocoo()
        table = np.hstack((col(f2f.row), col(f2f.col), col(f2f.data)))
        which = (table[:, 0] < table[:, 1]) & (table[:, 2] >= 2)
        return np.asarray(table[which, :2], np.uint32)

    return _cached("edgecache_new", faces, build)


def get_faces_per_edge_old(mesh):
    """Legacy spelling kept for reference compat (connectivity.py:164-200).
    The reference retains two generations of this computation whose only
    contract is "one row per interior edge, the two adjacent face ids";
    both are served by the modern implementation here (row order is not part
    of the contract and differs between the reference's own two versions)."""
    return get_faces_per_edge(mesh)


def vertices_to_edges_matrix(mesh, want_xyz=True):
    """Sparse matrix M with e = M.dot(v): per-edge difference operator
    (reference connectivity.py:57-80)."""
    vpe = get_vertices_per_edge(mesh)
    IS = np.repeat(np.arange(len(vpe)), 2)
    JS = np.asarray(vpe, dtype=np.int64).flatten()
    data = np.ones_like(vpe, dtype=np.float64)
    data[:, 1] = -1
    data = data.flatten()
    if want_xyz:
        IS = np.concatenate((IS * 3, IS * 3 + 1, IS * 3 + 2))
        JS = np.concatenate((JS * 3, JS * 3 + 1, JS * 3 + 2))
        data = np.concatenate((data, data, data))
    return sp.csc_matrix((data, np.vstack((IS, JS))))


def edge_topology_arrays(f, num_vertices):
    """TPU-facing static topology bundle: fixed-shape int32 arrays for device
    kernels (no reference analog — this is the padded-gather form that jitted
    code consumes instead of dicts/sparse matrices).

    :returns: dict with ``edges`` (E,2), ``edge_opposites`` (E,2; -1 padded
        for boundary edges), ``faces_per_edge`` (E,2; -1 padded).
    """
    f = np.asarray(f, dtype=np.int64)
    half = np.concatenate([f[:, [0, 1]], f[:, [1, 2]], f[:, [2, 0]]], axis=0)
    opp = np.concatenate([f[:, 2], f[:, 0], f[:, 1]], axis=0)
    face_id = np.tile(np.arange(len(f)), 3)
    key = np.sort(half, axis=1)
    uniq, inverse = np.unique(key, axis=0, return_inverse=True)
    n_e = len(uniq)
    edge_opposites = np.full((n_e, 2), -1, dtype=np.int64)
    faces_per_edge = np.full((n_e, 2), -1, dtype=np.int64)
    slot = np.zeros(n_e, dtype=np.int64)
    for e_idx, o, fi in zip(inverse, opp, face_id):
        s = slot[e_idx]
        if s < 2:
            edge_opposites[e_idx, s] = o
            faces_per_edge[e_idx, s] = fi
            slot[e_idx] = s + 1
    return {
        "edges": uniq.astype(np.int32),
        "edge_opposites": edge_opposites.astype(np.int32),
        "faces_per_edge": faces_per_edge.astype(np.int32),
    }
