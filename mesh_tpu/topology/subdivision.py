"""Loop subdivision producing a sparse upsample transform
(reference mesh/topology/subdivision.py).

Host-side setup algorithm (data-dependent dict lookups over texture seams);
the resulting LinearMeshTransform applies on-device as a sparse matmul.
Weights follow Loop's scheme exactly as the reference implements it:
original vertices smoothed with wt = 3/16 (valence 3) or 3/(8n), edge
midpoints = 3/8 endpoints + 1/8 opposite vertices (subdivision.py:50-91),
faces split 1->4 (subdivision.py:97-128).
"""

import numpy as np
import scipy.sparse as sp

from .connectivity import (
    get_vert_connectivity,
    get_vert_opposites_per_edge,
    get_vertices_per_edge,
)
from .linear_mesh_transform import LinearMeshTransform


def loop_subdivider(mesh):
    IS, JS, data = [], [], []

    vc = get_vert_connectivity(mesh)
    ve = get_vertices_per_edge(mesh)
    vo = get_vert_opposites_per_edge(mesh)

    has_texture = hasattr(mesh, "ft") and hasattr(mesh, "vt")
    if has_texture:
        from ..mesh import Mesh

        flat_mesh = Mesh(v=np.asarray(mesh.vt), f=np.asarray(mesh.ft))
        vt_start = len(flat_mesh.v)
        vt_edge_to_midpoint = {}
        vt_e = get_vertices_per_edge(flat_mesh)
        vt = flat_mesh.v[:, :2].tolist()
        for idx, vs in enumerate(np.asarray(vt_e, dtype=np.int64)):
            v0, v1 = sorted(vs.tolist())
            vt_edge_to_midpoint[(v0, v1)] = vt_start + idx
            vt_edge_to_midpoint[(v1, v0)] = vt_start + idx
            vt.append((np.array(vt[v0]) + np.array(vt[v1])) / 2.0)
        vt = np.array(vt)

    # smoothed original vertices
    for idx in range(len(mesh.v)):
        nbrs = np.nonzero(vc[:, idx])[0]
        nn = len(nbrs)
        if nn == 3:
            wt = 3.0 / 16.0
        elif nn > 3:
            wt = 3.0 / (8.0 * nn)
        else:
            raise ValueError("vertex valence should be 3 or more")
        for nbr in nbrs:
            IS.append(idx)
            JS.append(nbr)
            data.append(wt)
        IS.append(idx)
        JS.append(idx)
        data.append(1.0 - wt * nn)

    # edge midpoints
    start = len(mesh.v)
    edge_to_midpoint = {}
    for idx, vs in enumerate(np.asarray(ve, dtype=np.int64)):
        v0, v1 = sorted(vs.tolist())
        IS += [start + idx, start + idx]
        JS += [v0, v1]
        data += [3.0 / 8.0, 3.0 / 8.0]
        opposites = vo[(v0, v1)]
        IS += [start + idx, start + idx]
        JS += [int(opposites[0]), int(opposites[1])]
        data += [1.0 / 8.0, 1.0 / 8.0]
        edge_to_midpoint[(v0, v1)] = start + idx
        edge_to_midpoint[(v1, v0)] = start + idx

    # 1 -> 4 face split
    f = []
    ft = [] if has_texture else None
    for f_i, old_f in enumerate(np.asarray(mesh.f, dtype=np.int64)):
        ff = np.concatenate((old_f, old_f))
        if has_texture:
            ftft = np.concatenate(
                (np.asarray(mesh.ft)[f_i], np.asarray(mesh.ft)[f_i])
            )
            anomalous = len(np.unique(np.asarray(mesh.ft)[f_i])) != 3
        for i in range(3):
            m0 = edge_to_midpoint[(ff[i], ff[i + 1])]
            m2 = edge_to_midpoint[(ff[i + 1], ff[i + 2])]
            f.append([m0, ff[i + 1], m2])
            if has_texture:
                if anomalous:
                    ft.append([0, 0, 0])
                else:
                    ft.append([
                        vt_edge_to_midpoint[(ftft[i], ftft[i + 1])],
                        ftft[i + 1],
                        vt_edge_to_midpoint[(ftft[i + 1], ftft[i + 2])],
                    ])
        f.append([
            edge_to_midpoint[(ff[0], ff[1])],
            edge_to_midpoint[(ff[1], ff[2])],
            edge_to_midpoint[(ff[2], ff[3])],
        ])
        if has_texture:
            if anomalous:
                ft.append([0, 0, 0])
            else:
                ft.append([
                    vt_edge_to_midpoint[(ftft[0], ftft[1])],
                    vt_edge_to_midpoint[(ftft[1], ftft[2])],
                    vt_edge_to_midpoint[(ftft[2], ftft[3])],
                ])

    f = np.array(f, dtype=np.int64)
    if has_texture:
        ft = np.array(ft, dtype=np.int64)

    IS = np.array(IS, dtype=np.int64)
    JS = np.array(JS, dtype=np.int64)
    data = np.array(data, dtype=np.float64)
    # expand to xyz coordinates
    IS3 = np.concatenate((IS * 3, IS * 3 + 1, IS * 3 + 2))
    JS3 = np.concatenate((JS * 3, JS * 3 + 1, JS * 3 + 2))
    data3 = np.concatenate((data, data, data))
    mtx = sp.csc_matrix((data3, np.vstack((IS3, JS3))))

    if has_texture:
        return LinearMeshTransform(mtx, f, vt=vt, ft=ft)
    return LinearMeshTransform(mtx, f)
