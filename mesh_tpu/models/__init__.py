from .body_model import (  # noqa: F401
    MODEL_FAMILIES,
    BodyModel,
    lbs,
    load_body_model_npz,
    mano_pose_from_pca,
    save_body_model_npz,
    smpl_sized_sphere,
    synthetic_body_model,
    synthetic_family_model,
)
