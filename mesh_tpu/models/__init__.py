from .body_model import (  # noqa: F401
    BodyModel,
    lbs,
    load_body_model_npz,
    synthetic_body_model,
    smpl_sized_sphere,
)
