"""TPU-native linear-blend-skinning body model (SMPL architecture).

The reference package is the geometric substrate under SMPL / FLAME / MANO
pipelines (reference README.md:10-22) but contains no body model itself; this
module supplies the model family those pipelines need, designed TPU-first:

- the whole forward pass (shape blendshapes -> joint regression -> pose
  blendshapes -> forward kinematics -> skinning) is one jittable function
  batched over arbitrary leading axes, with the kinematic-tree scan unrolled
  over the (static) joint count so XLA sees straight-line MXU work;
- per-joint rotations come from the Taylor-guarded `rodrigues2rotmat`, so
  gradients flow through theta = 0 (rest pose);
- weights can be loaded from a standard SMPL-family .npz, or synthesized
  (`synthetic_body_model`) for tests/benchmarks where real model weights
  cannot be shipped.

Layout conventions: V vertices, J joints, B shape coefficients.
"""

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..geometry.rodrigues import rodrigues2rotmat


@dataclasses.dataclass(frozen=True)
class BodyModel:
    """Model weights as device arrays; `parents` is static metadata."""

    v_template: jax.Array          # (V, 3)
    shapedirs: jax.Array           # (V, 3, B)
    posedirs: jax.Array            # (V, 3, 9*(J-1))
    joint_regressor: jax.Array     # (J, V)
    lbs_weights: jax.Array         # (V, J)
    faces: jax.Array               # (F, 3) int32
    parents: Tuple[int, ...]       # static kinematic tree, parents[0] == -1
    # MANO/SMPL-H pose-PCA basis when the file ships one (None otherwise):
    # components are stored full-rank (45, 45); users select the first n
    # at pose construction time (mano_pose_from_pca)
    hands_components: Optional[jax.Array] = None   # (45, 45)
    hands_mean: Optional[jax.Array] = None         # (45,)

    @property
    def num_vertices(self):
        return self.v_template.shape[0]

    @property
    def num_joints(self):
        return self.joint_regressor.shape[0]

    @property
    def num_betas(self):
        return self.shapedirs.shape[-1]


jax.tree_util.register_dataclass(
    BodyModel,
    data_fields=["v_template", "shapedirs", "posedirs", "joint_regressor",
                 "lbs_weights", "faces", "hands_components", "hands_mean"],
    meta_fields=["parents"],
)


def _with_homogeneous_row(R, t):
    """Stack (..., 3, 3) rotation and (..., 3) translation into (..., 4, 4)."""
    top = jnp.concatenate([R, t[..., :, None]], axis=-1)         # (..., 3, 4)
    bottom = jnp.broadcast_to(
        jnp.array([0.0, 0.0, 0.0, 1.0], dtype=R.dtype), top.shape[:-2] + (1, 4)
    )
    return jnp.concatenate([top, bottom], axis=-2)


def lbs(model, betas, pose, trans=None, precision=jax.lax.Precision.HIGHEST):
    """Linear blend skinning forward pass.

    :param betas: (..., B) shape coefficients
    :param pose: (..., J, 3) axis-angle per joint (joint 0 = global rotation)
    :param trans: optional (..., 3) root translation
    :param precision: matmul precision for the einsums/FK chain.  Default
        HIGHEST: XLA's default f32 matmul runs at reduced (bf16-style)
        precision on TPU, which accumulates to ~cm errors down a 24-joint
        kinematic chain; pass Precision.DEFAULT to trade accuracy for MXU
        throughput in benchmarks.
    :returns: (vertices (..., V, 3), joints (..., J, 3))
    """
    betas = jnp.asarray(betas)
    pose = jnp.asarray(pose)
    dtype = model.v_template.dtype

    # 1. shape blendshapes
    v_shaped = model.v_template + jnp.einsum(
        "vcb,...b->...vc", model.shapedirs, betas.astype(dtype),
        precision=precision,
    )
    # 2. joint locations from the shaped body
    joints = jnp.einsum(
        "jv,...vc->...jc", model.joint_regressor, v_shaped, precision=precision
    )
    # 3. per-joint rotations + pose blendshapes
    R = rodrigues2rotmat(pose.astype(dtype))                    # (..., J, 3, 3)
    eye = jnp.eye(3, dtype=dtype)
    pose_feature = (R[..., 1:, :, :] - eye).reshape(pose.shape[:-2] + (-1,))
    v_posed = v_shaped + jnp.einsum(
        "vcp,...p->...vc", model.posedirs, pose_feature, precision=precision
    )
    # 4. forward kinematics, unrolled over the static tree
    rel_joints = [joints[..., 0, :]]
    for j in range(1, model.num_joints):
        rel_joints.append(joints[..., j, :] - joints[..., model.parents[j], :])
    world = [None] * model.num_joints
    world[0] = _with_homogeneous_row(R[..., 0, :, :], rel_joints[0])
    for j in range(1, model.num_joints):
        local = _with_homogeneous_row(R[..., j, :, :], rel_joints[j])
        world[j] = jnp.einsum(
            "...ab,...bc->...ac", world[model.parents[j]], local,
            precision=precision,
        )
    G = jnp.stack(world, axis=-3)                               # (..., J, 4, 4)
    posed_joints = G[..., :3, 3]
    # 5. remove the rest-pose joint offset: A_j = G_j - [0 | G_j[:3,:3] j_rest]
    correction = jnp.einsum(
        "...jab,...jb->...ja", G[..., :3, :3], joints, precision=precision
    )
    A = _with_homogeneous_row(G[..., :3, :3], G[..., :3, 3] - correction)
    # 6. skinning: blend joint transforms per vertex and apply
    T = jnp.einsum(
        "vj,...jab->...vab", model.lbs_weights, A, precision=precision
    )
    v_out = (
        jnp.einsum(
            "...vab,...vb->...va", T[..., :3, :3], v_posed, precision=precision
        )
        + T[..., :3, 3]
    )
    if trans is not None:
        v_out = v_out + jnp.asarray(trans, dtype)[..., None, :]
        posed_joints = posed_joints + jnp.asarray(trans, dtype)[..., None, :]
    return v_out, posed_joints


def _uv_sphere(n_seg, n_ring):
    """Unit UV-sphere: n_ring latitude rings x n_seg segments + 2 poles
    -> (n_seg * n_ring + 2 vertices, 2 * n_seg * n_ring faces)."""
    theta = np.pi * (np.arange(1, n_ring + 1)) / (n_ring + 1)
    phi = 2 * np.pi * np.arange(n_seg) / n_seg
    rings = np.stack(
        [
            np.outer(np.sin(theta), np.cos(phi)),
            np.outer(np.sin(theta), np.sin(phi)),
            np.outer(np.cos(theta), np.ones(n_seg)),
        ],
        axis=-1,
    ).reshape(-1, 3)
    v = np.vstack([[[0, 0, 1.0]], rings, [[0, 0, -1.0]]])
    faces = []
    for r in range(n_ring - 1):
        base0 = 1 + r * n_seg
        base1 = 1 + (r + 1) * n_seg
        for s in range(n_seg):
            s1 = (s + 1) % n_seg
            faces.append([base0 + s, base1 + s, base1 + s1])
            faces.append([base0 + s, base1 + s1, base0 + s1])
    for s in range(n_seg):  # pole fans
        s1 = (s + 1) % n_seg
        faces.append([0, 1 + s, 1 + s1])
        last = 1 + (n_ring - 1) * n_seg
        faces.append([len(v) - 1, last + s1, last + s])
    return v, np.array(faces, dtype=np.int32)


def smpl_sized_sphere():
    """A UV-sphere with *exactly* SMPL's vertex/face counts (6890 v, 13776 f):
    84 latitude rings x 82 segments + 2 poles.  Used so benchmarks exercise
    the precise shapes of BASELINE.md configs without shipping SMPL data."""
    v, f = _uv_sphere(82, 84)
    assert v.shape == (6890, 3) and f.shape == (13776, 3)
    return v, f


def synthetic_body_model(seed=0, n_betas=10, n_joints=24, template=None,
                         dtype=jnp.float32):
    """A well-formed random body model for tests and benchmarks.

    Joint centers are placed along a chain inside the body; skinning weights
    are a softmax over vertex-to-joint distances (smooth, convex); shape/pose
    blendshape magnitudes roughly match SMPL's (~cm scale).
    """
    rng = np.random.RandomState(seed)
    if template is None:
        v, f = smpl_sized_sphere()
        v = v * np.array([0.3, 0.2, 0.9])  # body-ish proportions, meters
    else:
        v, f = template
    n_v = v.shape[0]

    # kinematic chain: root at centroid, children spread along +z
    parents = [-1] + [max(0, j - 1 + (0 if j < 3 else rng.randint(-2, 1))) for j in range(1, n_joints)]
    z_span = np.linspace(v[:, 2].min(), v[:, 2].max(), n_joints)
    joint_centers = np.stack(
        [0.05 * rng.randn(n_joints), 0.05 * rng.randn(n_joints), z_span], axis=1
    )
    # joint regressor: normalized RBF of vertices around each center
    d2 = ((v[None, :, :] - joint_centers[:, None, :]) ** 2).sum(-1)
    reg = np.exp(-d2 / 0.02)
    joint_regressor = reg / reg.sum(axis=1, keepdims=True)
    # skinning weights: softmax over -distance to joints
    w = np.exp(-d2.T / 0.05)
    lbs_weights = w / w.sum(axis=1, keepdims=True)
    # smooth random blendshapes (low-frequency via joint-space mixing)
    shape_basis = reg.T @ rng.randn(n_joints, 3 * n_betas) * 0.5
    shapedirs = shape_basis.reshape(n_v, 3, n_betas) * 0.3
    posedirs = (reg.T @ rng.randn(n_joints, 3 * 9 * (n_joints - 1))).reshape(
        n_v, 3, 9 * (n_joints - 1)
    ) * 0.01

    return BodyModel(
        v_template=jnp.asarray(v, dtype),
        shapedirs=jnp.asarray(shapedirs, dtype),
        posedirs=jnp.asarray(posedirs, dtype),
        joint_regressor=jnp.asarray(joint_regressor, dtype),
        lbs_weights=jnp.asarray(lbs_weights, dtype),
        faces=jnp.asarray(f, jnp.int32),
        parents=tuple(parents),
    )


def _parametric_sphere(n_v_target):
    """A UV-sphere with exactly ``n_v_target`` vertices, proportioned like
    smpl_sized_sphere.  Used by the synthetic model-family constructors so
    each family exercises its real vertex count without shipping licensed
    template data.

    Builds the near-square rings*segs+2 grid not exceeding the target
    (n_seg closest to sqrt(target), so triangles stay well-proportioned
    like smpl_sized_sphere's 82x84 rather than a sliver needle), then adds
    the remainder — at most n_seg - 1 vertices — via centroid face splits
    (1 face -> 3, projected back to the sphere): exact counts even when
    n_v_target - 2 has no usable factorization (e.g. SMPL-X's 10475)."""
    root = float(np.sqrt(max(n_v_target - 2, 1)))
    best = None
    for n_seg in range(3, 400):
        n_ring = (n_v_target - 2) // n_seg
        if n_ring >= 3:
            if best is None or abs(n_seg - root) < abs(best[0] - root):
                best = (n_seg, n_ring)
    if best is None:
        raise ValueError("n_v_target too small: %d" % n_v_target)
    n_seg, n_ring = best
    v, f = _uv_sphere(n_seg, n_ring)
    faces = f.tolist()
    v = list(v)
    n_extra = n_v_target - len(v)
    stride = max(1, len(faces) // max(n_extra, 1))
    for k in range(n_extra):
        fi = (k * stride) % len(faces)
        a, b, c = faces[fi]
        centroid = (np.asarray(v[a]) + v[b] + v[c]) / 3.0
        centroid = centroid / np.linalg.norm(centroid)
        new = len(v)
        v.append(centroid)
        faces[fi] = [a, b, new]
        faces.append([b, c, new])
        faces.append([c, a, new])
    v = np.asarray(v)
    assert len(v) == n_v_target
    return v, np.array(faces, dtype=np.int32)


#: (vertices, joints, betas) of the SMPL-family architectures this module's
#: synthetic constructors reproduce; the real weight files load through
#: load_body_model_npz with the same shapes
MODEL_FAMILIES = {
    "smpl": (6890, 24, 10),
    "smplx": (10475, 55, 10),
    "flame": (5023, 5, 100),
    "mano": (778, 16, 10),
}


def synthetic_family_model(family, seed=0, dtype=jnp.float32):
    """A synthetic model with the exact (V, J, B) architecture of a named
    SMPL family member ("smpl", "smplx", "flame", "mano") — the model
    families the reference package is the substrate for (reference
    README.md:10-22).  Weights are synthesized (see synthetic_body_model);
    load real .npz weights with load_body_model_npz for production use.
    """
    try:
        n_v, n_joints, n_betas = MODEL_FAMILIES[family]
    except KeyError:
        raise ValueError(
            "unknown family %r (have %s)" % (family, sorted(MODEL_FAMILIES))
        ) from None
    if family == "smpl":
        template = None    # smpl_sized_sphere, exactly as before
    else:
        v, f = _parametric_sphere(n_v)
        scale = {"smplx": [0.3, 0.2, 0.9], "flame": [0.09, 0.12, 0.1],
                 "mano": [0.04, 0.09, 0.02]}[family]
        template = (v * np.array(scale), f)
    return synthetic_body_model(
        seed=seed, n_betas=n_betas, n_joints=n_joints, template=template,
        dtype=dtype,
    )


def save_body_model_npz(model, path):
    """Write a BodyModel as a standard SMPL-family .npz (the key set
    load_body_model_npz reads: v_template, shapedirs, posedirs,
    J_regressor, weights, f, kintree_table) — lets synthetic or converted
    models round-trip through the ecosystem's interchange format."""
    parents = np.asarray(model.parents, np.int64)
    kintree = np.stack([parents, np.arange(len(parents))])
    kintree[0, 0] = 2 ** 32 - 1   # SMPL files mark the root this way
    extras = {}
    if model.hands_components is not None:
        extras["hands_components"] = np.asarray(model.hands_components)
    if model.hands_mean is not None:
        extras["hands_mean"] = np.asarray(model.hands_mean)
    np.savez(
        path,
        v_template=np.asarray(model.v_template),
        shapedirs=np.asarray(model.shapedirs),
        posedirs=np.asarray(model.posedirs),
        J_regressor=np.asarray(model.joint_regressor),
        weights=np.asarray(model.lbs_weights),
        f=np.asarray(model.faces),
        kintree_table=kintree,
        **extras,
    )


def _densify(name, value):
    """A plain numeric ndarray from whatever a released SMPL-family file
    stored under ``name``.

    Real SMPL/SMPL-X/FLAME/MANO distributions (pickled chumpy models
    converted to .npz with varying care) wrap arrays three ways: 0-d
    object arrays holding a scipy.sparse matrix (J_regressor in the
    original SMPL pkl is scipy CSC), chumpy ``Ch`` objects (read through
    their ``.r`` dense view — note np.load still needs the pickled
    object's module importable to UNPICKLE it; the duck-typing only
    avoids depending on chumpy's API), and f64 payloads.  dtype
    conversion happens at the caller.
    """
    a = np.asarray(value)
    if a.dtype != object:
        return a
    obj = a.item() if a.ndim == 0 else value
    if hasattr(obj, "toarray"):            # scipy.sparse.*_matrix
        return np.asarray(obj.toarray())
    if hasattr(obj, "r"):                  # chumpy.Ch duck type
        return np.asarray(obj.r)
    try:
        # object array of equal-length rows (seen in hand-rolled exports)
        return np.asarray([np.asarray(row, np.float64) for row in obj])
    except (TypeError, ValueError):
        raise TypeError(
            "key %r holds %r, which is not convertible to a dense array"
            % (name, type(obj).__name__)
        ) from None


# keys as written by the official distributions, plus aliases seen in
# common conversions of the family files
_KEY_ALIASES = {
    "v_template": ("v_template",),
    "shapedirs": ("shapedirs",),
    "posedirs": ("posedirs",),
    "J_regressor": ("J_regressor",),
    "weights": ("weights", "lbs_weights"),
    "f": ("f", "faces"),
    "kintree_table": ("kintree_table",),
}


def _fetch(data, canonical):
    for key in _KEY_ALIASES[canonical]:
        if key in data:
            return _densify(key, data[key])
    raise KeyError(
        "SMPL-family file is missing %r (accepted aliases: %s; file keys: "
        "%s)" % (canonical, list(_KEY_ALIASES[canonical]),
                 sorted(getattr(data, "files", data.keys())))
    )


def load_body_model_npz(path, dtype=jnp.float32):
    """Load a SMPL-family .npz (canonical keys: v_template, shapedirs,
    posedirs, J_regressor, weights, f, kintree_table).

    Tolerates the layout quirks of real released files: scipy-sparse
    J_regressor, chumpy object arrays (densified via ``.r`` — the pickled
    module must still be importable for np.load to unpickle them), f64
    payloads (cast to ``dtype``), uint32 root sentinel in kintree_table,
    ``faces``/``lbs_weights`` key aliases, and extra keys (MANO's
    ``hands_components``/``hands_mean`` pose-PCA basis is kept on the
    model; anything else — including SMPL-H's per-hand
    ``hands_components{l,r}`` — is ignored).  doc/models.md lists the
    family files known to load.
    """
    data = np.load(path, allow_pickle=True)
    kintree = _fetch(data, "kintree_table")
    parents = kintree[0].astype(np.int64)
    parents[0] = -1
    posedirs = _fetch(data, "posedirs")
    if posedirs.ndim == 3:
        posedirs = posedirs.reshape(posedirs.shape[0], 3, -1)
    shapedirs = _fetch(data, "shapedirs")
    if shapedirs.ndim == 2:                # some exports flatten (V*3, B)
        shapedirs = shapedirs.reshape(-1, 3, shapedirs.shape[-1])
    pca = {}
    if "hands_components" in data:
        pca["hands_components"] = jnp.asarray(
            _densify("hands_components", data["hands_components"]), dtype
        )
        if "hands_mean" in data:
            pca["hands_mean"] = jnp.asarray(
                _densify("hands_mean", data["hands_mean"]), dtype
            )
    return BodyModel(
        v_template=jnp.asarray(_fetch(data, "v_template"), dtype),
        shapedirs=jnp.asarray(shapedirs, dtype),
        posedirs=jnp.asarray(posedirs, dtype),
        joint_regressor=jnp.asarray(_fetch(data, "J_regressor"), dtype),
        lbs_weights=jnp.asarray(_fetch(data, "weights"), dtype),
        faces=jnp.asarray(
            _fetch(data, "f").astype(np.int64), jnp.int32
        ),
        parents=tuple(int(p) for p in parents),
        **pca,
    )


def mano_pose_from_pca(model, coeffs, flat_hand_mean=False):
    """(..., n) MANO pose-PCA coefficients -> (..., J, 3) axis-angle pose.

    The released MANO/SMPL-H files parameterize the 45-dim hand pose by a
    full-rank PCA basis (``hands_components`` (45, 45), ``hands_mean``
    (45,)); callers use the first ``n <= 45`` components ("reduced
    components" — the official mano package's ``ncomps``).  The global
    rotation (joint 0) is returned as zeros; set it on the result.
    """
    if model.hands_components is None:
        raise ValueError("model has no pose-PCA basis (hands_components)")
    coeffs = jnp.asarray(coeffs, model.hands_components.dtype)
    n = coeffs.shape[-1]
    flat = jnp.einsum(
        "...n,nk->...k", coeffs, model.hands_components[:n]
    )
    if not flat_hand_mean and model.hands_mean is not None:
        flat = flat + model.hands_mean
    flat = flat.reshape(coeffs.shape[:-1] + (-1, 3))
    root = jnp.zeros(flat.shape[:-2] + (1, 3), flat.dtype)
    return jnp.concatenate([root, flat], axis=-2)
