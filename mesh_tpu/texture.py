"""Texture handling (reference mesh/texture.py).

Image IO stays host-side (cv2, BGR order, pow2-size snapping); the per-vertex
UV gather `texture_rgb_vec` is vectorized numpy as in the reference
(texture.py:99-107).
"""

import os

import numpy as np

__all__ = ["texture_coordinates_by_vertex"]


def texture_coordinates_by_vertex(self):
    tc_by_vertex = [[] for _ in range(len(self.v))]
    for i, face in enumerate(np.asarray(self.f)):
        for j in (0, 1, 2):
            tc_by_vertex[face[j]].append(np.asarray(self.vt)[np.asarray(self.ft)[i][j]])
    return tc_by_vertex


def reload_texture_image(self):
    import cv2

    # loaded height x width x 3, BGR order (reference texture.py:26-36)
    self._texture_image = (
        cv2.imread(self.texture_filepath) if self.texture_filepath else None
    )
    texture_sizes = [32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384]
    im = self._texture_image
    if im is not None and (
        im.shape[0] != im.shape[1] or im.shape[0] not in texture_sizes
    ):
        closest = (np.abs(np.array(texture_sizes) - max(im.shape))).argmin()
        sz = texture_sizes[closest]
        self._texture_image = cv2.resize(im, (sz, sz))


def load_texture(self, texture_version):
    """Load a numbered textured-template OBJ from the package texture_path
    (reference texture.py:39-55)."""
    from . import texture_path
    from .mesh import Mesh

    lowres = os.path.join(
        texture_path, "textured_template_low_v%d.obj" % texture_version
    )
    highres = os.path.join(
        texture_path, "textured_template_high_v%d.obj" % texture_version
    )
    mesh_with_texture = Mesh(filename=lowres)
    if not np.all(mesh_with_texture.f.shape == self.f.shape):
        mesh_with_texture = Mesh(filename=highres)
    self.transfer_texture(mesh_with_texture)


def transfer_texture(self, mesh_with_texture):
    """Copy vt/ft from a topology-matched mesh, tolerating flipped or
    reordered faces (reference texture.py:58-87)."""
    if not np.all(mesh_with_texture.f.shape == self.f.shape):
        raise ValueError("Mesh topology mismatch")

    self.vt = np.asarray(mesh_with_texture.vt).copy()
    self.ft = np.asarray(mesh_with_texture.ft).copy()
    src_f = np.asarray(mesh_with_texture.f)
    dst_f = np.asarray(self.f)

    if not np.all(src_f == dst_f):
        if np.all(src_f == np.fliplr(dst_f)):
            self.ft = np.fliplr(self.ft)
        else:
            face_mapping = {}
            for ii, face in enumerate(dst_f):
                face_mapping[tuple(sorted(face))] = ii
            new_ft = np.zeros(dst_f.shape, dtype=np.uint32)
            for face, ft_row in zip(src_f, np.asarray(mesh_with_texture.ft)):
                key = tuple(sorted(face))
                if key not in face_mapping:
                    raise ValueError("Mesh topology mismatch")
                target = face_mapping[key]
                ids = np.array(
                    [np.where(dst_f[target] == f_id)[0][0] for f_id in face]
                )
                new_ft[target] = ft_row[ids]
            self.ft = new_ft

    self.texture_filepath = mesh_with_texture.texture_filepath
    self._texture_image = None


def set_texture_image(self, path_to_texture):
    self.texture_filepath = path_to_texture


def texture_rgb(self, texture_coordinate):
    h, w = np.array(self.texture_image.shape[:2]) - 1
    return np.double(
        self.texture_image[int(h * (1.0 - texture_coordinate[1]))][
            int(w * texture_coordinate[0])
        ]
    )[::-1]


def texture_rgb_vec(self, texture_coordinates):
    """Flat-index gather of RGB values for N texture coords, clipped to [0,1]
    (reference texture.py:99-107)."""
    h, w = np.array(self.texture_image.shape[:2]) - 1
    n_ch = self.texture_image.shape[2]
    d1 = (h * (1.0 - np.clip(texture_coordinates[:, 1], 0, 1))).astype(np.int64)
    d0 = (w * np.clip(texture_coordinates[:, 0], 0, 1)).astype(np.int64)
    flat_texture = self.texture_image.flatten()
    indices = np.hstack(
        [
            ((d1 * (w + 1) * n_ch) + (d0 * n_ch) + (2 - i)).reshape(-1, 1)
            for i in range(n_ch)
        ]
    )
    return flat_texture[indices]
