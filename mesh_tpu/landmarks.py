"""Named landmark points on a mesh (reference mesh/landmarks.py).

Landmarks live in two forms: raw xyz (`landm_raw_xyz`), and mesh-attached
forms that survive deformation — nearest vertex indices (`landm`) and
barycentric regressors (`landm_regressors`: name -> (3 vertex indices,
3 coefficients)).  Recomputing indices runs the TPU closest-point kernels
through the Mesh facade (landmarks.py:45-65 in the reference runs the C++
AABB stack here).
"""

import logging

import numpy as np

from .utils import col, sparse

log = logging.getLogger(__name__)


def landm_xyz_linear_transform(self, ordering=None):
    """Sparse (3L x 3V) matrix mapping flattened vertices to flattened
    landmark locations (reference landmarks.py:15-33)."""
    landmark_order = ordering if ordering else self.landm_names
    if not landmark_order:
        return np.zeros((0, 0))
    if hasattr(self, "landm_regressors") and self.landm_regressors:
        coeffs = np.hstack([self.landm_regressors[name][1] for name in landmark_order])
        indices = np.hstack([self.landm_regressors[name][0] for name in landmark_order])
        column_indices = np.hstack(
            [col(3 * indices + i) for i in range(3)]
        ).flatten()
        row_indices = np.hstack(
            [
                [3 * index, 3 * index + 1, 3 * index + 2]
                * len(self.landm_regressors[landmark_order[index]][0])
                for index in np.arange(len(landmark_order))
            ]
        )
        values = np.hstack([col(coeffs) for _ in range(3)]).flatten()
        return sparse(row_indices, column_indices, values,
                      3 * len(landmark_order), 3 * self.v.shape[0])
    elif hasattr(self, "landm"):
        indices = np.array([self.landm[name] for name in landmark_order])
        column_indices = np.hstack(
            [col(3 * indices + i) for i in range(3)]
        ).flatten()
        row_indices = np.arange(3 * len(landmark_order))
        return sparse(row_indices, column_indices, np.ones(len(column_indices)),
                      3 * len(landmark_order), 3 * self.v.shape[0])
    return np.zeros((0, 0))


def recompute_landmark_indices(self, landmark_fname=None, safe_mode=True):
    """Snap raw xyz landmarks to the mesh: nearest vertex index + barycentric
    regressor on the nearest face (reference landmarks.py:45-65)."""
    filtered_landmarks = dict(
        filter(
            lambda e: e[1] != [0.0, 0.0, 0.0],
            self.landm_raw_xyz.items(),
        )
        if (landmark_fname and safe_mode)
        else self.landm_raw_xyz.items()
    )
    if len(filtered_landmarks) != len(self.landm_raw_xyz):
        log.warning(
            "%d landmarks in file %s are positioned at (0.0, 0.0, 0.0)"
            " and were ignored",
            len(self.landm_raw_xyz) - len(filtered_landmarks), landmark_fname,
        )
    self.landm = {}
    self.landm_regressors = {}
    if filtered_landmarks:
        names = list(filtered_landmarks.keys())
        xyz = np.array(list(filtered_landmarks.values()), dtype=np.float64).reshape(-1, 3)
        closest, _ = self.closest_vertices(xyz)
        self.landm = dict(zip(names, np.asarray(closest).tolist()))
        if len(self.f):
            face_indices, closest_points = self.closest_faces_and_points(xyz)
            vertex_indices, coefficients = self.barycentric_coordinates_for_points(
                closest_points, face_indices
            )
            self.landm_regressors = dict(
                (name, (vertex_indices[i], coefficients[i]))
                for i, name in enumerate(names)
            )
        else:
            self.landm_regressors = dict(
                (name, (np.array([self.landm[name]]), np.array([1.0])))
                for name in names
            )


def landm_xyz(self, ordering=None):
    """Current landmark locations as a name -> xyz dict, evaluated through
    the sparse regressor so they track vertex deformation (reference
    landmarks.py:37-42)."""
    order = ordering if ordering else self.landm_names
    if not order:
        return {}
    locations = (
        landm_xyz_linear_transform(self, order) * np.asarray(self.v).flatten()
    ).reshape(-1, 3)
    return dict(zip(order, locations))


def set_landmarks_from_xyz(self, landm_raw_xyz):
    self.landm_raw_xyz = (
        landm_raw_xyz
        if hasattr(landm_raw_xyz, "keys")
        else dict((str(i), l) for i, l in enumerate(landm_raw_xyz))
    )
    self.recompute_landmark_indices()


def is_vertex(x):
    return hasattr(x, "__len__") and len(x) == 3


def is_index(x):
    return isinstance(x, (int, np.integer))


def set_landmarks_from_raw(self, landmarks):
    """Accept dicts or lists of xyz triples or vertex indices
    (reference landmarks.py:81-102)."""
    landmarks = (
        landmarks
        if hasattr(landmarks, "keys")
        else dict((str(i), l) for i, l in enumerate(landmarks))
    )
    if all(is_vertex(x) for x in landmarks.values()):
        landmarks = dict((i, np.array(l)) for i, l in landmarks.items())
        set_landmarks_from_xyz(self, landmarks)
    elif all(is_index(x) for x in landmarks.values()):
        self.landm = landmarks
        self.recompute_landmark_xyz()
    else:
        raise ValueError("Can't parse landmarks")
