"""Hermetic perf observability: the staged, watchdogged bench harness
plus the jax-free ``perfcheck`` regression gate (doc/benchmarking.md).

Why this module exists: the monolithic bench bootstrap could be wedged
by a single blocked backend init for longer than the whole gate budget
(BENCH_r02-r05 all read ``backend probe hung > 150s``; the watchdog log
recorded a >26.5h continuous wedge).  The harness here decomposes a
bench run into declarative **stages**, each executed in its own
subprocess under a per-stage timeout, so:

- a hang in stage k can never destroy stages 1..k-1 — every stage's
  record is persisted incrementally to ``bench_partial.json`` (atomic
  temp+rename) the moment the stage ends;
- the orchestrator itself can never wedge: the child wait runs inside
  ``call_with_timeout`` (the wedge-proof abandoned-attempt-thread
  pattern extracted from serve/deadline.py) with a grace margin on top
  of the subprocess timeout, and a timed-out child is reaped by
  ``reap_child`` (terminate -> poll -> kill -> poll -> abandon — never
  a blocking pipe read);
- the first hang/crash auto-dumps ONE flight-recorder incident tagged
  ``bench_stage_hang`` (stage name, timeout, statuses so far, partial
  path), and every stage outcome lands in the
  ``mesh_tpu_bench_stage_{ok,hung,crashed,skipped}_total`` counters and
  the ``mesh_tpu_bench_stage_seconds`` histogram.

``perfcheck`` is the read side: stdlib-only comparison of a saved bench
JSON (final record or the partial file) against ``bench_last_good.json``
and the committed CPU-proxy golden, with tolerance bands, exiting
nonzero on regression — runnable while the chip is wedged, which is
exactly when it is needed.

Import cost: stdlib only; jax is never touched (the stages that need it
run in child processes).
"""

import json
import os
import subprocess
import threading
import time
from collections import OrderedDict

from ..errors import DeadlineExceeded
from .clock import monotonic, wall
from .metrics import REGISTRY
from .recorder import get_recorder

__all__ = [
    "StageSpec", "StageResult", "call_with_timeout", "reap_child",
    "run_stages", "write_partial", "read_bench_json", "extract_records",
    "perfcheck", "PARTIAL_SCHEMA_VERSION", "INCIDENT_REASON",
    "FAULT_ENV", "PARTIAL_ENV", "TIMEOUT_ENV_PREFIX",
]

#: incident reason tag for any stage hang/crash (doc/benchmarking.md)
INCIDENT_REASON = "bench_stage_hang"

#: fault injection: ``<stage>:hang`` / ``<stage>:crash`` / ``<stage>:error``
#: makes that stage's child wedge / exit nonzero / raise (tests only)
FAULT_ENV = "MESH_TPU_BENCH_FAULT"

#: relocates the incremental partial-results file
PARTIAL_ENV = "MESH_TPU_BENCH_PARTIAL"

#: per-stage timeout override: MESH_TPU_BENCH_TIMEOUT_<STAGE> seconds
TIMEOUT_ENV_PREFIX = "MESH_TPU_BENCH_TIMEOUT_"

#: bench_partial.json schema (bump on breaking shape changes)
PARTIAL_SCHEMA_VERSION = 1

#: orchestrator-side margin on top of the subprocess timeout: covers
#: spawn latency plus a full reap escalation before the attempt thread
#: itself is declared wedged and abandoned
_ATTEMPT_GRACE_S = 30.0


def call_with_timeout(fn, timeout):
    """Run ``fn()`` on a daemon helper thread, waiting at most
    ``timeout`` seconds.  Raises DeadlineExceeded on timeout — the stuck
    thread is abandoned, not joined, because the whole point is that a
    wedged device call may never return.

    (Extracted from serve/deadline.py, which re-exports it: the serving
    ladder's rung attempts and the bench harness's stage attempts share
    this one wedge-proof primitive.)
    """
    box = {}
    done = threading.Event()

    def _run():
        try:
            box["result"] = fn()
        except BaseException as e:     # noqa: BLE001 — re-raised below
            box["error"] = e
        finally:
            done.set()

    worker = threading.Thread(target=_run, name="mesh-tpu-attempt",
                              daemon=True)
    worker.start()
    if not done.wait(timeout=max(float(timeout), 0.0)):
        raise DeadlineExceeded(
            "rung call still running after %.3fs slice" % timeout)
    if "error" in box:
        raise box["error"]
    return box["result"]


def reap_child(proc, term_grace_s=3.0, kill_grace_s=10.0,
               clock=monotonic, sleep=time.sleep):
    """Escalating child teardown that can never block the caller:
    SIGTERM -> bounded poll -> SIGKILL -> bounded poll -> abandon.

    Every wait is ``poll()`` (WNOHANG — it also reaps the zombie);
    nothing here reads a pipe, because a pipe held open by a wedged
    child (or its grandchild) is exactly what made the old
    ``kill(); communicate(timeout=10)`` teardown block.  Returns
    ``"terminated"`` / ``"killed"`` / ``"abandoned"`` — abandoned means
    the child survived SIGKILL (uninterruptible device I/O); the caller
    moves on and init never blocks on it again.
    """
    if proc.poll() is not None:
        return "terminated"
    try:
        proc.terminate()
    except OSError:
        pass
    deadline = clock() + term_grace_s
    while clock() < deadline:
        if proc.poll() is not None:
            return "terminated"
        sleep(0.05)
    try:
        proc.kill()
    except OSError:
        pass
    deadline = clock() + kill_grace_s
    while clock() < deadline:
        if proc.poll() is not None:
            return "killed"
        sleep(0.05)
    return "abandoned"


class StageSpec(object):
    """One declarative bench stage.

    :param name: stage name (also the child's ``--stage`` argument and
        the ``stage=`` metric label).
    :param argv: child command line; the stage runs subprocess-isolated
        so a wedge dies with the child, not the orchestrator.
    :param timeout_s: per-stage budget; past it the child is reaped and
        the stage is ``hung``.
    :param requires_backend: stage needs the (possibly wedged)
        accelerator backend; skipped once the backend is known-bad.
    :param gate: a non-ok outcome marks the backend bad (the probe).
    :param env: extra child environment (e.g. the proxy stage's
        ``JAX_PLATFORMS=cpu``, which keeps it off the wedged tunnel).
    """

    __slots__ = ("name", "argv", "timeout_s", "requires_backend", "gate",
                 "env")

    def __init__(self, name, argv, timeout_s, requires_backend=False,
                 gate=False, env=None):
        self.name = name
        self.argv = list(argv)
        self.timeout_s = float(timeout_s)
        self.requires_backend = bool(requires_backend)
        self.gate = bool(gate)
        self.env = dict(env) if env else {}


class StageResult(object):
    """Outcome of one stage attempt: ``ok`` / ``hung`` / ``crashed`` /
    ``skipped``, elapsed wall time, the stage's JSON record (ok only),
    and the error string otherwise."""

    __slots__ = ("name", "status", "elapsed_s", "timeout_s", "record",
                 "error")

    def __init__(self, name, status, elapsed_s, timeout_s, record=None,
                 error=None):
        self.name = name
        self.status = status
        self.elapsed_s = elapsed_s
        self.timeout_s = timeout_s
        self.record = record
        self.error = error

    @property
    def ok(self):
        return self.status == "ok"

    def to_json(self):
        out = {
            "status": self.status,
            "elapsed_s": round(self.elapsed_s, 3),
            "timeout_s": self.timeout_s,
            "record": self.record,
        }
        if self.error:
            out["error"] = self.error
        return out


def write_partial(path, state):
    """Atomically persist the partial-results state (temp + rename so a
    crash mid-write — the wedge modes this file exists for — can never
    clobber the previous good copy)."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as fh:
            json.dump(state, fh, indent=1, default=str)
            fh.write("\n")
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _last_json_line(text):
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _stage_counter(status):
    # one literal name per status so the metrics-doc lint sees them all
    names = {
        "ok": "mesh_tpu_bench_stage_ok_total",
        "hung": "mesh_tpu_bench_stage_hung_total",
        "crashed": "mesh_tpu_bench_stage_crashed_total",
        "skipped": "mesh_tpu_bench_stage_skipped_total",
    }
    help_text = "bench harness stages by outcome (label: stage)"
    REGISTRY.counter("mesh_tpu_bench_stage_ok_total", help_text)
    REGISTRY.counter("mesh_tpu_bench_stage_hung_total", help_text)
    REGISTRY.counter("mesh_tpu_bench_stage_crashed_total", help_text)
    REGISTRY.counter("mesh_tpu_bench_stage_skipped_total", help_text)
    return REGISTRY.get(names[status])


def _stage_histogram():
    return REGISTRY.histogram(
        "mesh_tpu_bench_stage_seconds",
        "wall seconds per bench stage attempt (label: stage)")


def _run_one(spec, clock, sleep, popen, log):
    """One subprocess-isolated stage attempt under its timeout, with the
    call_with_timeout backstop around the whole spawn+wait+reap path."""
    t0 = clock()

    def attempt():
        env = dict(os.environ)
        env.update(spec.env)
        proc = popen(spec.argv, stdout=subprocess.PIPE,
                     stderr=subprocess.PIPE, text=True, env=env)
        try:
            out, err = proc.communicate(timeout=spec.timeout_s)
        except subprocess.TimeoutExpired:
            how = reap_child(proc, clock=clock, sleep=sleep)
            return ("hung", None,
                    "stage still running after %.1fs budget (child %s)"
                    % (spec.timeout_s, how))
        if proc.returncode != 0:
            tail = (err or "").strip().splitlines()
            return ("crashed", None, "stage exited %d: %s" % (
                proc.returncode, tail[-1] if tail else "no stderr"))
        record = _last_json_line(out)
        if record is None:
            return ("crashed", None, "stage exited 0 without a JSON record")
        return ("ok", record, None)

    try:
        status, record, error = call_with_timeout(
            attempt, spec.timeout_s + _ATTEMPT_GRACE_S)
    except DeadlineExceeded:
        # even the reap path wedged; the attempt thread is abandoned
        status, record, error = "hung", None, (
            "stage attempt still wedged %.0fs past its %.1fs budget "
            "(attempt thread abandoned)"
            % (_ATTEMPT_GRACE_S, spec.timeout_s))
    except Exception as e:          # noqa: BLE001 — spawn failures etc.
        status, record, error = "crashed", None, "%s: %s" % (
            type(e).__name__, e)
    if error:
        log("stage %s %s: %s" % (spec.name, status, error))
    return StageResult(spec.name, status, clock() - t0, spec.timeout_s,
                       record, error)


def run_stages(specs, partial_path, clock=monotonic, sleep=time.sleep,
               popen=subprocess.Popen, recorder=None, log=None):
    """Execute ``specs`` in order; returns ``OrderedDict`` name ->
    StageResult.

    Contract (the measurement floor every perf PR stands on):

    - each stage runs in its own child under its own timeout; the
      orchestrator never waits unboundedly on anything;
    - after EVERY stage the partial state lands in ``partial_path`` —
      a hang in stage k never destroys stages 1..k-1;
    - a failed ``gate`` stage, or a hung backend stage, marks the
      backend bad: later ``requires_backend`` stages are skipped
      (re-touching a wedged tunnel just burns their budgets), while
      backend-free stages (the CPU-interpreter proxy) still run;
    - the FIRST hang/crash dumps exactly one ``bench_stage_hang``
      incident via the flight recorder (later failures only ring-record,
      so a fully wedged run produces one forensic file, not a pile).
    """
    if log is None:
        log = lambda msg: None      # noqa: E731 — quiet default
    recorder = recorder or get_recorder()
    results = OrderedDict()
    state = {
        "schema_version": PARTIAL_SCHEMA_VERSION,
        "kind": "bench_partial",
        "started_utc": wall(),
        "order": [s.name for s in specs],
        "stages": {},
    }
    write_partial(partial_path, state)
    backend_ok = True
    incident_dumped = False
    hist = _stage_histogram()
    for spec in specs:
        if spec.requires_backend and not backend_ok:
            res = StageResult(spec.name, "skipped", 0.0, spec.timeout_s,
                              error="backend unavailable (gate/hang "
                                    "earlier in the pipeline)")
        else:
            log("stage %s (budget %.0fs)..." % (spec.name, spec.timeout_s))
            res = _run_one(spec, clock, sleep, popen, log)
            hist.observe(res.elapsed_s, stage=spec.name)
        results[spec.name] = res
        _stage_counter(res.status).inc(stage=spec.name)
        recorder.record("bench.stage", stage=spec.name, status=res.status,
                        elapsed_s=round(res.elapsed_s, 3),
                        timeout_s=spec.timeout_s)
        if spec.gate and (res.status != "ok"
                          or (res.record or {}).get("backend_ok") is False):
            backend_ok = False
        if res.status == "hung" and spec.requires_backend:
            # a hang INSIDE a backend stage means the tunnel wedged
            # mid-run; later backend stages would hang the same way
            backend_ok = False
        if res.status in ("hung", "crashed") and not incident_dumped:
            recorder.trigger(INCIDENT_REASON, context={
                "stage": spec.name,
                "status": res.status,
                "timeout_s": spec.timeout_s,
                "elapsed_s": round(res.elapsed_s, 3),
                "error": res.error,
                "completed": [n for n, r in results.items() if r.ok],
                "partial_path": partial_path,
            }, force=True)
            incident_dumped = True
        state["stages"][spec.name] = res.to_json()
        write_partial(partial_path, state)
    return results


# ---------------------------------------------------------------------------
# perfcheck: the jax-free regression gate


def read_bench_json(path):
    """Load a bench JSON file: either the one-line final record
    ``python bench.py`` prints, or the incremental ``bench_partial.json``
    the staged harness maintains."""
    with open(path) as fh:
        text = fh.read()
    doc = _last_json_line(text)
    if doc is None:
        doc = json.loads(text)
    return doc


def extract_records(doc):
    """Normalize either bench JSON shape into ``{"headline": rec|None,
    "proxy": rec|None, "accel": rec|None, "stream": rec|None,
    "mxu": rec|None, "store": rec|None, "tuner": rec|None,
    "replay": rec|None, "fleet": rec|None, "anim": rec|None,
    "trace": rec|None, "stages": {...}|None}``.

    The headline slot is only filled by a FRESH measurement — a
    ``stale: true`` envelope (last-good value republished while the
    tunnel was wedged) is deliberately dropped here, so stale records
    can neither pass nor fail a regression gate.
    """
    headline = None
    proxy = None
    accel = None
    stream = None
    mxu = None
    store = None
    tuner = None
    replay = None
    fleet = None
    anim = None
    trace = None
    stages = None
    if doc.get("kind") == "bench_partial":
        stages = doc.get("stages") or {}
        cp = stages.get("closest_point") or {}
        if cp.get("status") == "ok":
            headline = cp.get("record")
        px = stages.get("pallas_proxy") or {}
        if px.get("status") == "ok":
            proxy = px.get("record")
        ax = stages.get("accel_proxy") or {}
        if ax.get("status") == "ok":
            accel = ax.get("record")
        st = stages.get("accel_stream_proxy") or {}
        if st.get("status") == "ok":
            stream = st.get("record")
        mx = stages.get("mxu_proxy") or {}
        if mx.get("status") == "ok":
            mxu = mx.get("record")
        sc = stages.get("store_cold_start") or {}
        if sc.get("status") == "ok":
            store = sc.get("record")
        tc = stages.get("tuner_convergence") or {}
        if tc.get("status") == "ok":
            tuner = tc.get("record")
        rp = stages.get("replay_proxy") or {}
        if rp.get("status") == "ok":
            replay = rp.get("record")
        fl = stages.get("fleet_proxy") or {}
        if fl.get("status") == "ok":
            fleet = fl.get("record")
        an = stages.get("anim_proxy") or {}
        if an.get("status") == "ok":
            anim = an.get("record")
        tp = stages.get("trace_proxy") or {}
        if tp.get("status") == "ok":
            trace = tp.get("record")
    else:
        if doc.get("value") is not None and not doc.get("stale"):
            headline = doc
        prox = doc.get("proxy")
        if isinstance(prox, dict) and prox.get("value") is not None:
            proxy = prox
        acc = doc.get("accel")
        if isinstance(acc, dict) and acc.get("value") is not None:
            accel = acc
        stm = doc.get("stream")
        if isinstance(stm, dict) and stm.get("value") is not None:
            stream = stm
        mx = doc.get("mxu")
        if isinstance(mx, dict) and mx.get("value") is not None:
            mxu = mx
        sto = doc.get("store")
        if isinstance(sto, dict) and sto.get("value") is not None:
            store = sto
        tun = doc.get("tuner")
        if isinstance(tun, dict) and tun.get("value") is not None:
            tuner = tun
        rp = doc.get("replay")
        if isinstance(rp, dict) and rp.get("value") is not None:
            replay = rp
        fl = doc.get("fleet")
        if isinstance(fl, dict) and fl.get("value") is not None:
            fleet = fl
        an = doc.get("anim")
        if isinstance(an, dict) and an.get("value") is not None:
            anim = an
        tp = doc.get("trace")
        if isinstance(tp, dict) and tp.get("value") is not None:
            trace = tp
        stages = doc.get("stages")
    return {"headline": headline, "proxy": proxy, "accel": accel,
            "stream": stream, "mxu": mxu, "store": store,
            "tuner": tuner, "replay": replay, "fleet": fleet,
            "anim": anim, "trace": trace, "stages": stages}


def perfcheck(doc, baseline=None, proxy_golden=None, proxy_tol=0.5,
              headline_tol=0.2, flops_tol=0.25, accel_golden=None,
              accel_tol=0.05, stream_golden=None, stream_tol=0.05,
              store_golden=None, store_tol=0.6, tuner_golden=None,
              tuner_tol=0.25, mxu_golden=None, mxu_tol=0.2,
              replay_golden=None, replay_tol=0.0,
              fleet_golden=None, fleet_tol=0.05,
              anim_golden=None, anim_tol=0.2,
              trace_golden=None, trace_tol=0.0):
    """Compare a bench JSON against the last-good baseline and the
    committed proxy golden.  Returns ``(rc, lines)`` — rc 0 when nothing
    regressed beyond its tolerance band, 1 on regression (including a
    missing proxy metric when a golden exists: the proxy is the number
    that must survive a wedge).

    Tolerances are one-sided fractions of the baseline: the candidate
    fails when it is below ``baseline * (1 - tol)`` (faster never
    fails).  HLO cost-model FLOPs are the exception — deterministic, so
    they fail in the *upward* direction (``> golden * (1 + flops_tol)``:
    the compiled algorithm got more expensive).

    ``accel_golden`` grades the accel_proxy stage's pair-tests-skipped
    ratio the same one-sided way.  The ratio is deterministic (fixed
    mesh, fixed queries, exact traversal), so its band is tight
    (``accel_tol`` default 5%) and a checksum drift is a hard FAIL —
    a changed checksum means the index returned different answers,
    which no tolerance can excuse.  ``stream_golden``/``stream_tol``
    grade the accel_stream_proxy stage (the DMA-streamed rope kernel's
    chip-free twin) under the identical contract.

    ``store_golden`` grades the store_cold_start stage: its value is
    the SPEEDUP of side-car open+first-query over rebuild-from-source
    (>1 means the side-car wins).  The band floor is
    ``max(golden * (1 - store_tol), 1.0)`` — wide (disk + interpreter
    timing), but never below 1.0, because a side-car that loses to
    rebuilding is a broken cold-start contract regardless of what the
    golden said.  Checksum drift is a hard FAIL (the side-car must be
    bit-identical to the built index's answers).

    ``mxu_golden`` grades the mxu_proxy stage: its value is the
    VPU-to-MXU-repair throughput ratio (>1 means the dot-product
    reformulation wins).  The band floor is
    ``max(golden * (1 - mxu_tol), 1.5)`` — interpreter timing is noisy,
    but a reformulation that stops clearing 1.5x has lost its reason to
    exist regardless of what the golden said.  Checksum drift is a hard
    FAIL (the repair pipeline must return the dense kernel's exact
    answers), and the repair RATE fails in the *upward* direction
    (``> golden * (1 + mxu_tol)``: the bf16 screen stopped pruning,
    which timing noise could otherwise hide).

    ``tuner_golden`` grades the tuner_convergence stage: its value is
    the closed-loop controller's STEPS-TO-CONVERGE on a deterministic
    fake-clock scenario — smaller is better, so this band fails in the
    *upward* direction (``> golden * (1 + tuner_tol)``: the control
    policy got slower to settle).  The knob-trajectory checksum is
    deterministic (fake clock, synthetic load) and drift is a hard
    FAIL — a changed checksum means the controller made *different
    decisions*, which no steps tolerance can excuse.

    ``replay_golden`` grades the replay_proxy stage: its value is the
    ADMISSION COUNT of the synthesized adversarial trace replayed twice
    under a fake clock.  Both the count and the admission-sequence
    checksum are fully deterministic (seeded generators, virtual time),
    so the band is exact by default (``replay_tol`` 0) and checksum
    drift is a hard FAIL — a changed checksum means record/replay no
    longer reproduces the same admission sequence, which is the entire
    contract (doc/observability.md "Record/replay").

    ``fleet_golden`` grades the fleet_proxy stage (doc/fleet.md): its
    value is the routing AFFINITY fraction (requests landing on their
    digest's ring primary) with a floor of
    ``max(golden * (1 - fleet_tol), 0.95)`` — under stable membership
    the ring is deterministic, so anything off 1.0 is a routing bug,
    and 0.95 is the hard floor no golden can excuse.  The warm-hit
    rate gets the same one-sided band; the spill count is exact-matched
    (the stampede scenario is deterministic); the combined per-replica
    admission checksum is a hard FAIL on drift (placement stopped
    reproducing); and the AOT tier must show ``warm_hits >= 1`` plus a
    compile-stage speedup >= ``max(golden * 0.4, 1.0)`` (wide band —
    disk + interpreter timing — but a warm start that does not beat a
    cold compile is a broken executable tier regardless).

    ``anim_golden`` grades the anim_proxy stage (doc/animation.md): its
    value is the refit-over-rebuild SPEEDUP per animation frame (>1
    means skipping the Morton re-sort pays).  The band floor is
    ``max(golden * (1 - anim_tol), 1.0)`` — interpreter timing is
    noisy, but a refit that loses to rebuilding from scratch is a
    broken animation tier regardless of what the golden said.  The
    traversal checksum covers every frame's query answers through the
    refit index and drift is a hard FAIL — refit boxes are allowed to
    be looser than fresh-build boxes, the *answers* are not allowed to
    differ by one ulp.

    ``trace_golden`` grades the trace_proxy stage (doc/observability.md
    "Request identity"): its value is the number of requests whose
    minted ``request_id`` joined router admission, ledger row, and span
    evidence across a 3-replica in-process fleet under a seeded
    deterministic mix.  The retained-tail count (every forced
    deadline-miss/error request must keep a connected span tree) is
    exact-matched, and the join checksum — computed over run-stable
    facts (replica, tenant, seq, outcome, stage names, retained span
    shapes), never wall-clock ids — is a hard FAIL on drift: a changed
    checksum means the identity join stopped reproducing, which is the
    entire contract.  A candidate without a checksum is a hard FAIL
    (determinism unproven).
    """
    lines = []
    rc = 0
    recs = extract_records(doc)

    for slot, golden_doc, tol, stage_name, make_cmd in (
            ("accel", accel_golden, accel_tol, "accel_proxy",
             "make accel-golden"),
            ("stream", stream_golden, stream_tol, "accel_stream_proxy",
             "make accel-stream-golden")):
        gold = None
        if golden_doc:
            gold = (extract_records(golden_doc)[slot]
                    or (golden_doc
                        if golden_doc.get("value") is not None
                        else None))
        cand = recs[slot]
        if gold is not None:
            if cand is None:
                rc = 1
                lines.append(
                    "FAIL %s: candidate carries no %s record (a golden "
                    "exists — the chip-free index metric must always be "
                    "fresh)" % (slot, stage_name))
                continue
            floor = gold["value"] * (1.0 - tol)
            verdict = ("ok" if cand["value"] >= floor else "FAIL")
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s %s pair-tests-skipped ratio: %.4f vs golden %.4f "
                "(floor %.4f, tol %.0f%%)"
                % (verdict, slot, cand["value"], gold["value"],
                   floor, 100 * tol))
            cand_sum = cand.get("checksum")
            gold_sum = gold.get("checksum")
            if cand_sum is not None and gold_sum is not None:
                same = abs(cand_sum - gold_sum) <= 1e-6 * max(
                    1.0, abs(gold_sum))
                if not same:
                    rc = 1
                lines.append(
                    "%s %s checksum: %.6f vs golden %.6f (exact)"
                    % ("ok" if same else "FAIL", slot, cand_sum,
                       gold_sum))
        elif cand is not None:
            lines.append("note: %s record present but no golden to "
                         "compare against (record one: %s)"
                         % (slot, make_cmd))

    mxu_gold = None
    if mxu_golden:
        mxu_gold = (extract_records(mxu_golden)["mxu"]
                    or (mxu_golden
                        if mxu_golden.get("value") is not None
                        else None))
    cand_mxu = recs["mxu"]
    if mxu_gold is not None:
        if cand_mxu is None:
            rc = 1
            lines.append(
                "FAIL mxu: candidate carries no mxu_proxy record (a "
                "golden exists — the chip-free matmul-form metric must "
                "always be fresh)")
        else:
            floor = max(mxu_gold["value"] * (1.0 - mxu_tol), 1.5)
            verdict = "ok" if cand_mxu["value"] >= floor else "FAIL"
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s mxu proxy speedup (vpu/repair): %.3fx vs golden "
                "%.3fx (floor %.3fx, tol %.0f%%, hard floor 1.5x)"
                % (verdict, cand_mxu["value"], mxu_gold["value"],
                   floor, 100 * mxu_tol))
            cand_sum = cand_mxu.get("checksum")
            gold_sum = mxu_gold.get("checksum")
            if cand_sum is not None and gold_sum is not None:
                same = abs(cand_sum - gold_sum) <= 1e-6 * max(
                    1.0, abs(gold_sum))
                if not same:
                    rc = 1
                lines.append(
                    "%s mxu checksum: %.6f vs golden %.6f (exact)"
                    % ("ok" if same else "FAIL", cand_sum, gold_sum))
            cand_rate = cand_mxu.get("repair_rate")
            gold_rate = mxu_gold.get("repair_rate")
            if cand_rate is not None and gold_rate is not None:
                # higher repair rate = weaker bf16 screen; fails upward
                ceil = gold_rate * (1.0 + mxu_tol)
                verdict = "ok" if cand_rate <= ceil else "FAIL"
                if verdict == "FAIL":
                    rc = 1
                lines.append(
                    "%s mxu repair rate: %.4f vs golden %.4f "
                    "(ceiling %.4f, tol %.0f%%)"
                    % (verdict, cand_rate, gold_rate, ceil,
                       100 * mxu_tol))
    elif cand_mxu is not None:
        lines.append("note: mxu record present but no golden to "
                     "compare against (record one: make mxu-golden)")

    store_gold = None
    if store_golden:
        store_gold = (extract_records(store_golden)["store"]
                      or (store_golden
                          if store_golden.get("value") is not None
                          else None))
    cand_store = recs["store"]
    if store_gold is not None:
        if cand_store is None:
            rc = 1
            lines.append(
                "FAIL store: candidate carries no store_cold_start "
                "record (a golden exists — the chip-free cold-start "
                "metric must always be fresh)")
        else:
            floor = max(store_gold["value"] * (1.0 - store_tol), 1.0)
            verdict = "ok" if cand_store["value"] >= floor else "FAIL"
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s store cold-start speedup (rebuild/sidecar): %.3fx "
                "vs golden %.3fx (floor %.3fx, tol %.0f%%, hard floor "
                "1.0x)" % (verdict, cand_store["value"],
                           store_gold["value"], floor, 100 * store_tol))
            cand_sum = cand_store.get("checksum")
            gold_sum = store_gold.get("checksum")
            if cand_sum is not None and gold_sum is not None:
                same = abs(cand_sum - gold_sum) <= 1e-6 * max(
                    1.0, abs(gold_sum))
                if not same:
                    rc = 1
                lines.append(
                    "%s store checksum: %.6f vs golden %.6f (exact)"
                    % ("ok" if same else "FAIL", cand_sum, gold_sum))
    elif cand_store is not None:
        lines.append("note: store record present but no golden to "
                     "compare against (record one: make store-golden)")

    tuner_gold = None
    if tuner_golden:
        tuner_gold = (extract_records(tuner_golden)["tuner"]
                      or (tuner_golden
                          if tuner_golden.get("value") is not None
                          else None))
    cand_tuner = recs["tuner"]
    if tuner_gold is not None:
        if cand_tuner is None:
            rc = 1
            lines.append(
                "FAIL tuner: candidate carries no tuner_convergence "
                "record (a golden exists — the chip-free controller "
                "metric must always be fresh)")
        else:
            # smaller-is-better: steps-to-converge fails upward
            ceil = tuner_gold["value"] * (1.0 + tuner_tol)
            verdict = "ok" if cand_tuner["value"] <= ceil else "FAIL"
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s tuner steps-to-converge: %d vs golden %d "
                "(ceiling %.1f, tol %.0f%%)"
                % (verdict, cand_tuner["value"], tuner_gold["value"],
                   ceil, 100 * tuner_tol))
            cand_sum = cand_tuner.get("checksum")
            gold_sum = tuner_gold.get("checksum")
            if cand_sum is not None and gold_sum is not None:
                same = abs(cand_sum - gold_sum) <= 1e-6 * max(
                    1.0, abs(gold_sum))
                if not same:
                    rc = 1
                lines.append(
                    "%s tuner trajectory checksum: %.6f vs golden %.6f "
                    "(exact)" % ("ok" if same else "FAIL", cand_sum,
                                 gold_sum))
    elif cand_tuner is not None:
        lines.append("note: tuner record present but no golden to "
                     "compare against (record one: make tuner-golden)")

    replay_gold = None
    if replay_golden:
        replay_gold = (extract_records(replay_golden)["replay"]
                       or (replay_golden
                           if replay_golden.get("value") is not None
                           else None))
    cand_replay = recs["replay"]
    if replay_gold is not None:
        if cand_replay is None:
            rc = 1
            lines.append(
                "FAIL replay: candidate carries no replay_proxy record "
                "(a golden exists — the chip-free replay-determinism "
                "metric must always be fresh)")
        else:
            floor = replay_gold["value"] * (1.0 - replay_tol)
            verdict = "ok" if cand_replay["value"] >= floor else "FAIL"
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s replay admissions: %d vs golden %d (floor %.1f, "
                "tol %.0f%%)"
                % (verdict, cand_replay["value"], replay_gold["value"],
                   floor, 100 * replay_tol))
            cand_sum = cand_replay.get("checksum")
            gold_sum = replay_gold.get("checksum")
            if cand_sum is None:
                rc = 1
                lines.append(
                    "FAIL replay: candidate record carries no "
                    "admission-sequence checksum — determinism "
                    "unproven")
            elif gold_sum is not None:
                # CRC-style sums are exact integers: a relative
                # tolerance (the float-accumulation idiom above) would
                # swallow real drift at CRC magnitudes, so compare to
                # within float-representation noise only.
                same = abs(cand_sum - gold_sum) <= 1e-6
                if not same:
                    rc = 1
                lines.append(
                    "%s replay admission-sequence checksum: %.6f vs "
                    "golden %.6f (exact — drift means replay no longer "
                    "reproduces the same sequence)"
                    % ("ok" if same else "FAIL", cand_sum, gold_sum))
    elif cand_replay is not None:
        lines.append("note: replay record present but no golden to "
                     "compare against (record one: make replay-golden)")

    fleet_gold = None
    if fleet_golden:
        fleet_gold = (extract_records(fleet_golden)["fleet"]
                      or (fleet_golden
                          if fleet_golden.get("value") is not None
                          else None))
    cand_fleet = recs["fleet"]
    if fleet_gold is not None:
        if cand_fleet is None:
            rc = 1
            lines.append(
                "FAIL fleet: candidate carries no fleet_proxy record "
                "(a golden exists — the chip-free fleet-fabric contract "
                "must always be fresh)")
        else:
            floor = max(fleet_gold["value"] * (1.0 - fleet_tol), 0.95)
            verdict = "ok" if cand_fleet["value"] >= floor else "FAIL"
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s fleet routing affinity: %.4f vs golden %.4f "
                "(floor %.4f, tol %.0f%%, hard floor 0.95)"
                % (verdict, cand_fleet["value"], fleet_gold["value"],
                   floor, 100 * fleet_tol))
            cand_warm = cand_fleet.get("warm_hit_rate")
            gold_warm = fleet_gold.get("warm_hit_rate")
            if cand_warm is not None and gold_warm is not None:
                floor = gold_warm * (1.0 - fleet_tol)
                verdict = "ok" if cand_warm >= floor else "FAIL"
                if verdict == "FAIL":
                    rc = 1
                lines.append(
                    "%s fleet warm-hit rate: %.4f vs golden %.4f "
                    "(floor %.4f, tol %.0f%%)"
                    % (verdict, cand_warm, gold_warm, floor,
                       100 * fleet_tol))
            cand_spills = cand_fleet.get("spills")
            gold_spills = fleet_gold.get("spills")
            if cand_spills is not None and gold_spills is not None:
                # the stampede scenario is deterministic: spill count
                # drift means admission behavior changed, exact match
                same = cand_spills == gold_spills
                if not same:
                    rc = 1
                lines.append(
                    "%s fleet spills under stampede: %d vs golden %d "
                    "(exact)" % ("ok" if same else "FAIL", cand_spills,
                                 gold_spills))
            cand_sum = cand_fleet.get("checksum")
            gold_sum = fleet_gold.get("checksum")
            if cand_sum is None:
                rc = 1
                lines.append(
                    "FAIL fleet: candidate record carries no combined "
                    "replica-admission checksum — placement determinism "
                    "unproven")
            elif gold_sum is not None:
                # CRC-exact, same rationale as the replay checksum
                same = abs(cand_sum - gold_sum) <= 1e-6
                if not same:
                    rc = 1
                lines.append(
                    "%s fleet replica-admission checksum: %.6f vs "
                    "golden %.6f (exact — drift means the router "
                    "stopped reproducing placement)"
                    % ("ok" if same else "FAIL", cand_sum, gold_sum))
            aot = cand_fleet.get("aot") or {}
            gold_aot = fleet_gold.get("aot") or {}
            warm_hits = aot.get("warm_hits")
            if warm_hits is not None:
                verdict = "ok" if warm_hits >= 1 else "FAIL"
                if verdict == "FAIL":
                    rc = 1
                lines.append(
                    "%s fleet aot warm start: %d executable cache "
                    "hit(s) (need >= 1 — the second process must load, "
                    "not recompile)" % (verdict, warm_hits))
            cand_speed = aot.get("speedup")
            gold_speed = gold_aot.get("speedup")
            if cand_speed is not None and gold_speed is not None:
                floor = max(gold_speed * 0.4, 1.0)
                verdict = "ok" if cand_speed >= floor else "FAIL"
                if verdict == "FAIL":
                    rc = 1
                lines.append(
                    "%s fleet aot compile-stage speedup (cold/warm): "
                    "%.2fx vs golden %.2fx (floor %.2fx, hard floor "
                    "1.0x)" % (verdict, cand_speed, gold_speed, floor))
    elif cand_fleet is not None:
        lines.append("note: fleet record present but no golden to "
                     "compare against (record one: make fleet-golden)")

    anim_gold = None
    if anim_golden:
        anim_gold = (extract_records(anim_golden)["anim"]
                     or (anim_golden
                         if anim_golden.get("value") is not None
                         else None))
    cand_anim = recs["anim"]
    if anim_gold is not None:
        if cand_anim is None:
            rc = 1
            lines.append(
                "FAIL anim: candidate carries no anim_proxy record (a "
                "golden exists — the chip-free refit-vs-rebuild metric "
                "must always be fresh)")
        else:
            floor = max(anim_gold["value"] * (1.0 - anim_tol), 1.0)
            verdict = "ok" if cand_anim["value"] >= floor else "FAIL"
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s anim refit speedup (rebuild/refit): %.3fx vs "
                "golden %.3fx (floor %.3fx, tol %.0f%%, hard floor "
                "1.0x)" % (verdict, cand_anim["value"],
                           anim_gold["value"], floor, 100 * anim_tol))
            cand_sum = cand_anim.get("checksum")
            gold_sum = anim_gold.get("checksum")
            if cand_sum is None:
                rc = 1
                lines.append(
                    "FAIL anim: candidate record carries no traversal "
                    "checksum — refit exactness unproven")
            elif gold_sum is not None:
                same = abs(cand_sum - gold_sum) <= 1e-6 * max(
                    1.0, abs(gold_sum))
                if not same:
                    rc = 1
                lines.append(
                    "%s anim traversal checksum: %.6f vs golden %.6f "
                    "(exact — drift means the refit index answered "
                    "differently from a fresh build)"
                    % ("ok" if same else "FAIL", cand_sum, gold_sum))
    elif cand_anim is not None:
        lines.append("note: anim record present but no golden to "
                     "compare against (record one: make anim-golden)")

    trace_gold = None
    if trace_golden:
        trace_gold = (extract_records(trace_golden)["trace"]
                      or (trace_golden
                          if trace_golden.get("value") is not None
                          else None))
    cand_trace = recs["trace"]
    if trace_gold is not None:
        if cand_trace is None:
            rc = 1
            lines.append(
                "FAIL trace: candidate carries no trace_proxy record "
                "(a golden exists — the chip-free request-identity join "
                "contract must always be fresh)")
        else:
            floor = trace_gold["value"] * (1.0 - trace_tol)
            verdict = "ok" if cand_trace["value"] >= floor else "FAIL"
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s trace requests joined: %d vs golden %d (floor %.1f, "
                "tol %.0f%%)"
                % (verdict, cand_trace["value"], trace_gold["value"],
                   floor, 100 * trace_tol))
            cand_tail = cand_trace.get("tail_retained")
            gold_tail = trace_gold.get("tail_retained")
            if cand_tail is not None and gold_tail is not None:
                # the forced deadline-miss/error mix is deterministic:
                # a different retained-tail count means the tail-sampling
                # guarantee (every miss/error keeps its tree) broke
                same = cand_tail == gold_tail
                if not same:
                    rc = 1
                lines.append(
                    "%s trace tail retained (miss/error trees): %d vs "
                    "golden %d (exact)"
                    % ("ok" if same else "FAIL", cand_tail, gold_tail))
            cand_sum = cand_trace.get("checksum")
            gold_sum = trace_gold.get("checksum")
            if cand_sum is None:
                rc = 1
                lines.append(
                    "FAIL trace: candidate record carries no join "
                    "checksum — the request-identity join is unproven")
            elif gold_sum is not None:
                # CRC-exact, same rationale as the replay checksum
                same = abs(cand_sum - gold_sum) <= 1e-6
                if not same:
                    rc = 1
                lines.append(
                    "%s trace join checksum: %.6f vs golden %.6f "
                    "(exact — drift means the ledger/span/router join "
                    "stopped reproducing)"
                    % ("ok" if same else "FAIL", cand_sum, gold_sum))
    elif cand_trace is not None:
        lines.append("note: trace record present but no golden to "
                     "compare against (record one: make trace-golden)")

    golden_rec = None
    if proxy_golden:
        golden_rec = (extract_records(proxy_golden)["proxy"]
                      or (proxy_golden
                          if proxy_golden.get("value") is not None
                          else None))
    cand_proxy = recs["proxy"]
    if golden_rec is not None:
        if cand_proxy is None:
            rc = 1
            lines.append(
                "FAIL proxy: candidate carries no pallas_proxy record "
                "(a golden exists — the chip-free metric must always "
                "be fresh)")
        else:
            floor = golden_rec["value"] * (1.0 - proxy_tol)
            verdict = "ok" if cand_proxy["value"] >= floor else "FAIL"
            if verdict == "FAIL":
                rc = 1
            lines.append(
                "%s proxy pair_tests/sec: %.1f vs golden %.1f "
                "(floor %.1f, tol %.0f%%)"
                % (verdict, cand_proxy["value"], golden_rec["value"],
                   floor, 100 * proxy_tol))
            cand_flops = (cand_proxy.get("hlo_cost") or {}).get("flops")
            gold_flops = (golden_rec.get("hlo_cost") or {}).get("flops")
            if cand_flops and gold_flops:
                ceil = gold_flops * (1.0 + flops_tol)
                verdict = "ok" if cand_flops <= ceil else "FAIL"
                if verdict == "FAIL":
                    rc = 1
                lines.append(
                    "%s proxy HLO cost-model flops: %.3g vs golden %.3g "
                    "(ceiling %.3g, tol %.0f%%)"
                    % (verdict, cand_flops, gold_flops, ceil,
                       100 * flops_tol))
    elif cand_proxy is not None:
        lines.append("note: proxy present but no golden to compare "
                     "against (record one: make proxy-golden)")

    base_head = None
    if baseline and baseline.get("value") is not None \
            and not baseline.get("stale"):
        base_head = baseline
    cand_head = recs["headline"]
    if cand_head is not None and base_head is not None:
        floor = base_head["value"] * (1.0 - headline_tol)
        verdict = "ok" if cand_head["value"] >= floor else "FAIL"
        if verdict == "FAIL":
            rc = 1
        lines.append(
            "%s headline %s: %.1f vs last-good %.1f (floor %.1f, "
            "tol %.0f%%)"
            % (verdict, cand_head.get("unit", "queries/sec"),
               cand_head["value"], base_head["value"], floor,
               100 * headline_tol))
    elif doc.get("stale"):
        lines.append(
            "note: headline is a STALE last-good republication "
            "(age %sh) — skipped, neither an improvement nor a "
            "regression" % doc.get("stale_age_hours"))
    elif cand_head is None:
        lines.append("note: no fresh headline in the candidate "
                     "(wedged or subset run) — headline not checked")
    elif base_head is None:
        lines.append("note: no usable last-good baseline — headline "
                     "not checked")

    # stage attribution: when both sides carry ledger stage evidence
    # (the prof_overhead / serve-load stages embed a stage_stats block),
    # say WHICH stage moved.  Informational — the bands above gate; this
    # turns "a band failed" into "p99 regressed because dispatch got
    # slower" (doc/observability.md runbook).
    cand_stage = _stage_stats_block(doc)
    base_stage = _stage_stats_block(baseline) if baseline else None
    if cand_stage is not None and base_stage is not None:
        from . import prof

        _, diff_lines = prof.diff(base_stage, cand_stage)
        lines.append("stage attribution vs last-good (prof diff):")
        lines.extend("  " + line for line in diff_lines)
    elif cand_stage is not None:
        lines.append("note: candidate carries stage_stats but the "
                     "baseline does not — stage attribution skipped")
    return rc, lines


def _stage_stats_block(doc):
    """The prof-shaped stage stats embedded in a bench doc (a final
    record with ``stage_stats``, or any record inside ``records`` /
    staged ``stages``), or None."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("stage_stats"), dict):
        return {"stages": doc["stage_stats"],
                "total": doc.get("stage_total"),
                "backends": doc.get("stage_backends") or {}}
    from . import prof

    return prof._from_bench_doc(doc)
