"""Per-request latency ledger: where inside each request the time went.

The SLO engine says latency is bad; the flight recorder says what events
surrounded it; neither says WHICH STAGE of a request ate the budget.
The ledger closes that gap: every served request carries one
:class:`RequestRecord` from admission to response, stamped as each stage
finishes —

    admit -> queue -> page_in -> coalesce -> pad -> compile
          -> dispatch -> device -> respond

(``queue`` = serve-queue wait, ``page_in`` = store-key resolution
through the mesh-store page cache (store-keyed requests only,
doc/store.md), ``coalesce`` = the engine executor's batching window,
``pad`` = stack/bucket-pad cost, ``compile`` = plan-cache lookup or
trace+compile, ``dispatch`` = host-side launch, ``device`` = on-device
wall time, ``respond`` = split + response build).
Stages a request never visits (cache hits, non-engine ladder rungs) are
simply absent; durations chain across the gap, so the per-record stage
seconds always sum to the full admit-to-respond latency.

Records carry provenance — tenant, op, query-shape bucket, accel
backend, degradation-ladder rung, certified/approximate — so breakdowns
separate pallas vs pallas_stream vs xla and certified vs degraded
traffic.  Closing a record feeds each stage duration into the
``mesh_tpu_request_stage_seconds{stage,backend}`` histogram (windowed
percentiles via obs/series.py) and appends one JSON-able row to a
bounded ring; the flight recorder copies the ring tail into incident
dumps, ``dump_jsonl()`` saves it for ``mesh-tpu prof diff``.

Always on (same contract as the recorder: the ``prof_overhead`` bench
guard pins the closed-loop p50 cost below 5%); kill switch
``MESH_TPU_LEDGER=0``; ring capacity ``MESH_TPU_LEDGER_CAPACITY``
(default 512); incident tail length ``MESH_TPU_LEDGER_TAIL`` (default
32).  Hot-path cost is one knob read at open, one perf_counter read per
stamp, and one locked append plus a handful of histogram observes at
close.  Stdlib-only; every clock read goes through the injected
``clock`` for fake-clock tests.
"""

import json
import threading
from collections import deque
from contextlib import contextmanager

from ..utils import knobs
from .clock import monotonic
from .context import TRACE_TAIL
from .metrics import REGISTRY

__all__ = [
    "LEDGER_STAGES", "LEDGER_OUTCOMES", "LEDGER_SCHEMA",
    "RequestRecord", "LatencyLedger", "LEDGER",
    "get_ledger", "ledger_enabled", "bind_current", "current_record",
    "LEDGER_ENV", "LEDGER_CAPACITY_ENV", "LEDGER_TAIL_ENV",
    "REPLAY_TRACE_ENV",
]

#: kill switch: set to 0/false/no/off to disable record creation
LEDGER_ENV = "MESH_TPU_LEDGER"

#: bounded-ring capacity in request records (default 512)
LEDGER_CAPACITY_ENV = "MESH_TPU_LEDGER_CAPACITY"

#: how many ring-tail records ride along in flight-recorder incidents
LEDGER_TAIL_ENV = "MESH_TPU_LEDGER_TAIL"

#: stream every close into a replayable trace at this path (obs/replay)
REPLAY_TRACE_ENV = "MESH_TPU_REPLAY_TRACE"

#: dumped-row schema version, stamped into every ``dump_jsonl`` line so
#: readers (obs/prof.py, obs/replay.py) can refuse rows from a future
#: shape instead of misparsing them; bump on incompatible row changes
LEDGER_SCHEMA = 1

#: stage names in request order; each is stamped when that stage ENDS
#: (the record's open time is the admit stamp).  The meshlint OBS rule
#: checks every name here is documented in doc/observability.md.
LEDGER_STAGES = (
    "queue", "page_in", "refit", "coalesce", "pad", "compile", "dispatch",
    "device", "respond",
)

_STAGE_INDEX = {name: i for i, name in enumerate(LEDGER_STAGES)}

#: the outcome-label contract: every ``close()`` must carry one of
#: these (``ok`` = served, ``cancelled`` = caller cancelled before
#: dispatch, ``deadline`` = deadline expired, ``error`` = rung/store
#: failure, ``shutdown`` = request dropped by a non-draining stop).
#: The meshlint LED rule verifies close sites use only these labels
#: and that each is documented in doc/observability.md.
LEDGER_OUTCOMES = ("ok", "cancelled", "deadline", "error", "shutdown")


def ledger_enabled():
    """True unless MESH_TPU_LEDGER explicitly turns the ledger off
    (unset means ON — attribution must be there when latency goes bad,
    like the flight recorder)."""
    return knobs.flag(LEDGER_ENV)


def _ring_capacity():
    return max(16, knobs.get_int(LEDGER_CAPACITY_ENV))


def tail_length():
    """How many ring-tail records incident dumps carry (min 1)."""
    return max(1, knobs.get_int(LEDGER_TAIL_ENV))


class RequestRecord(object):
    """One request's stage stamps + provenance.

    Mutable and intentionally unlocked: each stamp is written by exactly
    one thread at a time (the request moves serve worker -> executor
    worker with happens-before edges at the queue handoffs), and the
    ledger only reads it at ``close()``.
    """

    __slots__ = ("t_admit", "stamps", "meta", "ctx", "_clock")

    def __init__(self, t_admit, meta, clock):
        self.t_admit = float(t_admit)
        self.stamps = {}
        self.meta = meta
        #: the live RequestContext riding this record across the engine's
        #: coalesce/drain thread hop (obs/context.py); never serialized —
        #: the JSON-able identity lives in ``meta`` (request_id/seq/...)
        self.ctx = None
        self._clock = clock

    def stamp(self, stage, t=None):
        """Mark ``stage`` as finished at ``t`` (now by default).  Unknown
        stage names raise — a typo'd stamp site must fail tests, not
        silently vanish from every breakdown."""
        if stage not in _STAGE_INDEX:
            raise ValueError("unknown ledger stage %r (have %s)"
                             % (stage, LEDGER_STAGES))
        self.stamps[stage] = self._clock() if t is None else float(t)

    def set(self, **meta):
        """Attach/overwrite provenance fields (tenant, op, bucket,
        backend, rung, certified, ...)."""
        self.meta.update(meta)

    def stage_seconds(self):
        """{stage: seconds} for every stamped stage, in stage order.
        Each duration runs from the previous PRESENT stamp (or admit),
        so missing stages are skipped, never double-counted, and the
        values sum to the last stamp minus admit.  Out-of-order stamps
        clamp to 0 rather than going negative."""
        out = {}
        prev = self.t_admit
        for stage in LEDGER_STAGES:
            t = self.stamps.get(stage)
            if t is None:
                continue
            out[stage] = max(t - prev, 0.0)
            prev = t
        return out

    def to_dict(self):
        """One JSON-able row: provenance + per-stage seconds + total."""
        stages = self.stage_seconds()
        row = dict(self.meta)
        row["t_admit"] = round(self.t_admit, 6)
        row["stages"] = {k: round(v, 9) for k, v in stages.items()}
        row["total_s"] = round(sum(stages.values()), 9)
        return row


class LatencyLedger(object):
    """Bounded ring of closed request records + the stage histogram.

    ``open()`` returns a record (or None with the ledger off — every
    stamp site is None-guarded, so the kill switch removes all cost but
    the one knob read).  ``close()`` stamps ``respond``, feeds the
    ``mesh_tpu_request_stage_seconds`` histogram, and appends the row to
    the ring.  Thread-safe: concurrent closes serialize on one lock.
    """

    def __init__(self, capacity=None, registry=None, clock=monotonic):
        self._registry = registry if registry is not None else REGISTRY
        self._clock = clock
        self._capacity = capacity
        self._lock = threading.Lock()
        self._ring = deque(maxlen=capacity or _ring_capacity())
        self._listeners = []

    # -- close listeners -----------------------------------------------

    def add_listener(self, fn):
        """Register ``fn(row)`` to observe every closed row (trace
        capture, tests).  Listener failures are swallowed: observers
        must never be able to fail a request that already served."""
        with self._lock:
            self._listeners.append(fn)
        return fn

    def remove_listener(self, fn):
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- lifecycle of one record ---------------------------------------

    def open(self, **meta):
        """Start a record at admit time; None when the ledger is off."""
        if not ledger_enabled():
            return None
        return RequestRecord(self._clock(), meta, self._clock)

    def close(self, record, outcome="ok", **meta):
        """Finish ``record``: stamp ``respond`` (unless already
        stamped), observe every stage duration into the stage histogram
        labeled with this record's backend, and ring-append the row.
        Returns the row dict (None for a None record)."""
        if record is None:
            return None
        if meta:
            record.meta.update(meta)
        record.meta.setdefault("outcome", outcome)
        if "respond" not in record.stamps:
            record.stamp("respond")
        stages = record.stage_seconds()
        backend = record.meta.get("backend") or "none"
        hist = self._registry.histogram(
            "mesh_tpu_request_stage_seconds",
            "Per-request wall seconds by ledger stage and accel backend.",
        )
        exemplar = record.meta.get("request_id")
        for stage, seconds in stages.items():
            hist.observe(seconds, exemplar=exemplar,
                         stage=stage, backend=backend)
        row = record.to_dict()
        with self._lock:
            self._ring.append(row)
            listeners = tuple(self._listeners)
        try:
            TRACE_TAIL.observe_close(row)
        except Exception:               # noqa: BLE001 — retention can't fail serving
            self._observer_error("tail")
        for fn in listeners:
            try:
                fn(row)
            except Exception:           # noqa: BLE001 — observers can't fail serving
                self._observer_error("listener")
        trace_path = knobs.get_str(REPLAY_TRACE_ENV)
        if trace_path:
            from .replay import capture_row
            try:
                capture_row(row, trace_path)
            except Exception:           # noqa: BLE001 — capture can't fail serving
                self._observer_error("capture")
        return row

    def _observer_error(self, where):
        """A swallowed observer/capture failure is still counted — a
        broken trace writer must be visible, never silent."""
        try:
            self._registry.counter(
                "mesh_tpu_ledger_observer_errors_total",
                "Ledger close-path observer failures swallowed to protect "
                "serving (label `where`: listener / capture / tail).",
            ).inc(where=where)
        except Exception:               # noqa: BLE001 — last-resort guard
            pass

    # -- consumption ---------------------------------------------------

    def tail(self, n=None):
        """The newest ``n`` closed rows (default: the incident tail
        length), oldest first."""
        n = tail_length() if n is None else int(n)
        with self._lock:
            rows = list(self._ring)
        return rows[-n:] if n < len(rows) else rows

    def records(self):
        """Every retained row, oldest first."""
        with self._lock:
            return list(self._ring)

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def clear(self):
        """Empty the ring and re-read the capacity knob (tests resize
        via env + obs.reset())."""
        with self._lock:
            self._ring = deque(maxlen=self._capacity or _ring_capacity())

    def dump_jsonl(self, path, n=None):
        """Write the newest ``n`` rows (default: everything retained) as
        JSON lines — the ``mesh-tpu prof diff`` input format.  Each line
        is stamped with ``schema`` = :data:`LEDGER_SCHEMA` (the in-ring
        rows stay unstamped; the version belongs to the file format).
        Returns the row count written."""
        rows = self.records() if n is None else self.tail(n)
        with open(path, "w") as fh:
            for row in rows:
                fh.write(json.dumps(dict(row, schema=LEDGER_SCHEMA),
                                    sort_keys=True))
                fh.write("\n")
        return len(rows)


# -- current-record binding -------------------------------------------------

_TLS = threading.local()


@contextmanager
def bind_current(record):
    """Bind ``record`` as this thread's in-flight request for the block.

    The degradation ladder (serve/deadline.py) keeps its
    ``fn(mesh, points, chunk, timeout)`` rung signature — custom rungs
    stay source-compatible — so built-in rungs reach the record through
    this binding instead of a threaded parameter.  Nesting restores the
    previous binding on exit; binding None is a no-op-shaped guard."""
    prev = getattr(_TLS, "record", None)
    _TLS.record = record
    try:
        yield record
    finally:
        _TLS.record = prev


def current_record():
    """The record bound on THIS thread, or None."""
    return getattr(_TLS, "record", None)


#: the process-wide ledger every serve/engine stamp site feeds
LEDGER = LatencyLedger()


def get_ledger():
    """The process-wide LatencyLedger (hot paths call this instead of
    importing LEDGER directly so tests can monkeypatch one place)."""
    return LEDGER
