"""Metrics registry: labeled counters, gauges, and histograms.

One process-wide ``Registry`` (``REGISTRY``) holds every series the
framework records: the engine's plan-cache/coalescing/pad-waste counters
(mesh_tpu/engine/stats.py is a compatibility view over this registry),
backend-selection counts (utils/dispatch.py), query-strategy and
Pallas-fallback counts (query/culled.py), XLA compilation-cache hits
(obs/jax_bridge.py), and per-op dispatch-latency histograms.

Unlike spans (gated by MESH_TPU_OBS), metrics are ALWAYS on: they are
plain locked dict updates — the same cost the pre-obs ``engine.stats()``
counters already paid — and the engine's stats contract depends on them.

Exporters: ``Registry.snapshot()`` (JSON-able, appended to every
bench.py record), ``obs.export.prometheus_text()``, and the
``mesh-tpu stats`` CLI.  See doc/observability.md for the name table.
"""

import threading
from collections import OrderedDict

__all__ = [
    "Registry", "Counter", "Gauge", "Histogram", "REGISTRY",
    "LATENCY_BUCKETS_S",
]

#: log-spaced latency bucket bounds in seconds: 50 us to 60 s covers
#: everything from a plan-cache hit to a cold tunneled-TPU compile
LATENCY_BUCKETS_S = (
    5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labels):
    """Canonical hashable form of a label set (sorted, values stringified
    so snapshots are stable and JSON-able)."""
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric(object):
    """Base: one named instrument holding labeled series under the
    registry's shared lock (snapshots see a consistent cut of every
    instrument at once)."""

    kind = "untyped"

    def __init__(self, name, help, lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._series = OrderedDict()    # _label_key -> value/state

    def reset(self):
        with self._lock:
            self._series.clear()

    def _labelled(self):
        with self._lock:
            return [
                (dict(key), value) for key, value in self._series.items()
            ]

    def snapshot(self):
        return {
            "type": self.kind,
            "help": self.help,
            "series": [
                {"labels": labels, "value": value}
                for labels, value in self._labelled()
            ],
        }


class Counter(_Metric):
    """Monotonically increasing sum (resets only via reset())."""

    kind = "counter"

    def inc(self, amount=1, **labels):
        if amount < 0:
            raise ValueError("counters only go up, got %r" % (amount,))
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def value(self, **labels):
        with self._lock:
            return self._series.get(_label_key(labels), 0)

    def total(self):
        """Sum across every label combination."""
        with self._lock:
            return sum(self._series.values())


class Gauge(_Metric):
    """A value that can go anywhere (or only up, via set_max)."""

    kind = "gauge"

    def set(self, value, **labels):
        with self._lock:
            self._series[_label_key(labels)] = value

    def inc(self, amount=1, **labels):
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def set_max(self, value, **labels):
        """Keep the running maximum (the engine's max-batch gauge)."""
        key = _label_key(labels)
        with self._lock:
            self._series[key] = max(self._series.get(key, value), value)

    def value(self, **labels):
        with self._lock:
            return self._series.get(_label_key(labels), 0)


class Histogram(_Metric):
    """Cumulative-bucket histogram with exact count/sum/min/max per
    labeled series (so mean and max survive even when every observation
    lands in one bucket)."""

    kind = "histogram"

    def __init__(self, name, help, lock, buckets=LATENCY_BUCKETS_S):
        super(Histogram, self).__init__(name, help, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value, exemplar=None, **labels):
        """Record one observation.  ``exemplar`` is an optional request
        identity (a request_id string, obs/context.py): the max-value
        observation per bucket keeps its exemplar, so a fleet histogram
        bucket links to one concrete replayable request — identity goes
        HERE, never into label values (meshlint OBS006)."""
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = {
                    "count": 0, "sum": 0.0,
                    "min": value, "max": value,
                    "bucket_counts": [0] * (len(self.buckets) + 1),
                }
                self._series[key] = state
            state["count"] += 1
            state["sum"] += value
            state["min"] = min(state["min"], value)
            state["max"] = max(state["max"], value)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    state["bucket_counts"][i] += 1
                    break
            else:
                i = len(self.buckets)               # +Inf bucket
                state["bucket_counts"][-1] += 1
            if exemplar is not None:
                exemplars = state.setdefault("exemplars", {})
                prev = exemplars.get(i)
                if prev is None or value >= prev["value"]:
                    exemplars[i] = {"request_id": str(exemplar),
                                    "value": value}

    def stat(self, **labels):
        """{count, sum, min, max, mean} for one labeled series (zeros when
        the series has never been observed)."""
        with self._lock:
            state = self._series.get(_label_key(labels))
            if state is None:
                return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                        "mean": 0.0}
            return {
                "count": state["count"], "sum": state["sum"],
                "min": state["min"], "max": state["max"],
                "mean": state["sum"] / state["count"],
            }

    def label_sets(self):
        with self._lock:
            return [dict(key) for key in self._series]

    def snapshot(self):
        out = {"type": self.kind, "help": self.help, "series": []}
        with self._lock:
            for key, state in self._series.items():
                cumulative, running = [], 0
                for i, bound in enumerate(self.buckets):
                    running += state["bucket_counts"][i]
                    cumulative.append([bound, running])
                cumulative.append(["+Inf", running + state["bucket_counts"][-1]])
                series = {
                    "labels": dict(key),
                    "count": state["count"],
                    "sum": round(state["sum"], 9),
                    "min": state["min"],
                    "max": state["max"],
                    "buckets": cumulative,
                }
                exemplars = state.get("exemplars")
                if exemplars:
                    bounds = list(self.buckets) + ["+Inf"]
                    series["exemplars"] = [
                        {"le": bounds[i], "request_id": e["request_id"],
                         "value": e["value"]}
                        for i, e in sorted(exemplars.items())
                    ]
                out["series"].append(series)
        return out


class Registry(object):
    """Named instruments, get-or-create, one shared lock.

    ``counter()``/``gauge()``/``histogram()`` are idempotent for a given
    name; asking for an existing name as a different type raises (a
    silent type change would corrupt whoever recorded first).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = OrderedDict()

    def _get_or_create(self, cls, name, help, **kwargs):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is not None:
                if not isinstance(metric, cls):
                    raise TypeError(
                        "metric %r already registered as %s, wanted %s"
                        % (name, metric.kind, cls.kind)
                    )
                return metric
            metric = cls(name, help, self._lock, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=LATENCY_BUCKETS_S):
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return list(self._metrics)

    def snapshot(self):
        """JSON-able dump of every instrument (the bench.py "obs" key)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return OrderedDict((m.name, m.snapshot()) for m in metrics)

    def reset(self):
        """Zero every series (instruments stay registered)."""
        with self._lock:
            for metric in self._metrics.values():
                metric.reset()


#: the process-wide registry every subsystem records into
REGISTRY = Registry()
