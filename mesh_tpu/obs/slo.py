"""SLO objectives and multi-window, multi-burn-rate evaluation.

An ``SLO`` declares what "good" means for a tenant — the fraction of
requests answered under a latency threshold, or availability (the
fraction neither shed nor past deadline) — and a target like 0.99.
Compliance is computed from the existing metrics registry (or a
serve-stats sink written by ``QueryService.write_stats()``): latency
objectives read the cumulative buckets of
``mesh_tpu_serve_latency_seconds``, availability objectives the
``mesh_tpu_serve_good_total`` / ``mesh_tpu_serve_requests_total``
counter pair, so evaluation needs no new instrumentation on the hot
path.

Alerting follows the Google-SRE multi-window multi-burn-rate recipe:
the burn rate is ``bad_fraction / error_budget`` (budget = 1 − target;
burn 1.0 spends the budget exactly over the SLO period), and a rule
fires only when the burn exceeds its factor over BOTH a long window
(sustained damage) and a short window (still happening now).  The
defaults are the classic pair — fast burn 1h/5m at 14.4×, slow burn
6h/30m at 6× — scaled down freely in tests via a fake clock, which is
all the ``SLOMonitor`` reads time from.

A confirmed fast-burn breach is the detect→capture→degrade hinge:
``bind_incident_response`` dumps a flight-recorder incident
(obs/recorder.py) and, under ``MESH_TPU_SLO_DRIVES_HEALTH=1``, trips
the serving health state machine into ``degraded`` so load shedding
starts before the error budget is gone.  See doc/observability.md.
"""

import threading

from .clock import env_flag, monotonic
from .metrics import REGISTRY
from .series import SampleRing, get_series

__all__ = [
    "SLO", "BurnRateRule", "SLOMonitor", "default_rules", "default_slos",
    "good_total", "compliance", "tenants", "bind_incident_response",
    "SLO_DRIVES_HEALTH_ENV",
]

#: opt-in: a confirmed fast-burn breach trips HealthMonitor -> degraded
SLO_DRIVES_HEALTH_ENV = "MESH_TPU_SLO_DRIVES_HEALTH"

_LATENCY_SERIES = "mesh_tpu_serve_latency_seconds"
_GOOD_SERIES = "mesh_tpu_serve_good_total"
_REQUESTS_SERIES = "mesh_tpu_serve_requests_total"


class SLO(object):
    """One declarative objective.

    ``kind="latency"`` — fraction of requests completing under
    ``threshold_s`` must be ≥ ``target``; ``kind="availability"`` —
    fraction of admitted+rejected requests answered good (ok and on
    time: not shed, not past deadline, no error) must be ≥ ``target``.
    ``tenant=None`` evaluates every tenant present in the metrics.
    """

    def __init__(self, name, kind, target, threshold_s=None, tenant=None):
        if kind not in ("latency", "availability"):
            raise ValueError("unknown SLO kind %r" % (kind,))
        if not 0.0 < target < 1.0:
            raise ValueError("target must be in (0, 1), got %r" % (target,))
        if kind == "latency" and not threshold_s:
            raise ValueError("latency SLOs need threshold_s")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.threshold_s = float(threshold_s) if threshold_s else None
        self.tenant = tenant

    def __repr__(self):
        return "SLO(%r, %s, target=%g%s)" % (
            self.name, self.kind, self.target,
            ", threshold_s=%g" % self.threshold_s if self.threshold_s else "",
        )


def default_slos(latency_threshold_s=0.25, latency_target=0.99,
                 availability_target=0.999):
    """The serving tier's stock objective pair."""
    return [
        SLO("latency_p99", "latency", latency_target,
            threshold_s=latency_threshold_s),
        SLO("availability", "availability", availability_target),
    ]


# -- snapshot readers (work offline on the serve-stats sink too) -------

def _series_list(metrics, name):
    entry = metrics.get(name) if metrics else None
    if not entry:
        return []
    return entry.get("series", [])


def tenants(metrics):
    """Sorted tenant names present in the serve series of a
    registry-snapshot-shaped dict."""
    seen = set()
    for name in (_REQUESTS_SERIES, _LATENCY_SERIES, _GOOD_SERIES):
        for series in _series_list(metrics, name):
            tenant = series.get("labels", {}).get("tenant")
            if tenant is not None:
                seen.add(tenant)
    return sorted(seen)


def good_total(metrics, slo, tenant):
    """(good, total) event counts for one objective+tenant from a
    registry-snapshot-shaped dict (cumulative since process start)."""
    if slo.kind == "latency":
        good = total = 0
        for series in _series_list(metrics, _LATENCY_SERIES):
            if series.get("labels", {}).get("tenant") != tenant:
                continue
            total += series.get("count", 0)
            # largest bucket bound <= threshold (bounds are sorted; a
            # tiny epsilon forgives float rendering of e.g. 0.1)
            best = 0
            for bound, cum in series.get("buckets", []):
                if bound == "+Inf":
                    continue
                if float(bound) <= slo.threshold_s * (1 + 1e-9):
                    best = cum
            good += best
        return good, total
    good = 0
    for series in _series_list(metrics, _GOOD_SERIES):
        if series.get("labels", {}).get("tenant") == tenant:
            good += series.get("value", 0)
    total = 0
    for series in _series_list(metrics, _REQUESTS_SERIES):
        if series.get("labels", {}).get("tenant") == tenant:
            total += series.get("value", 0)
    return good, total


def compliance(metrics, slo, tenant):
    """One evaluation row: counts, achieved fraction, and met/missed."""
    good, total = good_total(metrics, slo, tenant)
    achieved = (good / total) if total else 1.0
    return {
        "objective": slo.name,
        "kind": slo.kind,
        "tenant": tenant,
        "target": slo.target,
        "threshold_s": slo.threshold_s,
        "good": good,
        "total": total,
        "compliance": achieved,
        "met": achieved >= slo.target,
    }


# -- burn-rate rules ---------------------------------------------------

class BurnRateRule(object):
    """Fire when burn ≥ factor over BOTH the long and short window."""

    def __init__(self, name, long_s, short_s, factor):
        self.name = name
        self.long_s = float(long_s)
        self.short_s = float(short_s)
        self.factor = float(factor)

    def __repr__(self):
        return "BurnRateRule(%r, %gs/%gs @%g)" % (
            self.name, self.long_s, self.short_s, self.factor)


def default_rules():
    """The Google-SRE page/ticket pair for a 30-day SLO period."""
    return [
        BurnRateRule("fast_burn", long_s=3600.0, short_s=300.0, factor=14.4),
        BurnRateRule("slow_burn", long_s=21600.0, short_s=1800.0, factor=6.0),
    ]


class SLOMonitor(object):
    """Windowed burn-rate evaluation over the live registry.

    ``tick()`` snapshots cumulative (good, total) per objective+tenant
    into a bounded history; ``evaluate()`` computes the burn rate over
    each rule's long and short window from the history (difference of
    the samples bracketing the window) and fires edge-triggered breach
    callbacks.  Every clock read goes through the injected ``clock`` so
    tests drive it deterministically.
    """

    def __init__(self, objectives=None, registry=REGISTRY, clock=monotonic,
                 rules=None, history=1024):
        self.objectives = list(objectives) if objectives else default_slos()
        self.rules = list(rules) if rules is not None else default_rules()
        self._registry = registry
        self._clock = clock
        self._history = history
        # (objective, tenant) -> SampleRing of cumulative (good, total):
        # the windowed-delta arithmetic lives in obs/series.py now
        self._samples = {}
        self._breached = set()    # (objective, tenant, rule) currently firing
        self._callbacks = []
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()

    # -- sampling ------------------------------------------------------

    def _tenant_list(self, metrics, slo):
        if slo.tenant is not None:
            return [slo.tenant]
        return tenants(metrics)

    def tick(self, metrics=None):
        """Append one (t, good, total) sample per objective+tenant."""
        now = self._clock()
        metrics = metrics if metrics is not None else self._registry.snapshot()
        with self._lock:
            for slo in self.objectives:
                for tenant in self._tenant_list(metrics, slo):
                    good, total = good_total(metrics, slo, tenant)
                    key = (slo.name, tenant)
                    ring = self._samples.get(key)
                    if ring is None:
                        ring = self._samples[key] = SampleRing(
                            history=self._history)
                    ring.append(now, (good, total))
        return now

    def _burn(self, ring, slo, window_s, now):
        """Burn rate over [now - window_s, now]: bad_fraction /
        error_budget from the ring's windowed deltas; 0.0 with no
        traffic in the window."""
        deltas = ring.deltas(window_s, now)
        if not deltas:
            return 0.0
        d_good, d_total = deltas
        if d_total <= 0:
            return 0.0
        d_bad = max(d_total - d_good, 0)
        return (d_bad / d_total) / (1.0 - slo.target)

    # -- evaluation ----------------------------------------------------

    def evaluate(self):
        """Burn rates + breach decisions for every objective/tenant/rule;
        fires on_breach callbacks for NEW breaches (edge-triggered) and
        updates the slo gauges/counters."""
        now = self._clock()
        burn_gauge = self._registry.gauge(
            "mesh_tpu_slo_burn_rate",
            "error-budget burn rate per objective/tenant/window")
        breach_counter = self._registry.counter(
            "mesh_tpu_slo_breach_total",
            "edge-triggered burn-rate rule breaches")
        results, fired = [], []
        with self._lock:
            slos = {s.name: s for s in self.objectives}
            items = [(key, ring.copy())
                     for key, ring in self._samples.items()]
        for (obj_name, tenant), series in items:
            slo = slos.get(obj_name)
            if slo is None or not len(series):
                continue
            row = {"objective": obj_name, "tenant": tenant, "rules": []}
            for rule in self.rules:
                long_burn = self._burn(series, slo, rule.long_s, now)
                short_burn = self._burn(series, slo, rule.short_s, now)
                breaching = (long_burn >= rule.factor
                             and short_burn >= rule.factor)
                burn_gauge.set(round(long_burn, 6), objective=obj_name,
                               tenant=tenant, window="%gs" % rule.long_s)
                burn_gauge.set(round(short_burn, 6), objective=obj_name,
                               tenant=tenant, window="%gs" % rule.short_s)
                key = (obj_name, tenant, rule.name)
                with self._lock:
                    was = key in self._breached
                    if breaching:
                        self._breached.add(key)
                    else:
                        self._breached.discard(key)
                new_breach = breaching and not was
                if new_breach:
                    breach_counter.inc(objective=obj_name, rule=rule.name)
                rule_row = {
                    "rule": rule.name,
                    "factor": rule.factor,
                    "long_window_s": rule.long_s,
                    "short_window_s": rule.short_s,
                    "long_burn": long_burn,
                    "short_burn": short_burn,
                    "breaching": breaching,
                    "new": new_breach,
                }
                row["rules"].append(rule_row)
                if new_breach:
                    fired.append({
                        "objective": obj_name, "tenant": tenant,
                        "rule": rule.name, "factor": rule.factor,
                        "long_burn": long_burn, "short_burn": short_burn,
                    })
            results.append(row)
        for event in fired:
            for callback in list(self._callbacks):
                try:
                    callback(event)
                except Exception:   # alerting must never break serving
                    pass
        return results

    def burn_rates(self, now=None):
        """Read-only burn rates per objective/tenant/rule: no gauges,
        no edge-triggered breach state, no callbacks — the poll the
        tuner controller (obs/controller.py) steers by.  Each row
        carries ``pressure`` = max(long, short burn) / rule factor, so
        1.0 means "breaching right now" and e.g. 0.5 means "fast burn
        approaching"."""
        now = self._clock() if now is None else now
        with self._lock:
            slos = {s.name: s for s in self.objectives}
            items = [(key, ring.copy())
                     for key, ring in self._samples.items()]
        rows = []
        for (obj_name, tenant), series in items:
            slo = slos.get(obj_name)
            if slo is None or not len(series):
                continue
            for rule in self.rules:
                long_burn = self._burn(series, slo, rule.long_s, now)
                short_burn = self._burn(series, slo, rule.short_s, now)
                rows.append({
                    "objective": obj_name, "tenant": tenant,
                    "rule": rule.name, "factor": rule.factor,
                    "long_burn": long_burn, "short_burn": short_burn,
                    "pressure": (max(long_burn, short_burn) / rule.factor
                                 if rule.factor else 0.0),
                })
        return rows

    def on_breach(self, callback):
        """Register ``callback(event_dict)`` for NEW breaches."""
        self._callbacks.append(callback)
        return callback

    def breaching(self):
        """Currently-firing (objective, tenant, rule) triples."""
        with self._lock:
            return set(self._breached)

    # -- background loop (production path; tests drive tick/evaluate) --

    def start(self, interval_s=15.0, recorder=None):
        """Spawn the daemon sampling loop: tick → recorder.sample() →
        evaluate, every ``interval_s``."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                    get_series().tick()
                    if recorder is not None:
                        recorder.sample()
                    self.evaluate()
                except Exception:
                    pass

        self._thread = threading.Thread(
            target=loop, name="mesh-tpu-slo", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def bind_incident_response(monitor, recorder=None, health=None):
    """Wire breaches into the forensics/feedback loop: every breach is
    recorded in the flight-recorder ring; a FAST-burn breach dumps an
    incident file and — under ``MESH_TPU_SLO_DRIVES_HEALTH=1`` — trips
    the health state machine into degraded (detect → capture →
    degrade)."""
    from .recorder import get_recorder

    def respond(event):
        rec = recorder if recorder is not None else get_recorder()
        rec.record("slo.breach", **event)
        if event.get("rule") == "fast_burn":
            rec.trigger("slo_fast_burn", context=event, health=health)
            if health is not None and env_flag(SLO_DRIVES_HEALTH_ENV):
                health.trip("slo_fast_burn")

    monitor.on_breach(respond)
    return respond
