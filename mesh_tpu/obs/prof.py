"""jax-free profiling readers: stage breakdowns and regression attribution.

The backing store is whatever latency evidence is on disk —

- a ledger JSONL dump (``LatencyLedger.dump_jsonl``): one request row
  per line with exact per-stage seconds;
- a serve-stats sink (``QueryService.write_stats``): the cumulative
  ``mesh_tpu_request_stage_seconds{stage,backend}`` histogram, quantiles
  estimated from buckets;
- a flight-recorder incident dump (schema >= 2): the ledger tail the
  recorder froze at trigger time;
- a bench JSON (final or ``bench_partial.json``): the ``stage_stats``
  block the ``prof_overhead`` / serve-load stages embed.

``load()`` normalizes all four into one shape; ``diff()`` attributes
p50/p99 deltas between two loads to named stages — the answer perf CI
wants is "p99 regressed because DISPATCH got slower", not "a band
failed".  ``mesh-tpu prof top`` / ``prof diff`` (cli.py) and the
``mesh-tpu perfcheck`` attribution lines sit on these functions.

Import cost: stdlib plus the stdlib-only obs siblings (ledger/series) —
safe to run while the device tunnel is wedged, same contract as
serve-stats/incidents/perfcheck.
"""

import json
import math

from .ledger import LEDGER_SCHEMA, LEDGER_STAGES
from .series import quantile_from_cumulative

__all__ = [
    "ProfError", "load", "stats_from_records", "top_lines", "diff",
    "attribution",
    "load_rows", "load_request_tails", "request_trace",
    "render_request_trace", "fleet_attribution",
]

#: histogram series the sink/bench paths read
STAGE_SERIES = "mesh_tpu_request_stage_seconds"


class ProfError(ValueError):
    """Unreadable/unrecognized profile input (CLI rc 2)."""


def _rank(sorted_vals, q):
    """Nearest-rank quantile of an ascending list (exact, no
    interpolation — these are real per-request samples)."""
    if not sorted_vals:
        return 0.0
    idx = max(int(math.ceil(q * len(sorted_vals))) - 1, 0)
    return sorted_vals[min(idx, len(sorted_vals) - 1)]


def stats_from_records(rows):
    """Normalize ledger rows (dicts with a ``stages`` seconds map) into
    the common shape: per-stage {count, p50_s, p99_s, mean_s}, the
    per-request total quantiles, and a backend histogram."""
    per_stage, totals, backends = {}, [], {}
    for row in rows:
        schema = row.get("schema")
        if schema is not None:
            # dump_jsonl stamps each line with its row-format version;
            # accept anything up to ours, refuse rows from the future
            try:
                schema = int(schema)
            except (TypeError, ValueError):
                raise ProfError("unparseable ledger row schema %r"
                                % (schema,))
            if schema > LEDGER_SCHEMA:
                raise ProfError(
                    "ledger row schema %d is newer than supported %d — "
                    "upgrade before profiling this dump"
                    % (schema, LEDGER_SCHEMA))
        stages = row.get("stages")
        if not isinstance(stages, dict):
            continue
        total = row.get("total_s")
        totals.append(float(total) if total is not None
                      else sum(stages.values()))
        backend = row.get("backend") or "none"
        backends[backend] = backends.get(backend, 0) + 1
        for stage, seconds in stages.items():
            per_stage.setdefault(stage, []).append(float(seconds))
    if not totals:
        raise ProfError("no request rows with a 'stages' map")
    stage_stats = {}
    for stage, vals in per_stage.items():
        vals.sort()
        stage_stats[stage] = {
            "count": len(vals),
            "p50_s": _rank(vals, 0.50),
            "p99_s": _rank(vals, 0.99),
            "mean_s": sum(vals) / len(vals),
        }
    totals.sort()
    return {
        "stages": stage_stats,
        "total": {"count": len(totals), "p50_s": _rank(totals, 0.50),
                  "p99_s": _rank(totals, 0.99)},
        "backends": backends,
    }


def _stats_from_hist(entry):
    """The common shape from a cumulative histogram snapshot entry of
    ``mesh_tpu_request_stage_seconds`` (quantiles estimated from bucket
    interpolation; no per-request totals exist at this granularity)."""
    per_stage, backends = {}, {}
    for series in entry.get("series", []):
        labels = series.get("labels", {})
        stage = labels.get("stage", "?")
        backend = labels.get("backend", "none")
        buckets = series.get("buckets", [])
        count = series.get("count", 0)
        backends[backend] = backends.get(backend, 0) + count
        agg = per_stage.get(stage)
        if agg is None:
            per_stage[stage] = {
                "count": count, "sum": series.get("sum", 0.0),
                "buckets": [[b, c] for b, c in buckets],
            }
        else:
            agg["count"] += count
            agg["sum"] += series.get("sum", 0.0)
            for i, (_, c) in enumerate(buckets):
                agg["buckets"][i][1] += c
    if not per_stage:
        raise ProfError("no %s series in the sink" % STAGE_SERIES)
    stage_stats = {}
    for stage, agg in per_stage.items():
        stage_stats[stage] = {
            "count": agg["count"],
            "p50_s": quantile_from_cumulative(agg["buckets"], 0.50) or 0.0,
            "p99_s": quantile_from_cumulative(agg["buckets"], 0.99) or 0.0,
            "mean_s": (agg["sum"] / agg["count"]) if agg["count"] else 0.0,
        }
    return {"stages": stage_stats, "total": None, "backends": backends}


def _from_bench_doc(doc):
    """The newest embedded ``stage_stats`` block in a bench JSON (final
    ``{"records": [...]}`` or staged ``bench_partial.json``), or None."""
    records = list(doc.get("records") or [])
    for stage in (doc.get("stages") or {}).values():
        rec = (stage or {}).get("record")
        if rec:
            records.append(rec)
    for rec in reversed(records):
        block = rec.get("stage_stats") if isinstance(rec, dict) else None
        if block:
            return {"stages": block, "total": rec.get("stage_total"),
                    "backends": rec.get("stage_backends") or {}}
    return None


def load(path):
    """Read any supported profile evidence file into the common shape
    (see module docstring for the four formats).  Raises
    :class:`ProfError` on unreadable/unrecognized input."""
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as e:
        raise ProfError("cannot read %s: %s" % (path, e))
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        pass
    if isinstance(doc, dict):
        if doc.get("kind") == "incident":
            return stats_from_records(doc.get("ledger") or [])
        if "stage_stats" in doc:
            return {"stages": doc["stage_stats"],
                    "total": doc.get("stage_total"),
                    "backends": doc.get("stage_backends") or {}}
        bench = _from_bench_doc(doc)
        if bench is not None:
            return bench
        metrics = doc.get("metrics", doc)
        entry = metrics.get(STAGE_SERIES)
        if entry:
            return _stats_from_hist(entry)
        raise ProfError(
            "%s: no ledger rows, %s series, or stage_stats block"
            % (path, STAGE_SERIES))
    if isinstance(doc, list):
        return stats_from_records(doc)
    # JSON lines: one ledger row per line
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            raise ProfError("%s: neither JSON nor JSONL" % path)
        if isinstance(row, dict):
            rows.append(row)
    return stats_from_records(rows)


def _stage_order(*stats):
    """Ledger stage order first, then any unknown stages alphabetically."""
    seen = set()
    for s in stats:
        seen.update(s.get("stages", {}))
    ordered = [s for s in LEDGER_STAGES if s in seen]
    ordered += sorted(seen - set(LEDGER_STAGES))
    return ordered


def _ms(seconds):
    return "%.3f" % (1e3 * seconds)


def top_lines(stats):
    """Human-readable stage/backend breakdown of one load()."""
    lines = ["stage        count      p50 ms      p99 ms     mean ms"]
    for stage in _stage_order(stats):
        row = stats["stages"][stage]
        lines.append("%-10s %7d %11s %11s %11s" % (
            stage, row["count"], _ms(row["p50_s"]), _ms(row["p99_s"]),
            _ms(row["mean_s"])))
    total = stats.get("total")
    if total:
        lines.append("%-10s %7d %11s %11s %11s" % (
            "TOTAL", total["count"], _ms(total["p50_s"]),
            _ms(total["p99_s"]), ""))
    backends = stats.get("backends") or {}
    if backends:
        lines.append("backends: " + ", ".join(
            "%s=%d" % (b, n) for b, n in sorted(backends.items())))
    return lines


def _totals(stats, q_key):
    """The comparable total for one quantile: per-request totals when
    the source has them, else the sum of per-stage quantiles (flagged
    by the caller as a stage-sum estimate)."""
    total = stats.get("total")
    if total:
        return total[q_key], True
    return sum(r[q_key] for r in stats["stages"].values()), False


def attribution(a, b, q_key="p99_s"):
    """Per-stage deltas (b - a) for one quantile, largest first:
    [(stage, delta_s), ...] over the union of stages (a stage absent on
    one side contributes its other side's value)."""
    deltas = []
    for stage in _stage_order(a, b):
        va = a["stages"].get(stage, {}).get(q_key, 0.0)
        vb = b["stages"].get(stage, {}).get(q_key, 0.0)
        deltas.append((stage, vb - va))
    deltas.sort(key=lambda kv: -kv[1])
    return deltas


def diff(a, b, tol=0.2, min_delta_s=1e-4):
    """Attribute the latency delta between two loads to named stages.

    Returns ``(rc, lines)``: rc 1 when the total p50 OR p99 of ``b``
    regressed past ``a`` by more than ``tol`` (relative) AND
    ``min_delta_s`` (absolute — sub-100 us noise never fails a gate),
    with the top line naming the dominating stage; rc 0 otherwise.
    """
    lines = ["stage            A p50      B p50     A p99      B p99   "
             "d p99 ms"]
    for stage in _stage_order(a, b):
        ra = a["stages"].get(stage)
        rb = b["stages"].get(stage)
        pa50 = ra["p50_s"] if ra else 0.0
        pb50 = rb["p50_s"] if rb else 0.0
        pa99 = ra["p99_s"] if ra else 0.0
        pb99 = rb["p99_s"] if rb else 0.0
        lines.append("%-10s %10s %10s %10s %10s %10s" % (
            stage, _ms(pa50), _ms(pb50), _ms(pa99), _ms(pb99),
            "%+.3f" % (1e3 * (pb99 - pa99))))
    rc = 0
    for q_key, label in (("p50_s", "p50"), ("p99_s", "p99")):
        ta, exact_a = _totals(a, q_key)
        tb, exact_b = _totals(b, q_key)
        exact = exact_a and exact_b
        kind = "total" if exact else "stage-sum"
        delta = tb - ta
        pct = (delta / ta) if ta > 0 else (float("inf") if delta > 0 else 0.0)
        regressed = delta > min_delta_s and pct > tol
        deltas = attribution(a, b, q_key)
        top_stage, top_delta = deltas[0] if deltas else ("?", 0.0)
        if regressed:
            rc = 1
            share = (top_delta / delta) if delta > 0 else 0.0
            lines.append(
                "FAIL %s %s regressed %s -> %s ms (%+.1f%%, tol %.0f%%) — "
                "stage '%s' accounts for %+.3f ms (%.0f%% of the delta)"
                % (label, kind, _ms(ta), _ms(tb), 1e2 * pct, 1e2 * tol,
                   top_stage, 1e3 * top_delta, 1e2 * share))
        else:
            lines.append("ok   %s %s %s -> %s ms (%+.1f%%)"
                         % (label, kind, _ms(ta), _ms(tb),
                            1e2 * pct if ta > 0 else 0.0))
    return rc, lines


# ---------------------------------------------------------------------------
# request identity: one request's joined evidence + fleet-wide attribution
#
# Everything below keys on the ``request_id`` the router/service mints
# (obs/context.py) and the ledger stamps into each row's meta — the join
# key that connects a fleet histogram exemplar, a ledger row, a retained
# span tree, and the router hop that placed the request.


def _read_doc(path):
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as e:
        raise ProfError("cannot read %s: %s" % (path, e))
    try:
        return json.loads(text), text
    except ValueError:
        return None, text


def load_rows(path):
    """Raw ledger rows from a row-based source — a JSONL dump, a JSON
    row list, or an incident's frozen ledger tail.  Returns ``None``
    for aggregate-only sources (serve-stats sink, bench stage_stats);
    raises :class:`ProfError` only on unreadable files."""
    doc, text = _read_doc(path)
    if isinstance(doc, dict):
        if doc.get("kind") == "incident":
            return [r for r in (doc.get("ledger") or [])
                    if isinstance(r, dict)]
        return None
    if isinstance(doc, list):
        return [r for r in doc if isinstance(r, dict)]
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            return None
        if isinstance(row, dict):
            rows.append(row)
    return rows or None


def load_request_tails(path):
    """Retained request tails (row + span tree) from an incident dump
    (schema >= 4, the ``requests`` key), or ``None`` when the source
    carries no tail."""
    doc, _text = _read_doc(path)
    if isinstance(doc, dict) and doc.get("kind") == "incident":
        tails = doc.get("requests")
        if isinstance(tails, list):
            return [t for t in tails if isinstance(t, dict)]
    return None


def request_trace(request_id, paths=(), tail=None):
    """Join one request's evidence by its ``request_id`` across disk
    sources (ledger dumps / incident dumps) and — when ``tail`` is
    given (a :class:`~mesh_tpu.obs.context.TraceTail`) — the live
    in-process tail buffer.

    Returns ``{"request_id", "rows", "spans", "retained", "sources"}``
    with ``rows`` the matching ledger rows (fleet: one per replica hop
    that admitted it) and ``spans`` the retained span tree (or ``[]``
    if the request was not tail-sampled).  Raises :class:`ProfError`
    when nothing matches anywhere.
    """
    rid = str(request_id)
    rows, spans, retained, sources = [], [], None, []

    def _norm(row):
        # dump_jsonl stamps rows with schema; incident/live copies of
        # the SAME close are unstamped — normalize so overlapping
        # sources collapse (fleet hops still differ in replica/seq)
        return {k: v for k, v in row.items() if k != "schema"}

    def _add_row(row):
        if isinstance(row, dict) and _norm(row) not in map(_norm, rows):
            rows.append(row)

    for path in paths:
        hit = False
        for row in load_rows(path) or ():
            if row.get("request_id") == rid:
                _add_row(row)
                hit = True
        for entry in load_request_tails(path) or ():
            if entry.get("request_id") == rid:
                if not spans:
                    spans = list(entry.get("spans") or [])
                    retained = entry.get("retained")
                _add_row(entry.get("row"))
                hit = True
        if hit:
            sources.append(str(path))
    if tail is not None:
        entry = tail.lookup(rid)
        if entry is not None:
            if not spans:
                spans = list(entry.get("spans") or [])
                retained = entry.get("retained")
            _add_row(entry.get("row"))
            sources.append("<live tail>")
    if not rows and not spans:
        raise ProfError(
            "request %s not found in %d source(s) — it may have aged "
            "out of the ledger ring, or was never tail-sampled "
            "(only deadline-miss/error/spilled and reservoir-slow "
            "requests keep their span tree)" % (rid, len(paths)))
    return {"request_id": rid, "rows": rows, "spans": spans,
            "retained": retained, "sources": sources}


def render_request_trace(trace):
    """Human-readable story of one request: identity/routing header,
    per-hop ledger stage timings, and the retained span tree."""
    from .export import render_tree

    lines = ["request %s" % trace["request_id"]]
    for row in trace["rows"]:
        ident = []
        for key in ("tenant", "seq", "session_id", "routing_key",
                    "replica", "outcome"):
            if row.get(key) is not None:
                ident.append("%s=%s" % (key, row[key]))
        if row.get("spilled"):
            ident.append("SPILLED (router hop: primary rejected "
                         "queue_full)")
        lines.append("  " + " ".join(ident))
        stages = row.get("stages") or {}
        for stage in [s for s in LEDGER_STAGES if s in stages] + sorted(
                set(stages) - set(LEDGER_STAGES)):
            lines.append("    %-10s %10s ms" % (stage, _ms(stages[stage])))
        if row.get("total_s") is not None:
            lines.append("    %-10s %10s ms" % ("TOTAL", _ms(row["total_s"])))
    if not trace["rows"]:
        lines.append("  (no ledger row found — span tree only)")
    if trace["spans"]:
        lines.append("retained span tree (%s):"
                     % (trace.get("retained") or "tail"))
        for ln in render_tree(trace["spans"]).splitlines():
            lines.append("  " + ln)
    else:
        lines.append("no retained span tree (request was not "
                     "tail-sampled)")
    if trace["sources"]:
        lines.append("sources: " + ", ".join(trace["sources"]))
    return lines


def fleet_attribution(named_stats, q_key="p99_s"):
    """Cross-replica latency attribution: which (replica, stage) owns
    the fleet tail.

    ``named_stats`` is ``[(replica_name, load()-shape stats), ...]`` —
    one entry per replica's ledger dump or serve-stats sink.  Returns
    ``(rc, lines)``: a per-replica quantile table, each laggard's
    delta vs the fastest replica attributed to its dominating stage,
    and a final fleet-p99 attribution line.  rc 0 always (this is a
    reader, not a gate); raises :class:`ProfError` on empty input.
    """
    if not named_stats:
        raise ProfError("fleet attribution needs at least one replica "
                        "profile")
    label = q_key.replace("_s", "")
    per = []
    for name, stats in named_stats:
        total, exact = _totals(stats, q_key)
        per.append((name, stats, total, exact))
    per.sort(key=lambda t: t[2])
    best_name, best_stats, best_total, _ = per[0]
    lines = ["replica            %s ms   d vs best   dominating stage"
             % label]
    worst = None
    for name, stats, total, exact in per:
        delta = total - best_total
        if name == best_name:
            lines.append("%-16s %9s %11s   (fastest%s)"
                         % (name, _ms(total), "-",
                            "" if exact else ", stage-sum"))
            continue
        deltas = attribution(best_stats, stats, q_key)
        top_stage, top_delta = deltas[0] if deltas else ("?", 0.0)
        lines.append("%-16s %9s %+10.3f   %s (%+.3f ms)"
                     % (name, _ms(total), 1e3 * delta, top_stage,
                        1e3 * top_delta))
        if worst is None or total > worst[2]:
            worst = (name, top_stage, total, delta, top_delta)
    if worst is not None:
        name, stage, total, delta, top_delta = worst
        lines.append(
            "fleet %s is set by replica '%s' (%s ms): stage '%s' "
            "accounts for %+.3f ms of its %+.3f ms gap to '%s'"
            % (label, name, _ms(total), stage, 1e3 * top_delta,
               1e3 * delta, best_name))
    else:
        lines.append("fleet %s: single replica '%s' at %s ms"
                     % (label, best_name, _ms(best_total)))
    return 0, lines
