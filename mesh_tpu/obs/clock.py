"""The observability subsystem's clocks and its env gate.

This is the ONE place in ``mesh_tpu/`` hot paths where the raw ``time``
module is read (tests/test_timing_lint.py pins it, with
``utils/profiling.py`` as the only other allowed reader): every span,
metric timestamp, and engine latency counter goes through these
aliases, so a future swap to a different clock (or a test fake) is a
one-line change.

``enabled()`` is the master gate: ``MESH_TPU_OBS`` unset/''/'0'/'false'
/'no'/'off' means OFF (same truthiness as the utils/dispatch escape
hatches, re-read per call so tests can toggle it), and OFF means spans
are no-ops — the overhead bound is pinned by tests/test_bench_guard.py
via ``bench.py --obs-overhead``.
"""

import time

from ..utils.knobs import flag as _knob_flag

__all__ = ["monotonic", "wall", "sleep", "enabled", "env_flag", "OBS_ENV"]

#: the observability master gate (spans; metrics counters stay always-on
#: because the engine's pre-existing stats contract depends on them)
OBS_ENV = "MESH_TPU_OBS"

#: monotonic high-resolution clock for durations
monotonic = time.perf_counter

#: wall clock for event timestamps (exporters)
wall = time.time

#: pacing sleep (loadgen/replay); aliased here so fake-clock tests swap
#: clock and sleep as one pair instead of patching ``time`` piecemeal
sleep = time.sleep


def env_flag(name):
    """Shared truthiness with utils/dispatch.env_flag — both now delegate
    to the central knob registry (utils/knobs.py, stdlib-only, so the obs
    primitives still never import jax transitively)."""
    return _knob_flag(name)


def enabled():
    """True when MESH_TPU_OBS turns span tracing on (read per call)."""
    return env_flag(OBS_ENV)
