"""Closed-loop tuner controller: windowed series + burn rates in,
gated, audited knob actuation out.

``TunerController.step()`` is one deterministic evaluation of the
feedback loop — every clock read goes through the injected ``clock``,
so tests drive the whole policy under a fake clock with no sleeps
(``start()`` wraps step() in the same daemon-loop idiom as
``SLOMonitor.start``).  Inputs: the windowed registry view
(obs/series.py percentiles / stage breakdowns) and the SLO monitor's
read-only ``burn_rates()`` poll (obs/slo.py).  Output: exclusively
``utils/tuning.py`` actuations, so every change is bounds-clamped,
generation-stamped, flight-recorded, and countable.

Policies (doc/observability.md):

- **throughput mode** (fast-burn pressure ≤ ``pressure_low``): widen
  the executor's coalescing window one step at a time, each widen
  opened under a **shadow A/B guard** — the p99 of the hold-out window
  after the change must not regress past ``before * (1 +
  MESH_TPU_TUNER_AB_TOL)`` or the change auto-reverts.  Guard verdicts
  follow tools/harvest_gates.py provenance semantics: missing or
  unreadable evidence is never an improvement, so a hold-out with no
  traffic reverts too.
- **latency mode** (pressure ≥ ``pressure_high``): shrink the
  coalescing window and pre-trip the degradation ladder
  (``serve_pre_trip`` → QueryService starts one rung down) before the
  fast-burn rule actually breaches; the pre-trip releases once
  pressure falls back below ``pressure_low``.
- **background retune**: every ``retune_every`` steps, re-publish
  query/autotune.py's persisted calibrations (``retune_hooks()``) into
  the tunable layer so ``accel_min_faces`` / stream buffer counts track
  the live measurement without a process restart.

``MESH_TPU_TUNER=0`` makes step() a no-op and start() refuse to spawn;
a controller that is never started leaves behavior bit-identical to
the static code path.  Stdlib-only.
"""

import threading

from ..utils import knobs, tuning
from .clock import monotonic
from .metrics import REGISTRY
from .recorder import get_recorder
from .series import get_series

__all__ = ["TunerController"]

#: histogram the shadow A/B guard judges hold-out windows on
LATENCY_METRIC = "mesh_tpu_serve_latency_seconds"


class TunerController(object):
    """The feedback loop. Construct with the live series/monitor (or
    fakes), call ``step()`` per evaluation (tests) or ``start()`` for
    the production daemon."""

    def __init__(self, series=None, monitor=None, registry=None,
                 recorder=None, clock=monotonic, ab_tol=None,
                 holdout_s=30.0, pressure_high=0.5, pressure_low=0.1,
                 latency_metric=LATENCY_METRIC, retune_fns=None,
                 retune_every=8, coordinator=None):
        self._series = series if series is not None else get_series()
        self._monitor = monitor
        # optional fleet arbitration (fleet/coordinator.py): widens ask
        # grant_widen() first so N replicas don't all widen into the
        # same fleet-wide fast burn
        self._coordinator = coordinator
        self._registry = registry if registry is not None else REGISTRY
        self._recorder = recorder
        self._clock = clock
        self._ab_tol = ab_tol          # None: re-read the knob per step
        self.holdout_s = float(holdout_s)
        self.pressure_high = float(pressure_high)
        self.pressure_low = float(pressure_low)
        self.latency_metric = latency_metric
        self._retune_fns = dict(retune_fns) if retune_fns else {}
        self.retune_every = int(retune_every)
        self._guard = None             # pending shadow A/B hold-out
        self._steps = 0
        self._lock = threading.Lock()  # guards _guard/_steps (step vs CLI)
        self._thread = None
        self._stop = threading.Event()

    # -- inputs --------------------------------------------------------

    def _tol(self):
        if self._ab_tol is not None:
            return float(self._ab_tol)
        return knobs.get_float("MESH_TPU_TUNER_AB_TOL")

    def _recorder_ref(self):
        return self._recorder if self._recorder is not None \
            else get_recorder()

    def pressure(self, now=None):
        """Worst fast-burn pressure (burn / rule factor) across every
        objective+tenant: 1.0 means breaching right now, 0.0 means idle
        or no monitor wired."""
        if self._monitor is None:
            return 0.0
        rows = self._monitor.burn_rates(now=now)
        fast = [r["pressure"] for r in rows if r["rule"] == "fast_burn"]
        if not fast:
            fast = [r["pressure"] for r in rows]
        return max(fast) if fast else 0.0

    # -- the loop ------------------------------------------------------

    def step(self, now=None):
        """One evaluation: settle any due A/B guard, pick the mode from
        fast-burn pressure, actuate, maybe retune.  Returns a summary
        dict ({"mode": "disabled"} when MESH_TPU_TUNER=0 — nothing is
        read, nothing moves)."""
        if not tuning.enabled():
            return {"mode": "disabled", "actions": []}
        now = self._clock() if now is None else float(now)
        actions = []
        with self._lock:
            guard = self._guard
            if guard is not None and now >= guard["deadline_t"]:
                self._guard = None
            else:
                guard = None
            self._steps += 1
            steps = self._steps
        if guard is not None:
            self._settle_guard(guard, now, actions)
        pressure = self.pressure(now)
        if pressure >= self.pressure_high:
            mode = "latency"
            self._latency_mode(now, pressure, actions)
        else:
            mode = "throughput"
            self._throughput_mode(now, pressure, actions)
        if self._retune_fns and steps % self.retune_every == 0:
            self._retune(now, actions)
        self._registry.counter(
            "mesh_tpu_tuner_evaluations_total",
            "controller step() evaluations by mode",
        ).inc(mode=mode)
        return {"mode": mode, "pressure": pressure, "t": now,
                "actions": actions}

    # -- policies ------------------------------------------------------

    def _latency_mode(self, now, pressure, actions):
        """Fast burn approaching: claw back coalescing latency and start
        requests one rung down the ladder before health degrades."""
        tun = tuning.lookup("coalesce_window_ms")
        cur = tuning.get("coalesce_window_ms")
        if cur > tun.lo:
            event = tuning.actuate(
                "coalesce_window_ms", cur - tun.step,
                reason="latency_mode: fast-burn pressure %.2f" % pressure,
                evidence={"pressure": pressure}, now=now)
            if event:
                actions.append(event)
                with self._lock:
                    # a shrink supersedes any pending widen hold-out
                    if (self._guard is not None and
                            self._guard["knob"] == "coalesce_window_ms"):
                        self._guard = None
        if tuning.get("serve_pre_trip") != 1:
            event = tuning.actuate(
                "serve_pre_trip", 1,
                reason="latency_mode: pre-trip degradation ladder",
                evidence={"pressure": pressure}, now=now)
            if event:
                actions.append(event)

    def _throughput_mode(self, now, pressure, actions):
        """Burn is low: release any pre-trip, then trade a step of
        latency for batching — under a shadow A/B hold-out."""
        if pressure <= self.pressure_low and \
                tuning.get("serve_pre_trip") == 1:
            event = tuning.actuate(
                "serve_pre_trip", 0,
                reason="throughput_mode: release pre-trip",
                evidence={"pressure": pressure}, now=now)
            if event:
                actions.append(event)
        with self._lock:
            guard_open = self._guard is not None
        if guard_open or pressure > self.pressure_low:
            return
        tun = tuning.lookup("coalesce_window_ms")
        cur = tuning.get("coalesce_window_ms")
        if cur >= tun.hi or tuning.pinned("coalesce_window_ms"):
            return
        before_p99 = self._series.percentile(
            self.latency_metric, 0.99, window_s=self.holdout_s, now=now)
        if before_p99 is None:
            return     # no traffic: nothing to optimize, don't churn
        if self._coordinator is not None and \
                not self._coordinator.grant_widen(now=now):
            return     # fleet arbitration: another replica holds the slot
        event = tuning.actuate(
            "coalesce_window_ms", cur + tun.step,
            reason="throughput_mode: widen coalescing "
                   "(pressure %.2f)" % pressure,
            evidence={"pressure": pressure, "before_p99_s": before_p99},
            now=now)
        if event:
            actions.append(event)
            with self._lock:
                self._guard = {
                    "knob": "coalesce_window_ms",
                    "revert_to": cur, "applied": event["after"],
                    "pivot_t": now, "deadline_t": now + self.holdout_s,
                    "before_p99_s": before_p99,
                }

    def _settle_guard(self, guard, now, actions):
        """Judge a due hold-out window.  harvest_gates provenance
        semantics: stale/missing evidence must never read as an
        improvement, so an unreadable after-window reverts.

        The caller has already popped the guard, so this is the only
        chance to revert: the actuation sits in a ``finally`` so a
        recorder or registry that raises mid-verdict can never leave an
        unconfirmed knob value applied with no hold-out watching it.
        """
        after_p99 = self._series.window_percentile(
            self.latency_metric, 0.99, guard["pivot_t"], now)
        before_p99 = guard["before_p99_s"]
        tol = self._tol()
        confirmed = (after_p99 is not None and before_p99 is not None
                     and after_p99 <= before_p99 * (1.0 + tol))
        verdict = "confirmed" if confirmed else "reverted"
        evidence = {
            "before_p99_s": before_p99, "after_p99_s": after_p99,
            "tol": tol, "holdout_s": now - guard["pivot_t"],
        }
        try:
            self._recorder_ref().record(
                "knob_ab", knob=guard["knob"], verdict=verdict,
                **evidence)
            self._registry.counter(
                "mesh_tpu_tuner_ab_total",
                "shadow A/B hold-out verdicts",
            ).inc(knob=guard["knob"], verdict=verdict)
        finally:
            if not confirmed:
                event = tuning.actuate(
                    guard["knob"], guard["revert_to"],
                    reason="ab_guard: hold-out %s" % (
                        "regressed past tolerance"
                        if after_p99 is not None
                        and before_p99 is not None
                        else "evidence missing"),
                    evidence=evidence, action="revert", now=now)
                if event:
                    actions.append(event)

    def _retune(self, now, actions):
        """Re-publish autotune's persisted calibrations into the
        tunable layer (query/autotune.py retune_hooks)."""
        for name, fn in self._retune_fns.items():
            try:
                result = fn()
            except Exception:
                continue       # retune must never break the loop
            if result is None:
                continue
            value, evidence = result
            event = tuning.actuate(
                name, value, reason="retune: autotune calibration",
                evidence=evidence, now=now)
            if event:
                actions.append(event)

    # -- background loop (tests drive step() directly) -----------------

    def start(self, interval_s=None):
        """Spawn the daemon evaluation loop (interval defaults to
        ``MESH_TPU_TUNER_INTERVAL``); no-op with the tuner killed."""
        if not tuning.enabled() or self._thread is not None:
            return self
        if interval_s is None:
            interval_s = knobs.get_float("MESH_TPU_TUNER_INTERVAL")
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.step()
                except Exception:
                    pass       # tuning must never break serving

        self._thread = threading.Thread(
            target=loop, name="mesh-tpu-tuner", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
