"""Span tracer: nested, thread-aware spans over the serving hot path.

A span is one timed region with a name, attributes, and a parent — the
enclosing span on the SAME thread, so the facade -> engine submit ->
(plan hit|compile) -> dispatch chain of one request renders as one tree
while the coalescing executor's worker thread grows its own (events
carry the thread name, so trees never interleave).

Spans record wall time and, separately, device-sync time: ``watch(out)``
registers a jax pytree that is host-synced (utils/profiling.host_sync)
just before the span closes, with the sync cost reported as
``sync_elapsed`` — the queue-time vs device-time split the engine
latency counters need.

Gate: ``MESH_TPU_OBS`` (obs/clock.enabled).  Off — the default — means
``span()`` returns a shared no-op object: no allocation, no clock read,
no buffer append; the < 5% overhead bound on the dispatch-latency
benchmark is pinned by tests/test_bench_guard.py.  ``timed_span()``
always measures (two clock reads) but only records when the gate is on;
it exists so the engine can feed its always-on latency counters through
one primitive.

Finished spans land in a bounded in-memory ring (``TRACER.events()``)
and fan out to sinks: a JSON-lines file (``MESH_TPU_OBS_JSONL=path`` or
``configure(jsonl=...)``) and, under ``MESH_TPU_OBS_JAX_TRACE``, a
``jax.profiler.TraceAnnotation`` wrapping each span so device traces
captured with ``utils.profiling.trace`` show the framework's phases on
the TensorBoard timeline.
"""

import functools
import itertools
import json
import sys
import threading
from collections import deque

from .clock import enabled, env_flag, monotonic, wall
from .context import TRACE_TAIL, current_context

__all__ = [
    "Span", "Tracer", "TRACER", "span", "timed_span", "traced",
    "configure", "jsonl_sink",
]

#: jax.profiler.TraceAnnotation bridge gate (adds real per-span cost on
#: the device timeline, so it is opt-in on top of MESH_TPU_OBS)
JAX_TRACE_ENV = "MESH_TPU_OBS_JAX_TRACE"

#: default JSON-lines sink path gate
JSONL_ENV = "MESH_TPU_OBS_JSONL"

#: size bound (megabytes) on the live sink before rotation (unset = off)
JSONL_MAX_MB_ENV = "MESH_TPU_OBS_JSONL_MAX_MB"

#: rotated files kept as path.1..path.N (default 3)
JSONL_KEEP_ENV = "MESH_TPU_OBS_JSONL_KEEP"

_span_ids = itertools.count(1)


class Span(object):
    """One live traced region; use via ``with span("name", k=v) as sp:``."""

    __slots__ = (
        "name", "attrs", "span_id", "parent_id", "thread_name",
        "t_start", "wall_start", "elapsed", "sync_elapsed", "status",
        "_tracer", "_watched", "_jax_ctx",
    )

    def __init__(self, tracer, name, attrs):
        self.name = name
        self.attrs = attrs
        self.span_id = next(_span_ids)
        self.parent_id = None
        self.thread_name = None
        self.t_start = None
        self.wall_start = None
        self.elapsed = None
        self.sync_elapsed = None
        self.status = "ok"
        self._tracer = tracer
        self._watched = None
        self._jax_ctx = None

    def set(self, **attrs):
        """Attach/update attributes mid-span; returns self for chaining."""
        self.attrs.update(attrs)
        return self

    def watch(self, out):
        """Register a jax pytree to host-sync before the span closes (the
        sync cost lands in ``sync_elapsed``).  Returns ``out`` unchanged
        so call sites can wrap a computation inline."""
        self._watched = out
        return out

    def __enter__(self):
        tracer = self._tracer
        stack = tracer._stack()
        ctx = current_context()
        if stack:
            self.parent_id = stack[-1].span_id
        elif ctx is not None and ctx.root_span_id is not None:
            # cross-thread linkage: a span opening with an empty stack
            # under a bound RequestContext parents under the request's
            # root span instead of rooting a per-thread forest
            self.parent_id = ctx.root_span_id
        if ctx is not None and "request_id" not in self.attrs:
            self.attrs["request_id"] = ctx.request_id
        stack.append(self)
        thread = threading.current_thread()
        self.thread_name = thread.name
        if env_flag(JAX_TRACE_ENV) and "jax" in sys.modules:
            try:
                import jax

                self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:   # the bridge must never break the workload
                self._jax_ctx = None
        self.wall_start = wall()
        self.t_start = monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        t_end = monotonic()
        self.elapsed = t_end - self.t_start
        if exc_type is None and self._watched is not None:
            try:
                from ..utils.profiling import host_sync

                host_sync(self._watched)
            finally:
                t_sync = monotonic()
                self.sync_elapsed = t_sync - t_end
                self.elapsed = t_sync - self.t_start
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("error", exc_type.__name__)
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:                  # unbalanced exit: be lenient
            stack.remove(self)
        self._tracer._finish(self)
        return False

    def to_dict(self):
        return {
            "kind": "span",
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "thread": self.thread_name,
            "ts": self.wall_start,
            "t_mono": self.t_start,
            "elapsed_s": self.elapsed,
            "sync_elapsed_s": self.sync_elapsed,
            "status": self.status,
            "attrs": dict(self.attrs),
        }


class _NoopSpan(object):
    """The shared do-nothing span handed out while MESH_TPU_OBS is off."""

    __slots__ = ()
    elapsed = None
    sync_elapsed = None
    attrs = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def watch(self, out):
        return out


_NOOP = _NoopSpan()


class _TimedOnlySpan(object):
    """timed_span() fallback while tracing is off: measures elapsed (and
    sync time via watch) but records nothing anywhere."""

    __slots__ = ("elapsed", "sync_elapsed", "_t0", "_watched")

    def __init__(self):
        self.elapsed = None
        self.sync_elapsed = None
        self._watched = None

    def set(self, **attrs):
        return self

    def watch(self, out):
        self._watched = out
        return out

    def __enter__(self):
        self._t0 = monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        t_end = monotonic()
        self.elapsed = t_end - self._t0
        if exc_type is None and self._watched is not None:
            from ..utils.profiling import host_sync

            host_sync(self._watched)
            t_sync = monotonic()
            self.sync_elapsed = t_sync - t_end
            self.elapsed = t_sync - self._t0
        return False


class Tracer(object):
    """Per-process span collector: thread-local nesting stacks, a bounded
    ring of finished spans, and push sinks."""

    def __init__(self, max_events=4096):
        self._events = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._sinks = []
        self._env_sink_checked = False

    # -- span lifecycle ------------------------------------------------

    def span(self, name, **attrs):
        return Span(self, name, attrs)

    def _stack(self):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _finish(self, span):
        event = span.to_dict()
        with self._lock:
            if not self._env_sink_checked:
                self._env_sink_checked = True
                self._install_env_sink_locked()
            self._events.append(event)
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink(event)
            except Exception:   # a broken sink must never break serving
                pass

    def _install_env_sink_locked(self):
        from ..utils import knobs

        path = knobs.get_str(JSONL_ENV, None)
        if path:
            self._sinks.append(jsonl_sink(path))

    # -- consumption ---------------------------------------------------

    def events(self):
        """Finished spans, oldest first (bounded ring)."""
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def add_sink(self, sink):
        with self._lock:
            self._sinks.append(sink)
        return sink

    def remove_sink(self, sink):
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)


#: the process-wide tracer (one request path, one tracer)
TRACER = Tracer()

# tail sampling: finished spans carrying a request_id buffer in the
# trace tail until their ledger row closes and decides retention
# (obs/context.py; a span with no request_id costs one dict lookup)
TRACER.add_sink(TRACE_TAIL.record_span)


def span(name, **attrs):
    """A traced region — or THE no-op singleton while MESH_TPU_OBS is
    off, which is the whole overhead story: one env read, no object."""
    if not enabled():
        return _NOOP
    return TRACER.span(name, **attrs)


def timed_span(name, **attrs):
    """Like ``span`` but ``elapsed``/``sync_elapsed`` are measured even
    when tracing is off — the engine's always-on latency counters feed
    from this, so hot paths never read raw clocks themselves."""
    if not enabled():
        return _TimedOnlySpan()
    return TRACER.span(name, **attrs)


def traced(name=None, **attrs):
    """Decorator form: ``@traced`` or ``@traced("custom.name", k=v)``.

    Zero work beyond one env read per call while tracing is off.
    """
    def decorate(fn, label=None):
        label = label or getattr(fn, "__qualname__", fn.__name__)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not enabled():
                return fn(*args, **kwargs)
            with TRACER.span(label, **attrs):
                return fn(*args, **kwargs)
        return wrapper

    if callable(name):          # bare @traced
        return decorate(name)
    return lambda fn: decorate(fn, name)


def jsonl_sink(path, max_mb=None, keep=None):
    """A push sink appending one JSON line per finished span to ``path``
    (opened lazily, line-buffered under a lock; errors are swallowed —
    observability must never take serving down).

    Size-bounded: when the file would exceed ``max_mb`` megabytes
    (default ``MESH_TPU_OBS_JSONL_MAX_MB``, unset = unbounded), it is
    rotated to ``path.1`` … ``path.<keep>`` (default keep
    ``MESH_TPU_OBS_JSONL_KEEP`` or 3, oldest dropped) so long serving
    runs can't grow the live trace sink without limit.
    """
    import os

    from ..utils import knobs

    if max_mb is None:
        max_mb = knobs.get_float(JSONL_MAX_MB_ENV)
    if keep is None:
        keep = max(1, knobs.get_int(JSONL_KEEP_ENV))
    max_bytes = int(max_mb * 1024 * 1024) if max_mb else None
    lock = threading.Lock()
    state = {"fh": None}

    def rotate_locked():
        state["fh"].close()
        state["fh"] = None
        for i in range(keep - 1, 0, -1):
            src = "%s.%d" % (path, i)
            if os.path.exists(src):
                os.replace(src, "%s.%d" % (path, i + 1))
        os.replace(path, "%s.1" % path)

    def sink(event):
        line = json.dumps(event) + "\n"
        with lock:
            if state["fh"] is None:
                state["fh"] = open(path, "a", buffering=1)
            if (max_bytes is not None and state["fh"].tell()
                    and state["fh"].tell() + len(line) > max_bytes):
                rotate_locked()
                state["fh"] = open(path, "a", buffering=1)
            state["fh"].write(line)
    return sink


def configure(jsonl=None):
    """Programmatic sink setup (the env-var-free path for tests and
    embedding apps).  Returns the sink handle for ``remove_sink``."""
    if jsonl is not None:
        return TRACER.add_sink(jsonl_sink(jsonl))
    return None
