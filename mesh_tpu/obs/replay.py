"""Fleet-scale record/replay: ledger-derived traces, shadow replay, synthesis.

The per-request ledger (obs/ledger.py) captures full provenance for every
served request; this module turns that evidence into a *workload corpus*:

- **capture** — :class:`TraceWriter` streams versioned trace records
  (relative admit timestamps + tenant/op/bucket/deadline/priority/
  store-key provenance) to JSONL as ledger records close;
  :func:`trace_from_ledger` / :func:`trace_from_incident` convert any
  existing ledger dump or schema->=2 flight-recorder incident into a
  replayable trace after the fact.  Setting ``MESH_TPU_REPLAY_TRACE``
  streams every close of the process-wide ledger into a trace file with
  no code changes (the ledger consults the knob per close).
- **replay** — ``serve/loadgen.py``'s ``run_trace_replay`` reproduces a
  trace's exact admission sequence against a live ``QueryService``
  (inter-arrival gaps, tenant mix, deadline spread, optional ``speed``
  time-warp); :func:`null_replay` is the service-less jax-free twin the
  CLI uses to validate traces and their checksums.
- **determinism** — :func:`admission_events` canonicalizes the admission
  sequence and :func:`sequence_checksum` hashes it, so "same trace twice
  => same sequence" is machine-checkable (the checksum is invariant to
  ``speed``: a time-warp changes pacing, never the sequence).
- **shadow diff** — :func:`shadow_rows` pushes a trace through a
  synthetic stage model and emits ledger-shaped rows, so two builds'
  replay reports (or any two evidence files) diff through the existing
  ``obs/prof.py`` attribution: ``mesh-tpu replay diff`` names the stage
  that regressed and exits 1 past tolerance.
- **synthesis** — composable adversarial generators (tenant stampede,
  bucket-ladder boundary shapes, volume-filling prune-defeating queries
  from the accel hard case, degenerate meshes) emit the same trace
  schema, so synthetic and captured traffic ride one replay path.

Stdlib-only, same contract as the ledger/prof siblings: every function
here runs while the device tunnel is wedged, and every clock read goes
through an injected clock.
"""

import json
import random
import threading
import zlib

__all__ = [
    "TRACE_SCHEMA", "TRACE_KIND", "REPLAY_TRACE_ENV", "ReplayError",
    "TraceWriter", "trace_from_ledger", "trace_from_incident",
    "load_trace", "write_trace", "trace_lines",
    "admission_events", "sequence_checksum", "null_replay",
    "shadow_rows", "attach_stage_stats",
    "synthesize", "SYNTH_KINDS", "synth_anim", "synth_steady",
    "synth_stampede",
    "synth_bucket_ladder", "synth_prune_defeat", "synth_degenerate",
    "synth_mix", "concat_traces", "capture_row", "reset_capture",
]

#: trace file schema version: bump when the record shape changes in a
#: way old readers must refuse (readers accept any schema <= current)
TRACE_SCHEMA = 1

#: the header line's ``kind`` tag — what makes a JSONL file a trace
TRACE_KIND = "mesh_tpu_trace"

#: knob: stream every process-wide ledger close into a trace at this
#: path (declared in utils/knobs.py; consulted by LatencyLedger.close)
REPLAY_TRACE_ENV = "MESH_TPU_REPLAY_TRACE"

#: provenance fields a trace record may carry beyond the admit offset
_RECORD_FIELDS = ("tenant", "op", "bucket", "q", "deadline_s", "priority",
                  "store_key", "shape")


class ReplayError(ValueError):
    """Unreadable/unrecognized trace input (CLI rc 2)."""


# ---------------------------------------------------------------------------
# trace records and files


def _trace_record(row, t0):
    """One trace record from a ledger row: relative admit offset plus
    the provenance fields replay needs to reproduce the admission."""
    rec = {"t": round(max(float(row.get("t_admit", t0)) - t0, 0.0), 6)}
    for key in _RECORD_FIELDS:
        value = row.get(key)
        if value is not None:
            rec[key] = value
    rec.setdefault("tenant", "default")
    return rec


def _header(source, extra=None):
    head = {"kind": TRACE_KIND, "schema": TRACE_SCHEMA, "source": source}
    if extra:
        head.update(extra)
    return head


def trace_lines(trace):
    """The JSONL serialization of a trace dict: header line first, one
    record per line after it (what ``mesh-tpu replay synth`` prints)."""
    lines = [json.dumps(_header(trace.get("source", "unknown"),
                                {"records": len(trace["records"])}),
                        sort_keys=True)]
    for rec in trace["records"]:
        lines.append(json.dumps(rec, sort_keys=True))
    return lines


def write_trace(trace, path):
    """Write a trace dict as JSONL; returns the record count."""
    with open(path, "w") as fh:
        for line in trace_lines(trace):
            fh.write(line)
            fh.write("\n")
    return len(trace["records"])


def load_trace(path):
    """Read a trace file into ``{"schema", "source", "records": [...]}``.

    Raises :class:`ReplayError` on a missing header, a schema newer than
    this reader supports, or malformed records — a trace that cannot be
    validated must fail loudly before replay starts admitting from it.
    Records are returned sorted by admit offset (ties keep file order).
    """
    try:
        with open(path) as fh:
            lines = [ln.strip() for ln in fh if ln.strip()]
    except OSError as e:
        raise ReplayError("cannot read trace %s: %s" % (path, e))
    if not lines:
        raise ReplayError("%s: empty trace file" % path)
    try:
        head = json.loads(lines[0])
    except ValueError:
        raise ReplayError("%s: first line is not JSON (expected the "
                          "trace header)" % path)
    if not isinstance(head, dict) or head.get("kind") != TRACE_KIND:
        raise ReplayError(
            "%s: not a trace file (header kind %r, expected %r)"
            % (path, head.get("kind") if isinstance(head, dict) else None,
               TRACE_KIND))
    schema = head.get("schema")
    if not isinstance(schema, int) or schema < 1:
        raise ReplayError("%s: trace header carries no schema version"
                          % path)
    if schema > TRACE_SCHEMA:
        raise ReplayError(
            "%s: trace schema %d is newer than supported %d — upgrade "
            "before replaying" % (path, schema, TRACE_SCHEMA))
    records = []
    for i, line in enumerate(lines[1:], 2):
        try:
            rec = json.loads(line)
        except ValueError:
            raise ReplayError("%s:%d: malformed trace record" % (path, i))
        if not isinstance(rec, dict) or "t" not in rec:
            raise ReplayError("%s:%d: trace record carries no admit "
                              "offset 't'" % (path, i))
        rec["t"] = float(rec["t"])
        rec.setdefault("tenant", "default")
        records.append(rec)
    records.sort(key=lambda r: r["t"])
    return {"schema": schema, "source": head.get("source", "unknown"),
            "records": records}


def trace_from_ledger(source, name=None):
    """A trace from ledger evidence: a ``dump_jsonl`` path, a list of
    ledger rows, or anything with a ``records()`` method (a live
    :class:`~mesh_tpu.obs.ledger.LatencyLedger`).  Admit offsets are
    rebased to the earliest row, so monotonic-clock origins never leak
    into the trace."""
    if hasattr(source, "records"):
        rows, name = source.records(), name or "ledger"
    elif isinstance(source, (list, tuple)):
        rows, name = list(source), name or "ledger"
    else:
        name = name or str(source)
        rows = []
        try:
            with open(source) as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
        except (OSError, ValueError) as e:
            raise ReplayError("cannot read ledger %s: %s" % (source, e))
    rows = [r for r in rows if isinstance(r, dict) and "t_admit" in r]
    if not rows:
        raise ReplayError("no ledger rows with a t_admit stamp in %s"
                          % name)
    t0 = min(float(r["t_admit"]) for r in rows)
    records = sorted((_trace_record(r, t0) for r in rows),
                     key=lambda rec: rec["t"])
    return {"schema": TRACE_SCHEMA, "source": name, "records": records}


def trace_from_incident(source):
    """A trace from a flight-recorder incident dump (path or already-
    parsed dict): the ledger tail the recorder froze at trigger time
    becomes the replayable last-moments workload.  Requires incident
    ``schema_version >= 2`` (the version that added the ledger key)."""
    doc = source
    if not isinstance(doc, dict):
        try:
            with open(source) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            raise ReplayError("cannot read incident %s: %s" % (source, e))
    if doc.get("kind") != "incident":
        raise ReplayError("not an incident dump (kind %r)"
                          % (doc.get("kind"),))
    if int(doc.get("schema_version") or 0) < 2:
        raise ReplayError(
            "incident schema_version %s predates the ledger tail "
            "(need >= 2) — nothing to replay" % doc.get("schema_version"))
    name = "incident:%s" % (doc.get("reason") or "unknown")
    return trace_from_ledger(doc.get("ledger") or [], name=name)


# ---------------------------------------------------------------------------
# streaming capture


class TraceWriter(object):
    """Streams ledger close rows to a trace file as they happen.

    The first observed row pins the trace origin (its ``t_admit``
    becomes offset 0) and writes the header; each subsequent row appends
    one record line.  Attach it to a ledger with
    ``ledger.add_listener(writer.observe)``, or let the
    ``MESH_TPU_REPLAY_TRACE`` knob install one on the process-wide
    ledger.  Thread-safe; rows are flushed per record so a crash loses
    at most the in-flight line."""

    def __init__(self, path, source="live"):
        self.path = path
        self.source = source
        self._lock = threading.Lock()
        self._fh = None
        self._t0 = None
        self.written = 0

    def observe(self, row):
        """Append one ledger row as a trace record; returns the record
        (or None for a row with no admit stamp)."""
        if not isinstance(row, dict) or "t_admit" not in row:
            return None
        with self._lock:
            if self._fh is None:
                self._fh = open(self.path, "w")
                self._t0 = float(row["t_admit"])
                self._fh.write(json.dumps(_header(self.source),
                                          sort_keys=True))
                self._fh.write("\n")
            rec = _trace_record(row, self._t0)
            self._fh.write(json.dumps(rec, sort_keys=True))
            self._fh.write("\n")
            self._fh.flush()
            self.written += 1
        return rec

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_CAPTURE_LOCK = threading.Lock()
_CAPTURE = {}                       # path -> TraceWriter


def capture_row(row, path):
    """The ``MESH_TPU_REPLAY_TRACE`` hook: stream ``row`` into the
    TraceWriter for ``path`` (created on first use).  Called by
    ``LatencyLedger.close`` with the knob's per-call value, so toggling
    the knob at runtime starts/stops capture without restarts."""
    with _CAPTURE_LOCK:
        writer = _CAPTURE.get(path)
        if writer is None:
            writer = _CAPTURE[path] = TraceWriter(path, source="capture")
    writer.observe(row)


def reset_capture():
    """Close every knob-installed capture writer (tests; atexit-free)."""
    with _CAPTURE_LOCK:
        writers = list(_CAPTURE.values())
        _CAPTURE.clear()
    for writer in writers:
        writer.close()


# ---------------------------------------------------------------------------
# admission sequence identity


def admission_events(trace, deadline_s=None):
    """The canonical admission sequence of a trace: one compact event
    per record, in admit order.  This is the list both the live replay
    and the null replay hash, so a report checksum is comparable with
    ``sequence_checksum(admission_events(trace))`` directly.  The
    optional ``deadline_s`` override is part of the sequence (replaying
    with a different deadline spread IS a different workload); ``speed``
    deliberately is not (a time-warp repaces the same sequence)."""
    events = []
    for i, rec in enumerate(trace["records"]):
        deadline = deadline_s if deadline_s is not None \
            else rec.get("deadline_s")
        events.append([
            i,
            round(float(rec["t"]), 6),
            rec.get("tenant", "default"),
            int(rec.get("priority") or 0),
            round(float(deadline), 6) if deadline is not None else None,
            rec.get("op") or "",
            int(rec.get("bucket") or -1),
            rec.get("store_key") or "",
            int(rec.get("q") or -1),
        ])
    return events


def sequence_checksum(events):
    """Deterministic checksum of an admission-event list (float, graded
    exactly by perfcheck's checksum contract: drift is a hard FAIL)."""
    payload = json.dumps(events, sort_keys=True, separators=(",", ":"))
    return float(zlib.crc32(payload.encode("utf-8")))


def null_replay(trace, speed=1.0, deadline_s=None, clock=None, sleep=None):
    """Replay the admission *pacing* of a trace with no service behind
    it: walks every record at its (time-warped) offset and reports the
    paced duration plus the sequence checksum.  Default clocks are fake
    (virtual time — instant), so the jax-free CLI can validate a trace
    and print its checksum without sleeping through it; pass real
    ``clock``/``sleep`` to rehearse wall-clock pacing."""
    if speed <= 0:
        raise ReplayError("replay speed must be > 0 (got %s)" % speed)
    if clock is None or sleep is None:
        t = [0.0]

        def clock():                # noqa: F811 — fake pair, by design
            return t[0]

        def sleep(dt):              # noqa: F811
            t[0] += max(dt, 0.0)
    events = admission_events(trace, deadline_s=deadline_s)
    t0 = clock()
    for rec in trace["records"]:
        target = t0 + float(rec["t"]) / speed
        wait = target - clock()
        if wait > 0:
            sleep(wait)
    paced_s = clock() - t0
    return {
        "loop": "replay",
        "mode": "null",
        "source": trace.get("source", "unknown"),
        "speed": float(speed),
        "admissions": len(events),
        "paced_s": round(paced_s, 4),
        "wall_s": round(paced_s, 4),
        "checksum": sequence_checksum(events),
    }


# ---------------------------------------------------------------------------
# shadow replay: trace -> synthetic ledger rows for stage attribution


def shadow_rows(trace, stage_model, deadline_s=None):
    """Push a trace through a synthetic stage model and return
    ledger-shaped rows (``t_admit``/``stages``/``total_s`` + trace
    provenance).  ``stage_model(record) -> {stage: seconds}`` plays the
    build under test: two models for the same trace yield two evidence
    sets whose ``prof.diff`` names the stage that moved — the
    "would the fix have held?" shadow experiment without a chip."""
    from .ledger import LEDGER_STAGES

    rows = []
    for rec in trace["records"]:
        stages = stage_model(rec)
        unknown = [s for s in stages if s not in LEDGER_STAGES]
        if unknown:
            raise ReplayError("stage model produced unknown stage(s) %s "
                              "(have %s)" % (unknown, list(LEDGER_STAGES)))
        ordered = {s: round(float(stages[s]), 9)
                   for s in LEDGER_STAGES if s in stages}
        row = {k: v for k, v in rec.items() if k != "t"}
        deadline = deadline_s if deadline_s is not None \
            else rec.get("deadline_s")
        if deadline is not None:
            row["deadline_s"] = float(deadline)
        row["t_admit"] = round(float(rec["t"]), 6)
        row["stages"] = ordered
        row["total_s"] = round(sum(ordered.values()), 9)
        row["outcome"] = "ok"
        rows.append(row)
    return rows


def attach_stage_stats(report, rows):
    """Embed prof-shaped stage evidence into a replay report so the
    report file itself is a ``mesh-tpu prof`` / ``replay diff`` source
    (the same ``stage_stats`` contract the bench prof_overhead record
    uses).  Returns the report."""
    from . import prof

    stats = prof.stats_from_records(rows)
    report["stage_stats"] = stats["stages"]
    report["stage_total"] = stats["total"]
    report["stage_backends"] = stats["backends"]
    return report


# ---------------------------------------------------------------------------
# adversarial workload synthesis


def _mk_trace(records, source):
    records.sort(key=lambda r: r["t"])
    for rec in records:
        rec["t"] = round(rec["t"], 6)
    return {"schema": TRACE_SCHEMA, "source": source, "records": records}


def synth_steady(rate_qps=20.0, duration_s=5.0, tenants=("steady",),
                 deadline_s=0.5, q=256, op="closest_point", seed=0):
    """Baseline: Poisson-free uniform arrivals round-robined across
    tenants — the calm traffic every adversarial mix is measured
    against (and the tuner_replay scenario's recovery phase)."""
    rng = random.Random(seed)
    interval = 1.0 / float(rate_qps)
    records, t, i = [], 0.0, 0
    while t < duration_s:
        records.append({
            "t": t + rng.uniform(0, 0.2 * interval),
            "tenant": tenants[i % len(tenants)],
            "op": op, "q": int(q), "deadline_s": float(deadline_s),
            "priority": 0,
        })
        t += interval
        i += 1
    return _mk_trace(records, "synth:steady")


def synth_stampede(tenants=6, burst_every_s=0.25, duration_s=2.0,
                   deadline_s=0.25, q=256, seed=1):
    """Tenant stampede: every tenant admits in the same instant, burst
    after burst — the shape that makes weighted-fair queueing and
    per-tenant bounds earn their keep (near-zero inter-arrival gaps
    inside a burst, deadline pressure across it)."""
    rng = random.Random(seed)
    records, t = [], 0.0
    while t < duration_s:
        for k in range(int(tenants)):
            records.append({
                "t": t + rng.uniform(0, 1e-3),
                "tenant": "stampede-%d" % k,
                "op": "closest_point", "q": int(q),
                "deadline_s": float(deadline_s),
                "priority": -1 if k == tenants - 1 else 0,
            })
        t += burst_every_s
    return _mk_trace(records, "synth:stampede")


def synth_bucket_ladder(buckets=(64, 128, 256, 512, 1024), rate_qps=40.0,
                        duration_s=3.0, deadline_s=0.5, seed=2):
    """Bucket-ladder boundary shapes: query counts walk each padding
    bucket's boundary (bucket-1, bucket, bucket+1), so every admission
    lands maximally awkwardly for the shape-bucketed plan cache — the
    pad-waste and retrace worst case."""
    rng = random.Random(seed)
    interval = 1.0 / float(rate_qps)
    records, t, i = [], 0.0, 0
    while t < duration_s:
        bucket = buckets[(i // 3) % len(buckets)]
        qn = max(1, bucket + (i % 3) - 1)        # bucket-1, bucket, bucket+1
        records.append({
            "t": t + rng.uniform(0, 0.1 * interval),
            "tenant": "ladder",
            "op": "closest_point", "q": int(qn), "bucket": int(bucket),
            "deadline_s": float(deadline_s), "priority": 0,
        })
        t += interval
        i += 1
    return _mk_trace(records, "synth:bucket_ladder")


def synth_prune_defeat(rate_qps=20.0, duration_s=3.0, q=1024,
                       deadline_s=0.5, seed=3):
    """Volume-filling prune-defeating queries: the accel tier's
    documented hard case — queries spread through the mesh bounding
    volume instead of hugging the surface, so BVH/grid traversal
    cannot cull and pair tests degrade toward brute force.  The
    ``shape`` tag rides the trace so replay harnesses can regenerate
    matching query clouds."""
    rng = random.Random(seed)
    interval = 1.0 / float(rate_qps)
    records, t = [], 0.0
    while t < duration_s:
        records.append({
            "t": t + rng.uniform(0, 0.1 * interval),
            "tenant": "prune-defeat",
            "op": "closest_point", "q": int(q),
            "deadline_s": float(deadline_s), "priority": 0,
            "shape": "volume_fill",
        })
        t += interval
    return _mk_trace(records, "synth:prune_defeat")


def synth_degenerate(rate_qps=10.0, duration_s=2.0, q=256,
                     deadline_s=0.5, seed=4):
    """Degenerate-mesh traffic: requests tagged as targeting
    sliver/zero-area-tail topology, the inputs that force the safe tile
    variants and the certificate-fallback path — replay them against a
    candidate build to prove the robustness ladder still holds."""
    rng = random.Random(seed)
    interval = 1.0 / float(rate_qps)
    records, t = [], 0.0
    while t < duration_s:
        records.append({
            "t": t + rng.uniform(0, 0.1 * interval),
            "tenant": "degenerate",
            "op": "closest_point", "q": int(q),
            "deadline_s": float(deadline_s), "priority": 0,
            "shape": "degenerate_mesh",
        })
        t += interval
    return _mk_trace(records, "synth:degenerate")


def synth_anim(sessions=6, hz=30.0, frames=90, q=128, seed=5):
    """Avatar-stream traffic: ``sessions`` fixed-topology streams each
    admitting one frame per ``1/hz`` with a hard per-frame deadline of
    exactly the frame budget — the periodic deadline-hard arrival
    process animated meshes present (serve/loadgen.run_periodic is the
    live twin of this trace).  Streams are phase-offset within one
    frame interval, so ticks interleave instead of stampeding; the
    ``anim_periodic`` shape tag tells replay harnesses to regenerate
    per-frame vertex deltas to match (doc/animation.md)."""
    rng = random.Random(seed)
    interval = 1.0 / float(hz)
    records = []
    for s in range(int(sessions)):
        phase = rng.random() * interval
        for k in range(int(frames)):
            records.append({
                "t": phase + k * interval,
                "tenant": "avatar-%d" % s,
                "op": "anim_frame", "q": int(q),
                "deadline_s": float(interval), "priority": 0,
                "shape": "anim_periodic", "frame": k,
            })
    return _mk_trace(records, "synth:anim")


def concat_traces(traces, gap_s=0.5, source=None):
    """Compose traces end to end (each shifted past the previous one's
    last admission plus ``gap_s``) — how adversarial mixes are built
    from the single-shape generators."""
    records, offset = [], 0.0
    names = []
    for trace in traces:
        names.append(trace.get("source", "?"))
        last = 0.0
        for rec in trace["records"]:
            moved = dict(rec)
            moved["t"] = rec["t"] + offset
            last = max(last, moved["t"])
            records.append(moved)
        offset = last + gap_s
    return _mk_trace(records, source or "+".join(names))


def synth_mix(seed=7):
    """The default adversarial mix: stampede -> bucket ladder ->
    prune-defeat -> degenerate, composed on one timeline (what the
    replay_proxy bench stage and ``replay synth mix`` emit)."""
    return concat_traces([
        synth_stampede(seed=seed),
        synth_bucket_ladder(seed=seed + 1),
        synth_prune_defeat(seed=seed + 2),
        synth_degenerate(seed=seed + 3),
    ], gap_s=0.5, source="synth:mix")


SYNTH_KINDS = {
    "steady": synth_steady,
    "stampede": synth_stampede,
    "bucket_ladder": synth_bucket_ladder,
    "prune_defeat": synth_prune_defeat,
    "degenerate": synth_degenerate,
    "anim": synth_anim,
    "mix": synth_mix,
}


def synthesize(kind, **kw):
    """Dispatch to one generator by name (``mesh-tpu replay synth``).
    Unknown kinds raise :class:`ReplayError` with the menu."""
    fn = SYNTH_KINDS.get(kind)
    if fn is None:
        raise ReplayError("unknown synth kind %r (have %s)"
                          % (kind, ", ".join(sorted(SYNTH_KINDS))))
    return fn(**kw)
