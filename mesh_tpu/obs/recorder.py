"""Always-on flight recorder: a bounded ring of recent events plus
self-contained JSON incident dumps.

The recorder is the black box for the serving tier: it keeps the last
few thousand interesting events (engine dispatches, serve admissions /
rejections / deadline outcomes, health trips, periodic metric deltas)
in a fixed-size in-memory ring, always on — one env read plus one
locked deque append per event, cheap enough that it runs with
``MESH_TPU_OBS`` off (``bench.py --recorder-overhead`` +
tests/test_bench_guard.py pin the cost below 5% of steady-state
dispatch latency).

When something goes wrong — a watchdog trip, an SLO fast-burn breach
(obs/slo.py), an uncaught executor or serve-worker exception, or an
explicit ``trigger()`` call — the recorder dumps one self-contained
JSON incident file: the ring contents, a full registry snapshot, the
``HealthMonitor.snapshot()``, an engine plan-cache summary, and the
relevant environment, so the *why* behind a deadline-miss storm
survives the process.  ``mesh-tpu incidents`` lists and pretty-prints
the dumps without initializing a jax backend.

Env gates (read per call, shared truthiness with the other escape
hatches): ``MESH_TPU_RECORDER=0`` disables recording entirely;
``MESH_TPU_RECORDER_EVENTS`` sizes the ring (default 2048);
``MESH_TPU_INCIDENT_DIR`` relocates the dump directory (default
``~/.mesh_tpu/incidents``); ``MESH_TPU_INCIDENT_KEEP`` bounds how many
dumps are retained (default 32, oldest pruned).
"""

import json
import os
import sys
import threading
from collections import deque

from ..utils import knobs
from .clock import monotonic, wall
from .metrics import REGISTRY
from .trace import TRACER

__all__ = [
    "FlightRecorder", "RECORDER", "get_recorder", "recorder_enabled",
    "default_incident_dir", "list_incidents", "RECORDER_ENV",
    "INCIDENT_DIR_ENV", "KEEP_ENV", "EVENTS_ENV", "SCHEMA_VERSION",
]

#: kill switch: set to 0/false/no/off to disable all recording
RECORDER_ENV = "MESH_TPU_RECORDER"

#: where incident dumps land (default ~/.mesh_tpu/incidents)
INCIDENT_DIR_ENV = "MESH_TPU_INCIDENT_DIR"

#: how many incident files to retain (oldest pruned; default 32)
KEEP_ENV = "MESH_TPU_INCIDENT_KEEP"

#: ring capacity for the process-wide recorder (default 2048 events)
EVENTS_ENV = "MESH_TPU_RECORDER_EVENTS"

#: incident-file schema version (bump on breaking shape changes).
#: v2: incidents carry a ``"ledger"`` key — the latency ledger's newest
#: MESH_TPU_LEDGER_TAIL request records (``mesh-tpu prof top`` reads it).
#: v3: incidents carry a ``"knob_history"`` key — the tuning layer's
#: newest MESH_TPU_KNOB_TAIL ``knob_change`` events (``mesh-tpu tune
#: history`` reads it: "what did the tuner do during this incident?").
#: v4: incidents carry a ``"requests"`` key — the tail-sampling ring's
#: retained request traces (ledger row + span tree joined by
#: request_id, obs/context.py; ``mesh-tpu prof trace <id>`` reads it).
SCHEMA_VERSION = 4

#: env prefixes captured into each incident (config forensics)
_ENV_PREFIXES = ("MESH_TPU_", "JAX_", "XLA_")

#: counters sampled as deltas by sample() — the cheap "what moved since
#: the last sample" view that makes ring timelines readable
_SAMPLED_TOTALS = (
    "mesh_tpu_serve_requests_total",
    "mesh_tpu_serve_shed_total",
    "mesh_tpu_serve_deadline_miss_total",
    "mesh_tpu_serve_retries_total",
    "mesh_tpu_engine_plan_misses_total",
    "mesh_tpu_engine_coalesced_dispatches_total",
)


def recorder_enabled():
    """True unless MESH_TPU_RECORDER explicitly turns recording off
    (unset means ON — the recorder is the always-on black box; the knob
    is declared with default=on)."""
    return knobs.flag(RECORDER_ENV)


def default_incident_dir():
    """MESH_TPU_INCIDENT_DIR, or ~/.mesh_tpu/incidents."""
    path = knobs.get_str(INCIDENT_DIR_ENV, None)
    if path:
        return path
    return os.path.join(os.path.expanduser("~"), ".mesh_tpu", "incidents")


def _keep_limit():
    return max(1, knobs.get_int(KEEP_ENV))


def _ring_capacity():
    return max(16, knobs.get_int(EVENTS_ENV))


def list_incidents(directory=None):
    """Sorted (oldest first) incident file paths in ``directory`` —
    stdlib-only, safe for the jax-free ``mesh-tpu incidents`` CLI."""
    directory = directory or default_incident_dir()
    try:
        names = sorted(
            n for n in os.listdir(directory)
            if n.startswith("incident-") and n.endswith(".json")
        )
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names]


class FlightRecorder(object):
    """Bounded ring of recent events + triggered incident dumps.

    ``record()`` is the hot-path entry: one enabled() env read, one
    dict build, one locked deque append.  ``trigger()`` freezes the
    ring plus every diagnostic snapshot we can reach into one JSON
    file; dumps are rate-limited (``min_dump_interval_s``) so a trip
    storm produces one incident, not a disk full of them —
    ``force=True`` (the explicit-API path) bypasses the limit.
    """

    def __init__(self, capacity=None, registry=REGISTRY, clock=monotonic,
                 min_dump_interval_s=30.0):
        self._ring = deque(maxlen=capacity or _ring_capacity())
        self._lock = threading.Lock()
        self._registry = registry
        self._clock = clock
        self._min_dump_interval_s = min_dump_interval_s
        self._last_dump_t = None
        self._dump_seq = 0
        self._health = None
        self._sample_prev = {}

    # -- recording (hot path) ------------------------------------------

    def record(self, kind, **fields):
        """Append one event to the ring; a no-op when
        MESH_TPU_RECORDER is off."""
        if not recorder_enabled():
            return
        fields["kind"] = kind
        fields["t"] = self._clock()
        with self._lock:
            self._ring.append(fields)

    def record_span(self, event):
        """TRACER sink: finished spans land in the ring too (only fires
        while MESH_TPU_OBS is on, so this adds nothing to the gated-off
        cost)."""
        if not recorder_enabled():
            return
        slim = {
            "kind": "span",
            "t": event.get("t_mono"),
            "name": event.get("name"),
            "elapsed_s": event.get("elapsed_s"),
            "status": event.get("status"),
            "thread": event.get("thread"),
        }
        attrs = event.get("attrs")
        if attrs:
            slim["attrs"] = attrs
        with self._lock:
            self._ring.append(slim)

    def sample(self):
        """Record one ``metrics.sample`` event holding the deltas of the
        serve/engine totals since the previous sample plus current queue
        depths — the periodic heartbeat an SLOMonitor loop drives."""
        if not recorder_enabled():
            return
        deltas = {}
        for name in _SAMPLED_TOTALS:
            metric = self._registry.get(name)
            if metric is None:
                continue
            try:
                total = metric.total()
            except AttributeError:
                continue
            prev = self._sample_prev.get(name, 0)
            self._sample_prev[name] = total
            if total != prev:
                deltas[name] = total - prev
        depths = {}
        depth_gauge = self._registry.get("mesh_tpu_serve_queue_depth")
        if depth_gauge is not None:
            for labels, value in depth_gauge._labelled():
                depths[labels.get("tenant", "?")] = value
        self.record("metrics.sample", deltas=deltas, queue_depths=depths)

    # -- consumption ---------------------------------------------------

    def events(self):
        """Ring contents, oldest first."""
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._sample_prev.clear()
            self._last_dump_t = None

    def attach_health(self, monitor):
        """Remember the HealthMonitor whose snapshot() belongs in dumps
        triggered away from the serve layer (executor exceptions, SLO
        breaches without an explicit health= argument)."""
        self._health = monitor

    # -- incident dumps ------------------------------------------------

    def trigger(self, reason, context=None, health=None, force=False):
        """Dump a self-contained incident file; returns its path, or
        None when recording is off, the rate limit holds it back, or the
        dump directory is unwritable (forensics never take serving
        down)."""
        if not recorder_enabled():
            return None
        now = self._clock()
        with self._lock:
            if (not force and self._last_dump_t is not None
                    and now - self._last_dump_t < self._min_dump_interval_s):
                return None
            self._last_dump_t = now
            self._dump_seq += 1
            seq = self._dump_seq
            ring = list(self._ring)
        health = health if health is not None else self._health
        incident = {
            "schema_version": SCHEMA_VERSION,
            "kind": "incident",
            "reason": reason,
            "written_utc": wall(),
            "mono_at_dump": now,
            "context": context or {},
            "ring": ring,
            "metrics": self._registry.snapshot(),
            "health": self._health_snapshot(health),
            "engine": self._engine_summary(),
            "ledger": self._ledger_tail(),
            "knob_history": self._knob_history(),
            "requests": self._requests_tail(),
            "env": {
                k: v for k, v in sorted(os.environ.items())
                if k.startswith(_ENV_PREFIXES)
            },
        }
        return self._write(incident, reason, seq)

    @staticmethod
    def _ledger_tail():
        """The latency ledger's newest request records (schema v2) —
        imported lazily so recorder stays importable standalone (ledger
        never imports recorder back, so no cycle either way)."""
        try:
            from .ledger import get_ledger

            return get_ledger().tail()
        except Exception:
            return []

    @staticmethod
    def _requests_tail():
        """The tail-sampling ring's retained request traces (schema v4)
        — imported lazily like the ledger tail (context never imports
        recorder, so no cycle either way)."""
        try:
            from .context import get_trace_tail

            return get_trace_tail().retained()
        except Exception:
            return []

    @staticmethod
    def _knob_history():
        """The tuning layer's newest knob_change events (schema v3) —
        imported lazily like the ledger tail (tuning never imports
        recorder at module scope, so no cycle either way)."""
        try:
            from ..utils import tuning

            return tuning.history_tail()
        except Exception:
            return []

    @staticmethod
    def _health_snapshot(health):
        if health is None:
            return None
        try:
            return health.snapshot()
        except Exception:
            return None

    @staticmethod
    def _engine_summary():
        """Plan-cache/coalescing summary — only if the engine is already
        imported (an incident dump must never pull in jax)."""
        engine = sys.modules.get("mesh_tpu.engine")
        if engine is None:
            return None
        try:
            return engine.stats()
        except Exception:
            return None

    def _write(self, incident, reason, seq):
        directory = default_incident_dir()
        stamp = "%013d" % int(incident["written_utc"] * 1000)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in str(reason)
        )[:48] or "manual"
        name = "incident-%s-%s-%03d.json" % (stamp, safe_reason, seq)
        path = os.path.join(directory, name)
        tmp = path + ".tmp"
        try:
            os.makedirs(directory, exist_ok=True)
            with open(tmp, "w") as fh:
                json.dump(incident, fh, indent=1, default=str)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        self._prune(directory)
        self._registry.counter(
            "mesh_tpu_incident_dumps_total",
            "incident files written by the flight recorder",
        ).inc(reason=reason)
        return path

    @staticmethod
    def _prune(directory):
        keep = _keep_limit()
        paths = list_incidents(directory)
        for stale in paths[:-keep] if len(paths) > keep else []:
            try:
                os.unlink(stale)
            except OSError:
                pass


#: the process-wide recorder every subsystem feeds
RECORDER = FlightRecorder()

# finished spans flow into the ring as soon as obs is imported (the sink
# only fires while MESH_TPU_OBS is on — see Tracer._finish)
TRACER.add_sink(RECORDER.record_span)


def get_recorder():
    """The process-wide FlightRecorder (hot paths call this instead of
    importing RECORDER directly so tests can monkeypatch one place)."""
    return RECORDER
