"""End-to-end request identity: RequestContext + tail-sampled traces.

Every observability signal in the stack used to be process- and
thread-local: spans parent through a thread-local stack (so the engine's
coalesce/drain thread hop forests one request's tree), ledger records
carry no identity a span or metric can reference, and each fleet replica
dumps its own sink with no join key across the router hop.  This module
is the identity plane that joins them:

- :class:`RequestContext` is minted once per admission (router
  ``submit`` for fleet traffic, ``QueryService.submit`` standalone,
  ``AvatarSession.frame`` for anim) and propagated *explicitly*: the
  serving tier stamps it into the ledger record's meta
  (``request_id``/``seq``/``replica``/``routing_key``/...), rides it on
  the record through the engine executor's thread hop, and binds it
  around worker-side work so spans opened on any thread tag
  ``request_id`` and parent under the request's root span
  (obs/trace.py's context fallback).
- **Tail sampling** (:class:`TraceTail`): spans stay cheap-always-on,
  but full span *trees* are retained per-request only for the tail —
  every deadline-miss/error/spilled request, plus a bounded reservoir
  of the slowest ``ok`` ones — in a bounded ring that flight-recorder
  incidents embed as their ``requests`` tail (schema v4), joining
  ledger row + span tree by request_id.

``request_id`` is a seeded CRC of ``(tenant, seq, admit)`` — unique
enough to join evidence within a fleet's retention window, cheap enough
to mint per request, and carrying no request payload.  It belongs in
ledger meta, span attrs, and histogram *exemplars* — never in metric
label values (the meshlint OBS006 rule enforces that statically).

Kill switch: ``MESH_TPU_TRACE_CONTEXT=0`` makes :func:`mint` return
``None`` and every propagation site no-op — bit-identical to the
identity-free path (pinned by test).

Stdlib-only; imports nothing from obs/trace.py (trace.py imports *this*
module for the parent fallback, so the dependency is one-way).
"""

import json
import threading
import zlib
from collections import deque
from contextlib import contextmanager

from ..utils import knobs

__all__ = [
    "RequestContext", "TraceTail", "TRACE_TAIL", "mint", "bind_context",
    "current_context", "trace_context_enabled", "get_trace_tail",
]


def trace_context_enabled():
    """``MESH_TPU_TRACE_CONTEXT=0`` = no identity anywhere (kill
    switch; re-read per mint so tests can toggle at runtime)."""
    return knobs.flag("MESH_TPU_TRACE_CONTEXT")


class RequestContext(object):
    """One request's identity, minted at admission.

    ``root_span_id`` is filled in by the serving tier when the
    request's root span opens; spans opened later on *other* threads
    (the executor's drain/dispatch hop) parent under it when their own
    thread-local span stack is empty.
    """

    __slots__ = ("request_id", "tenant", "seq", "routing_key", "replica",
                 "session_id", "spilled", "root_span_id")

    def __init__(self, request_id, tenant, seq, routing_key=None,
                 replica=None, session_id=None):
        self.request_id = request_id
        self.tenant = tenant
        self.seq = seq
        self.routing_key = routing_key
        self.replica = replica
        self.session_id = session_id
        self.spilled = False
        self.root_span_id = None

    def to_meta(self):
        """The JSON-able identity fields the ledger record's meta
        carries (the join key set `mesh-tpu prof trace` looks up)."""
        meta = {"request_id": self.request_id, "seq": self.seq}
        if self.routing_key is not None:
            meta["routing_key"] = self.routing_key
        if self.replica is not None:
            meta["replica"] = self.replica
        if self.session_id is not None:
            meta["session_id"] = self.session_id
        if self.spilled:
            meta["spilled"] = True
        return meta

    def __repr__(self):
        return ("RequestContext(%s, tenant=%r, seq=%r)"
                % (self.request_id, self.tenant, self.seq))


def mint(tenant, seq, admit, routing_key=None, replica=None,
         session_id=None):
    """Mint one request's context (or ``None`` with the kill switch
    off).  The id is a seeded CRC of ``(tenant, seq, admit)`` — stable
    for a given admission, unique within a retention window."""
    if not trace_context_enabled():
        return None
    payload = json.dumps([str(tenant), int(seq), round(float(admit), 6)],
                         separators=(",", ":"))
    request_id = "req-%08x" % (zlib.crc32(payload.encode("utf-8"))
                               & 0xFFFFFFFF)
    return RequestContext(request_id, tenant, int(seq),
                          routing_key=routing_key, replica=replica,
                          session_id=session_id)


# -- thread-local binding ---------------------------------------------------

_TLS = threading.local()


@contextmanager
def bind_context(ctx):
    """Bind ``ctx`` as the thread's current request identity for the
    block (``None`` binds nothing — the no-op the kill switch rides)."""
    if ctx is None:
        yield None
        return
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = ctx
    try:
        yield ctx
    finally:
        _TLS.ctx = prev


def current_context():
    """The thread's bound :class:`RequestContext`, or ``None``."""
    return getattr(_TLS, "ctx", None)


# -- tail sampling ----------------------------------------------------------

#: hard cap on distinct request_ids buffering finished spans at once —
#: an unclosed record can never grow the pending map without bound
_PENDING_REQUESTS_MAX = 1024
#: spans buffered per request before the oldest are dropped
_SPANS_PER_REQUEST_MAX = 256


class TraceTail(object):
    """Per-process bounded ring of retained request traces.

    Fed from two sides: a tracer sink buffers every finished span that
    carries a ``request_id`` attr, and the ledger's close path calls
    :meth:`observe_close` with the closed row — which either *retains*
    the request (ledger row + buffered span tree) or drops its spans.

    Retention policy (the tail-sampling contract, doc/observability.md):
    every request whose outcome is not ``ok`` — deadline misses, errors,
    cancellations — and every spilled request keeps its full trace;
    ``ok`` requests compete for a small reservoir that keeps the
    slowest ones.  The ring is bounded (``MESH_TPU_TRACE_TAIL``), so a
    storm of misses ages out the oldest traces instead of growing.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = {}            # request_id -> [span dict, ...]
        self._ring = deque()          # retained entries, oldest first
        self._reservoir = []          # (total_s, request_id) of slow-ok

    # -- feed: tracer sink --------------------------------------------

    def record_span(self, event):
        """Tracer sink: buffer one finished span under its request_id
        (spans without one are not request-joinable and are skipped)."""
        attrs = event.get("attrs") or {}
        rid = attrs.get("request_id")
        if not rid:
            return
        with self._lock:
            spans = self._pending.get(rid)
            if spans is None:
                if len(self._pending) >= _PENDING_REQUESTS_MAX:
                    # oldest-inserted request's buffer is evicted
                    self._pending.pop(next(iter(self._pending)))
                spans = self._pending[rid] = []
            spans.append(event)
            if len(spans) > _SPANS_PER_REQUEST_MAX:
                del spans[0]

    # -- feed: ledger close -------------------------------------------

    def observe_close(self, row):
        """Ledger-close hook: decide retention for the closed row."""
        rid = row.get("request_id")
        if not rid:
            return
        with self._lock:
            spans = self._pending.pop(rid, None)
            outcome = row.get("outcome")
            tail = (outcome is not None and outcome != "ok") \
                or bool(row.get("spilled"))
            if not tail and not self._reserve_locked(rid, row):
                return
            self._ring.append({
                "request_id": rid,
                "outcome": outcome,
                "retained": "tail" if tail else "reservoir",
                "row": row,
                "spans": spans or [],
            })
            capacity = max(4, knobs.get_int("MESH_TPU_TRACE_TAIL") or 64)
            while len(self._ring) > capacity:
                self._ring.popleft()

    def _reserve_locked(self, rid, row):
        # slow-ok reservoir: keep the N slowest ok closes seen so far
        slots = knobs.get_int("MESH_TPU_TRACE_RESERVOIR")
        slots = 0 if slots is None else max(0, slots)
        if slots <= 0:
            return False
        total = row.get("total_s")
        if total is None:
            return False
        total = float(total)
        if len(self._reservoir) < slots:
            self._reservoir.append((total, rid))
            self._reservoir.sort()
            return True
        if total <= self._reservoir[0][0]:
            return False
        evicted = self._reservoir[0][1]
        self._reservoir[0] = (total, rid)
        self._reservoir.sort()
        # the evicted request's retained entry leaves the ring too
        for i, entry in enumerate(self._ring):
            if entry["request_id"] == evicted \
                    and entry["retained"] == "reservoir":
                del self._ring[i]
                break
        return True

    # -- query ---------------------------------------------------------

    def retained(self):
        """Retained entries, oldest first (what incidents embed)."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def lookup(self, request_id):
        """The retained entry for one request_id, or ``None``."""
        with self._lock:
            for entry in reversed(self._ring):
                if entry["request_id"] == request_id:
                    return dict(entry)
        return None

    def clear(self):
        with self._lock:
            self._pending.clear()
            self._ring.clear()
            del self._reservoir[:]


#: process singleton (obs.reset() clears it)
TRACE_TAIL = TraceTail()


def get_trace_tail():
    return TRACE_TAIL
