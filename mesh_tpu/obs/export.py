"""Exporters: JSON-lines dumps, Prometheus text, and span-tree rendering.

Three consumers, three formats (doc/observability.md):

- ``write_jsonl(path)`` — every buffered span as one JSON object per
  line plus a final ``{"kind": "metrics", ...}`` line with the registry
  snapshot; the pull counterpart of the live ``MESH_TPU_OBS_JSONL``
  sink (obs/trace.py).
- ``prometheus_text()`` — the registry in the Prometheus exposition
  format (``# HELP`` / ``# TYPE`` + samples; histograms as cumulative
  ``_bucket{le=...}`` / ``_sum`` / ``_count``), for scraping or the
  ``mesh-tpu stats --prom`` CLI.
- ``render_tree(events)`` — the nested ascii span tree ``mesh-tpu
  trace`` prints, grouped per thread so the executor worker's spans
  never interleave with facade callers'.
"""

import json

from .metrics import REGISTRY

__all__ = ["write_jsonl", "prometheus_text", "render_tree"]


def write_jsonl(path, events=None, registry=None):
    """Dump buffered spans + a final metrics snapshot as JSON lines.

    :param events: span event dicts; default the process tracer's buffer.
    :param registry: metrics registry; default the process registry.
    :returns: number of lines written.
    """
    if events is None:
        from .trace import TRACER

        events = TRACER.events()
    registry = registry or REGISTRY
    lines = 0
    with open(path, "w") as fh:
        for event in events:
            fh.write(json.dumps(event) + "\n")
            lines += 1
        fh.write(json.dumps(
            {"kind": "metrics", "metrics": registry.snapshot()}
        ) + "\n")
        lines += 1
    return lines


def _prom_escape(value):
    """Label-value escaping per the text-format spec: backslash, double
    quote, and line feed (a raw newline would truncate the sample)."""
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r'\"')
        .replace("\n", r"\n")
    )


def _prom_escape_help(value):
    """HELP-line escaping per the spec: backslash and line feed only."""
    return str(value).replace("\\", r"\\").replace("\n", r"\n")


def _prom_labels(labels, extra=None):
    items = list(labels.items()) + (list(extra.items()) if extra else [])
    if not items:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, _prom_escape(v)) for k, v in items
    )


def _prom_num(value):
    if value == float("inf"):
        return "+Inf"
    return repr(float(value))


def prometheus_text(registry=None):
    """The registry in Prometheus exposition format (text/plain 0.0.4)."""
    registry = registry or REGISTRY
    out = []
    for name, snap in registry.snapshot().items():
        if snap["help"]:
            out.append("# HELP %s %s" % (name, _prom_escape_help(snap["help"])))
        out.append("# TYPE %s %s" % (name, snap["type"]))
        for series in snap["series"]:
            labels = series["labels"]
            if snap["type"] == "histogram":
                for bound, cumulative in series["buckets"]:
                    le = "+Inf" if bound == "+Inf" else _prom_num(bound)
                    out.append("%s_bucket%s %d" % (
                        name, _prom_labels(labels, {"le": le}), cumulative
                    ))
                out.append("%s_sum%s %s" % (
                    name, _prom_labels(labels), _prom_num(series["sum"])
                ))
                out.append("%s_count%s %d" % (
                    name, _prom_labels(labels), series["count"]
                ))
            else:
                out.append("%s%s %s" % (
                    name, _prom_labels(labels), _prom_num(series["value"])
                ))
    return "\n".join(out) + "\n"


def _fmt_ms(seconds):
    if seconds is None:
        return "?"
    return "%.3f ms" % (seconds * 1e3)


def render_tree(events=None):
    """Ascii tree of a span event list (default: the tracer's buffer).

    Spans nest by ``parent_id``; roots sort by start time; each thread
    gets its own heading.  A parent evicted from the bounded ring leaves
    its children rendered as roots (annotated), never dropped.
    """
    if events is None:
        from .trace import TRACER

        events = TRACER.events()
    if not events:
        return "(no spans recorded — is MESH_TPU_OBS=1 set?)"
    by_id = {e["span_id"]: e for e in events}
    children = {}
    roots_by_thread = {}
    for e in sorted(events, key=lambda e: (e["t_mono"] or 0)):
        parent = e.get("parent_id")
        if parent is not None and parent in by_id:
            children.setdefault(parent, []).append(e)
        else:
            roots_by_thread.setdefault(e.get("thread") or "?", []).append(e)

    lines = []

    def emit(e, depth):
        attrs = e.get("attrs") or {}
        detail = " ".join("%s=%s" % (k, v) for k, v in sorted(attrs.items()))
        sync = e.get("sync_elapsed_s")
        label = "%s%s  [%s%s]%s%s" % (
            "  " * depth + ("- " if depth else ""),
            e["name"],
            _fmt_ms(e.get("elapsed_s")),
            ", sync %s" % _fmt_ms(sync) if sync is not None else "",
            " " + detail if detail else "",
            " !%s" % e["status"] if e.get("status") not in (None, "ok") else "",
        )
        lines.append(label)
        for child in children.get(e["span_id"], []):
            emit(child, depth + 1)

    for thread, roots in roots_by_thread.items():
        lines.append("thread %s:" % thread)
        for root in roots:
            if root.get("parent_id") is not None:
                lines.append("  (parent span evicted from buffer)")
            emit(root, 1)
    return "\n".join(lines)
