"""mesh_tpu.obs: the unified observability subsystem.

One place for everything the serving stack measures (doc/observability.md;
SURVEY.md section 5 names the reference's total lack of tracing/profiling
as a gap to fill, and the engine's value is invisible without it):

- **spans** (obs/trace.py) — nested, thread-aware timed regions through
  the hot path: facade -> engine submit -> (plan hit|compile) ->
  dispatch, plus the executor worker and batch entry points.  Gated by
  ``MESH_TPU_OBS`` (off by default: no-ops, < 5% overhead pinned by
  ``bench.py --obs-overhead`` and tests/test_bench_guard.py).
- **metrics** (obs/metrics.py) — the always-on labeled
  counter/gauge/histogram registry; ``engine.stats()`` is a
  compatibility snapshot view over it since the PR-2 migration.
- **exporters** (obs/export.py) — JSON-lines (live sink via
  ``MESH_TPU_OBS_JSONL=path`` or pull via ``export_jsonl``), Prometheus
  text, the ascii span tree, and a ``jax.profiler.TraceAnnotation``
  bridge (``MESH_TPU_OBS_JAX_TRACE=1``) annotating TensorBoard device
  traces.  CLI: ``mesh-tpu stats`` / ``mesh-tpu trace``.
- **jax bridge** (obs/jax_bridge.py) — jax.monitoring events
  (persistent compilation-cache hits/misses, compile durations) folded
  into the same registry.
- **flight recorder** (obs/recorder.py) — the always-on bounded event
  ring + triggered JSON incident dumps (``mesh-tpu incidents``),
  running even with ``MESH_TPU_OBS`` off (kill switch:
  ``MESH_TPU_RECORDER=0``; cost pinned by ``bench.py
  --recorder-overhead``).
- **perf harness** (obs/perf.py) — the staged, subprocess-isolated
  bench pipeline (per-stage timeouts, incremental ``bench_partial.json``
  persistence, ``bench_stage_hang`` incident dumps) and the jax-free
  ``mesh-tpu perfcheck`` regression gate (doc/benchmarking.md).
- **SLOs** (obs/slo.py) — declarative latency/availability objectives
  per tenant, evaluated from the registry with multi-window
  multi-burn-rate alerting; a fast-burn breach dumps an incident and
  (``MESH_TPU_SLO_DRIVES_HEALTH=1``) trips the serving health machine.

Import cost: stdlib only — jax is touched lazily and never required.
"""

from .clock import enabled, monotonic, wall  # noqa: F401
from .context import (  # noqa: F401
    TRACE_TAIL,
    RequestContext,
    TraceTail,
    bind_context,
    current_context,
    get_trace_tail,
    mint,
    trace_context_enabled,
)
from .export import prometheus_text, render_tree, write_jsonl  # noqa: F401
from .jax_bridge import install_jax_monitoring_bridge  # noqa: F401
from .ledger import (  # noqa: F401
    LEDGER,
    LEDGER_STAGES,
    LatencyLedger,
    RequestRecord,
    bind_current,
    current_record,
    get_ledger,
    ledger_enabled,
)
from .metrics import (  # noqa: F401
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
)
from .perf import (  # noqa: F401
    StageResult,
    StageSpec,
    call_with_timeout,
    perfcheck,
    reap_child,
    run_stages,
)
from .replay import (  # noqa: F401
    TRACE_SCHEMA,
    ReplayError,
    TraceWriter,
    admission_events,
    load_trace,
    null_replay,
    sequence_checksum,
    synthesize,
    trace_from_incident,
    trace_from_ledger,
    write_trace,
)
from .recorder import (  # noqa: F401
    RECORDER,
    FlightRecorder,
    default_incident_dir,
    get_recorder,
    list_incidents,
    recorder_enabled,
)
from .series import (  # noqa: F401
    SERIES,
    SampleRing,
    WindowedSeries,
    get_series,
    quantile_from_cumulative,
)
from .slo import (  # noqa: F401
    SLO,
    BurnRateRule,
    SLOMonitor,
    bind_incident_response,
    compliance,
    default_rules,
    default_slos,
)
from .trace import (  # noqa: F401
    TRACER,
    Tracer,
    configure,
    jsonl_sink,
    span,
    timed_span,
    traced,
)

__all__ = [
    "enabled", "span", "timed_span", "traced", "configure", "jsonl_sink",
    "TRACER", "Tracer",
    "REGISTRY", "Registry", "Counter", "Gauge", "Histogram",
    "LATENCY_BUCKETS_S",
    "counter", "gauge", "histogram", "metrics_snapshot", "reset",
    "prometheus_text", "render_tree", "write_jsonl", "export_jsonl",
    "install_jax_monitoring_bridge",
    "RECORDER", "FlightRecorder", "get_recorder", "recorder_enabled",
    "default_incident_dir", "list_incidents",
    "LEDGER", "LEDGER_STAGES", "LatencyLedger", "RequestRecord",
    "get_ledger", "ledger_enabled", "bind_current", "current_record",
    "TRACE_TAIL", "RequestContext", "TraceTail", "bind_context",
    "current_context", "get_trace_tail", "mint", "trace_context_enabled",
    "SERIES", "SampleRing", "WindowedSeries", "get_series",
    "quantile_from_cumulative",
    "SLO", "BurnRateRule", "SLOMonitor", "default_slos", "default_rules",
    "compliance", "bind_incident_response",
    "monotonic", "wall",
    "StageSpec", "StageResult", "call_with_timeout", "reap_child",
    "run_stages", "perfcheck",
    "TRACE_SCHEMA", "ReplayError", "TraceWriter", "load_trace",
    "write_trace", "trace_from_ledger", "trace_from_incident",
    "admission_events", "sequence_checksum", "null_replay", "synthesize",
]


def counter(name, help=""):
    """Get-or-create a counter in the process registry."""
    return REGISTRY.counter(name, help)


def gauge(name, help=""):
    return REGISTRY.gauge(name, help)


def histogram(name, help="", buckets=LATENCY_BUCKETS_S):
    return REGISTRY.histogram(name, help, buckets=buckets)


def metrics_snapshot():
    """JSON-able snapshot of every registered metric (the exact object
    bench.py appends to its records under the ``"obs"`` key)."""
    return REGISTRY.snapshot()


#: pull-mode JSON-lines export (spans + final metrics snapshot)
export_jsonl = write_jsonl


def reset():
    """Zero every metric series, drop buffered spans, and empty the
    flight-recorder ring, latency-ledger ring, windowed-series ring,
    and the tuned-knob layer (tests, and the per-run isolation of the
    CLI subcommands)."""
    from ..utils import tuning

    REGISTRY.reset()
    TRACER.clear()
    RECORDER.clear()
    LEDGER.clear()
    TRACE_TAIL.clear()
    SERIES.clear()
    tuning.reset()
