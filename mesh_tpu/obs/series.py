"""Windowed time-series over the metrics registry.

The registry (obs/metrics.py) is cumulative-since-process-start: perfect
for Prometheus scrapes, useless on its own for "what is the p99 over the
last minute" or "how fast is the error budget burning NOW".  This module
adds the windowed view both consumers need:

- :class:`SampleRing` — a bounded history of cumulative samples with
  window-boundary deltas.  This is the *one* implementation of the
  "difference of the samples bracketing the window" arithmetic: the SLO
  monitor's burn rates (obs/slo.py) read their windowed (good, total)
  deltas from it instead of carrying their own ad-hoc loop.
- :class:`WindowedSeries` — a ring of fixed-resolution (1 s by default)
  registry snapshots with rate / delta / percentile queries over any
  trailing window, including histogram quantiles by bucket-delta
  interpolation.  This is what turns the per-request
  ``mesh_tpu_request_stage_seconds{stage,backend}`` histogram
  (obs/ledger.py) into "queue p99 over the last 60 s" for dashboards
  and the ``mesh-tpu prof`` CLI.

Every clock read goes through the injected ``clock`` (default
``obs.clock.monotonic``) so tests drive windows deterministically with a
fake clock.  Stdlib-only; safe for the jax-free CLI subcommands.
"""

import threading
from collections import deque

from .clock import monotonic
from .metrics import REGISTRY

__all__ = ["SampleRing", "WindowedSeries", "SERIES", "get_series",
           "quantile_from_cumulative"]


def quantile_from_cumulative(buckets, q):
    """The q-quantile (``q`` in [0, 1]) from a cumulative bucket list
    ``[[bound, cum], ..., ["+Inf", total]]`` by linear interpolation
    inside the landing bucket; observations past the largest finite
    bound report that bound.  None with zero observations."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = max(float(q), 0.0) * total
    lower, prev_cum = 0.0, 0
    for bound, cum in buckets:
        d = cum - prev_cum
        if cum >= rank and d > 0:
            if bound == "+Inf":
                return lower            # best finite estimate
            bound = float(bound)
            frac = (rank - prev_cum) / d
            return lower + (bound - lower) * max(frac, 0.0)
        prev_cum = cum
        if bound != "+Inf":
            lower = float(bound)
    return lower


class SampleRing(object):
    """Bounded history of cumulative ``(t, v0, v1, ...)`` samples.

    ``append()`` records one cumulative observation; ``deltas()`` answers
    "how much did each value grow over the trailing window" by
    differencing the newest sample against the window boundary — the
    newest sample at/before ``now - window_s``, falling back to the
    oldest retained sample when history is shorter than the window (the
    SLO monitor's burn-rate semantics, now shared).
    """

    __slots__ = ("_samples",)

    def __init__(self, history=1024, samples=None):
        self._samples = deque(samples or (), maxlen=int(history))

    def __len__(self):
        return len(self._samples)

    def append(self, t, values):
        """Record one cumulative sample: ``values`` is a tuple/list of
        monotonically growing numbers observed at time ``t``."""
        self._samples.append((float(t),) + tuple(values))

    def latest(self):
        """The newest ``(t, v0, ...)`` sample (raises IndexError when
        empty)."""
        return self._samples[-1]

    def boundary(self, start_t):
        """Newest sample at/before ``start_t`` (window baseline); falls
        back to the oldest retained sample when history is shorter than
        the window."""
        boundary = self._samples[0]
        for sample in self._samples:
            if sample[0] <= start_t:
                boundary = sample
            else:
                break
        return boundary

    def deltas(self, window_s, now):
        """Per-value growth over ``[now - window_s, now]`` as a tuple
        (newest minus boundary); all-zeros when fewer than one sample."""
        if not self._samples:
            return ()
        base = self.boundary(now - float(window_s))
        last = self._samples[-1]
        return tuple(last[i] - base[i] for i in range(1, len(last)))

    def copy(self):
        """A snapshot copy safe to query while the original keeps
        appending (same bounded capacity)."""
        return SampleRing(history=self._samples.maxlen,
                          samples=list(self._samples))


# ---------------------------------------------------------------------------
# windowed registry snapshots


def _match(labels, want):
    """True when the series' label dict contains every (k, v) in the
    ``want`` filter (values compared as strings, the registry's canonical
    form)."""
    if not want:
        return True
    for key, value in want.items():
        if labels.get(key) != str(value):
            return False
    return True


def _counter_value(entry, want):
    """Summed value of every matching series in a counter/gauge
    snapshot entry."""
    total = 0
    for series in entry.get("series", []):
        if _match(series.get("labels", {}), want):
            total += series.get("value", 0)
    return total


def _hist_state(entry, want):
    """(count, sum, cumulative-bucket list) summed over every matching
    series of a histogram snapshot entry; None when nothing matches."""
    count, total, buckets = 0, 0.0, None
    for series in entry.get("series", []):
        if not _match(series.get("labels", {}), want):
            continue
        count += series.get("count", 0)
        total += series.get("sum", 0.0)
        cum = series.get("buckets", [])
        if buckets is None:
            buckets = [[bound, c] for bound, c in cum]
        else:
            for i, (_, c) in enumerate(cum):
                buckets[i][1] += c
    if buckets is None:
        return None
    return count, total, buckets


class WindowedSeries(object):
    """Ring of fixed-resolution cumulative registry snapshots.

    ``tick()`` files the current registry state into the window whose
    start covers ``now`` (one snapshot per resolution window; a second
    tick inside the same window refreshes it).  Queries difference the
    newest snapshot against the one bracketing the requested trailing
    window — the same boundary semantics as :class:`SampleRing`.
    Thread-safe; capacity-bounded (default 120 windows of 1 s = two
    minutes of history).
    """

    def __init__(self, registry=None, resolution_s=1.0, capacity=120,
                 clock=monotonic):
        self._registry = registry if registry is not None else REGISTRY
        self.resolution_s = float(resolution_s)
        self._ring = deque(maxlen=int(capacity))    # (window_start, snapshot)
        self._clock = clock
        self._lock = threading.Lock()

    # -- sampling ------------------------------------------------------

    def tick(self, now=None):
        """Snapshot the registry into the current window; returns the
        window start time."""
        now = self._clock() if now is None else float(now)
        start = int(now / self.resolution_s) * self.resolution_s
        snap = self._registry.snapshot()
        with self._lock:
            if self._ring and self._ring[-1][0] == start:
                self._ring[-1] = (start, snap)
            else:
                self._ring.append((start, snap))
        return start

    def clear(self):
        with self._lock:
            self._ring.clear()

    def windows(self):
        """Retained (window_start, snapshot) pairs, oldest first."""
        with self._lock:
            return list(self._ring)

    def _bracket(self, window_s, now):
        """(baseline snapshot or None, newest snapshot) for the trailing
        window; (None, None) with no history."""
        with self._lock:
            ring = list(self._ring)
        if not ring:
            return None, None
        if now is None:
            now = ring[-1][0]
        start_t = float(now) - float(window_s)
        baseline = None
        for t, snap in ring:
            if t <= start_t:
                baseline = snap
            else:
                break
        return baseline, ring[-1][1]

    # -- queries -------------------------------------------------------

    def delta(self, name, window_s=60.0, now=None, labels=None):
        """Counter growth of ``name`` over the trailing window (summed
        over series matching the ``labels`` filter).  The oldest retained
        window is the baseline when history is shorter than the window;
        0 with no history."""
        base, last = self._bracket(window_s, now)
        if last is None:
            return 0
        entry = last.get(name)
        if entry is None:
            return 0
        value = _counter_value(entry, labels)
        if base is not None and name in base:
            value -= _counter_value(base[name], labels)
        return value

    def rate(self, name, window_s=60.0, now=None, labels=None):
        """Counter growth per second over the trailing window."""
        return self.delta(name, window_s, now, labels) / float(window_s)

    def percentile(self, name, q, window_s=60.0, now=None, labels=None):
        """The q-quantile (``q`` in [0, 1]) of histogram ``name`` over
        the trailing window, from bucket-count deltas with linear
        interpolation inside the landing bucket (Prometheus
        ``histogram_quantile`` semantics; observations past the largest
        finite bound report that bound).  None with no observations in
        the window."""
        base, last = self._bracket(window_s, now)
        if last is None or name not in last:
            return None
        state = _hist_state(last[name], labels)
        if state is None:
            return None
        _, _, buckets = state
        base_state = (_hist_state(base[name], labels)
                      if base is not None and name in base else None)
        windowed = []
        for i, (bound, cum_new) in enumerate(buckets):
            cum_old = base_state[2][i][1] if base_state is not None else 0
            windowed.append([bound, cum_new - cum_old])
        return quantile_from_cumulative(windowed, q)

    def _at(self, t):
        """Newest snapshot at/before ``t`` (None when history starts
        later than ``t`` or is empty)."""
        with self._lock:
            ring = list(self._ring)
        snap = None
        for window_t, s in ring:
            if window_t <= float(t):
                snap = s
            else:
                break
        return snap

    def window_percentile(self, name, q, start_t, end_t, labels=None):
        """The q-quantile of histogram ``name`` over the ABSOLUTE window
        ``[start_t, end_t]`` — unlike :meth:`percentile`, the window end
        need not be "now", so the shadow A/B guard (obs/controller.py)
        can read its before-change hold-out window after the fact.  None
        with no snapshot at/before ``end_t`` or no observations in the
        window."""
        last = self._at(end_t)
        if last is None or name not in last:
            return None
        state = _hist_state(last[name], labels)
        if state is None:
            return None
        base = self._at(start_t)
        base_state = (_hist_state(base[name], labels)
                      if base is not None and name in base else None)
        windowed = []
        for i, (bound, cum_new) in enumerate(state[2]):
            cum_old = base_state[2][i][1] if base_state is not None else 0
            windowed.append([bound, cum_new - cum_old])
        return quantile_from_cumulative(windowed, q)

    def stage_breakdown(self, window_s=60.0, now=None,
                        name="mesh_tpu_request_stage_seconds"):
        """Per-(stage, backend) {count, p50_s, p99_s} over the trailing
        window of the request-stage histogram — the live view behind
        ``mesh-tpu prof top``."""
        base, last = self._bracket(window_s, now)
        if last is None or name not in last:
            return {}
        label_sets = []
        for series in last[name].get("series", []):
            labels = series.get("labels", {})
            key = (labels.get("stage", "?"), labels.get("backend", "?"))
            if key not in label_sets:
                label_sets.append(key)
        out = {}
        for stage, backend in label_sets:
            want = {"stage": stage, "backend": backend}
            state = _hist_state(last[name], want)
            n = state[0] if state else 0
            if base is not None and name in base:
                base_st = _hist_state(base[name], want)
                if base_st:
                    n -= base_st[0]
            if n <= 0:
                continue
            out[(stage, backend)] = {
                "count": n,
                "p50_s": self.percentile(name, 0.50, window_s, now, want),
                "p99_s": self.percentile(name, 0.99, window_s, now, want),
            }
        return out


#: the process-wide windowed view (periodic loops — the SLO monitor's
#: sampling thread — call SERIES.tick(); queries are always safe)
SERIES = WindowedSeries()


def get_series():
    """The process-wide WindowedSeries (one place to monkeypatch)."""
    return SERIES
