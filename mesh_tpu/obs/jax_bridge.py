"""jax.monitoring -> metrics-registry bridge.

JAX instruments its own internals (persistent compilation-cache hits and
misses, tracing/compile durations) through ``jax.monitoring`` events.
Registering listeners here folds those into the framework registry, so
the question PR 1 left open — "did warmup() actually LOAD plans from the
disk cache, or recompile them?" — is answered by
``mesh_tpu_xla_cache_hits_total`` in the same snapshot as the engine's
own plan-cache counters.

Installed (idempotently) by
``utils.compilation_cache.enable_persistent_compilation_cache``; safe on
any jax version — an absent/renamed monitoring API degrades to a logged
no-op, never an error.
"""

import logging
import threading

from .metrics import REGISTRY

__all__ = ["install_jax_monitoring_bridge"]

_log = logging.getLogger(__name__)

_install_lock = threading.Lock()
_installed = False

#: jax event key -> framework counter (other events fall through to the
#: generic per-event counter below, so new jax versions stay visible)
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": (
        "mesh_tpu_xla_cache_hits_total",
        "Persistent XLA compilation-cache hits (compiles served from disk).",
    ),
    "/jax/compilation_cache/cache_misses": (
        "mesh_tpu_xla_cache_misses_total",
        "Persistent XLA compilation-cache misses (fresh compiles).",
    ),
    "/jax/compilation_cache/task_disabled_cache": (
        "mesh_tpu_xla_cache_disabled_total",
        "Compilation tasks that ran with the persistent cache disabled.",
    ),
}


def install_jax_monitoring_bridge(registry=None):
    """Register the jax.monitoring listeners once per process.

    :returns: True when the listeners are active (now or already).
    """
    global _installed
    registry = registry or REGISTRY
    with _install_lock:
        if _installed:
            return True
        try:
            from jax import monitoring
        except Exception as e:
            _log.debug("jax.monitoring unavailable: %s", e)
            return False

        generic = registry.counter(
            "mesh_tpu_jax_events_total",
            "Unmapped jax.monitoring events, labeled by event key.",
        )
        durations = registry.histogram(
            "mesh_tpu_jax_event_duration_seconds",
            "jax.monitoring duration events (compiles, tracing, ...).",
        )

        def on_event(event, **kwargs):
            try:
                mapped = _EVENT_COUNTERS.get(event)
                if mapped is not None:
                    registry.counter(*mapped).inc()
                else:
                    generic.inc(event=event)
            except Exception:   # monitoring must never break compilation
                pass

        def on_duration(event, duration, **kwargs):
            try:
                durations.observe(duration, event=event)
            except Exception:
                pass

        try:
            monitoring.register_event_listener(on_event)
            monitoring.register_event_duration_secs_listener(on_duration)
        except Exception as e:
            _log.debug("jax.monitoring listener registration failed: %s", e)
            return False
        _installed = True
        return True
