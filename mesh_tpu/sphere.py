"""Analytic sphere primitive (reference mesh/sphere.py).

The reference hardcodes a 42-vertex icosphere table; here the same mesh is
generated: an icosahedron subdivided once with midpoints projected onto the
unit sphere (42 vertices, 80 faces).
"""

import numpy as np

from .colors import name_to_rgb
from .mesh import Mesh

__all__ = ["Sphere"]


def _icosphere(subdivisions=1):
    phi = (1.0 + np.sqrt(5.0)) / 2.0
    v = np.array(
        [
            [-1, phi, 0], [1, phi, 0], [-1, -phi, 0], [1, -phi, 0],
            [0, -1, phi], [0, 1, phi], [0, -1, -phi], [0, 1, -phi],
            [phi, 0, -1], [phi, 0, 1], [-phi, 0, -1], [-phi, 0, 1],
        ],
        dtype=np.float64,
    )
    v /= np.linalg.norm(v[0])
    f = np.array(
        [
            [0, 11, 5], [0, 5, 1], [0, 1, 7], [0, 7, 10], [0, 10, 11],
            [1, 5, 9], [5, 11, 4], [11, 10, 2], [10, 7, 6], [7, 1, 8],
            [3, 9, 4], [3, 4, 2], [3, 2, 6], [3, 6, 8], [3, 8, 9],
            [4, 9, 5], [2, 4, 11], [6, 2, 10], [8, 6, 7], [9, 8, 1],
        ],
        dtype=np.int64,
    )
    for _ in range(subdivisions):
        verts = list(v)
        midpoint_cache = {}

        def midpoint(i, j):
            key = (min(i, j), max(i, j))
            if key not in midpoint_cache:
                m = (v[i] + v[j]) / 2.0
                m /= np.linalg.norm(m)
                midpoint_cache[key] = len(verts)
                verts.append(m)
            return midpoint_cache[key]

        new_f = []
        for a, b, c in f:
            ab, bc, ca = midpoint(a, b), midpoint(b, c), midpoint(c, a)
            new_f += [[a, ab, ca], [b, bc, ab], [c, ca, bc], [ab, bc, ca]]
        v = np.array(verts)
        f = np.array(new_f, dtype=np.int64)
    return v, f


class Sphere(object):
    def __init__(self, center, radius):
        center = np.asarray(center)
        if center.flatten().shape != (3,):
            raise ValueError(
                "Center should have size(1,3) instead of %s" % (center.shape,)
            )
        self.center = center.flatten()
        self.radius = radius

    def __str__(self):
        return "%s:%s" % (self.center, self.radius)

    def to_mesh(self, color=name_to_rgb["red"]):
        v, f = _icosphere(1)
        return Mesh(
            v=v * self.radius + self.center,
            f=f,
            vc=np.tile(color, (v.shape[0], 1)),
        )

    def has_inside(self, point):
        return np.linalg.norm(point - self.center) <= self.radius

    def intersects(self, sphere):
        return np.linalg.norm(sphere.center - self.center) < (self.radius + sphere.radius)

    def intersection_vol(self, sphere):
        """Lens volume of two overlapping spheres
        (mathworld.wolfram.com/Sphere-SphereIntersection.html)."""
        if not self.intersects(sphere):
            return 0
        d = np.linalg.norm(sphere.center - self.center)
        R, r = (
            (self.radius, sphere.radius)
            if self.radius > sphere.radius
            else (sphere.radius, self.radius)
        )
        if R >= (d + r):
            return (4 * np.pi * (r ** 3)) / 3
        return (
            np.pi
            * (R + r - d) ** 2
            * (d ** 2 + 2 * d * r - 3 * r * r + 2 * d * R + 6 * r * R - 3 * R * R)
        ) / (12 * d)
