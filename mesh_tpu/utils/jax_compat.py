"""Version-compat shims for jax API renames the package straddles.

The package targets the current jax spellings, but the pinned container
environments (and some user installs) carry jax 0.4.x, where two of the
APIs we use live under older names:

- ``pallas.tpu.CompilerParams`` was ``TPUCompilerParams`` before the
  0.5-era rename;
- ``jax.shard_map`` lived at ``jax.experimental.shard_map.shard_map``,
  with ``check_vma`` spelled ``check_rep``.

Each shim resolves the modern name first, so on new jax these are
zero-cost pass-throughs and the deprecated spellings can be dropped by
deleting this module.
"""

import jax

__all__ = ["tpu_compiler_params", "shard_map", "enable_x64"]


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` under whichever name this jax
    ships (``TPUCompilerParams`` on 0.4.x)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def shard_map(f, **kwargs):
    """``jax.shard_map`` with the pre-0.5 fallback (and its ``check_rep``
    kwarg spelling).  Same call shape as the modern API, so
    ``partial(shard_map, mesh=..., in_specs=..., out_specs=...)`` keeps
    working as a decorator."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm  # noqa: F811

        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return sm(f, **kwargs)


def enable_x64(enabled=True):
    """``jax.enable_x64(...)`` context manager under whichever name this
    jax ships (``jax.experimental.enable_x64`` on 0.4.x)."""
    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx  # noqa: F811
    return ctx(enabled)
