"""Mutable tunable-knob layer: the ONLY write path the closed-loop
controller (obs/controller.py) is allowed to use.

``utils/knobs.py`` declares the static environment registry; this module
layers a small set of *tunables* on top of it — knobs the controller may
move at runtime, each declared once with bounds, a step size, and the
environment variable that hard-pins it.  The contract:

- **env pins win.**  A tunable whose pin variable is set in the
  environment always reads the pinned value and silently refuses
  actuation — the operator's explicit choice beats the controller.
- **defaults are today's behavior.**  Every tunable's default equals the
  static pre-tuner behavior, and ``MESH_TPU_TUNER=0`` freezes every
  tunable at that default, so the kill switch (and an untouched layer)
  is bit-identical to the static code path.
- **one write path.**  :func:`actuate` clamps to bounds, bumps the
  process-wide generation counter, appends to the bounded knob-change
  history (the flight recorder's incident ``knob_history`` tail), emits
  a ``knob_change`` flight-recorder event with before/after/evidence,
  and moves the ``mesh_tpu_tuner_*`` series.  The meshlint KNB003 rule
  fails the build on any other write to tunable state, so ad-hoc
  mutation can't bypass the A/B gate or the audit trail.

Stdlib-only (the jax-free ``mesh-tpu tune`` CLI sits on it); obs is
imported lazily inside the actuation path only.
"""

import threading
from collections import OrderedDict, deque

from . import knobs

__all__ = [
    "TunableKnob", "tunables", "lookup", "enabled", "pinned", "get",
    "tuned_value", "generation", "actuate", "history_tail", "status",
    "reset",
]


class TunableKnob(object):
    """One declared runtime-tunable knob."""

    __slots__ = ("name", "kind", "default", "lo", "hi", "step",
                 "pin_env", "pin_means_default", "doc")

    def __init__(self, name, kind, default, lo, hi, step, pin_env, doc,
                 pin_means_default=False):
        self.name = name
        self.kind = kind              # "int" | "float"
        self.default = default
        self.lo = lo
        self.hi = hi
        self.step = step
        self.pin_env = pin_env        # env knob that hard-pins this tunable
        #: True: the pin env var configures something else explicitly
        #: (e.g. a hand-picked serve ladder) — its presence pins the
        #: tunable at the default rather than supplying a value.
        self.pin_means_default = pin_means_default
        self.doc = doc

    def clamp(self, value):
        value = max(self.lo, min(self.hi, value))
        return int(value) if self.kind == "int" else float(value)


#: declaration order is `mesh-tpu tune status` order
_TUNABLES = OrderedDict()

#: guards every piece of mutable tuner state below (declarations run at
#: import, but redeclaration from a reloading test is possible too)
_LOCK = threading.Lock()


def _declare_tunable(name, kind, default, lo, hi, step, pin_env, doc,
                     pin_means_default=False):
    with _LOCK:
        _TUNABLES[name] = TunableKnob(
            name, kind, default, lo, hi, step, pin_env, doc,
            pin_means_default=pin_means_default)
    return name


COALESCE_WINDOW_MS = _declare_tunable(
    "coalesce_window_ms", "float", 0.0, 0.0, 20.0, 1.0,
    "MESH_TPU_COALESCE_WINDOW_MS",
    "Executor drain-loop coalescing window (ms): how long the drain "
    "thread lingers after the first pending request to let a batch "
    "accumulate.  0 (default) drains immediately — the static "
    "behavior.")
ACCEL_MIN_FACES = _declare_tunable(
    "accel_min_faces", "int", None, 4096, 4194304, 32768,
    "MESH_TPU_ACCEL_MIN_FACES",
    "Tuned override for the accel crossover face count "
    "(query/autotune.py consults it between the env pin and the "
    "measured cache); None falls through to the calibrated chain.")
MXU_CROSSOVER = _declare_tunable(
    "mxu_crossover", "int", None, 1024, 4194304, 8192,
    "MESH_TPU_MXU_CROSSOVER_FACES",
    "Tuned override for the MXU dot-product crossover face count "
    "(query/autotune.py consults it between the env pin and the "
    "measured cache; only routes when MESH_TPU_MXU is on); None falls "
    "through to the calibrated chain.")
STREAM_N_BUFFERS = _declare_tunable(
    "stream_n_buffers", "int", None, 2, 8, 1,
    "MESH_TPU_BVH_STREAM_BUFFERS",
    "Tuned override for the streamed-BVH leaf-ring buffer count; None "
    "falls through to the calibrated chain.")
SHARD_MIN_Q = _declare_tunable(
    "shard_min_q", "int", None, 1024, 1048576, 4096,
    "MESH_TPU_FLEET_SHARD_MIN_Q",
    "Query count at which the engine routes a single-mesh closest-point "
    "dispatch through the dp-sharded big-batch lane "
    "(parallel/sharding.py; also gated by MESH_TPU_FLEET_SHARD); None "
    "(default) keeps the lane off — the static single-device path.")
ANIM_REFIT_MAX_INFLATION = _declare_tunable(
    "anim_refit_max_inflation", "float", 1.5, 1.05, 4.0, 0.05,
    "MESH_TPU_ANIM_REFIT_MAX_INFLATION",
    "Refit/rebuild crossover for avatar sessions (mesh_tpu/anim/): the "
    "box-inflation ratio (refit boxes vs the fresh boxes captured at "
    "the last rebuild) past which a frame pays a full host rebuild "
    "through the digest cache.  1.5 (default) tolerates moderate "
    "deformation; lower rebuilds more eagerly (better pruning, more "
    "host work), higher stretches the frozen Morton order further.")
SERVE_PRE_TRIP = _declare_tunable(
    "serve_pre_trip", "int", 0, 0, 1, 1,
    "MESH_TPU_SERVE_LADDER",
    "Latency-mode pre-trip: 1 makes QueryService start requests one "
    "rung down the degradation ladder before health actually degrades "
    "(fast-burn approaching).  Pinned to 0 whenever the operator set "
    "an explicit ladder.", pin_means_default=True)


# -- mutable state (guarded by _LOCK; actuate() is the only writer) --------

_values = {}                  # name -> tuned value
_generation = 0
#: bounded knob-change audit trail; history_tail() slices the incident
#: tail (MESH_TPU_KNOB_TAIL) off the newest end
_HISTORY_CAP = 64
_history = deque(maxlen=_HISTORY_CAP)


def tunables():
    """All declared tunables, in declaration order."""
    return list(_TUNABLES.values())


def lookup(name):
    """The :class:`TunableKnob` for ``name`` (KeyError on undeclared)."""
    try:
        return _TUNABLES[name]
    except KeyError:
        raise KeyError("undeclared tunable %r (declare it in "
                       "mesh_tpu/utils/tuning.py)" % (name,))


def enabled():
    """Tuner kill switch: ``MESH_TPU_TUNER=0`` freezes every tunable at
    its static default."""
    return knobs.flag("MESH_TPU_TUNER")


def pinned(name):
    """True when the tunable's environment pin is set — the operator's
    explicit value beats the controller, which must not actuate it."""
    tun = lookup(name)
    raw = knobs.raw(tun.pin_env)
    return raw is not None and bool(raw.strip())


def _pin_value(tun):
    if tun.pin_means_default:
        return tun.default
    if tun.kind == "int":
        value = knobs.get_int(tun.pin_env)
    else:
        value = knobs.get_float(tun.pin_env)
    return tun.default if value is None else value


def get(name):
    """The effective value: env pin > tuned value (tuner on) > default."""
    tun = lookup(name)
    if pinned(name):
        return _pin_value(tun)
    if not enabled():
        return tun.default
    with _LOCK:
        return _values.get(name, tun.default)


def tuned_value(name):
    """The actuated value only — None when the tuner is off, the knob is
    pinned, or nothing has been actuated (callers fall through to their
    static chain, e.g. autotune's measured cache)."""
    if not enabled() or pinned(name):
        return None
    with _LOCK:
        return _values.get(name)


def generation():
    """Process-wide actuation generation counter (0 = never actuated)."""
    with _LOCK:
        return _generation


def actuate(name, value, reason, evidence=None, action="set", now=None):
    """THE write path for tunable knobs (KNB003 enforces exclusivity).

    Clamps ``value`` to the declared bounds, bumps the generation
    counter, appends to the bounded history, emits a ``knob_change``
    flight-recorder event, and moves the ``mesh_tpu_tuner_*`` series.
    Returns the event dict, or None when the write was refused (tuner
    off / knob pinned) or a no-op (value unchanged).
    """
    tun = lookup(name)
    if not enabled() or pinned(name):
        return None
    value = tun.clamp(value)
    with _LOCK:
        before = _values.get(name, tun.default)
        if value == before:
            return None
        _values[name] = value
        global _generation
        _generation += 1
        event = {
            "knob": name, "action": action,
            "before": before, "after": value,
            "reason": reason, "generation": _generation,
            "evidence": dict(evidence or {}),
        }
        if now is not None:
            event["t"] = now
        _history.append(dict(event))
        gen = _generation
    _emit(event, gen)
    return event


def _emit(event, gen):
    # recorder + registry moves happen OUTSIDE _LOCK: the tuning lock
    # takes no other mesh_tpu lock, so it adds no ordering edges to
    # doc/concurrency.md's graph (events carry the generation, so the
    # audit trail stays reconstructible under concurrent actuation)
    from ..obs.recorder import get_recorder
    from ..obs.metrics import REGISTRY

    get_recorder().record("knob_change", **event)
    REGISTRY.counter(
        "mesh_tpu_tuner_changes_total",
        "knob_change actuations by the tuning layer",
    ).inc(knob=event["knob"], action=event["action"])
    REGISTRY.gauge(
        "mesh_tpu_tuner_generation",
        "process-wide tunable-knob actuation generation",
    ).set(gen)
    REGISTRY.gauge(
        "mesh_tpu_tuner_knob_value",
        "current tuned value per tunable knob",
    ).set(event["after"], knob=event["knob"])


def history_tail(k=None):
    """The newest ``k`` knob-change events (incident ``knob_history``
    tail; default ``MESH_TPU_KNOB_TAIL``), oldest first."""
    if k is None:
        k = max(1, knobs.get_int("MESH_TPU_KNOB_TAIL"))
    with _LOCK:
        events = list(_history)
    return [dict(e) for e in events[-k:]]


def status():
    """Per-tunable state for the jax-free `mesh-tpu tune status` CLI."""
    with _LOCK:
        values = dict(_values)
        gen = _generation
    live = enabled()
    rows = []
    for tun in tunables():
        is_pinned = pinned(tun.name)
        if is_pinned:
            value = _pin_value(tun)
        elif live:
            value = values.get(tun.name, tun.default)
        else:
            value = tun.default
        rows.append({
            "knob": tun.name, "value": value, "default": tun.default,
            "lo": tun.lo, "hi": tun.hi, "step": tun.step,
            "pinned": is_pinned, "pin_env": tun.pin_env,
            "tuned": (not is_pinned and live
                      and tun.name in values),
        })
    return {"enabled": live, "generation": gen, "knobs": rows}


def reset():
    """Drop every tuned value and the history (tests, obs.reset())."""
    global _generation
    with _LOCK:
        _values.clear()
        _history.clear()
        _generation = 0
