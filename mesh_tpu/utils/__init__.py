"""Shared utilities.  Lazy re-exports (PEP 562) so the stdlib-only
submodule (``knobs``) and the jax-free obs/ primitives that import it
never pay the numpy/scipy/jax import of ``arrays``/``profiling``."""

_LAZY = {
    "row": "arrays", "col": "arrays", "sparse": "arrays",
    "asarray_f32": "arrays", "asarray_i32": "arrays",
    "Timer": "profiling", "host_sync": "profiling",
    "time_fn": "profiling", "trace": "profiling",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        value = getattr(
            importlib.import_module("." + _LAZY[name], __name__), name)
        globals()[name] = value     # cache: __getattr__ runs once per name
        return value
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
