from .arrays import row, col, sparse, asarray_f32, asarray_i32  # noqa: F401
