from .arrays import row, col, sparse, asarray_f32, asarray_i32  # noqa: F401
from .profiling import Timer, host_sync, time_fn, trace  # noqa: F401
