"""Small array helpers (parity with reference mesh/utils.py:6-22).

`row`/`col`/`sparse` keep the reference's numpy/scipy semantics for host-side
topology code; `asarray_f32`/`asarray_i32` are the dtype-policy chokepoints for
device arrays (reference keeps v float64 / f uint32, mesh.py:68-70 — on TPU we
standardize on float32 / int32, see SURVEY.md section 7.1).
"""

import numpy as np
import scipy.sparse as sp


def row(A):
    """Reshape to a (1, N) row vector (reference utils.py:6-7)."""
    return np.reshape(A, (1, -1))


def col(A):
    """Reshape to an (N, 1) column vector (reference utils.py:10-11)."""
    return np.reshape(A, (-1, 1))


def sparse(i, j, data, m=None, n=None):
    """Build a csc matrix from triplets (reference utils.py:14-22)."""
    ij = np.vstack((row(i), row(j)))
    if m is None:
        m = ij[0].max() + 1
    if n is None:
        n = ij[1].max() + 1
    return sp.csc_matrix((data, ij), shape=(m, n))


def asarray_f32(x):
    return np.ascontiguousarray(np.asarray(x, dtype=np.float64).astype(np.float32))


def asarray_i32(x):
    return np.ascontiguousarray(np.asarray(x), dtype=np.int32)
