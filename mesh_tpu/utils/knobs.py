"""Central ``MESH_TPU_*`` environment-knob registry.

Every environment variable the framework reads is declared HERE — name,
type, default, and a one-line doc string — and read through the accessors
below.  Three things hang off that single table:

- ``doc/configuration.md`` is generated from it (tools/build_docs.py), so
  the knob reference cannot rot;
- the meshlint ``KNB`` rule (mesh_tpu/analysis/rules/knb.py) fails the
  build on any raw ``os.environ`` read of a ``MESH_TPU_*`` key outside
  this module, and on any declared knob missing from the generated doc;
- ``raw()`` raises ``KeyError`` on an undeclared name, so a typo'd or
  undeclared knob can never be read silently.

Stdlib-only (no jax, no numpy): the obs/ primitives and the jax-free CLI
subcommands (serve-stats, incidents, slo, perfcheck, lint) all sit on top
of it.  Accessors re-read ``os.environ`` per call — same contract as the
utils/dispatch escape hatches — so tests can toggle knobs at runtime.
"""

import os

__all__ = [
    "Knob", "declared", "lookup", "raw", "flag", "get_int", "get_float",
    "get_str", "render_markdown", "OFF_VALUES",
]

#: shared flag truthiness: a knob explicitly set to one of these is OFF
#: (so ``=0`` disables rather than enables)
OFF_VALUES = ("", "0", "false", "no", "off")


class Knob(object):
    """One declared environment knob."""

    __slots__ = ("name", "kind", "default", "doc", "section", "prefix")

    def __init__(self, name, kind, default, doc, section, prefix=False):
        self.name = name
        self.kind = kind          # "flag" | "int" | "float" | "str" | "path"
        self.default = default
        self.doc = doc
        self.section = section
        self.prefix = prefix      # True: name is a prefix (MESH_TPU_X_<SUFFIX>)


#: declaration order is doc order
_REGISTRY = {}


def _declare(name, kind, default, doc, section, prefix=False):
    _REGISTRY[name] = Knob(name, kind, default, doc, section, prefix=prefix)
    return name


# -- core ------------------------------------------------------------------

CACHE = _declare(
    "MESH_TPU_CACHE", "path", "~/.mesh_tpu/cache",
    "Topology/calibration cache folder (the reference's "
    "$PSBODY_MESH_CACHE idea); the test harness points it at a throwaway "
    "tmpdir.", "Core")
TEST_TPU = _declare(
    "MESH_TPU_TEST_TPU", "flag", False,
    "Compiled-kernel test mode: keep the default (real-chip) backend "
    "instead of the virtual 8-device CPU platform "
    "(`make tpu_tests`, tests/conftest.py).", "Core")

# -- dispatch escape hatches ----------------------------------------------

FORCE_XLA = _declare(
    "MESH_TPU_FORCE_XLA", "flag", False,
    "Force the pure-XLA kernel paths even on TPU (escape hatch for a "
    "Pallas kernel that misbehaves only when Mosaic-compiled).",
    "Dispatch")
SAFE_TILES = _declare(
    "MESH_TPU_SAFE_TILES", "flag", False,
    "Pin every Pallas kernel to its sliver-safe tile variant and force "
    "the data-derived nondegeneracy check off.", "Dispatch")
NO_ENGINE = _declare(
    "MESH_TPU_NO_ENGINE", "flag", False,
    "Bypass the shape-bucketed plan-cache engine: facades fall back to "
    "the direct exact-shape jit-per-call path.", "Dispatch")
VERTEX_CHAMFER = _declare(
    "MESH_TPU_VERTEX_CHAMFER", "flag", False,
    "Pin parallel/fit.py's data term to the legacy min-over-vertices "
    "chamfer instead of the point-to-surface energy (read at step-build "
    "time).", "Dispatch")
NO_ACCEL = _declare(
    "MESH_TPU_NO_ACCEL", "flag", False,
    "Disable the spatial-index query paths (mesh_tpu.accel): auto never "
    "routes to the index; callers fall back to brute/culled.", "Dispatch")
ACCEL_KIND = _declare(
    "MESH_TPU_ACCEL_KIND", "str", "bvh",
    "Which spatial index the accel facade builds: `bvh` (flattened rope "
    "LBVH, default) or `grid` (uniform grid); unknown values fall back "
    "to bvh.", "Dispatch")
BRUTE_MAX_FACES = _declare(
    "MESH_TPU_BRUTE_MAX_FACES", "int", None,
    "Face count up to which the auto strategy uses brute force "
    "(overrides the calibrated crossover; query/autotune.py).",
    "Dispatch")
ACCEL_MIN_FACES = _declare(
    "MESH_TPU_ACCEL_MIN_FACES", "int", None,
    "Face count at which the auto strategy switches to the spatial "
    "index (overrides the calibrated accel crossover).", "Dispatch")
MXU = _declare(
    "MESH_TPU_MXU", "flag", False,
    "Route the closest-point facades to the MXU dot-product tile "
    "(matmul-form pair tests with f32 exact repair) when the fast "
    "variant is eligible; off (default) keeps the 19-row VPU tiles — "
    "bit-identical to the pre-MXU paths.", "Dispatch")
MXU_BF16 = _declare(
    "MESH_TPU_MXU_BF16", "flag", False,
    "With MESH_TPU_MXU: run the bf16 first-pass survivor filter before "
    "the f32 exact-repair pass (certified error envelope, "
    "doc/acceleration.md); off computes the MXU pass in f32 directly.",
    "Dispatch")
MXU_CROSSOVER_FACES = _declare(
    "MESH_TPU_MXU_CROSSOVER_FACES", "int", None,
    "Face count at which the MXU dot-product tile takes over from the "
    "VPU tile (overrides the calibrated mxu crossover and pins the "
    "`mxu_crossover` tunable; query/autotune.py).", "Dispatch")
BVH_STREAM = _declare(
    "MESH_TPU_BVH_STREAM", "flag", True,
    "Streamed Pallas BVH kill switch: on (default) lets meshes whose "
    "face planes exceed the VMEM budget run the double-buffered "
    "DMA-streamed rope kernel; off restores the legacy behavior (XLA "
    "traversal above the resident ceiling).", "Dispatch")
BVH_STREAM_FORCE = _declare(
    "MESH_TPU_BVH_STREAM_FORCE", "flag", False,
    "Force the STREAMED Pallas rope kernel even when the resident "
    "variant would fit VMEM (A/B hatch; results are bit-identical).",
    "Dispatch")
BVH_STREAM_BUFFERS = _declare(
    "MESH_TPU_BVH_STREAM_BUFFERS", "int", None,
    "Leaf-ring buffer count for the streamed Pallas rope kernel "
    "(min 2); unset uses the autotuned value, else 2.", "Dispatch")
BVH_STREAM_VMEM_MB = _declare(
    "MESH_TPU_BVH_STREAM_VMEM_MB", "float", 12.0,
    "VMEM budget (MiB) the accel facade measures the resident rope "
    "kernel's face planes against when picking resident vs streamed "
    "(headroom below the ~16 MiB ceiling for accumulators and Mosaic "
    "overhead).", "Dispatch")
COALESCE_WINDOW_MS = _declare(
    "MESH_TPU_COALESCE_WINDOW_MS", "float", None,
    "Hard pin for the executor's request-coalescing window in "
    "milliseconds (0 = drain immediately, today's behavior); setting it "
    "pins the `coalesce_window_ms` tunable and disables tuner actuation "
    "for it (utils/tuning.py).", "Dispatch")
NO_XLA_CACHE = _declare(
    "MESH_TPU_NO_XLA_CACHE", "flag", False,
    "Opt out of the persistent XLA compilation cache "
    "(utils/compilation_cache.py).", "Dispatch")
XLA_CACHE = _declare(
    "MESH_TPU_XLA_CACHE", "path", None,
    "Relocate the persistent XLA compilation cache (default "
    "`<MESH_TPU_CACHE>/xla`).", "Dispatch")

# -- observability ---------------------------------------------------------

OBS = _declare(
    "MESH_TPU_OBS", "flag", False,
    "Master gate for span tracing (metrics counters stay always-on); "
    "off means spans are no-ops with <5% overhead pinned by the bench "
    "guard.", "Observability")
OBS_JSONL = _declare(
    "MESH_TPU_OBS_JSONL", "path", None,
    "Live span/metric JSON-lines sink path (obs/trace.py installs it on "
    "first span).", "Observability")
OBS_JSONL_MAX_MB = _declare(
    "MESH_TPU_OBS_JSONL_MAX_MB", "float", None,
    "Size cap (MiB) that rotates the JSONL sink; unset = unbounded.",
    "Observability")
OBS_JSONL_KEEP = _declare(
    "MESH_TPU_OBS_JSONL_KEEP", "int", 3,
    "Rotated JSONL generations to keep (oldest dropped).",
    "Observability")
OBS_JAX_TRACE = _declare(
    "MESH_TPU_OBS_JAX_TRACE", "flag", False,
    "Also emit spans as jax.profiler TraceAnnotations onto the device "
    "timeline (opt-in on top of MESH_TPU_OBS).", "Observability")
RECORDER = _declare(
    "MESH_TPU_RECORDER", "flag", True,
    "Always-on flight recorder kill switch: unset means ON; set to "
    "0/false/off to disable recording entirely.", "Observability")
RECORDER_EVENTS = _declare(
    "MESH_TPU_RECORDER_EVENTS", "int", 2048,
    "Flight-recorder ring capacity in events (min 16).", "Observability")
INCIDENT_DIR = _declare(
    "MESH_TPU_INCIDENT_DIR", "path", "~/.mesh_tpu/incidents",
    "Directory for flight-recorder incident dumps.", "Observability")
INCIDENT_KEEP = _declare(
    "MESH_TPU_INCIDENT_KEEP", "int", 32,
    "Incident dumps to keep before pruning the oldest (min 1).",
    "Observability")
SLO_DRIVES_HEALTH = _declare(
    "MESH_TPU_SLO_DRIVES_HEALTH", "flag", False,
    "Opt-in: a confirmed SLO fast-burn breach trips the serving "
    "HealthMonitor to degraded (closes the detect->capture->degrade "
    "loop).", "Observability")
LEDGER = _declare(
    "MESH_TPU_LEDGER", "flag", True,
    "Always-on per-request latency ledger kill switch (obs/ledger.py): "
    "unset means ON; set to 0/false/off to skip stage stamping and the "
    "request-stage histogram entirely.", "Observability")
LEDGER_CAPACITY = _declare(
    "MESH_TPU_LEDGER_CAPACITY", "int", 512,
    "Ledger ring capacity in closed request records (min 16).",
    "Observability")
LEDGER_TAIL = _declare(
    "MESH_TPU_LEDGER_TAIL", "int", 32,
    "How many newest ledger records ride along in each flight-recorder "
    "incident dump (min 1).", "Observability")
REPLAY_TRACE = _declare(
    "MESH_TPU_REPLAY_TRACE", "path", None,
    "Stream every ledger close into a replayable traffic trace at this "
    "JSONL path (obs/replay.py schema v1: relative admit offsets + "
    "tenant/op/bucket/deadline/priority/store-key provenance; replay "
    "with `mesh-tpu replay run`).", "Observability")
LOCK_WITNESS = _declare(
    "MESH_TPU_LOCK_WITNESS", "flag", False,
    "Wrap every threading.Lock/RLock/Condition created by mesh_tpu "
    "modules to record real lock-acquisition orders, keyed by creation "
    "site; cross-check the log with `mesh-tpu lint --witness <file>` "
    "(doc/concurrency.md). Must be set before the first import.",
    "Observability")
LOCK_WITNESS_FILE = _declare(
    "MESH_TPU_LOCK_WITNESS_FILE", "path", "~/.mesh_tpu/lock_witness.jsonl",
    "Where the lock witness dumps its acquisition-order log (JSONL, "
    "written at process exit and by tests that flush explicitly).",
    "Observability")
TUNER = _declare(
    "MESH_TPU_TUNER", "flag", True,
    "Closed-loop tuner kill switch (utils/tuning.py + obs/controller.py): "
    "unset means the tunable-knob layer is live (the controller still "
    "only runs when started explicitly); set to 0/false/off to freeze "
    "every tunable at its static default — bit-identical to the "
    "pre-tuner behavior.", "Observability")
TUNER_INTERVAL = _declare(
    "MESH_TPU_TUNER_INTERVAL", "float", 15.0,
    "TunerController background evaluation interval in seconds "
    "(controller.start(); tests drive step() under a fake clock "
    "instead).", "Observability")
TUNER_AB_TOL = _declare(
    "MESH_TPU_TUNER_AB_TOL", "float", 0.2,
    "Shadow A/B guard tolerance: a knob change whose hold-out window "
    "p99 regresses past `before * (1 + tol)` is auto-reverted "
    "(harvest-gates provenance semantics: missing/failed evidence never "
    "reads as an improvement).", "Observability")
KNOB_TAIL = _declare(
    "MESH_TPU_KNOB_TAIL", "int", 8,
    "How many newest `knob_change` events ride along in each "
    "flight-recorder incident dump's `knob_history` tail (min 1).",
    "Observability")
TRACE_CONTEXT = _declare(
    "MESH_TPU_TRACE_CONTEXT", "flag", True,
    "End-to-end request identity kill switch (obs/context.py): on "
    "(default) mints a RequestContext per admission — request_id in "
    "ledger meta, span request_id tags, cross-thread span parent "
    "linkage, tail-sampled trace retention; off is bit-identical to "
    "the identity-free path (no context is ever minted).",
    "Observability")
TRACE_TAIL = _declare(
    "MESH_TPU_TRACE_TAIL", "int", 64,
    "Tail-sampling ring capacity in retained request traces (ledger "
    "row + span tree + exemplar identity) per process; every "
    "deadline-miss/error/spilled request is retained, plus a reservoir "
    "of slow-ok ones (min 4).", "Observability")
TRACE_RESERVOIR = _declare(
    "MESH_TPU_TRACE_RESERVOIR", "int", 8,
    "Slots in the slow-ok reservoir inside the tail-sampling ring: the "
    "N slowest requests that closed `ok` keep their span trees too "
    "(0 disables the reservoir; misses/errors are always retained).",
    "Observability")

# -- serving ---------------------------------------------------------------

SERVE_STATS = _declare(
    "MESH_TPU_SERVE_STATS", "path", "~/.mesh_tpu/serve_stats.json",
    "QueryService stats sink path (written on stop(); read by "
    "`mesh-tpu serve-stats` / `slo`).", "Serving")
SERVE_QUEUE = _declare(
    "MESH_TPU_SERVE_QUEUE", "int", 64,
    "Per-tenant admission queue bound (overridable per constructor).",
    "Serving")
SERVE_DEADLINE_S = _declare(
    "MESH_TPU_SERVE_DEADLINE_S", "float", 1.0,
    "Default request deadline in seconds.", "Serving")
SERVE_WORKERS = _declare(
    "MESH_TPU_SERVE_WORKERS", "int", 1,
    "Queue-drain worker threads.", "Serving")
SERVE_LADDER = _declare(
    "MESH_TPU_SERVE_LADDER", "str", None,
    "Comma-separated degradation-ladder rung names "
    "(engine,culled,anchored,accel) to filter/reorder the default "
    "engine->culled->anchored ladder.", "Serving")
SERVE_WEDGE_S = _declare(
    "MESH_TPU_SERVE_WEDGE_S", "float", 5.0,
    "In-flight seconds before the health watchdog counts a dispatch as "
    "wedged.", "Serving")

# -- store -----------------------------------------------------------------

STORE_DIR = _declare(
    "MESH_TPU_STORE_DIR", "path", "~/.mesh_tpu/store",
    "Content-addressed mesh-store root (doc/store.md): objects/, tmp/ "
    "staging, and accel side-cars all live under it.", "Store")
STORE_BLOCK_ROWS = _declare(
    "MESH_TPU_STORE_BLOCK_ROWS", "int", 262144,
    "Rows per chunked store block; a single-block tier is served as one "
    "zero-copy mmap.", "Store")
STORE_COMPACT = _declare(
    "MESH_TPU_STORE_COMPACT", "flag", True,
    "Write the quantized uint16 compact vertex tier on ingest (the "
    "manifest states its worst-case tolerance); off stores the exact "
    "tier only.", "Store")
STORE_SIDECAR = _declare(
    "MESH_TPU_STORE_SIDECAR", "flag", True,
    "AccelIndex side-car consult/persist in accel get_index: a side-car "
    "hit serves the index off mmap with no host build "
    "(mesh_tpu_store_sidecar_hits_total); off restores build-only "
    "behavior.", "Store")
STORE_VERIFY = _declare(
    "MESH_TPU_STORE_VERIFY", "flag", True,
    "CRC-verify every store block on read; off trades integrity "
    "checking for open latency (verification stays on for `mesh-tpu "
    "store verify` regardless).", "Store")
STORE_PAGE_CACHE_MB = _declare(
    "MESH_TPU_STORE_PAGE_CACHE_MB", "float", 256.0,
    "Byte budget (MiB) of the in-process digest-keyed page cache the "
    "serving tier resolves store keys through.", "Store")
STORE_GC_MB = _declare(
    "MESH_TPU_STORE_GC_MB", "float", 4096.0,
    "Default corpus size budget (MiB) for `mesh-tpu store gc` / "
    "MeshStore.gc: least-recently-used objects are deleted until the "
    "corpus fits.", "Store")

# -- fleet -----------------------------------------------------------------

FLEET = _declare(
    "MESH_TPU_FLEET", "flag", True,
    "Fleet router kill switch (mesh_tpu/fleet/router.py): on (default) "
    "routes by (op, topology digest, shape bucket) over the hash ring; "
    "off makes FleetRouter.submit a direct pass-through to its first "
    "replica — with one replica, bit-identical to calling the service.",
    "Fleet")
FLEET_SPILL = _declare(
    "MESH_TPU_FLEET_SPILL", "flag", True,
    "Spill-to-sibling admission: a primary replica rejecting with "
    "`queue_full` spills the request to the ring's second choice (one "
    "hop); off propagates the rejection exactly like a standalone "
    "service.", "Fleet")
FLEET_VNODES = _declare(
    "MESH_TPU_FLEET_VNODES", "int", 64,
    "Virtual nodes per replica on the consistent-hash ring (placement "
    "evenness vs lookup size; changing it remaps keys).", "Fleet")
FLEET_AOT = _declare(
    "MESH_TPU_FLEET_AOT", "flag", True,
    "Persistent AOT executable tier (store/aot.py): on (default) homes "
    "the XLA compilation cache under `<store>/aot/` with a CRC'd index "
    "audited by `mesh-tpu store verify`, so replica cold start skips "
    "compiles; off leaves the compilation cache wherever "
    "MESH_TPU_XLA_CACHE points.", "Fleet")
FLEET_SHARD = _declare(
    "MESH_TPU_FLEET_SHARD", "flag", True,
    "Sharded big-batch lane kill switch: on (default) lets the engine "
    "route single-mesh closest-point dispatches at or above the "
    "`shard_min_q` tunable through parallel/sharding.py's dp-sharded "
    "plan (bit-identical results); off pins the single-device path. "
    "The lane is also off while `shard_min_q` is unset (its default).",
    "Fleet")
FLEET_SHARD_MIN_Q = _declare(
    "MESH_TPU_FLEET_SHARD_MIN_Q", "int", None,
    "Hard pin for the `shard_min_q` tunable: query count at which a "
    "coalesced closest-point batch takes the sharded big-batch lane; "
    "setting it disables tuner actuation for the threshold "
    "(utils/tuning.py).", "Fleet")
FLEET_STATS_DIR = _declare(
    "MESH_TPU_FLEET_STATS_DIR", "path", "~/.mesh_tpu/fleet",
    "Directory `mesh-tpu fleet status` scans for per-replica serve-stats "
    "sink files (each replica writes its own via MESH_TPU_SERVE_STATS).",
    "Fleet")

# -- animation -------------------------------------------------------------

ANIM = _declare(
    "MESH_TPU_ANIM", "flag", True,
    "Dynamic-mesh subsystem kill switch (mesh_tpu/anim/): on (default) "
    "avatar sessions answer each frame with a frozen-order BVH refit "
    "(rebuild only on inflation trips); off rebuilds the index cold per "
    "frame through get_index — bit-identical to the pre-anim path.",
    "Animation")
ANIM_REFIT_MAX_INFLATION = _declare(
    "MESH_TPU_ANIM_REFIT_MAX_INFLATION", "float", None,
    "Hard pin for the `anim_refit_max_inflation` tunable: box-inflation "
    "ratio past which a session's refit trips a full rebuild; setting "
    "it disables tuner actuation for the threshold (utils/tuning.py).",
    "Animation")

# -- bench harness ---------------------------------------------------------

BENCH_FAULT = _declare(
    "MESH_TPU_BENCH_FAULT", "str", None,
    "Fault injection for the staged bench pipeline: "
    "`<stage>:hang|crash|error` (tests only).", "Bench harness")
BENCH_PARTIAL = _declare(
    "MESH_TPU_BENCH_PARTIAL", "path", None,
    "Relocate the incremental bench_partial.json written after every "
    "stage.", "Bench harness")
BENCH_TIMEOUT_ = _declare(
    "MESH_TPU_BENCH_TIMEOUT_", "float", None,
    "Per-stage child timeout override in seconds "
    "(`MESH_TPU_BENCH_TIMEOUT_<STAGE>`, e.g. ..._PALLAS_PROXY).",
    "Bench harness", prefix=True)
BENCH_REDUCTION = _declare(
    "MESH_TPU_BENCH_REDUCTION", "str", None,
    "bench.py kernel-knob A/B: reduction variant for gate 2b "
    "(`fused`); non-default knobs never overwrite the last-good record.",
    "Bench harness")
BENCH_VARIANT = _declare(
    "MESH_TPU_BENCH_VARIANT", "str", None,
    "bench.py kernel-knob A/B: tile variant override (read by bench.py, "
    "not the package).", "Bench harness")
ACCEL_PROXY_FACES = _declare(
    "MESH_TPU_ACCEL_PROXY_FACES", "int", None,
    "accel_proxy bench stage: override the proxy mesh face count "
    "(read by bench.py).", "Bench harness")
ACCEL_PROXY_QUERIES = _declare(
    "MESH_TPU_ACCEL_PROXY_QUERIES", "int", None,
    "accel_proxy bench stage: override the proxy query count (read by "
    "bench.py).", "Bench harness")
STREAM_PROXY_FACES = _declare(
    "MESH_TPU_STREAM_PROXY_FACES", "int", None,
    "accel_stream_proxy bench stage: override the proxy mesh face count "
    "(read by bench.py).", "Bench harness")
STREAM_PROXY_QUERIES = _declare(
    "MESH_TPU_STREAM_PROXY_QUERIES", "int", None,
    "accel_stream_proxy bench stage: override the proxy query count "
    "(read by bench.py).", "Bench harness")
MXU_PROXY_FACES = _declare(
    "MESH_TPU_MXU_PROXY_FACES", "int", None,
    "mxu_proxy bench stage: override the proxy mesh face count (read "
    "by bench.py).", "Bench harness")
MXU_PROXY_QUERIES = _declare(
    "MESH_TPU_MXU_PROXY_QUERIES", "int", None,
    "mxu_proxy bench stage: override the proxy query count (read by "
    "bench.py).", "Bench harness")
STORE_PROXY_FACES = _declare(
    "MESH_TPU_STORE_PROXY_FACES", "int", None,
    "store_cold_start bench stage: override the proxy mesh face count "
    "(read by bench.py).", "Bench harness")
STORE_PROXY_QUERIES = _declare(
    "MESH_TPU_STORE_PROXY_QUERIES", "int", None,
    "store_cold_start bench stage: override the proxy query count "
    "(read by bench.py).", "Bench harness")
REPLAY_PROXY_SEED = _declare(
    "MESH_TPU_REPLAY_PROXY_SEED", "int", None,
    "replay_proxy bench stage: override the synthesized adversarial-mix "
    "trace seed (read by bench.py; changing it is expected to change "
    "the committed golden checksum).", "Bench harness")
FLEET_PROXY_SEED = _declare(
    "MESH_TPU_FLEET_PROXY_SEED", "int", None,
    "fleet_proxy bench stage: override the synthesized mixed-digest "
    "trace seed (read by bench.py; changing it is expected to change "
    "the committed golden checksums).", "Bench harness")
ANIM_PROXY_FACES = _declare(
    "MESH_TPU_ANIM_PROXY_FACES", "int", None,
    "anim_proxy bench stage: override the proxy mesh face count (read "
    "by bench.py).", "Bench harness")
ANIM_PROXY_FRAMES = _declare(
    "MESH_TPU_ANIM_PROXY_FRAMES", "int", None,
    "anim_proxy bench stage: override the deformation-loop frame count "
    "(read by bench.py).", "Bench harness")
ANIM_PROXY_QUERIES = _declare(
    "MESH_TPU_ANIM_PROXY_QUERIES", "int", None,
    "anim_proxy bench stage: override the per-frame query count (read "
    "by bench.py).", "Bench harness")
TRACE_PROXY_SEED = _declare(
    "MESH_TPU_TRACE_PROXY_SEED", "int", None,
    "trace_proxy bench stage: override the synthesized mixed-outcome "
    "trace seed (read by bench.py; changing it is expected to change "
    "the committed golden checksum).", "Bench harness")


# -- accessors -------------------------------------------------------------

_UNSET = object()


def declared():
    """All declared knobs, in declaration (= doc) order."""
    return list(_REGISTRY.values())


def lookup(name):
    """The :class:`Knob` for ``name`` (exact, or a declared prefix knob).

    Raises ``KeyError`` for undeclared names — reading an undeclared
    MESH_TPU knob is a bug the KNB lint rule catches statically and this
    raise catches dynamically.
    """
    try:
        return _REGISTRY[name]
    except KeyError:
        for knob in _REGISTRY.values():
            if knob.prefix and name.startswith(knob.name):
                return knob
        raise KeyError("undeclared knob %r (declare it in "
                       "mesh_tpu/utils/knobs.py)" % (name,))


def raw(name):
    """The raw environment value (or None).  The ONE place in the
    package that reads ``os.environ`` with a MESH_TPU key."""
    lookup(name)
    return os.environ.get(name)


def flag(name):
    """Flag truthiness shared by every escape hatch: unset means the
    declared default; explicitly set to ''/'0'/'false'/'no'/'off' means
    OFF; anything else means ON."""
    knob = lookup(name)
    value = raw(name)
    if value is None:
        return bool(knob.default)
    return value.strip().lower() not in OFF_VALUES


def get_int(name, default=_UNSET):
    """Integer knob; unset/blank/malformed falls back to ``default``
    (the declared default unless overridden)."""
    if default is _UNSET:
        default = lookup(name).default
    value = raw(name)
    if value is None or not value.strip():
        return default
    try:
        return int(value.strip())
    except ValueError:
        return default


def get_float(name, default=_UNSET):
    """Float knob; unset/blank/malformed falls back to ``default``."""
    if default is _UNSET:
        default = lookup(name).default
    value = raw(name)
    if value is None or not value.strip():
        return default
    try:
        return float(value.strip())
    except ValueError:
        return default


def get_str(name, default=_UNSET):
    """String/path knob, stripped; unset or blank falls back to
    ``default`` (paths are NOT expanded — callers expanduser)."""
    if default is _UNSET:
        default = lookup(name).default
    value = raw(name)
    if value is None or not value.strip():
        return default
    return value.strip()


# -- doc generation --------------------------------------------------------

def render_markdown():
    """The doc/configuration.md body (tools/build_docs.py writes it; the
    KNB rule checks every declared knob appears there)."""
    lines = [
        "# Configuration knobs",
        "",
        "Every `MESH_TPU_*` environment variable the framework reads, "
        "generated",
        "from the declaration table in `mesh_tpu/utils/knobs.py` by",
        "`tools/build_docs.py` — edit the table, not this file.  Flags "
        "share one",
        "truthiness: explicitly set to ``''``/``0``/``false``/``no``/"
        "``off`` means",
        "OFF, anything else means ON, unset means the default below.  "
        "All knobs",
        "are re-read per call unless their doc says otherwise.",
        "",
        "The meshlint `KNB` rule ([static_analysis.md]"
        "(static_analysis.md)) enforces",
        "that no module outside `utils/knobs.py` reads these keys raw "
        "and that",
        "this page stays complete.",
        "",
    ]
    sections = []
    for knob in declared():
        if knob.section not in sections:
            sections.append(knob.section)
    for section in sections:
        lines += ["## %s" % section, "",
                  "| knob | type | default | effect |", "|---|---|---|---|"]
        for knob in declared():
            if knob.section != section:
                continue
            name = (knob.name + "<STAGE>") if knob.prefix else knob.name
            default = ("on" if knob.default else "off") \
                if knob.kind == "flag" else (
                "unset" if knob.default is None else "`%s`" % (knob.default,))
            lines.append("| `%s` | %s | %s | %s |"
                         % (name, knob.kind, default, knob.doc))
        lines.append("")
    return "\n".join(lines)
