"""Timing / profiling utilities (SURVEY.md section 5: the reference has no
tracing or profiling subsystem — a gap to fill, not parity to match).

Three layers:

- ``host_sync(tree)``: materialize every jax leaf on the host.  The honest
  synchronization primitive on backends where ``jax.block_until_ready``
  returns early (observed on the experimental `axon` TPU tunnel: a scalar
  read after block_until_ready still waited tens of ms).
- ``Timer``: a wall-clock context manager with optional jax sync on exit.
- ``time_fn(fn, ...)``: warmup + N pipelined repetitions with one final
  host read, the measurement loop used by bench.py and benchmarks/.
- ``trace(path)``: thin wrapper over ``jax.profiler.trace`` for capturing
  a TensorBoard-viewable device trace.
"""

import contextlib
import time


def host_sync(out):
    """Force full host materialization of every jax array in ``out``.

    Returns ``out`` unchanged, so it can wrap a call site inline:
    ``res = host_sync(fn(x))``.
    """
    import numpy as np
    import jax

    for leaf in jax.tree.leaves(out):
        if hasattr(leaf, "block_until_ready") or hasattr(leaf, "device"):
            np.asarray(leaf)
    return out


class Timer:
    """Wall-clock timer context manager.

    >>> with Timer("normals") as t:
    ...     out = vert_normals(v, f)
    >>> t.elapsed  # seconds; sync=True (default) host-syncs `out` via t.watch

    ``elapsed`` is recorded even when the body raises (sync is skipped
    then — the watched output may be half-built), so a timing harness
    around flaky device code never reads back ``None``.  On success
    ``sync_elapsed`` holds the host-sync share of ``elapsed``: the
    dispatch-vs-device split the span tracer reports (doc/observability.md).
    """

    def __init__(self, name="", sync=True, log=None):
        self.name = name
        self.sync = sync
        self.log = log
        self.elapsed = None
        self.sync_elapsed = None
        self._watched = None

    def watch(self, out):
        """Register values to host-sync before the clock stops."""
        self._watched = out
        return out

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None and self.sync and self._watched is not None:
            t_sync = time.perf_counter()
            host_sync(self._watched)
            self.sync_elapsed = time.perf_counter() - t_sync
        self.elapsed = time.perf_counter() - self._t0
        if self.log is not None:
            self.log("%s: %.3f ms" % (self.name or "timer", self.elapsed * 1e3))
        return False


def time_fn(fn, reps=10, warmup=1):
    """Average seconds per call of ``fn()`` (jax-aware).

    Runs ``warmup`` untimed calls (compile), then ``reps`` pipelined calls
    with a single host read at the end — the read cost is amortized across
    the repetitions, and dead-code elimination cannot drop any call because
    dispatch happens eagerly per call.  ``warmup=0`` measures cold start:
    the first timed call then includes compilation.
    """
    out = None
    for _ in range(warmup):
        out = fn()
    if warmup:
        host_sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    host_sync(out)
    return (time.perf_counter() - t0) / reps


@contextlib.contextmanager
def trace(log_dir):
    """Capture a device trace viewable in TensorBoard/Perfetto.

    >>> with trace("/tmp/jax-trace"):
    ...     host_sync(workload())
    """
    import jax

    with jax.profiler.trace(str(log_dir)):
        yield
