"""Runtime lock witness: record real acquisition orders.

``MESH_TPU_LOCK_WITNESS=1`` (read at import, see ``mesh_tpu/__init__``)
patches the ``threading.Lock`` / ``RLock`` / ``Condition`` factories so
that every primitive **created by mesh_tpu code** is wrapped in a thin
recorder.  Creations from anywhere else (stdlib, jax, user code) get
the raw primitive back untouched — the caller-frame filter makes the
patch invisible outside the package.

Each wrapped lock is keyed by its *creation site* (repo-relative
``path.py:lineno``), which is exactly how the static interprocedural
analysis keys discovered locks (``analysis/interproc.py``), so the
dynamic log and the static graph join without any name mapping.  A
per-thread shadow stack tracks held wrapped locks; on every acquire we
record one ``held-site -> acquired-site`` edge per lock currently held
(deduped, counted).  Re-entrant re-acquires of a site already on the
stack record nothing: an RLock taken twice is not an ordering fact.

``dump()`` writes the edge multiset as JSONL and
``mesh-tpu lint --witness <file>`` cross-checks it against the static
graph and the canonical order in doc/concurrency.md — each side
catches what the other can't (static: paths tests never take; dynamic:
orders the AST can't resolve).  See doc/concurrency.md.

The witness deliberately lives below the knobs layer and imports
nothing from the rest of the package: it must be installable before
any lock-creating module is imported.
"""

import atexit
import json
import os
import sys
import threading

__all__ = ["install", "installed", "reset", "dump", "edges",
           "witness_file", "load"]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

#: .../mesh_tpu — creations from files under here get wrapped
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: repo checkout root, so site keys are repo-relative like the analysis
_ROOT_DIR = os.path.dirname(_PKG_DIR)
_SELF = os.path.abspath(__file__)


class _WitnessState(object):
    """Shadow stacks + the recorded edge multiset (process-global)."""

    def __init__(self):
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        self.edges = {}      # (src_site, dst_site) -> count
        self.sites = set()   # every site that ever acquired

    def _stack(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def on_acquire(self, site):
        stack = self._stack()
        if site in stack:          # re-entrant: not an ordering fact,
            stack.append(site)     # but keep release bookkeeping honest
            return
        with self._mu:
            self.sites.add(site)
            for held in stack:
                if held != site:
                    key = (held, site)
                    self.edges[key] = self.edges.get(key, 0) + 1
        stack.append(site)

    def on_release(self, site):
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                return

    def snapshot(self):
        with self._mu:
            return dict(self.edges), set(self.sites)

    def clear(self):
        with self._mu:
            self.edges.clear()
            self.sites.clear()


_STATE = _WitnessState()


class _WitnessedLock(object):
    """Records acquire/release against the shadow stack, delegates
    everything else (including Condition's ``_release_save`` protocol)
    to the real primitive."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site):
        self._inner = inner
        self._site = site

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _STATE.on_acquire(self._site)
        return got

    def release(self):
        self._inner.release()
        _STATE.on_release(self._site)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        probe = getattr(self._inner, "locked", None)
        return probe() if probe is not None else None

    # Condition hands lock state save/restore through these when
    # present; the witness treats a wait() as "still held" (the thread
    # acquires nothing while blocked, so no spurious edges appear).
    def _release_save(self):
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)

    def _is_owned(self):
        return self._inner._is_owned()

    def __repr__(self):
        return "<witnessed %r @ %s>" % (self._inner, self._site)


def _creation_site(depth):
    """Repo-relative ``path.py:lineno`` of the creating frame, or None
    when the creator is not mesh_tpu code (leave those locks raw)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    path = os.path.abspath(frame.f_code.co_filename)
    if not path.startswith(_PKG_DIR + os.sep) or path == _SELF:
        return None
    rel = os.path.relpath(path, _ROOT_DIR).replace(os.sep, "/")
    return "%s:%d" % (rel, frame.f_lineno)


def _lock_factory():
    site = _creation_site(2)
    inner = _REAL_LOCK()
    return inner if site is None else _WitnessedLock(inner, site)


def _rlock_factory():
    site = _creation_site(2)
    inner = _REAL_RLOCK()
    return inner if site is None else _WitnessedLock(inner, site)


def _condition_factory(lock=None):
    if lock is None:
        site = _creation_site(2)
        if site is not None:
            lock = _WitnessedLock(_REAL_RLOCK(), site)
    return _REAL_CONDITION(lock)


_installed = False


def install():
    """Patch the threading factories (idempotent).  Must run before the
    lock-creating mesh_tpu modules are imported — ``mesh_tpu/__init__``
    calls this right after the knob registry loads when
    ``MESH_TPU_LOCK_WITNESS`` is set."""
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    atexit.register(_dump_at_exit)


def installed():
    return _installed


def reset():
    """Drop every recorded edge (tests)."""
    _STATE.clear()


def edges():
    """{(src_site, dst_site): count} snapshot of recorded orders."""
    snap, _ = _STATE.snapshot()
    return snap


def witness_file():
    from . import knobs

    return os.path.expanduser(
        knobs.get_str("MESH_TPU_LOCK_WITNESS_FILE"))


def dump(path=None):
    """Write the edge multiset as JSONL: one
    ``{"src": [path, line], "dst": [path, line], "count": n}`` object
    per line (plus one ``{"site": [path, line]}`` line per lock that
    ever acquired, so single-lock runs still prove the witness ran).
    Returns the path written."""
    path = path or witness_file()
    snap, sites = _STATE.snapshot()

    def split(site):
        rel, _, line = site.rpartition(":")
        return [rel, int(line)]

    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        for site in sorted(sites):
            fh.write(json.dumps({"site": split(site)}) + "\n")
        for (src, dst), count in sorted(snap.items()):
            fh.write(json.dumps({
                "src": split(src), "dst": split(dst), "count": count,
            }) + "\n")
    return path


def _dump_at_exit():
    try:
        dump()
    except Exception:
        pass     # exit-time best effort: never mask the real exit


def load(path):
    """Parse a witness JSONL file ->
    ``[((src_path, src_line), (dst_path, dst_line), count), ...]``."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if "src" not in rec:
                continue
            out.append((
                (rec["src"][0], int(rec["src"][1])),
                (rec["dst"][0], int(rec["dst"][1])),
                int(rec.get("count", 1))))
    return out
