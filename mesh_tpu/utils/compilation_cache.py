"""Persistent XLA compilation cache for the framework's entry points.

The reference amortizes its expensive setup work with a disk cache (the
crc32-keyed topology pickles, mesh/topology/connectivity.py:115-130).  The
TPU-native analog of that cost is XLA compilation: every benchmark config
compiles several programs at ~20-40 s each on the tunneled chip, paid again
in every fresh process.  JAX ships a content-keyed persistent cache for
exactly this; enabling it turns rerun compiles into disk loads, which
matters doubly on this machine where TPU processes must run one at a time
(tools/run_tpu_gates.sh) and a long-running suite risks tunnel flakiness.

Opt-out with ``MESH_TPU_NO_XLA_CACHE=1``; relocate with
``MESH_TPU_XLA_CACHE=/path`` (defaults to ``<cache folder>/xla``, so a
throwaway ``MESH_TPU_CACHE`` — the test harness's setting — also isolates
the compilation cache unless MESH_TPU_XLA_CACHE pins it elsewhere).
"""

import logging
import os

from . import knobs

_log = logging.getLogger(__name__)


def enable_persistent_compilation_cache(path=None, min_compile_secs=1.0):
    """Point JAX's persistent compilation cache at a framework-owned dir.

    Safe to call more than once and before or after backend init (the cache
    is consulted per-compile).  Failures are logged, never raised: an
    unsupported backend simply keeps compiling from scratch.

    :param path: cache directory; default ``$MESH_TPU_XLA_CACHE`` else
        ``<mesh_package_cache_folder>/xla``.
    :param min_compile_secs: only persist compiles at least this slow
        (tiny programs aren't worth the disk round trip).
    :returns: the cache directory in use, or ``None`` when disabled/failed.
    """
    if knobs.flag("MESH_TPU_NO_XLA_CACHE"):
        return None
    if path is None:
        path = knobs.get_str("MESH_TPU_XLA_CACHE")
    if path is None:
        from .. import mesh_package_cache_folder

        path = os.path.join(mesh_package_cache_folder, "xla")
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        prev = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs
        )
        if prev is not None and prev != path:
            # the cache backend binds its directory at first use; without a
            # reset, re-pointing the config mid-process silently keeps
            # writing to the old dir
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        # fold jax.monitoring's cache hit/miss events into the metrics
        # registry and flag the cache as live for `mesh-tpu stats`
        from ..obs.jax_bridge import install_jax_monitoring_bridge
        from ..obs.metrics import REGISTRY

        install_jax_monitoring_bridge()
        REGISTRY.gauge(
            "mesh_tpu_compilation_cache_enabled",
            "1 when the persistent XLA compilation cache is active.",
        ).set(1)
        return path
    except Exception as e:  # never let a cache problem break real work
        _log.warning("persistent compilation cache unavailable: %s", e)
        return None
