"""Central Pallas-vs-XLA dispatch policy.

Every query kernel has two implementations: a Pallas TPU kernel (the fast
path) and a pure-XLA fallback that runs anywhere.  By default the Pallas
path is used whenever the backend is TPU, but ``MESH_TPU_FORCE_XLA=1``
forces the XLA path even on TPU.  This is the escape hatch for the case
where a kernel compiles in interpret mode / on CPU but misbehaves only
when Mosaic-compiled on the real chip: users can disable the kernels
without downgrading or patching (advisor round-2 finding).

The env var is read per call (not cached) so tests can toggle it.
"""

import jax

from .knobs import flag as env_flag    # noqa: F401  (re-export: the
# escape-hatch truthiness now lives in the central knob registry)
from . import knobs

__all__ = ["env_flag", "force_xla", "safe_tiles", "tile_variant",
           "pallas_default", "mesh_on_tpu", "no_engine", "vertex_chamfer",
           "no_accel", "accel_kind", "mxu_enabled", "mxu_bf16_enabled",
           "bvh_stream_enabled",
           "bvh_stream_force", "bvh_stream_buffers",
           "bvh_stream_vmem_budget"]


def force_xla():
    """True when MESH_TPU_FORCE_XLA requests the XLA paths everywhere."""
    return env_flag("MESH_TPU_FORCE_XLA")


def safe_tiles():
    """True when MESH_TPU_SAFE_TILES pins the Pallas kernels to their
    safe tile variants (sliver-safe + degenerate-tail closest point,
    segment tri-tri) by forcing the data-derived nondegeneracy check to
    False and routing every closest-point facade to the sliver-safe
    brute tile (tile_variant below)."""
    return env_flag("MESH_TPU_SAFE_TILES")


def tile_variant():
    """The closest-point tile the facades should compile: ``"safe"``
    (sliver-safe direct-corner tile) under MESH_TPU_SAFE_TILES, else
    ``"fast"``.  Threaded through the auto, batched, sharded, and
    multi-host facades so the escape hatch reaches every entry point."""
    return "safe" if safe_tiles() else "fast"


def vertex_chamfer():
    """True when MESH_TPU_VERTEX_CHAMFER pins the fit loss's data term to
    the pre-diff min-over-VERTICES chamfer instead of the default
    point-to-SURFACE energy (parallel/fit.py) — the A/B hatch for the
    PR-3 loss rewire.  Read at step-BUILD time (the loss is jitted:
    toggling mid-run cannot retrace an already-built step, so rebuild the
    step after changing it)."""
    return env_flag("MESH_TPU_VERTEX_CHAMFER")


def no_accel():
    """True when MESH_TPU_NO_ACCEL disables the spatial-index query paths
    (mesh_tpu.accel): auto never routes to the index and the facades'
    callers fall back to brute/culled.  The kill switch for a bad index
    build or traversal kernel — read per call like the other hatches."""
    return env_flag("MESH_TPU_NO_ACCEL")


def accel_kind():
    """Which spatial index the accel facade builds by default: ``"bvh"``
    (flattened rope LBVH) unless MESH_TPU_ACCEL_KIND=grid selects the
    uniform grid.  Unknown values fall back to bvh."""
    value = (knobs.get_str("MESH_TPU_ACCEL_KIND") or "").lower()
    return "grid" if value == "grid" else "bvh"


def mxu_enabled():
    """True when MESH_TPU_MXU opts the closest-point facades into the
    MXU dot-product tile (matmul-form pair tests, f32 exact repair).
    Off by default: the pre-MXU routing is bit-identical with the knob
    unset.  Read per call like the other hatches."""
    return env_flag("MESH_TPU_MXU")


def mxu_bf16_enabled():
    """True when MESH_TPU_MXU_BF16 additionally enables the bf16
    first-pass survivor filter in front of the f32 exact-repair pass
    (certified error envelope, doc/acceleration.md).  Only consulted on
    paths already routed to the MXU tile."""
    return env_flag("MESH_TPU_MXU_BF16")


def bvh_stream_enabled():
    """True unless MESH_TPU_BVH_STREAM turns the streamed Pallas rope
    kernel off — the kill switch that restores the legacy behavior
    (XLA traversal above the resident VMEM ceiling)."""
    return env_flag("MESH_TPU_BVH_STREAM")


def bvh_stream_force():
    """True when MESH_TPU_BVH_STREAM_FORCE pins the accel facade to the
    STREAMED rope kernel even where the resident variant fits VMEM —
    the bit-identity A/B hatch (results are identical by construction,
    only DMA traffic and pair accounting differ)."""
    return env_flag("MESH_TPU_BVH_STREAM_FORCE")


def bvh_stream_buffers(default=2):
    """Leaf-ring depth for the streamed rope kernel: the
    MESH_TPU_BVH_STREAM_BUFFERS override when set, else ``default``
    (the facade passes the autotuned value), clamped to >= 2."""
    value = knobs.get_int("MESH_TPU_BVH_STREAM_BUFFERS")
    if value is None:
        value = default
    return max(2, int(value))


def bvh_stream_vmem_budget():
    """The VMEM byte budget the facade measures the resident kernel's
    face-plane footprint against (MESH_TPU_BVH_STREAM_VMEM_MB, MiB)."""
    mb = knobs.get_float("MESH_TPU_BVH_STREAM_VMEM_MB")
    return int(float(mb) * 1024 * 1024)


def no_engine():
    """True when MESH_TPU_NO_ENGINE requests today's direct dispatch path
    (exact-shape jit per call) instead of the shape-bucketed plan-cache
    engine (mesh_tpu.engine).  Read per call like the other hatches, so a
    misbehaving plan can be routed around at runtime without a restart."""
    return env_flag("MESH_TPU_NO_ENGINE")


_BACKEND_COUNTER = None


def _record_backend(use_pallas, reason):
    """Count every backend decision in the metrics registry
    (``mesh_tpu_dispatch_backend_total{backend=,reason=}`` — the
    "how often did the escape hatch fire" series, doc/observability.md)."""
    global _BACKEND_COUNTER
    if _BACKEND_COUNTER is None:
        from ..obs.metrics import REGISTRY

        _BACKEND_COUNTER = REGISTRY.counter(
            "mesh_tpu_dispatch_backend_total",
            "Pallas-vs-XLA dispatch decisions by backend and reason.",
        )
    _BACKEND_COUNTER.inc(
        backend="pallas" if use_pallas else "xla", reason=reason)
    return use_pallas


def pallas_default():
    """Whether Pallas kernels should be the default for this process:
    the default jax backend is TPU and the escape hatch is not set."""
    if force_xla():
        return _record_backend(False, "forced")
    return _record_backend(
        jax.devices()[0].platform == "tpu", "platform")


def mesh_on_tpu(mesh):
    """Same policy for an explicit device mesh (sharded paths)."""
    if force_xla():
        return _record_backend(False, "forced")
    return _record_backend(
        mesh.devices.flat[0].platform == "tpu", "platform")
