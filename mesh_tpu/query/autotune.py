"""Measured brute-vs-culled crossover for the auto closest-point strategy.

The reference's CGAL tree is O(log F) for any mesh size
(spatialsearchmodule.cpp:105-127); this framework instead has two exact
strategies with different scaling — the O(Q*F) brute-force scan and the
tile-sphere-culled kernel whose exact work is O(Q*k) after an O(Q*F)
cheap-bound pass.  Which one wins at a given F is a property of the
backend (VPU throughput vs the cull's overhead), so the switch point
used by ``closest_faces_and_points_auto`` is MEASURED, not guessed:

- ``calibrate_crossover()`` times both strategies over a geometric
  ladder of synthetic face counts on the live backend and returns the
  smallest F where the culled path wins; the result is cached in-process
  and persisted under $MESH_TPU_CACHE keyed by device kind, so one
  calibration serves all later processes on the same hardware.
- ``crossover_faces()`` is what auto consults: the
  $MESH_TPU_BRUTE_MAX_FACES env override, else the cached measurement,
  else a conservative default (32768 — safely inside the brute-force
  comfort zone on every backend measured so far).
"""

import json
import logging
import os
import time

import numpy as np

from ..utils import knobs

log = logging.getLogger(__name__)

DEFAULT_CROSSOVER = 32768

# face count above which the spatial-index path (mesh_tpu.accel) takes
# over from the culled strategies.  Conservative default: below this the
# culled kernels' O(Q*F) cheap-bound pass still fits the latency budget
# everywhere measured, and the index's host build + traversal overhead
# isn't guaranteed to pay for itself.
ACCEL_DEFAULT_CROSSOVER = 131072

# face count at which the MXU dot-product tile takes over from the VPU
# tile once MESH_TPU_MXU opts the facades in.  Conservative default:
# below this the matmul-form prologue (G layout + 11 planes) isn't
# guaranteed to amortize against the 19-row VPU tile everywhere.
MXU_DEFAULT_CROSSOVER = 8192

# default (tile_q, tile_f, n_buffers) for the streamed rope kernel, and
# the sweep calibrate_stream_tiles ranks: tile_f stays a multiple of 128
# (DMA lane alignment) and n_buffers >= 2 (double buffering)
STREAM_DEFAULT_TILES = (128, 256, 2)
STREAM_SWEEP = (
    (128, 256, 2), (128, 256, 3), (128, 512, 2), (256, 256, 2),
)

# in-process resolution cache (covers the cache-file miss too, so hot query
# loops don't pay a filesystem probe per call; a calibration persisted by
# ANOTHER process mid-run is picked up on the next interpreter start)
_measured = None
_accel_measured = None
_stream_measured = None
_mxu_measured = None


def _tuned(name):
    """Live closed-loop override (utils/tuning.py), consulted between
    the env hard pin and the measured cache: None when the tuner is
    off, the knob is env-pinned, or the controller never actuated it —
    every one of those falls through to the static chain."""
    from ..utils import tuning

    return tuning.tuned_value(name)


def _cache_path():
    from .. import mesh_package_cache_folder

    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform).replace(" ", "_")
    return os.path.join(
        mesh_package_cache_folder, "crossover_%s_%s.json" % (dev.platform, kind)
    )


def crossover_faces():
    """The face count up to which auto uses brute force (env override >
    cached measurement > default); above it the culled strategy runs."""
    env = knobs.raw("MESH_TPU_BRUTE_MAX_FACES")
    if env:
        value = knobs.get_int("MESH_TPU_BRUTE_MAX_FACES")
        if value is not None:
            return value
        log.warning(
            "ignoring malformed MESH_TPU_BRUTE_MAX_FACES=%r "
            "(want an integer face count)", env,
        )
    global _measured
    if _measured is not None:
        return _measured
    try:
        with open(_cache_path()) as fh:
            value = int(json.load(fh)["crossover_faces"])
        if value <= 0:
            raise ValueError(value)
        log.info("using measured brute/culled crossover %d from %s "
                 "(delete the file or re-run calibrate_crossover() to "
                 "re-measure)", value, _cache_path())
        _measured = value
    except (OSError, ValueError, KeyError, TypeError):
        _measured = DEFAULT_CROSSOVER
    return _measured


def _accel_cache_path():
    return _cache_path().replace("crossover_", "accel_crossover_")


def accel_crossover_faces():
    """The face count at which auto switches to the spatial-index path
    (env override > cached measurement > default).  auto routes to accel
    iff ``F >= accel_crossover_faces()`` and MESH_TPU_NO_ACCEL is unset."""
    env = knobs.raw("MESH_TPU_ACCEL_MIN_FACES")
    if env:
        value = knobs.get_int("MESH_TPU_ACCEL_MIN_FACES")
        if value is not None:
            return value
        log.warning(
            "ignoring malformed MESH_TPU_ACCEL_MIN_FACES=%r "
            "(want an integer face count)", env,
        )
    tuned = _tuned("accel_min_faces")
    if tuned is not None:
        return int(tuned)
    global _accel_measured
    if _accel_measured is not None:
        return _accel_measured
    try:
        with open(_accel_cache_path()) as fh:
            value = int(json.load(fh)["accel_min_faces"])
        if value <= 0:
            raise ValueError(value)
        log.info("using measured accel crossover %d from %s (delete the "
                 "file or re-run calibrate_accel_crossover() to "
                 "re-measure)", value, _accel_cache_path())
        _accel_measured = value
    except (OSError, ValueError, KeyError, TypeError):
        _accel_measured = ACCEL_DEFAULT_CROSSOVER
    return _accel_measured


def _mxu_cache_path():
    return _cache_path().replace("crossover_", "mxu_crossover_")


def mxu_crossover_faces():
    """The face count at which the facades route the fast closest-point
    tile to the MXU dot-product form (env override > tuned > cached
    ``calibrate_mxu_crossover`` measurement > default).  Only consulted
    when MESH_TPU_MXU is on; same resolution contract as
    ``accel_crossover_faces``."""
    env = knobs.raw("MESH_TPU_MXU_CROSSOVER_FACES")
    if env:
        value = knobs.get_int("MESH_TPU_MXU_CROSSOVER_FACES")
        if value is not None:
            return value
        log.warning(
            "ignoring malformed MESH_TPU_MXU_CROSSOVER_FACES=%r "
            "(want an integer face count)", env,
        )
    tuned = _tuned("mxu_crossover")
    if tuned is not None:
        return int(tuned)
    global _mxu_measured
    if _mxu_measured is not None:
        return _mxu_measured
    try:
        with open(_mxu_cache_path()) as fh:
            value = int(json.load(fh)["mxu_crossover_faces"])
        if value <= 0:
            raise ValueError(value)
        log.info("using measured mxu crossover %d from %s (delete the "
                 "file or re-run calibrate_mxu_crossover() to "
                 "re-measure)", value, _mxu_cache_path())
        _mxu_measured = value
    except (OSError, ValueError, KeyError, TypeError):
        _mxu_measured = MXU_DEFAULT_CROSSOVER
    return _mxu_measured


def _stream_cache_path():
    return _cache_path().replace("crossover_", "stream_tiles_")


def stream_tile_params():
    """``(tile_q, tile_f, n_buffers)`` the accel facade hands the
    streamed rope kernel: the cached ``calibrate_stream_tiles``
    measurement when one exists (else the conservative default), with
    the MESH_TPU_BVH_STREAM_BUFFERS override applied on top."""
    from ..utils.dispatch import bvh_stream_buffers

    global _stream_measured
    if _stream_measured is None:
        try:
            with open(_stream_cache_path()) as fh:
                data = json.load(fh)
            params = (int(data["tile_q"]), int(data["tile_f"]),
                      int(data["n_buffers"]))
            if params[0] <= 0 or params[1] <= 0 or params[1] % 128 \
                    or params[2] < 2:
                raise ValueError(params)
            log.info("using measured stream tiles %r from %s (delete the "
                     "file or re-run calibrate_stream_tiles() to "
                     "re-measure)", params, _stream_cache_path())
            _stream_measured = params
        except (OSError, ValueError, KeyError, TypeError):
            _stream_measured = STREAM_DEFAULT_TILES
    tile_q, tile_f, n_buffers = _stream_measured
    tuned = _tuned("stream_n_buffers")
    if tuned is not None:
        n_buffers = int(tuned)
    return tile_q, tile_f, bvh_stream_buffers(default=n_buffers)


def retune_hooks():
    """Controller-facing retune callables (obs/controller.py background
    retune): each re-resolves the CHEAP persisted calibration — the
    side-effect-free read of the calibrate_* cache file — and returns
    ``(value, evidence)`` for ``tuning.actuate``, or None when nothing
    was ever measured (publishing the static default would be
    generation churn for no signal).  The expensive calibrate_* sweeps
    themselves stay explicit and operator-driven."""

    def _from_file(path_fn, key, floor):
        try:
            path = path_fn()
            with open(path) as fh:
                value = int(json.load(fh)[key])
            if value < floor:
                raise ValueError(value)
        except Exception:     # includes the jax probe in _cache_path
            return None
        return value, {"source": path, "key": key}

    return {
        "accel_min_faces": lambda: _from_file(
            _accel_cache_path, "accel_min_faces", 1),
        "stream_n_buffers": lambda: _from_file(
            _stream_cache_path, "n_buffers", 2),
        "mxu_crossover": lambda: _from_file(
            _mxu_cache_path, "mxu_crossover_faces", 1),
    }


def calibrate_stream_tiles(n_faces=262144, n_queries=1024, reps=3,
                           sweep=STREAM_SWEEP, save=True):
    """Rank ``(tile_q, tile_f, n_buffers)`` configs for the streamed
    rope kernel on the live backend and persist the winner.

    Mirrors the crossover calibrations: each config's coarse index build
    is warmed OUTSIDE the timed region (steady-state regime), a
    re-measure of the winner that disagrees with itself by >2x marks
    the run unstable and skips persisting.  Off-TPU the sweep runs the
    interpret-mode kernel — rankings there reflect emulation, so they
    are persisted under the CPU device key and never leak onto a chip.
    """
    from ..accel.build import get_index
    from ..accel.pallas_stream import closest_point_pallas_bvh_stream
    from ..utils.dispatch import pallas_default

    interpret = not pallas_default()
    rng = np.random.RandomState(0)
    pts = rng.randn(n_queries, 3).astype(np.float32)
    v, f = _sphere_mesh(n_faces)
    timings = []
    for tile_q, tile_f, n_buffers in sweep:
        index = get_index(v, f, kind="bvh", leaf_size=int(tile_f))
        timings.append((
            _time_best(lambda: closest_point_pallas_bvh_stream(
                v, f, pts, tile_q=tile_q, tile_f=tile_f,
                n_buffers=n_buffers, interpret=interpret, index=index),
                reps),
            (tile_q, tile_f, n_buffers)))
    t_best, best = min(timings)
    tile_q, tile_f, n_buffers = best
    index = get_index(v, f, kind="bvh", leaf_size=int(tile_f))
    recheck = _time_best(lambda: closest_point_pallas_bvh_stream(
        v, f, pts, tile_q=tile_q, tile_f=tile_f, n_buffers=n_buffers,
        interpret=interpret, index=index), reps)
    stable = max(t_best, recheck) <= 2.0 * min(t_best, recheck)
    global _stream_measured
    _stream_measured = best
    if not stable:
        log.warning(
            "calibrate_stream_tiles: backend timings unstable (%.3fs vs "
            "%.3fs for %r) — not persisting; using %r for this process "
            "only", t_best, recheck, best, best)
        save = False
    if save:
        try:
            with open(_stream_cache_path(), "w") as fh:
                json.dump({
                    "tile_q": tile_q,
                    "tile_f": tile_f,
                    "n_buffers": n_buffers,
                    "interpret": bool(interpret),
                    "sweep": [
                        {"tile_q": tq, "tile_f": tf, "n_buffers": nb,
                         "t": t}
                        for t, (tq, tf, nb) in timings
                    ],
                    "n_faces": n_faces,
                    "n_queries": n_queries,
                    "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
                }, fh, indent=1)
        except OSError:
            pass
    return best


def _sphere_mesh(n_faces, seed=0):
    """Synthetic parametric sphere with ~n_faces triangles (queried-mesh
    stand-in for calibration; the crossover depends on F, not geometry)."""
    n_ring = max(3, int(np.sqrt(n_faces / 2)))
    n_seg = max(3, n_faces // (2 * n_ring))
    theta = np.pi * np.arange(1, n_ring + 1) / (n_ring + 1)
    phi = 2 * np.pi * np.arange(n_seg) / n_seg
    v = np.stack([
        np.outer(np.sin(theta), np.cos(phi)),
        np.outer(np.sin(theta), np.sin(phi)),
        np.outer(np.cos(theta), np.ones(n_seg)),
    ], axis=-1).reshape(-1, 3)
    # vectorized quad split, same face order as the equivalent
    # (ring, segment) double loop — config 6 builds ~1M faces per run
    r = np.arange(n_ring - 1)[:, None]
    s = np.arange(n_seg)[None, :]
    s1 = (s + 1) % n_seg
    b0s, b1s, b1s1, b0s1 = (
        r * n_seg + s, (r + 1) * n_seg + s,
        (r + 1) * n_seg + s1, r * n_seg + s1,
    )
    faces = np.stack(
        [np.stack([b0s, b1s, b1s1], axis=-1),
         np.stack([b0s, b1s1, b0s1], axis=-1)],
        axis=2,
    ).reshape(-1, 3)
    return v.astype(np.float32), faces.astype(np.int32)


def _time_best(fn, reps):
    from ..utils.profiling import time_fn

    return time_fn(fn, reps=reps)


def calibrate_crossover(ladder=(8192, 16384, 32768, 65536, 131072),
                        n_queries=1024, reps=3, save=True):
    """Measure the brute-vs-culled switch point on the live backend.

    Returns the smallest ladder F where the culled strategy beats brute
    force (and every larger ladder point agrees), or the point past the
    whole ladder when brute force always won.  Persists to the cache dir
    unless ``save=False``.
    """
    from .closest_point import closest_faces_and_points
    from ..utils.dispatch import pallas_default

    use_pallas = pallas_default()
    if use_pallas:
        from functools import partial

        from .pallas_closest import closest_point_pallas
        from .pallas_culled import closest_point_pallas_culled

        # mirror the facade dispatch (culled.py): both kernels run with
        # the nondegeneracy flag the facade would derive for the
        # calibration mesh (a sphere — always nondegenerate)
        brute = partial(closest_point_pallas, assume_nondegenerate=True)
        culled = partial(closest_point_pallas_culled,
                         assume_nondegenerate=True)
    else:
        from .culled import closest_faces_and_points_culled

        brute = closest_faces_and_points
        culled = closest_faces_and_points_culled

    rng = np.random.RandomState(0)
    pts = rng.randn(n_queries, 3).astype(np.float32)
    wins = []
    for n_f in ladder:
        v, f = _sphere_mesh(n_f)
        t_brute = _time_best(lambda: brute(v, f, pts), reps)
        t_culled = _time_best(lambda: culled(v, f, pts), reps)
        wins.append((f.shape[0], t_brute, t_culled))
    # transient-degradation guard: this machine's tunneled backend has
    # shown temporary ~25x slowdowns; a calibration taken then would
    # poison every later process.  Re-measure one ladder point — if it
    # disagrees with itself by >2x the numbers are not trustworthy.
    check_f, check_t, _ = wins[len(wins) // 2]
    v, f = _sphere_mesh(check_f)
    recheck = _time_best(lambda: brute(v, f, pts), reps)
    stable = max(check_t, recheck) <= 2.0 * min(check_t, recheck)
    # auto uses the value as brute_force_max_faces (brute iff F <= value),
    # so return the LARGEST brute-winning F, one below the first ladder
    # point where culled takes over for good
    crossover = None
    for i, (n_f, t_b, t_c) in enumerate(wins):
        if t_c < t_b and all(tc < tb for _, tb, tc in wins[i:]):
            crossover = wins[i - 1][0] if i > 0 else max(1, n_f - 1)
            break
    if crossover is None:
        crossover = 2 * wins[-1][0]   # brute won everywhere measured
    global _measured
    _measured = crossover
    if not stable:
        log.warning(
            "calibrate_crossover: backend timings unstable (%.3fs vs %.3fs "
            "at F=%d) — not persisting; using %d for this process only",
            check_t, recheck, check_f, crossover,
        )
        save = False
    if save:
        try:
            with open(_cache_path(), "w") as fh:
                json.dump({
                    "crossover_faces": crossover,
                    "ladder": [
                        {"faces": n, "t_brute": tb, "t_culled": tc}
                        for n, tb, tc in wins
                    ],
                    "n_queries": n_queries,
                    "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
                }, fh, indent=1)
        except OSError:
            pass
    return crossover


def calibrate_accel_crossover(ladder=(32768, 65536, 131072, 262144,
                                      524288),
                              n_queries=1024, reps=3, save=True):
    """Measure where the spatial-index path starts beating the ladder's
    incumbent large-F strategy (culled) on the live backend.

    Mirrors ``calibrate_crossover``: returns the smallest ladder F where
    accel wins and keeps winning (auto routes to accel iff F >= value),
    or 2x past the ladder when the incumbent always won.  The index
    build is paid OUTSIDE the timed region — the steady-state regime the
    per-topology cache puts every real caller in — and persisted to the
    cache dir unless ``save=False`` or the timings look unstable.

    The top rung(s) sit past the resident rope kernel's VMEM budget on
    purpose, so on TPU they time the STREAMED kernel — the ladder spans
    both Pallas variants, and each persisted rung records which one
    (``variant``) served it.
    """
    from ..accel.build import get_index
    from ..accel.traverse import closest_faces_and_points_accel, \
        pallas_bvh_variant
    from ..utils.dispatch import accel_kind, pallas_default
    from .culled import closest_faces_and_points_auto

    kind = accel_kind()
    use_pallas = bool(pallas_default())
    rng = np.random.RandomState(0)
    pts = rng.randn(n_queries, 3).astype(np.float32)
    # time the incumbent through the auto facade with accel disabled, so
    # it exercises exactly the routing (pallas or xla, brute or culled)
    # that accel would displace at each F
    incumbent_env = {"MESH_TPU_NO_ACCEL": "1"}
    wins = []
    for n_f in ladder:
        v, f = _sphere_mesh(n_f)
        get_index(v, f, kind=kind)   # warm the per-topology index cache
        old = {k: os.environ.get(k) for k in incumbent_env}
        os.environ.update(incumbent_env)
        try:
            t_inc = _time_best(
                lambda: closest_faces_and_points_auto(v, f, pts), reps)
        finally:
            for k, val in old.items():
                os.environ.pop(k, None) if val is None \
                    else os.environ.__setitem__(k, val)
        t_accel = _time_best(
            lambda: closest_faces_and_points_accel(v, f, pts, kind=kind),
            reps)
        variant = (pallas_bvh_variant(f.shape[0])
                   if kind == "bvh" and use_pallas else None)
        wins.append((f.shape[0], t_inc, t_accel, variant or "xla"))
    check_f, check_t = wins[len(wins) // 2][:2]
    v, f = _sphere_mesh(check_f)
    old = {k: os.environ.get(k) for k in incumbent_env}
    os.environ.update(incumbent_env)
    try:
        recheck = _time_best(
            lambda: closest_faces_and_points_auto(v, f, pts), reps)
    finally:
        for k, val in old.items():
            os.environ.pop(k, None) if val is None \
                else os.environ.__setitem__(k, val)
    stable = max(check_t, recheck) <= 2.0 * min(check_t, recheck)
    crossover = None
    for i, (n_f, t_i, t_a, _var) in enumerate(wins):
        if t_a < t_i and all(ta < ti for _, ti, ta, _v in wins[i:]):
            crossover = n_f
            break
    if crossover is None:
        crossover = 2 * wins[-1][0]
    global _accel_measured
    _accel_measured = crossover
    if not stable:
        log.warning(
            "calibrate_accel_crossover: backend timings unstable (%.3fs vs "
            "%.3fs at F=%d) — not persisting; using %d for this process "
            "only", check_t, recheck, check_f, crossover,
        )
        save = False
    if save:
        try:
            with open(_accel_cache_path(), "w") as fh:
                json.dump({
                    "accel_min_faces": crossover,
                    "kind": kind,
                    "pallas": use_pallas,
                    "ladder": [
                        {"faces": n, "t_incumbent": ti, "t_accel": ta,
                         "variant": var}
                        for n, ti, ta, var in wins
                    ],
                    "n_queries": n_queries,
                    "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
                }, fh, indent=1)
        except OSError:
            pass
    return crossover


def calibrate_mxu_crossover(ladder=(2048, 8192, 32768, 131072),
                            n_queries=1024, reps=3, tile_q=256,
                            tile_f=2048, save=True):
    """Measure where the MXU dot-product tile starts beating the 19-row
    VPU tile on the live backend (``benchmarks/tile_sweep.py --mxu``
    feeds it the best swept tile shape).

    Mirrors ``calibrate_accel_crossover``: returns the smallest ladder F
    where the MXU form wins and keeps winning (the facades route to MXU
    iff ``F >= value`` and MESH_TPU_MXU is on), or 2x past the ladder
    when the VPU tile always won.  Off-TPU both kernels run interpret
    mode, so the result lands under the CPU device key and never leaks
    onto a chip.  Persisted to the cache dir unless ``save=False`` or
    the timings look unstable.
    """
    from .pallas_closest import closest_point_pallas, \
        closest_point_pallas_mxu
    from ..utils.dispatch import pallas_default

    interpret = not pallas_default()
    rng = np.random.RandomState(0)
    pts = rng.randn(n_queries, 3).astype(np.float32)
    wins = []
    for n_f in ladder:
        v, f = _sphere_mesh(n_f)
        t_vpu = _time_best(lambda: closest_point_pallas(
            v, f, pts, tile_q=tile_q, tile_f=tile_f, interpret=interpret,
            assume_nondegenerate=True), reps)
        t_mxu = _time_best(lambda: closest_point_pallas_mxu(
            v, f, pts, tile_q=tile_q, tile_f=tile_f, interpret=interpret,
            assume_nondegenerate=True), reps)
        wins.append((f.shape[0], t_vpu, t_mxu))
    check_f, check_t, _ = wins[len(wins) // 2]
    v, f = _sphere_mesh(check_f)
    recheck = _time_best(lambda: closest_point_pallas(
        v, f, pts, tile_q=tile_q, tile_f=tile_f, interpret=interpret,
        assume_nondegenerate=True), reps)
    stable = max(check_t, recheck) <= 2.0 * min(check_t, recheck)
    crossover = None
    for i, (n_f, t_v, t_m) in enumerate(wins):
        if t_m < t_v and all(tm < tv for _, tv, tm in wins[i:]):
            crossover = n_f
            break
    if crossover is None:
        crossover = 2 * wins[-1][0]   # the VPU tile won everywhere
    global _mxu_measured
    _mxu_measured = crossover
    if not stable:
        log.warning(
            "calibrate_mxu_crossover: backend timings unstable (%.3fs vs "
            "%.3fs at F=%d) — not persisting; using %d for this process "
            "only", check_t, recheck, check_f, crossover,
        )
        save = False
    if save:
        try:
            with open(_mxu_cache_path(), "w") as fh:
                json.dump({
                    "mxu_crossover_faces": crossover,
                    "tile_q": tile_q,
                    "tile_f": tile_f,
                    "interpret": bool(interpret),
                    "ladder": [
                        {"faces": n, "t_vpu": tv, "t_mxu": tm}
                        for n, tv, tm in wins
                    ],
                    "n_queries": n_queries,
                    "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
                }, fh, indent=1)
        except OSError:
            pass
    return crossover
