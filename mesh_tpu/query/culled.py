"""Two-phase culled closest-point for large target meshes.

SURVEY.md section 7.1, second regime: for meshes beyond the brute-force
comfort zone (F >> 16k — e.g. querying against a raw 200k-face scan), the
reference descends a CGAL AABB tree (mesh/src/spatialsearchmodule.cpp:
129-218).  Pointer-chasing trees are hostile to XLA, so here the cull is
rank-based and branch-free:

  phase 1  a cheap conservative lower bound on the point-triangle distance
           is evaluated for every (query, triangle) pair:
               lb = max(0, |q - centroid| - bounding_radius)
           (~6 flops/pair vs ~60 for the exact Ericson test), and
           ``lax.top_k`` selects the k candidates with the smallest bound;
  phase 2  the exact branch-free test (point_triangle.py) runs on the
           k candidates only, and an argmin picks the winner.

Every non-candidate triangle has true distance >= lb >= (k-th smallest lb),
so each query also gets a certificate: ``tight[q]`` is True iff the best
exact distance found is <= the k-th lower bound — i.e. the result is provably
the global optimum.  ``closest_faces_and_points_auto`` re-runs the rare
non-tight queries through the exact brute-force path, so its results are
always exact while the O(Q*F) work is the cheap bound, not the full test.

All kernels are jit-compatible with fixed shapes and batch over query tiles.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .closest_point import _pad_to_multiple, closest_faces_and_points
from .point_triangle import closest_point_on_triangle
from ..utils.dispatch import pallas_default

_STRATEGY_COUNTER = None
_FALLBACK_COUNTER = None
_MXU_REPAIR_COUNTER = None


def _record_strategy(path):
    """Count which kernel the auto facade picked
    (``mesh_tpu_query_strategy_total{path=}``) — the Pallas-vs-XLA and
    brute-vs-culled routing visibility doc/observability.md promises."""
    global _STRATEGY_COUNTER
    if _STRATEGY_COUNTER is None:
        from ..obs.metrics import REGISTRY

        _STRATEGY_COUNTER = REGISTRY.counter(
            "mesh_tpu_query_strategy_total",
            "closest_faces_and_points_auto kernel-path decisions.",
        )
    _STRATEGY_COUNTER.inc(path=path)


def _record_fallback(queries):
    """Count certificate-miss re-runs: queries whose culled result could
    not be proven optimal and went back through brute force."""
    global _FALLBACK_COUNTER
    if _FALLBACK_COUNTER is None:
        from ..obs.metrics import REGISTRY

        _FALLBACK_COUNTER = REGISTRY.counter(
            "mesh_tpu_query_certificate_fallback_total",
            "Loose-certificate queries re-run through exact brute force.",
        )
    _FALLBACK_COUNTER.inc(int(queries))


def _record_mxu_repair(screened, repaired, kind):
    """Count the bf16 first pass's screening outcomes per face tile
    (``mesh_tpu_query_mxu_repair_total{kind=,outcome=}``): ``repaired``
    tiles ran the f32 exact-repair matmul, ``skipped`` tiles were proven
    empty by the certified bf16 bound.  A screen that stops pruning
    (repair rate -> 1) or the arrival of the series at all is visible in
    the registry, never silent (doc/observability.md)."""
    global _MXU_REPAIR_COUNTER
    if _MXU_REPAIR_COUNTER is None:
        from ..obs.metrics import REGISTRY

        _MXU_REPAIR_COUNTER = REGISTRY.counter(
            "mesh_tpu_query_mxu_repair_total",
            "bf16-screened MXU face tiles by repair outcome.",
        )
    _MXU_REPAIR_COUNTER.inc(int(repaired), kind=kind, outcome="repaired")
    _MXU_REPAIR_COUNTER.inc(int(screened) - int(repaired), kind=kind,
                            outcome="skipped")


def triangle_bounds(v, f):
    """Per-triangle centroid [F, 3] and bounding radius [F] (max distance
    from centroid to a corner)."""
    tri = jnp.asarray(v)[jnp.asarray(f)]
    cen = jnp.mean(tri, axis=1)
    rad = jnp.sqrt(jnp.max(jnp.sum((tri - cen[:, None, :]) ** 2, axis=-1), axis=1))
    return cen, rad


@partial(jax.jit, static_argnames=("k", "chunk"))
def closest_faces_and_points_culled(v, f, points, k=64, chunk=256):
    """Top-k culled closest point on mesh.

    :param v: [V, 3] vertices
    :param f: [F, 3] int faces
    :param points: [Q, 3] query points
    :param k: candidate-set size (exactness certificate gets stronger with k)
    :param chunk: query-tile size; each tile holds a chunk x F bound matrix
    :returns: dict with ``face`` [Q] int32, ``part`` [Q] int32 (CGAL codes),
        ``point`` [Q, 3], ``sqdist`` [Q], and ``tight`` [Q] bool — True where
        the result is provably the global optimum.
    """
    v = jnp.asarray(v)
    points = jnp.asarray(points, dtype=v.dtype)
    center = jnp.mean(v, axis=0)
    v = v - center
    points = points - center

    tri = v[f]
    a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]
    cen, rad = triangle_bounds(v, f)
    # f32 guard: the certificate must stay conservative under rounding in
    # d_cen/rad, so shrink the claimed bound by a scene-relative tolerance.
    cert_tol = 1e-5 * jnp.max(jnp.abs(v))

    k = min(k, f.shape[0])
    padded, n_q = _pad_to_multiple(points, chunk, axis=0)
    tiles = padded.reshape(-1, chunk, 3)

    def one_tile(pts):
        diff = pts[:, None, :] - cen[None]  # [chunk, F, 3]
        d_cen = jnp.sqrt(jnp.sum(diff * diff, axis=-1))
        lb = jnp.maximum(d_cen - rad[None], 0.0)
        neg_kth, idx = jax.lax.top_k(-lb, k)  # k smallest lower bounds
        kth_lb = -neg_kth[:, -1]
        pt, sq, part = closest_point_on_triangle(
            pts[:, None, :], a[idx], b[idx], c[idx]
        )
        j = jnp.argmin(sq, axis=-1)
        rows = jnp.arange(pts.shape[0])
        best_sq = sq[rows, j]
        tight = jnp.sqrt(best_sq) <= kth_lb - cert_tol
        return (
            idx[rows, j].astype(jnp.int32),
            part[rows, j],
            pt[rows, j],
            best_sq,
            tight,
        )

    face, part, point, sqdist, tight = jax.lax.map(one_tile, tiles)
    return {
        "face": face.reshape(-1)[:n_q],
        "part": part.reshape(-1)[:n_q],
        "point": point.reshape(-1, 3)[:n_q] + center,
        "sqdist": sqdist.reshape(-1)[:n_q],
        "tight": tight.reshape(-1)[:n_q],
    }


def closest_faces_and_points_auto(
    v, f, points, brute_force_max_faces=None, k=64, chunk=256
):
    """Exact closest point with automatic strategy choice.

    Small meshes take the exact brute-force path (closest_point.py); large
    meshes take the culled path, and any query whose certificate is not tight
    (candidate set could not be proven optimal) is re-run through brute force,
    so the result is always exact.  Host-boundary function (returns numpy).

    The switch point defaults to the MEASURED brute-vs-culled crossover for
    this backend (query/autotune.py: $MESH_TPU_BRUTE_MAX_FACES override,
    else a cached `calibrate_crossover()` run, else 32768); pass
    ``brute_force_max_faces`` to pin it explicitly.

    Above a second, larger crossover (autotune.accel_crossover_faces —
    $MESH_TPU_ACCEL_MIN_FACES override, else a cached calibration, else
    131072) the spatial-index path (mesh_tpu.accel) takes over: the
    per-topology flattened BVH / uniform grid makes pair tests sub-linear
    in F, and its own certificate/fallback pass keeps results exact.
    ``MESH_TPU_NO_ACCEL=1`` is the kill switch back to this ladder.

    On TPU both non-accel branches run their Pallas kernels: the
    VMEM-tiled brute-force scan, and the tile-sphere-culled kernel, which
    is exact by construction (its bounds are conservative — no
    certificate/fallback pass is needed, pallas_culled.py).

    The chosen strategy is recorded in
    ``mesh_tpu_query_strategy_total{path=}`` exactly once per call — a
    certificate-miss fallback re-run counts under
    ``mesh_tpu_query_certificate_fallback_total``, never as a second
    strategy decision (doc/observability.md lists every label).
    """
    if brute_force_max_faces is None:
        from .autotune import crossover_faces

        brute_force_max_faces = crossover_faces()
    f = np.asarray(f)
    from ..utils.dispatch import accel_kind, no_accel

    if not no_accel():
        from .autotune import accel_crossover_faces

        if f.shape[0] >= accel_crossover_faces():
            kind = accel_kind()
            _record_strategy("accel_%s" % kind)
            from ..accel.traverse import closest_faces_and_points_accel

            return closest_faces_and_points_accel(
                v, f, points, kind=kind)
    if pallas_default():
        from .pallas_closest import closest_point_pallas, mesh_is_nondegenerate
        from .pallas_culled import closest_point_pallas_culled

        v32 = np.asarray(v, np.float32)
        pts32 = np.asarray(points, np.float32).reshape(-1, 3)
        # the numpy boundary is the one place the nondegeneracy flag can
        # be asserted from data: meshes whose every face clears the
        # relative area cut compile their tile without its
        # degenerate-face override (~25% fewer VPU ops, bit-identical
        # results — pallas_closest._ericson_tail); content-crc cached
        nondegen = mesh_is_nondegenerate(v32, f)
        # MESH_TPU_SAFE_TILES pins the sliver-safe direct-corner tile as
        # well as the degenerate tail (mesh_is_nondegenerate already
        # returns False under it): untrusted long-edge sliver meshes keep
        # reference-grade argmin conditioning (_sqdist_tile_safe).  The
        # culled kernel runs the same safe tile inside its sphere-culled
        # grid (pallas_culled tile_variant="safe"), so the brute-vs-culled
        # crossover applies under the flag too — the escape hatch no
        # longer costs large-F meshes their tiling.
        from ..utils.dispatch import (
            mxu_bf16_enabled, mxu_enabled, tile_variant)

        variant = tile_variant()
        if (mxu_enabled() and variant == "fast"
                and f.shape[0] <= brute_force_max_faces):
            from .autotune import mxu_crossover_faces

            if f.shape[0] >= mxu_crossover_faces():
                # MESH_TPU_MXU + the calibrated crossover route the
                # dense scan to the matmul-form tile; with the bf16
                # first pass on, the repair outcome feeds its series.
                # Off (the default) every path below is bit-identical
                # to the pre-MXU routing.
                _record_strategy("mxu")
                if mxu_bf16_enabled():
                    from .pallas_closest import \
                        closest_point_pallas_mxu_repair

                    res, stats = closest_point_pallas_mxu_repair(
                        v32, f.astype(np.int32), pts32,
                        assume_nondegenerate=nondegen, with_stats=True)
                    _record_mxu_repair(
                        stats["screened"], stats["repaired"], "dense")
                else:
                    from .pallas_closest import closest_point_pallas_mxu

                    res = closest_point_pallas_mxu(
                        v32, f.astype(np.int32), pts32,
                        assume_nondegenerate=nondegen)
                return {key: np.asarray(val) for key, val in res.items()}
        if f.shape[0] <= brute_force_max_faces:
            _record_strategy(
                "pallas_safe" if variant == "safe" else "pallas_brute")
            res = closest_point_pallas(
                v32, f.astype(np.int32), pts32,
                assume_nondegenerate=nondegen, tile_variant=variant,
            )
        else:
            _record_strategy(
                "pallas_culled_safe" if variant == "safe"
                else "pallas_culled")
            res = closest_point_pallas_culled(
                v32, f.astype(np.int32), pts32,
                assume_nondegenerate=nondegen, tile_variant=variant,
            )
        return {key: np.asarray(val) for key, val in res.items()}
    if f.shape[0] <= brute_force_max_faces:
        _record_strategy("xla_brute")
        res = closest_faces_and_points(v, f, points)
        return {key: np.asarray(val) for key, val in res.items()}
    # strategy recorded BEFORE the certificate check: a loose-certificate
    # re-run below is part of this same xla_culled call, counted only in
    # the fallback series — it must not look like a second routing decision
    _record_strategy("xla_culled")
    res = closest_faces_and_points_culled(v, f, points, k=k, chunk=chunk)
    out = {key: np.asarray(val) for key, val in res.items()}
    tight = out.pop("tight")
    loose = np.nonzero(~tight)[0]
    if loose.size:
        _record_fallback(loose.size)
        fix = closest_faces_and_points(v, f, np.asarray(points)[loose])
        for key in ("face", "part", "sqdist"):
            out[key] = out[key].copy()
            out[key][loose] = np.asarray(fix[key])
        out["point"] = out["point"].copy()
        out["point"][loose] = np.asarray(fix["point"])
    return out
