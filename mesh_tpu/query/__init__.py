from .point_triangle import closest_point_on_triangle  # noqa: F401
from .closest_point import (  # noqa: F401
    closest_faces_and_points,
    closest_vertices,
    closest_vertices_with_distance,
)
from .autotune import calibrate_crossover, crossover_faces  # noqa: F401
from .culled import (  # noqa: F401
    closest_faces_and_points_auto,
    closest_faces_and_points_culled,
    triangle_bounds,
)
from .anchored import (  # noqa: F401
    build_anchor_tables,
    closest_point_anchored,
    closest_point_anchored_auto,
)
from .normal_weighted import nearest_normal_weighted  # noqa: F401

# Pallas kernels (pallas_closest.closest_point_pallas,
# pallas_culled.closest_point_pallas_culled) are intentionally not imported
# here: accelerator users import them from their modules, mirroring the
# reference's lazy compiled-extension boundary (search.py:22-24).
from .ray import (  # noqa: F401
    ray_triangle_hits,
    nearest_alongnormal,
    intersections_mask,
    self_intersection_count,
)
from .visibility import visibility_compute  # noqa: F401
