"""Closest-point-on-mesh and closest-vertex queries, pure JAX.

TPU-native replacement for the reference `spatialsearch` CGAL AABB tree
(mesh/src/spatialsearchmodule.cpp:74-218) and the scipy-KDTree
`ClosestPointTree` (mesh/search.py:52-65, which loops per query point in
Python).  Strategy per SURVEY.md section 7.1: for SMPL-scale meshes
(F <~ 16k) exact brute force over (query x triangle) pairs is the *fast*
path on TPU — branch-free arithmetic on the VPU beats pointer-chasing — so we
tile the query axis to bound memory and argmin over faces.

All functions are jit-friendly, batch over leading axes of ``v`` via vmap,
and return fixed-shape arrays.
"""

from functools import partial

import jax
import jax.numpy as jnp

from .point_triangle import closest_point_barycentric, closest_point_on_triangle
from ..utils.dispatch import pallas_default


def _pad_to_multiple(x, multiple, axis):
    n = x.shape[axis]
    pad = (-n) % multiple
    if pad == 0:
        return x, n
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, mode="edge"), n


@partial(jax.jit, static_argnames=("chunk",))
def closest_faces_and_points(v, f, points, chunk=512):
    """For each query point, the nearest face / part / point on the mesh.

    :param v: [V, 3] mesh vertices
    :param f: [F, 3] int faces
    :param points: [Q, 3] query points
    :param chunk: query-tile size (memory knob: each tile materializes a
        chunk x F distance matrix)
    :returns: dict with ``face`` [Q] int32, ``part`` [Q] int32 (CGAL codes
        0-6, spatialsearchmodule.cpp:129-140), ``point`` [Q, 3], and
        ``sqdist`` [Q].
    """
    v = jnp.asarray(v)
    points = jnp.asarray(points, dtype=v.dtype)
    # f32 conditioning: center on the mesh so coordinates are small relative
    # to the query geometry (SURVEY.md 7.1 dtype policy).
    center = jnp.mean(v, axis=0)
    v = v - center
    points = points - center

    tri = v[f]  # [F, 3, 3]
    a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]

    padded, n_q = _pad_to_multiple(points, chunk, axis=0)
    tiles = padded.reshape(-1, chunk, 3)

    def one_tile(pts):
        # [chunk, 1, 3] vs [1, F, 3] -> [chunk, F]
        bary, _ = closest_point_barycentric(
            pts[:, None, :], a[None], b[None], c[None]
        )
        cp = (
            bary[..., 0:1] * a[None]
            + bary[..., 1:2] * b[None]
            + bary[..., 2:3] * c[None]
        )
        diff = pts[:, None, :] - cp
        sq = jnp.sum(diff * diff, axis=-1)  # [chunk, F]
        best = jnp.argmin(sq, axis=-1)  # [chunk]
        # Recompute exactly for the winning face (cheap: chunk x 1).
        pt, sqd, part = closest_point_on_triangle(
            pts, a[best], b[best], c[best]
        )
        return best.astype(jnp.int32), part, pt, sqd

    face, part, point, sqdist = jax.lax.map(one_tile, tiles)
    face = face.reshape(-1)[:n_q]
    part = part.reshape(-1)[:n_q]
    point = point.reshape(-1, 3)[:n_q] + center
    sqdist = sqdist.reshape(-1)[:n_q]
    return {"face": face, "part": part, "point": point, "sqdist": sqdist}


def closest_vertices_with_distance(v, points, chunk=2048):
    """Nearest mesh vertex per query -> (index [Q] int32, distance [Q]).

    Replaces reference ClosestPointTree (search.py:52-65) / the
    degenerate-triangle CGALClosestPointTree (search.py:68-86) with a tiled
    brute-force pairwise argmin — one fused XLA computation instead of a
    Python loop over scipy KDTree queries.  On TPU the scan runs in the
    Pallas argmin kernel (pallas_closest.nearest_vertices_pallas).
    """
    if pallas_default():
        from .pallas_closest import nearest_vertices_pallas

        return nearest_vertices_pallas(v, points)
    return _closest_vertices_xla(v, points, chunk=chunk)


@partial(jax.jit, static_argnames=("chunk",))
def _closest_vertices_xla(v, points, chunk=2048):
    v = jnp.asarray(v)
    points = jnp.asarray(points, dtype=v.dtype)
    center = jnp.mean(v, axis=0)
    vc = v - center
    padded, n_q = _pad_to_multiple(points - center, chunk, axis=0)
    tiles = padded.reshape(-1, chunk, 3)

    def one_tile(pts):
        diff = pts[:, None, :] - vc[None]  # [chunk, V, 3]
        sq = jnp.sum(diff * diff, axis=-1)
        idx = jnp.argmin(sq, axis=-1)
        return idx.astype(jnp.int32), jnp.sqrt(sq[jnp.arange(pts.shape[0]), idx])

    idx, dist = jax.lax.map(one_tile, tiles)
    return idx.reshape(-1)[:n_q], dist.reshape(-1)[:n_q]


def closest_vertices(v, points, chunk=2048):
    """Nearest-vertex indices only (reference ClosestPointTree.nearest)."""
    return closest_vertices_with_distance(v, points, chunk=chunk)[0]


def closest_point_dispatch(v, f, pts, chunk=512, use_pallas=False,
                           nondegen=False, variant="fast"):
    """The one Pallas-vs-XLA closest-point dispatch body shared by the
    batched and sharded facades (batch.py, parallel/sharding.py): the
    Pallas tile — with the staging-derived ``nondegen`` flag and the
    MESH_TPU_SAFE_TILES ``variant`` — when the caller runs on TPU, the
    chunked XLA tiling elsewhere.  One body means a new kernel flag is
    threaded once, not once per facade."""
    if use_pallas:
        from .pallas_closest import closest_point_pallas

        return closest_point_pallas(
            v, f, pts, assume_nondegenerate=nondegen, tile_variant=variant)
    return closest_faces_and_points(v, f, pts, chunk=chunk)
