"""Normal-weighted nearest neighbor, pure JAX.

TPU-native replacement for the reference `aabb_normals` extension
(mesh/src/AABB_n_tree.h:40-84): find, per query (point, normal), the triangle
minimizing ``|p - q| + eps * (1 - n_p . n_tri)`` where q is the euclidean
closest point on the triangle and n_tri its unit normal.  Brute force over
(query x triangle) makes the reference's 300 lines of custom CGAL traits
(sphere-pruned tree descent with a random-hint warm start noted "slow" in
source, AABB_n_tree.h:276-279) unnecessary: one tiled argmin.

Default eps = 0.1 matches AabbNormalsTree (search.py:94).
"""

from functools import partial

import jax
import jax.numpy as jnp

from ..geometry.tri_normals import tri_normals
from .point_triangle import closest_point_barycentric


@partial(jax.jit, static_argnames=("chunk",))
def nearest_normal_weighted(v, f, points, normals, eps=0.1, chunk=512):
    """(face [Q] int32, point [Q, 3]) under the blended distance metric.

    Matches AabbNormalsTree.nearest (search.py:96-100): query normals are
    used as given (the reference does not normalize them); triangle normals
    are unit.
    """
    v = jnp.asarray(v)
    points = jnp.asarray(points, v.dtype)
    normals = jnp.asarray(normals, v.dtype)
    tri = v[f]
    a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]
    tn = tri_normals(v, f)  # [F, 3] unit

    n_q = points.shape[0]
    pad = (-n_q) % chunk
    points_p = jnp.pad(points, ((0, pad), (0, 0)), mode="edge")
    normals_p = jnp.pad(normals, ((0, pad), (0, 0)), mode="edge")

    def one_tile(args):
        pts, nrm = args
        bary, _ = closest_point_barycentric(
            pts[:, None, :], a[None], b[None], c[None]
        )
        cp = (
            bary[..., 0:1] * a[None]
            + bary[..., 1:2] * b[None]
            + bary[..., 2:3] * c[None]
        )  # [chunk, F, 3]
        d_euclid = jnp.linalg.norm(pts[:, None, :] - cp, axis=-1)
        penalty = eps * (1.0 - jnp.sum(nrm[:, None, :] * tn[None], axis=-1))
        cost = d_euclid + penalty
        best = jnp.argmin(cost, axis=-1)
        rows = jnp.arange(pts.shape[0])
        return best.astype(jnp.int32), cp[rows, best]

    face, point = jax.lax.map(
        one_tile, (points_p.reshape(-1, chunk, 3), normals_p.reshape(-1, chunk, 3))
    )
    return face.reshape(-1)[:n_q], point.reshape(-1, 3)[:n_q]
