"""Ray / segment / triangle-triangle intersection queries, pure JAX.

TPU-native replacement for the reference's CGAL intersection machinery:
- `nearest_alongnormal` (spatialsearchmodule.cpp:222-323): per query, the
  nearest mesh intersection along +/- the query normal; sentinel 1e100 when
  nothing is hit.  The CGAL all-hits list is never materialized — it becomes
  a min-reduction over all triangles (SURVEY.md section 7.3).
- `intersections_mask` (spatialsearchmodule.cpp:326-417): which query
  triangles intersect the mesh.  Returned as a fixed-shape boolean mask
  (the reference's variable-length index list has a data-dependent shape).
  NB the reference implementation has a real data race here
  (SURVEY.md section 5) — the functional formulation removes it.
- `self_intersection_count` (aabb_normals.cpp:192-207 /
  AABB_n_tree.h:95-117): number of faces involved in at least one
  intersection with a face they share no vertex index with (the reference
  counts per-face involvement, not pairs).

Triangle-triangle overlap uses the segment-vs-triangle formulation (each edge
of one triangle tested against the face of the other, both ways), which is
exact for non-coplanar pairs; exactly-coplanar overlapping pairs are not
counted (CGAL counts them; they do not occur in generic float data).
"""

from functools import partial

import jax
import jax.numpy as jnp
from ..utils.dispatch import pallas_default

_EPS = 1e-9
# Barycentric inclusion tolerance for ray hits.  Must be much wider than f32
# roundoff: a ray crossing exactly on the shared edge of two triangles must
# register on at least one of them (with 1e-9 it can slip through the crack
# between both and a back-face vertex reports visible).  1e-6 in barycentric
# units errs toward counting edge hits on both neighbors, matching CGAL's
# exact-arithmetic behavior for occlusion tests.
_BARY_EPS = 1e-6
# The reference uses 1e100 as its no-hit sentinel (spatialsearchmodule.cpp:
# 309-311); that overflows float32, so device code uses +inf and the Mesh
# facade converts to 1e100 at the numpy boundary.
NO_HIT = jnp.inf


def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


def ray_triangle_hits(o, d, a, b, c, eps=_EPS, bary_eps=_BARY_EPS):
    """Moller-Trumbore: signed ray parameter t per (ray, triangle) pair.

    All inputs broadcastable to [..., 3].  Returns (t, hit): the intersection
    is at o + t*d where `hit` (t unrestricted in sign — callers clamp).
    `eps` guards the parallel-ray determinant; `bary_eps` is the barycentric
    inclusion tolerance (wide default for watertight occlusion/along-normal
    queries; intersection predicates pass a tight value — see
    tri_tri_intersects).
    """
    e1 = b - a
    e2 = c - a
    pvec = jnp.cross(d, e2)
    det = _dot(e1, pvec)
    parallel = jnp.abs(det) < eps
    inv_det = 1.0 / jnp.where(parallel, 1.0, det)
    tvec = o - a
    u = _dot(tvec, pvec) * inv_det
    qvec = jnp.cross(tvec, e1)
    v = _dot(d, qvec) * inv_det
    t = _dot(e2, qvec) * inv_det
    hit = (
        (~parallel)
        & (u >= -bary_eps)
        & (v >= -bary_eps)
        & (u + v <= 1 + bary_eps)
    )
    return t, hit


def nearest_alongnormal(v, f, points, normals, chunk=512):
    """Nearest mesh hit along the line through each point in +/-normal.

    Matches reference AabbTree.nearest_alongnormal (search.py:32-37):
    returns (distance [Q], face [Q] int32, point [Q, 3]); distance is the
    euclidean distance from the query to the hit (|t| * |n|), +inf when no
    triangle is hit in either direction (the Mesh facade maps that to the
    reference's 1e100 sentinel).  On accelerators the O(Q*F) scan runs in
    the Pallas min-hit kernel (pallas_ray.py); the XLA tiling below is the
    CPU/interpret path.
    """
    if pallas_default():
        from .pallas_ray import nearest_alongnormal_pallas

        return nearest_alongnormal_pallas(v, f, points, normals)
    return _nearest_alongnormal_xla(v, f, points, normals, chunk=chunk)


@partial(jax.jit, static_argnames=("chunk",))
def _nearest_alongnormal_xla(v, f, points, normals, chunk=512):
    v = jnp.asarray(v)
    points = jnp.asarray(points, v.dtype)
    normals = jnp.asarray(normals, v.dtype)
    tri = v[f]
    a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]

    pad = (-points.shape[0]) % chunk
    n_q = points.shape[0]
    points_p = jnp.pad(points, ((0, pad), (0, 0)), mode="edge")
    normals_p = jnp.pad(normals, ((0, pad), (0, 0)), mode="edge")

    def one_tile(args):
        pts, nrm = args  # [chunk, 3]
        t, hit = ray_triangle_hits(
            pts[:, None, :], nrm[:, None, :], a[None], b[None], c[None]
        )  # [chunk, F]
        dist = jnp.abs(t) * jnp.linalg.norm(nrm, axis=-1, keepdims=True)
        dist = jnp.where(hit, dist, NO_HIT)
        best = jnp.argmin(dist, axis=-1)
        rows = jnp.arange(pts.shape[0])
        best_t = t[rows, best]
        best_d = dist[rows, best]
        pt = pts + best_t[:, None] * nrm
        pt = jnp.where(jnp.isfinite(best_d)[:, None], pt, 0.0)
        return best_d, best.astype(jnp.int32), pt

    dist, face, point = jax.lax.map(
        one_tile, (points_p.reshape(-1, chunk, 3), normals_p.reshape(-1, chunk, 3))
    )
    return (
        dist.reshape(-1)[:n_q],
        face.reshape(-1)[:n_q],
        point.reshape(-1, 3)[:n_q],
    )


def _segment_hits_triangles(s0, s1, a, b, c, eps=_EPS):
    """True where segment s0->s1 crosses triangle abc (broadcast [...]).

    Uses a tight barycentric tolerance: intersection predicates must not
    report grazing-but-separate geometry as intersecting."""
    d = s1 - s0
    t, hit = ray_triangle_hits(s0, d, a, b, c, eps, bary_eps=eps)
    return hit & (t >= -eps) & (t <= 1 + eps)


def tri_tri_intersects(p, q, eps=_EPS):
    """Pairwise triangle-triangle intersection.

    :param p: [..., 3, 3] triangles (3 vertices x xyz)
    :param q: [..., 3, 3] triangles, broadcast-compatible with p
    :returns: boolean [...]
    """
    out = jnp.zeros(jnp.broadcast_shapes(p.shape[:-2], q.shape[:-2]), bool)
    for src, dst in ((p, q), (q, p)):
        a, b, c = dst[..., 0, :], dst[..., 1, :], dst[..., 2, :]
        for i in range(3):
            s0 = src[..., i, :]
            s1 = src[..., (i + 1) % 3, :]
            out = out | _segment_hits_triangles(s0, s1, a, b, c, eps)
    return out


def tri_tri_intersects_moller(p, q, eps=None):
    """Pairwise triangle intersection via the Möller '97 no-division
    interval test — decision parity with ``tri_tri_intersects`` on
    non-degenerate, non-coplanar, non-borderline pairs at ~half the
    arithmetic.  A DEGENERATE (zero-normal) triangle is blind here
    (reports no intersection even when its edges pierce the other
    triangle), so callers must gate on
    ``pallas_closest.mesh_is_nondegenerate`` for both sides — the facade
    does (``intersections_mask``).  Coplanar overlap is not counted,
    matching the segment formulation (module docstring).

    :param p: [..., 3, 3] triangles; :param q: broadcast-compatible
    :param eps: plane-thickening tolerance in INPUT units, rescaled
        internally into the unit-box frame the intervals run in (the
        joint prescale maps a length L to L * s, so eps rides along).
        None (default) uses the module ``_EPS`` directly in prescaled
        units — the O(1) data scale the published algorithm assumes.
    :returns: boolean [...]
    """
    from .pallas_ray import _moller_hit, _tri_planes, moller_prescale

    p = jnp.asarray(p)
    q = jnp.asarray(q, p.dtype)
    # joint unit-box prescale: the interval terms scale as extent^13 and
    # overflow f32 on mm-scale inputs otherwise (moller_prescale docstring)
    (p, q), scale = moller_prescale(p, q, with_scale=True)
    eps = _EPS if eps is None else eps * scale
    pa, pb, pc, pn, pd = _tri_planes(p)
    qa, qb, qc, qn, qd = _tri_planes(q)

    def comps(arr):
        return tuple(arr[..., k] for k in range(3))

    return _moller_hit(
        comps(pa), comps(pb), comps(pc), comps(pn), pd,
        comps(qa), comps(qb), comps(qc), comps(qn), qd, eps,
    )


def intersections_mask(v, f, q_v, q_f, chunk=128):
    """Boolean mask over query faces: does q_f[i] intersect the (v, f) mesh?

    Fixed-shape replacement for AabbTree.intersections_indices
    (search.py:39-49); `np.nonzero(mask)` recovers the reference's index list.
    On accelerators the O(QF*F) pair grid runs in the Pallas triangle-
    triangle kernel (pallas_ray.py) — the Möller interval tile (~2x fewer
    ops) when every face of both meshes is non-degenerate (checked from
    data at this numpy boundary), the segment tile otherwise; the XLA
    tiling below is the CPU/interpret path.
    """
    if pallas_default():
        return _intersections_mask_pallas(
            v, f, q_v, q_f,
            algorithm=_tri_tri_algorithm(v, f, q_v, q_f),
        )
    return _intersections_mask_xla(v, f, q_v, q_f, chunk=chunk)


def _tri_tri_algorithm(v, f, q_v, q_f):
    """Kernel choice for the pair grid: the Möller interval tile needs
    every triangle of BOTH meshes non-degenerate; anything else keeps the
    segment tile, whose edge tests stay meaningful on zero-area faces."""
    from .pallas_closest import mesh_is_nondegenerate

    return (
        "moller"
        if mesh_is_nondegenerate(v, f) and mesh_is_nondegenerate(q_v, q_f)
        else "segment"
    )


@partial(jax.jit, static_argnames=("algorithm",))
def _intersections_mask_pallas(v, f, q_v, q_f, algorithm="segment"):
    # one jitted dispatch: the gathers fuse into the same launch as the
    # kernel instead of running as eager per-op round trips
    from .pallas_ray import tri_tri_any_hit_pallas

    v = jnp.asarray(v)
    return tri_tri_any_hit_pallas(
        jnp.asarray(q_v, v.dtype)[q_f], v[f], algorithm=algorithm
    )


@partial(jax.jit, static_argnames=("chunk",))
def _intersections_mask_xla(v, f, q_v, q_f, chunk=128):
    v = jnp.asarray(v)
    tri_mesh = v[f]  # [F, 3, 3]
    q_tri = jnp.asarray(q_v, v.dtype)[q_f]  # [QF, 3, 3]
    n_q = q_tri.shape[0]
    pad = (-n_q) % chunk
    q_tri_p = jnp.pad(q_tri, ((0, pad), (0, 0), (0, 0)), mode="edge")

    def one_tile(qt):  # [chunk, 3, 3]
        return jnp.any(
            tri_tri_intersects(qt[:, None], tri_mesh[None]), axis=-1
        )

    mask = jax.lax.map(one_tile, q_tri_p.reshape(-1, chunk, 3, 3))
    return mask.reshape(-1)[:n_q]


def self_intersection_count(v, f, chunk=128):
    """Number of faces that intersect at least one other face of the mesh,
    excluding vertex-sharing pairs.

    Parity with aabb_normals.aabbtree_n_selfintersects (aabb_normals.cpp:
    193-207): the loop there asks, PER TRIANGLE, whether the tree intersects
    it anywhere (`if (tree.do_intersect(*it)) ++n`), so each involved face
    counts once no matter how many partners it has — e.g. the reference's
    bent-cylinder fixture counts 2*8 involved faces even though the cap and
    wall fans cross in more than 8 pairs (tests/test_aabb_n_tree.py:85-89).
    Pairs sharing any vertex index are excluded (Do_intersect_noself_traits,
    AABB_n_tree.h:95-117).  On accelerators the O(F^2) pair grid runs in the
    Pallas kernel (pallas_ray.py) — the Möller interval tile when every
    face is non-degenerate (count parity with the segment tile is pinned
    by the reference fixtures), the segment tile otherwise.
    """
    if pallas_default():
        from .pallas_closest import mesh_is_nondegenerate
        from .pallas_ray import self_intersection_count_pallas

        algorithm = (
            "moller" if mesh_is_nondegenerate(v, f) else "segment"
        )
        return self_intersection_count_pallas(v, f, algorithm=algorithm)
    return _self_intersection_count_xla(v, f, chunk=chunk)


@partial(jax.jit, static_argnames=("chunk",))
def _self_intersection_count_xla(v, f, chunk=128):
    v = jnp.asarray(v)
    tri = v[f]  # [F, 3, 3]
    n_f = tri.shape[0]
    pad = (-n_f) % chunk
    tri_p = jnp.pad(tri, ((0, pad), (0, 0), (0, 0)), mode="edge")
    f_p = jnp.pad(f, ((0, pad), (0, 0)), mode="edge")
    idx_p = jnp.pad(jnp.arange(n_f), (0, pad), constant_values=-1)

    def one_tile(args):
        qt, qf, qi = args
        inter = tri_tri_intersects(qt[:, None], tri[None])  # [chunk, F]
        shares = jnp.any(
            qf[:, None, :, None] == f[None, :, None, :], axis=(-1, -2)
        )  # [chunk, F]
        not_self = qi[:, None] != jnp.arange(n_f)[None]
        valid = (qi >= 0)[:, None]
        involved = jnp.any(inter & ~shares & not_self & valid, axis=1)
        return jnp.sum(involved, dtype=jnp.int32)

    counts = jax.lax.map(
        one_tile,
        (
            tri_p.reshape(-1, chunk, 3, 3),
            f_p.reshape(-1, chunk, 3),
            idx_p.reshape(-1, chunk),
        ),
    )
    return jnp.sum(counts)
