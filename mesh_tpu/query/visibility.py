"""Per-(camera, vertex) ray-cast visibility, pure JAX.

TPU-native replacement for the reference `visibility` extension
(mesh/src/visibility.cpp:75-133, py_visibility.cpp:81-213): a vertex is
visible from a camera iff the ray from ``vert + min_dist * dir`` towards the
camera (``dir = normalize(cam - vert)``, extended to infinity like CGAL's
Ray_3) hits no occluder triangle.  Optionally a 9-float sensor model per
camera (x-axis, y-axis, z-axis of the sensor plane) gates visibility by
whether the ray lands within the sensor extents, and an extra occluder mesh
can be merged in.  The reference parallelizes over cameras with TBB; here the
whole (camera x vertex x triangle) grid is one fused computation, tiled over
vertices.
"""

from functools import partial

import jax
import jax.numpy as jnp

from .ray import ray_triangle_hits
from ..utils.dispatch import pallas_default


def _sensor_mask(vts, dirs, cam, sensor):
    """True where the ray from vts along dirs lands within the camera's
    sensor plane extents (the reference's 9-float sensor model,
    visibility.cpp:96-113: x-axis, y-axis, z-axis rows of the plane)."""
    xoff, yoff, zoff = sensor[0:3], sensor[3:6], -sensor[6:9]
    planeoff = jnp.dot(zoff, cam + zoff)
    denom = jnp.sum(zoff[None] * dirs, axis=-1)
    denom = jnp.where(denom == 0, 1e-30, denom)
    tt = -(vts @ zoff - planeoff) / denom
    p_i = (vts + tt[:, None] * dirs) - (cam + zoff)[None]
    return (
        (jnp.abs(p_i @ xoff) < jnp.dot(xoff, xoff))
        & (jnp.abs(p_i @ yoff) < jnp.dot(yoff, yoff))
    )


@partial(jax.jit, static_argnames=("chunk",))
def _visibility_kernel(verts, occ_a, occ_b, occ_c, cams, normals, sensors, min_dist, chunk=1024):
    n_v = verts.shape[0]
    pad = (-n_v) % chunk
    verts_p = jnp.pad(verts, ((0, pad), (0, 0)), mode="edge")
    nrm_p = jnp.pad(normals, ((0, pad), (0, 0)), mode="edge")

    def per_cam(cam, sensor):
        def one_tile(args):
            vts, nrm = args  # [chunk, 3]
            dirs = cam[None] - vts
            dirs = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
            origin = vts + min_dist * dirs
            t, hit = ray_triangle_hits(
                origin[:, None, :], dirs[:, None, :],
                occ_a[None], occ_b[None], occ_c[None],
            )  # [chunk, F]
            blocked = jnp.any(hit & (t >= 0.0), axis=-1)
            reach = ~blocked
            n_dot_cam = jnp.sum(nrm * dirs, axis=-1)
            if sensor is not None:
                reach = reach & _sensor_mask(vts, dirs, cam, sensor)
            return reach, n_dot_cam

        vis, ndc = jax.lax.map(
            one_tile, (verts_p.reshape(-1, chunk, 3), nrm_p.reshape(-1, chunk, 3))
        )
        return vis.reshape(-1)[:n_v], ndc.reshape(-1)[:n_v]

    if sensors is None:
        vis, ndc = jax.vmap(lambda cc: per_cam(cc, None))(cams)
    else:
        vis, ndc = jax.vmap(per_cam)(cams, sensors)
    return vis, ndc


@partial(jax.jit, static_argnames=("interpret",))
def _visibility_kernel_pallas(verts, tri, cams, normals, sensors, min_dist,
                              interpret=False):
    """Accelerator path: the O(C*V*F) blocked test runs in the Pallas
    any-hit kernel (VMEM-resident accumulators, one launch for all
    cameras); the O(C*V) direction/sensor math stays in XLA."""
    from .pallas_ray import ray_any_hit_pallas

    dirs = cams[:, None, :] - verts[None]               # (C, V, 3)
    dirs = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
    origins = verts[None] + min_dist * dirs
    n_c, n_v = dirs.shape[:2]
    blocked = ray_any_hit_pallas(
        origins.reshape(-1, 3), dirs.reshape(-1, 3), tri,
        interpret=interpret,
    ).reshape(n_c, n_v)
    reach = ~blocked
    ndc = jnp.sum(normals[None] * dirs, axis=-1)
    if sensors is not None:
        reach = reach & jax.vmap(
            lambda cam, sensor, d: _sensor_mask(verts, d, cam, sensor)
        )(cams, sensors, dirs)
    return reach, ndc


def _visibility_local(verts, occ_tri, cams, normals, sensors, min_dist,
                      chunk=1024, use_pallas=None):
    """Single dispatch point for the (camera x vertex x triangle) core:
    the Pallas any-hit kernel when running on TPU devices, the XLA tiling
    otherwise.  ``use_pallas`` overrides the process-default check when
    the caller targets a specific device set (the shard_map bodies in
    parallel/sharding.py pass the mesh's platform)."""
    if use_pallas is None:
        use_pallas = pallas_default()
    if use_pallas:
        return _visibility_kernel_pallas(
            verts, occ_tri, cams, normals, sensors, min_dist
        )
    return _visibility_kernel(
        verts, occ_tri[:, 0], occ_tri[:, 1], occ_tri[:, 2], cams, normals,
        sensors, min_dist, chunk=chunk,
    )


def visibility_compute(
    v,
    f,
    cams,
    n=None,
    sensors=None,
    extra_v=None,
    extra_f=None,
    min_dist=1e-3,
):
    """Reference-compatible entry point (py_visibility.cpp:81-213).

    :param v: [V, 3] vertices to test
    :param f: [F, 3] occluder faces over v
    :param cams: [C, 3] camera centers
    :param n: optional [V, 3] vertex normals (for the n.dir output)
    :param sensors: optional [C, 9] sensor axes (x, y, z rows flattened)
    :param extra_v / extra_f: optional additional occluder mesh
    :param min_dist: ray-origin offset epsilon (default 1e-3 as reference)
    :returns: (visibility [C, V] uint32, n_dot_cam [C, V] float)
    """
    import numpy as np

    v = jnp.asarray(v, jnp.float32)
    f = jnp.asarray(f, jnp.int32)
    cams = jnp.atleast_2d(jnp.asarray(cams, jnp.float32))
    occ = v[f]
    if extra_v is not None and extra_f is not None:
        extra = jnp.asarray(extra_v, jnp.float32)[jnp.asarray(extra_f, jnp.int32)]
        occ = jnp.concatenate([occ, extra], axis=0)
    normals = (
        jnp.asarray(n, jnp.float32)
        if n is not None
        else jnp.zeros_like(v)
    )
    sens = None if sensors is None else jnp.atleast_2d(jnp.asarray(sensors, jnp.float32))
    vis, ndc = _visibility_local(
        v, occ, cams, normals, sens, jnp.float32(min_dist)
    )
    return np.asarray(vis).astype(np.uint32), np.asarray(ndc, dtype=np.float64)
