"""Pallas TPU kernel for brute-force closest-point-on-mesh.

The plain-JAX path (closest_point.py) materializes a (Q, F) distance matrix
(plus barycentric intermediates) in HBM per query tile — bandwidth-bound.
This kernel tiles (query x face) onto the VPU and keeps the running
min/argmin accumulators in VMEM, so HBM traffic is O(Q + F) instead of
O(Q * F): each (TQ, TF) tile computes the branch-free Ericson point-triangle
squared distance and folds it into per-query best-distance / best-face
registers.  The exact closest point and CGAL part code are recomputed on the
winning faces afterwards (O(Q) work) by the shared point_triangle module.

Inputs are passed as component planes — px/py/pz of shape (Q, 1) and
per-face planes (corner a, edge vectors ab/ac, normal, hoisted dot products
and reciprocals) of shape (1, F) — so every kernel operand broadcasts to the
native (TQ, TF) VPU tile shape with no in-kernel transposes.
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .point_triangle import closest_point_on_triangle
from ..utils.jax_compat import tpu_compiler_params

_BIG = 1e30

#: grid semantics shared by every (query-tiles, face-tiles) kernel: query
#: tiles are independent ("parallel", Mosaic may split/reorder them); the
#: face dim is "arbitrary" — it carries the VMEM/SMEM accumulators.  A 3D
#: batched grid prepends another "parallel" (pallas_culled).
DIMSEM_QF = ("parallel", "arbitrary")


def _sqdist_tile_fast(px, py, pz,
                      ax, ay, az, abx, aby, abz, acx, acy, acz, nx, ny, nz,
                      ab2, ac2, abac, inv_ab2, inv_ac2, inv_bc2, inv_n2,
                      degenerate_tail=True):
    """Division-free, gather-light Ericson closest-point squared distance
    on a (TQ, TF) tile.

    Same region classification as point_triangle.closest_point_barycentric,
    with two algebraic reductions over the straightforward form:

    - each region's distance has a closed form using per-face reciprocals
      hoisted out of the scan (inv_ab2 = 1/|b-a|^2 etc., nx/ny/nz =
      unnormalized face normal, inv_n2 = 1/|n|^2), so no per-pair division:

        vertex V:    |p - V|^2
        edge   UV:   |p - U|^2 - ((p-U).(V-U))^2 / |V-U|^2
        interior:    ((p-a).n)^2 / |n|^2

    - only the corner-a dot products are computed per pair; the b/c-corner
      Ericson terms follow from bp = ap - ab, cp = ap - ac and hoisted
      per-face dot products (ab2 = ab.ab, ac2 = ac.ac, abac = ab.ac):

        d3 = ab.bp = d1 - ab2        d4 = ac.bp = d2 - abac
        d5 = ab.cp = d1 - abac       d6 = ac.cp = d2 - ac2
        bp2 = ap2 - 2 d1 + ab2       cp2 = ap2 - 2 d2 + ac2

      which drops the b/c coordinate planes and three 5-op dot products
      per pair (~19% faster than the 12-plane form on v5e; the two forms
      together are ~30% over the original reconstruction tile).

    Argmin results agree with the reconstruction form up to exact-distance
    ties (verified in f64: on a posed-body workload 520/532 face
    disagreements were exactly equidistant neighbors, the rest differed by
    < 6e-8).  The winning face's exact point/part are recomputed in the
    epilogue either way.

    Accuracy caveat: the derived corner terms cancel catastrophically for
    queries near corner b/c of faces with LONG edges — bp2 = ap2 - 2 d1 +
    ab2 has absolute error ~ulp(ap2), not ~ulp(bp2), so the error grows
    with edge length (worse for elongated/sliver meshes than the direct
    |p-b|^2 form).  Query centering bounds the magnitudes and only argmin
    tie-flips between near-equidistant faces are affected — the epilogue's
    exact recompute fixes the reported distance/point regardless.  If
    tie-flips ever matter, computing bp2/cp2 directly from b/c coordinate
    planes costs two extra plane loads per face tile.
    """
    apx, apy, apz = px - ax, py - ay, pz - az
    d1 = abx * apx + aby * apy + abz * apz
    d2 = acx * apx + acy * apy + acz * apz
    ap2 = apx * apx + apy * apy + apz * apz
    n_ap = nx * apx + ny * apy + nz * apz
    return _ericson_tail(d1, d2, ap2, n_ap, ab2, ac2, abac,
                         inv_ab2, inv_ac2, inv_bc2, inv_n2,
                         degenerate_tail=degenerate_tail)


def _ericson_tail(d1, d2, ap2, n_ap, ab2, ac2, abac,
                  inv_ab2, inv_ac2, inv_bc2, inv_n2,
                  degenerate_tail=True):
    """Region selection + distance from the four query-dependent scalars
    (d1, d2, ap2, n_ap) and the hoisted per-face constants — the part of
    the fast tile that is independent of HOW the dot products were
    produced (VPU component planes, or the MXU tile's matmul)."""
    d3 = d1 - ab2
    d4 = d2 - abac
    d5 = d1 - abac
    d6 = d2 - ac2
    bp2 = ap2 - (d1 + d1) + ab2
    cp2 = ap2 - (d2 + d2) + ac2
    return _region_select(d1, d2, d3, d4, d5, d6, ap2, bp2, cp2, n_ap,
                          ab2, ac2, abac, inv_ab2, inv_ac2, inv_bc2,
                          inv_n2, degenerate_tail=degenerate_tail)


def _region_select(d1, d2, d3, d4, d5, d6, ap2, bp2, cp2, n_ap,
                   ab2, ac2, abac, inv_ab2, inv_ac2, inv_bc2, inv_n2,
                   degenerate_tail=True):
    """Ericson region classification + squared distance from the full set
    of per-pair dot products — shared by the fast tile (which DERIVES the
    b/c-corner terms from corner-a quantities) and the sliver-safe tile
    (which computes each term directly from its own corner difference)."""
    va = d3 * d6 - d5 * d4
    vb = d5 * d2 - d1 * d6
    vc = d1 * d4 - d3 * d2
    d_bc = d4 - d3                     # (c-b).(p-b), since ac - ab = bc

    # region-selected squared distance; interior first (most common), then
    # progressively override with edge/vertex regions in priority order.
    # (Degenerate faces — inv_n2 == 0 — are fully overridden by the
    # segment minimum at the end, so the interior term's value for them
    # is irrelevant.)
    d = n_ap * n_ap * inv_n2
    on_bc = (va <= 0) & (d_bc >= 0) & (d5 - d6 >= 0)
    d = jnp.where(on_bc, bp2 - d_bc * d_bc * inv_bc2, d)
    on_ca = (vb <= 0) & (d2 >= 0) & (d6 <= 0)
    d = jnp.where(on_ca, ap2 - d2 * d2 * inv_ac2, d)
    on_ab = (vc <= 0) & (d1 >= 0) & (d3 <= 0)
    d = jnp.where(on_ab, ap2 - d1 * d1 * inv_ab2, d)
    in_c = (d6 >= 0) & (d5 <= d6)
    d = jnp.where(in_c, cp2, d)
    in_b = (d3 >= 0) & (d4 <= d3)
    d = jnp.where(in_b, bp2, d)
    in_a = (d1 <= 0) & (d2 <= 0)
    d = jnp.where(in_a, ap2, d)

    # degenerate-face override (inv_n2 == 0, zeroed by fast_tile_rows'
    # RELATIVE area cut): the va/vb/vc region tests above cancel to
    # rounding noise on zero-area faces, so the selected region — and the
    # distance — is arbitrary.  Such a face IS its edge segments; the
    # best clamped segment projection is exact there and costs only
    # already-loaded planes (mirrors point_triangle's override, which the
    # epilogue recompute uses).  Padded faces (zero edges) are safe with
    # OR without this tail: d1 = d2 = 0 routes them to the in_a override
    # above, where ap2 = +inf (corner-a planes pad with _BIG) never wins.
    #
    # ``degenerate_tail=False`` drops the override — ~30 of the tile's
    # ~120 per-pair VPU ops — for callers that KNOW the mesh has no
    # near-degenerate faces (n2 > 1e-10 * ab2 * ac2 for every face; the
    # facade checks this at staging).  With the flag wrongly set, a
    # near-degenerate face's interior term is garbage and it can steal or
    # lose the argmin; the epilogue still reports the winner's exact
    # distance either way.
    if degenerate_tail:
        t_ab = jnp.clip(d1 * inv_ab2, 0.0, 1.0)
        e_ab = ap2 - t_ab * (d1 + d1 - t_ab * ab2)
        t_ca = jnp.clip(d2 * inv_ac2, 0.0, 1.0)
        e_ca = ap2 - t_ca * (d2 + d2 - t_ca * ac2)
        bc2 = ab2 + ac2 - (abac + abac)
        t_bc = jnp.clip(d_bc * inv_bc2, 0.0, 1.0)
        e_bc = bp2 - t_bc * (d_bc + d_bc - t_bc * bc2)
        d = jnp.where(
            inv_n2 > 0, d, jnp.minimum(e_ab, jnp.minimum(e_ca, e_bc))
        )
    # the edge forms subtract two nearly-equal squares; clamp the rounding
    return jnp.maximum(d, 0.0)


#: content-keyed results of mesh_is_nondegenerate: repeated facade calls on
#: an unchanged mesh (registration loops) must not pay the O(B*F) f64
#: gather per call — digest the raw bytes instead (blake2b, not crc: the
#: flag gates kernel correctness, see mesh_is_nondegenerate).  Bounded FIFO.
_NONDEGEN_CACHE = {}
_NONDEGEN_CACHE_MAX = 64


def mesh_is_nondegenerate(v, f, margin=100.0):
    """Host-side staging check backing ``assume_nondegenerate``: True when
    EVERY face clears the fast tile's relative area cut
    (``n2 > 1e-10 * ab2 * ac2``, fast_tile_rows) with ``margin`` to spare —
    the margin absorbs the f32 centering/rounding between this f64 check
    and the planes the kernel actually sees.

    ``v`` may carry leading batch axes ([..., V, 3]); the answer covers
    every mesh in the batch.  Meant for the numpy-boundary staging points
    (facade dispatch, benchmark setup) where the flag can be asserted
    from data rather than assumed.  Results are cached by a blake2b
    content digest — the flag is correctness-bearing (it selects a kernel
    that is wrong on degenerate data), so a 32-bit crc's collision odds
    were too loose (advisor round-4); the 128-bit digest costs the same
    O(bytes) pass and makes collisions effectively impossible.

    ``MESH_TPU_SAFE_TILES=1`` makes this always return False — the
    escape hatch that pins every facade to the safe tile variants
    (degenerate-tail closest point, segment tri-tri) should a fast tile
    misbehave on a new backend, mirroring MESH_TPU_FORCE_XLA one level
    down.
    """
    import hashlib

    from ..utils.dispatch import safe_tiles

    if safe_tiles():
        return False

    v = np.ascontiguousarray(np.asarray(v))
    f = np.ascontiguousarray(np.asarray(f))
    digest = hashlib.blake2b(digest_size=16)
    digest.update(v.tobytes())
    digest.update(b"\0")
    digest.update(f.tobytes())
    key = (v.shape, f.shape, float(margin), str(v.dtype), str(f.dtype),
           digest.digest())
    hit = _NONDEGEN_CACHE.get(key)
    if hit is not None:
        return hit
    v64 = v.astype(np.float64)
    tri = v64[..., f, :]
    ab = tri[..., 1, :] - tri[..., 0, :]
    ac = tri[..., 2, :] - tri[..., 0, :]
    n = np.cross(ab, ac)
    n2 = np.sum(n * n, axis=-1)
    ab2 = np.sum(ab * ab, axis=-1)
    ac2 = np.sum(ac * ac, axis=-1)
    result = bool(np.all(n2 > margin * 1e-10 * ab2 * ac2))
    if len(_NONDEGEN_CACHE) >= _NONDEGEN_CACHE_MAX:
        _NONDEGEN_CACHE.pop(next(iter(_NONDEGEN_CACHE)))
    _NONDEGEN_CACHE[key] = result
    return result


def make_argmin_kernel(cost_tile):
    """Running min/argmin kernel scaffold shared by the brute-force and
    normal-weighted kernels.

    ``cost_tile(*planes) -> (TQ, TF)`` computes the per-pair cost from the
    input plane blocks.  Invariants the scaffold encodes once: grid dim 1
    (faces) is innermost so the VMEM accumulators survive across j; the
    strict ``<`` merge keeps the lowest face index on exact ties (matching
    the XLA paths' argmin); accumulators init to ``_BIG`` at j == 0 and the
    winner index is written at the last face tile.
    """

    def kernel(*refs):
        ins = refs[:-3]
        out_i, acc_d, acc_i = refs[-3:]
        j = pl.program_id(1)
        n_j = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            acc_d[:] = jnp.full_like(acc_d, _BIG)
            acc_i[:] = jnp.zeros_like(acc_i)

        cost = cost_tile(*[r[:] for r in ins])           # (TQ, TF)
        tf = cost.shape[1]
        tile_min = jnp.min(cost, axis=1, keepdims=True)  # (TQ, 1)
        tile_arg = jnp.argmin(cost, axis=1).astype(jnp.int32)[:, None] + j * tf
        better = tile_min < acc_d[:]
        acc_d[:] = jnp.where(better, tile_min, acc_d[:])
        acc_i[:] = jnp.where(better, tile_arg, acc_i[:])

        @pl.when(j == n_j - 1)
        def _write():
            out_i[:] = acc_i[:]

    return kernel


def make_fused_argmin_kernel(cost_tile):
    """Experimental single-pass fused min+argmin scaffold (VERDICT r4 #4:
    doc/perf.md names the two-pass tile reduction as the next lever after
    the degenerate tail).

    Instead of a min pass plus an argmin pass over each (TQ, TF) tile,
    the cost's f32 bit pattern (monotonic as int32 for the tile's
    non-negative distances) is masked down by log2(TF) low mantissa bits
    and OR-ed with the within-tile column index, and ONE int32 min
    reduction yields both the (quantized) best distance and the winning
    column; the face-tile index rides in a second (TQ, 1) accumulator
    updated per tile, not per pair.

    Accuracy contract: faces whose distances agree to within 2^-(23 -
    log2(TF)) RELATIVE (~2.4e-4 for TF=2048) form a tie group and the
    lowest packed key — not necessarily the lowest face index — wins; the
    epilogue still reports the winner's exact distance/point.  That tie
    radius is far wider than the exact scaffold's, so this kernel is
    opt-in (``reduction="fused"``) and only becomes a default if the
    on-chip sweep (tile_sweep.py fused arm) shows a win worth the
    documented tie semantics.  NaN costs pack to large positive keys and
    can never win (unlike jnp.min, which would propagate them).

    Edge case (ADVICE r5, low #4): when NO pair in the whole scan beats
    the init — every cost is +inf/NaN (e.g. all faces are the _BIG
    sentinel padding, or every cost NaN-packed) — ``acc_p`` keeps its
    int32-max init, whose low ``log2(TF)`` bits are all ones, and
    ``acc_j`` keeps 0; the unpack ``acc_j * tf + (acc_p & (tf - 1))``
    therefore reports index ``tf - 1`` (last column of the FIRST face
    tile), where the exact scaffold's untouched ``acc_i`` init reports 0.
    Both picks are equally arbitrary — no finite winner exists — and the
    epilogue's exact recompute still reports the true distance of
    whichever face is named, but comparisons against the exact scaffold
    must not assume the indices agree in this (never-valid-input) case.
    """

    def kernel(*refs):
        ins = refs[:-3]
        out_i, acc_p, acc_j = refs[-3:]
        j = pl.program_id(1)
        n_j = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            acc_p[:] = jnp.full_like(acc_p, jnp.iinfo(jnp.int32).max)
            acc_j[:] = jnp.zeros_like(acc_j)

        cost = cost_tile(*[r[:] for r in ins])           # (TQ, TF)
        tf = cost.shape[1]
        assert tf & (tf - 1) == 0, "fused reduction wants power-of-two TF"
        bits = jax.lax.bitcast_convert_type(cost, jnp.int32)
        col = jax.lax.broadcasted_iota(jnp.int32, cost.shape, 1)
        packed = (bits & jnp.int32(~(tf - 1))) | col
        tile_min = jnp.min(packed, axis=1, keepdims=True)
        better = tile_min < acc_p[:]
        acc_p[:] = jnp.where(better, tile_min, acc_p[:])
        acc_j[:] = jnp.where(better, j, acc_j[:])

        @pl.when(j == n_j - 1)
        def _write():
            out_i[:] = acc_j[:] * tf + (acc_p[:] & (tf - 1))

    return kernel


def _pad_cols(x, multiple, fill):
    pad = (-x.shape[-1]) % multiple
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)), constant_values=fill)
    return x


#: number of per-face planes `fast_tile_rows` produces
N_FACE_ROWS = 19


def fast_tile_rows(tri):
    """The 19 per-face quantities `_sqdist_tile_fast` consumes, hoisted
    out of the O(Q*F) scan, in its exact face-parameter order: corner a
    and edge vectors ab/ac, the unnormalized normal n, the edge dot
    products ab2/ac2/abac, and the reciprocals
    inv_ab2/inv_ac2/inv_bc2/inv_n2.  Zeroed reciprocals route degenerate
    faces to their vertex/edge regions with finite distances.

    ``tri`` is ``[..., F, 3 corners, 3 xyz]``; returns a list of 19
    ``[..., F]`` arrays.  Single source of truth for every kernel feeding
    the fast tile (brute-force, normal-weighted, culled)."""
    a = tri[..., 0, :]
    ab = tri[..., 1, :] - a
    ac = tri[..., 2, :] - a
    bc = tri[..., 2, :] - tri[..., 1, :]
    n = jnp.cross(ab, ac)

    def _safe_recip(x):
        # below-threshold (near-degenerate) faces get 0, which routes them
        # to the vertex/edge fallbacks in the tile instead of a clamped
        # reciprocal that would under-report their distance
        return jnp.where(x < 1e-30, 0.0, 1.0 / x)

    ab2 = jnp.sum(ab * ab, axis=-1)
    ac2 = jnp.sum(ac * ac, axis=-1)
    n2 = jnp.sum(n * n, axis=-1)
    rows = [
        a[..., 0], a[..., 1], a[..., 2],
        ab[..., 0], ab[..., 1], ab[..., 2],
        ac[..., 0], ac[..., 1], ac[..., 2],
        n[..., 0], n[..., 1], n[..., 2],
        ab2, ac2, jnp.sum(ab * ac, axis=-1),
        _safe_recip(ab2),
        _safe_recip(ac2),
        _safe_recip(jnp.sum(bc * bc, axis=-1)),
        # the degeneracy cut must be RELATIVE: a collinear face at unit
        # scale has n2 ~ rounding noise (1e-14), far above any absolute
        # epsilon, and its huge reciprocal would turn the interior term
        # into garbage.  Matches point_triangle's degenerate test.
        jnp.where(n2 <= 1e-10 * ab2 * ac2, 0.0, _safe_recip(n2)),
    ]
    assert len(rows) == N_FACE_ROWS
    return rows


def _face_rows_fast(tri, tile_f):
    """`fast_tile_rows` as padded (1, F_pad) planes for the 2D-grid kernels.

    Padding: the a-planes get _BIG so a padded face's vertex-region
    distance overflows to +inf (its edge vectors are zero, so every
    Ericson term is finite or +inf, never NaN) and can never win the
    argmin; every other plane pads with zero."""
    face_rows = fast_tile_rows(tri)
    fills = [_BIG] * 3 + [0.0] * (len(face_rows) - 3)
    return [
        _pad_cols(x[None, :], tile_f, fill)
        for x, fill in zip(face_rows, fills, strict=True)
    ]


def _pad_rows(x, multiple, fill):
    pad = (-x.shape[0]) % multiple
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)), constant_values=fill)
    return x


# ---------------------------------------------------------------------------
# Sliver-safe tile (VERDICT r4 #7).  The fast tile's long-edge failure
# mode (tests/test_sliver_numerics.py) is CANCELLATION at the ap2 scale:
# both its derived corner terms (bp2 = ap2 - 2*d1 + ab2) and every
# closed-form edge distance (ap2 - t*(2*d1 - t*ab2)) subtract nearly
# equal ~|ap|^2-sized quantities, so the absolute error is ~ulp(ap2) =
# eps * length^2 regardless of how small the true distance is.  This
# tile restores reference-grade conditioning at f32 by
#
# - loading the b/c corner planes and computing every dot product and
#   squared corner distance from its own corner difference, and
# - computing each clamped edge distance from the RESIDUAL VECTOR
#   (p - foot point) formed componentwise first and squared second: the
#   component subtractions cancel benignly (error ~ eps * |t*edge| per
#   component), so the squared distance's error is ~ eps * length *
#   |residual| + (eps * length)^2 instead of eps * length^2.
#
# Same plane count as the fast tile (19: three corners + unnormalized
# normal + the seven shared scalars; edges are rebuilt on the cheap
# (1, TF) broadcast axis), ~+55 VPU ops/pair for the direct dots and the
# three residual-vector edge distances (which double as the degenerate
# tail, so the tail costs nothing extra here).  The on-chip price is
# measured by tile_sweep's sliver_safe arm; `MESH_TPU_SAFE_TILES=1` pins
# facades to this tile.


def _sqdist_tile_safe(px, py, pz,
                      ax, ay, az, bx, by, bz, cx, cy, cz, nx, ny, nz,
                      ab2, ac2, abac, inv_ab2, inv_ac2, inv_bc2, inv_n2,
                      degenerate_tail=True):
    """Direct-corner, residual-vector Ericson squared distance on a
    (TQ, TF) tile — the sliver-safe counterpart of _sqdist_tile_fast
    (same contract; ``degenerate_tail=False`` drops only the final
    override select, the edge distances themselves are shared)."""
    # per-face edges from the corner planes: (1, TF) work, amortized by TQ
    abx, aby, abz = bx - ax, by - ay, bz - az
    acx, acy, acz = cx - ax, cy - ay, cz - az
    bcx, bcy, bcz = cx - bx, cy - by, cz - bz
    apx, apy, apz = px - ax, py - ay, pz - az
    bpx, bpy, bpz = px - bx, py - by, pz - bz
    cpx, cpy, cpz = px - cx, py - cy, pz - cz
    d1 = abx * apx + aby * apy + abz * apz
    d2 = acx * apx + acy * apy + acz * apz
    d3 = abx * bpx + aby * bpy + abz * bpz
    d4 = acx * bpx + acy * bpy + acz * bpz
    d5 = abx * cpx + aby * cpy + abz * cpz
    d6 = acx * cpx + acy * cpy + acz * cpz
    ap2 = apx * apx + apy * apy + apz * apz
    bp2 = bpx * bpx + bpy * bpy + bpz * bpz
    cp2 = cpx * cpx + cpy * cpy + cpz * cpz
    n_ap = nx * apx + ny * apy + nz * apz

    # clamped-foot residual-vector edge distances; inside an edge's
    # Voronoi region the clamp is the identity, so these serve the edge
    # regions AND the degenerate tail
    def seg_sqdist(t, ox_, oy_, oz_, ex_, ey_, ez_):
        rx = ox_ - t * ex_
        ry = oy_ - t * ey_
        rz = oz_ - t * ez_
        return rx * rx + ry * ry + rz * rz

    e_ab = seg_sqdist(jnp.clip(d1 * inv_ab2, 0.0, 1.0),
                      apx, apy, apz, abx, aby, abz)
    e_ca = seg_sqdist(jnp.clip(d2 * inv_ac2, 0.0, 1.0),
                      apx, apy, apz, acx, acy, acz)
    d_bc = d4 - d3
    e_bc = seg_sqdist(jnp.clip(d_bc * inv_bc2, 0.0, 1.0),
                      bpx, bpy, bpz, bcx, bcy, bcz)

    # same region predicates as _region_select, residual-form distances
    va = d3 * d6 - d5 * d4
    vb = d5 * d2 - d1 * d6
    vc = d1 * d4 - d3 * d2
    d = n_ap * n_ap * inv_n2
    d = jnp.where((va <= 0) & (d_bc >= 0) & (d5 - d6 >= 0), e_bc, d)
    d = jnp.where((vb <= 0) & (d2 >= 0) & (d6 <= 0), e_ca, d)
    d = jnp.where((vc <= 0) & (d1 >= 0) & (d3 <= 0), e_ab, d)
    d = jnp.where((d6 >= 0) & (d5 <= d6), cp2, d)
    d = jnp.where((d3 >= 0) & (d4 <= d3), bp2, d)
    d = jnp.where((d1 <= 0) & (d2 <= 0), ap2, d)
    if degenerate_tail:
        d = jnp.where(
            inv_n2 > 0, d, jnp.minimum(e_ab, jnp.minimum(e_ca, e_bc))
        )
    return jnp.maximum(d, 0.0)


#: number of per-face planes `safe_tile_rows` produces (same as fast)
N_FACE_ROWS_SAFE = 19


def safe_tile_rows(tri):
    """The 19 per-face quantities `_sqdist_tile_safe` consumes, in its
    face-parameter order: the three corners, the unnormalized normal, and
    the same seven hoisted scalars as `fast_tile_rows` (rows 12-18 are
    shared with it)."""
    a = tri[..., 0, :]
    b = tri[..., 1, :]
    c = tri[..., 2, :]
    n = jnp.cross(b - a, c - a)
    rows = [
        a[..., 0], a[..., 1], a[..., 2],
        b[..., 0], b[..., 1], b[..., 2],
        c[..., 0], c[..., 1], c[..., 2],
        n[..., 0], n[..., 1], n[..., 2],
        *fast_tile_rows(tri)[12:],
    ]
    assert len(rows) == N_FACE_ROWS_SAFE
    return rows


def _face_rows_safe(tri, tile_f):
    """`safe_tile_rows` as padded (1, F_pad) planes.  Padding: every
    corner plane gets _BIG, so a padded face's corners coincide (edges and
    all dot products exactly zero — no inf*0 NaNs) while ap2/bp2/cp2
    overflow to +inf; the region chain always lands on one of those, so a
    padded face can never win the argmin."""
    face_rows = safe_tile_rows(tri)
    fills = [_BIG] * 9 + [0.0] * (len(face_rows) - 9)
    return [
        _pad_cols(x[None, :], tile_f, fill)
        for x, fill in zip(face_rows, fills, strict=True)
    ]


def _vertex_sqdist_tile(px, py, pz, vx, vy, vz):
    """Point-to-vertex squared distance on a (TQ, TV) tile — the cost of
    the nearest-vertex scan (reference ClosestPointTree, search.py:52-65)."""
    dx, dy, dz = px - vx, py - vy, pz - vz
    return dx * dx + dy * dy + dz * dz


_vertex_kernel = make_argmin_kernel(_vertex_sqdist_tile)


@partial(jax.jit, static_argnames=("tile_q", "tile_v", "interpret"))
def nearest_vertices_pallas(v, points, tile_q=256, tile_v=2048,
                            interpret=False):
    """Pallas path of query.closest_vertices_with_distance: nearest mesh
    vertex per query -> (index [Q] int32, distance [Q]).  Same VMEM
    argmin scaffold as the closest-point scan with the trivial
    point-point cost; padded vertices sit at _BIG and can never win."""
    v = jnp.asarray(v, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    center = jnp.mean(v, axis=0)
    vc_ = v - center
    pts = points - center
    n_q = pts.shape[0]

    p_cols = [_pad_rows(pts[:, k:k + 1], tile_q, 0.0) for k in range(3)]
    v_rows = [
        _pad_cols(vc_[:, k][None, :], tile_v, _BIG) for k in range(3)
    ]
    q_pad = p_cols[0].shape[0]
    v_pad = v_rows[0].shape[1]
    grid = (q_pad // tile_q, v_pad // tile_v)

    out_i = pl.pallas_call(
        _vertex_kernel,
        grid=grid,
        in_specs=[
            *[pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)) for _ in range(3)],
            *[pl.BlockSpec((1, tile_v), lambda i, j: (0, j)) for _ in range(3)],
        ],
        out_specs=pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.int32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(*p_cols, *v_rows)

    best = out_i[:n_q, 0]
    dist = jnp.linalg.norm(pts - vc_[best], axis=-1)
    return best, dist


def _center_inputs(v, f, points):
    """Shared query prologue: f32 cast, centering (the f32-conditioning
    step every kernel relies on), face corner gather."""
    v = jnp.asarray(v, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    center = jnp.mean(v, axis=0)
    vc_ = v - center
    pts = points - center
    return vc_, pts, center, vc_[jnp.asarray(f)]


def _winner_epilogue(best, tri, pts, center):
    """Shared epilogue: exact recompute on the winning faces (also yields
    the CGAL part code) -> the closest_faces_and_points result dict."""
    a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]
    point, sqd, part = closest_point_on_triangle(
        pts, a[best], b[best], c[best]
    )
    return {
        "face": best,
        "part": part,
        "point": point + center,
        "sqdist": sqd,
    }


#: (variant, nondegen, reduction) -> built kernel; kernels are tiny
#: closures, built once per combination
_CLOSEST_KERNELS = {}


def _closest_kernel(tile_variant, assume_nondegenerate, reduction):
    key = (tile_variant, bool(assume_nondegenerate), reduction)
    kernel = _CLOSEST_KERNELS.get(key)
    if kernel is None:
        tile = {"fast": _sqdist_tile_fast, "safe": _sqdist_tile_safe}[
            tile_variant]
        cost = (partial(tile, degenerate_tail=False)
                if assume_nondegenerate else tile)
        make = {"exact": make_argmin_kernel,
                "fused": make_fused_argmin_kernel}[reduction]
        kernel = _CLOSEST_KERNELS[key] = make(cost)
    return kernel


@partial(jax.jit,
         static_argnames=("tile_q", "tile_f", "interpret",
                          "assume_nondegenerate", "tile_variant",
                          "reduction"))
def closest_point_pallas(v, f, points, tile_q=256, tile_f=2048,
                         interpret=False, assume_nondegenerate=False,
                         tile_variant="fast", reduction="exact"):
    """Pallas-accelerated closest_faces_and_points.

    Same contract as query.closest_faces_and_points: returns dict with
    ``face`` [Q] int32, ``part`` [Q] int32, ``point`` [Q, 3], ``sqdist`` [Q].

    ``assume_nondegenerate=True`` compiles the tile without the
    degenerate-face override (~25% fewer VPU ops) — bit-identical results
    when every face passes the relative area cut
    ``n2 > 1e-10 * ab2 * ac2`` (see _ericson_tail; the numpy facade
    verifies this at staging via ``mesh_is_nondegenerate``); with actually
    degenerate faces present the flag can misreport WHICH face is
    closest, never the reported point/distance for the face it picks.

    ``tile_variant="safe"`` selects the sliver-safe direct-corner tile
    (see _sqdist_tile_safe: no ap2-scale cancellation on long-edged
    slivers, ~+55 VPU ops/pair); ``MESH_TPU_SAFE_TILES=1`` makes the
    facades pick it.  ``reduction="fused"`` selects the experimental
    single-pass packed min+argmin (make_fused_argmin_kernel: wider
    documented tie radius, measured by the tile sweep's fused arm).
    """
    if tile_variant not in ("fast", "safe"):
        raise ValueError("tile_variant must be 'fast' or 'safe', got %r"
                         % (tile_variant,))
    if reduction not in ("exact", "fused"):
        raise ValueError("reduction must be 'exact' or 'fused', got %r"
                         % (reduction,))
    if reduction == "fused" and tile_f & (tile_f - 1):
        # the packed key masks the low log2(tile_f) bits; a non-power-of-
        # two tile would corrupt cost bits with the OR-ed column index
        raise ValueError(
            "reduction='fused' requires a power-of-two tile_f, got %d"
            % tile_f)
    vc_, pts, center, tri = _center_inputs(v, f, points)
    n_q = pts.shape[0]

    p_cols = [_pad_rows(pts[:, k:k + 1], tile_q, 0.0) for k in range(3)]
    rows_builder = (_face_rows_fast if tile_variant == "fast"
                    else _face_rows_safe)
    face_rows = rows_builder(tri, tile_f)
    q_pad = p_cols[0].shape[0]
    f_pad = face_rows[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)
    acc_d_dtype = jnp.float32 if reduction == "exact" else jnp.int32

    out_i = pl.pallas_call(
        _closest_kernel(tile_variant, assume_nondegenerate, reduction),
        grid=grid,
        in_specs=[
            *[pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)) for _ in range(3)],
            *[
                pl.BlockSpec((1, tile_f), lambda i, j: (0, j))
                for _ in range(len(face_rows))
            ],
        ],
        out_specs=pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), acc_d_dtype),
            pltpu.VMEM((tile_q, 1), jnp.int32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(*p_cols, *face_rows)

    return _winner_epilogue(out_i[:n_q, 0], tri, pts, center)


# ---------------------------------------------------------------------------
# EXPERIMENTAL MXU-fed tile.  The fast tile's four query-dependent dot
# products (d1, d2, n.ap and the p.a term of ap2) are 20 of its ~65 VPU ops
# per pair; here one (TQ, 3) x (3, 4*TF) matmul produces all four on the
# MXU and the VPU keeps only the region logic (_ericson_tail).  Whether
# Mosaic overlaps the K=3 matmul with the VPU tail enough to win is an
# on-chip question (benchmarks/tile_sweep.py --mxu); parity with the
# production tile is pinned in tests either way.
#
# Numerics: ap2 = p2 - 2 p.a + a2 cancels like the documented corner-b/c
# derivation (absolute error ~ulp(|p|^2) after centering, vs ~ulp(ap2)
# direct) — argmin tie-flips only; the epilogue's exact recompute is
# unchanged.  The matmul runs at Precision.HIGHEST (3-pass f32).

#: per-face planes the MXU tile consumes alongside the G matrix
N_FACE_ROWS_MXU = 11


def _sqdist_tile_mxu(p, p2, g, a_ab, a_ac, a_n, a2,
                     ab2, ac2, abac, inv_ab2, inv_ac2, inv_bc2, inv_n2,
                     degenerate_tail=True):
    tf = a_ab.shape[1]
    pg = jax.lax.dot_general(
        p, g, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                   # (TQ, 4*TF)
    d1 = pg[:, :tf] - a_ab
    d2 = pg[:, tf:2 * tf] - a_ac
    n_ap = pg[:, 2 * tf:3 * tf] - a_n
    pa = pg[:, 3 * tf:]
    ap2 = jnp.maximum(p2 - (pa + pa) + a2, 0.0)
    return _ericson_tail(d1, d2, ap2, n_ap, ab2, ac2, abac,
                         inv_ab2, inv_ac2, inv_bc2, inv_n2,
                         degenerate_tail=degenerate_tail)


_kernel_mxu = make_argmin_kernel(_sqdist_tile_mxu)
_kernel_mxu_nodegen = make_argmin_kernel(
    partial(_sqdist_tile_mxu, degenerate_tail=False))


def _mxu_plane_rows(tri, tile_f):
    """The 11 padded (1, F_pad) per-face planes the MXU tile consumes
    alongside the dot-product operands: the corner-a projections
    a.ab/a.ac/a.n, a2 (padded _BIG so padded faces never win), and the 7
    shared Ericson constants (fast_tile_rows rows 12-18)."""
    a = tri[:, 0]
    ab = tri[:, 1] - a
    ac = tri[:, 2] - a
    n = jnp.cross(ab, ac)

    def pad_f(x, fill=0.0):                 # [F] -> (1, F_pad)
        return _pad_cols(x[None, :], tile_f, fill)

    planes = [
        pad_f(jnp.sum(a * ab, axis=-1)),
        pad_f(jnp.sum(a * ac, axis=-1)),
        pad_f(jnp.sum(a * n, axis=-1)),
        pad_f(jnp.sum(a * a, axis=-1), _BIG),
    ]
    # reuse the production builder for the 7 shared constants (rows 12-18)
    shared = fast_tile_rows(tri)[12:]
    planes += [pad_f(x) for x in shared]
    assert len(planes) == N_FACE_ROWS_MXU
    return planes


def _mxu_face_inputs(tri, tile_f):
    """(G [3, T*4*tile_f], 11 padded (1, F_pad) planes) for the MXU tile.

    G is laid out in per-tile groups — tile j's block columns are
    [ab_j | ac_j | n_j | a_j], each tile_f wide — so the plain
    (0, j)-indexed BlockSpec hands the kernel all four dot operands of
    its face tile.  Padded faces: zero G columns and a2 = _BIG, so their
    ap2 (hence every region distance) overflows and never wins."""
    a = tri[:, 0]
    ab = tri[:, 1] - a
    ac = tri[:, 2] - a
    n = jnp.cross(ab, ac)

    planes = _mxu_plane_rows(tri, tile_f)
    f_pad = planes[0].shape[1]

    def grouped(x):                          # [F, 3] -> [T, tile_f, 3]
        x = jnp.pad(x, ((0, f_pad - x.shape[0]), (0, 0)))
        return x.reshape(-1, tile_f, 3)

    g = jnp.concatenate(
        [grouped(ab), grouped(ac), grouped(n), grouped(a)], axis=1
    )                                        # [T, 4*tile_f, 3]
    g = jnp.moveaxis(g, -1, 0).reshape(3, -1)  # (3, T*4*tile_f)
    return g, planes


def _mxu_reach_row(tri, tile_f):
    """Per-face corner-a reach as a padded (1, F_pad) plane: the farthest
    triangle point from corner a is a vertex (|x - a| is convex), so
    ``r = sqrt(max(ab2, ac2))`` covers the whole face.  The bf16 screen
    uses it to turn the corner-distance bound into a face-distance bound
    (``d_tri >= |p - a| - r``).  Padded faces get r = 0 (their a2 = _BIG
    already keeps them out of every bound)."""
    ab = tri[:, 1] - tri[:, 0]
    ac = tri[:, 2] - tri[:, 0]
    r2 = jnp.maximum(jnp.sum(ab * ab, axis=-1), jnp.sum(ac * ac, axis=-1))
    return _pad_cols(jnp.sqrt(r2)[None, :], tile_f, 0.0)


#: certified bf16 envelope for the screen's corner-distance bound
#: (doc/acceleration.md carries the derivation).  The screen computes
#: ``ap2~ = p2 - 2*(p.a)_bf16 + a2`` where ONLY the matmul operands are
#: rounded to bf16 (8 mantissa bits, relative ulp 2^-8; p2/a2 stay f32):
#:   |(p.a)_bf16 - p.a| <= ((1+2^-8)^2 * (1+2^-24)^3 - 1) * sum|p_k||a_k|
#:                      <= 1.01 * 2^-7 * |p| * |a|          (Cauchy-Schwarz)
#: so |ap2~ - ap2| <= 2.02 * 2^-7 * |p||a| <= 1.01 * 2^-7 * (p2 + a2)
#: (AM-GM).  2^-6 * (p2 + a2) leaves ~2x headroom for the f32 rounding
#: of the three-term combine and any accumulation-order slack.
MXU_BF16_EPS = 2.0 ** -6


def _mxu_ap2_env(p, p2, ga, a2):
    """bf16 corner-distance core on a (TQ, TF) tile: the approximate
    squared corner distance ``ap2~`` (only the matmul operands rounded
    to bf16) and its certified error envelope ``E``."""
    pa = jax.lax.dot_general(
        p.astype(jnp.bfloat16), ga.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # (TQ, TF)
    ap2t = jnp.maximum(p2 - (pa + pa) + a2, 0.0)
    env = MXU_BF16_EPS * (p2 + a2)
    return ap2t, env


def _mxu_screen_tile(p, p2, ga, a2, reach=None, ub=None):
    """The bf16 first-pass quantities on a (TQ, TF) tile: the envelope-
    widened corner-distance bound.  With ``reach``/``ub`` supplied it
    returns the per-pair SURVIVOR mask (faces that can still beat the
    certified upper bound ``ub``); without them it returns the per-pair
    upper bound ``ap2~ + E`` whose running min certifies ``ub``."""
    ap2t, env = _mxu_ap2_env(p, p2, ga, a2)
    if ub is None:
        return ap2t + env
    # face f can hold a point within sqrt(ub) of p only if
    # |p - a_f| <= sqrt(ub) + r_f, i.e. ap2 <= ub + 2*sqrt(ub)*r + r^2;
    # ap2t - env is a certified lower bound on the true ap2
    su = jnp.sqrt(jnp.maximum(ub, 0.0))
    bound = ub + (su + su) * reach + reach * reach
    return ap2t - env <= bound


def _mxu_bound_kernel(p_ref, p2_ref, ga_ref, a2_ref, reach_ref,
                      out_ub, out_m, acc_ub):
    """bf16 first pass: per-query running min of the envelope-widened
    corner-distance upper bound — ``ub >= min_f d_tri^2`` certified —
    PLUS a per-(query, repair-tile) survivor certificate

        m[q, t] = min_{f in tile t} sqrt(max(ap2~ - E, 0)) - r_f

    so the repair pass's screen is the scalar test ``m <= sqrt(ub)``
    (algebraically the survivor predicate: ap2 <= (sqrt(ub) + r)^2) and
    never re-runs the bf16 matmul.  The block already holds ap2~ for
    every face, so the certificate costs one sqrt + a sub-tile min; the
    bound tile is a multiple of the repair tile, hence the reshape."""
    j = pl.program_id(1)
    n_j = pl.num_programs(1)
    n_sub = out_m.shape[1]           # repair tiles per bound tile

    @pl.when(j == 0)
    def _init():
        acc_ub[:] = jnp.full_like(acc_ub, _BIG)

    ap2t, env = _mxu_ap2_env(p_ref[:], p2_ref[:], ga_ref[:], a2_ref[:])
    tile_min = jnp.min(ap2t + env, axis=1, keepdims=True)
    acc_ub[:] = jnp.minimum(tile_min, acc_ub[:])

    m = jnp.sqrt(jnp.maximum(ap2t - env, 0.0)) - reach_ref[:]
    out_m[:] = jnp.min(
        m.reshape(m.shape[0], n_sub, m.shape[1] // n_sub), axis=2)

    @pl.when(j == n_j - 1)
    def _write():
        out_ub[:] = acc_ub[:]


def _make_mxu_repair_kernel(degenerate_tail):
    """f32 exact-repair scaffold: every face tile is screened against the
    first pass's certificates and the full f32 MXU cost runs ONLY on
    surviving tiles (``@pl.when``), so the expensive matmul + Ericson
    tail is skipped wherever the bf16 pass proved no face can win.  The
    screen itself is the scalar test ``m <= sqrt(ub)`` on pass-1 outputs
    — skipped tiles cost block loads and nothing else.  The per-query-
    tile survivor count lands in an SMEM output — the facade turns it
    into the repair series, so a screen that stops pruning (or starts
    over-pruning) is visible, never silent."""

    def kernel(p_ref, p2_ref, ub_ref, m_ref, g_ref, *refs):
        ins = refs[:N_FACE_ROWS_MXU]
        out_i, out_rep, acc_d, acc_i = refs[N_FACE_ROWS_MXU:]
        j = pl.program_id(1)
        n_j = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            acc_d[:] = jnp.full_like(acc_d, _BIG)
            acc_i[:] = jnp.zeros_like(acc_i)
            out_rep[0, 0] = jnp.int32(0)

        p = p_ref[:]
        p2 = p2_ref[:]
        g = g_ref[:]                                     # (3, 4*TF)
        tf = g.shape[1] // 4
        su = jnp.sqrt(jnp.maximum(ub_ref[:], 0.0))
        survives = jnp.any(m_ref[:] <= su)

        @pl.when(survives)
        def _repair():
            cost = _sqdist_tile_mxu(
                p, p2, g, *[r[:] for r in ins],
                degenerate_tail=degenerate_tail)         # (TQ, TF)
            tile_min = jnp.min(cost, axis=1, keepdims=True)
            tile_arg = jnp.argmin(cost, axis=1).astype(
                jnp.int32)[:, None] + j * tf
            better = tile_min < acc_d[:]
            acc_d[:] = jnp.where(better, tile_min, acc_d[:])
            acc_i[:] = jnp.where(better, tile_arg, acc_i[:])
            out_rep[0, 0] = out_rep[0, 0] + jnp.int32(1)

        @pl.when(j == n_j - 1)
        def _write():
            out_i[:] = acc_i[:]

    return kernel


_kernel_mxu_repair = _make_mxu_repair_kernel(True)
_kernel_mxu_repair_nodegen = _make_mxu_repair_kernel(False)


#: digest-keyed MXU face-input staging (the satellite fix: the G layout +
#: 11 planes were rebuilt from ``tri`` on every call).  Same bounded-FIFO
#: blake2b idiom as _NONDEGEN_CACHE; entries hold device arrays, so
#: repeated queries on a stored mesh skip the whole host prep.  Keyed by
#: topology digest + tile_f (the padding/grouping depends on the tile).
_MXU_FACE_CACHE = {}
_MXU_FACE_CACHE_MAX = 16


def _mxu_staged_inputs(v, f, tile_f):
    """(center, tri, g, planes, ga, reach) for the MXU kernels, cached by
    content digest.  Returns None for traced inputs (a jit caller gets
    the uncached traced build — correct, just not host-cached)."""
    import hashlib

    if isinstance(v, jax.core.Tracer) or isinstance(f, jax.core.Tracer):
        return None
    v_np = np.ascontiguousarray(np.asarray(v))
    f_np = np.ascontiguousarray(np.asarray(f))
    digest = hashlib.blake2b(digest_size=16)
    digest.update(v_np.tobytes())
    digest.update(b"\0")
    digest.update(f_np.tobytes())
    key = (v_np.shape, f_np.shape, int(tile_f), str(v_np.dtype),
           str(f_np.dtype), digest.digest())
    hit = _MXU_FACE_CACHE.get(key)
    if hit is not None:
        return hit
    v32 = jnp.asarray(v_np, jnp.float32)
    center = jnp.mean(v32, axis=0)
    tri = (v32 - center)[jnp.asarray(f_np)]
    g, planes = _mxu_face_inputs(tri, tile_f)
    f_pad = planes[0].shape[1]
    ga = _pad_cols(jnp.transpose(tri[:, 0]), f_pad, 0.0)   # (3, F_pad)
    reach = _mxu_reach_row(tri, tile_f)
    staged = (center, tri, g, tuple(planes), ga, reach)
    if len(_MXU_FACE_CACHE) >= _MXU_FACE_CACHE_MAX:
        _MXU_FACE_CACHE.pop(next(iter(_MXU_FACE_CACHE)))
    _MXU_FACE_CACHE[key] = staged
    return staged


@partial(jax.jit, static_argnames=("tile_q", "tile_f", "interpret",
                                   "assume_nondegenerate"))
def _mxu_dense_staged(g, planes, tri, center, points, tile_q, tile_f,
                      interpret, assume_nondegenerate):
    """Jitted body of closest_point_pallas_mxu over pre-staged face
    inputs (cache hit: only the query prologue re-traces work)."""
    pts = jnp.asarray(points, jnp.float32) - center
    n_q = pts.shape[0]
    p = _pad_rows(pts, tile_q, 0.0)                      # (Qp, 3)
    p2 = jnp.sum(p * p, axis=-1, keepdims=True)          # (Qp, 1)
    q_pad = p.shape[0]
    f_pad = planes[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)

    out_i = pl.pallas_call(
        _kernel_mxu_nodegen if assume_nondegenerate else _kernel_mxu,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_q, 3), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((3, 4 * tile_f), lambda i, j: (0, j)),
            *[
                pl.BlockSpec((1, tile_f), lambda i, j: (0, j))
                for _ in range(N_FACE_ROWS_MXU)
            ],
        ],
        out_specs=pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.int32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(p, p2, g, *planes)

    return _winner_epilogue(out_i[:n_q, 0], tri, pts, center)


def closest_point_pallas_mxu(v, f, points, tile_q=256, tile_f=2048,
                             interpret=False, assume_nondegenerate=False):
    """MXU-fed closest_faces_and_points; same contract (and
    ``assume_nondegenerate`` semantics) as closest_point_pallas.

    The face-side staging (G layout + 11 planes) depends only on the
    topology and tile_f, so it is cached by content digest
    (_MXU_FACE_CACHE) — repeated queries on an unchanged mesh skip the
    host prep entirely."""
    staged = _mxu_staged_inputs(v, f, tile_f)
    if staged is None:
        # traced inputs: fall back to the in-trace build
        vc_, pts, center, tri = _center_inputs(v, f, points)
        g, planes = _mxu_face_inputs(tri, tile_f)
        return _mxu_dense_staged(
            g, tuple(planes), tri, center, jnp.asarray(points),
            tile_q, tile_f, interpret, assume_nondegenerate)
    center, tri, g, planes, _ga, _reach = staged
    return _mxu_dense_staged(g, planes, tri, center, points,
                             tile_q, tile_f, interpret,
                             assume_nondegenerate)


@partial(jax.jit, static_argnames=("tile_q", "tile_f", "interpret",
                                   "assume_nondegenerate"))
def _mxu_repair_staged(g, planes, ga, reach, tri, center, points, tile_q,
                       tile_f, interpret, assume_nondegenerate):
    """Jitted bf16-first-pass + f32-exact-repair body: pass 1 certifies a
    per-query upper bound on the squared distance (bf16 matmul, envelope-
    widened); pass 2 re-screens each face tile against it and runs the
    full f32 MXU cost only on survivors.  Returns (result dict, repaired
    tile count per query tile)."""
    pts = jnp.asarray(points, jnp.float32) - center
    n_q = pts.shape[0]
    p = _pad_rows(pts, tile_q, 0.0)
    p2 = jnp.sum(p * p, axis=-1, keepdims=True)
    q_pad = p.shape[0]
    f_pad = planes[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)
    a2 = planes[3]

    # the bound pass is one bf16 matmul + a handful of VPU ops per pair,
    # so its grid overhead dominates at the repair pass's tile width —
    # run it over wider face tiles (the largest tile_f multiple dividing
    # f_pad, capped at 4x) with the same width-agnostic kernel
    bound_tf = max(m * tile_f for m in (1, 2, 4)
                   if f_pad % (m * tile_f) == 0)
    n_sub = bound_tf // tile_f
    n_tiles = f_pad // tile_f

    ub, cert = pl.pallas_call(
        _mxu_bound_kernel,
        grid=(q_pad // tile_q, f_pad // bound_tf),
        in_specs=[
            pl.BlockSpec((tile_q, 3), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((3, bound_tf), lambda i, j: (0, j)),
            pl.BlockSpec((1, bound_tf), lambda i, j: (0, j)),
            pl.BlockSpec((1, bound_tf), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_q, n_sub), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((q_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((q_pad, n_tiles), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((tile_q, 1), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(p, p2, ga, a2, reach)

    out_i, out_rep = pl.pallas_call(
        _kernel_mxu_repair_nodegen if assume_nondegenerate
        else _kernel_mxu_repair,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_q, 3), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_q, 1), lambda i, j: (i, j)),
            pl.BlockSpec((3, 4 * tile_f), lambda i, j: (0, j)),
            *[
                pl.BlockSpec((1, tile_f), lambda i, j: (0, j))
                for _ in range(N_FACE_ROWS_MXU)
            ],
        ],
        out_specs=[
            pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((q_pad // tile_q, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.int32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(p, p2, ub, cert, g, *planes)

    result = _winner_epilogue(out_i[:n_q, 0], tri, pts, center)
    return result, out_rep[:, 0]


def closest_point_pallas_mxu_repair(v, f, points, tile_q=256, tile_f=2048,
                                    interpret=False,
                                    assume_nondegenerate=False,
                                    with_stats=False):
    """bf16 first pass + f32 exact repair on the dense MXU form.

    Same contract as closest_point_pallas_mxu — the survivor set is
    conservative by construction (certified MXU_BF16_EPS envelope +
    corner reach bound), so the f32 repair's argmin equals the dense
    MXU kernel's.  ``with_stats=True`` additionally returns
    ``{"screened": total face tiles, "repaired": tiles that needed the
    f32 pass}`` for the repair series — missing repair evidence must
    never read as an improvement."""
    staged = _mxu_staged_inputs(v, f, tile_f)
    if staged is None:
        vc_, pts, center, tri = _center_inputs(v, f, points)
        g, planes = _mxu_face_inputs(tri, tile_f)
        f_pad = planes[0].shape[1]
        ga = _pad_cols(jnp.transpose(tri[:, 0]), f_pad, 0.0)
        reach = _mxu_reach_row(tri, tile_f)
        planes = tuple(planes)
    else:
        center, tri, g, planes, ga, reach = staged
    result, rep = _mxu_repair_staged(
        g, planes, ga, reach, tri, center, points, tile_q, tile_f,
        interpret, assume_nondegenerate)
    if not with_stats:
        return result
    n_tiles = planes[0].shape[1] // tile_f
    stats = {
        "screened": int(rep.shape[0]) * n_tiles,
        "repaired": int(np.sum(np.asarray(rep))),
    }
    return result, stats
