"""Pallas TPU kernel for brute-force closest-point-on-mesh.

The plain-JAX path (closest_point.py) materializes a (Q, F) distance matrix
(plus barycentric intermediates) in HBM per query tile — bandwidth-bound.
This kernel tiles (query x face) onto the VPU and keeps the running
min/argmin accumulators in VMEM, so HBM traffic is O(Q + F) instead of
O(Q * F): each (TQ, TF) tile computes the branch-free Ericson point-triangle
squared distance and folds it into per-query best-distance / best-face
registers.  The exact closest point and CGAL part code are recomputed on the
winning faces afterwards (O(Q) work) by the shared point_triangle module.

Inputs are passed as component planes — px/py/pz of shape (Q, 1) and
ax/.../cz of shape (1, F) — so every kernel operand broadcasts to the native
(TQ, TF) VPU tile shape with no in-kernel transposes.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .point_triangle import closest_point_on_triangle

_BIG = 1e30


def _sqdist_tile(px, py, pz, ax, ay, az, bx, by, bz, cx, cy, cz):
    """Branch-free Ericson closest-point squared distance on a (TQ, TF) tile.

    Component-plane version of point_triangle.closest_point_barycentric:
    identical region logic, but expressed on x/y/z planes so the whole tile
    stays in native 2D vector registers.
    """

    def dot(ux, uy, uz, vx, vy, vz):
        return ux * vx + uy * vy + uz * vz

    abx, aby, abz = bx - ax, by - ay, bz - az
    acx, acy, acz = cx - ax, cy - ay, cz - az
    apx, apy, apz = px - ax, py - ay, pz - az
    d1 = dot(abx, aby, abz, apx, apy, apz)
    d2 = dot(acx, acy, acz, apx, apy, apz)
    bpx, bpy, bpz = px - bx, py - by, pz - bz
    d3 = dot(abx, aby, abz, bpx, bpy, bpz)
    d4 = dot(acx, acy, acz, bpx, bpy, bpz)
    cpx, cpy, cpz = px - cx, py - cy, pz - cz
    d5 = dot(abx, aby, abz, cpx, cpy, cpz)
    d6 = dot(acx, acy, acz, cpx, cpy, cpz)

    va = d3 * d6 - d5 * d4
    vb = d5 * d2 - d1 * d6
    vc = d1 * d4 - d3 * d2

    def safe_div(n, d):
        return n / jnp.where(d == 0, 1.0, d)

    t_ab = safe_div(d1, d1 - d3)
    t_ca = safe_div(d2, d2 - d6)
    t_bc = safe_div(d4 - d3, (d4 - d3) + (d5 - d6))
    denom = safe_div(jnp.ones_like(va), va + vb + vc)
    v_in = vb * denom
    w_in = vc * denom

    # barycentric (b1, b2) per region, selected in priority order
    b1 = v_in
    b2 = w_in
    on_bc = (va <= 0) & (d4 - d3 >= 0) & (d5 - d6 >= 0)
    b1 = jnp.where(on_bc, 1.0 - t_bc, b1)
    b2 = jnp.where(on_bc, t_bc, b2)
    on_ca = (vb <= 0) & (d2 >= 0) & (d6 <= 0)
    b1 = jnp.where(on_ca, 0.0, b1)
    b2 = jnp.where(on_ca, t_ca, b2)
    on_ab = (vc <= 0) & (d1 >= 0) & (d3 <= 0)
    b1 = jnp.where(on_ab, t_ab, b1)
    b2 = jnp.where(on_ab, 0.0, b2)
    in_c = (d6 >= 0) & (d5 <= d6)
    b1 = jnp.where(in_c, 0.0, b1)
    b2 = jnp.where(in_c, 1.0, b2)
    in_b = (d3 >= 0) & (d4 <= d3)
    b1 = jnp.where(in_b, 1.0, b1)
    b2 = jnp.where(in_b, 0.0, b2)
    in_a = (d1 <= 0) & (d2 <= 0)
    b1 = jnp.where(in_a, 0.0, b1)
    b2 = jnp.where(in_a, 0.0, b2)

    qx = ax + b1 * abx + b2 * acx
    qy = ay + b1 * aby + b2 * acy
    qz = az + b1 * abz + b2 * acz
    dx, dy, dz = px - qx, py - qy, pz - qz
    return dx * dx + dy * dy + dz * dz


def make_argmin_kernel(cost_tile):
    """Running min/argmin kernel scaffold shared by the brute-force and
    normal-weighted kernels.

    ``cost_tile(*planes) -> (TQ, TF)`` computes the per-pair cost from the
    input plane blocks.  Invariants the scaffold encodes once: grid dim 1
    (faces) is innermost so the VMEM accumulators survive across j; the
    strict ``<`` merge keeps the lowest face index on exact ties (matching
    the XLA paths' argmin); accumulators init to ``_BIG`` at j == 0 and the
    winner index is written at the last face tile.
    """

    def kernel(*refs):
        ins = refs[:-3]
        out_i, acc_d, acc_i = refs[-3:]
        j = pl.program_id(1)
        n_j = pl.num_programs(1)

        @pl.when(j == 0)
        def _init():
            acc_d[:] = jnp.full_like(acc_d, _BIG)
            acc_i[:] = jnp.zeros_like(acc_i)

        cost = cost_tile(*[r[:] for r in ins])           # (TQ, TF)
        tf = cost.shape[1]
        tile_min = jnp.min(cost, axis=1, keepdims=True)  # (TQ, 1)
        tile_arg = jnp.argmin(cost, axis=1).astype(jnp.int32)[:, None] + j * tf
        better = tile_min < acc_d[:]
        acc_d[:] = jnp.where(better, tile_min, acc_d[:])
        acc_i[:] = jnp.where(better, tile_arg, acc_i[:])

        @pl.when(j == n_j - 1)
        def _write():
            out_i[:] = acc_i[:]

    return kernel


_kernel = make_argmin_kernel(_sqdist_tile)


def _pad_cols(x, multiple, fill):
    pad = (-x.shape[-1]) % multiple
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)), constant_values=fill)
    return x


def _pad_rows(x, multiple, fill):
    pad = (-x.shape[0]) % multiple
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)), constant_values=fill)
    return x


@partial(jax.jit, static_argnames=("tile_q", "tile_f", "interpret"))
def closest_point_pallas(v, f, points, tile_q=256, tile_f=2048, interpret=False):
    """Pallas-accelerated closest_faces_and_points.

    Same contract as query.closest_faces_and_points: returns dict with
    ``face`` [Q] int32, ``part`` [Q] int32, ``point`` [Q, 3], ``sqdist`` [Q].
    """
    v = jnp.asarray(v, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    center = jnp.mean(v, axis=0)
    vc_ = v - center
    pts = points - center

    tri = vc_[f]  # (F, 3, 3)
    n_q = pts.shape[0]

    p_cols = [_pad_rows(pts[:, k:k + 1], tile_q, 0.0) for k in range(3)]
    tri_rows = [
        _pad_cols(tri[:, corner, k][None, :], tile_f, _BIG)
        for corner in range(3)
        for k in range(3)
    ]  # ax, ay, az, bx, ..., cz each (1, F_pad)
    q_pad = p_cols[0].shape[0]
    f_pad = tri_rows[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)

    out_i = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            *[pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)) for _ in range(3)],
            *[pl.BlockSpec((1, tile_f), lambda i, j: (0, j)) for _ in range(9)],
        ],
        out_specs=pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.int32),
        ],
        interpret=interpret,
    )(*p_cols, *tri_rows)

    best = out_i[:n_q, 0]
    # exact recompute on the winning faces (also yields the CGAL part code)
    a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]
    point, sqd, part = closest_point_on_triangle(
        pts, a[best], b[best], c[best]
    )
    return {
        "face": best,
        "part": part,
        "point": point + center,
        "sqdist": sqd,
    }
