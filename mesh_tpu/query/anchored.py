"""Vertex-anchored candidate tables: fast exact closest-point for scan-scale
query counts.

The reference answers scan-registration queries by descending a CGAL AABB
tree per point (mesh/src/spatialsearchmodule.cpp:129-218) — O(log F) per
query but recursive and pointer-chasing, the opposite of what XLA wants.
This module gets the same effect with fixed shapes and gathers only:

  setup (per mesh, jit, ~tens of ms — the analog of the reference's
  ``aabbtree_compute`` tree build):
    for every vertex ``vi`` rank all faces by the conservative bound
        lbv(vi, f) = |vi - centroid_f| - bounding_radius_f  <=  dist(vi, f)
    and store the K smallest as ``table[vi]`` plus the (K+1)-th value as
    ``safe[vi]`` — no face outside the table can be closer to ``vi`` than
    ``safe[vi]``.

  query (jit):
    1. anchor: a near-nearest vertex ``vi`` per query via one (Q, 3) x
       (3, V) matmul (MXU) + row argmin; ``dhat = |q - v_vi|``.
    2. exact branch-free Ericson test on the K table faces only.
    3. certificate: the true closest point p* satisfies |q - p*| <= dhat,
       so any face containing p* has dist(vi, f) <= |vi - p*| <= 2*dhat.
       If ``2*dhat < safe[vi]`` every such face is in the table and the
       answer is provably the global optimum (``tight``).  The anchor does
       NOT need to be the true nearest vertex for this to hold.

  ``closest_point_anchored_auto`` re-runs the rare non-tight queries through
  the exact brute-force path, so results are always exact while per-query
  work drops from O(F) to O(K).

Numerics note: the anchor argmin uses the matmul expansion of |q - v|^2,
whose f32 rounding can mis-rank near-tied vertices — harmless, since the
certificate only uses the recomputed true distance to the chosen anchor.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.dispatch import pallas_default

from .point_triangle import closest_point_on_triangle

_CERT_SLACK_REL = 1e-5  # slack per unit scene scale, keeps the cert conservative


@partial(jax.jit, static_argnames=("k", "vchunk"))
def build_anchor_tables(v, f, k=128, vchunk=512):
    """Per-vertex K-nearest-face tables by conservative lower bound.

    :returns: ``(table, safe)`` — ``table`` [V, k] int32 face ids sorted by
        increasing bound; ``safe`` [V] f32, the (k+1)-th smallest bound
        (``+inf`` when k >= F: the table is exhaustive).
    """
    v = jnp.asarray(v, jnp.float32)
    f = jnp.asarray(f, jnp.int32)
    n_v, n_f = v.shape[0], f.shape[0]
    k = min(k, n_f)

    # the bounds are translation-invariant; centering matches the query-side
    # conditioning so f32 rounding in `safe` stays scene-relative
    v = v - jnp.mean(v, axis=0)
    tri = v[f]
    cen = jnp.mean(tri, axis=1)
    rad = jnp.sqrt(jnp.max(jnp.sum((tri - cen[:, None]) ** 2, axis=-1), axis=1))

    def chunk_tables(vc):
        # iterative min-extraction: k+1 passes over [C, F] (no lax.top_k —
        # measured ~50x slower than this on TPU at these shapes)
        d = jnp.sqrt(jnp.sum((vc[:, None, :] - cen[None]) ** 2, axis=-1))
        lbv = d - rad[None]                      # [C, F]
        c_rows = jnp.arange(vc.shape[0])

        def body(_, carry):
            lbv, tab, val, j = carry
            am = jnp.argmin(lbv, axis=-1)        # [C]
            m = lbv[c_rows, am]
            tab = tab.at[:, j].set(am.astype(jnp.int32))
            val = val.at[:, j].set(m)
            lbv = lbv.at[c_rows, am].set(jnp.inf)
            return lbv, tab, val, j + 1

        tab = jnp.zeros((vc.shape[0], k + 1), jnp.int32)
        val = jnp.zeros((vc.shape[0], k + 1), jnp.float32)
        n_extract = min(k + 1, n_f)
        lbv, tab, val, _ = jax.lax.fori_loop(
            0, n_extract, body, (lbv, tab, val, 0)
        )
        safe = val[:, k] if n_extract > k else jnp.full((vc.shape[0],), jnp.inf)
        return tab[:, :k], safe

    pad = (-n_v) % vchunk
    vp = jnp.pad(v, ((0, pad), (0, 0)))
    tab, safe = jax.lax.map(chunk_tables, vp.reshape(-1, vchunk, 3))
    return tab.reshape(-1, k)[:n_v], safe.reshape(-1)[:n_v]


@partial(jax.jit, static_argnames=("chunk",))
def closest_point_anchored(v, f, points, table, safe, chunk=8192):
    """Anchored closest point on mesh; same contract as
    ``closest_faces_and_points`` plus a ``tight`` certificate mask.
    """
    v = jnp.asarray(v, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    center = jnp.mean(v, axis=0)
    vc = v - center
    pts = points - center
    n_q = pts.shape[0]

    tri = vc[jnp.asarray(f, jnp.int32)]
    a_, b_, c_ = tri[:, 0], tri[:, 1], tri[:, 2]
    vn2 = jnp.sum(vc * vc, axis=-1)
    # slack scales with the scene so f32 rounding in dhat/safe can never
    # out-grow it (an absolute constant would break at e.g. millimeter units)
    slack = _CERT_SLACK_REL * jnp.maximum(jnp.max(jnp.abs(vc)), 1.0)

    def one_chunk(p):
        # anchor vertex: matmul-form distances ride the MXU
        d2v = (
            jnp.sum(p * p, axis=-1)[:, None]
            + vn2[None]
            - 2.0 * p @ vc.T
        )                                               # [C, V]
        vi = jnp.argmin(d2v, axis=-1)
        dhat = jnp.sqrt(
            jnp.maximum(jnp.sum((p - vc[vi]) ** 2, axis=-1), 0.0)
        )                                               # true anchor distance
        cand = table[vi]                                # [C, K]
        pt, sq, part = closest_point_on_triangle(
            p[:, None, :], a_[cand], b_[cand], c_[cand]
        )
        j = jnp.argmin(sq, axis=-1)
        rows = jnp.arange(p.shape[0])
        tight = 2.0 * dhat < safe[vi] - slack
        return (
            cand[rows, j].astype(jnp.int32),
            part[rows, j],
            pt[rows, j],
            sq[rows, j],
            tight,
        )

    pad = (-n_q) % chunk
    pp = jnp.pad(pts, ((0, pad), (0, 0)))
    face, part, pt, sq, tight = jax.lax.map(
        one_chunk, pp.reshape(-1, chunk, 3)
    )
    return {
        "face": face.reshape(-1)[:n_q],
        "part": part.reshape(-1)[:n_q],
        "point": pt.reshape(-1, 3)[:n_q] + center,
        "sqdist": sq.reshape(-1)[:n_q],
        "tight": tight.reshape(-1)[:n_q],
    }


def closest_point_anchored_auto(v, f, points, tables=None, k=128, chunk=8192):
    """Exact anchored closest point: non-tight queries re-run through the
    brute-force path (Pallas on accelerators, XLA elsewhere).  Host-boundary
    function, returns numpy.  Pass ``tables=build_anchor_tables(v, f, k)`` to
    amortize setup across calls (the reference's cached AabbTree pattern,
    mesh/search.py:21-24).
    """
    if tables is None:
        tables = build_anchor_tables(v, f, k=k)
    table, safe = tables
    res = closest_point_anchored(v, f, points, table, safe, chunk=chunk)
    out = {key: np.asarray(val) for key, val in res.items()}
    tight = out.pop("tight")
    loose = np.nonzero(~tight)[0]
    if loose.size:
        loose_pts = np.asarray(points)[loose]
        if pallas_default():
            from .pallas_closest import closest_point_pallas

            fix = closest_point_pallas(v, f, loose_pts)
        else:
            # pure-XLA brute force runs on any backend (the Pallas kernel's
            # Mosaic lowering is TPU-only)
            from .closest_point import closest_faces_and_points

            fix = closest_faces_and_points(v, f, loose_pts)
        for key in ("face", "part", "sqdist"):
            out[key] = out[key].copy()
            out[key][loose] = np.asarray(fix[key])
        out["point"] = out["point"].copy()
        out["point"][loose] = np.asarray(fix["point"])
    return out
