"""Pallas TPU kernel for normal-weighted nearest neighbor.

The blended metric ``cost = |p - q| + eps * (1 - n_p . n_tri)`` is the
registration workhorse the reference built 300 lines of custom CGAL traits
for (mesh/src/AABB_n_tree.h:40-84, with a random-hint warm start noted
"slow" in-source).  The plain-JAX path (normal_weighted.py) materializes
(chunk, F, 3) closest-point intermediates in HBM; this kernel fuses the
Ericson distance, the normal penalty (an outer-product of query-normal and
face-normal component planes), and the running argmin into one VMEM-resident
(TQ, TF) tile pass — the same structure as pallas_closest.

eps is compile-time static (one kernel per eps value, cached by jit).
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry.tri_normals import tri_normals
from .pallas_closest import (
    _BIG, _face_const_rows, _pad_cols, _pad_rows, _sqdist_tile_fast,
)
from .point_triangle import closest_point_on_triangle


def _nw_kernel(eps, px, py, pz, qnx, qny, qnz,
               ax, ay, az, bx, by, bz, cx, cy, cz,
               inv_ab2, inv_ac2, inv_bc2, nx, ny, nz, inv_n2,
               tnx, tny, tnz,
               out_i, acc_d, acc_i):
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_d[:] = jnp.full_like(acc_d, _BIG)
        acc_i[:] = jnp.zeros_like(acc_i)

    d2 = _sqdist_tile_fast(
        px[:], py[:], pz[:], ax[:], ay[:], az[:],
        bx[:], by[:], bz[:], cx[:], cy[:], cz[:],
        inv_ab2[:], inv_ac2[:], inv_bc2[:], nx[:], ny[:], nz[:], inv_n2[:],
    )  # (TQ, TF)
    ndot = qnx[:] * tnx[:] + qny[:] * tny[:] + qnz[:] * tnz[:]
    cost = jnp.sqrt(d2) + eps * (1.0 - ndot)
    tf = cost.shape[1]
    tile_min = jnp.min(cost, axis=1, keepdims=True)
    tile_arg = jnp.argmin(cost, axis=1).astype(jnp.int32)[:, None] + j * tf
    better = tile_min < acc_d[:]
    acc_d[:] = jnp.where(better, tile_min, acc_d[:])
    acc_i[:] = jnp.where(better, tile_arg, acc_i[:])

    @pl.when(j == n_j - 1)
    def _write():
        out_i[:] = acc_i[:]


@partial(jax.jit, static_argnames=("eps", "tile_q", "tile_f", "interpret"))
def nearest_normal_weighted_pallas(v, f, points, normals, eps=0.1,
                                   tile_q=256, tile_f=2048, interpret=False):
    """Pallas-accelerated AabbNormalsTree.nearest.

    Same contract as normal_weighted.nearest_normal_weighted: returns
    ``(face [Q] int32, point [Q, 3])`` minimizing the blended metric.  Query
    normals are used as given (the reference does not normalize them,
    search.py:96-100); triangle normals are unit.
    """
    v = jnp.asarray(v, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    center = jnp.mean(v, axis=0)
    vc = v - center
    pts = points - center

    tri = vc[f]  # (F, 3, 3)
    tn = tri_normals(vc, f)  # (F, 3) unit
    n_q = pts.shape[0]

    p_cols = [_pad_rows(pts[:, k:k + 1], tile_q, 0.0) for k in range(3)]
    n_cols = [_pad_rows(normals[:, k:k + 1], tile_q, 0.0) for k in range(3)]
    tri_rows = [
        _pad_cols(tri[:, corner, k][None, :], tile_f, _BIG)
        for corner in range(3)
        for k in range(3)
    ]
    const_rows = _face_const_rows(tri, tile_f)
    # padded faces get a zero normal: their penalty is eps, but their
    # distance to any query is ~_BIG, so they can never win
    tn_rows = [_pad_cols(tn[:, k][None, :], tile_f, 0.0) for k in range(3)]
    q_pad = p_cols[0].shape[0]
    f_pad = tri_rows[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)

    out_i = pl.pallas_call(
        partial(_nw_kernel, float(eps)),  # static python float: baked literal
        grid=grid,
        in_specs=[
            *[pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)) for _ in range(6)],
            *[pl.BlockSpec((1, tile_f), lambda i, j: (0, j)) for _ in range(19)],
        ],
        out_specs=pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.int32),
        ],
        interpret=interpret,
    )(*p_cols, *n_cols, *tri_rows, *const_rows, *tn_rows)

    best = out_i[:n_q, 0]
    a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]
    point, _, _ = closest_point_on_triangle(pts, a[best], b[best], c[best])
    return best, point + center
