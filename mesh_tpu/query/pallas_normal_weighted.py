"""Pallas TPU kernel for normal-weighted nearest neighbor.

The blended metric ``cost = |p - q| + eps * (1 - n_p . n_tri)`` is the
registration workhorse the reference built 300 lines of custom CGAL traits
for (mesh/src/AABB_n_tree.h:40-84, with a random-hint warm start noted
"slow" in-source).  The plain-JAX path (normal_weighted.py) materializes
(chunk, F, 3) closest-point intermediates in HBM; this kernel fuses the
Ericson distance, the normal penalty (an outer-product of query-normal and
face-normal component planes), and the running argmin into one VMEM-resident
(TQ, TF) tile pass — the same structure as pallas_closest.

eps is compile-time static (one kernel per eps value, cached by jit).
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry.tri_normals import tri_normals
from .pallas_closest import (
    DIMSEM_QF, N_FACE_ROWS, _face_rows_fast, _pad_cols, _pad_rows,
    _sqdist_tile_fast, make_argmin_kernel,
)
from .point_triangle import closest_point_on_triangle
from ..utils.jax_compat import tpu_compiler_params


def _nw_cost_tile(eps, degenerate_tail, *planes):
    """Blended-metric cost on a (TQ, TF) tile: plugged into the shared
    make_argmin_kernel scaffold (init/merge/write semantics live there)."""
    (px, py, pz, qnx, qny, qnz) = planes[:6]
    face_planes = planes[6:6 + N_FACE_ROWS]
    tnx, tny, tnz = planes[6 + N_FACE_ROWS:]
    d2 = _sqdist_tile_fast(px, py, pz, *face_planes,
                           degenerate_tail=degenerate_tail)  # (TQ, TF)
    ndot = qnx * tnx + qny * tny + qnz * tnz
    return jnp.sqrt(d2) + eps * (1.0 - ndot)


@partial(jax.jit, static_argnames=("eps", "tile_q", "tile_f", "interpret",
                                   "assume_nondegenerate"))
def nearest_normal_weighted_pallas(v, f, points, normals, eps=0.1,
                                   tile_q=256, tile_f=2048, interpret=False,
                                   assume_nondegenerate=False):
    """Pallas-accelerated AabbNormalsTree.nearest.

    Same contract as normal_weighted.nearest_normal_weighted: returns
    ``(face [Q] int32, point [Q, 3])`` minimizing the blended metric.  Query
    normals are used as given (the reference does not normalize them,
    search.py:96-100); triangle normals are unit.
    ``assume_nondegenerate`` has the closest_point_pallas semantics (the
    facade derives it from data via mesh_is_nondegenerate).
    """
    v = jnp.asarray(v, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    center = jnp.mean(v, axis=0)
    vc = v - center
    pts = points - center

    tri = vc[f]  # (F, 3, 3)
    tn = tri_normals(vc, f)  # (F, 3) unit
    n_q = pts.shape[0]

    p_cols = [_pad_rows(pts[:, k:k + 1], tile_q, 0.0) for k in range(3)]
    n_cols = [_pad_rows(normals[:, k:k + 1], tile_q, 0.0) for k in range(3)]
    face_rows = _face_rows_fast(tri, tile_f)
    # padded faces get a zero normal: their penalty is eps, but their
    # distance to any query is +inf, so they can never win
    tn_rows = [_pad_cols(tn[:, k][None, :], tile_f, 0.0) for k in range(3)]
    q_pad = p_cols[0].shape[0]
    f_pad = face_rows[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)

    out_i = pl.pallas_call(
        # static python float eps: baked literal, one kernel per value
        make_argmin_kernel(partial(_nw_cost_tile, float(eps),
                                   not assume_nondegenerate)),
        grid=grid,
        in_specs=[
            *[pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)) for _ in range(6)],
            *[
                pl.BlockSpec((1, tile_f), lambda i, j: (0, j))
                for _ in range(N_FACE_ROWS + 3)
            ],
        ],
        out_specs=pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.int32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(*p_cols, *n_cols, *face_rows, *tn_rows)

    best = out_i[:n_q, 0]
    a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]
    point, _, _ = closest_point_on_triangle(pts, a[best], b[best], c[best])
    return best, point + center
