"""Culled (BVH-lite) Pallas TPU kernel for closest-point-on-mesh.

The brute-force kernel (pallas_closest.py) evaluates every (query, face)
pair; at SMPL scale that is the compute roofline of the whole pipeline
(~60 VPU flops/pair).  The reference escapes O(Q*F) with a CGAL AABB tree
(mesh/src/spatialsearchmodule.cpp:129-218) — recursive, pointer-chasing,
hostile to XLA.  This kernel gets the same asymptotic win in a TPU-shaped
way: *tile-granular sphere culling* over Morton-sorted data.

  host/XLA prologue (all jit, all fixed-shape):
    1. Morton-sort faces by centroid and queries by position, so that each
       contiguous tile of 256 queries / `tile_f` faces is spatially compact.
    2. Bounding sphere (center, radius) per face tile and per query tile.
    3. Per-query upper-bound seed: min over 128-face sub-tiles of
       (dist(q, sub_center) + sub_radius)^2 — a valid upper bound on the
       true closest distance, since some face of the sub-tile lies entirely
       inside that sphere.  Inflated by a safety margin so f32 rounding can
       never make it smaller than the true distance.

  pallas kernel, grid (B, Q_tiles, F_tiles), F innermost:
    - the per-query running-best accumulator starts at the seed;
    - each (query-tile, face-tile) step first evaluates the sphere-to-sphere
      lower bound  lb = max(0, |qc-fc| - qr - fr); if lb^2 exceeds the worst
      running best in the query tile, the whole tile's exact work is skipped
      (`pl.when`) — only the O(1) bound test is paid;
    - otherwise the branch-free Ericson distance runs on the (TQ, TF) tile
      exactly as in the brute-force kernel.

  epilogue: winning face indices are mapped back through the Morton orders
  and the exact closest point / CGAL part code are recomputed on the winner.

Exactness: a query's true-best face tile always satisfies lb <= true_dist
<= seed >= running_best, so it is never skipped; the margin (1e-3 relative,
orders of magnitude beyond f32 rounding on centered coordinates) keeps the
certificates conservative.  Results equal the brute-force kernel up to ties.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_closest import (
    DIMSEM_QF,
    N_FACE_ROWS,
    _sqdist_tile_fast,
    _sqdist_tile_safe,
    fast_tile_rows,
    safe_tile_rows,
)
from .point_triangle import closest_point_on_triangle
from ..utils.jax_compat import tpu_compiler_params

_SUB = 128          # sub-tile size for the seed upper bound
_MARGIN = 1e-3      # relative safety margin on seeds / lower bounds


def _part1by2(x):
    """Spread the low 10 bits of x two apart: abcdefghij -> a00b00c00...j."""
    x = x & np.uint32(0x3FF)
    x = (x | (x << 16)) & np.uint32(0x030000FF)
    x = (x | (x << 8)) & np.uint32(0x0300F00F)
    x = (x | (x << 4)) & np.uint32(0x030C30C3)
    x = (x | (x << 2)) & np.uint32(0x09249249)
    return x


def _morton_codes(xyz):
    """30-bit Morton code per row of xyz [N, 3] (own-bbox normalized)."""
    lo = jnp.min(xyz, axis=0)
    span = jnp.maximum(jnp.max(xyz, axis=0) - lo, 1e-30)
    q = jnp.clip((xyz - lo) / span * 1023.0, 0.0, 1023.0).astype(jnp.uint32)
    return (
        (_part1by2(q[:, 0]) << 2)
        | (_part1by2(q[:, 1]) << 1)
        | _part1by2(q[:, 2])
    )


def _pad_rows_edge(x, multiple):
    pad = (-x.shape[0]) % multiple
    if pad:
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        x = jnp.pad(x, widths, mode="edge")
    return x


def _tile_spheres(pts, tile):
    """Bounding sphere per contiguous tile of `tile` rows of pts [N, 3]."""
    t = pts.reshape(-1, tile, pts.shape[-1])
    cen = jnp.mean(t, axis=1)
    rad = jnp.sqrt(jnp.max(jnp.sum((t - cen[:, None]) ** 2, axis=-1), axis=1))
    return cen, rad


def _prologue(vc, f, pts, tile_q, tile_f):
    """Morton sort + pad + spheres + seeds for one (centered) mesh."""
    tri = vc[f]                                   # (F, 3, 3)
    fcen = jnp.mean(tri, axis=1)
    forder = jnp.argsort(_morton_codes(fcen))
    tri_s = _pad_rows_edge(tri[forder], tile_f)   # (Fp, 3, 3)
    face_ids = _pad_rows_edge(forder.astype(jnp.int32), tile_f)

    # face-tile spheres over all 3 corners of each face in the tile (a
    # tile's corner set is just 3*tile_f points)
    corners = tri_s.reshape(-1, 3)
    fc, fr = _tile_spheres(corners, tile_f * 3)                   # (Gf, ...)

    # sub-tile spheres for the seed upper bound
    sub = _SUB if tile_f % _SUB == 0 else tile_f
    sc, sr = _tile_spheres(corners, sub * 3)                      # (S, ...)

    qorder = jnp.argsort(_morton_codes(pts))
    pts_s = _pad_rows_edge(pts[qorder], tile_q)   # (Qp, 3)
    qc, qr = _tile_spheres(pts_s, tile_q)

    # seed: min over sub-tiles of (dist + sub_radius), squared, inflated
    d = jnp.sqrt(
        jnp.sum((pts_s[:, None, :] - sc[None]) ** 2, axis=-1)
    ) + sr[None]                                   # (Qp, S)
    seed = jnp.min(d, axis=1) ** 2 * (1.0 + _MARGIN) + 1e-12

    return {
        "tri_s": tri_s,
        "face_ids": face_ids,
        "fc": fc,
        "fr": fr,
        "qorder": qorder.astype(jnp.int32),
        "pts_s": pts_s,
        "qc": qc,
        "qr": qr,
        "seed": seed,
    }


def _make_culled_kernel(tile, degenerate_tail):
    """The culled argmin kernel over a given sqdist tile
    (``_sqdist_tile_fast`` or ``_sqdist_tile_safe`` — both consume 19
    face planes, so the grid/spec plumbing is shared), with the exact
    tile's degenerate-face override compile-time optional
    (pallas_closest._ericson_tail): the tail-free variant is bit-identical
    when every face clears the relative area cut — the facade gates on
    mesh_is_nondegenerate, same as the brute kernel."""

    def kernel(*refs):
        qsph, fsph, seed, px, py, pz = refs[:6]
        face_refs = refs[6:6 + N_FACE_ROWS]
        out_i, acc_d, acc_i, worst = refs[6 + N_FACE_ROWS:]
        i = pl.program_id(1)
        j = pl.program_id(2)
        n_j = pl.num_programs(2)

        @pl.when(j == 0)
        def _init():
            acc_d[:] = seed[0]
            acc_i[:] = jnp.zeros_like(acc_i)
            worst[0] = jnp.max(seed[0])

        # sphere-to-sphere lower bound from SMEM tile metadata (scalar ALU
        # only); the metadata blocks are per-batch rows, so the batch index
        # is already applied by the BlockSpec
        dx = qsph[0, i, 0] - fsph[0, j, 0]
        dy = qsph[0, i, 1] - fsph[0, j, 1]
        dz = qsph[0, i, 2] - fsph[0, j, 2]
        dist = jnp.sqrt(dx * dx + dy * dy + dz * dz)
        lb = jnp.maximum(
            dist - qsph[0, i, 3] - fsph[0, j, 3], 0.0) * (1.0 - _MARGIN)

        @pl.when(lb * lb <= worst[0])
        def _exact_tile():
            d2 = tile(
                px[0], py[0], pz[0], *[r[0] for r in face_refs],
                degenerate_tail=degenerate_tail,
            )  # (TQ, TF)
            tf = d2.shape[1]
            tile_min = jnp.min(d2, axis=1, keepdims=True)
            tile_arg = (
                jnp.argmin(d2, axis=1).astype(jnp.int32)[:, None] + j * tf
            )
            better = tile_min < acc_d[:]
            acc_d[:] = jnp.where(better, tile_min, acc_d[:])
            acc_i[:] = jnp.where(better, tile_arg, acc_i[:])
            worst[0] = jnp.max(acc_d[:])

        @pl.when(j == n_j - 1)
        def _write():
            out_i[0] = acc_i[:]

    return kernel


_CULLED_TILES = {"fast": _sqdist_tile_fast, "safe": _sqdist_tile_safe}
_CULLED_ROW_BUILDERS = {"fast": fast_tile_rows, "safe": safe_tile_rows}
#: (tile_variant, assume_nondegenerate) -> built kernel, built lazily once
_CULLED_KERNELS = {}


def _culled_kernel_for(tile_variant, assume_nondegenerate):
    key = (tile_variant, bool(assume_nondegenerate))
    kernel = _CULLED_KERNELS.get(key)
    if kernel is None:
        kernel = _CULLED_KERNELS[key] = _make_culled_kernel(
            _CULLED_TILES[tile_variant],
            degenerate_tail=not assume_nondegenerate,
        )
    return kernel


@partial(jax.jit, static_argnames=("tile_q", "tile_f", "interpret",
                                   "assume_nondegenerate", "tile_variant"))
def closest_point_pallas_culled(
    v, f, points, tile_q=256, tile_f=1024, interpret=False,
    assume_nondegenerate=False, tile_variant="fast",
):
    """Culled closest_faces_and_points on TPU.  Same contract as
    query.closest_faces_and_points; ``v`` [V, 3] or batched [B, V, 3] with
    ``points`` [Q, 3] resp. [B, Q, 3].  Exact (up to distance ties).

    ``assume_nondegenerate=True`` drops the exact tile's degenerate-face
    override (same contract as closest_point_pallas: bit-identical when
    every face clears the relative area cut; the facades derive the flag
    from data via mesh_is_nondegenerate).

    ``tile_variant="safe"`` runs the sliver-safe direct-corner tile
    (pallas_closest._sqdist_tile_safe) inside the SAME sphere-culled
    grid, so MESH_TPU_SAFE_TILES keeps large-F tiling instead of falling
    back to the brute scan.  The cull's certificates are tile-geometry
    only (sphere centers/radii and seeds) and identical across variants;
    only the exact per-pair distance changes, and the safe tile's errors
    are strictly smaller, so every conservative-bound argument in the
    module docstring carries over unchanged.  Both tiles consume 19 face
    planes (fast_tile_rows / safe_tile_rows), so the kernel signature is
    shared.
    """
    if tile_variant not in ("fast", "safe"):
        raise ValueError("tile_variant must be 'fast' or 'safe', got %r"
                         % (tile_variant,))
    v = jnp.asarray(v, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    batched = v.ndim == 3
    if not batched:
        v = v[None]
        points = points[None]
    n_q = points.shape[1]

    center = jnp.mean(v, axis=1, keepdims=True)
    vc = v - center
    pts = points - center

    pro = jax.vmap(lambda vm, pm: _prologue(vm, f, pm, tile_q, tile_f))(
        vc, pts
    )
    tri_s = pro["tri_s"]                       # (B, Fp, 3, 3)
    b_n, f_pad = tri_s.shape[:2]
    q_pad = pro["pts_s"].shape[1]
    grid = (b_n, q_pad // tile_q, f_pad // tile_f)

    # tile-sphere metadata lives in SMEM, blocked one batch row at a time —
    # whole-array SMEM residency overflows SMEM at large B (scalar loads by
    # program id; (1, 1) VMEM blocks are not a legal Mosaic tiling)
    qsph = jnp.concatenate([pro["qc"], pro["qr"][..., None]], axis=-1)
    fsph = jnp.concatenate([pro["fc"], pro["fr"][..., None]], axis=-1)
    seed = pro["seed"][..., None]              # (B, Qp, 1)
    p_planes = [pro["pts_s"][..., k:k + 1] for k in range(3)]  # (B, Qp, 1)
    # the 19 per-face planes of the selected tile, from the shared
    # builders; tri_s is edge-padded with real duplicated faces, so no
    # sentinel fill is needed — a padded duplicate that wins a tie maps
    # back to the same original face id
    t_planes = [
        r.reshape(b_n, 1, f_pad)
        for r in _CULLED_ROW_BUILDERS[tile_variant](tri_s)
    ]

    qsph_spec = pl.BlockSpec(
        (1,) + qsph.shape[1:], lambda b, i, j: (b, 0, 0),
        memory_space=pltpu.SMEM,
    )
    fsph_spec = pl.BlockSpec(
        (1,) + fsph.shape[1:], lambda b, i, j: (b, 0, 0),
        memory_space=pltpu.SMEM,
    )
    qcol_spec = pl.BlockSpec((1, tile_q, 1), lambda b, i, j: (b, i, 0))
    frow_spec = pl.BlockSpec((1, 1, tile_f), lambda b, i, j: (b, 0, j))

    out_i = pl.pallas_call(
        _culled_kernel_for(tile_variant, assume_nondegenerate),
        grid=grid,
        in_specs=[
            qsph_spec,
            fsph_spec,
            qcol_spec,
            *[qcol_spec] * 3,
            *[frow_spec] * N_FACE_ROWS,
        ],
        out_specs=pl.BlockSpec((1, tile_q, 1), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b_n, q_pad, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.int32),
            pltpu.SMEM((1,), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",) + DIMSEM_QF),
        interpret=interpret,
    )(qsph, fsph, seed, *p_planes, *t_planes)

    def _epilogue(best_sorted, face_ids, qorder, pm, vm):
        # winner in sorted-face space -> original face index, sorted-query
        # order -> original query order, then exact recompute
        inv = jnp.argsort(qorder)
        best = face_ids[best_sorted[:, 0]][inv][:n_q]
        tri = vm[f]
        a, b, c = tri[:, 0], tri[:, 1], tri[:, 2]
        point, sqd, part = closest_point_on_triangle(
            pm, a[best], b[best], c[best]
        )
        return best, part, point, sqd

    best, part, point, sqd = jax.vmap(_epilogue)(
        out_i, pro["face_ids"], pro["qorder"], pts[:, :n_q], vc
    )
    point = point + center
    if not batched:
        return {
            "face": best[0],
            "part": part[0],
            "point": point[0],
            "sqdist": sqd[0],
        }
    return {"face": best, "part": part, "point": point, "sqdist": sqd}
