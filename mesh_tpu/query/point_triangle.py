"""Branch-free exact closest-point-on-triangle with CGAL part codes.

This replaces the recursive CGAL machinery behind the reference's
`spatialsearch` extension: the Voronoi-region case analysis of
mesh/src/nearest_point_triangle_3.h:113-154 becomes straight-line arithmetic
with `where` selection (the standard Ericson formulation), which vmaps over
(query x triangle) pair grids and maps onto the TPU VPU with no control flow.

Part codes match the reference exactly (spatialsearchmodule.cpp:129-140):
0 = triangle interior, 1 = edge ab, 2 = edge bc, 3 = edge ca,
4 = vertex a, 5 = vertex b, 6 = vertex c.
"""

import jax.numpy as jnp

PART_INTERIOR = 0
PART_EDGE_AB = 1
PART_EDGE_BC = 2
PART_EDGE_CA = 3
PART_VERT_A = 4
PART_VERT_B = 5
PART_VERT_C = 6


def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


def _safe_div(num, den):
    den = jnp.where(den == 0, 1.0, den)
    return num / den


def closest_point_barycentric(p, a, b, c):
    """Barycentric coords + part code of the point on triangle abc closest to p.

    All inputs broadcastable to [..., 3].  Returns (bary [..., 3], part [...]).
    Branch-free: every Voronoi region's candidate is computed, the right one is
    selected by region tests evaluated in the same priority order as the
    textbook algorithm (vertices, then edges, then interior).
    """
    ab = b - a
    ac = c - a
    ap = p - a
    d1 = _dot(ab, ap)
    d2 = _dot(ac, ap)
    bp = p - b
    d3 = _dot(ab, bp)
    d4 = _dot(ac, bp)
    cp = p - c
    d5 = _dot(ab, cp)
    d6 = _dot(ac, cp)

    va = d3 * d6 - d5 * d4
    vb = d5 * d2 - d1 * d6
    vc = d1 * d4 - d3 * d2

    # Region conditions, in priority order.
    in_a = (d1 <= 0) & (d2 <= 0)
    in_b = (d3 >= 0) & (d4 <= d3)
    in_c = (d6 >= 0) & (d5 <= d6)
    on_ab = (vc <= 0) & (d1 >= 0) & (d3 <= 0)
    on_ca = (vb <= 0) & (d2 >= 0) & (d6 <= 0)
    on_bc = (va <= 0) & (d4 - d3 >= 0) & (d5 - d6 >= 0)

    # Candidate barycentric coordinates per region.
    t_ab = _safe_div(d1, d1 - d3)
    t_ca = _safe_div(d2, d2 - d6)
    t_bc = _safe_div(d4 - d3, (d4 - d3) + (d5 - d6))
    denom = _safe_div(jnp.ones_like(va), va + vb + vc)
    v_int = vb * denom
    w_int = vc * denom

    def bary(b0, b1, b2):
        return jnp.stack(jnp.broadcast_arrays(b0, b1, b2), axis=-1)

    one = jnp.ones_like(d1)
    zero = jnp.zeros_like(d1)
    cand = [
        (in_a, bary(one, zero, zero), PART_VERT_A),
        (in_b, bary(zero, one, zero), PART_VERT_B),
        (in_c, bary(zero, zero, one), PART_VERT_C),
        (on_ab, bary(1.0 - t_ab, t_ab, zero), PART_EDGE_AB),
        (on_ca, bary(1.0 - t_ca, zero, t_ca), PART_EDGE_CA),
        (on_bc, bary(zero, 1.0 - t_bc, t_bc), PART_EDGE_BC),
    ]

    out_bary = bary(1.0 - v_int - w_int, v_int, w_int)
    out_part = jnp.full(va.shape, PART_INTERIOR, dtype=jnp.int32)
    # Walk the priority list backwards; each higher-priority region overwrites
    # unconditionally, so the highest-priority matching region wins.
    for cond, bxyz, code in reversed(cand):
        out_bary = jnp.where(cond[..., None], bxyz, out_bary)
        out_part = jnp.where(cond, code, out_part)
    return out_bary, out_part


def closest_point_on_triangle(p, a, b, c):
    """Closest point, squared distance, and part code.

    Returns (point [..., 3], sqdist [...], part [...]).
    """
    bary, part = closest_point_barycentric(p, a, b, c)
    point = (
        bary[..., 0:1] * a + bary[..., 1:2] * b + bary[..., 2:3] * c
    )
    diff = p - point
    return point, _dot(diff, diff), part
