"""Branch-free exact closest-point-on-triangle with CGAL part codes.

This replaces the recursive CGAL machinery behind the reference's
`spatialsearch` extension: the Voronoi-region case analysis of
mesh/src/nearest_point_triangle_3.h:113-154 becomes straight-line arithmetic
with `where` selection (the standard Ericson formulation), which vmaps over
(query x triangle) pair grids and maps onto the TPU VPU with no control flow.

Part codes match the reference exactly (spatialsearchmodule.cpp:129-140):
0 = triangle interior, 1 = edge ab, 2 = edge bc, 3 = edge ca,
4 = vertex a, 5 = vertex b, 6 = vertex c.
"""

import jax.numpy as jnp

PART_INTERIOR = 0
PART_EDGE_AB = 1
PART_EDGE_BC = 2
PART_EDGE_CA = 3
PART_VERT_A = 4
PART_VERT_B = 5
PART_VERT_C = 6


def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


def _safe_div(num, den):
    den = jnp.where(den == 0, 1.0, den)
    return num / den


def closest_point_barycentric(p, a, b, c):
    """Barycentric coords + part code of the point on triangle abc closest to p.

    All inputs broadcastable to [..., 3].  Returns (bary [..., 3], part [...]).
    Branch-free: every Voronoi region's candidate is computed, the right one is
    selected by region tests evaluated in the same priority order as the
    textbook algorithm (vertices, then edges, then interior).
    """
    ab = b - a
    ac = c - a
    ap = p - a
    d1 = _dot(ab, ap)
    d2 = _dot(ac, ap)
    bp = p - b
    d3 = _dot(ab, bp)
    d4 = _dot(ac, bp)
    cp = p - c
    d5 = _dot(ab, cp)
    d6 = _dot(ac, cp)

    va = d3 * d6 - d5 * d4
    vb = d5 * d2 - d1 * d6
    vc = d1 * d4 - d3 * d2

    # Region conditions, in priority order.
    in_a = (d1 <= 0) & (d2 <= 0)
    in_b = (d3 >= 0) & (d4 <= d3)
    in_c = (d6 >= 0) & (d5 <= d6)
    on_ab = (vc <= 0) & (d1 >= 0) & (d3 <= 0)
    on_ca = (vb <= 0) & (d2 >= 0) & (d6 <= 0)
    on_bc = (va <= 0) & (d4 - d3 >= 0) & (d5 - d6 >= 0)

    # Candidate barycentric coordinates per region.
    t_ab = _safe_div(d1, d1 - d3)
    t_ca = _safe_div(d2, d2 - d6)
    t_bc = _safe_div(d4 - d3, (d4 - d3) + (d5 - d6))
    denom = _safe_div(jnp.ones_like(va), va + vb + vc)
    v_int = vb * denom
    w_int = vc * denom

    def bary(b0, b1, b2):
        return jnp.stack(jnp.broadcast_arrays(b0, b1, b2), axis=-1)

    one = jnp.ones_like(d1)
    zero = jnp.zeros_like(d1)
    cand = [
        (in_a, bary(one, zero, zero), PART_VERT_A),
        (in_b, bary(zero, one, zero), PART_VERT_B),
        (in_c, bary(zero, zero, one), PART_VERT_C),
        (on_ab, bary(1.0 - t_ab, t_ab, zero), PART_EDGE_AB),
        (on_ca, bary(1.0 - t_ca, zero, t_ca), PART_EDGE_CA),
        (on_bc, bary(zero, 1.0 - t_bc, t_bc), PART_EDGE_BC),
    ]

    out_bary = bary(1.0 - v_int - w_int, v_int, w_int)
    out_part = jnp.full(va.shape, PART_INTERIOR, dtype=jnp.int32)
    # Walk the priority list backwards; each higher-priority region overwrites
    # unconditionally, so the highest-priority matching region wins.
    for cond, bxyz, code in reversed(cand):
        out_bary = jnp.where(cond[..., None], bxyz, out_bary)
        out_part = jnp.where(cond, code, out_part)

    # Degenerate-face override.  For (near-)zero-area triangles —
    # duplicate corners, collinear corners — the region tests above ride
    # on va/vb/vc, which are exact zeros cancelling in f32: rounding noise
    # picks an arbitrary region and the reported point can be badly wrong
    # (real meshes contain such faces: scan soup, decimation output, the
    # reference's own vertex-only CGALClosestPointTree builds them on
    # purpose, search.py:68-86).  A degenerate triangle IS its edge
    # segments, so the best of the three clamped segment projections is
    # exact there.  The threshold (squared-sine of the corner angle
    # <= 1e-10) only fires where the override differs from the true
    # distance by O(|edge| * 1e-5) — inside the framework's parity bar.
    ab2 = _dot(ab, ab)
    ac2 = _dot(ac, ac)
    n = jnp.cross(ab, ac)
    # the vertex regions (in_a/in_b/in_c) ride on plain dot comparisons
    # that stay exact for degenerate faces — keep their classification
    # (and part codes); only the cancellation-dependent edge/interior
    # selection needs the segment override
    degen = (_dot(n, n) <= 1e-10 * ab2 * ac2) & ~(in_a | in_b | in_c)

    def on_segment(p0, s0, s1):
        d = s1 - s0
        t = jnp.clip(_safe_div(_dot(p0 - s0, d), _dot(d, d)), 0.0, 1.0)
        pt = s0 + t[..., None] * d
        diff = p0 - pt
        return t, _dot(diff, diff)

    t_e_ab, d_e_ab = on_segment(p, a, b)
    t_e_bc, d_e_bc = on_segment(p, b, c)
    t_e_ca, d_e_ca = on_segment(p, c, a)
    seg_cands = [
        (d_e_ab, bary(1.0 - t_e_ab, t_e_ab, zero), PART_EDGE_AB),
        (d_e_bc, bary(zero, 1.0 - t_e_bc, t_e_bc), PART_EDGE_BC),
        (d_e_ca, bary(t_e_ca, zero, 1.0 - t_e_ca), PART_EDGE_CA),
    ]
    seg_d, seg_bary, seg_part = seg_cands[0][0], seg_cands[0][1], jnp.full(
        va.shape, PART_EDGE_AB, dtype=jnp.int32
    )
    for d_e, b_e, code in seg_cands[1:]:
        closer = d_e < seg_d
        seg_bary = jnp.where(closer[..., None], b_e, seg_bary)
        seg_part = jnp.where(closer, code, seg_part)
        seg_d = jnp.minimum(seg_d, d_e)
    out_bary = jnp.where(degen[..., None], seg_bary, out_bary)
    out_part = jnp.where(degen, seg_part, out_part)
    return out_bary, out_part


def closest_point_on_triangle(p, a, b, c):
    """Closest point, squared distance, and part code.

    Returns (point [..., 3], sqdist [...], part [...]).
    """
    bary, part = closest_point_barycentric(p, a, b, c)
    point = (
        bary[..., 0:1] * a + bary[..., 1:2] * b + bary[..., 2:3] * c
    )
    diff = p - point
    return point, _dot(diff, diff), part
