"""Pallas TPU kernels for ray / segment / triangle-triangle queries.

TPU-shaped replacements for the XLA paths in ray.py and visibility.py,
which materialize [chunk, F] hit/parameter matrices in HBM per tile —
bandwidth-bound, like the closest-point scan before its Pallas kernel.
Every kernel here streams face tiles through the VPU against a
VMEM-resident per-query accumulator, so HBM traffic is O(Q + F):

- ``ray_any_hit_pallas``  — blocked flag per ray (the visibility hot loop,
  reference mesh/src/visibility.cpp:86-114);
- ``nearest_alongnormal_pallas`` — nearest |t| hit along +/- the query
  normal (reference spatialsearchmodule.cpp:222-323);
- ``tri_tri_any_hit_pallas`` — does query triangle i intersect any mesh
  triangle (reference spatialsearchmodule.cpp:326-417).

The shared per-pair test is Moller-Trumbore in a division-free
sign-carried form: with det = e1.(d x e2), every barycentric / ray-bound
of ray.ray_triangle_hits's divided form

    u >= -beps,  v >= -beps,  u + v <= 1 + beps,  t_lo <= t <= t_hi

is multiplied through by |det| (positive), giving the equivalent

    un*sign(det) >= -beps*|det|, ...,  tn*sign(det) >= t_lo*|det|

with un = s.(d x e2), vn = d.(s x e1), tn = e2.(s x e1) — no reciprocal
per pair.  Semantic parity with ray.ray_triangle_hits (and through it the
reference's CGAL predicates) is asserted by the interpret-mode tests.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_closest import (
    _BIG,
    _pad_cols,
    _pad_rows,
    DIMSEM_QF,
    make_argmin_kernel,
)
from .ray import _BARY_EPS, _EPS
from ..utils.jax_compat import tpu_compiler_params


def _mt_terms(o, d, a, e1, e2):
    """Division-free Moller-Trumbore terms on broadcastable plane triples.

    ``o``/``d``/``a``/``e1``/``e2`` are (x, y, z) tuples of planes shaped
    (TQ, 1) or (1, TF) in any mix; every output broadcasts to (TQ, TF).
    Returns (ad, sd, un, vn, tn): |det|, sign(det), and the sign-carried
    numerators of u, v, t."""
    ox, oy, oz = o
    dx, dy, dz = d
    ax, ay, az = a
    e1x, e1y, e1z = e1
    e2x, e2y, e2z = e2
    # pvec = d x e2
    px = dy * e2z - dz * e2y
    py = dz * e2x - dx * e2z
    pz = dx * e2y - dy * e2x
    det = e1x * px + e1y * py + e1z * pz
    sd = jnp.sign(det)
    ad = jnp.abs(det)
    sx, sy, sz = ox - ax, oy - ay, oz - az
    un = (sx * px + sy * py + sz * pz) * sd
    # qvec = s x e1
    qx = sy * e1z - sz * e1y
    qy = sz * e1x - sx * e1z
    qz = sx * e1y - sy * e1x
    vn = (dx * qx + dy * qy + dz * qz) * sd
    tn = (e2x * qx + e2y * qy + e2z * qz) * sd
    return ad, sd, un, vn, tn


def _mt_line_hit(o, d, a, e1, e2, eps=_EPS, beps=_BARY_EPS):
    """Division-free line-vs-triangle acceptance (t unbounded in sign).

    Returns (hit, ad, tn).  This is THE acceptance predicate for the
    alongnormal kernel: the cost tile and the nearest_alongnormal_pallas
    epilogue both call it, so a winner accepted in-kernel can never
    recompute as a miss (they would otherwise have to stay bitwise
    identical by hand — advisor round-2 finding)."""
    ad, _, un, vn, tn = _mt_terms(o, d, a, e1, e2)
    tol = beps * ad
    hit = (
        (ad >= eps)
        & (un >= -tol)
        & (vn >= -tol)
        & (un + vn <= ad + tol)
    )
    return hit, ad, tn


def _mt_hit(o, d, a, e1, e2, eps, beps, t_lo, t_hi):
    """Boolean hit tile; ``t_lo``/``t_hi`` are python floats or None
    (unbounded).  Matches ray.ray_triangle_hits(...) & the t bounds."""
    hit, ad, tn = _mt_line_hit(o, d, a, e1, e2, eps, beps)
    if t_lo is not None:
        hit = hit & (tn >= t_lo * ad)
    if t_hi is not None:
        hit = hit & (tn <= t_hi * ad)
    return hit


def _any_hit_kernel(eps, beps, t_lo, t_hi, *refs):
    o = tuple(r[:] for r in refs[:3])
    d = tuple(r[:] for r in refs[3:6])
    a = tuple(r[:] for r in refs[6:9])
    e1 = tuple(r[:] for r in refs[9:12])
    e2 = tuple(r[:] for r in refs[12:15])
    out_b, acc_b = refs[15:]
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_b[:] = jnp.zeros_like(acc_b)

    hit = _mt_hit(o, d, a, e1, e2, eps, beps, t_lo, t_hi)
    acc_b[:] = acc_b[:] | jnp.any(hit, axis=1, keepdims=True).astype(
        jnp.int32
    )

    @pl.when(j == n_j - 1)
    def _write():
        out_b[:] = acc_b[:]


def _query_cols(arrs, tile_q):
    """[Q, 3] arrays -> 3 (Q_pad, 1) planes each, zero-padded."""
    return [
        _pad_rows(arr[:, k:k + 1], tile_q, 0.0)
        for arr in arrs
        for k in range(3)
    ]


def _tri_rows(tri, tile_f):
    """[F, 3, 3] triangles -> 9 (1, F_pad) planes (a, e1, e2); padded
    faces are fully degenerate (zero edges): det == 0 -> never hit."""
    a = tri[:, 0]
    e1 = tri[:, 1] - tri[:, 0]
    e2 = tri[:, 2] - tri[:, 0]
    return [
        _pad_cols(x[None, :], tile_f, 0.0)
        for arr in (a, e1, e2)
        for x in (arr[:, 0], arr[:, 1], arr[:, 2])
    ]


_QCOL = lambda tile_q: pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0))  # noqa: E731
_FROW = lambda tile_f: pl.BlockSpec((1, tile_f), lambda i, j: (0, j))  # noqa: E731


@partial(jax.jit, static_argnames=("t_lo", "t_hi", "tile_q", "tile_f",
                                   "interpret"))
def ray_any_hit_pallas(origins, dirs, tri, t_lo=0.0, t_hi=None,
                       tile_q=256, tile_f=2048, interpret=False):
    """True per ray iff ``origins[i] + t * dirs[i]`` (t in [t_lo, t_hi],
    None = unbounded) hits any triangle of ``tri`` [F, 3, 3].  Semantics
    match ``ray.ray_triangle_hits(...)[1] & (t >= t_lo) ...`` reduced over
    faces."""
    origins = jnp.asarray(origins, jnp.float32)
    dirs = jnp.asarray(dirs, jnp.float32)
    tri = jnp.asarray(tri, jnp.float32)
    n_q = origins.shape[0]

    qcols = _query_cols([origins, dirs], tile_q)
    frows = _tri_rows(tri, tile_f)
    q_pad = qcols[0].shape[0]
    f_pad = frows[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)

    out_b = pl.pallas_call(
        partial(_any_hit_kernel, float(_EPS), float(_BARY_EPS), t_lo, t_hi),
        grid=grid,
        in_specs=[*[_QCOL(tile_q)] * 6, *[_FROW(tile_f)] * 9],
        out_specs=_QCOL(tile_q),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[pltpu.VMEM((tile_q, 1), jnp.int32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(*qcols, *frows)
    return out_b[:n_q, 0].astype(bool)


def _alongnormal_cost_tile(*planes):
    """|t| per (ray, face) pair where hit, else _BIG — the argmin cost of
    nearest_alongnormal (t unrestricted in sign: the line through the
    point along +/- its normal, reference spatialsearchmodule.cpp:275-321).
    One VPU division per pair (|t| = |tn| / |det|) — unavoidable, the
    ray parameter itself is needed for the ordering."""
    o = planes[:3]
    d = planes[3:6]
    a = planes[6:9]
    e1 = planes[9:12]
    e2 = planes[12:15]
    hit, ad, tn = _mt_line_hit(o, d, a, e1, e2)
    t_abs = jnp.abs(tn) / jnp.where(ad == 0, 1.0, ad)
    return jnp.where(hit, t_abs, _BIG)


_alongnormal_kernel = make_argmin_kernel(_alongnormal_cost_tile)


@partial(jax.jit, static_argnames=("tile_q", "tile_f", "interpret"))
def nearest_alongnormal_pallas(v, f, points, normals, tile_q=256,
                               tile_f=2048, interpret=False):
    """Pallas path of ray.nearest_alongnormal: (distance [Q], face [Q]
    int32, point [Q, 3]); distance is |t| * |n| with +inf when no triangle
    is hit in either direction."""
    from .ray import NO_HIT

    v = jnp.asarray(v, jnp.float32)
    points = jnp.asarray(points, jnp.float32)
    normals = jnp.asarray(normals, jnp.float32)
    tri = v[f]
    n_q = points.shape[0]

    qcols = _query_cols([points, normals], tile_q)
    frows = _tri_rows(tri, tile_f)
    q_pad = qcols[0].shape[0]
    f_pad = frows[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)

    out_i = pl.pallas_call(
        _alongnormal_kernel,
        grid=grid,
        in_specs=[*[_QCOL(tile_q)] * 6, *[_FROW(tile_f)] * 9],
        out_specs=_QCOL(tile_q),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((tile_q, 1), jnp.float32),
            pltpu.VMEM((tile_q, 1), jnp.int32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(*qcols, *frows)

    best = out_i[:n_q, 0]
    # recompute t on the winning face with the SAME division-free
    # acceptance as the kernel (not ray_triangle_hits's divided form, whose
    # tolerances differ by ~1 ulp at borderline pairs: a winner accepted
    # in-kernel must never recompute as a miss, or a genuinely-hit query
    # would return +inf); a no-hit winner (arbitrary index, cost _BIG)
    # still fails the acceptance here -> +inf
    wa = tri[best, 0]
    we1 = tri[best, 1] - wa
    we2 = tri[best, 2] - wa
    hit, ad, tn = _mt_line_hit(
        tuple(points[:, k] for k in range(3)),
        tuple(normals[:, k] for k in range(3)),
        tuple(wa[:, k] for k in range(3)),
        tuple(we1[:, k] for k in range(3)),
        tuple(we2[:, k] for k in range(3)),
    )
    t = tn / jnp.where(ad == 0, 1.0, ad)
    dist = jnp.where(hit, jnp.abs(t) * jnp.linalg.norm(normals, axis=-1),
                     NO_HIT)
    point = jnp.where(
        hit[:, None], points + t[:, None] * normals, 0.0
    )
    return dist, best, point


# ---------------------------------------------------------------------------
# Möller '97 no-division triangle-triangle interval test — the fast tile for
# NON-DEGENERATE pairs (~180 per-pair VPU ops vs ~330 for the 6-segment
# formulation).  Decision parity with the segment form holds for generic
# (non-coplanar, non-degenerate, non-borderline) geometry; coplanar overlaps
# are not counted by either form (ray.py module docstring).  Degenerate
# triangles (zero normal) make this test blind, so the facade only selects
# it when BOTH meshes pass mesh_is_nondegenerate (the same data-derived
# gate as the closest-point fast tile); padded faces/queries are all-zero
# -> their plane distances are identically zero -> the coplanar guard
# rejects them.
#
# Branch-free formulation of the published tri_tri_intersect_no_div: the
# 5-way COMPUTE_INTERVALS case chain becomes three formula sets (base
# vertex 0/1/2) under nested selects, and the interval-overlap comparison
# uses the common XX*YY scaling, which preserves interval intersection
# under a shared (possibly negative) scale because each endpoint pair is
# re-sorted before comparing.


def moller_prescale(*tris, with_scale=False):
    """Jointly center and scale triangle arrays into the unit box before
    the Möller interval computation.

    ``with_scale=True`` additionally returns the applied scale factor
    ``s`` (``scaled = (t - center) * s``) so callers can map tolerances
    expressed in input units into the prescaled frame (a length ``L`` in
    input coordinates is ``L * s`` after prescale) — see
    ``ray.tri_tri_intersects_moller``'s eps handling.

    The no-div intervals multiply tolerances through instead of dividing,
    so the compared terms (``a * XX * YY`` etc., _moller_hit) scale as
    coordinate-extent^13: raw mm-scale scans (extents ~1e3) overflow f32
    to inf/NaN, and a NaN endpoint makes ``~((hi1 < lo2) | (hi2 < lo1))``
    report overlap — spurious intersections for plane-straddling but
    disjoint pairs (advisor round-4 finding).  Mapping every input to
    max-abs 1 bounds the degree-13 terms at O(1) for ANY input extent,
    leaves the per-pair arithmetic graph untouched (so the Pallas/XLA
    parity tests still pin identical graphs), and puts the fixed EPSILON
    plane-thickening at the O(1) data scale the published algorithm — and
    this repo's random battery — assume.  Intersection decisions are
    scale-invariant (every compared pair of terms shares its degree), so
    only rounding-level borderline pairs can move.

    All inputs share one (center, scale) — the pair test mixes both
    meshes, so per-mesh normalization would change the geometry.  Sharing
    a scale across pairs of very different sizes is safe because
    _tri_planes normalizes the plane normals: the eps-thickened plane
    distances scale LINEARLY with the shared scale (not cubically), so a
    small pair in a large scene is thickened at f32-noise level, never
    clamped to coplanar.

    f32 representational limit: features smaller than ~1e-7 of the joint
    scene extent do not survive the centering subtraction itself
    (ulp(center offset) exceeds their edges) — true of ANY f32 transform
    of such data, not a prescale artifact.  Pairs in batches spanning
    more than ~7 orders of magnitude need f64 inputs (the f64 path keeps
    full precision through the same code).
    """
    flats = [t.reshape(-1, 3) for t in tris if t.size]
    if not flats:
        # nothing to measure (empty query or face set) — shapes are
        # static under jit, so plain Python control flow is fine here
        return (tris, 1.0) if with_scale else tris
    lo = flats[0].min(axis=0)
    hi = flats[0].max(axis=0)
    for c in flats[1:]:
        lo = jnp.minimum(lo, c.min(axis=0))
        hi = jnp.maximum(hi, c.max(axis=0))
    center = (lo + hi) * 0.5
    m = jnp.max(hi - lo) * 0.5
    s = jnp.where(m > 0, 1.0 / jnp.maximum(m, 1e-30), 1.0)
    scaled = tuple((t - center) * s for t in tris)
    return (scaled, s) if with_scale else scaled


def _moller_intervals(vp0, vp1, vp2, dv0, dv1, dv2, dv0dv1, dv0dv2):
    """(A, B, C, X0, X1, coplanar) of the no-div interval computation for
    one triangle's projections ``vp*`` and plane distances ``dv*``."""
    case1 = dv0dv1 > 0                      # dv2 is alone
    case2 = dv0dv2 > 0                      # dv1 is alone
    case3 = (dv1 * dv2 > 0) | (dv0 != 0)    # dv0 is alone
    case4 = dv1 != 0                        # same formula set as case2
    case5 = dv2 != 0                        # same formula set as case1
    sel_d1 = (~case1 & case2) | (~case1 & ~case2 & ~case3 & case4)
    sel_d2 = case1 | (~case1 & ~case2 & ~case3 & ~case4 & case5)
    coplanar = ~case1 & ~case2 & ~case3 & ~case4 & ~case5

    # base-vertex-2 formulas (case1/case5)
    a2 = vp2
    b2 = (vp0 - vp2) * dv2
    c2 = (vp1 - vp2) * dv2
    x0_2 = dv2 - dv0
    x1_2 = dv2 - dv1
    # base-vertex-1 formulas (case2/case4)
    a1 = vp1
    b1 = (vp0 - vp1) * dv1
    c1 = (vp2 - vp1) * dv1
    x0_1 = dv1 - dv0
    x1_1 = dv1 - dv2
    # base-vertex-0 formulas (case3)
    a0 = vp0
    b0 = (vp1 - vp0) * dv0
    c0 = (vp2 - vp0) * dv0
    x0_0 = dv0 - dv1
    x1_0 = dv0 - dv2

    pick = lambda f2, f1, f0: jnp.where(  # noqa: E731
        sel_d2, f2, jnp.where(sel_d1, f1, f0))
    return (pick(a2, a1, a0), pick(b2, b1, b0), pick(c2, c1, c0),
            pick(x0_2, x0_1, x0_0), pick(x1_2, x1_1, x1_0), coplanar)


def _moller_hit(q0, q1, q2, n1, d1, m0, m1, m2, n2, d2, eps):
    """Branch-free Möller no-div intersection on broadcastable component
    triples: ``q0/q1/q2``/``m0/m1/m2`` are (x, y, z) corner tuples,
    ``n1``/``n2`` the (hoisted) unnormalized triangle normals, ``d1``/``d2``
    the (hoisted) plane offsets -n.corner0.  Shapes (TQ, 1) or (1, TF) in
    any mix (or full [...] arrays on the XLA path — the arithmetic graph is
    identical, which is what the parity tests pin)."""

    def plane_dist(n, d, p):
        val = n[0] * p[0] + n[1] * p[1] + n[2] * p[2] + d
        # the published EPSILON thickening: |dist| < eps counts as ON the
        # plane, so sign tests below are stable at rounding level
        return jnp.where(jnp.abs(val) < eps, 0.0, val)

    dv0 = plane_dist(n2, d2, q0)
    dv1 = plane_dist(n2, d2, q1)
    dv2 = plane_dist(n2, d2, q2)
    dv0dv1 = dv0 * dv1
    dv0dv2 = dv0 * dv2
    reject_q = (dv0dv1 > 0) & (dv0dv2 > 0)   # query strictly on one side

    du0 = plane_dist(n1, d1, m0)
    du1 = plane_dist(n1, d1, m1)
    du2 = plane_dist(n1, d1, m2)
    du0du1 = du0 * du1
    du0du2 = du0 * du2
    reject_m = (du0du1 > 0) & (du0du2 > 0)

    # intersection-line direction and its dominant axis
    dx = n1[1] * n2[2] - n1[2] * n2[1]
    dy = n1[2] * n2[0] - n1[0] * n2[2]
    dz = n1[0] * n2[1] - n1[1] * n2[0]
    ax, ay, az = jnp.abs(dx), jnp.abs(dy), jnp.abs(dz)
    use_y = ay > ax
    use_z = az > jnp.maximum(ax, ay)

    def proj(p):
        return jnp.where(use_z, p[2], jnp.where(use_y, p[1], p[0]))

    a1_, b1_, c1_, x0, x1, cop1 = _moller_intervals(
        proj(q0), proj(q1), proj(q2), dv0, dv1, dv2, dv0dv1, dv0dv2)
    a2_, b2_, c2_, y0, y1, cop2 = _moller_intervals(
        proj(m0), proj(m1), proj(m2), du0, du1, du2, du0du1, du0du2)

    xx = x0 * x1
    yy = y0 * y1
    xxyy = xx * yy
    t1 = a1_ * xxyy
    i1a = t1 + b1_ * x1 * yy
    i1b = t1 + c1_ * x0 * yy
    t2 = a2_ * xxyy
    i2a = t2 + b2_ * xx * y1
    i2b = t2 + c2_ * xx * y0
    lo1 = jnp.minimum(i1a, i1b)
    hi1 = jnp.maximum(i1a, i1b)
    lo2 = jnp.minimum(i2a, i2b)
    hi2 = jnp.maximum(i2a, i2b)
    overlap = ~((hi1 < lo2) | (hi2 < lo1))
    return overlap & ~reject_q & ~reject_m & ~cop1 & ~cop2


def _moller_tri_tri_kernel(eps, *refs):
    """Any-intersection Möller tile, OR-reduced per query (same scaffold
    as _tri_tri_kernel; 13 query cols + 13 face rows)."""
    q0 = tuple(r[:] for r in refs[0:3])
    q1 = tuple(r[:] for r in refs[3:6])
    q2 = tuple(r[:] for r in refs[6:9])
    n1 = tuple(r[:] for r in refs[9:12])
    d1 = refs[12][:]
    m0 = tuple(r[:] for r in refs[13:16])
    m1 = tuple(r[:] for r in refs[16:19])
    m2 = tuple(r[:] for r in refs[19:22])
    n2 = tuple(r[:] for r in refs[22:25])
    d2 = refs[25][:]
    out_b, acc_b = refs[26:]
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_b[:] = jnp.zeros_like(acc_b)

    hit = _moller_hit(q0, q1, q2, n1, d1, m0, m1, m2, n2, d2, eps)
    acc_b[:] = acc_b[:] | jnp.any(hit, axis=1, keepdims=True).astype(
        jnp.int32
    )

    @pl.when(j == n_j - 1)
    def _write():
        out_b[:] = acc_b[:]


def _tri_planes(tri):
    """Per-triangle Möller quantities: corners, UNIT normal, plane offset
    d = -n.corner0 — hoisted once, like fast_tile_rows.

    Normalizing the normal (one rsqrt per triangle, hoisted out of the
    O(Q*F) scan) makes the plane distances in _moller_hit true distances:
    the fixed eps thickening is then uniform across triangle sizes (small
    faces of a finely tessellated mesh are not clamped to coplanar), and
    the interval-overlap terms drop from degree 13 to degree 5 in the
    coordinate extent, so f32 holds to extents ~1e7 even before
    moller_prescale's unit-box mapping (advisor round-4 overflow
    finding).  Degenerate (zero-normal) triangles keep n = 0 -> every
    plane distance is 0 -> coplanar reject: the documented Möller blind
    spot, unchanged.  The degeneracy cut is RELATIVE (n2 vs |e1|^2|e2|^2,
    like fast_tile_rows'): an absolute epsilon would zero the normals of
    VALID triangles that are merely tiny relative to the prescaled scene
    (a far outlier in the batch shrinks everyone else), turning real
    intersections into coplanar rejects."""
    a = tri[..., 0, :]
    e1 = tri[..., 1, :] - a
    e2 = tri[..., 2, :] - a
    n = jnp.cross(e1, e2)
    n2 = jnp.sum(n * n, axis=-1, keepdims=True)
    e12 = jnp.sum(e1 * e1, axis=-1, keepdims=True)
    e22 = jnp.sum(e2 * e2, axis=-1, keepdims=True)
    # collinear-at-any-scale has n2 ~ (eps(dtype) * |e1||e2|)^2 of
    # e12*e22 (~1.4e-14 in f32, ~4.9e-32 in f64); 1e2 * eps^2 sits above
    # that rounding floor with margin in EITHER width.  A fixed f32-tuned
    # 1e-12 would coplanar-reject valid f64 slivers with corner-angle
    # sine down at ~1e-6 that f64 resolves perfectly well (advisor
    # round-5 finding).
    degenerate = n2 <= 1e2 * jnp.finfo(tri.dtype).eps ** 2 * e12 * e22
    n = n * jnp.where(
        degenerate, 0.0, jax.lax.rsqrt(jnp.where(degenerate, 1.0, n2))
    )
    d = -jnp.sum(n * a, axis=-1)
    return a, tri[..., 1, :], tri[..., 2, :], n, d


def _tri_tri_kernel(eps, *refs):
    """Any-intersection per (query triangle, mesh triangle) tile,
    OR-reduced into the per-query accumulator."""
    qa = tuple(r[:] for r in refs[0:3])
    qb = tuple(r[:] for r in refs[3:6])
    qc = tuple(r[:] for r in refs[6:9])
    ma = tuple(r[:] for r in refs[9:12])
    me1 = tuple(r[:] for r in refs[12:15])
    me2 = tuple(r[:] for r in refs[15:18])
    out_b, acc_b = refs[18:]
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_b[:] = jnp.zeros_like(acc_b)

    hit = _tri_tri_hit_tile(qa, qb, qc, ma, me1, me2, eps)
    acc_b[:] = acc_b[:] | jnp.any(hit, axis=1, keepdims=True).astype(
        jnp.int32
    )

    @pl.when(j == n_j - 1)
    def _write():
        out_b[:] = acc_b[:]


def _tri_tri_hit_tile(qa, qb, qc, ma, me1, me2, eps):
    """Any-intersection boolean tile between query triangles (corner
    plane triples qa/qb/qc) and mesh triangles (a/e1/e2 plane triples):
    the 3 query edges against the mesh face and the 3 mesh edges against
    the query face — ray.tri_tri_intersects's segment formulation.
    Shared by the intersection-mask and self-intersection kernels."""

    def sub(u, w):
        return tuple(ui - wi for ui, wi in zip(u, w))

    mb = tuple(a + e for a, e in zip(ma, me1))
    mc = tuple(a + e for a, e in zip(ma, me2))
    # segment t in [-eps, 1+eps] with tight barycentric tolerance, exactly
    # ray._segment_hits_triangles
    seg = partial(_mt_hit, eps=eps, beps=eps, t_lo=-eps, t_hi=1.0 + eps)
    hit = None
    for s0, s1 in ((qa, qb), (qb, qc), (qc, qa)):
        h = seg(s0, sub(s1, s0), ma, me1, me2)
        hit = h if hit is None else hit | h
    qe1 = sub(qb, qa)
    qe2 = sub(qc, qa)
    for s0, s1 in ((ma, mb), (mb, mc), (mc, ma)):
        hit = hit | seg(s0, sub(s1, s0), qa, qe1, qe2)
    return hit


def _make_self_intersect_kernel(eps, n_tri_planes):
    """Per-face count of intersecting other faces, excluding the face
    itself and any vertex-sharing pair (reference
    Do_intersect_noself_traits, AABB_n_tree.h:95-117).  ``n_tri_planes``
    selects the pair predicate: 9 -> segment formulation (corners/edges),
    13 -> Möller interval tile (corners + hoisted normal/offset)."""

    def kernel(*refs):
        n = n_tri_planes
        qplanes = refs[0:n]
        qi = refs[n][:]                 # (TQ, 3) int32 vertex ids
        mplanes = refs[n + 1:2 * n + 1]
        mi = refs[2 * n + 1][:]         # (3, TF) int32 vertex ids
        out_c, acc_c = refs[2 * n + 2:]
        i = pl.program_id(0)
        j = pl.program_id(1)
        n_j = pl.num_programs(1)
        tq = qi.shape[0]
        tf = mi.shape[1]

        @pl.when(j == 0)
        def _init():
            acc_c[:] = jnp.zeros_like(acc_c)

        if n == 9:
            qa = tuple(r[:] for r in qplanes[0:3])
            qb = tuple(r[:] for r in qplanes[3:6])
            qc = tuple(r[:] for r in qplanes[6:9])
            ma = tuple(r[:] for r in mplanes[0:3])
            me1 = tuple(r[:] for r in mplanes[3:6])
            me2 = tuple(r[:] for r in mplanes[6:9])
            hit = _tri_tri_hit_tile(qa, qb, qc, ma, me1, me2, eps)
        else:
            q0 = tuple(r[:] for r in qplanes[0:3])
            q1 = tuple(r[:] for r in qplanes[3:6])
            q2 = tuple(r[:] for r in qplanes[6:9])
            n1 = tuple(r[:] for r in qplanes[9:12])
            d1 = qplanes[12][:]
            m0 = tuple(r[:] for r in mplanes[0:3])
            m1 = tuple(r[:] for r in mplanes[3:6])
            m2 = tuple(r[:] for r in mplanes[6:9])
            n2 = tuple(r[:] for r in mplanes[9:12])
            d2 = mplanes[12][:]
            hit = _moller_hit(q0, q1, q2, n1, d1, m0, m1, m2, n2, d2, eps)

        # vertex-sharing exclusion: any of the 9 (row vertex, col vertex)
        # index pairs equal; plus self-pair exclusion by global face id
        shares = None
        for r in range(3):
            for c in range(3):
                eq = qi[:, r:r + 1] == mi[c:c + 1, :]
                shares = eq if shares is None else shares | eq
        row_id = jax.lax.broadcasted_iota(jnp.int32, (tq, 1), 0) + i * tq
        col_id = jax.lax.broadcasted_iota(jnp.int32, (1, tf), 1) + j * tf
        not_self = row_id != col_id
        counted = hit & ~shares & not_self
        acc_c[:] = acc_c[:] + jnp.sum(
            counted.astype(jnp.int32), axis=1, keepdims=True
        )

        @pl.when(j == n_j - 1)
        def _write():
            out_c[:] = acc_c[:]

    return kernel


def _moller_qcols(tri, tile_q):
    """Query-side Möller planes: 13 (Q_pad, 1) cols (corners + hoisted
    normal + plane offset), zero-padded — all-zero padding has zero plane
    distances everywhere and lands in the coplanar reject."""
    a, b, c, n, d = _tri_planes(tri)
    qcols = _query_cols([a, b, c, n], tile_q)
    qcols.append(_pad_rows(d[:, None], tile_q, 0.0))
    return qcols


def _moller_frows(tri, tile_f):
    """Face-side Möller planes: 13 (1, F_pad) rows; padding as above."""
    a, b, c, n, d = _tri_planes(tri)
    frows = [
        _pad_cols(x[None, :], tile_f, 0.0)
        for arr in (a, b, c, n)
        for x in (arr[:, 0], arr[:, 1], arr[:, 2])
    ]
    frows.append(_pad_cols(d[None, :], tile_f, 0.0))
    return frows


@partial(jax.jit, static_argnames=("tile_q", "tile_f", "interpret",
                                   "algorithm"))
def self_intersection_count_pallas(v, f, tile_q=256, tile_f=512,
                                   interpret=False, algorithm="segment"):
    """Pallas path of query.self_intersection_count: the number of faces
    intersecting at least one other non-vertex-sharing face (the kernel
    accumulates per-face partner counts; involvement is counted here).

    ``algorithm="moller"`` runs the interval tile (~2x fewer ops; only
    valid when every face is non-degenerate — the facade gates on
    mesh_is_nondegenerate).  Count parity between the two algorithms is
    pinned by the reference self-intersection fixtures
    (tests/test_reference_fixtures.py)."""
    v = jnp.asarray(v, jnp.float32)
    f = jnp.asarray(f, jnp.int32)
    tri = v[f]
    n_f = tri.shape[0]

    if algorithm == "moller":
        (tri_n,) = moller_prescale(tri)
        qcols = _moller_qcols(tri_n, tile_q)
        frows = _moller_frows(tri_n, tile_f)
        n_planes = 13
    elif algorithm == "segment":
        qcols = _query_cols([tri[:, 0], tri[:, 1], tri[:, 2]], tile_q)
        frows = _tri_rows(tri, tile_f)
        n_planes = 9
    else:
        raise ValueError("algorithm must be 'segment' or 'moller', got %r"
                         % (algorithm,))
    # vertex-id planes: padded rows/cols get distinct negative ids so a
    # padded row never "shares" with a padded column; padded geometry is
    # degenerate (zero) and never intersects anyway
    qi = _pad_rows(f, tile_q, -1)
    mi = _pad_cols(f.T, tile_f, -2)
    q_pad = qcols[0].shape[0]
    f_pad = frows[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)

    out_c = pl.pallas_call(
        _make_self_intersect_kernel(float(_EPS), n_planes),
        grid=grid,
        in_specs=[
            *[_QCOL(tile_q)] * n_planes,
            pl.BlockSpec((tile_q, 3), lambda i, j: (i, 0)),
            *[_FROW(tile_f)] * n_planes,
            pl.BlockSpec((3, tile_f), lambda i, j: (0, j)),
        ],
        out_specs=_QCOL(tile_q),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[pltpu.VMEM((tile_q, 1), jnp.int32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(*qcols, qi, *frows, mi)
    return jnp.sum(out_c[:n_f, 0] > 0)


@partial(jax.jit, static_argnames=("tile_q", "tile_f", "interpret",
                                   "algorithm"))
def tri_tri_any_hit_pallas(q_tri, tri, tile_q=256, tile_f=512,
                           interpret=False, algorithm="segment"):
    """True per query triangle iff it intersects any triangle of ``tri``
    — the Pallas path of query.intersections_mask.  Both inputs are
    [*, 3, 3] triangle arrays.

    ``algorithm="moller"`` selects the no-division interval tile (~2x
    fewer VPU ops) — only valid when every triangle of BOTH inputs is
    non-degenerate (the facade checks via mesh_is_nondegenerate; a
    degenerate triangle is blind to intersections under Möller, whereas
    the default segment formulation still tests its edges)."""
    q_tri = jnp.asarray(q_tri, jnp.float32)
    tri = jnp.asarray(tri, jnp.float32)
    n_q = q_tri.shape[0]

    if algorithm == "moller":
        q_tri_n, tri_n = moller_prescale(q_tri, tri)
        qcols = _moller_qcols(q_tri_n, tile_q)
        frows = _moller_frows(tri_n, tile_f)
        kernel = partial(_moller_tri_tri_kernel, float(_EPS))
        n_qcols, n_frows = 13, 13
    elif algorithm == "segment":
        # query corners as columns (zero-padded: a degenerate query
        # triangle has zero-length edges and a zero-normal face -> never
        # intersects)
        qcols = _query_cols([q_tri[:, 0], q_tri[:, 1], q_tri[:, 2]], tile_q)
        frows = _tri_rows(tri, tile_f)
        kernel = partial(_tri_tri_kernel, float(_EPS))
        n_qcols, n_frows = 9, 9
    else:
        raise ValueError("algorithm must be 'segment' or 'moller', got %r"
                         % (algorithm,))
    q_pad = qcols[0].shape[0]
    f_pad = frows[0].shape[1]
    grid = (q_pad // tile_q, f_pad // tile_f)

    out_b = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[*[_QCOL(tile_q)] * n_qcols, *[_FROW(tile_f)] * n_frows],
        out_specs=_QCOL(tile_q),
        out_shape=jax.ShapeDtypeStruct((q_pad, 1), jnp.int32),
        scratch_shapes=[pltpu.VMEM((tile_q, 1), jnp.int32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=DIMSEM_QF),
        interpret=interpret,
    )(*qcols, *frows)
    return out_b[:n_q, 0].astype(bool)
