"""meshlint: AST-based static analysis for the framework's own hazards.

Generic linters know nothing about the failure modes that actually bite
this codebase: a ``float()`` on a tracer inside a jitted function (host
sync in the hot path), a ``jax.jit`` constructed per loop iteration
(recompile storm), a Pallas BlockSpec whose tile footprint blows the
16 MiB VMEM budget, a module-level cache mutated outside the lock that
guards it elsewhere, an env knob read around the central registry, or a
metric series the docs never heard of.  ``mesh_tpu.analysis`` is the
in-repo engine that encodes them as first-class rules.

The package is deliberately stdlib-only (``ast`` + friends): the
``mesh-tpu lint`` subcommand and the gate-0 pre-chip check in
tools/run_tpu_gates.sh must run on a box with a wedged axon tunnel or
no accelerator at all.  See doc/static_analysis.md for the rule
catalog and the baseline-suppression workflow
(tools/meshlint_baseline.json).
"""

from .engine import (     # noqa: F401
    Finding,
    FileContext,
    Project,
    Report,
    Rule,
    SEVERITIES,
    build_project,
    check_source,
    load_baseline,
    run_lint,
)
