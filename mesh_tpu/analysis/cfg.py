"""Per-function control-flow graphs for meshlint's flow-sensitive rules.

``build_cfg(funcdef)`` lowers one ``ast.FunctionDef`` (or async def /
lambda-free nested def) into a statement-granularity CFG:

- every simple statement is one node; compound statements contribute a
  node for their *evaluated head* (the ``if``/``while`` test, the
  ``for`` iterable, the ``with`` context expression) plus nodes for the
  statements in their bodies;
- branch edges carry ``kind`` ("true"/"false") and, when the test is a
  recognisable None-check (``x is None`` / ``x is not None`` / bare
  truthiness), an *assumption* ``(expr_key, "none"|"notnone")`` so
  dataflow clients can prune paths that contradict a guard;
- loops get back edges ("back"), exit edges ("loop-exit"), and
  ``break``/``continue`` edges routed through every intervening
  ``finally`` body;
- ``try/except/else/finally`` is modelled with *may* semantics: any
  statement that can raise (contains a call, or is ``raise``/
  ``assert``) gets exception edges to each live handler of the
  innermost enclosing try, and — because the exception may not match a
  non-catch-all handler — onward through ``finally`` bodies to the
  next enclosing try or the synthetic ``raise_exit``;
- ``with`` blocks whose context manager is ``contextlib.suppress`` (or
  any ``*suppress*`` callee) swallow exception edges from their body to
  the statement after the ``with``;
- ``return`` routes through enclosing ``finally`` bodies to the
  synthetic normal ``exit``; falling off the end does too.

Over-approximations (deliberate, documented for rule authors):

- ``finally`` bodies are shared nodes, so the join at a finally merges
  the normal / exceptional / return continuations; a may-analysis sees
  a superset of real paths, never a subset.
- exception type matching is name-blind except that ``except:``,
  ``except Exception`` and ``except BaseException`` count as catch-all.
- ``yield`` is a plain flow-through node (no GeneratorExit edge): a
  raise edge per yield would drown resource rules in noise.

Stdlib-only.  ``STATS`` accumulates build/solve wall time for
``mesh-tpu lint --profile``; ``reset_stats()`` also clears the
per-function CFG cache.
"""

import ast
import time

__all__ = [
    "CFG", "Edge", "Node", "build_cfg", "cfg_for", "expr_key",
    "may_raise", "reset_stats", "snapshot_stats", "STATS",
]

STATS = {"cfg_s": 0.0, "cfg_builds": 0, "dataflow_s": 0.0,
         "dataflow_solves": 0}

_CACHE = {}

#: caches keyed by function-object identity elsewhere in the analysis
#: package (e.g. flw's reaching-defs cache) register here so one
#: reset clears every per-run cache
EXTRA_CACHES = []


def reset_stats():
    STATS["cfg_s"] = 0.0
    STATS["cfg_builds"] = 0
    STATS["dataflow_s"] = 0.0
    STATS["dataflow_solves"] = 0
    _CACHE.clear()
    for cache in EXTRA_CACHES:
        cache.clear()


def snapshot_stats():
    return dict(STATS)


def qualname(node):
    """Dotted name of a Name/Attribute chain, or None (duplicated from
    rules/common.py — importing the rules package from here would be
    circular, since every rule module imports this one)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def expr_key(node):
    """Stable key for an expression: dotted path for name/attribute
    chains (``req.record``), ``ast.dump`` otherwise."""
    q = qualname(node)
    return q if q else ast.dump(node)


class Node(object):
    """One CFG node.  ``stmt`` is the AST statement (or handler) it
    represents; synthetic nodes (entry/exit/raise_exit) have none."""

    __slots__ = ("stmt", "kind", "line")

    def __init__(self, stmt=None, kind="stmt", line=0):
        self.stmt = stmt
        self.kind = kind
        self.line = int(getattr(stmt, "lineno", line) or line)

    def __repr__(self):   # pragma: no cover - debugging aid
        return "<Node %s L%d>" % (self.kind, self.line)


class Edge(object):
    __slots__ = ("src", "dst", "kind", "assume")

    def __init__(self, src, dst, kind, assume=None):
        self.src = src
        self.dst = dst
        self.kind = kind
        self.assume = assume

    def __repr__(self):   # pragma: no cover - debugging aid
        return "<Edge %s L%d->L%d>" % (self.kind, self.src.line,
                                       self.dst.line)


class CFG(object):
    __slots__ = ("func", "entry", "exit", "raise_exit", "nodes",
                 "succ", "pred")

    def __init__(self, func):
        self.func = func
        self.entry = Node(kind="entry",
                          line=getattr(func, "lineno", 0) or 0)
        self.exit = Node(kind="exit")
        self.raise_exit = Node(kind="raise_exit")
        self.nodes = [self.entry, self.exit, self.raise_exit]
        self.succ = {self.entry: [], self.exit: [], self.raise_exit: []}
        self.pred = {self.entry: [], self.exit: [], self.raise_exit: []}

    def add_node(self, node):
        self.nodes.append(node)
        self.succ[node] = []
        self.pred[node] = []
        return node

    def link(self, src, dst, kind, assume=None):
        for e in self.succ[src]:
            if e.dst is dst and e.kind == kind and e.assume == assume:
                return e
        e = Edge(src, dst, kind, assume)
        self.succ[src].append(e)
        self.pred[dst].append(e)
        return e

    def stmt_nodes(self):
        return [n for n in self.nodes if n.stmt is not None]


_CATCH_ALL = ("Exception", "BaseException")


def _is_catch_all(handler):
    if handler.type is None:
        return True
    t = handler.type
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        q = qualname(n) or ""
        if q.split(".")[-1] in _CATCH_ALL:
            return True
    return False


def may_raise(stmt):
    """May evaluating this node's *own* code raise?  For compound
    statements only the evaluated head counts (test / iter / items)."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    if isinstance(stmt, (ast.If, ast.While)):
        probe = stmt.test
    elif isinstance(stmt, ast.For):
        probe = stmt.iter
    elif isinstance(stmt, (ast.With, getattr(ast, "AsyncWith", ast.With))):
        probe = ast.Module(body=[ast.Expr(value=i.context_expr)
                                 for i in stmt.items],
                           type_ignores=[])
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Try)):
        return False
    else:
        probe = stmt
    for sub in ast.walk(probe):
        if isinstance(sub, (ast.Call, ast.Await, ast.Subscript)):
            return True
    return False


def _test_assumes(test):
    """(true_assume, false_assume) for a branch test, or (None, None).
    Truthiness of a bare name approximates a not-None check — good
    enough to prune ``if rec: close(rec)`` guard paths."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        t, f = _test_assumes(test.operand)
        return f, t
    if (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None):
        key = expr_key(test.left)
        if isinstance(test.ops[0], ast.Is):
            return (key, "none"), (key, "notnone")
        if isinstance(test.ops[0], ast.IsNot):
            return (key, "notnone"), (key, "none")
    if isinstance(test, (ast.Name, ast.Attribute)):
        key = expr_key(test)
        return (key, "notnone"), (key, "none")
    return None, None


class _Scope(object):
    """One enclosing try (or swallowing with) as seen from a statement
    being wired.  ``handlers`` are the live handler nodes, ``fin`` the
    (entry_node, exit_frontier) of a finally body, ``swallow`` a
    collector list for exception edges that vanish (contextlib.suppress).
    """

    __slots__ = ("handlers", "catch_all", "fin", "swallow")

    def __init__(self, handlers=(), catch_all=False, fin=None,
                 swallow=None):
        self.handlers = list(handlers)
        self.catch_all = catch_all
        self.fin = fin          # (entry_node, exit_frontier) | None
        self.swallow = swallow  # list collector | None


class _Loop(object):
    __slots__ = ("header", "breaks", "try_depth")

    def __init__(self, header, try_depth):
        self.header = header
        self.breaks = []        # frontier entries wired to after-loop
        self.try_depth = try_depth


class _Builder(object):
    def __init__(self, func):
        self.cfg = CFG(func)
        self.loops = []
        self.tries = []

    # frontier: list of (src_node, kind, assume) dangling edges

    def build(self):
        frontier = [(self.cfg.entry, "seq", None)]
        frontier = self.seq(self.cfg.func.body, frontier)
        for src, kind, assume in frontier:
            self.cfg.link(src, self.cfg.exit, kind, assume)
        return self.cfg

    def attach(self, frontier, node, default_kind="seq"):
        for src, kind, assume in frontier:
            self.cfg.link(src, node, kind or default_kind, assume)

    def seq(self, stmts, frontier):
        for stmt in stmts:
            if not frontier:
                break           # unreachable tail; stop wiring
            frontier = self.stmt(stmt, frontier)
        return frontier

    # -- exception / teardown routing ---------------------------------

    def raise_from(self, node):
        """Wire exception edges from ``node`` to handlers / finallys /
        raise_exit per the live scope stack."""
        srcs = [(node, "raise", None)]
        for scope in reversed(self.tries):
            if scope.swallow is not None:
                scope.swallow.extend(
                    (s, "swallow", a) for s, _k, a in srcs)
                return
            for h in scope.handlers:
                for s, _k, a in srcs:
                    self.cfg.link(s, h, "except", a)
            if scope.handlers and scope.catch_all:
                return
            if scope.fin is not None:
                fin_entry, fin_exits = scope.fin
                for s, _k, a in srcs:
                    self.cfg.link(s, fin_entry, "finally", a)
                srcs = [(s, "raise", a) for s, _k, a in fin_exits]
        for s, _k, a in srcs:
            self.cfg.link(s, self.cfg.raise_exit, "raise", a)

    def through_finallys(self, srcs, down_to_depth, kind):
        """Route ``srcs`` through every finally between the current
        scope depth and ``down_to_depth``; returns the surviving
        frontier."""
        for scope in reversed(self.tries[down_to_depth:]):
            if scope.fin is not None:
                fin_entry, fin_exits = scope.fin
                for s, _k, a in srcs:
                    self.cfg.link(s, fin_entry, kind, a)
                srcs = [(s, kind, a) for s, _k, a in fin_exits]
        return srcs

    # -- statement dispatch -------------------------------------------

    def stmt(self, stmt, frontier):
        if isinstance(stmt, ast.If):
            return self.if_(stmt, frontier)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self.loop(stmt, frontier)
        if isinstance(stmt, ast.Try):
            return self.try_(stmt, frontier)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self.with_(stmt, frontier)
        node = self.cfg.add_node(Node(stmt))
        self.attach(frontier, node)
        if isinstance(stmt, ast.Return):
            srcs = self.through_finallys([(node, "return", None)], 0,
                                         "return")
            for s, k, a in srcs:
                self.cfg.link(s, self.cfg.exit, k, a)
            return []
        if isinstance(stmt, ast.Raise):
            self.raise_from(node)
            return []
        if isinstance(stmt, (ast.Break, ast.Continue)):
            if not self.loops:
                return []       # malformed source; be lenient
            loop = self.loops[-1]
            kind = "break" if isinstance(stmt, ast.Break) else "continue"
            srcs = self.through_finallys([(node, kind, None)],
                                         loop.try_depth, kind)
            if kind == "break":
                loop.breaks.extend(srcs)
            else:
                for s, k, a in srcs:
                    self.cfg.link(s, loop.header, k, a)
            return []
        if may_raise(stmt):
            self.raise_from(node)
        return [(node, "seq", None)]

    def if_(self, stmt, frontier):
        node = self.cfg.add_node(Node(stmt))
        self.attach(frontier, node)
        if may_raise(stmt):
            self.raise_from(node)
        t_assume, f_assume = _test_assumes(stmt.test)
        out = self.seq(stmt.body, [(node, "true", t_assume)])
        if stmt.orelse:
            out += self.seq(stmt.orelse, [(node, "false", f_assume)])
        else:
            out.append((node, "false", f_assume))
        return out

    def loop(self, stmt, frontier):
        header = self.cfg.add_node(Node(stmt))
        self.attach(frontier, header)
        if may_raise(stmt):
            self.raise_from(header)
        loop = _Loop(header, len(self.tries))
        self.loops.append(loop)
        if isinstance(stmt, ast.While):
            t_assume, f_assume = _test_assumes(stmt.test)
            body_in = [(header, "true", t_assume)]
            infinite = (isinstance(stmt.test, ast.Constant)
                        and bool(stmt.test.value))
            exit_out = [] if infinite else [(header, "false", f_assume)]
        else:
            body_in = [(header, "iter", None)]
            exit_out = [(header, "loop-exit", None)]
        body_out = self.seq(stmt.body, body_in)
        for s, _k, a in body_out:
            self.cfg.link(s, header, "back", a)
        self.loops.pop()
        if stmt.orelse:
            exit_out = self.seq(stmt.orelse, exit_out)
        return exit_out + loop.breaks

    def try_(self, stmt, frontier):
        fin = None
        if stmt.finalbody:
            # build the finally body first (under the *outer* scope
            # stack — exceptions in a finally propagate outward) so
            # teardown routing from the try/handler bodies can target it
            fin_entry = self.cfg.add_node(
                Node(kind="finally", line=stmt.finalbody[0].lineno))
            fin_exits = self.seq(stmt.finalbody,
                                 [(fin_entry, "seq", None)])
            fin = (fin_entry, fin_exits)
        handler_nodes = []
        catch_all = False
        for h in stmt.handlers:
            hn = self.cfg.add_node(Node(h, kind="handler"))
            handler_nodes.append(hn)
            catch_all = catch_all or _is_catch_all(h)
        # try body: exceptions live against our handlers + finally
        self.tries.append(_Scope(handler_nodes, catch_all, fin))
        body_out = self.seq(stmt.body, list(frontier))
        self.tries.pop()
        # handler / else bodies: our handlers no longer catch, but the
        # finally still interposes on the way out
        if fin is not None:
            self.tries.append(_Scope((), False, fin))
        out = []
        for h, hn in zip(stmt.handlers, handler_nodes):
            out += self.seq(h.body, [(hn, "seq", None)])
        if stmt.orelse:
            body_out = self.seq(stmt.orelse, body_out)
        out += body_out
        if fin is not None:
            self.tries.pop()
            fin_entry, fin_exits = fin
            for s, _k, a in out:
                self.cfg.link(s, fin_entry, "seq", a)
            return list(fin_exits)
        return out

    def with_(self, stmt, frontier):
        node = self.cfg.add_node(Node(stmt))
        self.attach(frontier, node)
        if may_raise(stmt):
            self.raise_from(node)
        swallow = None
        for item in stmt.items:
            expr = item.context_expr
            callee = qualname(expr.func) if isinstance(expr, ast.Call) \
                else None
            if callee and "suppress" in callee.split(".")[-1]:
                swallow = []
        if swallow is not None:
            self.tries.append(_Scope(swallow=swallow))
        out = self.seq(stmt.body, [(node, "seq", None)])
        if swallow is not None:
            self.tries.pop()
            out = out + swallow
        return out


def build_cfg(funcdef):
    """Lower one function def to a :class:`CFG` (uncached)."""
    t0 = time.monotonic()
    try:
        return _Builder(funcdef).build()
    finally:
        STATS["cfg_s"] += time.monotonic() - t0
        STATS["cfg_builds"] += 1


def cfg_for(funcdef):
    """Cached :func:`build_cfg` — rules within one lint run share the
    graph.  Cleared by :func:`reset_stats`."""
    key = id(funcdef)
    hit = _CACHE.get(key)
    if hit is not None and hit[0] is funcdef:
        return hit[1]
    cfg = build_cfg(funcdef)
    _CACHE[key] = (funcdef, cfg)
    return cfg
