"""OBS: metric/span hygiene.

The observability contract has three legs: every series the code can
create is documented in doc/observability.md's name table, series names
are static literals (so the doc lint CAN see them), and nothing in the
hot path reads raw clocks around the span tracer's sync-aware
measurement.  The first two used to live in tests/test_metrics_doc_lint
as regexes and the third in tests/test_timing_lint; both tests are now
thin wrappers over this rule pack (same test names, same coverage).

Codes:

- OBS001 (error): a metric series / serve-tier span name created in
  code is absent from doc/observability.md (the ``{a,b}`` brace
  shorthand in the doc table is expanded before comparison).
- OBS002 (warning): ``counter``/``gauge``/``histogram`` called with a
  non-literal name — a dynamic series name is invisible to OBS001 and
  unbounded in cardinality (the registry implementation itself,
  obs/metrics.py, is exempt: its methods forward a name parameter).
- OBS003 (warning): a metric mutator (``inc``/``set``/``observe``...)
  called with ``**kwargs`` whose keys are not statically visible —
  dynamic label NAMES are an unbounded-cardinality hazard (dynamic
  label values are fine).
- OBS004 (warning): a raw ``time.time()``-family clock call outside
  utils/profiling.py, obs/, viewer/, and analysis/ — hot-path timing
  must go through obs.clock / Timer / timed_span so the sync-aware
  accounting and the overhead gate stay honest.
- OBS005 (error): a latency-ledger stage name (the ``LEDGER_STAGES``
  tuple in obs/ledger.py) is absent from doc/observability.md — the
  stage vocabulary is the ``mesh-tpu prof`` CLI's user-facing contract,
  so every name must appear in the doc as a backticked literal.
- OBS006 (error): a metric mutator (``inc``/``observe``) called with a
  label VALUE that is provably unbounded — an f-string, a %-formatted
  string, a ``str()``/``.format()`` call, or a name ending in
  ``request_id`` / ``digest`` / ``store_key`` / ``routing_key``.
  Bounded label values (tenant, stage, outcome, replica) are fine;
  per-request identity belongs in histogram **exemplars** (the
  sanctioned ``exemplar=`` keyword is exempt, doc/observability.md
  "Request identity") — as a label value it makes every request its
  own series and explodes registry cardinality.
"""

import ast
import re

from .common import qualname
from ..engine import Finding, Rule

_SERIES_FUNCS = {"counter", "gauge", "histogram"}
_SPAN_FUNCS = {"span", "timed_span", "obs_span"}
_LABEL_MUTATORS = {"inc", "dec", "set", "set_max", "observe"}
#: mutators checked for unbounded label VALUES (OBS006) — ``set`` is
#: deliberately absent: ``span.set(request_id=...)`` is the sanctioned
#: span-tagging idiom and spans are bounded by the tracer ring
_VALUE_MUTATORS = {"inc", "observe"}
#: terminal identifier names that are per-request/per-object identity —
#: unbounded by construction (tenant/session ids are admission-bounded
#: and deliberately NOT here)
_IDENTITY_NAMES = {"request_id", "digest", "store_key", "routing_key"}
_CLOCK_FUNCS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.process_time"}

#: files allowed to read raw clocks: the profiling primitives, the obs
#: subsystem that aliases them, the (non-hot-path) viewer, and this
#: offline analysis tooling itself
_CLOCK_EXEMPT = ("mesh_tpu/utils/profiling.py", "mesh_tpu/obs/",
                 "mesh_tpu/viewer/", "mesh_tpu/analysis/")

#: the registry implementation and its package facade forward name
#: parameters by design (they ARE the API the literal names flow into)
_SERIES_EXEMPT = ("mesh_tpu/obs/metrics.py", "mesh_tpu/obs/__init__.py")

#: jax_bridge registers series through helper indirection — every
#: literal that LOOKS like a series name counts as created (the old
#: regex lint's _BRIDGE_RE, kept bug-for-bug compatible)
_BRIDGE_BASENAME = "jax_bridge.py"
_BRIDGE_NAME_RE = re.compile(r"^mesh_tpu_[a-z0-9_]+$")

#: doc-side names, allowing the {a,b,c} brace shorthand the table uses
_DOC_NAME_RE = re.compile(
    r"(?:mesh_tpu|serve\.)(?:[a-z0-9_.]|\{[a-z0-9_,]+\})+")


def expand_braces(token):
    """``a_{x,y}_b`` -> {a_x_b, a_y_b} (recursive, one level is all the
    doc uses)."""
    match = re.search(r"\{([a-z0-9_,]+)\}", token)
    if not match:
        return {token}
    out = set()
    for alt in match.group(1).split(","):
        out |= expand_braces(
            token[:match.start()] + alt + token[match.end():])
    return out


def documented_names(doc_text):
    """Every series/span name doc/observability.md mentions, braces
    expanded."""
    names = set()
    for token in _DOC_NAME_RE.findall(doc_text):
        names |= expand_braces(token.rstrip("."))
    return names


def _created_names(ctx):
    """[(name, node)] of series/span names this file can create."""
    out = []
    for node in ctx.nodes():
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func_name = qualname(node.func)
        last = func_name.rsplit(".", 1)[-1] if func_name else None
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue
        if last in _SERIES_FUNCS and first.value.startswith("mesh_tpu_"):
            out.append((first.value, node))
        elif last in _SPAN_FUNCS and first.value.startswith("serve."):
            out.append((first.value, node))
    if ctx.relpath.rsplit("/", 1)[-1] == _BRIDGE_BASENAME:
        for node in ctx.nodes():
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _BRIDGE_NAME_RE.match(node.value)):
                out.append((node.value, node))
    return out


def collect_code_names(project):
    """{name: (relpath, line)} of every creatable series/span name —
    the first creation site wins (also the wrapper test's entry point)."""
    names = {}
    for ctx in project.contexts:
        for name, node in _created_names(ctx):
            names.setdefault(
                name, (ctx.relpath, getattr(node, "lineno", 0)))
    return names


def collect_ledger_stages(project):
    """{stage_name: (relpath, line)} from every ``LEDGER_STAGES = (...)``
    tuple-of-string-literals assignment in the tree (obs/ledger.py owns
    the canonical one; the collector is name-keyed so a moved definition
    stays covered)."""
    stages = {}
    for ctx in project.contexts:
        for node in ctx.nodes():
            if not isinstance(node, ast.Assign):
                continue
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if "LEDGER_STAGES" not in targets:
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            for elt in node.value.elts:
                if (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)):
                    stages.setdefault(
                        elt.value,
                        (ctx.relpath, getattr(node, "lineno", 0)))
    return stages


class ObservabilityHygieneRule(Rule):

    id = "OBS"
    name = "metric/span hygiene"

    def check(self, ctx):
        findings = []
        relpath = ctx.relpath.replace("\\", "/")
        series_exempt = any(relpath.endswith(e) for e in _SERIES_EXEMPT)
        clock_exempt = any(e in relpath if e.endswith("/")
                           else relpath.endswith(e)
                           for e in _CLOCK_EXEMPT)
        for node in ctx.nodes():
            if not isinstance(node, ast.Call):
                continue
            func_name = qualname(node.func)
            last = func_name.rsplit(".", 1)[-1] if func_name else None
            if (not series_exempt and last in _SERIES_FUNCS
                    and isinstance(node.func, ast.Attribute)
                    and node.args
                    and not (isinstance(node.args[0], ast.Constant)
                             and isinstance(node.args[0].value, str))):
                findings.append(ctx.finding(
                    "OBS002", "warning", node,
                    "dynamic series name in %s(...): invisible to the "
                    "doc-coverage lint and unbounded in cardinality"
                    % last,
                    hint="use a literal name (put variation in labels, "
                         "not the series name)"))
            if (last in _LABEL_MUTATORS
                    and isinstance(node.func, ast.Attribute)):
                for kw in node.keywords:
                    if kw.arg is None and not _static_label_keys(kw.value):
                        findings.append(ctx.finding(
                            "OBS003", "warning", node,
                            "**kwargs label expansion in .%s(): dynamic "
                            "label NAMES make series cardinality "
                            "unbounded" % last,
                            hint="spell the label names out "
                                 "(.%s(tenant=t) is fine — values may "
                                 "vary, names must not)" % last))
            if (not series_exempt and last in _VALUE_MUTATORS
                    and isinstance(node.func, ast.Attribute)):
                for kw in node.keywords:
                    if kw.arg is None or kw.arg == "exemplar":
                        # **kwargs is OBS003's territory; exemplar= is
                        # the sanctioned per-request identity path
                        continue
                    why = _unbounded_label_value(kw.value)
                    if why:
                        findings.append(ctx.finding(
                            "OBS006", "error", node,
                            "unbounded label value (%s) for label "
                            "'%s' in .%s(): every distinct value "
                            "becomes its own series" % (why, kw.arg,
                                                        last),
                            hint="per-request identity goes in "
                                 "exemplars (.observe(v, exemplar="
                                 "ctx.request_id)) or span attrs, "
                                 "never in a label value; keep label "
                                 "values bounded (tenant, stage, "
                                 "outcome, replica)"))
            if (not clock_exempt and func_name in _CLOCK_FUNCS):
                findings.append(ctx.finding(
                    "OBS004", "warning", node,
                    "raw clock read %s() outside utils/profiling.py "
                    "and obs/" % func_name,
                    hint="route it through obs.clock (monotonic/wall), "
                         "utils.profiling.Timer, or timed_span"))
        return findings

    def finalize(self, project):
        doc = project.doc_text("doc", "observability.md")
        if doc is None:
            return []
        documented = documented_names(doc)
        findings = []
        for name, (relpath, line) in sorted(
                collect_code_names(project).items()):
            if name not in documented:
                findings.append(Finding(
                    "OBS001", "error", relpath, line,
                    "series '%s' is created in code but absent from "
                    "doc/observability.md" % name,
                    hint="add it to the series table in "
                         "doc/observability.md (the {a,b} brace "
                         "shorthand is expanded)"))
        for stage, (relpath, line) in sorted(
                collect_ledger_stages(project).items()):
            if ("`%s`" % stage) not in doc:
                findings.append(Finding(
                    "OBS005", "error", relpath, line,
                    "ledger stage '%s' (LEDGER_STAGES) is absent from "
                    "doc/observability.md" % stage,
                    hint="add `%s` (backticked) to the ledger stage "
                         "table in doc/observability.md — the stage "
                         "vocabulary is the `mesh-tpu prof` CLI's "
                         "user-facing contract" % stage))
        return findings


def _static_label_keys(node):
    """True when a ``**expr`` expansion provably has constant keys."""
    return (isinstance(node, ast.Dict)
            and all(isinstance(k, ast.Constant)
                    and isinstance(k.value, str) for k in node.keys))


def _unbounded_label_value(node):
    """A short reason when a label-value expression is provably
    unbounded (OBS006), else None.  Conservative by design: plain
    names/attributes pass unless their terminal identifier IS a
    per-request identity — a lint that cried wolf on ``tenant=t``
    would get turned off."""
    if isinstance(node, ast.JoinedStr):
        return "f-string"
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod)
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)):
        return "%-formatted string"
    if isinstance(node, ast.Call):
        func = qualname(node.func)
        last = func.rsplit(".", 1)[-1] if func else None
        if last in ("str", "format"):
            return "stringified value"
    terminal = None
    if isinstance(node, ast.Name):
        terminal = node.id
    elif isinstance(node, ast.Attribute):
        terminal = node.attr
    if terminal in _IDENTITY_NAMES:
        return "per-request identity '%s'" % terminal
    return None
