"""LCK: lock discipline over module-level mutable state.

The serving tier made the framework multi-threaded: worker pools,
watchdogs, and SLO loops all touch module-level caches and registries.
The repo's convention is a module-level ``threading.Lock`` next to the
state it guards, mutations under ``with <lock>:``, and ``*_locked``
helper functions for code that requires the caller to hold it.

This rule checks that discipline per module.  It only activates in
files that define a module-level lock (a module with no lock is
assumed single-threaded by design), and module-top-level statements are
exempt (import-time init runs before any thread exists).

Codes:

- LCK001 (error): a module-level container is mutated *outside* any
  lock in a function, while the SAME container is mutated under a lock
  elsewhere in the module — mixed discipline, i.e. a real race.
- LCK002 (warning): a module-level container is only ever mutated
  without a lock in functions, in a module that defines one.
"""

import ast

from .common import enclosing_function, qualname
from ..engine import Rule

_LOCK_FACTORY_PARTS = {"Lock", "RLock", "Condition", "Semaphore",
                       "BoundedSemaphore"}

_CONTAINER_FACTORIES = {"dict", "list", "set", "deque", "OrderedDict",
                        "defaultdict", "Counter"}

_MUTATOR_ATTRS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "add", "discard", "update", "setdefault", "appendleft", "popleft",
    "sort", "reverse",
}


def _module_level_names(tree, predicate):
    out = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
            if isinstance(target, ast.Name) and predicate(stmt.value):
                out[target.id] = stmt
    return out


def _is_lock_factory(node):
    if not isinstance(node, ast.Call):
        return False
    name = qualname(node.func)
    return bool(name) and name.rsplit(".", 1)[-1] in _LOCK_FACTORY_PARTS


def _is_container_literal(node):
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = qualname(node.func)
        return bool(name) and (name.rsplit(".", 1)[-1]
                               in _CONTAINER_FACTORIES)
    return False


def _under_lock(parents, node, lock_names):
    """True if an ancestor ``with`` statement's context mentions a lock
    (by declared name, or any name containing "lock"), or the enclosing
    function is a ``*_locked`` caller-holds-it helper."""
    current = parents.get(node)
    while current is not None:
        if isinstance(current, (ast.With, ast.AsyncWith)):
            for item in current.items:
                name = qualname(item.context_expr) or ""
                if isinstance(item.context_expr, ast.Call):
                    name = qualname(item.context_expr.func) or ""
                last = name.rsplit(".", 1)[-1]
                if last in lock_names or "lock" in last.lower():
                    return True
        if isinstance(current, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if current.name.endswith("_locked"):
                return True
        current = parents.get(current)
    return False


def _mutated_name(node):
    """The bare container name a statement/call mutates, or None."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if (node.func.attr in _MUTATOR_ATTRS
                and isinstance(node.func.value, ast.Name)):
            return node.func.value.id
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for target in targets:
            if (isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)):
                return target.value.id
    if isinstance(node, ast.Delete):
        for target in node.targets:
            if (isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)):
                return target.value.id
    return None


class LockDisciplineRule(Rule):

    id = "LCK"
    name = "lock discipline on module-level mutable state"

    def check(self, ctx):
        tree = ctx.tree
        locks = _module_level_names(tree, _is_lock_factory)
        if not locks:
            return []
        containers = _module_level_names(tree, _is_container_literal)
        if not containers:
            return []
        parents = ctx.parents()
        lock_names = set(locks)
        # name -> [(node, guarded)]
        sites = {}
        for node in ctx.nodes():
            name = _mutated_name(node)
            if name not in containers:
                continue
            if enclosing_function(parents, node) is None:
                continue            # import-time init: single-threaded
            guarded = _under_lock(parents, node, lock_names)
            sites.setdefault(name, []).append((node, guarded))
        findings = []
        for name, entries in sorted(sites.items()):
            any_guarded = any(guarded for _, guarded in entries)
            for node, guarded in entries:
                if guarded:
                    continue
                if any_guarded:
                    findings.append(ctx.finding(
                        "LCK001", "error", node,
                        "module-level '%s' mutated outside the lock "
                        "that guards it elsewhere in this module — a "
                        "race under the serving tier's threads" % name,
                        hint="wrap the mutation in `with %s:` (or move "
                             "it into a *_locked helper)"
                             % sorted(lock_names)[0]))
                else:
                    findings.append(ctx.finding(
                        "LCK002", "warning", node,
                        "module-level '%s' mutated in a function "
                        "without holding any lock (this module defines "
                        "%s)" % (name, ", ".join(sorted(lock_names))),
                        hint="guard the mutation or document why it is "
                             "single-threaded"))
        return findings
