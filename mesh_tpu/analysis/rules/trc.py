"""TRC: tracer-leak / host-sync detection inside traced code.

A jitted (or custom_vjp / pallas_call-reachable) function that calls
``.item()``, ``.block_until_ready()``, ``np.asarray`` or ``float()`` on
a traced value either fails at trace time or — worse — silently forces
a device->host round trip on every call, which is exactly the class of
hot-path stall the span tracer's sync accounting exists to surface.

Reachability is a name-level call graph per module: roots are functions
decorated with jit/pjit/custom_vjp/custom_jvp (including via
``functools.partial``), functions wrapped by an explicit
``jax.jit(f)`` / ``pl.pallas_call(kernel, ...)`` call, and
``defvjp``/``defjvp`` registrations; everything a root (transitively)
calls by simple name in the same module is treated as traced.

Codes:

- TRC001 (error): ``.item()`` / ``.tolist()`` in traced code.
- TRC002 (error): ``.block_until_ready()`` in traced code.
- TRC003 (warning): numpy materialization (``np.asarray``/``np.array``)
  in traced code.
- TRC004 (warning): ``float()``/``int()``/``bool()`` on a value derived
  from a traced function's arguments (``x.shape[0]``-style static
  expressions are fine and not flagged).
"""

import ast

from .common import decorator_names, qualname
from ..engine import Rule

#: decorator name components that mark a function as traced
_TRACED_DECORATOR_PARTS = {
    "jit", "pjit", "custom_vjp", "custom_jvp", "checkpoint", "remat",
}

#: call wrappers whose function argument becomes traced
_WRAPPER_LAST_PARTS = {"jit", "pjit", "pallas_call", "checkpoint", "remat"}

#: registration methods whose arguments become traced
_REGISTER_ATTRS = {"defvjp", "defjvp"}

_NUMPY_ROOTS = {"np", "onp", "numpy", "jnp"}
_NUMPY_SYNC_ATTRS = {"asarray", "array"}

#: attribute accesses that yield static (host) values even on tracers
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "sharding"}

#: call roots that produce traced values (so float() on them syncs)
_DEVICE_CALL_ROOTS = {"jnp", "jax", "lax", "pl", "pltpu"}


def _last_part(name):
    return name.rsplit(".", 1)[-1] if name else None


class _OneDecorator(object):
    """Minimal funcdef stand-in so decorator_names() can inspect one
    decorator at a time (its static_argnames ride on the same Call)."""

    def __init__(self, decorator_list):
        self.decorator_list = decorator_list


def _collect_function_defs(nodes):
    """Every def in the module keyed by bare name (nested and methods
    included; last definition wins, which is fine for lint purposes)."""
    defs = {}
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    return defs


def _called_names(funcdef):
    """Bare names this function calls or references (a function passed
    to jax.jit / pallas_call inside the body counts as reachable)."""
    out = set()
    for node in ast.walk(funcdef):
        if isinstance(node, ast.Call):
            name = qualname(node.func)
            if name and "." not in name:
                out.add(name)
    return out


def _static_spec(keywords):
    """(names, nums) declared static via static_argnames/static_argnums
    keyword literals."""
    names, nums = set(), set()
    for kw in keywords:
        if kw.arg not in ("static_argnames", "static_argnums"):
            continue
        values = (kw.value.elts
                  if isinstance(kw.value, (ast.Tuple, ast.List))
                  else [kw.value])
        for value in values:
            if isinstance(value, ast.Constant):
                if isinstance(value.value, str):
                    names.add(value.value)
                elif isinstance(value.value, int):
                    nums.add(value.value)
    return names, nums


def _traced_roots(nodes):
    """{name: (static_names, static_nums)} of functions that directly
    enter tracing in this module."""
    roots = {}

    def add(name, keywords=()):
        names, nums = _static_spec(keywords)
        prev = roots.get(name)
        if prev:
            names |= prev[0]
            nums |= prev[1]
        roots[name] = (names, nums)

    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                deco_names = decorator_names(
                    _OneDecorator([deco]))
                if any(_last_part(d) in _TRACED_DECORATOR_PARTS
                       for d in deco_names):
                    keywords = (deco.keywords
                                if isinstance(deco, ast.Call) else ())
                    add(node.name, keywords)
                    break
        elif isinstance(node, ast.Call):
            last = _last_part(qualname(node.func))
            if last in _WRAPPER_LAST_PARTS:
                for arg in node.args[:1]:
                    inner = qualname(arg)
                    if inner and "." not in inner:
                        add(inner, node.keywords)
                    elif isinstance(arg, ast.Call):
                        # functools.partial(kernel, ...) as the target
                        pfunc = qualname(arg.func)
                        if _last_part(pfunc) == "partial" and arg.args:
                            inner = qualname(arg.args[0])
                            if inner and "." not in inner:
                                add(inner, node.keywords)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTER_ATTRS):
                for arg in node.args:
                    inner = qualname(arg)
                    if inner and "." not in inner:
                        add(inner)
    return roots


def _traced_functions(nodes):
    """[(funcdef, direct_root_spec_or_None)] reachable from the traced
    roots by name; the spec is (static_names, static_nums) for direct
    roots and None for transitively reached helpers."""
    defs = _collect_function_defs(nodes)
    roots = _traced_roots(nodes)
    seen = set()
    frontier = [name for name in roots if name in defs]
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        for callee in _called_names(defs[name]):
            if callee in defs and callee not in seen:
                frontier.append(callee)
    return [(defs[name], roots.get(name)) for name in sorted(seen)]


def _param_names(funcdef, spec):
    """Parameters treated as traced values.

    For a direct root (``spec`` is its (static_names, static_nums)),
    that is every parameter except the statically-declared ones; for a
    transitively reached helper (``spec`` is None) it is empty — those
    run at trace-build time on static config (tile variants, eps
    literals), and flagging ``float()`` on their bare parameters is
    pure noise.  Device-derived expressions (``float(jnp.sum(x))``)
    are still flagged everywhere via the call heuristic.
    """
    if spec is None:
        return set()
    static_names, static_nums = spec
    args = funcdef.args
    ordered = [a.arg for a in args.posonlyargs + args.args]
    names = set(ordered) | {a.arg for a in args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    names -= static_names
    names -= {ordered[i] for i in static_nums if i < len(ordered)}
    names.discard("self")
    names.discard("cls")
    return names


def _is_dynamic(node, params):
    """Conservatively: does this expression derive from traced inputs?

    Static things (never flagged): literals, ``.shape``-family
    attributes, ``len(...)``, names that are not parameters of the
    enclosing traced function.
    """
    if isinstance(node, ast.Constant):
        return False
    if isinstance(node, ast.Name):
        return node.id in params
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _is_dynamic(node.value, params)
    if isinstance(node, ast.Subscript):
        return _is_dynamic(node.value, params)
    if isinstance(node, ast.BinOp):
        return (_is_dynamic(node.left, params)
                or _is_dynamic(node.right, params))
    if isinstance(node, ast.UnaryOp):
        return _is_dynamic(node.operand, params)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_is_dynamic(e, params) for e in node.elts)
    if isinstance(node, ast.IfExp):
        return any(_is_dynamic(e, params)
                   for e in (node.test, node.body, node.orelse))
    if isinstance(node, ast.Call):
        root = qualname(node.func)
        if root and root.split(".", 1)[0] in _DEVICE_CALL_ROOTS:
            return True
        return False
    return False


class TracerLeakRule(Rule):

    id = "TRC"
    name = "tracer leak / host sync in traced code"

    def check(self, ctx):
        findings = []
        for funcdef, spec in _traced_functions(ctx.nodes()):
            params = _param_names(funcdef, spec)
            for node in ast.walk(funcdef):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute):
                    if func.attr in ("item", "tolist") and not node.args:
                        findings.append(ctx.finding(
                            "TRC001", "error", node,
                            ".%s() inside traced '%s' forces a "
                            "device->host sync (or fails at trace time)"
                            % (func.attr, funcdef.name),
                            hint="return the array and convert it "
                                 "outside the traced function"))
                    elif func.attr == "block_until_ready":
                        findings.append(ctx.finding(
                            "TRC002", "error", node,
                            ".block_until_ready() inside traced '%s' "
                            "is a host sync in the hot path"
                            % funcdef.name,
                            hint="sync at the caller (utils.profiling."
                                 "host_sync) or via Span.watch()"))
                    else:
                        root = qualname(func)
                        if (root
                                and root.split(".", 1)[0] in _NUMPY_ROOTS
                                and root.split(".", 1)[0] != "jnp"
                                and func.attr in _NUMPY_SYNC_ATTRS):
                            findings.append(ctx.finding(
                                "TRC003", "warning", node,
                                "%s() inside traced '%s' materializes "
                                "the tracer on host"
                                % (root, funcdef.name),
                                hint="use jnp inside traced code; "
                                     "convert with numpy at the caller"))
                elif (isinstance(func, ast.Name)
                        and func.id in ("float", "int", "bool")
                        and len(node.args) == 1
                        and _is_dynamic(node.args[0], params)):
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        # flow-sensitive suppression (FLW): a parameter
                        # rebound to a proven host value on every path
                        # reaching this call is not a tracer leak
                        from .flw import all_host_redefined

                        if all_host_redefined(funcdef, ctx.parents(),
                                              node, arg.id, params):
                            continue
                    findings.append(ctx.finding(
                        "TRC004", "warning", node,
                        "%s() on a traced value inside '%s' breaks "
                        "tracing (ConcretizationTypeError or a silent "
                        "host sync)" % (func.id, funcdef.name),
                        hint="keep it as a jnp array, or hoist the "
                             "conversion out of the traced function"))
        return findings
