"""VMEM: Pallas tile-budget estimation from BlockSpec shapes.

A Pallas TPU kernel's working set — every ``in_specs``/``out_specs``
block plus every ``pltpu.VMEM`` scratch buffer — must fit the core's
~16 MiB of VMEM, and Mosaic physically lays f32 tiles out as (8, 128)
(sublane, lane): a lane dimension that is not a multiple of 128 is
padded up, silently multiplying the real footprint and the DMA traffic.
Both failure modes surface only on the real chip (interpret mode does
not model VMEM), so this rule budgets them statically at lint time.

Tile dimensions are resolved best-effort from literals, the enclosing
function's keyword defaults (the ``tile_q=256`` idiom every
query/pallas_*.py builder uses), module-level constants, and simple
arithmetic over those; unresolvable specs are skipped, and the budget
message says how many specs it could price.

Pricing is of the *padded* physical footprint: the last two dims are
rounded up to the dtype's Mosaic tile — (8, 128) for 4-byte dtypes,
(16, 128) for 2-byte (bf16 packs two values per sublane row), (32,
128) for 1-byte — matching what VMEM002/VMEM003 warn about, and any
leading dims multiply it: a double-buffered DMA ring like
``pltpu.VMEM((n_buffers, rows, tile_f), f32)`` is charged
``n_buffers`` times its padded block, the way Mosaic actually
allocates it.  The dtype-aware sublane multiple matters for the MXU
screen's bf16 operand scratch, whose sublane padding an f32-priced
budget would under-charge by up to 2x.

Codes:

- VMEM001 (error): priced blocks for one ``pallas_call`` exceed the
  16 MiB VMEM ceiling (assuming f32 where the dtype is not visible).
- VMEM002 (warning): a block's lane (last) dimension > 1 is not a
  multiple of 128 — Mosaic pads it to 128.
- VMEM003 (note): a block's sublane (second-to-last) dimension > 1 is
  not a multiple of 8 — padded to the next multiple of 8.
"""

import ast

from .common import ConstEnv, enclosing_function, qualname
from ..engine import Rule

#: VMEM ceiling per TensorCore (v4/v5 class); the budget is advisory so
#: a few hundred KiB of Mosaic overhead does not need modelling
VMEM_BUDGET_BYTES = 16 * 1024 * 1024

#: assumed element size when the dtype is not statically visible
_DEFAULT_ITEMSIZE = 4

_DTYPE_SIZES = {
    "float32": 4, "f32": 4, "int32": 4, "i32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1,
    "float64": 8, "int64": 8,
}


def _last_part(name):
    return name.rsplit(".", 1)[-1] if name else None


def _sublane_multiple(itemsize):
    """Mosaic's minimum tile holds 8 rows of 4-byte lanes, and narrower
    dtypes PACK: the physical tile is (8 * 4 / itemsize, 128), so bf16
    tiles are (16, 128) and int8 (32, 128).  Pricing a bf16 scratch
    with the f32 sublane multiple would under-charge its padding by up
    to 2x — exactly the MXU screen's bf16 operand staging shape."""
    return max(8, 32 // max(1, int(itemsize)))


def _padded_bytes(dims, itemsize):
    """Physical footprint of one block: last two dims rounded up to the
    dtype's Mosaic tile — (8, 128) for f32, (16, 128) for 2-byte dtypes
    (dims of 1 stay 1 — scalar rows/columns are exempt, same as the
    VMEM002/VMEM003 checks), leading dims (buffer rings, stacked
    scratch) multiplying the padded tile count."""
    padded = [int(d) for d in dims]
    if padded and padded[-1] > 1:
        padded[-1] = -(-padded[-1] // 128) * 128
    if len(padded) >= 2 and padded[-2] > 1:
        sub = _sublane_multiple(itemsize)
        padded[-2] = -(-padded[-2] // sub) * sub
    size = itemsize
    for d in padded:
        size *= d
    return size


def _dtype_itemsize(node):
    """Element size of a dtype expression (``jnp.float32``), default f32."""
    name = _last_part(qualname(node))
    return _DTYPE_SIZES.get(name, _DEFAULT_ITEMSIZE)


def _block_shape(call):
    """The shape tuple node of a BlockSpec/VMEM call, or None."""
    if call.args:
        node = call.args[0]
    else:
        node = next((kw.value for kw in call.keywords
                     if kw.arg == "block_shape"), None)
    return node if isinstance(node, (ast.Tuple, ast.List)) else None


def _spec_calls(container, attr_name):
    """Calls named ``attr_name`` anywhere under one keyword value."""
    if container is None:
        return []
    return [node for node in ast.walk(container)
            if isinstance(node, ast.Call)
            and _last_part(qualname(node.func)) == attr_name]


class VmemBudgetRule(Rule):

    id = "VMEM"
    name = "Pallas VMEM budget / tiling alignment"

    def check(self, ctx):
        findings = []
        parents = ctx.parents()
        for node in ctx.nodes():
            if not (isinstance(node, ast.Call)
                    and _last_part(qualname(node.func)) == "pallas_call"):
                continue
            env = ConstEnv(ctx.tree, enclosing_function(parents, node))
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            total, priced, unpriced = 0, 0, 0
            blocks = []
            for spec_kw in ("in_specs", "out_specs"):
                for spec in _spec_calls(kwargs.get(spec_kw), "BlockSpec"):
                    blocks.append((spec, _DEFAULT_ITEMSIZE))
            for scratch in _spec_calls(kwargs.get("scratch_shapes"),
                                       "VMEM"):
                itemsize = (_dtype_itemsize(scratch.args[1])
                            if len(scratch.args) > 1 else _DEFAULT_ITEMSIZE)
                blocks.append((scratch, itemsize))
            for spec, itemsize in blocks:
                shape = _block_shape(spec)
                if shape is None:
                    unpriced += 1
                    continue
                dims = [env.resolve(d) for d in shape.elts]
                findings.extend(
                    self._tiling_findings(ctx, spec, dims, itemsize))
                if dims and all(isinstance(d, (int, float)) and d > 0
                                for d in dims):
                    priced += 1
                    total += _padded_bytes(dims, itemsize)
                else:
                    unpriced += 1
            if total > VMEM_BUDGET_BYTES:
                findings.append(ctx.finding(
                    "VMEM001", "error", node,
                    "pallas_call blocks total ~%.2f MiB (%d spec(s) "
                    "priced%s, (8, 128)-padded, f32 assumed) — over the "
                    "%d MiB VMEM ceiling; Mosaic will fail or spill on "
                    "the real chip" % (
                        total / 2 ** 20, priced,
                        ", %d unpriced" % unpriced if unpriced else "",
                        VMEM_BUDGET_BYTES // 2 ** 20),
                    hint="shrink the tile dims (the autotuner sweep in "
                         "benchmarks/tile_sweep.py maps the viable "
                         "range) or move blocks to HBM with explicit "
                         "DMA"))
        return findings

    @staticmethod
    def _tiling_findings(ctx, spec, dims, itemsize=_DEFAULT_ITEMSIZE):
        out = []
        if not dims:
            return out
        lane = dims[-1]
        if isinstance(lane, int) and lane > 1 and lane % 128:
            out.append(ctx.finding(
                "VMEM002", "warning", spec,
                "block lane dimension %d is not a multiple of 128: "
                "Mosaic pads each (%d, 128) tile, wasting VMEM and "
                "DMA bandwidth" % (lane, _sublane_multiple(itemsize)),
                hint="pad the lane dim to 128 (mask the tail) or fold "
                     "the small axis into the sublane dim"))
        if len(dims) >= 2:
            sublane = dims[-2]
            sub = _sublane_multiple(itemsize)
            if isinstance(sublane, int) and sublane > 1 and sublane % sub:
                out.append(ctx.finding(
                    "VMEM003", "note", spec,
                    "block sublane dimension %d is not a multiple of "
                    "%d for this %d-byte dtype (padded to the next "
                    "(%d, 128) tile row)"
                    % (sublane, sub, itemsize, sub)))
        return out
