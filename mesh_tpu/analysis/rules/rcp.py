"""RCP: jit recompile hazards.

``jax.jit`` keys its compile cache on the *function object* plus static
arguments.  Construct the jit inside a loop, hand it a fresh lambda per
call, or feed ``static_argnums`` something non-hashable and every call
compiles from scratch — tens of seconds per compile on the tunneled
chip, which is how a "fast" path quietly becomes a recompile storm.

Codes (all warning severity — each is a real hazard but occasionally
deliberate, e.g. a build-once helper; baseline those with a reason):

- RCP001: ``jax.jit(...)`` constructed under a loop or comprehension.
- RCP002: a lambda passed to ``jax.jit`` inside a function body (a new
  function identity per call defeats the cache; module-level lambdas
  run once and are exempt).
- RCP003: ``static_argnums=``/``static_argnames=`` bound to something
  that is not a literal (or module-level-constant) int/str/tuple —
  unhashable or varying values defeat or poison the cache key.
"""

import ast

from .common import enclosing_function, in_loop, module_constants, qualname
from ..engine import Rule

_JIT_LAST_PARTS = {"jit", "pjit"}
_STATIC_KWARGS = {"static_argnums", "static_argnames", "donate_argnums"}


def _is_jit_call(node):
    if not isinstance(node, ast.Call):
        return False
    name = qualname(node.func)
    return bool(name) and name.rsplit(".", 1)[-1] in _JIT_LAST_PARTS


def _build_once_guard(parents, node):
    """True when a jit call inside a loop sits under a build-once memo
    guard — ``if f is None: f = jit(g)``, ``if not f: f = jit(g)``, or
    ``if key not in cache: cache[key] = jit(g)`` — so it runs once, not
    per iteration.  The flow-sensitive suppression FLW brings to
    RCP001: the jit result must be bound back to the guarded subject."""
    assign = parents.get(node)
    if not isinstance(assign, ast.Assign) or len(assign.targets) != 1:
        return False
    target = assign.targets[0]
    cur = parents.get(assign)
    while cur is not None and not isinstance(
            cur, (ast.For, ast.While, ast.FunctionDef,
                  ast.AsyncFunctionDef, ast.Lambda)):
        if isinstance(cur, ast.If):
            test = cur.test
            if isinstance(test, ast.UnaryOp) and \
                    isinstance(test.op, ast.Not):
                test_subject = test.operand
                kind = "falsy"
            elif isinstance(test, ast.Compare) and len(test.ops) == 1:
                if isinstance(test.ops[0], ast.Is) and isinstance(
                        test.comparators[0], ast.Constant) and \
                        test.comparators[0].value is None:
                    test_subject = test.left
                    kind = "none"
                elif isinstance(test.ops[0], ast.NotIn):
                    # membership guard: target must index the container
                    if isinstance(target, ast.Subscript):
                        container = qualname(test.comparators[0])
                        indexed = qualname(target.value)
                        if container and container == indexed:
                            return True
                    test_subject = None
                    kind = None
                else:
                    test_subject = None
                    kind = None
            else:
                test_subject = None
                kind = None
            if kind in ("falsy", "none") and test_subject is not None:
                subject = qualname(test_subject)
                bound = qualname(target)
                if subject and subject == bound:
                    return True
        cur = parents.get(cur)
    return False


def _is_constant_static_spec(node, consts):
    """Literal int/str, or a tuple/list of those, possibly via one
    module-level constant indirection."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, str, bool, type(None)))
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_constant_static_spec(e, consts) for e in node.elts)
    if isinstance(node, ast.Name) and node.id in consts:
        return _is_constant_static_spec(consts[node.id], {})
    return False


class RecompileHazardRule(Rule):

    id = "RCP"
    name = "jit recompile hazard"

    def check(self, ctx):
        findings = []
        parents = ctx.parents()
        consts = module_constants(ctx.tree)
        for node in ctx.nodes():
            if not _is_jit_call(node):
                continue
            jit_name = qualname(node.func)
            if in_loop(parents, node) and not _build_once_guard(
                    parents, node):
                findings.append(ctx.finding(
                    "RCP001", "warning", node,
                    "%s(...) constructed inside a loop: every iteration "
                    "builds a fresh callable and recompiles" % jit_name,
                    hint="hoist the jit out of the loop (or cache the "
                         "jitted callable, e.g. functools.lru_cache)"))
            if (any(isinstance(arg, ast.Lambda) for arg in node.args)
                    and enclosing_function(parents, node) is not None):
                findings.append(ctx.finding(
                    "RCP002", "warning", node,
                    "lambda passed to %s inside a function body: a new "
                    "function identity per call defeats the compile "
                    "cache" % jit_name,
                    hint="jit a named module-level function (or cache "
                         "the wrapped callable once)"))
            for kw in node.keywords:
                if (kw.arg in _STATIC_KWARGS
                        and not _is_constant_static_spec(kw.value, consts)):
                    findings.append(ctx.finding(
                        "RCP003", "warning", node,
                        "%s=%s is not a literal constant: a varying or "
                        "unhashable spec poisons the jit cache key"
                        % (kw.arg, ast.unparse(kw.value)[:60]),
                        hint="use a literal tuple of ints/names (hoist "
                             "it to a module-level constant)"))
        return findings
