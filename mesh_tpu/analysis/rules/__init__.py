"""The meshlint rule packs (one module per rule-id family).

``all_rules()`` is the registry the engine and CLI default to; the
``--rules TRC,VMEM`` CLI filter matches on each rule's ``id`` prefix.
See doc/static_analysis.md for the catalog.  The RES/LED/FLW families
are the flow-sensitive layer (per-function CFG + dataflow, analysis/
cfg.py); the rest are pattern rules.
"""

from .trc import TracerLeakRule
from .rcp import RecompileHazardRule
from .vmem import VmemBudgetRule
from .lck import LockDisciplineRule
from .knb import KnobRegistryRule
from .obs import ObservabilityHygieneRule
from .lok import LockOrderRule
from .pal import PallasDmaRule
from .res import ResourcePathRule
from .led import LedgerLifecycleRule
from .flw import FlowSensitiveRule

__all__ = [
    "TracerLeakRule", "RecompileHazardRule", "VmemBudgetRule",
    "LockDisciplineRule", "KnobRegistryRule", "ObservabilityHygieneRule",
    "LockOrderRule", "PallasDmaRule", "ResourcePathRule",
    "LedgerLifecycleRule", "FlowSensitiveRule",
    "all_rules",
]


def all_rules():
    """Fresh instances of every registered rule, in catalog order."""
    return [
        TracerLeakRule(),
        RecompileHazardRule(),
        VmemBudgetRule(),
        LockDisciplineRule(),
        KnobRegistryRule(),
        ObservabilityHygieneRule(),
        LockOrderRule(),
        PallasDmaRule(),
        ResourcePathRule(),
        LedgerLifecycleRule(),
        FlowSensitiveRule(),
    ]
