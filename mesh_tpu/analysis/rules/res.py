"""RES: path-sensitive resource pairing over the per-function CFG.

The pattern rules (LCK/PAL) count sites; this family walks paths.  A
ledger record bound to a local, a manual ``lock.acquire()``, or a
manual ``cm.__enter__()`` must reach its close / release / ``__exit__``
on *every* path out of the function — including the exception edges the
CFG models for any statement that can raise.  PAL004's loop-body
site counting is upgraded here to real per-path balance: a DMA
``start``/``wait`` pair inside a ``fori_loop``/``while_loop`` body must
balance on every branch combination, not merely have equal site counts.

Codes:

- RES001 (error): resource opened here can reach the function's normal
  exit with no close on the path.  The finding carries the CFG path
  witness (the branch sequence proving the leak) into SARIF codeFlows.
- RES002 (error): every normal path closes, but an exception edge
  escapes the function between open and close — the close belongs in a
  ``finally`` (or the resource in a ``with``).
- RES003 (warning): DMA start/wait imbalance on some path through a
  loop-body function (both operations present, but a branch skips one
  side) — the path-sensitive upgrade of PAL004.

Escape hatches keep this conservative: a ledger record that is
returned, yielded, stored into an attribute/container, or passed to
another callable is someone else's to close and is not tracked.
``with``-managed resources never fire (the with IS the pairing), and a
``self.*`` attribute entered inside a method named ``__enter__`` is
the cm-delegation idiom — its ``__exit__`` lives in the sibling
``__exit__`` method, outside this CFG — so it is not tracked either.
"""

import ast

from .common import enclosing_function, qualname
from ..cfg import cfg_for, expr_key
from ..dataflow import find_path, render_witness, solve_forward
from ..engine import Rule

#: any of these substrings in a file skips the whole-file prefilter
_FILE_TOKENS = (".acquire(", "__enter__", ".open(", "make_async_copy")

#: loop constructs whose body callee gets per-path DMA balance checks
_LOOP_WRAPPER_PARTS = {"fori_loop", "while_loop"}

_MAX_CFG_NODES = 600


def _own_exprs(stmt):
    """The expressions a CFG node for ``stmt`` actually evaluates —
    compound statements contribute only their head, and nested defs
    contribute nothing (they are separate CFGs)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return (stmt.test,)
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return (stmt.iter,)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return tuple(i.context_expr for i in stmt.items)
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Try)):
        return ()
    if isinstance(stmt, ast.ExceptHandler):
        return (stmt.type,) if stmt.type is not None else ()
    return (stmt,)


def node_calls(node):
    """Every Call in the expressions this CFG node evaluates."""
    out = []
    for expr in _own_exprs(node.stmt):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                out.append(sub)
    return out


def _receiver_text(call):
    """Dotted text of a ``recv.method(...)`` receiver; calls in the
    chain resolve through their callee (``get_ledger().open`` ->
    ``get_ledger``)."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return ""
    recv = func.value
    if isinstance(recv, ast.Call):
        return qualname(recv.func) or ""
    return qualname(recv) or ""


def _ledgerish(call):
    return "ledger" in _receiver_text(call).lower()


class _Spec(object):
    """One tracked resource: where it opens, how it closes."""

    __slots__ = ("kind", "key", "open_node", "noun", "closer")

    def __init__(self, kind, key, open_node, noun, closer):
        self.kind = kind          # ledger | lock | cm
        self.key = key            # var name or receiver expr key
        self.open_node = open_node
        self.noun = noun          # human text for messages
        self.closer = closer      # human text of the expected close


def _collect_specs(cfg, funcdef):
    specs = []
    for node in cfg.stmt_nodes():
        stmt = node.stmt
        for call in node_calls(node):
            func = call.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr == "open" and _ledgerish(call) and \
                    isinstance(stmt, ast.Assign) and \
                    len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name) and \
                    stmt.value is call:
                var = stmt.targets[0].id
                specs.append(_Spec(
                    "ledger", var, node,
                    "ledger record '%s'" % var, "close"))
            elif func.attr == "acquire":
                key = expr_key(func.value)
                specs.append(_Spec(
                    "lock", key, node,
                    "lock '%s'" % key, "release"))
            elif func.attr == "__enter__":
                key = expr_key(func.value)
                if funcdef.name == "__enter__" and \
                        key.startswith("self."):
                    # delegation idiom: a cm class entering an inner cm
                    # stored on self — the paired __exit__ lives in the
                    # sibling __exit__ method, outside this CFG
                    continue
                specs.append(_Spec(
                    "cm", key, node,
                    "context manager '%s'" % key, "__exit__"))
    return specs


def _close_nodes(cfg, spec):
    """CFG nodes that close this resource (plus, for ledger records,
    escape nodes that transfer ownership — treated as closes so the
    rule stays conservative)."""
    out = set()
    for node in cfg.stmt_nodes():
        if node is spec.open_node:
            continue
        for call in node_calls(node):
            func = call.func
            if not isinstance(func, ast.Attribute):
                continue
            if spec.kind == "ledger" and func.attr == "close" and \
                    _ledgerish(call):
                for arg in call.args[:1]:
                    if isinstance(arg, ast.Name) and arg.id == spec.key:
                        out.add(node)
            elif spec.kind == "lock" and func.attr == "release" and \
                    expr_key(func.value) == spec.key:
                out.add(node)
            elif spec.kind == "cm" and func.attr == "__exit__" and \
                    expr_key(func.value) == spec.key:
                out.add(node)
    return out


def _ledger_escapes(cfg, spec):
    """Does the record var leave this function's custody?  Returns,
    yields, attribute/container stores, deletes, re-binds, or being
    passed as a call argument all count."""
    for node in cfg.stmt_nodes():
        if node is spec.open_node:
            continue
        stmt = node.stmt
        for expr in _own_exprs(stmt):
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Name) and sub.id == spec.key:
                    if isinstance(sub.ctx, (ast.Store, ast.Del)):
                        return True
        if isinstance(stmt, ast.Assign):
            for sub in ast.walk(stmt.value):
                if isinstance(sub, ast.Name) and sub.id == spec.key:
                    return True    # aliased / stored somewhere
        if isinstance(stmt, (ast.Return, ast.Expr)) and isinstance(
                getattr(stmt, "value", None), (ast.Yield, ast.YieldFrom)):
            probe = stmt.value
        elif isinstance(stmt, ast.Return):
            probe = stmt.value
        else:
            probe = None
        if probe is not None:
            for sub in ast.walk(probe):
                if isinstance(sub, ast.Name) and sub.id == spec.key:
                    return True
        for call in node_calls(node):
            func = call.func
            is_close = (isinstance(func, ast.Attribute)
                        and func.attr == "close" and _ledgerish(call))
            if is_close:
                continue
            for arg in list(call.args) + [k.value for k in call.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id == spec.key:
                        return True
    return False


def _candidate_functions(ctx):
    """Functions worth building a CFG for, found in one pass over the
    flat node cache (the PR 13 prefilter pattern)."""
    parents = ctx.parents()
    out = {}
    for node in ctx.nodes():
        if not isinstance(node, ast.Call) or not \
                isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr in ("acquire", "__enter__") or (
                node.func.attr == "open" and _ledgerish(node)):
            fn = enclosing_function(parents, node)
            if fn is not None:
                out[id(fn)] = fn
    return list(out.values())


class ResourcePathRule(Rule):

    id = "RES"
    name = "path-sensitive resource pairing"

    def check(self, ctx):
        findings = []
        if any(tok in ctx.source for tok in _FILE_TOKENS):
            for funcdef in _candidate_functions(ctx):
                findings.extend(self._check_function(ctx, funcdef))
        if "make_async_copy" in ctx.source or (
                ".start(" in ctx.source and ".wait(" in ctx.source):
            findings.extend(self._check_dma_balance(ctx))
        return findings

    # -- RES001 / RES002 ----------------------------------------------

    def _check_function(self, ctx, funcdef):
        cfg = cfg_for(funcdef)
        if len(cfg.nodes) > _MAX_CFG_NODES:
            return
        for spec in _collect_specs(cfg, funcdef):
            if spec.kind == "ledger" and _ledger_escapes(cfg, spec):
                continue
            closes = _close_nodes(cfg, spec)
            if spec.kind in ("lock", "cm") and not closes:
                # acquire with no release anywhere: either LCK001's
                # territory (pattern rule) or a handoff we cannot see;
                # a path witness adds nothing — stay quiet.
                if spec.kind == "lock":
                    continue
            prune = {spec.key}

            def not_own_raise(edge, open_node=spec.open_node):
                # if the open call itself raises, the resource was
                # never acquired — that edge is not a leak path
                return not (edge.src is open_node
                            and edge.kind in ("raise", "except",
                                              "finally"))

            path = find_path(
                cfg, spec.open_node, lambda n: n is cfg.exit,
                avoid=closes, prune_none_of=prune,
                edge_filter=not_own_raise)
            if path is not None:
                yield self._leak(ctx, funcdef, cfg, spec, path,
                                 "RES001",
                                 "can reach the function exit with no "
                                 "%s on the path" % spec.closer,
                                 "close/release on every branch (or "
                                 "hand the resource to a with-block)")
                continue
            path = find_path(
                cfg, spec.open_node, lambda n: n is cfg.raise_exit,
                avoid=closes, prune_none_of=prune,
                edge_filter=not_own_raise)
            if path is not None:
                yield self._leak(ctx, funcdef, cfg, spec, path,
                                 "RES002",
                                 "is closed on the normal path but "
                                 "leaks when an exception escapes "
                                 "before the %s" % spec.closer,
                                 "move the %s into a finally (or use "
                                 "a with-block)" % spec.closer)

    def _leak(self, ctx, funcdef, cfg, spec, path, code, what, hint):
        finding = ctx.finding(
            code, "error", spec.open_node.stmt,
            "%s opened in '%s' %s" % (spec.noun, funcdef.name, what),
            hint=hint)
        finding.witness = render_witness(ctx, spec.open_node, path)
        return finding

    # -- RES003: DMA start/wait path balance --------------------------

    def _check_dma_balance(self, ctx):
        findings = []
        loop_bodies = set()
        defs = {}
        for node in ctx.nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
            elif isinstance(node, ast.Call):
                name = qualname(node.func)
                if name and name.rsplit(".", 1)[-1] in \
                        _LOOP_WRAPPER_PARTS:
                    for arg in node.args:
                        inner = qualname(arg)
                        if inner and "." not in inner:
                            loop_bodies.add(inner)
        for name in sorted(loop_bodies):
            funcdef = defs.get(name)
            if funcdef is None:
                continue
            findings.extend(self._balance_one(ctx, funcdef))
        return findings

    def _balance_one(self, ctx, funcdef):
        cfg = cfg_for(funcdef)
        if len(cfg.nodes) > _MAX_CFG_NODES:
            return
        # family -> {node: (starts, waits)}
        families = {}
        for node in cfg.stmt_nodes():
            for call in node_calls(node):
                func = call.func
                if not isinstance(func, ast.Attribute) or \
                        func.attr not in ("start", "wait"):
                    continue
                recv = func.value
                key = expr_key(recv)
                if isinstance(recv, ast.Call):
                    inner = qualname(recv.func) or ""
                    if "make_async_copy" not in inner:
                        continue
                    key = ast.dump(recv)
                fam = families.setdefault(key, {})
                s, w = fam.get(node, (0, 0))
                if func.attr == "start":
                    fam[node] = (s + 1, w)
                else:
                    fam[node] = (s, w + 1)
        for key, sites in sorted(families.items()):
            starts = sum(s for s, _ in sites.values())
            waits = sum(w for _, w in sites.values())
            if not starts or not waits:
                continue    # one-sided prefetch idiom: PAL's call

            def transfer(node, state, sites=sites):
                s, w = sites.get(node, (0, 0))
                delta = s - w
                if not delta:
                    return state
                return frozenset(
                    max(-3, min(3, d + delta)) for d in state)

            exit_state = solve_forward(
                cfg, frozenset([0]), transfer,
                lambda a, b: a | b).get(cfg.exit, frozenset([0]))
            if any(d != 0 for d in exit_state):
                first = min(sites, key=lambda n: n.line)
                yield ctx.finding(
                    "RES003", "warning", first.stmt,
                    "DMA start/wait on '%s' is unbalanced on some path "
                    "through loop body '%s' (a branch skips one side)"
                    % (key, funcdef.name),
                    hint="start and wait the descriptor on every "
                         "branch, or hoist the conditional out of "
                         "the loop body")
