"""FLW: flow-sensitive upgrades of the TRC/RCP families.

The pattern rules reason name-locally: TRC004 flags ``float(x)`` when
``x`` is a traced parameter even if every path rebinds ``x`` to a host
value first, and misses ``y = jnp.sum(x); float(y)`` entirely because
``y`` is not a parameter.  This family runs reaching definitions over
the per-function CFG to close both gaps:

- FLW001 (warning): ``float()``/``int()``/``bool()`` on a local whose
  reaching definitions include a device-derived value (a ``jnp``/
  ``jax``/``lax``/``pl`` call or an expression over traced parameters)
  inside traced code — the leak TRC004's parameter-only view misses.
- FLW002 (warning): ``.item()``/``.tolist()`` inside a host-side loop
  on a value produced by a jitted callable in that same loop — one
  device->host sync per iteration from the *caller* side, invisible to
  TRC because the loop body is not traced.

The exported helpers are the suppression side of the same analysis:
``all_host_redefined`` lets TRC004 stay quiet when every reaching
definition of the parameter is a proven host value (the measured
false-positive reduction), without touching TRC's own structure.
"""

import ast

from .common import in_loop, qualname
from .trc import (_DEVICE_CALL_ROOTS, _is_dynamic, _param_names,
                  _traced_functions, _traced_roots)
from ..cfg import EXTRA_CACHES, cfg_for
from ..dataflow import PARAM, ReachingDefs
from ..engine import Rule

_RD_CACHE = {}
EXTRA_CACHES.append(_RD_CACHE)


def _analysis_for(funcdef):
    """(cfg, ReachingDefs) for a function, cached per function object
    for the lifetime of the run (TRC suppression + FLW share it)."""
    hit = _RD_CACHE.get(id(funcdef))
    if hit is not None and hit[0] is funcdef:
        return hit[1], hit[2]
    cfg = cfg_for(funcdef)
    rd = ReachingDefs(cfg)
    _RD_CACHE[id(funcdef)] = (funcdef, cfg, rd)
    return cfg, rd


def _stmt_node_of(cfg, parents, ast_node):
    """The CFG node whose statement contains ``ast_node``, or None."""
    index = {id(n.stmt): n for n in cfg.stmt_nodes()}
    cur = ast_node
    while cur is not None:
        hit = index.get(id(cur))
        if hit is not None:
            return hit
        cur = parents.get(cur)
    return None


def _def_rhs(def_node):
    """RHS expression of a defining CFG node, when it is a plain
    single-target assignment; None otherwise (for-targets, with-as,
    augmented — treated as opaque)."""
    stmt = def_node.stmt
    if isinstance(stmt, ast.Assign):
        return stmt.value
    return None


def _device_rhs(rhs, params):
    if rhs is None:
        return False
    if isinstance(rhs, ast.Call):
        root = qualname(rhs.func)
        if root and root.split(".", 1)[0] in _DEVICE_CALL_ROOTS:
            return True
    return _is_dynamic(rhs, params)


def all_host_redefined(funcdef, parents, use_node, name, params):
    """True when every definition of ``name`` reaching ``use_node`` is
    a provable host value — i.e. the traced parameter binding cannot
    reach this use.  TRC004's suppression hook."""
    cfg, rd = _analysis_for(funcdef)
    node = _stmt_node_of(cfg, parents, use_node)
    if node is None:
        return False
    defs = rd.at(node).get(name)
    if not defs or PARAM in defs:
        return False
    for d in defs:
        rhs = _def_rhs(d)
        if rhs is None or _device_rhs(rhs, params):
            return False
    return True


def _device_defined(funcdef, parents, use_node, name, params):
    """Some reaching definition of ``name`` is device-derived."""
    cfg, rd = _analysis_for(funcdef)
    node = _stmt_node_of(cfg, parents, use_node)
    if node is None:
        return False
    defs = rd.at(node).get(name)
    if not defs:
        return False
    for d in defs:
        if d == PARAM:
            continue
        if _device_rhs(_def_rhs(d), params):
            return True
    return False


class FlowSensitiveRule(Rule):

    id = "FLW"
    name = "flow-sensitive tracer/host-sync upgrades"

    def check(self, ctx):
        findings = []
        source = ctx.source
        traced = []
        if "float(" in source or "int(" in source or "bool(" in source \
                or ".item(" in source or ".tolist(" in source:
            traced = _traced_functions(ctx.nodes())
        if traced:
            findings.extend(self._check_traced(ctx, traced))
        if ".item(" in source or ".tolist(" in source:
            findings.extend(self._check_host_loops(ctx, traced))
        return findings

    # -- FLW001: device-derived local crosses to host in traced code --

    def _check_traced(self, ctx, traced):
        parents = ctx.parents()
        for funcdef, spec in traced:
            params = _param_names(funcdef, spec)
            for node in ast.walk(funcdef):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int", "bool")
                        and len(node.args) == 1
                        and isinstance(node.args[0], ast.Name)):
                    continue
                name = node.args[0].id
                if name in params:
                    continue    # TRC004's case (possibly suppressed)
                if _device_defined(funcdef, parents, node, name,
                                   params):
                    yield ctx.finding(
                        "FLW001", "warning", node,
                        "%s() on '%s' inside traced '%s': a reaching "
                        "definition is device-derived, so this is a "
                        "tracer leak TRC004's parameter-only view "
                        "misses" % (node.func.id, name, funcdef.name),
                        hint="keep the value as a jnp array (or "
                             "rebind it to a host value on every "
                             "path first)")

    # -- FLW002: per-iteration host sync on jitted results ------------

    def _check_host_loops(self, ctx, traced):
        parents = ctx.parents()
        traced_ids = {id(fd) for fd, _ in traced}
        roots = set(_traced_roots(ctx.nodes()))
        if not roots:
            return
        for node in ctx.nodes():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("item", "tolist")
                    and isinstance(node.func.value, ast.Name)
                    and not node.args):
                continue
            if not in_loop(parents, node):
                continue
            funcdef = self._enclosing_def(parents, node)
            if funcdef is None or id(funcdef) in traced_ids:
                continue    # traced code is TRC001's territory
            name = node.func.value.id
            cfg, rd = _analysis_for(funcdef)
            cnode = _stmt_node_of(cfg, parents, node)
            if cnode is None:
                continue
            defs = rd.at(cnode).get(name, ())
            for d in defs:
                if d == PARAM:
                    continue
                rhs = _def_rhs(d)
                if isinstance(rhs, ast.Call):
                    callee = qualname(rhs.func)
                    if callee and "." not in callee and \
                            callee in roots:
                        yield ctx.finding(
                            "FLW002", "warning", node,
                            ".%s() on '%s' inside a host loop syncs "
                            "the device once per iteration ('%s' is "
                            "produced by jitted '%s')"
                            % (node.func.attr, name, name, callee),
                            hint="accumulate on device and sync once "
                                 "after the loop")
                        break

    @staticmethod
    def _enclosing_def(parents, node):
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None
