"""LED: request-lifecycle completeness for the latency ledger.

The ledger contract (doc/observability.md): every admitted request's
:class:`RequestRecord` reaches exactly one ``close()`` carrying an
outcome label from the documented ``LEDGER_OUTCOMES`` set, on *every*
path — cancelled, deadline-expired, errored, or shut down mid-queue.
A missed close silently drops the request from every stage breakdown
and the incident ring; a bogus outcome label splinters the breakdown
cardinality.

Scope is behavioral, not path-list based: the checks engage wherever
records are *owned* — methods of any class that opens ledger records,
plus any function that closes one — which today means
``serve/service.py`` (opener) with ``serve/deadline.py`` and
``engine/executor.py`` stamping but never owning (so they cannot
false-fire).  Callee effects ride PR 12's interprocedural call graph:
a call to a function that (transitively) closes the record counts as a
close point on the path.

Codes:

- LED001 (error): a path that completes a request future
  (``set_result`` / ``set_exception`` / ``cancel``) with no ledger
  close anywhere on it, while a record can exist (``if record is not
  None`` guard edges prune the record-absent paths).  Carries the CFG
  path witness.
- LED002 (error): a close site's outcome label — literal, conditional
  literal, or a variable whose reaching definitions are all literals —
  is not in the documented ``LEDGER_OUTCOMES`` set.
- LED003 (error): an outcome in ``LEDGER_OUTCOMES`` is not documented
  (backticked) in doc/observability.md — same contract OBS005 enforces
  for stage names.
- LED004 (warning): one path can close the same record twice with no
  rebinding in between (loops excluded: a back edge means a new
  record).
"""

import ast

from .common import enclosing_function, qualname
from ..cfg import cfg_for, expr_key
from ..dataflow import PARAM, ReachingDefs, find_path, render_witness
from ..engine import Finding, Rule
from .res import node_calls, _ledgerish

#: fallback when no LEDGER_OUTCOMES assignment exists in the scanned
#: tree (single-file fixtures); obs/ledger.py owns the canonical tuple
_DEFAULT_OUTCOMES = ("ok", "cancelled", "deadline", "error", "shutdown")

_COMPLETION_ATTRS = ("set_result", "set_exception", "cancel")


def collect_ledger_outcomes(project):
    """(values, relpath, lineno) from the first ``LEDGER_OUTCOMES =
    (...)`` tuple-of-string-literals in the tree, name-keyed like
    obs.collect_ledger_stages so a moved definition stays covered."""
    for ctx in project.contexts:
        for node in ctx.nodes():
            if not isinstance(node, ast.Assign):
                continue
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if "LEDGER_OUTCOMES" not in targets:
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            values = tuple(
                elt.value for elt in node.value.elts
                if isinstance(elt, ast.Constant)
                and isinstance(elt.value, str))
            if values:
                return values, ctx.relpath, node.lineno
    return None


def _is_ledger_close(call):
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "close" and _ledgerish(call))


def _is_ledger_open(call):
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "open" and _ledgerish(call))


def _is_completion(call):
    """A future being completed: ``*.future.set_result(...)`` etc. —
    receiver spelling must mention fut/future so dict ``cancel`` or
    file ``close`` lookalikes stay out."""
    func = call.func
    if not isinstance(func, ast.Attribute) or \
            func.attr not in _COMPLETION_ATTRS:
        return False
    recv = qualname(func.value) or ""
    last = recv.rsplit(".", 1)[-1].lower()
    return "fut" in last


def _close_record_keys(cfg):
    """Expr keys of the record arguments at direct close sites — these
    drive the ``is None`` guard-edge pruning."""
    keys = set()
    for node in cfg.stmt_nodes():
        for call in node_calls(node):
            if _is_ledger_close(call) and call.args:
                keys.add(expr_key(call.args[0]))
    return keys


class LedgerLifecycleRule(Rule):

    id = "LED"
    name = "ledger request-lifecycle completeness"

    _inter = None

    def finalize(self, project):
        findings = []
        contract = collect_ledger_outcomes(project)
        outcomes = contract[0] if contract else _DEFAULT_OUTCOMES
        closers = self._may_closers(project)
        for ctx in project.contexts:
            if ".close(" not in ctx.source and \
                    ".open(" not in ctx.source:
                continue
            findings.extend(
                self._check_file(ctx, outcomes, closers))
        if contract:
            findings.extend(self._check_doc(project, contract))
        return findings

    # -- callee close effects (PR 12 interprocedural graph) -----------

    def _may_closers(self, project):
        """Function keys that (transitively) may close a ledger record,
        propagated backwards over the interprocedural call graph."""
        inter = project.interproc()
        direct = set()
        for key, fn in inter.functions.items():
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call) and _is_ledger_close(node):
                    direct.add(key)
                    break
        closers = set(direct)
        changed = True
        while changed:
            changed = False
            for key, summary in inter.summaries.items():
                if key in closers:
                    continue
                if any(callee in closers
                       for callee, _, _ in summary.calls):
                    closers.add(key)
                    changed = True
        self._inter = inter
        return closers

    # -- per-file checks ----------------------------------------------

    def _opener_classes(self, ctx):
        names = set()
        parents = ctx.parents()
        for node in ctx.nodes():
            if isinstance(node, ast.Call) and _is_ledger_open(node):
                p = parents.get(node)
                while p is not None:
                    if isinstance(p, ast.ClassDef):
                        names.add(p.name)
                        break
                    p = parents.get(p)
        return names

    def _check_file(self, ctx, outcomes, closers):
        parents = ctx.parents()
        opener_classes = self._opener_classes(ctx)
        seen = set()
        for node in ctx.nodes():
            if not isinstance(node, ast.Call):
                continue
            if not (_is_ledger_close(node) or _is_completion(node)):
                continue
            funcdef = enclosing_function(parents, node)
            if funcdef is None or id(funcdef) in seen:
                continue
            seen.add(id(funcdef))
            in_opener = False
            p = parents.get(funcdef)
            while p is not None:
                if isinstance(p, ast.ClassDef):
                    in_opener = p.name in opener_classes
                    break
                p = parents.get(p)
            has_close = any(
                isinstance(n, ast.Call) and _is_ledger_close(n)
                for n in ast.walk(funcdef))
            if not (in_opener or has_close):
                continue
            yield from self._check_function(
                ctx, funcdef, outcomes, closers)

    def _check_function(self, ctx, funcdef, outcomes, closers):
        cfg = cfg_for(funcdef)
        close_nodes = {}
        completion_nodes = {}
        for node in cfg.stmt_nodes():
            for call in node_calls(node):
                if _is_ledger_close(call):
                    close_nodes[node] = call
                elif _is_completion(call):
                    completion_nodes[node] = call
                elif self._calls_closer(ctx, funcdef, call, closers):
                    close_nodes.setdefault(node, None)
        guard_keys = _close_record_keys(cfg)
        rd = None
        # LED002: outcome labels at direct close sites
        for node, call in sorted(
                close_nodes.items(), key=lambda kv: kv[0].line):
            if call is None:
                continue
            if rd is None and any(
                    isinstance(a, ast.Name)
                    for a in self._outcome_exprs(call)):
                rd = ReachingDefs(cfg)
            for label in self._resolve_outcomes(call, rd, node):
                if label not in outcomes:
                    yield ctx.finding(
                        "LED002", "error", call,
                        "close() outcome %r in '%s' is not in the "
                        "documented outcome set %s"
                        % (label, funcdef.name, list(outcomes)),
                        hint="use a documented label (or extend "
                             "LEDGER_OUTCOMES + doc/observability.md)")
        # LED001: completion with no close on the path
        avoid = set(close_nodes)
        for node, call in sorted(
                completion_nodes.items(), key=lambda kv: kv[0].line):
            if node in avoid:
                continue
            head = find_path(
                cfg, cfg.entry, lambda n, node=node: n is node,
                avoid=avoid, prune_none_of=guard_keys)
            if head is None:
                continue
            tail = find_path(
                cfg, node, lambda n: n is cfg.exit,
                avoid=avoid, prune_none_of=guard_keys)
            if tail is None:
                continue
            finding = ctx.finding(
                "LED001", "error", call,
                "request future completed in '%s' on a path with no "
                "ledger close — the record never reaches the stage "
                "histogram or the incident ring" % funcdef.name,
                hint="close the record (with an outcome label) on "
                     "every completion path")
            finding.witness = render_witness(ctx, cfg.entry,
                                             head + tail)
            yield finding
        # LED004: double close of one record expr on one path
        direct = [(n, c) for n, c in close_nodes.items()
                  if c is not None and c.args]
        for i, (n1, c1) in enumerate(direct):
            k1 = expr_key(c1.args[0])
            for n2, c2 in direct:
                if n2 is n1 or expr_key(c2.args[0]) != k1:
                    continue
                rebinds = self._rebind_nodes(cfg, c1.args[0])
                path = find_path(
                    cfg, n1, lambda n, n2=n2: n is n2,
                    avoid=rebinds - {n1, n2},
                    edge_filter=lambda e: e.kind != "back",
                )
                if path is not None:
                    yield ctx.finding(
                        "LED004", "warning", c2.args[0],
                        "record '%s' can be closed twice on one path "
                        "through '%s' (double ring-append skews the "
                        "breakdown)" % (k1, funcdef.name),
                        hint="make the closes mutually exclusive or "
                             "guard the second with a closed flag")

    def _calls_closer(self, ctx, funcdef, call, closers):
        if not closers or self._inter is None:
            return False
        fn = None
        for key, info in self._inter.functions.items():
            if info.node is funcdef:
                fn = info
                break
        if fn is None:
            return False
        callee = self._inter._resolve_call(fn, call)
        return callee in closers

    @staticmethod
    def _outcome_exprs(call):
        out = []
        if len(call.args) >= 2:
            out.append(call.args[1])
        for kw in call.keywords:
            if kw.arg == "outcome":
                out.append(kw.value)
        return out

    def _resolve_outcomes(self, call, rd, node):
        """Every string the outcome argument can statically be; empty
        when unresolvable (conservative silence)."""
        labels = set()
        for expr in self._outcome_exprs(call):
            labels |= self._expr_strings(expr, rd, node)
        return sorted(labels)

    def _expr_strings(self, expr, rd, node, depth=0):
        if depth > 3:
            return set()
        if isinstance(expr, ast.Constant) and \
                isinstance(expr.value, str):
            return {expr.value}
        if isinstance(expr, ast.IfExp):
            return (self._expr_strings(expr.body, rd, node, depth + 1)
                    | self._expr_strings(expr.orelse, rd, node,
                                         depth + 1))
        if isinstance(expr, ast.Name) and rd is not None:
            defs = rd.at(node).get(expr.id)
            if not defs or PARAM in defs:
                return set()
            out = set()
            for d in defs:
                stmt = d.stmt
                if isinstance(stmt, ast.Assign):
                    got = self._expr_strings(stmt.value, rd, d,
                                             depth + 1)
                    if not got:
                        return set()    # one opaque def: give up
                    out |= got
                else:
                    return set()
            return out
        return set()

    def _rebind_nodes(self, cfg, record_expr):
        """Nodes that rebind the record expression's root name — a
        close after a rebind is a different record."""
        if isinstance(record_expr, ast.Name):
            root = record_expr.id
        else:
            q = qualname(record_expr)
            root = q.split(".", 1)[0] if q else None
        if root is None:
            return set()
        out = set()
        for node in cfg.stmt_nodes():
            stmt = node.stmt
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name) and sub.id == root:
                            out.add(node)
        return out

    # -- LED003: doc coverage of the outcome contract -----------------

    def _check_doc(self, project, contract):
        values, relpath, lineno = contract
        doc = project.doc_text("doc", "observability.md")
        if doc is None:
            return
        for outcome in values:
            if "`%s`" % outcome not in doc:
                yield Finding(
                    "LED003", "error", relpath, lineno,
                    "ledger outcome '%s' is not documented in "
                    "doc/observability.md" % outcome,
                    hint="add it to the outcome-label table (the "
                         "LED/OBS doc-coverage contract)")
