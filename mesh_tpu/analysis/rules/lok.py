"""LOK: interprocedural lock-order / deadlock analysis.

Consumes the cross-module model from ``project.interproc()`` (see
``analysis/interproc.py``): every module/instance/local lock, and the
global acquisition-order graph built by propagating held-lock context
through resolved calls.

========  ========  =====================================================
code      severity  fires on
========  ========  =====================================================
LOK001    error     a cycle in the global lock acquisition-order graph
                    (including re-acquisition of a non-reentrant lock)
LOK002    warning   a blocking call (file I/O, ``join``, ``subprocess``,
                    ``os.rename``/``replace``, ``sleep`` ...) made while
                    holding a lock — directly or through any resolved
                    call chain — unless allowlisted in
                    ``doc/concurrency.md``
LOK003    error     an observed acquisition edge that contradicts the
                    canonical lock order declared in
                    ``doc/concurrency.md``
LOK004    warning   a cross-subsystem acquisition edge whose locks are
                    not (both) declared in the canonical order table
LOK005    warning   a canonical-order entry naming a lock the analysis
                    no longer discovers (stale doc)
========  ========  =====================================================

The canonical order and the blocking allowlist live in
``doc/concurrency.md``:

- the **Canonical lock order** section is scanned for backticked lock
  names (``path.py:QualifiedName``) in declaration order — earlier
  means "acquired first" (outermost);
- the **Blocking-under-lock allowlist** section is scanned for table
  rows whose first backticked tokens are a lock name, a call name
  (last dotted part, or ``*`` for any call under that lock), and
  optionally the function qualname the blocking call lives in — a
  site-scoped entry keeps the *rest* of the locked region checked,
  which is how PR 11's "persist back outside the cache lock" stays a
  machine-checked invariant rather than a wildcard.

Messages are deliberately line-free (function qualnames, not line
numbers) so baseline fingerprints survive unrelated edits — same
contract as every other family.
"""

import re

from ..engine import Rule

__all__ = ["LockOrderRule", "parse_concurrency_doc", "validate_witness"]

#: doc/concurrency.md section headers the parser anchors on
_ORDER_HEADER = "canonical lock order"
_ALLOW_HEADER = "blocking-under-lock allowlist"

_BACKTICK = re.compile(r"`([^`]+)`")

#: a lock name as written in the doc: path.py:Qualified.Name
_LOCK_TOKEN = re.compile(r"^[\w/.-]+\.py:[\w.]+$")


def parse_concurrency_doc(text):
    """(order, allow) from doc/concurrency.md text.

    ``order`` maps lock display name -> rank (0 = outermost);
    ``allow`` is a set of (lock display name, call last-part or "*",
    site qualname or "*").
    """
    order, allow = {}, set()
    if not text:
        return order, allow
    section = None
    for line in text.splitlines():
        if line.startswith("#"):
            title = line.lstrip("#").strip().lower()
            if _ORDER_HEADER in title:
                section = "order"
            elif _ALLOW_HEADER in title:
                section = "allow"
            else:
                section = None
            continue
        tokens = [t for t in _BACKTICK.findall(line)]
        if section == "order":
            for token in tokens:
                if _LOCK_TOKEN.match(token) and token not in order:
                    order[token] = len(order)
        elif section == "allow" and len(tokens) >= 2 \
                and _LOCK_TOKEN.match(tokens[0]):
            site = tokens[2] if len(tokens) >= 3 else "*"
            allow.add((tokens[0], tokens[1], site))
    return order, allow


def _family(lock_name):
    """Subsystem of a lock display name: second path component
    (``mesh_tpu/store/...`` -> ``store``), or the filename for
    top-level modules."""
    path = lock_name.split(":", 1)[0]
    parts = path.split("/")
    return parts[1] if len(parts) > 2 else parts[-1]


class LockOrderRule(Rule):
    id = "LOK"
    name = "interprocedural lock order"

    def finalize(self, project):
        graph = project.interproc()
        order, allow = parse_concurrency_doc(
            project.doc_text("doc", "concurrency.md"))
        findings = []
        findings.extend(self._cycles(project, graph))
        findings.extend(self._blocking(project, graph, allow))
        findings.extend(self._declared_order(project, graph, order))
        return findings

    # -- LOK001: cycles ------------------------------------------------

    def _cycles(self, project, graph, _rule="LOK001"):
        from ..engine import Finding

        findings = []
        for scc in graph.cycles():
            names = [graph.locks[k].name for k in scc]
            # anchor at the lexically first witness edge inside the SCC
            witness = min(
                (e for (s, d), e in graph.edges.items()
                 if s in scc and d in scc),
                key=lambda e: (e.relpath, e.lineno))
            if len(scc) == 1:
                message = ("non-reentrant lock %s can be re-acquired "
                           "on the same thread (%s)" % (
                               names[0], witness.via))
            else:
                message = ("lock-order cycle between %s (%s)" % (
                    " <-> ".join(sorted(names)), witness.via))
            findings.append(Finding(
                _rule, "error", witness.relpath, witness.lineno, message,
                hint="break the cycle: pick one order, document it in "
                     "doc/concurrency.md, and release before crossing"))
        return findings

    # -- LOK002: blocking calls under a lock ---------------------------

    def _blocking(self, project, graph, allow):
        from ..engine import Finding

        findings = []
        seen = set()

        def allowed(lock_name, desc, site):
            last = desc.rsplit(".", 1)[-1]
            for call in (last, desc, "*"):
                for where in (site, "*"):
                    if (lock_name, call, where) in allow:
                        return True
            return False

        for key, summary in sorted(graph.summaries.items()):
            fn = graph.functions[key]
            for desc, held, lineno in summary.blocking:
                if not held:
                    continue
                lock = graph.locks[held[-1]].name
                dedup = (lock, desc, fn.qualname)
                if dedup in seen or allowed(lock, desc, fn.qualname):
                    continue
                seen.add(dedup)
                findings.append(Finding(
                    "LOK002", "warning", fn.relpath, lineno,
                    "blocking call `%s` while holding %s (in %s)" % (
                        desc, lock, fn.qualname),
                    hint="move the blocking work outside the lock, or "
                         "allowlist it with a reason in "
                         "doc/concurrency.md"))
            for callee, held, lineno in summary.calls:
                if not held:
                    continue
                lock = graph.locks[held[-1]].name
                callee_fn = graph.functions[callee]
                for desc, site in graph.blocking_reach.get(callee, ()):
                    dedup = (lock, desc, site)
                    if dedup in seen or allowed(lock, desc, site):
                        continue
                    seen.add(dedup)
                    findings.append(Finding(
                        "LOK002", "warning", fn.relpath, lineno,
                        "holding %s, call to %s() reaches blocking "
                        "`%s` (in %s)" % (
                            lock, callee_fn.qualname, desc, site),
                        hint="hoist the call out of the locked region, "
                             "or allowlist it with a reason in "
                             "doc/concurrency.md"))
        return findings

    # -- LOK003/4/5: the declared canonical order ----------------------

    def _declared_order(self, project, graph, order):
        from ..engine import Finding

        findings = []
        if not order:
            return findings    # no doc (fixture runs) — nothing to check
        known = {info.name for info in graph.locks.values()}
        scanned = {ctx.relpath for ctx in project.contexts}
        for name in sorted(order):
            # partial runs (--changed) can't judge staleness for files
            # they never parsed — only report when the file was scanned
            if name.split(":", 1)[0] not in scanned:
                continue
            if name not in known:
                findings.append(Finding(
                    "LOK005", "warning", "doc/concurrency.md", 0,
                    "canonical order lists %s but no such lock is "
                    "discovered" % name,
                    hint="update doc/concurrency.md after moving or "
                         "removing a lock"))
        seen_undeclared = set()
        for (src, dst), edge in sorted(graph.edges.items()):
            if src == dst:
                continue    # LOK001 owns self-edges
            src_name = graph.locks[src].name
            dst_name = graph.locks[dst].name
            if src_name in order and dst_name in order:
                if order[src_name] > order[dst_name]:
                    findings.append(Finding(
                        "LOK003", "error", edge.relpath, edge.lineno,
                        "%s is acquired while holding %s, against the "
                        "canonical order in doc/concurrency.md (%s)" % (
                            dst_name, src_name, edge.via),
                        hint="acquire in the declared order or update "
                             "the canonical table (with review)"))
            elif _family(src_name) != _family(dst_name):
                dedup = (src_name, dst_name)
                if dedup in seen_undeclared:
                    continue
                seen_undeclared.add(dedup)
                missing = [n for n in (src_name, dst_name)
                           if n not in order]
                findings.append(Finding(
                    "LOK004", "warning", edge.relpath, edge.lineno,
                    "cross-subsystem acquisition %s -> %s is not "
                    "declared in doc/concurrency.md (%s undeclared; "
                    "%s)" % (src_name, dst_name,
                             " and ".join(missing), edge.via),
                    hint="add the lock(s) to the canonical order table "
                         "in doc/concurrency.md"))
        return findings


# -- witness cross-check (mesh-tpu lint --witness) ----------------------

def validate_witness(project, witness_edges):
    """Cross-check dynamically recorded acquisition edges against the
    static graph and the declared canonical order.

    ``witness_edges``: iterable of ((src_path, src_line),
    (dst_path, dst_line), count) from the runtime lock witness.

    Returns a dict: ``ok`` (bool), ``problems`` (list of strings —
    order contradictions and cycles introduced by dynamic edges),
    ``dynamic_only`` (edges the static analysis missed — informational:
    name-level resolution can't see every dynamic dispatch),
    ``unknown_sites`` (creation sites not matching any discovered
    lock), ``checked`` (count of validated edges).
    """
    graph = project.interproc()
    order, _ = parse_concurrency_doc(
        project.doc_text("doc", "concurrency.md"))
    problems, dynamic_only, unknown = [], [], []
    combined = {(s, d) for (s, d) in graph.edges}
    checked = 0
    for (src_site, dst_site, count) in witness_edges:
        src = graph.lock_by_site(*src_site)
        dst = graph.lock_by_site(*dst_site)
        if src is None or dst is None:
            for site, info in ((src_site, src), (dst_site, dst)):
                if info is None:
                    unknown.append("%s:%d" % site)
            continue
        checked += 1
        if src.key == dst.key:
            continue    # per-site aggregation can't split instances
        if (src.key, dst.key) not in combined:
            dynamic_only.append(
                "%s -> %s (seen %dx at runtime, not in the static "
                "graph)" % (src.name, dst.name, count))
            combined.add((src.key, dst.key))
        if src.name in order and dst.name in order \
                and order[src.name] > order[dst.name]:
            problems.append(
                "witnessed acquisition %s -> %s contradicts the "
                "canonical order in doc/concurrency.md" % (
                    src.name, dst.name))
    # cycle check over static + dynamic union
    adj = {}
    for (s, d) in combined:
        adj.setdefault(s, set()).add(d)
    state = {}

    def has_cycle(v, path):
        state[v] = 1
        for w in adj.get(v, ()):
            if state.get(w) == 1:
                names = [graph.locks[k].name for k in path + [w]]
                problems.append(
                    "combined static+dynamic graph has a lock-order "
                    "cycle: %s" % " -> ".join(names))
                return True
            if state.get(w) is None and has_cycle(w, path + [w]):
                return True
        state[v] = 2
        return False

    for v in sorted(adj):
        if state.get(v) is None and has_cycle(v, [v]):
            break
    return {
        "ok": not problems,
        "problems": problems,
        "dynamic_only": sorted(set(dynamic_only)),
        "unknown_sites": sorted(set(unknown)),
        "checked": checked,
    }
