"""Shared AST helpers for the meshlint rule packs (stdlib-only)."""

import ast

__all__ = [
    "qualname", "decorator_names", "enclosing_function", "in_loop",
    "module_constants", "ConstEnv",
]


def qualname(node):
    """Dotted name of a Name/Attribute chain (``jax.jit``, ``self.x``),
    or None for anything not a plain chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def decorator_names(funcdef):
    """Flattened decorator name list; ``functools.partial(jax.jit, ...)``
    and decorator-factory calls (``jax.jit(static_argnums=...)``)
    contribute their callee's name too."""
    names = []
    for deco in funcdef.decorator_list:
        if isinstance(deco, ast.Call):
            base = qualname(deco.func)
            if base:
                names.append(base)
            if base and base.rsplit(".", 1)[-1] == "partial":
                for arg in deco.args[:1]:
                    inner = qualname(arg)
                    if inner:
                        names.append(inner)
        else:
            name = qualname(deco)
            if name:
                names.append(name)
    return names


def enclosing_function(parents, node):
    """Nearest enclosing FunctionDef/AsyncFunctionDef, or None."""
    node = parents.get(node)
    while node is not None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
        node = parents.get(node)
    return None


def in_loop(parents, node):
    """True when ``node`` sits under a For/While/comprehension without a
    function boundary in between (i.e. the loop re-executes it)."""
    node = parents.get(node)
    while node is not None:
        if isinstance(node, (ast.For, ast.While, ast.comprehension,
                             ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return False
        node = parents.get(node)
    return False


def module_constants(tree):
    """{name: constant-node} for simple module-level ``NAME = literal``
    assignments (the ``FOO_ENV = "MESH_TPU_FOO"`` idiom)."""
    out = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
            if isinstance(target, ast.Name):
                out[target.id] = stmt.value
    return out


class ConstEnv(object):
    """Best-effort integer/float resolver for tile-shape expressions:
    literals, module-level constants, the enclosing function's keyword
    defaults, and +,-,*,//,/ over those.  ``resolve`` returns None for
    anything it cannot prove."""

    def __init__(self, tree, func=None):
        self._values = {}
        for name, node in module_constants(tree).items():
            if isinstance(node, ast.Constant) and isinstance(
                    node.value, (int, float)) and not isinstance(
                    node.value, bool):
                self._values[name] = node.value
        if func is not None:
            args = func.args
            positional = args.posonlyargs + args.args
            defaults = args.defaults
            for arg, default in zip(positional[len(positional)
                                               - len(defaults):], defaults):
                self._maybe_bind(arg.arg, default)
            for arg, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None:
                    self._maybe_bind(arg.arg, default)

    def _maybe_bind(self, name, node):
        if isinstance(node, ast.Constant) and isinstance(
                node.value, (int, float)) and not isinstance(
                node.value, bool):
            self._values[name] = node.value

    def resolve(self, node):
        if isinstance(node, ast.Constant):
            value = node.value
            if isinstance(value, (int, float)) and not isinstance(
                    value, bool):
                return value
            return None
        if isinstance(node, ast.Name):
            return self._values.get(node.id)
        if isinstance(node, ast.UnaryOp) and isinstance(
                node.op, ast.USub):
            value = self.resolve(node.operand)
            return None if value is None else -value
        if isinstance(node, ast.BinOp):
            left = self.resolve(node.left)
            right = self.resolve(node.right)
            if left is None or right is None:
                return None
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.FloorDiv) and right:
                return left // right
            if isinstance(node.op, ast.Div) and right:
                return left / right
        return None
