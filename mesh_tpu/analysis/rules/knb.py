"""KNB: env-knob registry enforcement.

Every ``MESH_TPU_*`` environment variable is declared once in
``mesh_tpu/utils/knobs.py`` — the registry gives each knob a type, a
default, one documented truthiness (``flag``), and a generated table in
doc/configuration.md.  A raw ``os.environ`` read anywhere else
reintroduces exactly the drift the registry removed: undocumented
knobs, per-site truthiness, silently diverging defaults.

Writes are deliberately exempt: ``os.environ["MESH_TPU_OBS"] = "1"``
(the CLI trace subcommand forcing the gate on) and the test-fixture
save/restore idiom configure the environment rather than read it.

Codes:

- KNB001 (error): a ``MESH_TPU_*`` key is read via ``os.environ.get``
  / ``os.getenv`` / ``os.environ[...]`` / ``setdefault`` outside
  utils/knobs.py (keys are resolved through module-level constants,
  so ``os.environ.get(RECORDER_ENV)`` is caught too).
- KNB002 (error): a knob declared in the registry is missing from
  doc/configuration.md — the generated table is stale; rerun
  ``make docs`` / tools/build_docs.py.
- KNB003 (error): tunable-knob state is written outside
  utils/tuning.py — a direct assignment / augmented assignment /
  deletion through a ``tuning`` module alias (``tuning._values[...] =
  ...``, ``tuning._generation += 1``, monkeypatching ``tuning.get``),
  or a call into the module's private API (``tuning._emit(...)``).
  ``tuning.actuate()`` is the SINGLE write path: it is what clamps to
  the declared bounds, bumps the generation counter, appends the
  audited history, and emits the ``knob_change`` flight-recorder event
  + ``mesh_tpu_tuner_*`` series — a side-door write silently skips all
  four, which is exactly the audit hole the tuner layer exists to
  close.
"""

import ast

from .common import module_constants, qualname
from ..engine import Finding, Rule

_REGISTRY_RELPATH = "mesh_tpu/utils/knobs.py"
_PREFIX = "MESH_TPU_"

_READ_FUNCS = {"os.environ.get", "environ.get", "os.getenv", "getenv",
               "os.environ.setdefault", "environ.setdefault"}


def _resolve_key(node, consts):
    """Best-effort string key of an environ access."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        const = consts.get(node.id)
        if isinstance(const, ast.Constant) and isinstance(
                const.value, str):
            return const.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_key(node.left, consts)
        if left:
            return left + "*"
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(
                first.value, str):
            return first.value + "*"
    return None


def _tuning_prefixes(tree):
    """Dotted-name prefixes bound to the tuning module in this file:
    ``from ..utils import tuning`` -> {"tuning"}, ``import
    mesh_tpu.utils.tuning as knobs_rt`` -> {"knobs_rt"}, a bare
    ``import mesh_tpu.utils.tuning`` -> {"mesh_tpu.utils.tuning"}."""
    prefixes = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "tuning" or alias.name.endswith(".tuning"):
                    prefixes.add(alias.asname or alias.name)
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "tuning":
                    prefixes.add(alias.asname or alias.name)
    return prefixes


def _tuning_remainder(name, prefixes):
    """The attribute path under a tuning alias (``"_values"`` for
    ``tuning._values`` with prefix ``tuning``), or None when ``name``
    is not rooted at one."""
    if not name:
        return None
    for prefix in prefixes:
        if name != prefix and name.startswith(prefix + "."):
            return name[len(prefix) + 1:]
    return None


def _is_store_context(parents, node):
    """True when the Subscript is an assignment/deletion target."""
    parent = parents.get(node)
    if isinstance(parent, ast.Assign) and node in parent.targets:
        return True
    if isinstance(parent, (ast.AugAssign, ast.AnnAssign)):
        return parent.target is node
    if isinstance(parent, ast.Delete):
        return node in parent.targets
    return False


class KnobRegistryRule(Rule):

    id = "KNB"
    name = "central env-knob registry enforcement"

    def check(self, ctx):
        relpath = ctx.relpath.replace("\\", "/")
        if relpath.endswith("utils/knobs.py"):
            return []
        findings = []
        if not relpath.endswith("utils/tuning.py"):
            findings.extend(self._check_tuning_writes(ctx))
        parents = ctx.parents()
        consts = module_constants(ctx.tree)
        for node in ctx.nodes():
            key_node = None
            if isinstance(node, ast.Call):
                name = qualname(node.func)
                if name in _READ_FUNCS and node.args:
                    key_node = node.args[0]
            elif isinstance(node, ast.Subscript):
                base = qualname(node.value)
                if (base in ("os.environ", "environ")
                        and not _is_store_context(parents, node)):
                    key_node = node.slice
            if key_node is None:
                continue
            key = _resolve_key(key_node, consts)
            if key and key.startswith(_PREFIX):
                findings.append(ctx.finding(
                    "KNB001", "error", node,
                    "raw environment read of %s outside the knob "
                    "registry" % key,
                    hint="declare it in mesh_tpu/utils/knobs.py and "
                         "read it via knobs.flag/get_int/get_float/"
                         "get_str/raw"))
        return findings

    def _check_tuning_writes(self, ctx):
        """KNB003: the tuning module's state is written, or its private
        API called, outside utils/tuning.py itself."""
        if "tuning" not in ctx.source:
            return []    # no alias can exist without the word appearing
        prefixes = _tuning_prefixes(ctx.tree)
        if not prefixes:
            return []
        findings = []
        for node in ctx.nodes():
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = node.targets
            elif isinstance(node, ast.Call):
                remainder = _tuning_remainder(qualname(node.func),
                                              prefixes)
                if remainder is not None and remainder.startswith("_"):
                    findings.append(ctx.finding(
                        "KNB003", "error", node,
                        "call into the tuning module's private API "
                        "(%s) outside utils/tuning.py"
                        % qualname(node.func),
                        hint="go through the audited write path: "
                             "tuning.actuate(name, value, reason=...) "
                             "clamps, bumps the generation, and emits "
                             "the knob_change event"))
                continue
            for target in targets:
                # a subscript store (tuning._values["x"] = 5) roots at
                # the attribute being indexed
                probe = target.value if isinstance(
                    target, ast.Subscript) else target
                remainder = _tuning_remainder(qualname(probe), prefixes)
                if remainder is None:
                    continue
                findings.append(ctx.finding(
                    "KNB003", "error", node,
                    "direct write to tuner state (%s) outside "
                    "utils/tuning.py" % qualname(probe),
                    hint="tuning.actuate() is the single write path: "
                         "it clamps to declared bounds, bumps the "
                         "generation counter, appends the audited "
                         "history, and emits knob_change + "
                         "mesh_tpu_tuner_* series"))
        return findings

    def finalize(self, project):
        registry = project.by_relpath.get(_REGISTRY_RELPATH)
        if registry is None:
            return []
        declared = []      # (name, lineno)
        for node in ast.walk(registry.tree):
            if (isinstance(node, ast.Call)
                    and qualname(node.func) == "_declare"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                declared.append((node.args[0].value, node.lineno))
        if not declared:
            return []
        doc = project.doc_text("doc", "configuration.md")
        if doc is None:
            return [Finding(
                "KNB002", "error", _REGISTRY_RELPATH, 0,
                "doc/configuration.md is missing: the knob table is "
                "generated from the registry",
                hint="run tools/build_docs.py (make docs) and commit "
                     "doc/configuration.md")]
        findings = []
        for name, lineno in declared:
            if name not in doc:
                findings.append(Finding(
                    "KNB002", "error", _REGISTRY_RELPATH, lineno,
                    "knob %s is declared but missing from "
                    "doc/configuration.md (stale generated table)"
                    % name,
                    hint="regenerate: make docs (tools/build_docs.py "
                         "rewrites the table from knobs.render_"
                         "markdown()) and commit the result"))
        return findings
