"""PAL: Pallas DMA / semaphore verifier (abstract interpretation).

The streamed BVH kernel (accel/pallas_stream.py) hand-maintains a
double-buffered DMA ring: ``pltpu.make_async_copy(...).start()`` in the
refill walk, ``.wait()`` at the ring head, compute strictly on landed
slots.  Nothing but review enforced that discipline; the ROADMAP's next
kernels repeat it.  PAL abstracts each kernel's DMA descriptors into
*families* (a descriptor-returning helper, a bound variable, or a
direct ``make_async_copy`` chain), tracks start/wait sites and the ring
slot expression each touches (with helper-argument substitution), and
checks:

========  ========  =====================================================
code      severity  fires on
========  ========  =====================================================
PAL001    error     a DMA family with starts but no wait anywhere in the
                    kernel (or waits with no start)
PAL002    error     compute reads/writes a ring-buffer slot the kernel
                    never waits (a slot with potentially outstanding DMA)
PAL003    error     a ``memory_space=ANY`` operand touched by compute
                    instead of exclusively via ``make_async_copy``
PAL004    warning   a ``fori_loop``/``while_loop`` body with an unequal
                    number of start and wait sites for one family
                    (per-iteration semaphore drift)
PAL005    error     the DMA ring scratch and its semaphore array declare
                    different slot counts (``pltpu.VMEM((N, ...))`` vs
                    ``pltpu.SemaphoreType.DMA((M,))``), or the kernel
                    signature arity disagrees with
                    in_specs+out_shape+scratch_shapes
========  ========  =====================================================

Slot tracking is syntactic (normalized expression equality), which is
exactly what the ring idiom gives us: the wait and the compute read use
the same ``head`` expression, the start uses the tail.  One-sided
loops (starts in the refill walk, waits in the main loop) are the
*intended* prefetch shape and stay silent; PAL004 only fires when a
single loop body both starts and waits a family unevenly.

Shape facts resolve through the VMEM rule's ``ConstEnv`` (module
constants + enclosing kw defaults), and the ring-mismatch message
prices the slot footprint with the same (8, 128) padded-tile model, so
the two rules can never disagree about a kernel's geometry.
"""

import ast

from ..engine import Rule
from .common import ConstEnv, qualname
from .vmem import _DTYPE_SIZES, _padded_bytes

__all__ = ["PallasDmaRule"]

_LOOP_CALLS = {"while_loop": 1, "fori_loop": 2}   # body arg position

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _last(qn):
    return qn.rsplit(".", 1)[-1] if qn else None


def _ref_root(node):
    """Root buffer name of ``buf``, ``buf.at[...]``, ``buf[...]`` chains,
    plus the first slot index expression (or None)."""
    slot = None
    while True:
        if isinstance(node, ast.Subscript):
            idx = node.slice
            first = idx.elts[0] if isinstance(idx, ast.Tuple) and idx.elts \
                else idx
            if slot is None:
                slot = first
            node = node.value
        elif isinstance(node, ast.Attribute) and node.attr == "at":
            node = node.value
        elif isinstance(node, ast.Name):
            return node.id, slot
        else:
            return None, slot


def _norm(node):
    return None if node is None else ast.dump(node)


class _Family(object):
    __slots__ = ("label", "dst_root", "starts", "waits")

    def __init__(self, label, dst_root):
        self.label = label
        self.dst_root = dst_root
        self.starts = []     # (slot_norm, call_node)
        self.waits = []      # (slot_norm, call_node)


class _Helper(object):
    """A nested def returning a make_async_copy descriptor."""

    __slots__ = ("name", "params", "dst_root", "slot", "copy_call")

    def __init__(self, node, copy_call):
        self.name = node.name
        self.params = [a.arg for a in node.args.args]
        self.copy_call = copy_call
        dst = copy_call.args[1] if len(copy_call.args) > 1 else None
        self.dst_root, self.slot = _ref_root(dst) if dst is not None \
            else (None, None)

    def slot_at(self, call):
        """The ring-slot expression at a helper call site, with the
        helper's formal substituted by the actual argument."""
        if isinstance(self.slot, ast.Name) and self.slot.id in self.params:
            pos = self.params.index(self.slot.id)
            if pos < len(call.args):
                return call.args[pos]
        return self.slot


class PallasDmaRule(Rule):
    id = "PAL"
    name = "pallas DMA/semaphore discipline"

    def check(self, ctx):
        findings = []
        units = [node for node in self._top_defs(ctx.tree)
                 if any(isinstance(n, ast.Call)
                        and _last(qualname(n.func)) == "make_async_copy"
                        for n in ast.walk(node))]
        for unit in units:
            findings.extend(self._check_unit(ctx, unit))
        for call in ctx.nodes():
            if isinstance(call, ast.Call) \
                    and _last(qualname(call.func)) == "pallas_call":
                findings.extend(self._check_call_site(ctx, call))
        return findings

    @staticmethod
    def _top_defs(tree):
        for node in tree.body:
            if isinstance(node, _SCOPES):
                yield node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, _SCOPES):
                        yield sub

    # -- kernel-body DMA analysis (PAL001/002/004) ---------------------

    def _check_unit(self, ctx, unit):
        parents = ctx.parents()
        # nested-def scope tree: name resolution walks outward
        def_parent = {}
        for node in ast.walk(unit):
            if isinstance(node, _SCOPES) and node is not unit:
                p = parents.get(node)
                while p is not None and not isinstance(p, _SCOPES):
                    p = parents.get(p)
                def_parent[node] = p or unit

        def resolve_def(name, scope):
            while scope is not None:
                for child in ast.iter_child_nodes(scope):
                    if isinstance(child, _SCOPES) and child.name == name:
                        return child
                scope = def_parent.get(scope)
            return None

        def scope_of(node):
            p = parents.get(node)
            while p is not None and not isinstance(p, _SCOPES):
                p = parents.get(p)
            return p or unit

        helpers = {}     # def node -> _Helper
        for node in ast.walk(unit):
            if isinstance(node, _SCOPES):
                for stmt in node.body:
                    if isinstance(stmt, ast.Return) \
                            and isinstance(stmt.value, ast.Call) \
                            and _last(qualname(stmt.value.func)) == \
                            "make_async_copy":
                        helpers[node] = _Helper(node, stmt.value)

        # simple descriptor bindings: dma = make_async_copy(...) / helper()
        bindings = {}    # var name -> ("copy", call) | ("helper", h, call)
        for node in ast.walk(unit):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                call = node.value
                if _last(qualname(call.func)) == "make_async_copy":
                    bindings[node.targets[0].id] = ("copy", call)
                elif isinstance(call.func, ast.Name):
                    target = resolve_def(call.func.id, scope_of(node))
                    if target in helpers:
                        bindings[node.targets[0].id] = (
                            "helper", helpers[target], call)

        families = {}    # label -> _Family
        event_chain = {}  # event call node -> tuple of enclosing defs

        def family(label, dst_root):
            if label not in families:
                families[label] = _Family(label, dst_root)
            return families[label]

        def descriptor_of(recv, scope):
            """(family, slot expr) for a ``.start()``/``.wait()``
            receiver, or (None, None)."""
            if isinstance(recv, ast.Call):
                if _last(qualname(recv.func)) == "make_async_copy":
                    dst = recv.args[1] if len(recv.args) > 1 else None
                    root, slot = _ref_root(dst) if dst is not None \
                        else (None, None)
                    return family("copy(->%s)" % root, root), slot
                if isinstance(recv.func, ast.Name):
                    target = resolve_def(recv.func.id, scope)
                    if target in helpers:
                        h = helpers[target]
                        return (family("%s()" % h.name, h.dst_root),
                                h.slot_at(recv))
            elif isinstance(recv, ast.Name) and recv.id in bindings:
                bound = bindings[recv.id]
                if bound[0] == "copy":
                    dst = bound[1].args[1] if len(bound[1].args) > 1 \
                        else None
                    root, slot = _ref_root(dst) if dst is not None \
                        else (None, None)
                    return family(recv.id, root), slot
                h, call = bound[1], bound[2]
                return family(recv.id, h.dst_root), h.slot_at(call)
            return None, None

        for node in ast.walk(unit):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("start", "wait")):
                continue
            fam, slot = descriptor_of(node.func.value, scope_of(node))
            if fam is None:
                continue
            chain = []
            scope = scope_of(node)
            while scope is not None:
                chain.append(scope)
                scope = def_parent.get(scope)
            event_chain[node] = tuple(chain)
            record = (fam.starts if node.func.attr == "start"
                      else fam.waits)
            record.append((_norm(slot), slot, node))

        findings = []
        for fam in sorted(families.values(), key=lambda f: f.label):
            findings.extend(self._check_family(ctx, unit, fam))
        findings.extend(self._check_loop_balance(
            ctx, unit, families, event_chain, resolve_def, scope_of))
        return findings

    def _check_family(self, ctx, unit, fam):
        findings = []
        if fam.starts and not fam.waits:
            findings.append(ctx.finding(
                "PAL001", "error", fam.starts[0][2],
                "DMA %s in %s is started but never awaited" % (
                    fam.label, unit.name),
                hint="every make_async_copy start needs a .wait() on "
                     "the same descriptor before its data is read"))
        elif fam.waits and not fam.starts:
            findings.append(ctx.finding(
                "PAL001", "error", fam.waits[0][2],
                "DMA %s in %s is awaited but never started" % (
                    fam.label, unit.name),
                hint="a wait with no start deadlocks the kernel on an "
                     "unsignalled semaphore"))
        findings.extend(self._check_aliasing(ctx, unit, fam))
        return findings

    def _check_aliasing(self, ctx, unit, fam):
        """PAL002: compute access to a ring slot nobody waits."""
        if not fam.dst_root or not fam.waits or not fam.starts:
            return
        waited = {norm for norm, _, _ in fam.waits if norm is not None}
        if not waited:
            return
        waited_src = sorted({
            ast.unparse(snode) if hasattr(ast, "unparse") else "<slot>"
            for _, snode, _ in fam.waits if snode is not None})
        # every node inside a make_async_copy call is DMA plumbing
        dma_nodes = set()
        for node in ast.walk(unit):
            if isinstance(node, ast.Call) \
                    and _last(qualname(node.func)) == "make_async_copy":
                for sub in ast.walk(node):
                    dma_nodes.add(sub)
        for node in ast.walk(unit):
            if not isinstance(node, ast.Subscript) or node in dma_nodes:
                continue
            root, slot = _ref_root(node)
            if root != fam.dst_root or slot is None:
                continue
            if _norm(slot) not in waited:
                yield ctx.finding(
                    "PAL002", "error", node,
                    "ring slot aliasing in %s: %s[%s] is accessed by "
                    "compute but only slot(s) %s are awaited for DMA "
                    "%s — the slot may have an outstanding copy" % (
                        unit.name, fam.dst_root,
                        ast.unparse(slot) if hasattr(ast, "unparse")
                        else "<slot>",
                        ", ".join(waited_src), fam.label),
                    hint="read only slots whose DMA was awaited (the "
                         "ring head), or wait this slot first")

    def _check_loop_balance(self, ctx, unit, families, event_chain,
                            resolve_def, scope_of):
        """PAL004: start/wait site imbalance inside one loop body."""
        loop_bodies = set()
        for node in ast.walk(unit):
            if isinstance(node, ast.Call):
                pos = _LOOP_CALLS.get(_last(qualname(node.func)))
                if pos is not None and pos < len(node.args) \
                        and isinstance(node.args[pos], ast.Name):
                    body = resolve_def(node.args[pos].id, scope_of(node))
                    if body is not None:
                        loop_bodies.add(body)
        findings = []
        for body in sorted(loop_bodies, key=lambda n: n.lineno):
            for label in sorted(families):
                fam = families[label]
                starts = sum(1 for _, _, node in fam.starts
                             if body in event_chain.get(node, ()))
                waits = sum(1 for _, _, node in fam.waits
                            if body in event_chain.get(node, ()))
                if starts and waits and starts != waits:
                    findings.append(ctx.finding(
                        "PAL004", "warning", body,
                        "loop body %s starts DMA %s at %d site(s) but "
                        "waits at %d — per-iteration semaphore drift" % (
                            body.name, fam.label, starts, waits),
                        hint="balance start/wait sites per iteration, "
                             "or split the prefetch into its own loop"))
        return findings

    # -- pallas_call site checks (PAL003/005) --------------------------

    def _check_call_site(self, ctx, call):
        parents = ctx.parents()
        enclosing = parents.get(call)
        while enclosing is not None and not isinstance(enclosing, _SCOPES):
            enclosing = parents.get(enclosing)
        env = ConstEnv(ctx.tree, enclosing)
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        spec_src = kw
        grid_spec = kw.get("grid_spec")
        if isinstance(grid_spec, ast.Call):
            spec_src = dict(kw)
            spec_src.update({k.arg: k.value for k in grid_spec.keywords
                             if k.arg})
        in_specs = spec_src.get("in_specs")
        out_shape = spec_src.get("out_shape")
        scratch = spec_src.get("scratch_shapes")
        prefetch = env.resolve(spec_src.get("num_scalar_prefetch")) \
            if spec_src.get("num_scalar_prefetch") is not None else 0
        kernel = self._resolve_kernel(ctx, call)
        findings = []
        if kernel is not None:
            findings.extend(self._check_any_operands(
                ctx, call, kernel, in_specs, enclosing, int(prefetch or 0)))
        findings.extend(self._check_arity(
            ctx, call, kernel, in_specs, out_shape, scratch,
            int(prefetch or 0)))
        if kernel is not None and isinstance(scratch, ast.List):
            findings.extend(self._check_ring_shapes(
                ctx, call, kernel, in_specs, out_shape, scratch, env,
                int(prefetch or 0)))
        return findings

    def _resolve_kernel(self, ctx, call):
        """The kernel FunctionDef behind pallas_call's first argument:
        a module-level def, or the def a module-level factory returns."""
        if not call.args:
            return None
        target = call.args[0]
        module_defs = {node.name: node for node in ctx.tree.body
                       if isinstance(node, _SCOPES)}
        if isinstance(target, ast.Name):
            return module_defs.get(target.id)
        if isinstance(target, ast.Call) and isinstance(
                target.func, ast.Name):
            factory = module_defs.get(target.func.id)
            if factory is None:
                return None
            nested = {node.name: node
                      for node in ast.iter_child_nodes(factory)
                      if isinstance(node, _SCOPES)}
            for stmt in factory.body:
                if isinstance(stmt, ast.Return) \
                        and isinstance(stmt.value, ast.Name):
                    return nested.get(stmt.value.id)
        return None

    @staticmethod
    def _spec_is_any(spec, enclosing):
        """True when an in_specs element is BlockSpec(memory_space=ANY),
        following one level of local-variable indirection."""
        if isinstance(spec, ast.Name) and enclosing is not None:
            for node in ast.walk(enclosing):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == spec.id:
                    spec = node.value
                    break
        if not (isinstance(spec, ast.Call)
                and _last(qualname(spec.func)) == "BlockSpec"):
            return False
        for k in spec.keywords:
            if k.arg == "memory_space" \
                    and _last(qualname(k.value)) == "ANY":
                return True
        return False

    def _check_any_operands(self, ctx, call, kernel, in_specs,
                            enclosing, prefetch):
        """PAL003: ANY-space operands are DMA-only."""
        if not isinstance(in_specs, ast.List):
            return
        params = [a.arg for a in kernel.args.args]
        for i, spec in enumerate(in_specs.elts):
            if not self._spec_is_any(spec, enclosing):
                continue
            idx = prefetch + i
            if idx >= len(params):
                continue
            name = params[idx]
            dma_nodes = set()
            for node in ast.walk(kernel):
                if isinstance(node, ast.Call) and _last(
                        qualname(node.func)) == "make_async_copy":
                    for sub in ast.walk(node):
                        dma_nodes.add(sub)
            for node in ast.walk(kernel):
                if isinstance(node, ast.Name) and node.id == name \
                        and isinstance(node.ctx, ast.Load) \
                        and node not in dma_nodes:
                    yield ctx.finding(
                        "PAL003", "error", node,
                        "memory_space=ANY operand %s of kernel %s is "
                        "touched by compute — ANY-resident data is only "
                        "reachable via make_async_copy" % (
                            name, kernel.name),
                        hint="DMA the block into VMEM scratch and "
                             "compute on the landed copy")
                    break

    def _check_arity(self, ctx, call, kernel, in_specs, out_shape,
                     scratch, prefetch):
        if kernel is None or not isinstance(in_specs, ast.List):
            return
        if kernel.args.vararg is not None:
            return    # *refs kernels unpack positionally — arity is theirs
        if isinstance(out_shape, ast.List):
            n_out = len(out_shape.elts)
        elif isinstance(out_shape, ast.Call):
            n_out = 1
        else:
            return
        n_scratch = len(scratch.elts) if isinstance(scratch, ast.List) \
            else 0
        expected = prefetch + len(in_specs.elts) + n_out + n_scratch
        params = kernel.args.args
        if len(params) != expected:
            yield ctx.finding(
                "PAL005", "error", call,
                "kernel %s takes %d ref(s) but pallas_call wires %d "
                "(%d prefetch + %d in + %d out + %d scratch)" % (
                    kernel.name, len(params), expected, prefetch,
                    len(in_specs.elts), n_out, n_scratch),
                hint="every in_spec, out_shape and scratch_shapes entry "
                     "becomes exactly one kernel ref argument, in order")

    def _check_ring_shapes(self, ctx, call, kernel, in_specs, out_shape,
                           scratch, env, prefetch):
        """PAL005: DMA ring slot count vs its semaphore array."""
        params = [a.arg for a in kernel.args.args]
        if isinstance(out_shape, ast.List):
            n_out = len(out_shape.elts)
        elif isinstance(out_shape, ast.Call):
            n_out = 1
        else:
            return
        n_in = len(in_specs.elts) if isinstance(in_specs, ast.List) \
            else None
        if n_in is None:
            return
        first_scratch = prefetch + n_in + n_out
        scratch_params = params[first_scratch:]
        if len(scratch_params) != len(scratch.elts):
            return    # arity check already reports the wiring bug
        by_param = dict(zip(scratch_params, scratch.elts))
        seen_pairs = set()
        for node in ast.walk(kernel):
            if not (isinstance(node, ast.Call) and _last(
                    qualname(node.func)) == "make_async_copy"):
                continue
            if len(node.args) < 3:
                continue
            dst_root, _ = _ref_root(node.args[1])
            sem_root, _ = _ref_root(node.args[2])
            if (dst_root, sem_root) in seen_pairs:
                continue
            seen_pairs.add((dst_root, sem_root))
            ring = by_param.get(dst_root)
            sem = by_param.get(sem_root)
            if not (isinstance(ring, ast.Call)
                    and isinstance(sem, ast.Call)):
                continue
            ring_dims = self._shape_dims(ring)
            sem_dims = self._shape_dims(sem)
            if not ring_dims or sem_dims is None:
                continue
            n_slots = env.resolve(ring_dims[0])
            n_sems = env.resolve(sem_dims[0]) if sem_dims else 1
            if n_slots is None or n_sems is None:
                continue
            if int(n_slots) != int(n_sems):
                slot_bytes = None
                rest = [env.resolve(d) for d in ring_dims[1:]]
                if rest and all(r is not None for r in rest):
                    itemsize = _DTYPE_SIZES.get(
                        _last(qualname(ring.args[1]))
                        if len(ring.args) > 1 else "", 4)
                    slot_bytes = _padded_bytes(
                        [int(r) for r in rest], itemsize)
                detail = (" (each slot ~%d KiB padded)" %
                          (slot_bytes // 1024)) if slot_bytes else ""
                yield ctx.finding(
                    "PAL005", "error", call,
                    "DMA ring %s in kernel %s has %d slot(s) but "
                    "semaphore array %s has %d%s" % (
                        dst_root, kernel.name, int(n_slots), sem_root,
                        int(n_sems), detail),
                    hint="ring buffer and SemaphoreType.DMA leading "
                         "dims must agree — one semaphore per in-"
                         "flight slot")

    @staticmethod
    def _shape_dims(spec_call):
        """Dim expression list of pltpu.VMEM((a, b), dt) /
        SemaphoreType.DMA((n,)); [] for scalar shapes, None when the
        call isn't shaped that way."""
        if not spec_call.args:
            return None
        shape = spec_call.args[0]
        if isinstance(shape, ast.Tuple):
            return list(shape.elts)
        return None
