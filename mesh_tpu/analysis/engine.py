"""meshlint engine: file contexts, the Rule protocol, baselines, output.

Pipeline: discover ``.py`` files under the package root, parse each one
once into a :class:`FileContext` (AST + parent map + line table), run
every rule's per-file ``check(ctx)`` hook, then every rule's
project-level ``finalize(project)`` hook (for cross-file facts like
"is this metric series documented").  Findings carry ``file:line``, a
rule id, a severity, and a fix hint; each has a stable fingerprint —
``sha1(rule|path|message)[:12]``, deliberately line-free so findings
survive unrelated edits above them — which is what the committed
baseline file (tools/meshlint_baseline.json) suppresses by.

Exit-code contract (pinned by tests/test_analysis.py):

- clean tree ............................ rc 0
- findings, all fingerprints baselined .. rc 0 (suppressed, listed on -v)
- any NEW warning- or error-severity .... rc 1
- notes ................................. never block

Stale baseline entries (fingerprint no longer produced — the hazard was
fixed) are reported so the file can be re-generated with
``--write-baseline``; they do not affect the exit code.

Stdlib-only by design: ``mesh-tpu lint`` and the gate-0 check must run
without jax, numpy, or a backend.
"""

import ast
import hashlib
import json
import os
import time

__all__ = [
    "SEVERITIES", "Finding", "FileContext", "Project", "Rule", "Report",
    "build_project", "check_source", "load_baseline", "save_baseline",
    "run_lint", "default_baseline_path",
]

#: severity order; rc goes 1 only for NEW findings at warning or above
SEVERITIES = ("note", "warning", "error")

_SEVERITY_RANK = {name: i for i, name in enumerate(SEVERITIES)}

#: JSON schema version of both the report and the baseline file
SCHEMA_VERSION = 1


class Finding(object):
    """One diagnostic: rule id, severity, location, message, fix hint.

    Flow-sensitive rules may attach a ``witness`` — the CFG path
    proving the finding, as [(line, note), ...] steps — which rides
    into the JSON report and SARIF codeFlows but stays OUT of the
    fingerprint (a witness re-route from an unrelated edit must not
    un-baseline a finding)."""

    __slots__ = ("rule", "severity", "path", "line", "message", "hint",
                 "witness")

    def __init__(self, rule, severity, path, line, message, hint=None,
                 witness=None):
        assert severity in SEVERITIES, severity
        self.rule = rule
        self.severity = severity
        self.path = path            # repo-relative, posix separators
        self.line = int(line or 0)
        self.message = message
        self.hint = hint
        self.witness = witness      # [(line, note), ...] or None

    @property
    def fingerprint(self):
        """Stable suppression key: line numbers excluded on purpose so a
        baselined finding survives edits elsewhere in the file."""
        key = "%s|%s|%s" % (self.rule, self.path, self.message)
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:12]

    def to_dict(self):
        out = {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }
        if self.hint:
            out["hint"] = self.hint
        if self.witness:
            out["witness"] = [
                {"line": int(line), "note": note}
                for line, note in self.witness]
        return out

    def render(self):
        text = "%s:%d: %s %s %s" % (
            self.path, self.line, self.severity, self.rule, self.message)
        if self.hint:
            text += "  [fix: %s]" % self.hint
        return text

    def __repr__(self):
        return "Finding(%s)" % self.render()


class FileContext(object):
    """One parsed source file: path, source, AST, lazy parent map."""

    def __init__(self, path, relpath, source, tree):
        self.path = path            # absolute
        self.relpath = relpath      # repo-relative, posix separators
        self.source = source
        self.tree = tree
        self._lines = None
        self._parents = None
        self._nodes = None

    def line(self, lineno):
        """1-based source line (stripped), for messages."""
        if self._lines is None:
            self._lines = self.source.splitlines()
        if 1 <= lineno <= len(self._lines):
            return self._lines[lineno - 1].strip()
        return ""

    def parents(self):
        """{node: parent} over the whole tree, built once per file."""
        if self._parents is None:
            self._build_maps()
        return self._parents

    def nodes(self):
        """Flat list of every AST node (module first, breadth-first),
        built once per file in the same pass as the parent map.  Rules
        iterate this instead of re-walking the tree with ``ast.walk`` —
        eight rules each re-walking ~140k nodes per run is what pushed
        the whole-tree lint toward its <3s budget."""
        if self._nodes is None:
            self._build_maps()
        return self._nodes

    def _build_maps(self):
        parents = {}
        nodes = [self.tree]
        # iterating while appending gives the same breadth-first order
        # as ast.walk, in one pass for both maps
        for node in nodes:
            for child in ast.iter_child_nodes(node):
                parents[child] = node
                nodes.append(child)
        self._parents = parents
        self._nodes = nodes

    def finding(self, rule, severity, node, message, hint=None):
        """Convenience constructor anchored at an AST node."""
        return Finding(rule, severity, self.relpath,
                       getattr(node, "lineno", 0), message, hint)


class Project(object):
    """The whole lint run's view: repo root + every parsed file."""

    def __init__(self, root, contexts):
        self.root = root
        self.contexts = list(contexts)
        self.by_relpath = {ctx.relpath: ctx for ctx in self.contexts}
        self._inter = None

    def interproc(self):
        """The interprocedural model (cross-module call graph + lock
        acquisition-order edges), built once per lint run no matter how
        many rules consume it — that sharing is what keeps the
        whole-tree run inside its <3s budget."""
        if self._inter is None:
            from .interproc import InterGraph

            self._inter = InterGraph.build(self)
        return self._inter

    def doc_text(self, *relparts):
        """Text of a repo file (docs live outside the scanned package),
        or None when absent."""
        path = os.path.join(self.root, *relparts)
        try:
            with open(path, encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None


class Rule(object):
    """Base rule: subclass, set ``id``/``name``, override one hook.

    ``check(ctx)`` yields findings for one file; ``finalize(project)``
    yields findings that need cross-file facts (doc coverage, registry
    completeness).  Both default to nothing so rules implement only
    what they need.
    """

    id = "XXX"
    name = "unnamed rule"

    def check(self, ctx):
        return ()

    def finalize(self, project):
        return ()


# -- discovery ---------------------------------------------------------

def _iter_py_files(path):
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in ("__pycache__", ".git", "_build"))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def build_project(root, paths=None):
    """Parse every target file into a Project.

    :param root: repo root (fingerprint paths are relative to it).
    :param paths: explicit files/dirs to scan; default ``<root>/mesh_tpu``.
    :returns: (project, parse_failures) — parse failures become
        PARSE-rule error findings rather than crashing the run.
    """
    root = os.path.abspath(root)
    if not paths:
        paths = [os.path.join(root, "mesh_tpu")]
    contexts, failures = [], []
    for target in paths:
        target = os.path.abspath(target)
        for path in _iter_py_files(target):
            relpath = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as fh:
                    source = fh.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError, ValueError) as exc:
                failures.append(Finding(
                    "PARSE", "error", relpath,
                    getattr(exc, "lineno", 0) or 0,
                    "cannot parse: %s" % exc))
                continue
            contexts.append(FileContext(path, relpath, source, tree))
    return Project(root, contexts), failures


def check_source(rule, source, relpath="snippet.py", root="/nonexistent"):
    """Run one rule over one in-memory snippet — the fixture-test entry
    point (positive and negative fixtures per rule id)."""
    tree = ast.parse(source)
    ctx = FileContext(os.path.join(root, relpath), relpath, source, tree)
    findings = list(rule.check(ctx))
    findings.extend(rule.finalize(Project(root, [ctx])))
    return findings


# -- baseline ----------------------------------------------------------

def default_baseline_path(root):
    return os.path.join(root, "tools", "meshlint_baseline.json")


def load_baseline(path):
    """{fingerprint: entry-dict}; a missing file is an empty baseline."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except OSError:
        return {}
    entries = doc.get("entries", {})
    if isinstance(entries, list):    # tolerate the list form
        entries = {e["fingerprint"]: e for e in entries}
    return dict(entries)


def save_baseline(path, findings, old_entries=None, default_reason=None):
    """Write the baseline for the given findings, carrying forward the
    human-written ``reason`` of any fingerprint already baselined."""
    old_entries = old_entries or {}
    entries = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        prev = old_entries.get(f.fingerprint, {})
        entries[f.fingerprint] = {
            "rule": f.rule,
            "path": f.path,
            "line": f.line,        # informational; not part of the match
            "severity": f.severity,
            "message": f.message,
            "reason": prev.get("reason")
            or default_reason
            or "TODO: justify this suppression",
        }
    doc = {
        "version": SCHEMA_VERSION,
        "note": ("meshlint baseline: known findings suppressed by "
                 "fingerprint (sha1(rule|path|message)[:12]). Regenerate "
                 "with `mesh-tpu lint --write-baseline`; every entry "
                 "needs a one-line reason."),
        "entries": entries,
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


# -- the run -----------------------------------------------------------

class Report(object):
    """One lint run's outcome: findings split against the baseline."""

    #: per-phase / per-rule wall time, filled by run_lint (always
    #: collected — it is a handful of monotonic reads — and rendered
    #: only under ``mesh-tpu lint --profile``)
    profile = None

    def __init__(self, findings, baseline, elapsed_s, files_scanned):
        self.findings = sorted(
            findings, key=lambda f: (f.path, f.line, f.rule, f.message))
        self.baseline = baseline
        self.elapsed_s = elapsed_s
        self.files_scanned = files_scanned
        produced = {f.fingerprint for f in self.findings}
        self.new = [f for f in self.findings
                    if f.fingerprint not in baseline]
        self.suppressed = [f for f in self.findings
                           if f.fingerprint in baseline]
        self.stale = {fp: entry for fp, entry in baseline.items()
                      if fp not in produced}

    @property
    def rc(self):
        """1 only for NEW findings at warning severity or above."""
        blocking = [f for f in self.new
                    if _SEVERITY_RANK[f.severity] >= 1]
        return 1 if blocking else 0

    def to_dict(self):
        out = {
            "schema_version": SCHEMA_VERSION,
            "rc": self.rc,
            "files_scanned": self.files_scanned,
            "elapsed_s": round(self.elapsed_s, 4),
            "counts": {
                "total": len(self.findings),
                "new": len(self.new),
                "suppressed": len(self.suppressed),
                "stale_baseline": len(self.stale),
            },
            "findings": [f.to_dict() for f in self.new],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "stale_baseline": [
                dict(entry, fingerprint=fp)
                for fp, entry in sorted(self.stale.items())
            ],
        }
        if self.profile is not None:
            out["profile"] = self.profile
        return out

    def render_profile(self):
        """Attribution table for ``--profile``: where the gate-0 wall
        time went — per phase, then per rule slowest-first.  The cfg/
        dataflow rows are carved out of (not additional to) the rule
        times: they accrue while RES/LED/FLW checks run."""
        p = self.profile or {}
        rules = p.get("rules_s", {})
        lines = [
            "meshlint profile (%.2fs total, %d files):"
            % (self.elapsed_s, self.files_scanned),
            "  parse     %7.3fs" % p.get("parse_s", 0.0),
            "  cfg       %7.3fs  (%d builds)"
            % (p.get("cfg_s", 0.0), p.get("cfg_builds", 0)),
            "  dataflow  %7.3fs  (%d solves)"
            % (p.get("dataflow_s", 0.0), p.get("dataflow_solves", 0)),
            "  rules     %7.3fs" % sum(rules.values()),
        ]
        for rid, s in sorted(rules.items(), key=lambda kv: (-kv[1],
                                                            kv[0])):
            lines.append("    %-5s %7.3fs" % (rid, s))
        return "\n".join(lines)

    def to_sarif(self):
        """SARIF 2.1.0 for code-scanning UIs.  Only NEW findings become
        ``results`` (baselined ones are suppressed with a reason), so
        the rc contract and the JSON schema-v1 report are untouched —
        this is a parallel serialization, not a new schema version."""
        severity_level = {"note": "note", "warning": "warning",
                          "error": "error"}
        rule_ids = sorted({f.rule for f in self.findings})
        results = []
        for f in self.new:
            results.append(self._sarif_result(f, severity_level))
        for f in self.suppressed:
            entry = self.baseline.get(f.fingerprint, {})
            result = self._sarif_result(f, severity_level)
            result["suppressions"] = [{
                "kind": "external",
                "justification": entry.get("reason", ""),
            }]
            results.append(result)
        return {
            "version": "2.1.0",
            "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                        "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                        ".json"),
            "runs": [{
                "tool": {"driver": {
                    "name": "meshlint",
                    "informationUri":
                        "doc/static_analysis.md",
                    "rules": [{"id": rid} for rid in rule_ids],
                }},
                "results": results,
            }],
        }

    @staticmethod
    def _sarif_result(f, severity_level):
        result = {
            "ruleId": f.rule,
            "level": severity_level[f.severity],
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {"meshlint/v1": f.fingerprint},
        }
        if f.hint:
            result["message"]["text"] += "  [fix: %s]" % f.hint
        if f.witness:
            # the CFG path witness: the branch sequence proving the
            # leaky path, one threadFlow location per step
            result["codeFlows"] = [{
                "threadFlows": [{
                    "locations": [{
                        "location": {
                            "physicalLocation": {
                                "artifactLocation": {"uri": f.path},
                                "region": {"startLine": max(1,
                                                            int(line))},
                            },
                            "message": {"text": note or "(step)"},
                        },
                    } for line, note in f.witness],
                }],
            }]
        return result

    def render_human(self, verbose=False):
        lines = []
        for f in self.new:
            lines.append(f.render())
            for line, note in (f.witness or ()):
                lines.append("    path: L%d%s"
                             % (line, " — " + note if note else ""))
        if verbose:
            for f in self.suppressed:
                lines.append("(baselined) " + f.render())
        for fp, entry in sorted(self.stale.items()):
            lines.append(
                "stale baseline entry %s (%s %s — fixed? regenerate with "
                "--write-baseline)" % (fp, entry.get("rule", "?"),
                                       entry.get("path", "?")))
        lines.append(
            "meshlint: %d file(s), %d finding(s) (%d new, %d baselined, "
            "%d stale baseline entr%s) in %.2fs -> %s" % (
                self.files_scanned, len(self.findings), len(self.new),
                len(self.suppressed), len(self.stale),
                "y" if len(self.stale) == 1 else "ies",
                self.elapsed_s, "FAIL" if self.rc else "OK"))
        return "\n".join(lines)


def run_lint(root, paths=None, rules=None, baseline_path=None,
             use_baseline=True):
    """Parse, run every rule, split against the baseline.

    :param rules: rule instances; default the full registry
        (mesh_tpu.analysis.rules.all_rules()).
    :param baseline_path: explicit path; default
        tools/meshlint_baseline.json under ``root``.
    :param use_baseline: False disables suppression (every finding is
        "new") — the CI mode for fixture tests.
    """
    from . import cfg as cfg_mod

    t0 = time.monotonic()
    if rules is None:
        from .rules import all_rules

        rules = all_rules()
    cfg_mod.reset_stats()
    project, findings = build_project(root, paths)
    t_parse = time.monotonic() - t0
    per_rule = {rule.id: 0.0 for rule in rules}
    for ctx in project.contexts:
        for rule in rules:
            t1 = time.monotonic()
            findings.extend(rule.check(ctx))
            per_rule[rule.id] += time.monotonic() - t1
    for rule in rules:
        t1 = time.monotonic()
        findings.extend(rule.finalize(project))
        per_rule[rule.id] += time.monotonic() - t1
    if baseline_path is None:
        baseline_path = default_baseline_path(project.root)
    baseline = load_baseline(baseline_path) if use_baseline else {}
    report = Report(findings, baseline, time.monotonic() - t0,
                    len(project.contexts))
    stats = cfg_mod.snapshot_stats()
    report.profile = {
        "parse_s": round(t_parse, 4),
        "cfg_s": round(stats["cfg_s"], 4),
        "cfg_builds": stats["cfg_builds"],
        "dataflow_s": round(stats["dataflow_s"], 4),
        "dataflow_solves": stats["dataflow_solves"],
        "rules_s": {rid: round(s, 4)
                    for rid, s in sorted(per_rule.items())},
    }
    return report
