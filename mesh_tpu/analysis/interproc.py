"""Interprocedural layer: cross-module call graph with held-lock context.

PR 8's rules are per-file; the LOK family needs whole-program facts:
which function calls which (across modules), which locks exist (module
globals, ``self._lock`` instance attributes, function-local closure
locks), and which locks are *held* when a call is made.  This module
builds that model once per lint run (``Project.interproc()`` caches it)
and derives the global lock **acquisition-order graph**: an edge
``A -> B`` means some path acquires ``B`` while already holding ``A``.

Resolution is name-level and deliberately conservative, in the spirit
of the TRC rule's per-module frontier:

- bare calls resolve to module functions, ``from``-imported symbols
  (relative imports included — the lazy-import idiom used everywhere in
  this codebase), nested closures, and class constructors;
- ``self.method()`` resolves within the enclosing class;
- ``alias.func()`` resolves through ``import``/``from``-module aliases;
- other attribute calls resolve only when exactly one project class
  defines that method name and the name is not a ubiquitous container
  method (the ``_COMMON_METHODS`` guard) — missing an edge is fine
  (the runtime lock witness covers dynamic dispatch), inventing one
  is not.

Stdlib-only, like the rest of the analysis package.
"""

import ast

__all__ = ["InterGraph", "LockInfo", "LOCK_FACTORY_PARTS"]

#: threading factory callables whose result is an acquisition-ordered
#: primitive (Condition wraps an RLock; Semaphore orders like a lock)
LOCK_FACTORY_PARTS = (
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore")

#: method names too generic to resolve by the unique-method heuristic —
#: they collide with dict/list/file/Future/str usage constantly
_COMMON_METHODS = frozenset({
    "get", "items", "keys", "values", "append", "pop", "add", "update",
    "clear", "copy", "read", "write", "split", "strip", "sort", "remove",
    "extend", "insert", "encode", "decode", "format", "join", "wait",
    "notify", "notify_all", "acquire", "release", "start", "close",
    "flush", "tell", "seek", "cancel", "result", "set_result", "done",
    "set_exception", "put", "send", "recv", "info", "debug", "warning",
    "error", "record", "set", "inc", "observe", "count", "index", "next",
    "setdefault", "popitem", "move_to_end", "tobytes", "reshape", "item",
})

#: callable names whose invocation can block indefinitely (I/O, process
#: waits) — making one while holding a lock serializes every contender
#: behind the disk/child process (LOK002)
_BLOCKING_PARTS = frozenset({
    "sleep", "rename", "replace", "rmtree", "copytree", "makedirs",
    "urlopen", "run", "Popen", "check_call", "check_output",
    "communicate",
})


def module_name_of(relpath):
    """Dotted module name of a repo-relative path:
    ``mesh_tpu/store/store.py`` -> ``mesh_tpu.store.store``;
    ``mesh_tpu/store/__init__.py`` -> ``mesh_tpu.store``."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else \
        relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class LockInfo(object):
    """One discovered lock primitive (module / instance / local)."""

    __slots__ = ("key", "relpath", "lineno", "kind", "scope", "name")

    def __init__(self, relpath, lineno, kind, scope, name):
        self.key = "%s:%d" % (relpath, lineno)
        self.relpath = relpath
        self.lineno = lineno
        self.kind = kind          # Lock | RLock | Condition | Semaphore...
        self.scope = scope        # module | instance | local
        self.name = name          # display: "<relpath>:<qualified var>"


class FunctionInfo(object):
    __slots__ = ("key", "relpath", "qualname", "node", "cls", "parent")

    def __init__(self, relpath, qualname, node, cls, parent):
        self.key = "%s::%s" % (relpath, qualname)
        self.relpath = relpath
        self.qualname = qualname
        self.node = node
        self.cls = cls            # enclosing class name or None
        self.parent = parent      # enclosing FunctionInfo key or None


class Edge(object):
    """One acquisition-order edge with a human-readable witness site."""

    __slots__ = ("src", "dst", "relpath", "lineno", "via")

    def __init__(self, src, dst, relpath, lineno, via):
        self.src = src
        self.dst = dst
        self.relpath = relpath
        self.lineno = lineno
        self.via = via


class _Summary(object):
    __slots__ = ("acquires", "calls", "blocking")

    def __init__(self):
        self.acquires = []    # (lock_key, held_tuple, lineno)
        self.calls = []       # (callee_key, held_tuple, lineno)
        self.blocking = []    # (desc, held_tuple, lineno)


def _qualname(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lock_kind(value):
    """Factory kind when ``value`` is a lock-constructor call, else
    None.  Accepts ``threading.Lock()`` and bare ``Lock()``."""
    if not isinstance(value, ast.Call):
        return None
    qn = _qualname(value.func)
    if not qn:
        return None
    last = qn.rsplit(".", 1)[-1]
    if last in LOCK_FACTORY_PARTS:
        root = qn.split(".", 1)[0]
        if root in ("threading", last):
            return last
    return None


class InterGraph(object):
    """The whole-program lock/call model.  Build with
    :meth:`InterGraph.build`; prefer ``project.interproc()`` which
    caches one instance per lint run."""

    def __init__(self):
        self.locks = {}           # key -> LockInfo
        self.functions = {}       # key -> FunctionInfo
        self.summaries = {}       # fn key -> _Summary
        self.edges = {}           # (src,dst) -> Edge (first witness wins)
        self.all_acquires = {}    # fn key -> frozenset(lock keys)
        self.blocking_reach = {}  # fn key -> ((desc, site_qual), ...)
        # per-module resolution state
        self._mod_locks = {}      # (relpath, var) -> lock key
        self._inst_locks = {}     # (relpath, cls, attr) -> lock key
        self._local_locks = {}    # (fn key, var) -> lock key
        self._mod_funcs = {}      # (relpath, name) -> fn key
        self._nested = {}         # (fn key, name) -> fn key
        self._methods = {}        # (relpath, cls, name) -> fn key
        self._by_method = {}      # name -> [fn key, ...]
        self._classes = {}        # (relpath, name) -> True
        self._mod_alias = {}      # (relpath, alias) -> target relpath
        self._sym_alias = {}      # (relpath, alias) -> (relpath, symbol)
        self._relpaths = set()

    # -- construction --------------------------------------------------

    @classmethod
    def build(cls, project):
        graph = cls()
        graph._relpaths = set(project.by_relpath)
        for ctx in project.contexts:
            graph._index_module(ctx)
        for ctx in project.contexts:
            graph._resolve_imports(ctx)
        for ctx in project.contexts:
            graph._summarize_module(ctx)
        graph._propagate()
        graph._derive_edges()
        return graph

    def _index_module(self, ctx):
        relpath = ctx.relpath
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                kind = _lock_kind(stmt.value)
                if kind:
                    var = stmt.targets[0].id
                    info = LockInfo(relpath, stmt.lineno, kind, "module",
                                    "%s:%s" % (relpath, var))
                    self.locks[info.key] = info
                    self._mod_locks[(relpath, var)] = info.key
        self._index_scope(ctx, ctx.tree, qual="", cls=None, parent=None)

    def _index_scope(self, ctx, node, qual, cls, parent):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = "%s.%s" % (qual, child.name) if qual else child.name
                info = FunctionInfo(ctx.relpath, q, child, cls, parent)
                self.functions[info.key] = info
                if parent is None and cls is None:
                    self._mod_funcs[(ctx.relpath, child.name)] = info.key
                elif parent is not None:
                    self._nested[(parent, child.name)] = info.key
                if cls is not None and parent is None:
                    self._methods[(ctx.relpath, cls, child.name)] = info.key
                    self._by_method.setdefault(child.name, []).append(
                        info.key)
                self._index_function(ctx, info)
                # nested defs keep ``cls``: closures capture self
                self._index_scope(ctx, child, q, cls=cls, parent=info.key)
            elif isinstance(child, ast.ClassDef):
                self._classes[(ctx.relpath, child.name)] = True
                self._index_scope(ctx, child, child.name, cls=child.name,
                                  parent=None)

    @staticmethod
    def _own_nodes(root):
        """Walk a function body without descending into nested
        function/class scopes (those are indexed on their own)."""
        todo = list(ast.iter_child_nodes(root))
        while todo:
            node = todo.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            todo.extend(ast.iter_child_nodes(node))

    def _index_function(self, ctx, fn):
        """Function-local and ``self.<attr>`` lock assignments."""
        for stmt in self._own_nodes(fn.node):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            kind = _lock_kind(stmt.value)
            if not kind:
                continue
            target = stmt.targets[0]
            if isinstance(target, ast.Name):
                info = LockInfo(
                    ctx.relpath, stmt.lineno, kind, "local",
                    "%s:%s.%s" % (ctx.relpath, fn.qualname, target.id))
                self.locks[info.key] = info
                self._local_locks[(fn.key, target.id)] = info.key
            elif isinstance(target, ast.Attribute) and isinstance(
                    target.value, ast.Name) and target.value.id == "self" \
                    and fn.cls is not None:
                lock_key = self._inst_locks.get(
                    (ctx.relpath, fn.cls, target.attr))
                if lock_key is None:
                    info = LockInfo(
                        ctx.relpath, stmt.lineno, kind, "instance",
                        "%s:%s.%s" % (ctx.relpath, fn.cls, target.attr))
                    self.locks[info.key] = info
                    self._inst_locks[
                        (ctx.relpath, fn.cls, target.attr)] = info.key

    # -- import resolution ---------------------------------------------

    def _module_relpath(self, dotted):
        """Project relpath of a dotted module name, or None."""
        base = dotted.replace(".", "/")
        for cand in (base + ".py", base + "/__init__.py"):
            if cand in self._relpaths:
                return cand
        return None

    def _resolve_imports(self, ctx):
        relpath = ctx.relpath
        pkg_parts = module_name_of(relpath).split(".")
        for node in ctx.nodes():
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = self._module_relpath(alias.name)
                    if target:
                        local = alias.asname or alias.name.split(".", 1)[0]
                        self._mod_alias[(relpath, local)] = target
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    # relative: strip the module's own name, then one
                    # more package per extra dot
                    base = pkg_parts[:-node.level] if not \
                        relpath.endswith("__init__.py") else \
                        pkg_parts[:len(pkg_parts) - node.level + 1]
                    prefix = ".".join(base)
                else:
                    prefix = ""
                mod = ".".join(p for p in (prefix, node.module or "") if p)
                mod_rel = self._module_relpath(mod) if mod else None
                for alias in node.names:
                    local = alias.asname or alias.name
                    sub = "%s.%s" % (mod, alias.name) if mod else alias.name
                    sub_rel = self._module_relpath(sub)
                    if sub_rel:
                        self._mod_alias[(relpath, local)] = sub_rel
                    elif mod_rel:
                        self._sym_alias[(relpath, local)] = (
                            mod_rel, alias.name)

    # -- call / lock-expression resolution -----------------------------

    def _resolve_symbol(self, relpath, name):
        """A bare name to a function key (module function, imported
        symbol, or class constructor), or None."""
        key = self._mod_funcs.get((relpath, name))
        if key:
            return key
        if (relpath, name) in self._classes:
            return self._methods.get((relpath, name, "__init__"))
        sym = self._sym_alias.get((relpath, name))
        if sym:
            target_rel, target_name = sym
            if target_rel == relpath and target_name == name:
                return None
            return self._resolve_symbol(target_rel, target_name)
        return None

    def _resolve_call(self, fn, node):
        """Callee FunctionInfo key for a Call node, or None."""
        func = node.func
        if isinstance(func, ast.Name):
            scope = fn
            while scope is not None:
                key = self._nested.get((scope.key, func.id))
                if key:
                    return key
                scope = self.functions.get(scope.parent) \
                    if scope.parent else None
            return self._resolve_symbol(fn.relpath, func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name):
                base = func.value.id
                if base == "self" and fn.cls is not None:
                    key = self._methods.get(
                        (fn.relpath, fn.cls, func.attr))
                    if key:
                        return key
                target_rel = self._mod_alias.get((fn.relpath, base))
                if target_rel:
                    return self._resolve_symbol(target_rel, func.attr)
            if func.attr not in _COMMON_METHODS:
                owners = self._by_method.get(func.attr, ())
                if len(owners) == 1:
                    return owners[0]
        return None

    def _resolve_lock_expr(self, fn, node):
        """Lock key for a ``with`` item's context expression, or None."""
        if isinstance(node, ast.Name):
            scope = fn
            while scope is not None:
                key = self._local_locks.get((scope.key, node.id))
                if key:
                    return key
                scope = self.functions.get(scope.parent) \
                    if scope.parent else None
            key = self._mod_locks.get((fn.relpath, node.id))
            if key:
                return key
            sym = self._sym_alias.get((fn.relpath, node.id))
            if sym:
                return self._mod_locks.get(sym)
            return None
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name):
            if node.value.id == "self" and fn.cls is not None:
                return self._inst_locks.get(
                    (fn.relpath, fn.cls, node.attr))
            target_rel = self._mod_alias.get((fn.relpath, node.value.id))
            if target_rel:
                return self._mod_locks.get((target_rel, node.attr))
        return None

    # -- summaries -----------------------------------------------------

    @staticmethod
    def _blocking_desc(node):
        """Dotted description when the call can block, else None."""
        func = node.func
        if isinstance(func, ast.Name):
            return "open" if func.id == "open" else None
        if not isinstance(func, ast.Attribute):
            return None
        qn = _qualname(func) or func.attr
        if func.attr == "join":
            # thread/process join, not str.join / os.path.join
            if "path" in qn or isinstance(func.value, ast.Constant):
                return None
            return qn
        if func.attr in _BLOCKING_PARTS:
            return qn
        return None

    def _summarize_module(self, ctx):
        for key, fn in self.functions.items():
            if fn.relpath != ctx.relpath:
                continue
            summary = _Summary()
            self._walk_body(fn, fn.node, (), summary)
            self.summaries[key] = summary

    def _walk_body(self, fn, node, held, summary):
        for child in ast.iter_child_nodes(node):
            self._visit(fn, child, held, summary)

    def _visit(self, fn, node, held, summary):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return    # separate scope, summarized on its own
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                # the context expression evaluates under the locks
                # already pushed by earlier items of this statement
                self._visit(fn, item.context_expr, tuple(inner), summary)
                lock = self._resolve_lock_expr(fn, item.context_expr)
                if lock is not None:
                    summary.acquires.append(
                        (lock, tuple(inner), node.lineno))
                    inner.append(lock)
            for stmt in node.body:
                self._visit(fn, stmt, tuple(inner), summary)
            return
        if isinstance(node, ast.Call):
            callee = self._resolve_call(fn, node)
            if callee is not None:
                summary.calls.append((callee, held, node.lineno))
            desc = self._blocking_desc(node)
            if desc is not None:
                summary.blocking.append((desc, held, node.lineno))
        self._walk_body(fn, node, held, summary)

    # -- propagation ---------------------------------------------------

    def _propagate(self):
        direct = {}
        for key, summary in self.summaries.items():
            direct[key] = {lock for lock, _, _ in summary.acquires}
        acquires = {key: set(v) for key, v in direct.items()}
        blocking = {
            key: {(desc, self.functions[key].qualname)
                  for desc, _, _ in summary.blocking}
            for key, summary in self.summaries.items()
        }
        changed = True
        passes = 0
        while changed and passes < 50:
            changed = False
            passes += 1
            for key, summary in self.summaries.items():
                acc = acquires[key]
                blk = blocking[key]
                for callee, _, _ in summary.calls:
                    extra = acquires.get(callee)
                    if extra and not extra <= acc:
                        acc |= extra
                        changed = True
                    more = blocking.get(callee)
                    if more and not more <= blk:
                        blk |= more
                        changed = True
        self.all_acquires = {k: frozenset(v) for k, v in acquires.items()}
        self.blocking_reach = {
            k: tuple(sorted(v)) for k, v in blocking.items()}

    def _derive_edges(self):
        for key, summary in self.summaries.items():
            fn = self.functions[key]
            for lock, held, lineno in summary.acquires:
                for h in held:
                    self._add_edge(h, lock, fn.relpath, lineno,
                                   "`with` nesting in %s" % fn.qualname)
            for callee, held, lineno in summary.calls:
                if not held:
                    continue
                callee_fn = self.functions[callee]
                for m in self.all_acquires.get(callee, ()):
                    for h in held:
                        self._add_edge(
                            h, m, fn.relpath, lineno,
                            "%s calls %s" % (fn.qualname,
                                             callee_fn.qualname))

    def _add_edge(self, src, dst, relpath, lineno, via):
        if src == dst:
            # re-acquisition is only a hazard for non-reentrant kinds
            if self.locks[src].kind == "RLock":
                return
        if (src, dst) not in self.edges:
            self.edges[(src, dst)] = Edge(src, dst, relpath, lineno, via)

    # -- queries -------------------------------------------------------

    def cycles(self):
        """Strongly connected components of the acquisition graph with
        more than one lock (plus non-reentrant self-edges), each a
        sorted list of lock keys."""
        adj = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, []).append(dst)
        index_counter = [0]
        stack, on_stack = [], set()
        index, lowlink = {}, {}
        out = []

        def strongconnect(v):
            work = [(v, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index[node] = lowlink[node] = index_counter[0]
                    index_counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                neighbors = adj.get(node, ())
                for i in range(pi, len(neighbors)):
                    w = neighbors[i]
                    if w not in index:
                        work[-1] = (node, i + 1)
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        lowlink[node] = min(lowlink[node], index[w])
                if recurse:
                    continue
                if lowlink[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1 or (node, node) in self.edges:
                        out.append(sorted(scc))
                work.pop()
                if work:
                    parent = work[-1][0]
                    lowlink[parent] = min(lowlink[parent], lowlink[node])

        for v in list(adj):
            if v not in index:
                strongconnect(v)
        return out

    def lock_by_site(self, relpath, lineno):
        """LockInfo at a creation site, or None — the join key the
        runtime witness uses (its wrapper records file:line of the
        ``threading.Lock()`` call)."""
        return self.locks.get("%s:%d" % (relpath, lineno))
