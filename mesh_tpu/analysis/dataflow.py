"""Forward dataflow over meshlint CFGs: generic worklist solver,
reaching definitions, guarded-path reachability, and witness paths.

The solver is a classic may-analysis kit: states merge with a
client-supplied ``merge`` (usually set union) at joins, ``transfer``
maps (node, in_state) -> out_state, and iteration runs to fixpoint over
a FIFO worklist.  CFGs are per-function and small (tens of nodes), so
no priority ordering is needed.

``reachable``/``find_path`` are the path primitives the RES/LED rules
are built on: BFS that can *avoid* a node set (e.g. close sites) and
*prune* edges whose assumption contradicts a tracked fact (e.g. an
``if rec is None`` edge while hunting paths where the record exists).
``find_path`` returns the concrete edge sequence — the CFG path
witness rendered into SARIF codeFlows.

Stdlib-only; solve time lands in ``cfg.STATS`` for ``--profile``.
"""

import ast
import time
from collections import deque

from .cfg import STATS, expr_key

__all__ = [
    "ReachingDefs", "defs_of", "find_path", "reachable",
    "render_witness", "solve_forward",
]


def solve_forward(cfg, init, transfer, merge):
    """Run a forward dataflow to fixpoint.  ``init`` seeds the entry
    in-state; returns {node: in_state}.  ``transfer(node, state)``
    must not mutate ``state``; ``merge(a, b)`` joins two in-states."""
    t0 = time.monotonic()
    try:
        states = {cfg.entry: init}
        work = deque([cfg.entry])
        on_work = {cfg.entry}
        while work:
            node = work.popleft()
            on_work.discard(node)
            out = transfer(node, states[node])
            for edge in cfg.succ[node]:
                dst = edge.dst
                cur = states.get(dst)
                new = out if cur is None else merge(cur, out)
                if cur is None or new != cur:
                    states[dst] = new
                    if dst not in on_work:
                        work.append(dst)
                        on_work.add(dst)
        return states
    finally:
        STATS["dataflow_s"] += time.monotonic() - t0
        STATS["dataflow_solves"] += 1


# -- reaching definitions ---------------------------------------------

PARAM = "<param>"


def defs_of(stmt):
    """Names (re)bound by executing this one statement node."""
    names = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    elif isinstance(stmt, ast.ExceptHandler):
        return [stmt.name] if stmt.name else []
    else:
        targets = []
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                names.append(sub.id)
    # walrus targets anywhere in the statement's expressions
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.NamedExpr) and \
                isinstance(sub.target, ast.Name):
            names.append(sub.target.id)
    return names


class ReachingDefs(object):
    """Which definition nodes may reach each program point.

    ``at(node)[name]`` is a frozenset of defining CFG nodes (or the
    :data:`PARAM` sentinel for the incoming parameter binding).  Absent
    name: nothing assigns it in this function (global / closure)."""

    def __init__(self, cfg):
        self.cfg = cfg
        params = set()
        args = cfg.func.args
        for a in (list(args.posonlyargs) if hasattr(args, "posonlyargs")
                  else []) + list(args.args) + list(args.kwonlyargs):
            params.add(a.arg)
        if args.vararg:
            params.add(args.vararg.arg)
        if args.kwarg:
            params.add(args.kwarg.arg)
        init = {p: frozenset([PARAM]) for p in params}

        def transfer(node, state):
            stmt = node.stmt
            if stmt is None:
                return state
            bound = defs_of(stmt)
            if not bound:
                return state
            out = dict(state)
            for name in bound:
                out[name] = frozenset([node])
            return out

        def merge(a, b):
            if a == b:
                return a
            out = dict(a)
            for k, v in b.items():
                cur = out.get(k)
                out[k] = v if cur is None else (cur | v)
            return out

        self._in = solve_forward(cfg, init, transfer, merge)

    def at(self, node):
        return self._in.get(node, {})


# -- guarded reachability + witnesses ---------------------------------

def _edge_ok(edge, prune_none_of):
    if not prune_none_of or edge.assume is None:
        return True
    key, fact = edge.assume
    return not (fact == "none" and key in prune_none_of)


def reachable(cfg, start, goal_pred, avoid=(), prune_none_of=(),
              edge_filter=None):
    """Is any node satisfying ``goal_pred`` reachable from ``start``
    without visiting ``avoid`` nodes, skipping edges that assume one of
    ``prune_none_of`` is None?  ``start`` itself is tested first."""
    return find_path(cfg, start, goal_pred, avoid, prune_none_of,
                     edge_filter) is not None


def find_path(cfg, start, goal_pred, avoid=(), prune_none_of=(),
              edge_filter=None):
    """BFS shortest edge-path from ``start`` to a goal node; returns
    the list of edges (possibly empty when start is a goal), or None.
    ``edge_filter(edge) -> bool`` can veto edges (e.g. loop back
    edges)."""
    avoid = set(avoid)
    if start in avoid:
        return None
    if goal_pred(start):
        return []
    seen = {start}
    work = deque([(start, ())])
    while work:
        node, path = work.popleft()
        for edge in cfg.succ[node]:
            dst = edge.dst
            if dst in seen or dst in avoid:
                continue
            if not _edge_ok(edge, prune_none_of):
                continue
            if edge_filter is not None and not edge_filter(edge):
                continue
            new_path = path + (edge,)
            if goal_pred(dst):
                return list(new_path)
            seen.add(dst)
            work.append((dst, new_path))
    return None


_KIND_NOTE = {
    "true": "branch taken", "false": "branch not taken",
    "except": "exception caught by handler", "raise": "raise edge",
    "finally": "into finally", "back": "loop repeats",
    "loop-exit": "loop exhausted", "iter": "loop iterates",
    "break": "break", "continue": "continue", "return": "return",
    "swallow": "exception swallowed by with-block",
}


def render_witness(ctx, start, path):
    """Render an edge path into [(line, note), ...] steps for SARIF
    codeFlows / ``--witness`` output.  ``ctx`` is the FileContext (for
    source lines); ``start`` the node the trace begins at."""
    def src_line(line):
        return ctx.line(line)

    steps = []
    if start.line:
        steps.append((start.line, src_line(start.line)))
    for edge in path:
        dst = edge.dst
        note = _KIND_NOTE.get(edge.kind, edge.kind)
        if dst.kind == "exit":
            steps.append((steps[-1][0] if steps else 1,
                          "function exits (%s)" % note))
        elif dst.kind == "raise_exit":
            steps.append((steps[-1][0] if steps else 1,
                          "exception escapes the function (%s)" % note))
        elif dst.line:
            text = src_line(dst.line)
            if edge.kind in ("seq",):
                steps.append((dst.line, text))
            else:
                steps.append((dst.line, "%s -> %s" % (note, text)))
    # collapse runs of plain sequential steps to keep witnesses short
    out = []
    for line, note in steps:
        if out and out[-1][0] == line and out[-1][1] == note:
            continue
        out.append((line, note))
    if len(out) > 12:
        out = out[:6] + [(out[6][0], "... %d steps elided ..."
                          % (len(out) - 11))] + out[-5:]
    return out
