"""Avatar stream sessions: per-client dynamic-mesh serving state.

A session pins everything that is invariant across an avatar's frames —
topology digest, faces, keyframe vertices, the base BVH plan, the
:class:`~mesh_tpu.anim.refit.RefitState`, and (on first query) the
fleet routing key — so the per-frame work is exactly: apply the vertex
delta, refit the frozen-layout BVH (or trip a rebuild past the
inflation bound), and run the exact traversal against the reused
compiled plan.  Because the routing key embeds the topology digest,
``fleet/router.py`` gives every frame of a session replica affinity
for free: the replica that built the plan keeps the session.

Each frame carries one ledger record (``op="anim_frame"``, tenant =
session id) with the new ``refit`` stage stamped between ``page_in``
and ``device`` — `mesh-tpu prof` breaks anim traffic down by refit vs
traversal cost like any other request.  Deadline-missed frames close
``deadline`` and count ``mesh_tpu_anim_frame_deadline_miss_total``;
a non-draining ``stop()`` (client gone) closes in-flight frames
``cancelled``, so the ledger leaks nothing (LED001).

Kill switch: ``MESH_TPU_ANIM=0`` makes every frame rebuild cold
through the digest-keyed ``get_index`` — bit-identical to the
pre-anim path (no refit stage, no refit arrays).
"""

import itertools
import threading

import numpy as np

from ..errors import MeshError
from ..obs.clock import monotonic
from ..obs.context import bind_context, mint as mint_context
from ..obs.ledger import get_ledger
from ..obs.recorder import get_recorder
from ..obs.trace import span as obs_span
from ..utils import knobs
from .refit import RefitState

__all__ = ["AvatarSession", "SessionClosed"]

_SESSION_SEQ = itertools.count(1)


class SessionClosed(MeshError):
    """The avatar session was stopped; frames are no longer accepted."""


def _metrics():
    from ..obs.metrics import REGISTRY

    return {
        "sessions": REGISTRY.gauge(
            "mesh_tpu_anim_sessions",
            "Open avatar stream sessions."),
        "frames": REGISTRY.counter(
            "mesh_tpu_anim_frames_total",
            "Session frames served (label: action — refit / "
            "rebuild / cold)."),
        "miss": REGISTRY.counter(
            "mesh_tpu_anim_frame_deadline_miss_total",
            "Session frames that finished after their per-frame "
            "deadline (label: tenant)."),
    }


class AvatarSession(object):
    """One client's animated-mesh stream over a fixed topology.

    Construct from a live keyframe mesh (``AvatarSession(mesh)``) or a
    store key (``AvatarSession(digest=...)`` — the keyframe pages in
    through the store).  Per frame, :meth:`frame` accepts either a
    vertex *delta* against the keyframe or absolute vertices, plus an
    optional query batch, and returns the query result dict with
    ``action`` (``refit`` / ``rebuild`` / ``cold``) and timing
    provenance.  Thread-safe; frames of one session serialize on the
    session lock (streams are ordered).
    """

    def __init__(self, mesh=None, digest=None, store=None, session_id=None,
                 leaf_size=None, kernel="host"):
        from ..accel.build import get_index, topology_digest

        if mesh is None and digest is None:
            raise ValueError("AvatarSession needs a keyframe mesh "
                             "or a store digest")
        if mesh is None:
            from ..store import get_store

            stored = (store or get_store()).open(digest, tier="exact")
            v_key = np.asarray(stored.v, np.float32)
            faces = np.asarray(stored.f, np.int32)
        else:
            v_key = np.asarray(mesh.v, np.float32)
            faces = np.asarray(mesh.f, np.int32)
            digest = topology_digest(v_key, faces)
        self.digest = digest
        self.v_key = v_key
        self.f = faces
        self.session_id = session_id or ("avatar-%d" % next(_SESSION_SEQ))
        self.leaf_size = leaf_size
        params = {} if leaf_size is None else {"leaf_size": int(leaf_size)}
        base = get_index(v_key, faces, kind="bvh", **params)
        self.refit_state = RefitState(base, faces, kernel=kernel)
        self.routing_key = None       # pinned on the first queried frame
        self._cond = threading.Condition()
        self._closed = False
        self._held = 0
        self._frame_seq = itertools.count()
        self._inflight = {}           # frame no -> RequestRecord
        self.frames = 0
        self.deadline_misses = 0
        _metrics()["sessions"].inc(1)
        get_recorder().record("anim.session_open", session=self.session_id,
                              digest=self.digest,
                              n_faces=int(faces.shape[0]))

    # -- per-frame ----------------------------------------------------

    def _vertices(self, delta, vertices):
        if (delta is None) == (vertices is None):
            raise ValueError("frame() wants exactly one of delta= / "
                             "vertices=")
        if delta is not None:
            delta = np.asarray(delta, np.float32)
            if delta.shape != self.v_key.shape:
                raise ValueError("delta shape %s != keyframe %s"
                                 % (delta.shape, self.v_key.shape))
            return self.v_key + delta
        vertices = np.asarray(vertices, np.float32)
        if vertices.shape != self.v_key.shape:
            raise ValueError("vertices shape %s != keyframe %s"
                             % (vertices.shape, self.v_key.shape))
        return vertices

    def frame(self, delta=None, vertices=None, points=None,
              deadline_s=None):
        """Serve one animation frame: apply the vertex update, refit
        (or rebuild, or — anim off — cold-build) the index, and answer
        the optional query batch exactly.

        Returns a dict: ``action``, ``index``, ``inflation``, and —
        when ``points`` were given — the facade-convention ``faces`` /
        ``points`` / ``sqdist`` arrays plus ``deadline_missed``."""
        from ..accel.build import get_index
        from ..accel.traverse import closest_faces_and_points_accel

        with self._cond:
            if self._closed:
                raise SessionClosed("session %s is stopped"
                                    % self.session_id)
            frame_no = next(self._frame_seq)
            # per-frame request identity: tenant is the session id, seq
            # the frame number, so a stream's frames join fleet-wide by
            # session (doc/observability.md request identity)
            ctx = mint_context(self.session_id, frame_no, monotonic(),
                               routing_key=self.routing_key,
                               session_id=self.session_id)
            rec = get_ledger().open(
                tenant=self.session_id, op="anim_frame", frame=frame_no,
                digest=self.digest,
                deadline_s=(None if deadline_s is None
                            else float(deadline_s)),
                **(ctx.to_meta() if ctx is not None else {}))
            if rec is not None:
                rec.ctx = ctx
                self._inflight[frame_no] = rec
        t0 = monotonic()
        out = {"frame": frame_no, "action": None, "inflation": None}
        try:
            with bind_context(ctx), \
                    obs_span("anim.frame", session=self.session_id,
                             frame=frame_no) as sp:
                if ctx is not None:
                    ctx.root_span_id = getattr(sp, "span_id", None)
                v_new = self._vertices(delta, vertices)
                if rec is not None:
                    rec.stamp("queue")
                if not knobs.flag("MESH_TPU_ANIM"):
                    # kill switch: the pre-anim path, bit for bit — a
                    # cold digest-keyed build, no refit arrays, no
                    # refit ledger stage
                    params = ({} if self.leaf_size is None
                              else {"leaf_size": int(self.leaf_size)})
                    index = get_index(v_new, self.f, kind="bvh", **params)
                    action = "cold"
                else:
                    index, action = self.refit_state.advance(v_new)
                    if rec is not None:
                        rec.stamp("refit")
                    out["inflation"] = self.refit_state.inflation
                out["action"] = action
                out["index"] = index
                _metrics()["frames"].inc(action=action)
                if points is not None:
                    res = closest_faces_and_points_accel(
                        v_new, self.f, points, index=index, record=rec)
                    out.update(faces=res["face"], points=res["point"],
                               sqdist=res["sqdist"])
                    if self.routing_key is None:
                        from ..fleet.router import routing_key

                        self.routing_key = routing_key(
                            "anim_frame", self.digest, points)
        except SessionClosed:
            raise
        except Exception as e:          # noqa: BLE001 — outcome must close
            self._finish(frame_no, rec, "error", error=type(e).__name__)
            raise
        latency = monotonic() - t0
        out["latency_s"] = latency
        missed = deadline_s is not None and latency > float(deadline_s)
        out["deadline_missed"] = missed
        if missed:
            self.deadline_misses += 1
            _metrics()["miss"].inc(tenant=self.session_id)
        self._finish(frame_no, rec, "deadline" if missed else "ok")
        self.frames += 1
        return out

    def _finish(self, frame_no, rec, outcome, **meta):
        # the in-flight entry is popped by whoever closes the record —
        # this frame on the serve path, stop(drain=False) on teardown —
        # so a record is closed exactly once (LED001)
        with self._cond:
            while self._held and not self._closed:
                self._cond.wait()
            if rec is not None:
                rec = self._inflight.pop(frame_no, None)
            if rec is None:
                return
        get_ledger().close(rec, outcome=outcome, **meta)

    # -- fences (tests) ------------------------------------------------

    def hold(self):
        """Fence frame finalization: frames compute but park before
        closing their ledger record until :meth:`release` (lets tests
        stop() a session with a deterministically in-flight frame)."""
        with self._cond:
            self._held += 1

    def release(self):
        with self._cond:
            self._held = max(0, self._held - 1)
            self._cond.notify_all()

    # -- lifecycle -----------------------------------------------------

    def stats(self):
        s = self.refit_state.stats()
        s.update(session=self.session_id, digest=self.digest,
                 frames=self.frames,
                 deadline_misses=self.deadline_misses,
                 routing_key=self.routing_key)
        return s

    def stop(self, drain=True):
        """End the session.  ``drain=True`` waits for in-flight frames
        to finish; ``drain=False`` (client gone) closes any in-flight
        frame's ledger record with outcome ``cancelled`` immediately —
        nothing leaks open."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            if drain:
                while self._inflight and not self._held:
                    self._cond.wait(timeout=0.1)
            pending = list(self._inflight.items())
            self._inflight.clear()
            self._cond.notify_all()
        ledger = get_ledger()
        for _frame_no, rec in pending:
            ledger.close(rec, outcome="cancelled")
        _metrics()["sessions"].inc(-1)
        get_recorder().record("anim.session_close", session=self.session_id,
                              frames=self.frames,
                              cancelled=len(pending))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
