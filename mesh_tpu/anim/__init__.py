"""Dynamic meshes: animation-rate BVH refit and avatar stream sessions.

The workload this package serves deforms a *fixed-topology* mesh every
frame (SMPL / FLAME / MANO body pipelines): the face buffer never
changes, only the vertex positions.  ``mesh_tpu/anim`` exploits that
end to end (doc/animation.md):

- :mod:`mesh_tpu.anim.refit` — bottom-up AABB refit over the frozen
  Morton order and preorder+skip rope layout of an existing
  :class:`~mesh_tpu.accel.build.AccelIndex`, with a tracked
  box-inflation ratio that trips a full rebuild through the digest
  cache when the frozen order decays past the
  ``anim_refit_max_inflation`` tunable.
- :mod:`mesh_tpu.anim.session` — serve-side avatar sessions: one
  pinned topology digest, plan, refit state, and fleet routing key
  per client; per-frame vertex deltas + queries at animation rate.

The vertex-delta store tier rides in :mod:`mesh_tpu.store.deltas`
(keyframe + uint16-quantized per-frame deltas), and the chip-free
``anim_proxy`` bench stage grades refit-vs-rebuild speedup against
``benchmarks/anim_golden.json``.

``MESH_TPU_ANIM=0`` is the kill switch: sessions fall back to a cold
``get_index`` build per frame — bit-identical to the pre-anim path.
"""

from .refit import RefitState, box_measure, refit_bvh, refit_max_inflation
from .session import AvatarSession, SessionClosed

__all__ = [
    "AvatarSession", "RefitState", "SessionClosed", "box_measure",
    "refit_bvh", "refit_max_inflation",
]
