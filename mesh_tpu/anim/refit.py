"""Animation-rate BVH refit over the frozen rope layout.

``build_bvh`` (accel/build.py) Morton-sorts faces once and lays the
complete tree out in preorder with skip ropes.  For a deforming
fixed-topology mesh the sort and the layout stay *valid* frame after
frame — only the boxes go stale.  ``refit_bvh`` therefore recomputes
node AABBs bottom-up from the deformed vertices over the SAME frozen
order, preorder positions, and centered build frame, and returns a new
:class:`~mesh_tpu.accel.build.AccelIndex` that shares every other
array, the digest, and the meta of the base index — so every frame of
a session reuses one compiled traversal plan instead of paying a host
sort + digest + build per frame.

Exactness is unconditional: refit boxes are true f32 min/max bounds of
the deformed triangles (exact lattice operations, no rounding), so the
rope walk prunes conservatively and the dense winner recompute in
``accel/traverse.py`` returns the true closest point — the same
conservative-certificate + dense-repair contract as a fresh build.
What decays is *pruning efficiency*: as triangles migrate, boxes of
the frozen Morton blocks inflate and overlap.  The certified quality
bound is the tracked **box-inflation ratio**

    inflation = box_measure(refit boxes) / box_measure(fresh boxes)

where the reference is captured at the last (re)build — refitting the
build geometry reproduces the build boxes exactly, so the ratio starts
at 1.0 by construction and grows only with real layout decay.  When it
crosses the ``anim_refit_max_inflation`` tunable (utils/tuning.py,
pinned by ``MESH_TPU_ANIM_REFIT_MAX_INFLATION``),
:meth:`RefitState.advance` trips a rebuild through the existing
digest-keyed ``get_index`` cache and re-anchors the reference.  The
bound governs performance only, never correctness (doc/animation.md
derives it).
"""

import threading

import numpy as np

from ..accel.build import AccelIndex, get_index

__all__ = [
    "RefitState", "box_measure", "refit_bvh", "refit_leaf_boxes",
    "refit_max_inflation",
]

def _metrics():
    from ..obs.metrics import REGISTRY

    return {
        "refits": REGISTRY.counter(
            "mesh_tpu_anim_refits_total",
            "Frames answered by a frozen-order BVH refit (no host "
            "rebuild)."),
        "rebuilds": REGISTRY.counter(
            "mesh_tpu_anim_rebuilds_total",
            "Refit frames that tripped a full rebuild through the "
            "digest cache (label: reason — inflation)."),
        "inflation": REGISTRY.gauge(
            "mesh_tpu_anim_inflation_ratio",
            "Latest refit-vs-rebuild box-inflation ratio (1.0 = "
            "fresh-build quality)."),
    }


def refit_max_inflation():
    """The effective refit/rebuild crossover: box-inflation ratio past
    which :meth:`RefitState.advance` trips a rebuild.  A bounded
    tunable (``anim_refit_max_inflation``) with the standard env pin
    and A/B-guarded actuation path."""
    from ..utils import tuning

    return float(tuning.get("anim_refit_max_inflation"))


def refit_leaf_boxes(tri_s, n_leaves, leaf_size):
    """Per-leaf AABBs of the Morton-ordered corner blocks — the numpy
    twin of the Pallas leaf-box kernel (accel/pallas_refit.py), and
    literally the builder's leaf stage over a frozen order."""
    blocks = np.asarray(tri_s, np.float32).reshape(
        n_leaves, leaf_size * 3, 3)
    return blocks.min(axis=1), blocks.max(axis=1)


def _level_boxes(lo_leaf, hi_leaf):
    """Internal levels bottom-up by pairwise min/max — bitwise the
    builder's reduction (build_bvh), just starting from refit leaves."""
    lo_levels = [np.asarray(lo_leaf, np.float32)]
    hi_levels = [np.asarray(hi_leaf, np.float32)]
    while lo_levels[-1].shape[0] > 1:
        lo_levels.append(
            np.minimum(lo_levels[-1][0::2], lo_levels[-1][1::2]))
        hi_levels.append(
            np.maximum(hi_levels[-1][0::2], hi_levels[-1][1::2]))
    lo_levels.reverse()
    hi_levels.reverse()
    return lo_levels, hi_levels


def _preorder_positions(depth):
    """The builder's level-by-level preorder scatter positions: level
    ``l``'s nodes land at ``pre`` computed by the same recurrence as
    build_bvh (pre(left) = pre(parent) + 1, pre(right) = pre(left) +
    subtree) — layout identity is what makes refit boxes drop into the
    frozen skip/leaf arrays unchanged."""
    positions = []
    pre = np.zeros(1, np.int64)
    for level in range(depth + 1):
        positions.append(pre)
        if level == depth:
            break
        subtree = (1 << (depth - level)) - 1
        pre_l = pre + 1
        pre_r = pre_l + subtree
        pre = np.stack([pre_l, pre_r], axis=1).reshape(-1)
    return positions


def box_measure(node_lo, node_hi):
    """Summed surface area of every node box (f64): the scalar the
    inflation ratio compares.  Surface area is the standard BVH quality
    functional (SAH): expected traversal cost is proportional to the
    summed area of the boxes a ray/query can intersect."""
    ext = np.maximum(
        np.asarray(node_hi, np.float64) - np.asarray(node_lo, np.float64),
        0.0)
    return float(2.0 * np.sum(
        ext[:, 0] * ext[:, 1] + ext[:, 1] * ext[:, 2]
        + ext[:, 0] * ext[:, 2]))


def refit_bvh(index, v, f, kernel="host", interpret=False):
    """Refit ``index`` (a ``kind="bvh"`` :class:`AccelIndex`) to the
    deformed vertices ``v`` over the same faces ``f``.

    Returns ``(refit_index, info)``.  The refit index shares the frozen
    ``order`` / ``node_skip`` / ``node_leaf`` / ``center`` arrays, the
    base digest, and the meta of ``index`` — two consequences: the
    compiled traversal plan is reused across frames (digest + meta are
    the plan's static identity), and only ``node_lo`` / ``node_hi`` are
    fresh.  The centered build frame is the FROZEN one (``center`` is
    an array of the base index, not recomputed), so boxes, queries,
    and prune slack stay in one coordinate system.

    ``kernel="pallas"`` computes the leaf boxes with the on-device
    Pallas kernel (accel/pallas_refit.py; ``interpret=True`` runs it
    chip-free) — bit-identical to the host path, which the anim bench
    stage asserts.  ``info`` carries ``box_measure`` for the caller's
    inflation tracking.
    """
    if index.kind != "bvh":
        raise ValueError("refit_bvh needs a bvh index, got %r" % index.kind)
    meta = index.meta
    arr = index.arrays
    leaf_size = int(meta["leaf_size"])
    n_leaves = int(meta["n_leaves"])
    depth = int(meta["depth"])
    n_nodes = int(meta["n_nodes"])

    v32 = np.asarray(v, np.float32)
    fi = np.asarray(f, np.int32)
    center = np.asarray(arr["center"], np.float32)
    order_p = np.asarray(arr["order"])
    vc = v32 - center                       # frozen build frame
    tri_s = vc[fi][order_p]                 # (Fp, 3, 3), frozen order

    if kernel == "pallas":
        from ..accel.pallas_refit import leaf_boxes_pallas

        lo_leaf, hi_leaf = leaf_boxes_pallas(
            tri_s, n_leaves, leaf_size, interpret=interpret)
        lo_leaf = np.asarray(lo_leaf)
        hi_leaf = np.asarray(hi_leaf)
    elif kernel == "host":
        lo_leaf, hi_leaf = refit_leaf_boxes(tri_s, n_leaves, leaf_size)
    else:
        raise ValueError("unknown refit kernel %r (host|pallas)" % kernel)

    lo_levels, hi_levels = _level_boxes(lo_leaf, hi_leaf)
    node_lo = np.empty((n_nodes, 3), np.float32)
    node_hi = np.empty((n_nodes, 3), np.float32)
    for level, pre in enumerate(_preorder_positions(depth)):
        node_lo[pre] = lo_levels[level]
        node_hi[pre] = hi_levels[level]

    refit = AccelIndex(
        index.kind, index.digest,
        arrays={
            "order": arr["order"],
            "node_lo": node_lo,
            "node_hi": node_hi,
            "node_skip": arr["node_skip"],
            "node_leaf": arr["node_leaf"],
            "center": arr["center"],
        },
        meta=dict(meta),
    )
    return refit, {"box_measure": box_measure(node_lo, node_hi)}


class RefitState(object):
    """Per-session refit bookkeeping: the live index, the fresh-build
    reference measure, and the tracked inflation ratio.

    :meth:`advance` is the one per-frame entry point: refit to the new
    vertices, compare against the reference captured at the last
    (re)build, and — past :func:`refit_max_inflation` — trip a rebuild
    through the digest-keyed ``get_index`` cache instead.  Thread-safe
    under its own lock (a session serializes frames anyway; the lock
    covers concurrent stat readers)."""

    def __init__(self, index, f, kernel="host"):
        self._lock = threading.Lock()
        self.index = index
        self.f = np.asarray(f, np.int32)
        self.kernel = kernel
        self.leaf_size = int(index.meta["leaf_size"])
        self.ref_measure = max(
            box_measure(index.arrays["node_lo"], index.arrays["node_hi"]),
            1e-30)
        self.inflation = 1.0
        self.refits = 0
        self.rebuilds = 0

    def advance(self, v_new, max_inflation=None):
        """Move the state to the deformed vertices; returns
        ``(index, action)`` with ``action`` in ``("refit", "rebuild")``.
        A rebuild resets the inflation reference to the fresh boxes."""
        if max_inflation is None:
            max_inflation = refit_max_inflation()
        metrics = _metrics()
        with self._lock:
            base = self.index
            ref_measure = self.ref_measure
        # The heavy work — refit, and on a trip the host rebuild (which
        # reaches store/side-car I/O) — runs OUTSIDE the lock: a session
        # serializes its frames, so `base` cannot change under us, and
        # concurrent stat readers never block behind a build.
        refit, info = refit_bvh(base, v_new, self.f, kernel=self.kernel)
        inflation = info["box_measure"] / ref_measure
        if inflation > max_inflation:
            # frozen-order quality decayed past the crossover: pay
            # one host rebuild (digest-cached — replaying the same
            # frame sequence rebuilds nothing) and re-anchor
            rebuilt = get_index(v_new, self.f, kind="bvh",
                                leaf_size=self.leaf_size)
            measure = max(box_measure(
                rebuilt.arrays["node_lo"],
                rebuilt.arrays["node_hi"]), 1e-30)
            with self._lock:
                self.index = rebuilt
                self.ref_measure = measure
                self.inflation = 1.0
                self.rebuilds += 1
            metrics["rebuilds"].inc(reason="inflation")
            metrics["inflation"].set(1.0)
            return rebuilt, "rebuild"
        with self._lock:
            self.index = refit
            self.inflation = float(inflation)
            self.refits += 1
        metrics["refits"].inc()
        metrics["inflation"].set(float(inflation))
        return refit, "refit"

    def stats(self):
        with self._lock:
            return {
                "refits": self.refits,
                "rebuilds": self.rebuilds,
                "inflation": self.inflation,
                "ref_measure": self.ref_measure,
            }
