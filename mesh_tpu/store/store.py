"""MeshStore: the content-addressed on-disk mesh corpus (doc/store.md).

Layout under one root (``MESH_TPU_STORE_DIR``)::

    <root>/objects/<digest>/manifest.json      object manifest (schema 2)
    <root>/objects/<digest>/exact/v_0000.npy   chunked exact-tier blocks
    <root>/objects/<digest>/compact/v_0000.npy quantized uint16 blocks
    <root>/objects/<digest>/sidecar/<tag>/     serialized AccelIndex
    <root>/objects/<digest>/last_used          LRU touch file (gc order)
    <root>/sequences/<digest>/<seq>/           anim delta frames (deltas.py)
    <root>/tmp/<digest>.<pid>.<n>/             staging (same filesystem)

Publishing is write-then-rename: an object is staged complete under
``tmp/`` and becomes visible with ONE ``os.rename`` of the directory,
so readers never observe a half-written object and two processes racing
the same digest publish exactly one copy (the rename loser discards its
staging and adopts the winner's object — content addressing makes both
byte-equivalent).  Every block CRC is verified on read; any mismatch
raises :class:`~mesh_tpu.errors.StoreCorrupt` after counting
``mesh_tpu_store_corrupt_total`` and dropping one rate-limited
flight-recorder incident — corruption is loud but never a crash loop.
"""

import json
import os
import shutil
import threading

import numpy as np

from ..errors import StoreCorrupt, StoreError
from ..obs.clock import monotonic, wall
from ..obs.trace import span as obs_span
from ..utils import knobs
from .blocks import (
    block_spans, dequantize_rows, file_crc32, quantize_rows, read_block,
    write_block,
)

__all__ = [
    "MeshStore", "StoredMesh", "default_store_root", "get_store",
    "MANIFEST_SCHEMA_VERSION",
]

#: manifest.json schema (bump on breaking shape changes); 2 adds the
#: ``anim_sequence`` manifest family under ``sequences/`` (store/deltas.py)
MANIFEST_SCHEMA_VERSION = 2

_STAGE_LOCK = threading.Lock()
_STAGE_SEQ = [0]


def default_store_root():
    """``MESH_TPU_STORE_DIR`` (expanded), default ``~/.mesh_tpu/store``."""
    return os.path.expanduser(
        knobs.get_str("MESH_TPU_STORE_DIR", None)
        or os.path.join("~", ".mesh_tpu", "store"))


_STORE = None
_STORE_LOCK = threading.Lock()


def get_store(root=None):
    """The process-wide :class:`MeshStore` over the knob-configured root
    (rebuilt when the knob moves the root, so tests can repoint it)."""
    global _STORE
    root = os.path.abspath(root or default_store_root())
    with _STORE_LOCK:
        if _STORE is None or _STORE.root != root:
            _STORE = MeshStore(root)
        return _STORE


def _metrics():
    from ..obs.metrics import REGISTRY

    return {
        "ingest": REGISTRY.counter(
            "mesh_tpu_store_ingest_total",
            "Meshes published into the store (label: tier — exact objects "
            "always, compact when the quantized tier is written, anim per "
            "delta sequence)."),
        "dedupe": REGISTRY.counter(
            "mesh_tpu_store_dedupe_total",
            "Ingests that found the digest already published (no bytes "
            "written)."),
        "corrupt": REGISTRY.counter(
            "mesh_tpu_store_corrupt_total",
            "Store reads that failed digest/CRC verification (label: what "
            "— block_crc / block_read / manifest / sidecar_digest / "
            "sidecar_crc / sidecar_meta / aot_meta / aot_version / "
            "aot_crc)."),
        "gc": REGISTRY.counter(
            "mesh_tpu_store_gc_deleted_total",
            "Objects and anim sequences deleted by the size-budgeted LRU "
            "gc."),
        "sidecar_writes": REGISTRY.counter(
            "mesh_tpu_store_sidecar_writes_total",
            "AccelIndex side-cars persisted next to store objects "
            "(label: kind)."),
        "bytes": REGISTRY.gauge(
            "mesh_tpu_store_bytes",
            "Total payload bytes across published objects (refreshed on "
            "ingest and gc)."),
        "open_hist": REGISTRY.histogram(
            "mesh_tpu_store_open_seconds",
            "Wall seconds to open (CRC-verify + map) one stored mesh "
            "(label: tier)."),
    }


def report_corrupt(what, digest, detail, recorder=None):
    """Count + flight-record one corruption observation.  The incident
    trigger is rate-limited (recorder default interval), so a corrupt
    object hammered by traffic produces one forensic dump, not a pile."""
    from ..obs.recorder import get_recorder

    _metrics()["corrupt"].inc(what=what)
    rec = recorder or get_recorder()
    rec.record("store.corrupt", what=what, digest=digest, detail=detail)
    rec.trigger("store_corrupt",
                context={"what": what, "digest": digest, "detail": detail})


class StoredMesh(object):
    """A (possibly mmap-backed) ``(v, f)`` holder straight off the
    store — duck-type compatible with every facade/engine/serve path
    that reads ``mesh.v`` / ``mesh.f`` (batch.stack_mesh_batch,
    serve/deadline._facade_arrays).  ``topology_key`` short-circuits the
    engine executor's coalescing-key CRC."""

    __slots__ = ("v", "f", "digest", "tier", "manifest")

    def __init__(self, v, f, digest, tier, manifest):
        self.v = v
        self.f = f
        self.digest = digest
        self.tier = tier
        self.manifest = manifest

    @property
    def topology_key(self):
        return self.digest

    def nbytes(self):
        return int(np.asarray(self.v).nbytes + np.asarray(self.f).nbytes)

    def to_mesh(self):
        from ..mesh import Mesh

        return Mesh(v=np.array(self.v), f=np.array(self.f))

    def __repr__(self):
        return "StoredMesh(digest=%r, tier=%r, v=%s, f=%s)" % (
            self.digest, self.tier, np.asarray(self.v).shape,
            np.asarray(self.f).shape)


class MeshStore(object):
    """One content-addressed corpus root; every method is safe to call
    concurrently from many threads/processes (publish is an atomic
    rename, reads only see published objects)."""

    def __init__(self, root=None):
        self.root = os.path.abspath(root or default_store_root())

    # -- paths ---------------------------------------------------------

    @property
    def objects_dir(self):
        return os.path.join(self.root, "objects")

    def object_dir(self, digest):
        self._check_key(digest)
        return os.path.join(self.objects_dir, digest)

    def manifest_path(self, digest):
        return os.path.join(self.object_dir(digest), "manifest.json")

    @staticmethod
    def _check_key(digest):
        if (not digest or os.path.sep in digest or digest != digest.strip()
                or digest.startswith(".")):
            raise StoreError("malformed store key %r" % (digest,))

    def _stage_dir(self, digest):
        with _STAGE_LOCK:
            _STAGE_SEQ[0] += 1
            seq = _STAGE_SEQ[0]
        stage = os.path.join(
            self.root, "tmp", "%s.%d.%d" % (digest, os.getpid(), seq))
        os.makedirs(stage)
        return stage

    def exists(self, digest):
        return os.path.isfile(self.manifest_path(digest))

    # -- ingest --------------------------------------------------------

    def ingest(self, v, f, source=None, block_rows=None, compact=None):
        """Publish ``(v, f)`` and return the store key (topology digest).

        Dedupe by content: an already-published digest touches the LRU
        stamp and returns immediately.  Otherwise the object is staged
        complete under ``tmp/`` (exact tier in the arrays' own dtypes,
        plus the quantized compact tier unless disabled) and published
        with one atomic directory rename — a lost publish race adopts
        the winner's object, so concurrent ingests of one digest yield
        exactly one copy."""
        from ..accel.build import topology_digest

        v = np.ascontiguousarray(np.asarray(v))
        f = np.ascontiguousarray(np.asarray(f))
        if v.ndim != 2 or v.shape[1] != 3:
            raise StoreError("vertices must be (N, 3), got %s"
                             % (v.shape,))
        if f.size and (f.ndim != 2 or f.shape[1] != 3):
            raise StoreError("faces must be (F, 3), got %s" % (f.shape,))
        f = f.reshape(-1, 3) if f.size else f.reshape(0, 3)
        digest = topology_digest(v, f)
        metrics = _metrics()
        with obs_span("store.ingest", digest=digest,
                      verts=int(v.shape[0]), faces=int(f.shape[0])) as sp:
            if self.exists(digest):
                metrics["dedupe"].inc()
                self._touch(digest)
                sp.set(dedupe=True)
                return digest
            if block_rows is None:
                block_rows = knobs.get_int("MESH_TPU_STORE_BLOCK_ROWS")
            if compact is None:
                compact = knobs.flag("MESH_TPU_STORE_COMPACT")
            stage = self._stage_dir(digest)
            try:
                manifest = self._write_object(stage, digest, v, f,
                                              block_rows, bool(compact),
                                              source)
                self._publish(stage, digest)
            finally:
                shutil.rmtree(stage, ignore_errors=True)
            metrics["ingest"].inc(tier="exact")
            if "compact" in manifest["tiers"]:
                metrics["ingest"].inc(tier="compact")
            metrics["bytes"].set(float(self.total_bytes()))
            sp.set(dedupe=False, bytes=manifest["bytes"])
        return digest

    def _write_object(self, stage, digest, v, f, block_rows, compact,
                      source):
        os.makedirs(os.path.join(stage, "exact"))
        tiers = {"exact": {}}
        total = 0
        for name, arr in (("v", v), ("f", f)):
            entries = []
            for i, (a, b) in enumerate(
                    block_spans(arr.shape[0], block_rows)):
                rel = "exact/%s_%04d.npy" % (name, i)
                crc, rows, nbytes = write_block(
                    os.path.join(stage, rel), arr[a:b])
                entries.append({"file": rel, "rows": rows, "crc32": crc})
                total += nbytes
            tiers["exact"][name] = entries
        if compact and v.size:
            os.makedirs(os.path.join(stage, "compact"))
            entries = []
            tolerance = 0.0
            for i, (a, b) in enumerate(block_spans(v.shape[0], block_rows)):
                q, lo, scale, tol = quantize_rows(v[a:b])
                rel = "compact/v_%04d.npy" % i
                crc, rows, nbytes = write_block(os.path.join(stage, rel), q)
                entries.append({
                    "file": rel, "rows": rows, "crc32": crc,
                    "lo": [float(x) for x in lo],
                    "scale": [float(x) for x in scale],
                })
                tolerance = max(tolerance, tol)
                total += nbytes
            tiers["compact"] = {"dtype": "uint16", "v": entries,
                                "tolerance": tolerance}
        manifest = {
            "schema_version": MANIFEST_SCHEMA_VERSION,
            "digest": digest,
            "created_utc": wall(),
            "n_vertices": int(v.shape[0]),
            "n_faces": int(f.shape[0]),
            "v_dtype": str(v.dtype),
            "f_dtype": str(f.dtype),
            "block_rows": int(max(1, block_rows)),
            "bytes": int(total),
            "tiers": tiers,
        }
        if source:
            manifest["source"] = dict(source)
        with open(os.path.join(stage, "manifest.json"), "w") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True)
            fh.write("\n")
        return manifest

    def _publish(self, stage, digest):
        os.makedirs(self.objects_dir, exist_ok=True)
        dest = self.object_dir(digest)
        try:
            os.rename(stage, dest)
        except OSError:
            # publish race (or leftover object): content addressing means
            # the published copy is byte-equivalent — adopt it
            if not self.exists(digest):
                raise
        self._touch(digest)

    def _touch(self, digest):
        # LRU stamp is a sibling touch file so the manifest stays
        # immutable (mmap readers never see it change)
        try:
            path = os.path.join(self.object_dir(digest), "last_used")
            with open(path, "a"):
                os.utime(path, None)
        except OSError:
            pass

    # -- read ----------------------------------------------------------

    def manifest(self, digest):
        """The parsed manifest; StoreError when absent, StoreCorrupt
        (counted + flight-recorded) when unreadable or digest-drifted."""
        path = self.manifest_path(digest)
        if not os.path.isfile(path):
            raise StoreError("no object %r in store %s"
                             % (digest, self.root))
        try:
            with open(path) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as exc:
            report_corrupt("manifest", digest, str(exc))
            raise StoreCorrupt("manifest for %s unreadable: %s"
                               % (digest, exc), what="manifest",
                               digest=digest)
        if manifest.get("digest") != digest:
            detail = ("manifest says digest %r" % manifest.get("digest"))
            report_corrupt("manifest", digest, detail)
            raise StoreCorrupt(
                "object %s manifest digest drift (%s)" % (digest, detail),
                what="manifest", digest=digest)
        return manifest

    def _tier_array(self, digest, manifest, tier, name, verify, mmap):
        entries = (manifest["tiers"].get(tier) or {}).get(name)
        if entries is None:
            raise StoreError("object %s has no %s/%s tier"
                             % (digest, tier, name))
        blocks = []
        for entry in entries:
            path = os.path.join(self.object_dir(digest), entry["file"])
            try:
                block = read_block(path, entry.get("crc32"), verify=verify,
                                   mmap=mmap)
            except StoreCorrupt as exc:
                report_corrupt(exc.what, digest, str(exc))
                raise StoreCorrupt(str(exc), what=exc.what, digest=digest)
            if int(block.shape[0]) != int(entry["rows"]):
                detail = ("%s has %d rows, manifest says %s"
                          % (entry["file"], block.shape[0], entry["rows"]))
                report_corrupt("block_read", digest, detail)
                raise StoreCorrupt("object %s truncated: %s"
                                   % (digest, detail), what="block_read",
                                   digest=digest)
            blocks.append(block)
        if not blocks:
            dtype = manifest["v_dtype"] if name == "v" \
                else manifest["f_dtype"]
            return np.zeros((0, 3), np.dtype(dtype))
        if len(blocks) == 1:
            return blocks[0]      # single block: stays mmap, zero-copy
        return np.concatenate([np.asarray(b) for b in blocks], axis=0)

    def open(self, digest, tier="exact", verify=None, mmap=True):
        """A :class:`StoredMesh` for ``digest``.  ``tier="exact"`` is a
        bit-identical (mmap-backed when single-block) view; ``compact``
        dequantizes the uint16 tier to float32 within the manifest's
        stated tolerance; ``anim:<sequence>:<frame>`` reconstructs one
        animation frame from the keyframe plus its quantized delta
        (store/deltas.py).  Every block CRC is checked unless
        ``MESH_TPU_STORE_VERIFY`` (or ``verify=``) turns it off."""
        if verify is None:
            verify = knobs.flag("MESH_TPU_STORE_VERIFY")
        if isinstance(tier, str) and tier.startswith("anim:"):
            from . import deltas as deltas_mod

            t0 = monotonic()
            with obs_span("store.open", digest=digest, tier=tier):
                mesh = deltas_mod.open_frame(self, digest, tier,
                                             verify=verify, mmap=mmap)
            _metrics()["open_hist"].observe(monotonic() - t0, tier="anim")
            return mesh
        t0 = monotonic()
        with obs_span("store.open", digest=digest, tier=tier):
            manifest = self.manifest(digest)
            faces = self._tier_array(digest, manifest, "exact", "f",
                                     verify, mmap)
            if tier == "exact":
                verts = self._tier_array(digest, manifest, "exact", "v",
                                         verify, mmap)
            elif tier == "compact":
                spec = manifest["tiers"].get("compact")
                if not spec:
                    raise StoreError("object %s has no compact tier"
                                     % digest)
                parts = []
                for entry in spec["v"]:
                    path = os.path.join(self.object_dir(digest),
                                        entry["file"])
                    try:
                        q = read_block(path, entry.get("crc32"),
                                       verify=verify, mmap=mmap)
                    except StoreCorrupt as exc:
                        report_corrupt(exc.what, digest, str(exc))
                        raise StoreCorrupt(str(exc), what=exc.what,
                                           digest=digest)
                    parts.append(dequantize_rows(
                        q, entry["lo"], entry["scale"]))
                verts = (np.concatenate(parts, axis=0) if parts
                         else np.zeros((0, 3), np.float32))
            else:
                raise StoreError(
                    "unknown tier %r (exact|compact|anim:<seq>:<frame>)"
                    % tier)
        self._touch(digest)
        _metrics()["open_hist"].observe(monotonic() - t0, tier=tier)
        return StoredMesh(verts, faces, digest, tier, manifest)

    # -- inventory / verify / gc --------------------------------------

    def ls(self):
        """Published digests, oldest-LRU first."""
        try:
            names = sorted(os.listdir(self.objects_dir))
        except FileNotFoundError:
            return []               # a fresh root IS an empty store; any
                                    # other OSError (file-as-root, perms)
                                    # must surface as unreadable instead
        out = [n for n in names
               if os.path.isfile(os.path.join(self.objects_dir, n,
                                              "manifest.json"))]
        out.sort(key=lambda n: self._last_used(n))
        return out

    def _last_used(self, digest):
        for name in ("last_used", "manifest.json"):
            try:
                return os.path.getmtime(
                    os.path.join(self.object_dir(digest), name))
            except OSError:
                continue
        return 0.0

    def stat(self, digest):
        """Manifest + size/sidecar summary for one object."""
        manifest = self.manifest(digest)
        return {
            "digest": digest,
            "n_vertices": manifest.get("n_vertices"),
            "n_faces": manifest.get("n_faces"),
            "v_dtype": manifest.get("v_dtype"),
            "f_dtype": manifest.get("f_dtype"),
            "bytes": self.object_bytes(digest),
            "tiers": sorted(manifest.get("tiers") or {}),
            "sidecars": self.sidecar_tags(digest),
            "created_utc": manifest.get("created_utc"),
            "source": manifest.get("source"),
        }

    def object_bytes(self, digest):
        total = 0
        for dirpath, _dirs, files in os.walk(self.object_dir(digest)):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    pass
        return int(total)

    def total_bytes(self):
        return int(sum(self.object_bytes(d) for d in self.ls())
                   + sum(self.sequence_bytes(d, s)
                         for d, s in self.list_sequences()))

    # -- anim sequences (codec lives in deltas.py) ---------------------

    @property
    def sequences_dir(self):
        return os.path.join(self.root, "sequences")

    def sequence_dir(self, digest, sequence_id):
        from . import deltas as deltas_mod

        self._check_key(digest)
        deltas_mod.check_sequence_id(sequence_id)
        return os.path.join(self.sequences_dir, digest, sequence_id)

    def list_sequences(self, digest=None):
        """Published ``(digest, sequence_id)`` pairs, oldest-LRU
        first (restricted to one keyframe digest when given)."""
        try:
            digests = [digest] if digest else sorted(
                os.listdir(self.sequences_dir))
        except FileNotFoundError:
            return []
        out = []
        for d in digests:
            base = os.path.join(self.sequences_dir, d)
            try:
                names = sorted(os.listdir(base))
            except OSError:
                continue
            out.extend(
                (d, s) for s in names
                if os.path.isfile(os.path.join(base, s, "manifest.json")))
        out.sort(key=lambda ds: self._seq_last_used(*ds))
        return out

    def sequence_manifest(self, digest, sequence_id, missing_ok=False):
        """The parsed sequence manifest; StoreError when absent (None
        with ``missing_ok``), StoreCorrupt (counted + flight-recorded)
        when unreadable or key-drifted."""
        path = os.path.join(self.sequence_dir(digest, sequence_id),
                            "manifest.json")
        if not os.path.isfile(path):
            if missing_ok:
                return None
            raise StoreError("no sequence %s/%s in store %s"
                             % (digest, sequence_id, self.root))
        try:
            with open(path) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as exc:
            report_corrupt("manifest", digest, str(exc))
            raise StoreCorrupt(
                "sequence %s/%s manifest unreadable: %s"
                % (digest, sequence_id, exc), what="manifest",
                digest=digest)
        if (manifest.get("kind") != "anim_sequence"
                or manifest.get("digest") != digest
                or manifest.get("sequence_id") != sequence_id):
            detail = ("manifest says %s/%s kind %r"
                      % (manifest.get("digest"),
                         manifest.get("sequence_id"),
                         manifest.get("kind")))
            report_corrupt("manifest", digest, detail)
            raise StoreCorrupt(
                "sequence %s/%s manifest drift (%s)"
                % (digest, sequence_id, detail), what="manifest",
                digest=digest)
        return manifest

    def _publish_sequence(self, stage, digest, sequence_id):
        dest = self.sequence_dir(digest, sequence_id)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        try:
            os.rename(stage, dest)
        except OSError:
            # publish race: the sequence is keyed by name, both writers
            # quantized against the same published keyframe — adopt
            if self.sequence_manifest(digest, sequence_id,
                                      missing_ok=True) is None:
                raise
        self._touch_sequence(digest, sequence_id)

    def _touch_sequence(self, digest, sequence_id):
        try:
            path = os.path.join(self.sequence_dir(digest, sequence_id),
                                "last_used")
            with open(path, "a"):
                os.utime(path, None)
        except OSError:
            pass

    def _seq_last_used(self, digest, sequence_id):
        for name in ("last_used", "manifest.json"):
            try:
                return os.path.getmtime(os.path.join(
                    self.sequence_dir(digest, sequence_id), name))
            except OSError:
                continue
        return 0.0

    def sequence_bytes(self, digest, sequence_id):
        total = 0
        for dirpath, _dirs, files in os.walk(
                self.sequence_dir(digest, sequence_id)):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    pass
        return int(total)

    def delete_sequence(self, digest, sequence_id):
        shutil.rmtree(self.sequence_dir(digest, sequence_id),
                      ignore_errors=True)
        # drop the now-empty per-digest directory so ls-style scans of
        # sequences/ stay proportional to live sequences
        try:
            os.rmdir(os.path.join(self.sequences_dir, digest))
        except OSError:
            pass

    def verify(self, digest=None, deep=True):
        """Verify one object (or every object): block CRCs, manifest
        digest, side-car digests/CRCs.  ``deep`` additionally recomputes
        the topology digest from the exact tier.  Returns a list of
        problem strings (empty = clean); each problem is also counted
        and flight-recorded."""
        if digest and not self.exists(digest):
            # naming an absent object is an argument error (CLI rc 2),
            # not a corruption finding
            raise StoreError("no such object %s" % digest)
        digests = [digest] if digest else self.ls()
        problems = []
        with obs_span("store.verify", objects=len(digests)):
            from . import deltas as deltas_mod

            for d in digests:
                problems.extend(self._verify_one(d, deep))
                for _d, seq in self.list_sequences(d):
                    problems.extend(
                        deltas_mod.verify_sequence(self, d, seq))
            if digest is None:
                # whole-store verify also audits the AOT executable
                # tier (store/aot.py) living next to the objects
                from . import aot as aot_mod

                problems.extend(aot_mod.verify_aot(self))
        return problems

    def _verify_one(self, digest, deep):
        from ..accel.build import topology_digest

        problems = []
        try:
            mesh = self.open(digest, verify=True)
        except (StoreError, StoreCorrupt) as exc:
            return ["%s: %s" % (digest, exc)]
        if deep:
            actual = topology_digest(mesh.v, mesh.f)
            if actual != digest:
                detail = "exact tier recomputes to %s" % actual
                report_corrupt("manifest", digest, detail)
                problems.append("%s: digest drift (%s)" % (digest, detail))
        spec = mesh.manifest["tiers"].get("compact")
        if spec:
            try:
                compact = self.open(digest, tier="compact", verify=True)
                err = float(np.max(np.abs(
                    np.asarray(compact.v, np.float64)
                    - np.asarray(mesh.v, np.float64)))) if mesh.v.size \
                    else 0.0
                if err > spec["tolerance"]:
                    report_corrupt(
                        "block_crc", digest,
                        "compact tier error %.3g > tolerance %.3g"
                        % (err, spec["tolerance"]))
                    problems.append(
                        "%s: compact tier error %.3g exceeds stated "
                        "tolerance %.3g" % (digest, err, spec["tolerance"]))
            except (StoreError, StoreCorrupt) as exc:
                problems.append("%s: %s" % (digest, exc))
        problems.extend(
            "%s: %s" % (digest, p)
            for p in self._verify_sidecars(digest))
        return problems

    def _verify_sidecars(self, digest):
        from . import sidecar as sidecar_mod

        problems = []
        for tag in self.sidecar_tags(digest):
            problems.extend(sidecar_mod.verify_sidecar(self, digest, tag))
        return problems

    def sidecar_tags(self, digest):
        base = os.path.join(self.object_dir(digest), "sidecar")
        try:
            return sorted(
                n for n in os.listdir(base)
                if os.path.isfile(os.path.join(base, n, "sidecar.json")))
        except OSError:
            return []

    def delete(self, digest):
        self._check_key(digest)
        shutil.rmtree(self.object_dir(digest), ignore_errors=True)

    def gc(self, budget_bytes=None, dry_run=False):
        """Size-budgeted, sequence-aware LRU gc: delete least-recently-
        used objects AND anim sequences until the corpus fits
        ``budget_bytes`` (default knob ``MESH_TPU_STORE_GC_MB``).

        A keyframe object is never removed while delta sequences still
        depend on it — evicting the base would orphan every frame — so
        pinned objects are skipped and whole sequences go oldest-first
        instead; once a digest's last sequence is gone the keyframe
        becomes evictable again (same call, second pass).  Returns the
        deleted keys: digests for objects, ``digest/sequence_id`` for
        sequences."""
        if budget_bytes is None:
            budget_bytes = int(
                knobs.get_float("MESH_TPU_STORE_GC_MB") * 1024 * 1024)
        deleted = []
        with obs_span("store.gc", budget_bytes=int(budget_bytes)) as sp:
            dependents = {}
            candidates = []
            for d, s in self.list_sequences():    # oldest-LRU first
                dependents[d] = dependents.get(d, 0) + 1
                candidates.append((self._seq_last_used(d, s), d, s,
                                   self.sequence_bytes(d, s)))
            for d in self.ls():
                candidates.append((self._last_used(d), d, None,
                                   self.object_bytes(d)))
            candidates.sort(key=lambda c: c[0])
            total = sum(c[3] for c in candidates)

            def _evict(digest, seq, size):
                if not dry_run:
                    if seq is None:
                        self.delete(digest)
                    else:
                        self.delete_sequence(digest, seq)
                    _metrics()["gc"].inc()
                deleted.append(digest if seq is None
                               else "%s/%s" % (digest, seq))
                return size

            pinned = []
            for _t, digest, seq, size in candidates:
                if total <= budget_bytes:
                    break
                if seq is None and dependents.get(digest):
                    pinned.append((digest, size))
                    continue
                total -= _evict(digest, seq, size)
                if seq is not None:
                    dependents[digest] -= 1
            # keyframes whose sequences all died above are fair game now
            for digest, size in pinned:
                if total <= budget_bytes:
                    break
                if dependents.get(digest):
                    continue
                total -= _evict(digest, None, size)
            if not dry_run:
                _metrics()["bytes"].set(float(total))
            sp.set(deleted=len(deleted), remaining_bytes=int(total))
        # leaked staging dirs from crashed writers age out here too
        self._sweep_tmp(dry_run)
        return deleted

    def _sweep_tmp(self, dry_run, max_age_s=3600.0):
        tmp = os.path.join(self.root, "tmp")
        try:
            names = os.listdir(tmp)
        except OSError:
            return
        now = wall()
        for name in names:
            path = os.path.join(tmp, name)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age > max_age_s and not dry_run:
                shutil.rmtree(path, ignore_errors=True)

    # -- side-cars (thin forwarders; the codec lives in sidecar.py) ----

    def put_sidecar(self, index, params=None):
        from . import sidecar as sidecar_mod

        return sidecar_mod.put_sidecar(self, index, params)

    def load_sidecar(self, digest, kind, params=None):
        from . import sidecar as sidecar_mod

        return sidecar_mod.load_sidecar(self, digest, kind, params)

    def sidecar_tag_exists(self, digest, kind, params=None):
        from . import sidecar as sidecar_mod

        tag = sidecar_mod.sidecar_tag(kind, params)
        return os.path.isfile(os.path.join(
            self.object_dir(digest), "sidecar", tag, "sidecar.json"))
