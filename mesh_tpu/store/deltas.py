"""Vertex-delta store tier: keyframe + uint16-quantized frame deltas.

An animation sequence over a fixed topology is keyed by
``(topology_digest, sequence_id, frame)``: the keyframe is a normal
store object (exact + compact tiers), and each frame is stored as a
uint16-quantized *delta* against the keyframe's exact vertices — a
fraction of the raw frame bytes, decoded straight back into the
accel-ready f32 layout.  Layout under the store root::

    <root>/sequences/<digest>/<sequence_id>/manifest.json
    <root>/sequences/<digest>/<sequence_id>/d_00000.npy   per-frame blocks
    <root>/sequences/<digest>/<sequence_id>/last_used     LRU touch (gc)

Sequence manifests carry the bumped store schema
(``MANIFEST_SCHEMA_VERSION`` = 2: schema 2 adds the anim sequence
manifest family next to object manifests), per-block CRCs, the frame's
quantization grid (``lo`` / ``scale``), and a TRUE reconstruction
bound like the compact tier: ``tolerance`` is the stated worst-case
``max |decoded - ingested f32 frame|``, taken as the max of the
analytic quantizer bound and the measured decode error at write time
(decode is bit-deterministic, so the measured error is a true bound
for every future read).  Publishing is the store's write-then-rename
protocol — readers never see a half-written sequence.

Frames page in through the existing ``store/pages.py`` PageCache using
the tier string ``anim:<sequence_id>:<frame>`` (``MeshStore.open``
dispatches it here), so resident frames cost zero disk reads and LRU
eviction is byte-budgeted with everything else.  ``MeshStore.gc`` is
sequence-aware: a keyframe object is never evicted while dependent
delta frames remain (doc/store.md, doc/animation.md).
"""

import json
import os

import numpy as np

from ..errors import StoreCorrupt, StoreError
from .blocks import dequantize_rows, quantize_rows, read_block, write_block

__all__ = [
    "ANIM_TIER_PREFIX", "frame_tier", "parse_tier", "read_frame",
    "resolve_frame", "sequence_tolerance", "verify_sequence",
    "write_sequence",
]

#: ``MeshStore.open`` tier prefix for delta frames
ANIM_TIER_PREFIX = "anim:"

_FRAME_FMT = "d_%05d.npy"


def frame_tier(sequence_id, frame):
    """The ``MeshStore.open`` / PageCache tier string for one frame."""
    return "%s%s:%d" % (ANIM_TIER_PREFIX, sequence_id, int(frame))


def parse_tier(tier):
    """``(sequence_id, frame)`` for an ``anim:<seq>:<frame>`` tier
    string, or ``None`` when ``tier`` is not a delta-frame tier."""
    if not isinstance(tier, str) or not tier.startswith(ANIM_TIER_PREFIX):
        return None
    body = tier[len(ANIM_TIER_PREFIX):]
    seq, sep, frame = body.rpartition(":")
    if not sep or not seq:
        raise StoreError("malformed anim tier %r "
                         "(want anim:<sequence>:<frame>)" % (tier,))
    try:
        return seq, int(frame)
    except ValueError:
        raise StoreError("malformed anim frame in tier %r" % (tier,))


def check_sequence_id(sequence_id):
    if (not sequence_id or os.path.sep in sequence_id or ":" in sequence_id
            or sequence_id != sequence_id.strip()
            or sequence_id.startswith(".")):
        raise StoreError("malformed sequence id %r" % (sequence_id,))
    return sequence_id


def write_sequence(store, digest, sequence_id, frames, source=None):
    """Publish an animation sequence of absolute per-frame vertex
    arrays as quantized deltas against the published keyframe object
    ``digest``; returns the sequence manifest.

    Dedupe by name: an already-published ``(digest, sequence_id)``
    touches its LRU stamp and returns the existing manifest.  The
    keyframe must already be ingested — deltas without their base are
    unreadable by construction."""
    from .store import MANIFEST_SCHEMA_VERSION, _metrics
    from ..obs.clock import wall
    from ..obs.trace import span as obs_span

    check_sequence_id(sequence_id)
    key = store.open(digest, tier="exact")      # raises when absent
    v_key = np.asarray(key.v, np.float32)
    existing = store.sequence_manifest(digest, sequence_id, missing_ok=True)
    if existing is not None:
        store._touch_sequence(digest, sequence_id)
        return existing

    frames = [np.asarray(fr, np.float32) for fr in frames]
    if not frames:
        raise StoreError("write_sequence needs at least one frame")
    for fr in frames:
        if fr.shape != v_key.shape:
            raise StoreError(
                "frame shape %s does not match keyframe %s"
                % (fr.shape, v_key.shape))

    with obs_span("store.ingest", digest=digest, sequence=sequence_id,
                  frames=len(frames)) as sp:
        stage = store._stage_dir("%s.%s" % (digest, sequence_id))
        blocks = []
        total = 0
        tolerance = 0.0
        try:
            for i, fr in enumerate(frames):
                delta = fr - v_key
                q, lo, scale, tol = quantize_rows(delta)
                rel = _FRAME_FMT % i
                crc, rows, nbytes = write_block(
                    os.path.join(stage, rel), q)
                # TRUE bound: analytic quantizer bound vs the measured
                # decode error of this exact frame (decode is
                # bit-deterministic, so measured is a true bound too)
                recon = v_key + dequantize_rows(q, lo, scale)
                err = float(np.max(np.abs(
                    np.asarray(recon, np.float64)
                    - np.asarray(fr, np.float64)))) if fr.size else 0.0
                f_tol = max(float(tol), err)
                blocks.append({
                    "file": rel, "frame": i, "rows": rows, "crc32": crc,
                    "lo": [float(x) for x in lo],
                    "scale": [float(x) for x in scale],
                    "tolerance": f_tol,
                })
                tolerance = max(tolerance, f_tol)
                total += nbytes
            manifest = {
                "schema_version": MANIFEST_SCHEMA_VERSION,
                "kind": "anim_sequence",
                "digest": digest,
                "sequence_id": sequence_id,
                "created_utc": wall(),
                "frames": len(frames),
                "n_vertices": int(v_key.shape[0]),
                "bytes": int(total),
                "tolerance": tolerance,
                "blocks": blocks,
            }
            if source:
                manifest["source"] = dict(source)
            with open(os.path.join(stage, "manifest.json"), "w") as fh:
                json.dump(manifest, fh, indent=1, sort_keys=True)
                fh.write("\n")
            store._publish_sequence(stage, digest, sequence_id)
        finally:
            import shutil

            shutil.rmtree(stage, ignore_errors=True)
        _metrics()["ingest"].inc(tier="anim")
        _metrics()["bytes"].set(float(store.total_bytes()))
        sp.set(bytes=total, tolerance=tolerance)
    return store.sequence_manifest(digest, sequence_id)


def _frame_entry(manifest, digest, frame):
    blocks = manifest.get("blocks") or []
    if not 0 <= int(frame) < len(blocks):
        raise StoreError(
            "sequence %s/%s has no frame %s (frames: %s)"
            % (digest, manifest.get("sequence_id"), frame,
               manifest.get("frames")))
    return blocks[int(frame)]


def read_frame(store, digest, sequence_id, frame, verify=None, mmap=True):
    """Reconstructed absolute f32 vertices of one frame: keyframe
    exact tier + dequantized delta, every block CRC-checked (unless
    ``MESH_TPU_STORE_VERIFY`` / ``verify=`` turns it off)."""
    from .store import report_corrupt
    from ..utils import knobs

    if verify is None:
        verify = knobs.flag("MESH_TPU_STORE_VERIFY")
    check_sequence_id(sequence_id)
    manifest = store.sequence_manifest(digest, sequence_id)
    entry = _frame_entry(manifest, digest, frame)
    path = os.path.join(
        store.sequence_dir(digest, sequence_id), entry["file"])
    try:
        q = read_block(path, entry.get("crc32"), verify=verify, mmap=mmap)
    except StoreCorrupt as exc:
        report_corrupt(exc.what, digest, str(exc))
        raise StoreCorrupt(str(exc), what=exc.what, digest=digest)
    if int(q.shape[0]) != int(entry["rows"]):
        detail = ("%s has %d rows, manifest says %s"
                  % (entry["file"], q.shape[0], entry["rows"]))
        report_corrupt("block_read", digest, detail)
        raise StoreCorrupt("sequence %s/%s truncated: %s"
                           % (digest, sequence_id, detail),
                           what="block_read", digest=digest)
    key = store.open(digest, tier="exact", verify=verify, mmap=mmap)
    v_key = np.asarray(key.v, np.float32)
    verts = v_key + dequantize_rows(q, entry["lo"], entry["scale"])
    store._touch_sequence(digest, sequence_id)
    return verts, np.asarray(key.f), manifest


def open_frame(store, digest, tier, verify=None, mmap=True):
    """``MeshStore.open`` dispatch target for ``anim:<seq>:<frame>``
    tiers: a :class:`StoredMesh` whose vertices are the reconstructed
    frame (within the manifest's stated ``tolerance``) over the
    keyframe's faces."""
    from .store import StoredMesh

    sequence_id, frame = parse_tier(tier)
    verts, faces, manifest = read_frame(
        store, digest, sequence_id, frame, verify=verify, mmap=mmap)
    return StoredMesh(verts, faces, digest, tier, manifest)


def resolve_frame(digest, sequence_id, frame, cache=None):
    """One frame through the serving tier's page cache:
    ``(StoredMesh, "resident" | "paged")``.  Resident frames cost no
    disk reads; misses page in under the ``store.page_in`` span like
    any other store tier."""
    from .pages import get_page_cache

    cache = cache or get_page_cache()
    return cache.resolve(digest, tier=frame_tier(sequence_id, frame))


def sequence_tolerance(manifest):
    """The sequence's TRUE worst-case reconstruction bound (meters, in
    vertex units): ``max |decoded - ingested f32 frame|`` over every
    frame."""
    return float(manifest.get("tolerance", 0.0))


def verify_sequence(store, digest, sequence_id):
    """CRC + shape audit of one sequence; returns problem strings
    (empty = clean).  Each problem is counted and flight-recorded by
    the shared corruption path."""
    problems = []
    try:
        manifest = store.sequence_manifest(digest, sequence_id)
    except (StoreError, StoreCorrupt) as exc:
        return ["%s/%s: %s" % (digest, sequence_id, exc)]
    for entry in manifest.get("blocks") or []:
        try:
            verts, _f, _m = read_frame(
                store, digest, sequence_id, entry["frame"], verify=True)
        except (StoreError, StoreCorrupt) as exc:
            problems.append("%s/%s: %s" % (digest, sequence_id, exc))
            continue
        if int(verts.shape[0]) != int(manifest["n_vertices"]):
            problems.append(
                "%s/%s: frame %s reconstructs %d vertices, manifest "
                "says %s" % (digest, sequence_id, entry["frame"],
                             verts.shape[0], manifest["n_vertices"]))
    return problems
