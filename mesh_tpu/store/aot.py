"""Persistent AOT executable tier: the XLA compilation cache homed
inside the content-addressed store (doc/fleet.md, doc/store.md).

A replica's cold start already skips BVH builds by loading accel
side-cars off the store; the compile analog is this tier.  Layout
under the store root::

    <root>/aot/xla/...        JAX's persistent compilation cache
                              (content-keyed executables, jax-owned)
    <root>/aot/index.json     schema + jax version + per-file CRC/bytes

``enable_aot_tier()`` points ``utils/compilation_cache`` at
``<root>/aot/xla`` so every sufficiently-slow compile lands next to
the side-cars it serves, and a second process's cold start loads the
executable from disk instead of recompiling — the ``compile``
ledger-stage delta and ``mesh_tpu_xla_cache_hits_total`` are the
evidence, graded by the ``fleet_proxy`` perfcheck band.

The cached executables are jax-owned opaque bytes, so the store audits
them the way it audits everything else: ``index_aot()`` snapshots the
tier into a CRC'd index (written stage-then-``os.replace`` atomic, the
side-car discipline), ``verify_aot()`` re-checks it for ``mesh-tpu
store verify``, and **enable-time validation quarantines instead of
crashing** — a schema/jax-version mismatch clears the whole tier, a
CRC-drifted file is deleted individually; either way the next compile
is fresh and the observation lands in the one-incident corruption
funnel (``mesh_tpu_store_corrupt_total{what=aot_meta|aot_version|
aot_crc}``, store.report_corrupt).  Files newer than the index (this
process's own compiles) are not findings; they are indexed at the next
``enable_aot_tier()``/``index_aot()``.

Opt out with ``MESH_TPU_FLEET_AOT=0`` (the compilation cache then
stays wherever ``MESH_TPU_XLA_CACHE`` points).  Stdlib-only; jax is
only touched by the underlying compilation-cache shim.
"""

import json
import logging
import os
import shutil

from ..utils import knobs
from .blocks import file_crc32

__all__ = [
    "AOT_SCHEMA_VERSION", "aot_dir", "aot_xla_dir", "aot_index_path",
    "enable_aot_tier", "index_aot", "verify_aot",
]

_log = logging.getLogger(__name__)

#: aot/index.json schema (bump on breaking shape changes)
AOT_SCHEMA_VERSION = 1


def aot_dir(store):
    return os.path.join(store.root, "aot")


def aot_xla_dir(store):
    return os.path.join(aot_dir(store), "xla")


def aot_index_path(store):
    return os.path.join(aot_dir(store), "index.json")


def _jax_version():
    try:
        import jax

        return jax.__version__
    except Exception:
        return None


def _scan(store):
    """relpath -> absolute path for every cached executable file.

    ``*-atime`` entries are jax's LRU access-time markers, rewritten on
    every cache *read* — content-stable CRCs don't exist for them, so
    they stay out of the index (and therefore out of verify/quarantine).
    """
    base = aot_xla_dir(store)
    out = {}
    for dirpath, _dirs, files in os.walk(base):
        for name in files:
            if name.endswith("-atime"):
                continue
            path = os.path.join(dirpath, name)
            out[os.path.relpath(path, base)] = path
    return out


def _read_index(store):
    """(index dict, problem) — problem is a string when the index file
    exists but cannot be trusted; (None, None) when absent."""
    path = aot_index_path(store)
    if not os.path.isfile(path):
        return None, None
    try:
        with open(path, "r", encoding="utf-8") as fh:
            index = json.load(fh)
    except (OSError, ValueError) as exc:
        return None, "aot index unreadable: %s" % exc
    if index.get("schema_version") != AOT_SCHEMA_VERSION:
        return index, ("aot index schema %r != %d"
                       % (index.get("schema_version"), AOT_SCHEMA_VERSION))
    return index, None


def index_aot(store):
    """Snapshot the tier into ``aot/index.json`` (atomic replace) and
    return the index dict.  Call after compiles have landed (enable
    does it for the previous process's output)."""
    files = {}
    for rel, path in sorted(_scan(store).items()):
        try:
            files[rel] = {"crc32": file_crc32(path),
                          "bytes": int(os.path.getsize(path))}
        except OSError:
            continue            # racing eviction: skip, not fatal
    index = {
        "schema_version": AOT_SCHEMA_VERSION,
        "jax_version": _jax_version(),
        "files": files,
    }
    os.makedirs(aot_dir(store), exist_ok=True)
    tmp = aot_index_path(store) + ".tmp.%d" % os.getpid()
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(index, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, aot_index_path(store))
    return index


def verify_aot(store):
    """Problem strings (empty = clean) for the AOT tier: readable
    index, every indexed file present with its recorded CRC.  Read-only
    (``mesh-tpu store verify`` surfaces these; quarantine happens at
    enable time).  Each finding is counted + flight-recorded through
    the store corruption funnel."""
    from .store import report_corrupt

    index, meta_problem = _read_index(store)
    if meta_problem:
        report_corrupt("aot_meta", "aot", meta_problem)
        return ["aot: %s" % meta_problem]
    if index is None:
        # no index at all: a fresh tier that was never enabled/indexed,
        # not corruption (enable_aot_tier writes the first index)
        return []
    problems = []
    current = _jax_version()
    recorded = index.get("jax_version")
    if recorded and current and recorded != current:
        detail = ("aot tier compiled under jax %s, running %s"
                  % (recorded, current))
        report_corrupt("aot_version", "aot", detail)
        problems.append("aot: %s" % detail)
    base = aot_xla_dir(store)
    for rel, entry in sorted(index.get("files", {}).items()):
        path = os.path.join(base, rel)
        if not os.path.isfile(path):
            detail = "aot file %s missing" % rel
            report_corrupt("aot_crc", "aot", detail)
            problems.append("aot: %s" % detail)
            continue
        actual = file_crc32(path)
        if actual != entry.get("crc32"):
            detail = ("aot file %s CRC mismatch (%s vs %s)"
                      % (rel, actual, entry.get("crc32")))
            report_corrupt("aot_crc", "aot", detail)
            problems.append("aot: %s" % detail)
    return problems


def _quarantine(store, index, meta_problem):
    """Enable-time validation: never let a bad cached executable reach
    XLA.  Meta/schema/version problems clear the whole tier; CRC drift
    deletes the drifted file.  Either way the next compile is fresh —
    the corruption funnel records it, nothing crashes."""
    from .store import report_corrupt

    base = aot_xla_dir(store)
    if meta_problem:
        report_corrupt("aot_meta", "aot", meta_problem)
        shutil.rmtree(base, ignore_errors=True)
        try:
            os.remove(aot_index_path(store))
        except OSError:
            pass
        return
    if index is None:
        return
    current = _jax_version()
    recorded = index.get("jax_version")
    if recorded and current and recorded != current:
        detail = ("aot tier compiled under jax %s, running %s; "
                  "clearing for fresh compiles" % (recorded, current))
        report_corrupt("aot_version", "aot", detail)
        shutil.rmtree(base, ignore_errors=True)
        try:
            os.remove(aot_index_path(store))
        except OSError:
            pass
        return
    for rel, entry in sorted(index.get("files", {}).items()):
        path = os.path.join(base, rel)
        if not os.path.isfile(path):
            continue            # evicted/missing: jax just recompiles
        try:
            drifted = file_crc32(path) != entry.get("crc32")
        except OSError:
            drifted = True
        if drifted:
            detail = "aot file %s CRC drift; deleting" % rel
            report_corrupt("aot_crc", "aot", detail)
            try:
                os.remove(path)
            except OSError:
                pass


def enable_aot_tier(store=None, min_compile_secs=1.0):
    """Home the persistent XLA compilation cache at ``<store>/aot/xla``.

    Validates (and quarantines) whatever a previous process left,
    refreshes the index over the survivors, then points
    ``utils/compilation_cache`` at the tier.  Gated by
    ``MESH_TPU_FLEET_AOT``; returns the cache dir in use or None
    (disabled / cache unavailable).  Never raises.
    """
    if not knobs.flag("MESH_TPU_FLEET_AOT"):
        return None
    try:
        if store is None:
            from .store import get_store

            store = get_store()
        index, meta_problem = _read_index(store)
        _quarantine(store, index, meta_problem)
        os.makedirs(aot_xla_dir(store), exist_ok=True)
        index_aot(store)
    except Exception as exc:    # the tier must never break real work
        _log.warning("aot tier unavailable: %s", exc)
        return None
    from ..utils.compilation_cache import (
        enable_persistent_compilation_cache,
    )

    return enable_persistent_compilation_cache(
        path=aot_xla_dir(store), min_compile_secs=min_compile_secs)
