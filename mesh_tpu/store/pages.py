"""In-process digest-keyed page cache over the mesh store.

The serving tier accepts a *store key* (topology digest) in place of a
mesh; :func:`PageCache.resolve` turns it into a ready
:class:`~mesh_tpu.store.store.StoredMesh`, LRU-bounded by a byte budget
(``MESH_TPU_STORE_PAGE_CACHE_MB``).  "Paged" vs "resident" is the
ledger-provenance distinction the serve integration records: a resident
hit costs a dict lookup; a paged miss walks ``store.open`` (CRC verify
+ mmap) under a ``store.page_in`` span.
"""

import threading
from collections import OrderedDict

from ..obs.trace import span as obs_span
from ..utils import knobs

__all__ = ["PageCache", "get_page_cache", "clear_page_cache"]


def _metrics():
    from ..obs.metrics import REGISTRY

    return (
        REGISTRY.counter(
            "mesh_tpu_store_page_cache_hits_total",
            "Store-key resolutions served by the resident page cache."),
        REGISTRY.counter(
            "mesh_tpu_store_page_cache_misses_total",
            "Store-key resolutions that paged the mesh in from disk."),
        REGISTRY.gauge(
            "mesh_tpu_store_page_cache_bytes",
            "Mesh bytes currently resident in the page cache."),
    )


class PageCache(object):
    """Byte-budgeted LRU of StoredMesh objects keyed by
    ``(digest, tier)`` — exact, compact, and anim delta-frame tiers of
    one digest are independent pages."""

    def __init__(self, budget_bytes=None, store=None):
        self._budget = budget_bytes
        self._store = store
        self._lock = threading.Lock()
        self._cache = OrderedDict()          # (digest, tier) -> StoredMesh
        self._bytes = 0

    @property
    def budget_bytes(self):
        if self._budget is not None:
            return int(self._budget)
        return int(knobs.get_float("MESH_TPU_STORE_PAGE_CACHE_MB")
                   * 1024 * 1024)

    def _get_store(self):
        if self._store is not None:
            return self._store
        from .store import get_store

        return get_store()

    def resolve(self, digest, tier="exact"):
        """``(mesh, provenance)`` for a store key; provenance is
        ``"resident"`` on a cache hit, ``"paged"`` when the mesh came
        off disk this call.  Raises StoreError/StoreCorrupt upward —
        admission already happened, the serve tier maps these to a
        request error."""
        hits, misses, gauge = _metrics()
        key = (digest, tier)
        with self._lock:
            mesh = self._cache.get(key)
            if mesh is not None:
                self._cache.move_to_end(key)
                hits.inc()
                return mesh, "resident"
        misses.inc()
        with obs_span("store.page_in", digest=digest, tier=tier):
            mesh = self._get_store().open(digest, tier=tier)
        nbytes = mesh.nbytes()
        with self._lock:
            prev = self._cache.pop(key, None)
            if prev is not None:
                self._bytes -= prev.nbytes()
            self._cache[key] = mesh
            self._bytes += nbytes
            budget = self.budget_bytes
            while self._bytes > budget and len(self._cache) > 1:
                _, old = self._cache.popitem(last=False)
                self._bytes -= old.nbytes()
            gauge.set(float(self._bytes))
        return mesh, "paged"

    def drop(self, digest=None):
        with self._lock:
            if digest is None:
                self._cache.clear()
                self._bytes = 0
            else:
                # every resident tier/frame of the digest goes at once
                for key in [k for k in self._cache if k[0] == digest]:
                    self._bytes -= self._cache.pop(key).nbytes()
            _metrics()[2].set(float(self._bytes))

    def info(self):
        with self._lock:
            return {
                "entries": len(self._cache),
                "bytes": int(self._bytes),
                "budget_bytes": self.budget_bytes,
                "digests": sorted({k[0] for k in self._cache}),
            }


_PAGE_CACHE = None
_PAGE_LOCK = threading.Lock()


def get_page_cache():
    """The process-wide page cache (knob-budgeted)."""
    global _PAGE_CACHE
    with _PAGE_LOCK:
        if _PAGE_CACHE is None:
            _PAGE_CACHE = PageCache()
        return _PAGE_CACHE


def clear_page_cache():
    global _PAGE_CACHE
    with _PAGE_LOCK:
        if _PAGE_CACHE is not None:
            _PAGE_CACHE.drop()
        _PAGE_CACHE = None
