"""Chunked block codec for the mesh store (doc/store.md).

An array tier is a list of row-contiguous ``.npy`` blocks, each with a
CRC32 over the whole file bytes recorded in the object manifest and
re-checked on read — a truncated or bit-flipped block can never be
returned as mesh data.  Two tiers share the layout:

- **exact** — the ingested array's own dtype, bit-identical round trip;
- **compact** — per-block uint16 quantization with the per-axis
  ``lo``/``scale`` recorded next to each block's CRC; the manifest
  states the worst-case per-coordinate absolute error (``scale / 2``).

Blocks are plain ``np.save`` output so a single-block tier can be
served straight off ``np.load(mmap_mode="r")`` with zero copies — the
cold-start path the side-car contract depends on.
"""

import os
import zlib

import numpy as np

from ..errors import StoreCorrupt, StoreError  # noqa: F401 — facade

__all__ = [
    "StoreError", "StoreCorrupt", "block_spans", "write_block",
    "read_block", "quantize_rows", "dequantize_rows", "file_crc32",
]

#: quantization levels per axis in the compact tier (uint16)
_Q_LEVELS = 65535


def block_spans(n_rows, block_rows):
    """Row ranges [(start, stop), ...] chunking ``n_rows`` into blocks
    of at most ``block_rows`` (empty list for an empty array)."""
    block_rows = max(1, int(block_rows))
    return [(start, min(start + block_rows, int(n_rows)))
            for start in range(0, int(n_rows), block_rows)]


def file_crc32(path):
    """CRC32 over a file's raw bytes, as the 8-hex-digit string the
    manifest records."""
    crc = 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return "%08x" % (crc & 0xFFFFFFFF)


def write_block(path, arr):
    """Write one ``.npy`` block and return its manifest entry fields
    ``(crc32_hex, rows, nbytes)``.  The array lands contiguous in its
    own dtype, so the exact tier is a bit-identical round trip."""
    arr = np.ascontiguousarray(arr)
    with open(path, "wb") as fh:
        np.save(fh, arr, allow_pickle=False)
    return file_crc32(path), int(arr.shape[0]), int(os.path.getsize(path))


def read_block(path, crc32_hex=None, verify=True, mmap=True):
    """Read one block back; CRC-verify the file bytes first (cheap —
    one sequential pass that also warms the page cache the subsequent
    mmap reads from).  Raises :class:`StoreCorrupt` on any mismatch or
    short/unreadable file."""
    try:
        if verify and crc32_hex is not None:
            actual = file_crc32(path)
            if actual != crc32_hex:
                raise StoreCorrupt(
                    "block %s CRC mismatch: %s on disk vs %s in manifest"
                    % (path, actual, crc32_hex), what="block_crc")
        return np.load(path, mmap_mode="r" if mmap else None,
                       allow_pickle=False)
    except StoreCorrupt:
        raise
    except (OSError, ValueError) as exc:
        raise StoreCorrupt("block %s unreadable: %s" % (path, exc),
                           what="block_read")


def quantize_rows(arr):
    """Quantize one float block to uint16: returns ``(q, lo, scale,
    tolerance)`` with ``dequant = lo + q * scale``.  ``tolerance`` is a
    TRUE worst-case per-coordinate absolute bound for the float32
    reconstruction: the quantization half-step ``max(scale) / 2`` plus
    the float32 rounding of the largest representable value.  Degenerate
    axes (zero span) get scale 0 and reconstruct exactly."""
    a = np.asarray(arr, np.float64)
    if a.size == 0:
        return (np.zeros(a.shape, np.uint16), np.zeros(a.shape[-1]),
                np.zeros(a.shape[-1]), 0.0)
    lo = a.min(axis=0)
    hi = a.max(axis=0)
    scale = (hi - lo) / float(_Q_LEVELS)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.rint((a - lo) / safe), 0, _Q_LEVELS).astype(np.uint16)
    cast_ulp = float(np.max(np.maximum(np.abs(lo), np.abs(hi)))) \
        * float(np.finfo(np.float32).eps)
    tolerance = float(scale.max() / 2.0) + cast_ulp if scale.size else 0.0
    return q, lo, scale, tolerance


def dequantize_rows(q, lo, scale, dtype=np.float32):
    """Reconstruct a quantized block (see :func:`quantize_rows`)."""
    lo = np.asarray(lo, np.float64)
    scale = np.asarray(scale, np.float64)
    return (lo + np.asarray(q, np.float64) * scale).astype(dtype)
