"""Content-addressed on-disk mesh corpus (doc/store.md).

Objects are keyed by the same topology digest the accel index cache
uses (``accel/build.py:topology_digest``), so a mesh, its spatial-index
side-car, and its engine plan companion all share one identity.  The
package is numpy + stdlib only at import time — the jax-free ``mesh-tpu
store`` CLI subcommands sit directly on it, and the accel side-car
consult path only imports jax lazily (through accel.build) when an
index object is actually materialized.
"""

from .blocks import quantize_rows, dequantize_rows  # noqa: F401
from .store import (  # noqa: F401
    MeshStore, StoredMesh, default_store_root, get_store,
)
from .pages import PageCache, get_page_cache, clear_page_cache  # noqa: F401

__all__ = [
    "MeshStore", "StoredMesh", "default_store_root", "get_store",
    "PageCache", "get_page_cache", "clear_page_cache",
    "quantize_rows", "dequantize_rows",
]
