"""AccelIndex side-car persistence (doc/store.md, side-car contract).

A side-car is the serialized form of one :class:`~mesh_tpu.accel.build.
AccelIndex` living inside the store object it indexes::

    objects/<digest>/sidecar/<tag>/sidecar.json   kind/digest/params/meta
    objects/<digest>/sidecar/<tag>/<name>.npy     one CRC'd block per array

``tag`` encodes the builder kind plus a CRC of the non-default build
params, so ``get_index(v, f, "bvh")`` and ``get_index(v, f, "bvh",
leaf_size=4)`` keep distinct side-cars.  Loading mmaps every array —
a cold replica serves its first query off the page cache without a
host build.  Every load re-checks the side-car's recorded digest
against the digest the caller derived from the mesh bytes (a stale
side-car next to drifted mesh data is *corruption*, not a fallback
tier) and each array's CRC; any failure counts
``mesh_tpu_store_corrupt_total``, drops one rate-limited
flight-recorder incident, and returns ``None`` so the caller falls
back to the host build — never a crash.
"""

import json
import os
import shutil
import zlib

import numpy as np

from ..errors import StoreCorrupt
from ..obs.trace import span as obs_span
from .blocks import file_crc32, read_block, write_block

__all__ = [
    "sidecar_tag", "put_sidecar", "load_sidecar", "verify_sidecar",
    "SIDECAR_SCHEMA_VERSION",
]

SIDECAR_SCHEMA_VERSION = 1


def sidecar_tag(kind, params=None):
    """Filesystem-safe side-car directory name for a builder invocation:
    the kind alone for default params, ``kind-<crc>`` otherwise."""
    items = tuple(sorted((params or {}).items()))
    if not items:
        return str(kind)
    blob = json.dumps(items, sort_keys=True).encode()
    return "%s-%08x" % (kind, zlib.crc32(blob) & 0xFFFFFFFF)


def put_sidecar(store, index, params=None):
    """Persist ``index`` next to its store object (which must already be
    published — a side-car without its mesh is unservable).  Atomic via
    the same stage-then-rename discipline as object publish; a lost race
    keeps the winner.  Returns the tag."""
    from .store import _metrics

    digest = index.digest
    obj_dir = store.object_dir(digest)
    if not store.exists(digest):
        raise StoreCorrupt(
            "cannot attach side-car: object %s not in store" % digest,
            what="sidecar_meta", digest=digest)
    tag = sidecar_tag(index.kind, params)
    with obs_span("store.sidecar_write", digest=digest, tag=tag):
        stage = store._stage_dir(digest)
        try:
            arrays = {}
            for name in sorted(index.arrays):
                arr = np.asarray(index.arrays[name])
                rel = "%s.npy" % name
                crc, _rows, _nbytes = write_block(
                    os.path.join(stage, rel), arr)
                arrays[name] = {
                    "file": rel, "crc32": crc,
                    "dtype": str(arr.dtype),
                    "shape": [int(s) for s in arr.shape],
                }
            doc = {
                "schema_version": SIDECAR_SCHEMA_VERSION,
                "kind": index.kind,
                "digest": digest,
                "params": dict(params or {}),
                "meta": dict(index.meta),
                "arrays": arrays,
            }
            with open(os.path.join(stage, "sidecar.json"), "w") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
                fh.write("\n")
            dest = os.path.join(obj_dir, "sidecar", tag)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            try:
                os.rename(stage, dest)
            except OSError:
                if not os.path.isfile(os.path.join(dest, "sidecar.json")):
                    raise
        finally:
            shutil.rmtree(stage, ignore_errors=True)
    _metrics()["sidecar_writes"].inc(kind=index.kind)
    return tag


def _read_doc(store, digest, tag):
    path = os.path.join(store.object_dir(digest), "sidecar", tag,
                        "sidecar.json")
    if not os.path.isfile(path):
        return None
    with open(path) as fh:
        return json.load(fh)


def load_sidecar(store, digest, kind, params=None, verify=True):
    """Rehydrate one side-car as a live :class:`AccelIndex` with
    mmap-backed arrays, or ``None`` when absent or corrupt (corruption
    is counted + flight-recorded; the caller host-builds instead)."""
    from ..accel.build import AccelIndex
    from .store import report_corrupt

    tag = sidecar_tag(kind, params)
    base = os.path.join(store.object_dir(digest), "sidecar", tag)
    with obs_span("store.sidecar_load", digest=digest, tag=tag) as sp:
        try:
            doc = _read_doc(store, digest, tag)
        except (OSError, ValueError) as exc:
            report_corrupt("sidecar_meta", digest,
                           "%s: %s" % (tag, exc))
            return None
        if doc is None:
            sp.set(outcome="absent")
            return None
        if doc.get("digest") != digest or doc.get("kind") != kind:
            report_corrupt(
                "sidecar_digest", digest,
                "side-car %s records digest=%r kind=%r (stale/drifted)"
                % (tag, doc.get("digest"), doc.get("kind")))
            sp.set(outcome="stale")
            return None
        arrays = {}
        try:
            for name, entry in doc.get("arrays", {}).items():
                arr = read_block(
                    os.path.join(base, entry["file"]),
                    entry.get("crc32"), verify=verify, mmap=True)
                if (list(arr.shape) != list(entry.get("shape", []))
                        or str(arr.dtype) != entry.get("dtype")):
                    raise StoreCorrupt(
                        "side-car array %s shape/dtype drift" % name,
                        what="sidecar_crc", digest=digest)
                arrays[name] = arr
        except StoreCorrupt as exc:
            what = "sidecar_crc" if exc.what == "block_crc" else exc.what
            report_corrupt(what, digest, "%s: %s" % (tag, exc))
            sp.set(outcome="corrupt")
            return None
        except (KeyError, OSError, ValueError) as exc:
            report_corrupt("sidecar_meta", digest, "%s: %s" % (tag, exc))
            sp.set(outcome="corrupt")
            return None
        sp.set(outcome="hit", arrays=len(arrays))
        return AccelIndex(kind, digest, arrays, doc.get("meta", {}))


def verify_sidecar(store, digest, tag):
    """Problem strings (empty = clean) for one side-car: readable json,
    digest match, per-array CRCs.  Used by ``mesh-tpu store verify``."""
    base = os.path.join(store.object_dir(digest), "sidecar", tag)
    try:
        doc = _read_doc(store, digest, tag)
    except (OSError, ValueError) as exc:
        return ["sidecar %s unreadable: %s" % (tag, exc)]
    if doc is None:
        return ["sidecar %s missing sidecar.json" % tag]
    problems = []
    if doc.get("digest") != digest:
        problems.append("sidecar %s digest drift (records %r)"
                        % (tag, doc.get("digest")))
    for name, entry in sorted(doc.get("arrays", {}).items()):
        path = os.path.join(base, entry.get("file", ""))
        if not os.path.isfile(path):
            problems.append("sidecar %s array %s missing" % (tag, name))
            continue
        actual = file_crc32(path)
        if actual != entry.get("crc32"):
            problems.append(
                "sidecar %s array %s CRC mismatch (%s vs %s)"
                % (tag, name, actual, entry.get("crc32")))
    return problems
