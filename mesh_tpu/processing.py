"""In-place mesh edits (reference mesh/processing.py, free functions bound as
Mesh methods).

These are host-side, setup-time operations on numpy-backed attributes; the
reference's per-face Python loops (subdivide_triangles' O(F^2) vstack loop,
processing.py:125-155; flip_faces' row loop, processing.py:98-105) are
vectorized.  Rotation goes through the in-package Rodrigues implementation
instead of cv2 (processing.py:113-117).
"""

import numpy as np


def reset_normals(self, face_to_verts_sparse_matrix=None, reset_face_normals=False):
    self.vn = self.estimate_vertex_normals(face_to_verts_sparse_matrix)
    if reset_face_normals:
        self.fn = self.f.copy()
    return self


def reset_face_normals(self):
    if not hasattr(self, "vn"):
        self.reset_normals()
    self.fn = self.f
    return self


def uniquified_mesh(self):
    """A copy in which each vertex appears in exactly one face
    (reference processing.py:31-45) — needed for per-face texturing."""
    from .mesh import Mesh

    flat = np.asarray(self.f).flatten()
    new_mesh = Mesh(v=np.asarray(self.v)[flat],
                    f=np.arange(len(flat)).reshape(-1, 3))
    if not hasattr(self, "vn"):
        self.reset_normals()
    new_mesh.vn = np.asarray(self.vn)[flat]
    if hasattr(self, "vt"):
        new_mesh.vt = np.asarray(self.vt)[np.asarray(self.ft).flatten()]
        new_mesh.ft = new_mesh.f.copy()
    return new_mesh


def keep_vertices(self, keep_list):
    """Restrict the mesh to a vertex subset, dropping faces that reference
    removed vertices (reference processing.py:47-64)."""
    keep_list = np.asarray(keep_list, dtype=np.int64)
    v_arr = np.asarray(self.v)
    f_arr = np.asarray(self.f, dtype=np.int64)
    trans = np.full(v_arr.shape[0], -1, dtype=np.int64)
    trans[keep_list] = np.arange(len(keep_list))
    trans_f = trans[f_arr]
    if hasattr(self, "vn") and np.asarray(self.vn).shape[0] == v_arr.shape[0]:
        self.vn = np.asarray(self.vn).reshape(-1, 3)[keep_list]
    if hasattr(self, "vc") and np.asarray(self.vc).shape[0] == v_arr.shape[0]:
        self.vc = np.asarray(self.vc).reshape(-1, 3)[keep_list]
    self.v = v_arr.reshape(-1, 3)[keep_list]
    self.f = trans_f[(trans_f != -1).all(axis=1)].astype(np.uint32)
    if hasattr(self, "landm_raw_xyz"):
        self.recompute_landmark_indices()
    return self


def remove_faces(self, face_indices_to_remove):
    """Drop faces and any vertices no longer referenced
    (reference processing.py:67-95)."""
    f = np.delete(np.asarray(self.f, dtype=np.int64), face_indices_to_remove, 0)
    v2keep = np.unique(f)
    self.v = np.asarray(self.v)[v2keep]
    remap = np.zeros(0 if f.size == 0 else f.max() + 1, dtype=np.int64)
    remap[v2keep] = np.arange(len(v2keep))
    self.f = remap[f].astype(np.uint32)
    if hasattr(self, "fc"):
        self.fc = np.delete(np.asarray(self.fc), face_indices_to_remove, 0)
    if hasattr(self, "vn") and np.asarray(self.vn).shape[0] > max(v2keep, default=-1):
        self.vn = np.asarray(self.vn).reshape(-1, 3)[v2keep]
    if hasattr(self, "vc") and np.asarray(self.vc).shape[0] > max(v2keep, default=-1):
        self.vc = np.asarray(self.vc).reshape(-1, 3)[v2keep]
    if hasattr(self, "ft"):
        ft = np.delete(np.asarray(self.ft, dtype=np.int64), face_indices_to_remove, 0)
        vt2keep = np.unique(ft)
        self.vt = np.asarray(self.vt)[vt2keep]
        remap_t = np.zeros(0 if ft.size == 0 else ft.max() + 1, dtype=np.int64)
        remap_t[vt2keep] = np.arange(len(vt2keep))
        self.ft = remap_t[ft].astype(np.uint32)
    if hasattr(self, "landm_raw_xyz"):
        self.recompute_landmark_indices()
    return self


def point_cloud(self):
    """Copy with no faces, keeping vertex colors if any
    (reference processing.py:62-64)."""
    from .mesh import Mesh

    if hasattr(self, "vc"):
        return Mesh(v=self.v, f=[], vc=self.vc)
    return Mesh(v=self.v, f=[])


def flip_faces(self):
    self.f = np.asarray(self.f)[:, ::-1].copy()
    if hasattr(self, "ft"):
        self.ft = np.asarray(self.ft)[:, ::-1].copy()
    return self


def scale_vertices(self, scale_factor):
    self.v = np.asarray(self.v) * scale_factor
    return self


def rotate_vertices(self, rotation):
    from .geometry.rodrigues import rodrigues

    rotation = np.asarray(rotation)
    R = rodrigues(rotation, calculate_jacobian=False) if rotation.shape != (3, 3) else rotation
    self.v = np.asarray(self.v) @ np.asarray(R).T
    return self


def translate_vertices(self, translation):
    self.v = np.asarray(self.v) + translation
    return self


def subdivide_triangles(self):
    """Centroid 1->3 split of every face (reference processing.py:125-155),
    vectorized: new vertex i + V is the centroid of old face i."""
    v = np.asarray(self.v)
    f = np.asarray(self.f, dtype=np.int64)
    centroids = v[f].mean(axis=1)
    n_v, n_f = v.shape[0], f.shape[0]
    cidx = n_v + np.arange(n_f)
    new_f = np.stack(
        [
            np.stack([f[:, 0], f[:, 1], cidx], axis=1),
            np.stack([f[:, 1], f[:, 2], cidx], axis=1),
            np.stack([f[:, 2], f[:, 0], cidx], axis=1),
        ],
        axis=1,
    ).reshape(-1, 3)
    self.v = np.vstack([v, centroids])
    self.f = new_f.astype(np.uint32)
    if hasattr(self, "vt"):
        vt = np.asarray(self.vt)
        ft = np.asarray(self.ft, dtype=np.int64)
        t_centroids = vt[ft].mean(axis=1)
        tcidx = vt.shape[0] + np.arange(ft.shape[0])
        new_ft = np.stack(
            [
                np.stack([ft[:, 0], ft[:, 1], tcidx], axis=1),
                np.stack([ft[:, 1], ft[:, 2], tcidx], axis=1),
                np.stack([ft[:, 2], ft[:, 0], tcidx], axis=1),
            ],
            axis=1,
        ).reshape(-1, 3)
        self.vt = np.vstack([vt, t_centroids])
        self.ft = new_ft.astype(np.uint32)
    return self


def concatenate_mesh(self, mesh):
    if len(self.v) == 0:
        self.f = np.asarray(mesh.f).copy()
        self.v = np.asarray(mesh.v).copy()
        if hasattr(mesh, "vc"):
            self.vc = np.asarray(mesh.vc).copy()
    elif len(mesh.v):
        self.f = np.concatenate(
            [np.asarray(self.f), np.asarray(mesh.f) + len(self.v)]
        ).astype(np.uint32)
        self.v = np.concatenate([np.asarray(self.v), np.asarray(mesh.v)])
        if hasattr(mesh, "vc") and hasattr(self, "vc") and self.vc is not None:
            self.vc = np.concatenate([np.asarray(self.vc), np.asarray(mesh.vc)])
        elif hasattr(self, "vc") and self.vc is not None:
            # color info can't be kept consistent across the concat
            del self.vc
    return self


def reorder_vertices(self, new_ordering, new_normal_ordering=None):
    """new_ordering[i] = j: vertex i becomes the j-th vertex
    (reference processing.py:171-186)."""
    new_ordering = np.asarray(new_ordering, dtype=np.int64)
    if new_normal_ordering is None:
        new_normal_ordering = new_ordering
    else:
        new_normal_ordering = np.asarray(new_normal_ordering, dtype=np.int64)
    inverse = np.zeros(len(new_ordering), dtype=np.int64)
    inverse[new_ordering] = np.arange(len(new_ordering))
    inv_norm = np.zeros(len(new_normal_ordering), dtype=np.int64)
    inv_norm[new_normal_ordering] = np.arange(len(new_normal_ordering))
    self.v = np.asarray(self.v)[inverse]
    if hasattr(self, "vn"):
        self.vn = np.asarray(self.vn)[inv_norm]
    self.f = new_ordering[np.asarray(self.f, dtype=np.int64)].astype(np.uint32)
    if hasattr(self, "fn"):
        self.fn = new_normal_ordering[np.asarray(self.fn, dtype=np.int64)].astype(np.uint32)
