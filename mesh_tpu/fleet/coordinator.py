"""Fleet SLO coordinator: fleet-level burn rate over per-replica
serve-stats sinks, plus arbitration so per-replica tuners don't fight.

Each replica already writes a serve-stats sink (``QueryService.
write_stats()``: health snapshot + queue depths + a metrics-registry
snapshot) — that JSON file is the fleet's wire format; no new
instrumentation, no RPC.  The coordinator:

1. reads every replica's sink (a path, or any callable returning a
   sink-shaped dict — in-process fleets pass ``service.stats``),
2. **aggregates** the snapshot-shaped metrics across replicas
   (counters/gauges sum per label set, histograms merge bucket-wise),
3. feeds the merged snapshot to one fleet-scoped ``SLOMonitor`` via
   ``tick(metrics=...)`` — ``obs/slo.py`` computes burn exactly as it
   would for one replica, so the fleet burn rate is the burn rate of
   the fleet-as-one-service,
4. actuates the shared latency levers through ``utils/tuning.py`` when
   fleet fast-burn pressure crosses the high-water mark (shrink
   coalescing, pre-trip the degradation ladder) and releases them when
   it falls below the low-water mark — every decision lands in the
   flight recorder and knob history like any other actuation.

**Arbitration** (``grant_widen``): per-replica ``TunerController``\\ s
in throughput mode all want to widen coalescing at once, and N widens
into the same fleet-wide fast burn is exactly the fight the issue
names.  A controller constructed with ``coordinator=`` asks for a
grant before widening; the coordinator hands out at most one grant per
cooldown window and none at all while fleet pressure is above the
release threshold — so at most one replica runs a widen hold-out at a
time, and its shadow A/B verdict lands before the next replica may
try.

Deterministic by construction: every clock read goes through the
injected ``clock`` and sinks are plain dicts, so tests (and the
``fleet_proxy`` bench stage) drive the whole loop under a fake clock.
``FleetCoordinator._lock`` guards only cached decision state and takes
no other lock; SLO sampling and actuations run outside it
(doc/concurrency.md).
"""

import json
import os
import threading

from ..utils import tuning
from ..obs.clock import monotonic
from ..obs.slo import SLOMonitor

__all__ = ["FleetCoordinator", "aggregate_sinks", "read_sink"]


def read_sink(source):
    """One replica's serve-stats sink as a dict: ``source`` is a path
    to a ``write_stats`` JSON file or a callable returning the same
    shape (in-process fleets pass ``service.stats``).  Unreadable
    sinks read as None — a replica that cannot report is missing, not
    fatal."""
    if callable(source):
        try:
            return source()
        except Exception:
            return None
    try:
        with open(os.fspath(source), "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _labels_key(labels):
    return tuple(sorted((labels or {}).items()))


def _merge_histogram(into, series):
    into["count"] += series.get("count", 0)
    into["sum"] += series.get("sum", 0.0)
    lo, hi = series.get("min"), series.get("max")
    if lo is not None:
        into["min"] = lo if into["min"] is None else min(into["min"], lo)
    if hi is not None:
        into["max"] = hi if into["max"] is None else max(into["max"], hi)
    buckets = into.setdefault("_buckets", {})
    for bound, cum in series.get("buckets", []):
        key = "+Inf" if bound == "+Inf" else float(bound)
        buckets[key] = buckets.get(key, 0) + cum


def aggregate_sinks(sinks):
    """Merge the ``metrics`` snapshots of N sink dicts into one
    registry-snapshot-shaped dict the SLO readers (``good_total``,
    ``tenants``) consume: counter/gauge series sum value per label set,
    histogram series sum count/sum and cumulative bucket counts
    bound-wise (min of mins, max of maxes).  Sinks that are None or
    carry no metrics are skipped."""
    merged = {}
    for sink in sinks:
        metrics = (sink or {}).get("metrics") or {}
        for name, entry in metrics.items():
            kind = entry.get("type")
            out = merged.setdefault(
                name, {"type": kind, "help": entry.get("help", ""),
                       "_series": {}})
            for series in entry.get("series", []):
                key = _labels_key(series.get("labels"))
                slot = out["_series"].get(key)
                if kind == "histogram":
                    if slot is None:
                        slot = out["_series"][key] = {
                            "labels": dict(series.get("labels") or {}),
                            "count": 0, "sum": 0.0,
                            "min": None, "max": None, "_buckets": {}}
                    _merge_histogram(slot, series)
                else:
                    if slot is None:
                        slot = out["_series"][key] = {
                            "labels": dict(series.get("labels") or {}),
                            "value": 0}
                    slot["value"] += series.get("value", 0)
    snapshot = {}
    for name, entry in merged.items():
        rows = []
        for _, slot in sorted(entry["_series"].items()):
            buckets = slot.pop("_buckets", None)
            if buckets is not None:
                finite = sorted(b for b in buckets if b != "+Inf")
                slot["buckets"] = [[b, buckets[b]] for b in finite]
                if "+Inf" in buckets:
                    slot["buckets"].append(["+Inf", buckets["+Inf"]])
            rows.append(slot)
        snapshot[name] = {"type": entry["type"], "help": entry["help"],
                          "series": rows}
    return snapshot


class FleetCoordinator(object):
    """Fleet-scoped burn-rate evaluation + tuner arbitration.

    ``sources`` maps replica name -> sink source (path or callable, see
    ``read_sink``).  ``step()`` is one deterministic evaluation; no
    background thread of its own — run it from a cron/driver loop or a
    test's fake clock.
    """

    def __init__(self, sources, objectives=None, rules=None,
                 clock=monotonic, recorder=None, registry=None,
                 pressure_high=0.5, pressure_low=0.1,
                 widen_cooldown_s=30.0):
        self._sources = dict(sources)
        self._clock = clock
        self._recorder = recorder
        if registry is None:
            from ..obs.metrics import REGISTRY as registry
        self._registry = registry
        self.pressure_high = float(pressure_high)
        self.pressure_low = float(pressure_low)
        self.widen_cooldown_s = float(widen_cooldown_s)
        self.monitor = SLOMonitor(objectives=objectives, rules=rules,
                                  registry=registry, clock=clock)
        # _lock guards only the cached arbitration state below and
        # takes no other lock; sampling/actuation run outside it
        self._lock = threading.Lock()
        self._pressure = 0.0          # last fleet fast-burn pressure
        self._pre_tripped = False     # coordinator-owned pre-trip latch
        self._last_grant_t = None     # last widen grant (fake-clock time)
        self._m_decisions = registry.counter(
            "mesh_tpu_fleet_coordinator_decisions_total",
            "Fleet coordinator step() decisions (shrink / release / "
            "hold).",
        )
        self._m_grants = registry.counter(
            "mesh_tpu_fleet_widen_grants_total",
            "Tuner widen-arbitration outcomes (granted / denied).",
        )
        self._m_pressure = registry.gauge(
            "mesh_tpu_fleet_pressure",
            "Worst fleet-level fast-burn pressure over the aggregated "
            "replica sinks (1.0 = breaching).",
        )
        self._m_sinks = registry.gauge(
            "mesh_tpu_fleet_sinks_readable",
            "Replica serve-stats sinks readable at the last "
            "coordinator step.",
        )

    def _record(self, kind, **fields):
        recorder = self._recorder
        if recorder is None:
            from ..obs.recorder import get_recorder

            recorder = get_recorder()
        recorder.record(kind, **fields)

    # -- evaluation ----------------------------------------------------

    def sample(self):
        """Read every sink, aggregate, feed the fleet monitor one tick.
        Returns (aggregated snapshot, readable-sink count)."""
        sinks = {name: read_sink(src)
                 for name, src in self._sources.items()}
        readable = sum(1 for s in sinks.values() if s is not None)
        agg = aggregate_sinks(sinks.values())
        self.monitor.tick(metrics=agg)
        self._m_sinks.set(readable)
        return agg, readable

    def pressure(self, now=None):
        """Worst fleet fast-burn pressure (read-only, like the
        controller's per-replica twin)."""
        rows = self.monitor.burn_rates(now=now)
        fast = [r["pressure"] for r in rows if r["rule"] == "fast_burn"]
        if not fast:
            fast = [r["pressure"] for r in rows]
        return max(fast) if fast else 0.0

    def step(self, now=None):
        """One coordinator evaluation: sample sinks, compute fleet
        pressure, actuate the shared latency levers through the audited
        knob path.  Deterministic under an injected clock."""
        if not tuning.enabled():
            return {"decision": "disabled", "actions": []}
        now = self._clock() if now is None else float(now)
        _, readable = self.sample()
        pressure = self.pressure(now=now)
        self._m_pressure.set(round(pressure, 6))
        with self._lock:
            self._pressure = pressure
            pre_tripped = self._pre_tripped
            if pressure >= self.pressure_high:
                decision = "shrink"
                self._pre_tripped = True
            elif pressure <= self.pressure_low and pre_tripped:
                decision = "release"
                self._pre_tripped = False
            else:
                decision = "hold"
        actions = []
        if decision == "shrink":
            tun = tuning.lookup("coalesce_window_ms")
            cur = tuning.get("coalesce_window_ms")
            if cur > tun.lo:
                event = tuning.actuate(
                    "coalesce_window_ms", cur - tun.step,
                    reason="fleet: fast-burn pressure %.2f across %d "
                           "replica sinks" % (pressure, readable),
                    evidence={"pressure": pressure, "sinks": readable},
                    now=now)
                if event:
                    actions.append(event)
            if tuning.get("serve_pre_trip") != 1:
                event = tuning.actuate(
                    "serve_pre_trip", 1,
                    reason="fleet: pre-trip degradation ladder",
                    evidence={"pressure": pressure}, now=now)
                if event:
                    actions.append(event)
        elif decision == "release":
            if tuning.get("serve_pre_trip") != 0:
                event = tuning.actuate(
                    "serve_pre_trip", 0,
                    reason="fleet: pressure %.2f back under release "
                           "threshold" % pressure,
                    evidence={"pressure": pressure}, now=now)
                if event:
                    actions.append(event)
        self._m_decisions.inc(decision=decision)
        self._record("fleet_decision", decision=decision,
                     pressure=round(pressure, 6), sinks=readable,
                     actions=len(actions), t=now)
        return {"decision": decision, "pressure": pressure,
                "sinks": readable, "actions": actions, "t": now}

    # -- arbitration ---------------------------------------------------

    def grant_widen(self, replica=None, now=None):
        """May one replica's tuner widen coalescing right now?  At most
        one grant per ``widen_cooldown_s`` (so one shadow A/B hold-out
        settles before the next replica tries) and none while the last
        observed fleet pressure is above the release threshold — the
        anti-fight rule.  Every verdict is audited."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            if self._pressure > self.pressure_low:
                verdict, why = False, "fleet_pressure"
            elif (self._last_grant_t is not None
                    and now - self._last_grant_t < self.widen_cooldown_s):
                verdict, why = False, "cooldown"
            else:
                verdict, why = True, "granted"
                self._last_grant_t = now
        self._m_grants.inc(outcome="granted" if verdict else "denied")
        self._record("fleet_widen", replica=replica, granted=verdict,
                     reason=why, t=now)
        return verdict

    def status(self):
        """JSON-able coordinator view for CLI/debugging."""
        with self._lock:
            return {
                "pressure": self._pressure,
                "pre_tripped": self._pre_tripped,
                "last_grant_t": self._last_grant_t,
                "sources": sorted(self._sources),
            }
