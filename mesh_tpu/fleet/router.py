"""FleetRouter: the digest-affine front door over N QueryService replicas.

One process, one chip is the serving tier's shape (serve/service.py);
the router is the piece that federates N of them (doc/fleet.md).  It
exposes the exact ``submit``/``query`` surface of ``QueryService`` —
loadgen generators, trace replay, and callers that code against the
service interface all take a router without changes — and places each
request by consistent-hashing its **routing key**:

    (op, topology digest, query-count bucket)

— the same identity the engine's plan cache and the store's page cache
key on, so every replica keeps re-seeing the digests it already has
warm plans and resident pages for (cache affinity is the entire win:
a random balancer makes every replica re-compile every plan).

Admission follows the replicas' own backpressure:

- **spill-to-sibling**: a primary that rejects with ``queue_full``
  spills the request to the second choice on the hash ring (one hop
  only — a fleet-wide full queue should reject, not cascade), so a hot
  tenant's stampede degrades one digest's affinity instead of turning
  into caller-visible rejections while siblings idle.
- **ring ejection**: replicas whose health monitor is not ``ready()``
  (DRAINING — graceful shutdown or watchdog escalation,
  serve/health.py) are skipped during the ring walk; consistent
  hashing means only their own keys move.  DEGRADED replicas stay in
  the ring (they still answer, one rung down).
- every other rejection (``draining``, ``low_priority``) propagates
  unchanged — the router adds placement, never new admission policy.

**Ledger cleanliness by construction**: the router opens no ledger
records.  Admission into a replica is what opens a record
(``QueryService.submit``), and every replica path closes it — a
``ServeRejected`` hop between replicas happens strictly *before* any
record exists, so no router edge can leak an open record
(LED001; regression-tested in tests/test_fleet.py).

``MESH_TPU_FLEET=0`` is the kill switch: ``submit`` delegates straight
to the first replica — no key, no ring walk, no fleet metrics — which
with a single replica is bit-identical to calling the service
directly (pinned by test).

Stdlib-only at import (numpy is touched lazily only when a raw-faces
mesh needs digesting); the fleet metrics ride the always-on registry:
``mesh_tpu_fleet_requests_total{replica,outcome}``,
``mesh_tpu_fleet_spill_total{replica}``,
``mesh_tpu_fleet_ring_members`` / ``mesh_tpu_fleet_ring_eligible``
(doc/observability.md).
"""

import itertools
import json
import threading
import zlib
from collections import OrderedDict

from ..errors import ServeRejected
from ..obs.clock import monotonic
from ..obs.context import mint as mint_context
from ..utils import knobs
from .ring import HashRing

__all__ = [
    "FleetRouter", "fleet_enabled", "spill_enabled", "routing_key",
    "topology_digest", "shape_bucket", "ROUTER_Q_LADDER",
]

#: the engine's query-count bucket ladder (engine/planner.py Q_LADDER),
#: restated here so the router stays importable without jax — the two
#: tables are pinned equal by tests/test_fleet.py
ROUTER_Q_LADDER = (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384)


def fleet_enabled():
    """Router kill switch: ``MESH_TPU_FLEET=0`` = direct pass-through."""
    return knobs.flag("MESH_TPU_FLEET")


def spill_enabled():
    """``MESH_TPU_FLEET_SPILL=0`` disables spill-to-sibling (a full
    primary rejects, exactly like a standalone service)."""
    return knobs.flag("MESH_TPU_FLEET_SPILL")


def shape_bucket(q):
    """Smallest ladder rung >= q (next multiple of the top rung beyond)
    — the engine's ``bucket_size`` over its Q_LADDER, restated jax-free."""
    q = int(q)
    if q <= 0:
        raise ValueError("shape_bucket wants a positive count, got %d" % q)
    for b in ROUTER_Q_LADDER:
        if q <= b:
            return b
    top = ROUTER_Q_LADDER[-1]
    return ((q + top - 1) // top) * top


def topology_digest(mesh):
    """The mesh identity the routing key hashes: a store key verbatim,
    a mesh's ``topology_key`` when it carries one, else a crc32 of the
    face buffer — the same chain the engine executor keys coalescing
    groups with."""
    if isinstance(mesh, str):
        return mesh
    topo = getattr(mesh, "topology_key", None)
    if topo:
        return str(topo)
    import numpy as np

    faces = np.ascontiguousarray(np.asarray(mesh.f, np.int32))
    return "crc32:%08x" % (zlib.crc32(faces.tobytes()) & 0xFFFFFFFF)


def routing_key(op, mesh, points):
    """``op|digest|bucket`` — the affinity identity one request hashes
    onto the ring with."""
    q = points.shape[0] if hasattr(points, "shape") else len(points)
    return "%s|%s|%d" % (op, topology_digest(mesh), shape_bucket(q))


class FleetRouter(object):
    """Digest-affine consistent-hash front end over replica services.

    ``replicas`` maps name -> service handle (anything exposing the
    ``QueryService`` interface: ``submit``, ``query``, ``health``,
    ``stop``).  Membership changes and the admission log are serialized
    by ``_lock``; replica ``submit`` calls and metric bumps run after
    it drops.  The only lock taken underneath is each replica's
    ``HealthMonitor._lock`` (the eligibility read in ``plan``), which
    is why the router sits above health in the canonical order
    (doc/concurrency.md).
    """

    def __init__(self, replicas=None, vnodes=None, recorder=None):
        if vnodes is None:
            vnodes = max(1, knobs.get_int("MESH_TPU_FLEET_VNODES"))
        self._lock = threading.Lock()
        self._replicas = OrderedDict()
        self._ring = HashRing(vnodes=vnodes)
        self._seq = 0
        self._mint_seq = itertools.count(1)
        self._log = {}                # name -> [admission event, ...]
        self._recorder = recorder
        self._init_metrics()
        for name, service in (replicas or {}).items():
            self.add_replica(name, service)

    # ------------------------------------------------------------------
    # metrics

    def _init_metrics(self):
        from ..obs.metrics import REGISTRY

        self._m_requests = REGISTRY.counter(
            "mesh_tpu_fleet_requests_total",
            "Router admissions by replica and outcome (routed / spilled "
            "/ rejected).",
        )
        self._m_spill = REGISTRY.counter(
            "mesh_tpu_fleet_spill_total",
            "Requests spilled to the ring's second choice because the "
            "primary replica's tenant queue was full.",
        )
        self._m_members = REGISTRY.gauge(
            "mesh_tpu_fleet_ring_members",
            "Replicas registered on the hash ring.",
        )
        self._m_eligible = REGISTRY.gauge(
            "mesh_tpu_fleet_ring_eligible",
            "Registered replicas currently admitting (health ready).",
        )

    def _record(self, kind, **fields):
        recorder = self._recorder
        if recorder is None:
            from ..obs.recorder import get_recorder

            recorder = get_recorder()
        recorder.record(kind, **fields)

    # ------------------------------------------------------------------
    # membership

    def add_replica(self, name, service):
        with self._lock:
            if name in self._replicas:
                raise ValueError("replica %r already registered" % (name,))
            self._replicas[name] = service
            self._ring.add(name)
            self._log.setdefault(name, [])
            members = len(self._ring)
        self._m_members.set(members)
        self._record("fleet.member", action="add", replica=name,
                     members=members)

    def remove_replica(self, name):
        """Take a replica off the ring (it is NOT stopped — draining is
        the owner's job); only its own keys remap."""
        with self._lock:
            service = self._replicas.pop(name, None)
            self._ring.remove(name)
            members = len(self._ring)
        self._m_members.set(members)
        if service is not None:
            self._record("fleet.member", action="remove", replica=name,
                         members=members)
        return service

    def replicas(self):
        with self._lock:
            return OrderedDict(self._replicas)

    def _eligible(self, name):
        service = self._replicas.get(name)
        if service is None:
            return False
        health = getattr(service, "health", None)
        if health is None:
            return True
        try:
            return bool(health.ready())
        except Exception:       # a dying health monitor reads as ejected
            return False

    def plan(self, op, mesh, points):
        """The eligible preference order for one request (primary
        first) — what ``submit`` walks; exposed for tests and the bench
        affinity probe."""
        key = routing_key(op, mesh, points)
        with self._lock:
            order = self._ring.choices(key)
            order = [n for n in order if self._eligible(n)]
            eligible = sum(1 for n in self._replicas
                           if self._eligible(n))
        self._m_eligible.set(eligible)
        return key, order

    # ------------------------------------------------------------------
    # admission (the QueryService-compatible surface)

    def submit(self, mesh, points, tenant="default", priority=0,
               deadline_s=None, op="closest_point"):
        """Route one request onto its affinity replica; returns that
        replica's Future.  ``ServeRejected`` propagates once spill is
        exhausted (or for any non-queue_full reason) — the router never
        queues requests itself."""
        with self._lock:
            if not self._replicas:
                raise ServeRejected("fleet has no replicas",
                                    retry_after=5.0, reason="draining")
            first = next(iter(self._replicas.values()))
        if not fleet_enabled():
            # kill switch: the single-replica direct path, bit-identical
            # to calling the service (no key, no ring, no fleet series)
            return first.submit(mesh, points, tenant=tenant,
                                priority=priority, deadline_s=deadline_s)
        key, order = self.plan(op, mesh, points)
        if not order:
            self._record("fleet.reject", key=key, reason="no_replica")
            raise ServeRejected(
                "no fleet replica is admitting", retry_after=5.0,
                reason="draining")
        primary = order[0]
        # Mint the fleet-wide request identity at the admission edge:
        # the routing key and chosen replica travel with the request so
        # a spill hop stays attributable end-to-end (doc/observability.md).
        ctx = mint_context(tenant, next(self._mint_seq), monotonic(),
                           routing_key=key, replica=primary)
        ctx_kw = {"ctx": ctx} if ctx is not None else {}
        try:
            future = self._replicas[primary].submit(
                mesh, points, tenant=tenant, priority=priority,
                deadline_s=deadline_s, **ctx_kw)
        except ServeRejected as e:
            if (e.reason != "queue_full" or not spill_enabled()
                    or len(order) < 2):
                self._m_requests.inc(replica=primary, outcome="rejected")
                self._record("fleet.reject", key=key, replica=primary,
                             reason=e.reason)
                raise
            sibling = order[1]
            self._m_spill.inc(replica=primary)
            self._record("fleet.spill", key=key, tenant=tenant,
                         src=primary, dst=sibling)
            if ctx is not None:
                ctx.replica = sibling
                ctx.spilled = True
            try:
                future = self._replicas[sibling].submit(
                    mesh, points, tenant=tenant, priority=priority,
                    deadline_s=deadline_s, **ctx_kw)
            except ServeRejected:
                self._m_requests.inc(replica=sibling, outcome="rejected")
                self._record("fleet.reject", key=key, replica=sibling,
                             reason="spill_exhausted")
                raise
            self._m_requests.inc(replica=sibling, outcome="spilled")
            self._log_admission(sibling, key, tenant)
            return future
        self._m_requests.inc(replica=primary, outcome="routed")
        self._log_admission(primary, key, tenant)
        return future

    def query(self, mesh, points, tenant="default", priority=0,
              deadline_s=None, op="closest_point"):
        """Synchronous submit (the ``QueryService.query`` twin)."""
        future = self.submit(mesh, points, tenant=tenant, priority=priority,
                             deadline_s=deadline_s, op=op)
        return future.result()

    # ------------------------------------------------------------------
    # determinism surface (per-replica admission checksums)

    def _log_admission(self, replica, key, tenant):
        with self._lock:
            self._seq += 1
            self._log.setdefault(replica, []).append(
                [len(self._log[replica]), tenant, key])

    def admission_checksums(self):
        """Deterministic per-replica CRC over the admission sequence
        each replica received (same trace + same membership => same
        checksums; the fleet golden pins them, loadgen reports carry
        them under ``replica_checksums``)."""
        with self._lock:
            logs = {name: list(events)
                    for name, events in self._log.items()}
        out = {}
        for name, events in logs.items():
            payload = json.dumps(events, sort_keys=True,
                                 separators=(",", ":"))
            out[name] = float(zlib.crc32(payload.encode("utf-8")))
        return out

    def reset_admission_log(self):
        """Zero the per-replica admission logs (between bench phases)."""
        with self._lock:
            for name in self._log:
                self._log[name] = []
            self._seq = 0

    # ------------------------------------------------------------------
    # introspection / lifecycle

    def status(self):
        """JSON-able ring/replica view (the in-process analog of
        ``mesh-tpu fleet status``)."""
        with self._lock:
            names = list(self._replicas)
            members = self._ring.members()
            log_sizes = {n: len(e) for n, e in self._log.items()}
        rows = []
        for name in names:
            service = self._replicas.get(name)
            health = getattr(service, "health", None)
            rows.append({
                "replica": name,
                "in_ring": name in members,
                "eligible": self._eligible(name),
                "health": (health.snapshot()
                           if health is not None else None),
                "admitted": log_sizes.get(name, 0),
            })
        return {"members": members, "replicas": rows}

    def stop(self, drain=True, write_stats=True):
        """Stop every replica (drain semantics are the services' own)."""
        for service in self.replicas().values():
            service.stop(drain=drain, write_stats=write_stats)
