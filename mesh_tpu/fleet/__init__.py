"""Fleet serving fabric: one process becomes N replicas behind a
digest-affine front door (doc/fleet.md).

- ``ring``: consistent-hash ring (stable placement, minimal movement).
- ``router``: the ``QueryService``-compatible front end — hashes
  (op, topology digest, shape bucket) onto replica handles, spills to
  the ring sibling on ``queue_full``, ejects draining replicas.
- ``coordinator``: fleet-level SLO burn over per-replica serve-stats
  sinks + widen arbitration for per-replica tuners.

The persistent AOT executable tier lives in ``store/aot.py`` (it is a
store concern) and the sharded big-batch lane in the engine executor;
this package is jax-free at import so the CLI can reach ``fleet
status`` without a backend.
"""

from .ring import DEFAULT_VNODES, HashRing
from .router import (
    FleetRouter, fleet_enabled, routing_key, shape_bucket, spill_enabled,
    topology_digest,
)
from .coordinator import FleetCoordinator, aggregate_sinks, read_sink

__all__ = [
    "HashRing", "DEFAULT_VNODES",
    "FleetRouter", "fleet_enabled", "spill_enabled",
    "routing_key", "shape_bucket", "topology_digest",
    "FleetCoordinator", "aggregate_sinks", "read_sink",
]
